// Native host column store for opentsdb_tpu.
//
// The storage-engine role the reference delegates to HBase region
// servers + the asynchbase client (SURVEY.md L0): append-optimized
// per-series column buffers with lazy sort/dedupe and a parallel
// range-materialize that fills flat (series_idx, ts, value) arrays
// ready for device upload. The Python MemoryBackend is the portable
// twin; this engine removes the per-series Python loop from the
// query path (ref analogue: SaltScanner's 20-way parallel scan,
// src/core/SaltScanner.java:70 — here a thread pool over series).
//
// C ABI (ctypes-friendly), no exceptions across the boundary.
// Build: g++ -O3 -march=native -shared -fPIC -std=c++17 -pthread
//        tsdbstore.cc -o libtsdbstore.so

#include <algorithm>
#include <atomic>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct SeriesBuffer {
  std::vector<int64_t> ts;
  std::vector<double> vals;
  std::vector<uint8_t> is_int;
  bool sorted = true;
  std::mutex mu;

  void append(int64_t t, double v, uint8_t ii) {
    std::lock_guard<std::mutex> lock(mu);
    if (sorted && !ts.empty() && t <= ts.back()) sorted = false;
    ts.push_back(t);
    vals.push_back(v);
    is_int.push_back(ii);
  }

  void append_many(int64_t n, const int64_t* t, const double* v,
                   const uint8_t* ii) {
    std::lock_guard<std::mutex> lock(mu);
    for (int64_t i = 0; i < n; ++i) {
      if (sorted && !ts.empty() && t[i] <= ts.back()) sorted = false;
      ts.push_back(t[i]);
      vals.push_back(v[i]);
      is_int.push_back(ii ? ii[i] : 0);
    }
  }

  // Sort by timestamp, last-write-wins dedupe (matches the Python
  // SeriesBuffer and the reference's fix_duplicates semantics).
  void ensure_sorted_locked() {
    if (sorted) return;
    const size_t n = ts.size();
    std::vector<uint32_t> order(n);
    for (size_t i = 0; i < n; ++i) order[i] = (uint32_t)i;
    std::stable_sort(order.begin(), order.end(),
                     [&](uint32_t a, uint32_t b) { return ts[a] < ts[b]; });
    std::vector<int64_t> nts;
    std::vector<double> nvals;
    std::vector<uint8_t> nint;
    nts.reserve(n);
    nvals.reserve(n);
    nint.reserve(n);
    for (size_t i = 0; i < n; ++i) {
      uint32_t idx = order[i];
      if (!nts.empty() && nts.back() == ts[idx]) {
        nvals.back() = vals[idx];  // last write wins
        nint.back() = is_int[idx];
      } else {
        nts.push_back(ts[idx]);
        nvals.push_back(vals[idx]);
        nint.push_back(is_int[idx]);
      }
    }
    ts.swap(nts);
    vals.swap(nvals);
    is_int.swap(nint);
    sorted = true;
  }

  // [lo, hi] inclusive range bounds after sorting.
  void range_bounds(int64_t start_ms, int64_t end_ms, int64_t* lo,
                    int64_t* hi) {
    std::lock_guard<std::mutex> lock(mu);
    ensure_sorted_locked();
    *lo = std::lower_bound(ts.begin(), ts.end(), start_ms) - ts.begin();
    *hi = std::upper_bound(ts.begin(), ts.end(), end_ms) - ts.begin();
  }
};

struct Store {
  // The directory vector REALLOCATES on growth, so every indexing
  // access holds the shared lock; the SeriesBuffer objects themselves
  // are heap-stable for the store's lifetime, so captured pointers
  // stay valid after the lock drops (each buffer has its own mutex).
  std::vector<SeriesBuffer*> series;
  std::shared_mutex dir_mu;
  std::atomic<int64_t> points_written{0};

  // nullptr on a bad sid.
  SeriesBuffer* lookup(int64_t sid) {
    std::shared_lock<std::shared_mutex> lock(dir_mu);
    if (sid < 0 || sid >= (int64_t)series.size()) return nullptr;
    return series[sid];
  }

  // Validate + capture all pointers under ONE shared lock (the
  // threaded bulk paths). Returns false on any bad sid.
  bool snapshot(const int64_t* sids, int64_t n,
                std::vector<SeriesBuffer*>* out) {
    std::shared_lock<std::shared_mutex> lock(dir_mu);
    out->resize(n);
    for (int64_t i = 0; i < n; ++i) {
      if (sids[i] < 0 || sids[i] >= (int64_t)series.size())
        return false;
      (*out)[i] = series[sids[i]];
    }
    return true;
  }

  ~Store() {
    for (auto* s : series) delete s;
  }
};

}  // namespace

extern "C" {

void* tss_create() { return new Store(); }

void tss_destroy(void* h) { delete static_cast<Store*>(h); }

// Returns the new series id. Series identity (metric+tags -> sid) is
// managed by the Python wrapper; this just allocates the buffer.
int64_t tss_add_series(void* h) {
  Store* s = static_cast<Store*>(h);
  std::unique_lock<std::shared_mutex> lock(s->dir_mu);
  s->series.push_back(new SeriesBuffer());
  return (int64_t)s->series.size() - 1;
}

// Bulk allocation: n new contiguous series ids, one lock take.
// Returns the first new id.
int64_t tss_add_series_n(void* h, int64_t n) {
  Store* s = static_cast<Store*>(h);
  std::unique_lock<std::shared_mutex> lock(s->dir_mu);
  int64_t first = (int64_t)s->series.size();
  s->series.reserve(s->series.size() + (size_t)n);
  for (int64_t i = 0; i < n; ++i) s->series.push_back(new SeriesBuffer());
  return first;
}

int64_t tss_series_count(void* h) {
  Store* s = static_cast<Store*>(h);
  std::shared_lock<std::shared_mutex> lock(s->dir_mu);
  return (int64_t)s->series.size();
}

int tss_append(void* h, int64_t sid, int64_t ts_ms, double value,
               int is_int) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  buf->append(ts_ms, value, (uint8_t)is_int);
  s->points_written.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

int tss_append_many(void* h, int64_t sid, int64_t n, const int64_t* ts,
                    const double* vals, const uint8_t* is_int) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  buf->append_many(n, ts, vals, is_int);
  s->points_written.fetch_add(n, std::memory_order_relaxed);
  return 0;
}

int64_t tss_points_written(void* h) {
  return static_cast<Store*>(h)->points_written.load();
}

// fsck in-place repair (ref: Fsck.java:99-119 repairing bad values /
// timestamps in storage): drop points whose timestamp falls outside
// [min_ts, max_ts], and — when drop_nonfinite — points whose value is
// NaN/Inf. Returns the number of points removed, or -1 on a bad sid.
int64_t tss_repair_series(void* h, int64_t sid, int64_t min_ts,
                          int64_t max_ts, int drop_nonfinite) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  std::lock_guard<std::mutex> lock(buf->mu);
  buf->ensure_sorted_locked();
  const size_t n = buf->ts.size();
  size_t w = 0;
  for (size_t i = 0; i < n; ++i) {
    bool ok = buf->ts[i] >= min_ts && buf->ts[i] <= max_ts;
    if (ok && drop_nonfinite && !std::isfinite(buf->vals[i])) ok = false;
    if (ok) {
      if (w != i) {
        buf->ts[w] = buf->ts[i];
        buf->vals[w] = buf->vals[i];
        buf->is_int[w] = buf->is_int[i];
      }
      ++w;
    }
  }
  buf->ts.resize(w);
  buf->vals.resize(w);
  buf->is_int.resize(w);
  return (int64_t)(n - w);
}

// fsck in-place repair: overwrite the value stored at an exact
// timestamp. Returns 0 on success, -1 on a bad sid, -2 when no point
// has that timestamp.
int tss_patch_value(void* h, int64_t sid, int64_t ts_ms, double value,
                    int is_int) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  std::lock_guard<std::mutex> lock(buf->mu);
  buf->ensure_sorted_locked();
  auto it = std::lower_bound(buf->ts.begin(), buf->ts.end(), ts_ms);
  if (it == buf->ts.end() || *it != ts_ms) return -2;
  size_t i = it - buf->ts.begin();
  buf->vals[i] = value;
  buf->is_int[i] = (uint8_t)is_int;
  return 0;
}

// Bulk grid write (the rollup job's output path): for every row i,
// append the mask-selected cells of grid[i, :] (shared bucket_ts
// columns) onto series sids[i]. Threaded over rows; one lock take per
// row instead of per cell. Returns the number of points written, or
// -1 on any invalid sid.
int64_t tss_append_grid(void* h, const int64_t* sids, int64_t nsids,
                        const int64_t* bucket_ts, int64_t nbuckets,
                        const double* grid, const uint8_t* mask,
                        int threads) {
  Store* s = static_cast<Store*>(h);
  std::vector<SeriesBuffer*> bufs;
  if (!s->snapshot(sids, nsids, &bufs)) return -1;
  if (threads < 1) threads = 1;
  std::atomic<int64_t> next{0};
  std::atomic<int64_t> total{0};
  auto worker = [&]() {
    int64_t local = 0;
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= nsids) break;
      SeriesBuffer* buf = bufs[i];
      const double* row = grid + i * nbuckets;
      const uint8_t* m = mask + i * nbuckets;
      std::lock_guard<std::mutex> lock(buf->mu);
      for (int64_t b = 0; b < nbuckets; ++b) {
        if (!m[b]) continue;
        if (buf->sorted && !buf->ts.empty() &&
            bucket_ts[b] <= buf->ts.back())
          buf->sorted = false;
        buf->ts.push_back(bucket_ts[b]);
        buf->vals.push_back(row[b]);
        buf->is_int.push_back(0);
        ++local;
      }
    }
    total.fetch_add(local);
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& t : pool) t.join();
  s->points_written.fetch_add(total.load());
  return total.load();
}

int64_t tss_series_length(void* h, int64_t sid) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  std::lock_guard<std::mutex> lock(buf->mu);
  buf->ensure_sorted_locked();
  return (int64_t)buf->ts.size();
}

// Remove points with start_ms <= ts <= end_ms from one series; returns
// the number deleted (ref: TsdbQuery delete=true issuing
// DeleteRequests per scanned row). -1 on a bad sid.
int64_t tss_delete_range(void* h, int64_t sid, int64_t start_ms,
                         int64_t end_ms) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  std::lock_guard<std::mutex> lock(buf->mu);
  buf->ensure_sorted_locked();
  auto lo = std::lower_bound(buf->ts.begin(), buf->ts.end(), start_ms);
  auto hi = std::upper_bound(buf->ts.begin(), buf->ts.end(), end_ms);
  int64_t n = hi - lo;
  if (n > 0) {
    buf->vals.erase(buf->vals.begin() + (lo - buf->ts.begin()),
                    buf->vals.begin() + (hi - buf->ts.begin()));
    buf->is_int.erase(buf->is_int.begin() + (lo - buf->ts.begin()),
                      buf->is_int.begin() + (hi - buf->ts.begin()));
    buf->ts.erase(lo, hi);
  }
  return n;
}

// Copy one series' sorted columns into caller-provided arrays of
// capacity `cap` (from a prior tss_series_length call). Returns the
// number of elements actually copied — concurrent appends between the
// two calls can grow the buffer past cap (copy truncates) and
// concurrent deletes/dedupes can shrink it (caller trims to the
// return value); never writes past cap. -1 on a bad sid.
int64_t tss_read_series(void* h, int64_t sid, int64_t cap,
                        int64_t* ts_out, double* vals_out,
                        uint8_t* int_out) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  std::lock_guard<std::mutex> lock(buf->mu);
  buf->ensure_sorted_locked();
  int64_t n = (int64_t)buf->ts.size();
  if (n > cap) n = cap;
  if (n > 0) {
    std::memcpy(ts_out, buf->ts.data(), n * sizeof(int64_t));
    std::memcpy(vals_out, buf->vals.data(), n * sizeof(double));
    if (int_out) std::memcpy(int_out, buf->is_int.data(), n);
  }
  return n;
}

// Phase 1 of materialize: per-series point counts within
// [start_ms, end_ms] (inclusive). Parallel over a thread pool — the
// reference's per-salt-bucket scanner fan-out.
int tss_count_range(void* h, const int64_t* sids, int64_t nsids,
                    int64_t start_ms, int64_t end_ms,
                    int64_t* counts_out, int threads) {
  Store* s = static_cast<Store*>(h);
  std::vector<SeriesBuffer*> bufs;
  if (!s->snapshot(sids, nsids, &bufs)) return -1;
  if (threads < 1) threads = 1;
  std::atomic<int64_t> next{0};
  auto worker = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= nsids) break;
      int64_t lo, hi;
      bufs[i]->range_bounds(start_ms, end_ms, &lo, &hi);
      counts_out[i] = hi - lo;
    }
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return 0;
}

// Phase 2: fill flat output arrays. offsets[i] must hold the exclusive
// prefix sum of the phase-1 counts and counts[i] the phase-1 count
// itself: the copy is capped at counts[i] so appends that land between
// the two phases can never overflow the caller's buffers (they are
// picked up by the next query). series_idx_out gets the *dense*
// position i (0..nsids-1), matching PointBatch.
int tss_fill_range(void* h, const int64_t* sids, int64_t nsids,
                   int64_t start_ms, int64_t end_ms,
                   const int64_t* offsets, const int64_t* counts,
                   int64_t* ts_out, double* vals_out,
                   int32_t* series_idx_out, int threads) {
  Store* s = static_cast<Store*>(h);
  std::vector<SeriesBuffer*> bufs;
  if (!s->snapshot(sids, nsids, &bufs)) return -1;
  if (threads < 1) threads = 1;
  std::atomic<int64_t> next{0};
  auto worker = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= nsids) break;
      SeriesBuffer* buf = bufs[i];
      std::lock_guard<std::mutex> lock(buf->mu);
      buf->ensure_sorted_locked();
      int64_t lo =
          std::lower_bound(buf->ts.begin(), buf->ts.end(), start_ms) -
          buf->ts.begin();
      int64_t hi =
          std::upper_bound(buf->ts.begin(), buf->ts.end(), end_ms) -
          buf->ts.begin();
      int64_t off = offsets[i];
      int64_t n = hi - lo;
      if (n > counts[i]) n = counts[i];
      if (n > 0) {
        std::memcpy(ts_out + off, buf->ts.data() + lo,
                    n * sizeof(int64_t));
        std::memcpy(vals_out + off, buf->vals.data() + lo,
                    n * sizeof(double));
        std::fill(series_idx_out + off, series_idx_out + off + n,
                  (int32_t)i);
      }
      // fewer points than counted (concurrent repair/delete): pad the
      // remainder with NaN placeholders the compute path skips
      for (int64_t j = n < 0 ? 0 : n; j < counts[i]; ++j) {
        ts_out[off + j] = start_ms;
        vals_out[off + j] = std::numeric_limits<double>::quiet_NaN();
        series_idx_out[off + j] = (int32_t)i;
      }
    }
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return 0;
}

// Fused range-scan + fixed-interval downsample pre-reduction: for
// each series i, every point with start_ms <= ts <= end_ms lands in
// bucket b = (ts - t0) / interval_ms (caller guarantees t0 <= start_ms
// and the last bucket covers end_ms), accumulating sum / count / min /
// max. Outputs are [nsids, nbuckets] row-major; cells with count 0
// hold sum 0, min +inf, max -inf (the Python wrapper NaN-fills).
// NaN stored values are skipped, matching the device bucketize's NaN
// guard (ref: Aggregators.runDouble skipping NaN). min_out/max_out may
// be null when the caller only needs sum/count. Threaded over series.
// Returns -1 on a bad sid, else 0.
//
// This removes the [N]-point materialize + host->device upload for
// simple-function downsamples: the device receives S*B cells instead
// of N points (60x smaller for 1m data in 1h buckets) and starts at
// the grid stage of the pipeline.
int tss_bucket_reduce(void* h, const int64_t* sids, int64_t nsids,
                      int64_t start_ms, int64_t end_ms, int64_t t0,
                      int64_t interval_ms, int64_t nbuckets,
                      double* sum_out, double* cnt_out, double* min_out,
                      double* max_out, int threads) {
  Store* s = static_cast<Store*>(h);
  std::vector<SeriesBuffer*> bufs;
  if (!s->snapshot(sids, nsids, &bufs)) return -1;
  if (interval_ms <= 0 || nbuckets <= 0) return -1;
  if (threads < 1) threads = 1;
  std::atomic<int64_t> next{0};
  const double inf = std::numeric_limits<double>::infinity();
  auto worker = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= nsids) break;
      double* srow = sum_out + i * nbuckets;
      double* crow = cnt_out + i * nbuckets;
      double* mnrow = min_out ? min_out + i * nbuckets : nullptr;
      double* mxrow = max_out ? max_out + i * nbuckets : nullptr;
      for (int64_t b = 0; b < nbuckets; ++b) {
        srow[b] = 0.0;
        crow[b] = 0.0;
        if (mnrow) mnrow[b] = inf;
        if (mxrow) mxrow[b] = -inf;
      }
      SeriesBuffer* buf = bufs[i];
      std::lock_guard<std::mutex> lock(buf->mu);
      buf->ensure_sorted_locked();
      int64_t lo =
          std::lower_bound(buf->ts.begin(), buf->ts.end(), start_ms) -
          buf->ts.begin();
      int64_t hi =
          std::upper_bound(buf->ts.begin(), buf->ts.end(), end_ms) -
          buf->ts.begin();
      // timestamps are sorted: resolve each bucket's point range with
      // a binary search, then accumulate over a fixed-bound inner loop
      // the compiler can vectorize (no per-point divide or
      // data-dependent exit). The NaN guard is a branchless blend.
      const int64_t* tsd = buf->ts.data();
      const double* vd = buf->vals.data();
      int64_t p = lo;
      while (p < hi) {
        // floor division (C++ '/' truncates toward zero): a point just
        // below t0 must be DROPPED like the Python twin's '//' does,
        // not folded into bucket 0
        int64_t d = tsd[p] - t0;
        int64_t b = d >= 0 ? d / interval_ms : -1;
        if (b < 0) {  // cannot happen when t0 <= start_ms; be safe
          ++p;
          continue;
        }
        if (b >= nbuckets) break;
        int64_t bucket_end = t0 + (b + 1) * interval_ms;
        int64_t pe =
            std::lower_bound(tsd + p, tsd + hi, bucket_end) - tsd;
        double sum = 0.0, cnt = 0.0;
        if (mnrow) {
          double mn = inf, mx = -inf;
          for (int64_t q = p; q < pe; ++q) {
            double v = vd[q];
            bool ok = v == v;
            sum += ok ? v : 0.0;
            cnt += ok ? 1.0 : 0.0;
            mn = (ok && v < mn) ? v : mn;
            mx = (ok && v > mx) ? v : mx;
          }
          mnrow[b] = mn;
          mxrow[b] = mx;
        } else {
          for (int64_t q = p; q < pe; ++q) {
            double v = vd[q];
            bool ok = v == v;
            sum += ok ? v : 0.0;
            cnt += ok ? 1.0 : 0.0;
          }
        }
        srow[b] = sum;
        crow[b] = cnt;
        p = pe;
      }
    }
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return 0;
}

}  // extern "C"

namespace {

// Charset the reference allows in metric/tag names and values
// (Tags.validateString: alphanumerics plus -_./ and unicode letters
// via Character.isLetter). Bytes >= 0x80 (UTF-8 sequences) pass here;
// the Python side re-validates non-ASCII names precisely.
inline bool valid_name_char(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '-' || c == '_' || c == '.' ||
         c == '/' || c >= 0x80;
}

inline bool valid_name(const char* p, int64_t n) {
  if (n <= 0) return false;
  for (int64_t i = 0; i < n; ++i)
    if (!valid_name_char((unsigned char)p[i])) return false;
  return true;
}

// One thread's share of the import parse: lines in [pos, limit) of
// the buffer, writing per-line outputs at global line index
// line_base.., building a LOCAL group table (keys + first-line byte
// ranges). Local group ids are remapped to global ids after the merge.
struct LocalGroups {
  std::unordered_map<std::string, int64_t> map;
  std::vector<int64_t> rep_off, rep_len;
};

void parse_import_range(const char* buf, int64_t pos, int64_t limit,
                        int64_t line_base, int64_t* ts_out,
                        double* val_out, uint8_t* int_out,
                        int64_t* group_out, int32_t* err_out,
                        LocalGroups* lg) {
  std::string key;
  key.reserve(256);
  std::string prev_key;
  int64_t prev_gid = -1;
  struct Tok {
    const char* p;
    int64_t n;
  };
  int64_t line = line_base;
  const int64_t kMaxTs = (int64_t)1 << 47;
  while (pos < limit) {
    int64_t eol = pos;
    while (eol < limit && buf[eol] != '\n') ++eol;
    int64_t lstart = pos;
    int64_t lend = eol;
    if (lend > lstart && buf[lend - 1] == '\r') --lend;
    pos = eol + 1;
    int64_t i = line++;
    ts_out[i] = 0;
    val_out[i] = 0.0;
    int_out[i] = 0;
    group_out[i] = -1;
    err_out[i] = 0;
    // tokenize on runs of space/tab
    Tok toks[16];
    int ntok = 0;
    int64_t q = lstart;
    bool overflow = false;
    while (q < lend) {
      while (q < lend && (buf[q] == ' ' || buf[q] == '\t')) ++q;
      if (q >= lend) break;
      int64_t t0 = q;
      while (q < lend && buf[q] != ' ' && buf[q] != '\t') ++q;
      if (ntok < 16) {
        toks[ntok].p = buf + t0;
        toks[ntok].n = q - t0;
        ++ntok;
      } else {
        overflow = true;
      }
    }
    // blank or comment: first NON-SPACE char decides, so indented
    // comments skip like the line.strip().startswith('#') fallback
    {
      int64_t fs = lstart;
      while (fs < lend && (buf[fs] == ' ' || buf[fs] == '\t')) ++fs;
      if (fs >= lend || buf[fs] == '#') {
        err_out[i] = -1;
        continue;
      }
    }
    if (ntok == 0) {
      err_out[i] = -1;
      continue;
    }
    if (ntok < 4 || overflow) {
      err_out[i] = ntok < 4 ? 1 : 4;
      continue;
    }
    if (!valid_name(toks[0].p, toks[0].n)) {
      err_out[i] = 5;
      continue;
    }
    // timestamp: plain digits (seconds or epoch-ms)
    {
      int64_t ts = 0;
      bool ok = toks[1].n > 0 && toks[1].n < 15;
      for (int64_t c = 0; ok && c < toks[1].n; ++c) {
        char ch = toks[1].p[c];
        if (ch < '0' || ch > '9') ok = false;
        else ts = ts * 10 + (ch - '0');
      }
      if (!ok || ts <= 0 || ts > kMaxTs) {
        err_out[i] = 2;
        continue;
      }
      ts_out[i] = ts;
    }
    // value: inline integer fast path, strtod for the rest
    {
      const char* vp = toks[2].p;
      int64_t vn = toks[2].n;
      int64_t st = (vn && (vp[0] == '-' || vp[0] == '+')) ? 1 : 0;
      bool neg = vn && vp[0] == '-';
      bool isint = vn - st > 0 && vn - st < 19;
      int64_t acc = 0;
      for (int64_t c = st; isint && c < vn; ++c) {
        char ch = vp[c];
        if (ch < '0' || ch > '9') isint = false;
        else acc = acc * 10 + (ch - '0');
      }
      if (isint) {
        val_out[i] = neg ? -(double)acc : (double)acc;
        int_out[i] = 1;
      } else {
        // decimal float shape only: strtod alone would accept 'nan',
        // 'inf', and hex floats, which the reference (and the NaN-as-
        // missing engine sentinel) must reject
        bool shape_ok = vn > 0 && vn < 64;
        for (int64_t c = 0; shape_ok && c < vn; ++c) {
          char ch = vp[c];
          if (!((ch >= '0' && ch <= '9') || ch == '.' || ch == '+' ||
                ch == '-' || ch == 'e' || ch == 'E'))
            shape_ok = false;
        }
        if (!shape_ok) {
          err_out[i] = 3;
          continue;
        }
        char tmp[64];
        std::memcpy(tmp, vp, vn);
        tmp[vn] = 0;
        char* end = nullptr;
        double v = std::strtod(tmp, &end);
        if (end != tmp + vn || v != v) {
          err_out[i] = 3;
          continue;
        }
        val_out[i] = v;
        int_out[i] = 0;
      }
    }
    // tags: validate k=v, sort for a canonical key
    int ntags = ntok - 3;
    if (ntags > 8) {  // the reference's hard tag cap (Const.java:28)
      err_out[i] = 4;
      continue;
    }
    Tok* tags = toks + 3;
    bool bad = false;
    for (int t = 0; t < ntags && !bad; ++t) {
      const char* eq =
          (const char*)memchr(tags[t].p, '=', (size_t)tags[t].n);
      if (!eq || eq == tags[t].p ||
          eq == tags[t].p + tags[t].n - 1) {
        err_out[i] = 4;
        bad = true;
        break;
      }
      if (!valid_name(tags[t].p, eq - tags[t].p) ||
          !valid_name(eq + 1, tags[t].p + tags[t].n - eq - 1)) {
        err_out[i] = 5;
        bad = true;
      }
    }
    if (bad) continue;
    std::sort(tags, tags + ntags, [](const Tok& a, const Tok& b) {
      int c = std::memcmp(a.p, b.p, (size_t)std::min(a.n, b.n));
      return c < 0 || (c == 0 && a.n < b.n);
    });
    key.assign(toks[0].p, (size_t)toks[0].n);
    for (int t = 0; t < ntags; ++t) {
      key.push_back(' ');
      key.append(tags[t].p, (size_t)tags[t].n);
    }
    // import files overwhelmingly write one series' points in runs
    // (scan --import emits them that way): the previous line's key
    // skips the hash lookup for the common case
    int64_t gid;
    if (prev_gid >= 0 && key == prev_key) {
      gid = prev_gid;
    } else {
      auto it = lg->map.find(key);
      if (it == lg->map.end()) {
        gid = (int64_t)lg->map.size();
        lg->map.emplace(key, gid);
        lg->rep_off.push_back(lstart);
        lg->rep_len.push_back(lend - lstart);
      } else {
        gid = it->second;
      }
      prev_key = key;
      prev_gid = gid;
    }
    group_out[i] = gid;
  }
}

// Shortest-round-trip double formatting, portable to libstdc++ < 11:
// gcc-10 hosts ship INTEGER std::to_chars only, so the double call is
// ambiguous among the integer overloads (the build failed outright
// there until this guard). Feature-test the floating-point overload;
// without it, walk %.*g precisions until strtod round-trips — the
// same shortest-digits contract to_chars guarantees by construction,
// so the emitted text parses to the identical double either way (the
// exponent spelling may differ: "1e16" vs "1e+16" — both valid JSON).
inline char* fmt_double_chars(char* p, char* end, double v) {
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  return std::to_chars(p, end, v).ptr;
#else
  char tmp[40];
  // three-step walk, not 1..17 (this path serves every large
  // response on gcc-10 hosts, so it must stay near to_chars speed):
  // %g strips trailing zeros, so %.15g already prints "human" values
  // (0.1, 42.5) at their shortest and round-trips most doubles; 16
  // covers the next band; 17 round-trips everything by construction
  // (no verify needed). A precision-p print that round-trips implies
  // the shortest form needs <= p digits, so this walk reproduces the
  // shortest text (and Python repr) for practical value populations.
  int n = 0;
  for (int prec = 15; prec <= 17; ++prec) {
    n = std::snprintf(tmp, sizeof tmp, "%.*g", prec, v);
    if (prec == 17 || (n > 0 && n < (int)sizeof tmp &&
                       std::strtod(tmp, nullptr) == v))
      break;
  }
  if (n <= 0 || n > end - p) return p;  // caller reserves headroom
  for (int i = 0; i < n; ++i)  // locale hardening: ',' decimal point
    if (tmp[i] == ',') tmp[i] = '.';
  std::memcpy(p, tmp, n);
  return p + n;
#endif
}

}  // namespace

extern "C" {

// 1 when doubles format through real std::to_chars (libstdc++ >= 11),
// 0 on the snprintf round-trip fallback (gcc-10 hosts). The Python
// serializer prefers its own columnar bulk formatter over a slow
// native one — the fallback's strtod verification makes it ~2x the
// cost of the pure-Python path, inverting the reason the native
// formatter exists.
int64_t tss_fmt_fast() {
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  return 1;
#else
  return 0;
#endif
}

// JSON-format a series' datapoints: entries joined by ',' with no
// surrounding braces (the Python serializer owns the envelope).
// seconds != 0 emits ts/1000 (the query's ms_resolution choice);
// as_arrays != 0 emits "[ts,val]" rows instead of "\"ts\":val".
// Value forms match the Python serializer's _format_value: NaN ->
// "NaN" (quoted), +/-inf -> quoted Infinity, integral |v| < 2^53 ->
// integer digits, else shortest round-trip (std::to_chars) with a
// ".0" float marker when the digits carry no '.'/'e' — byte-identical
// to Python repr except the exponent-style choice at |v| >= 1e16
// (both forms parse to the same double).
// Returns bytes written, or -1 if cap is too small.
// Why native: Python pays ~1.3us per point building response JSON;
// a 3M-point response costs 4s of serialization on one core. This
// loop does it ~20x faster.
int64_t tss_format_dps(const int64_t* ts_ms, const double* vals,
                       int64_t n, int seconds, int as_arrays,
                       char* out, int64_t cap) {
  char* p = out;
  char* end = out + cap;
  const double kMaxInt = 9007199254740992.0;  // 2^53
  for (int64_t i = 0; i < n; ++i) {
    if (end - p < 64) return -1;
    if (i) *p++ = ',';
    int64_t t = seconds ? ts_ms[i] / 1000 : ts_ms[i];
    if (as_arrays) {
      *p++ = '[';
      auto r = std::to_chars(p, end, t);
      p = r.ptr;
      *p++ = ',';
    } else {
      *p++ = '"';
      auto r = std::to_chars(p, end, t);
      p = r.ptr;
      *p++ = '"';
      *p++ = ':';
    }
    double v = vals[i];
    if (v != v) {
      std::memcpy(p, "\"NaN\"", 5);
      p += 5;
    } else if (v == std::numeric_limits<double>::infinity()) {
      std::memcpy(p, "\"Infinity\"", 10);
      p += 10;
    } else if (v == -std::numeric_limits<double>::infinity()) {
      std::memcpy(p, "\"-Infinity\"", 11);
      p += 11;
    } else if (v > -kMaxInt && v < kMaxInt &&
               v == (double)(int64_t)v) {
      // range-guard BEFORE the int64 cast: converting an
      // unrepresentable double is UB
      auto r = std::to_chars(p, end, (int64_t)v);
      p = r.ptr;
    } else {
      char* start = p;
      p = fmt_double_chars(p, end, v);
      // Python repr always marks floats (".0" or an exponent);
      // integral doubles >= 2^53 would otherwise print bare digits
      bool marked = false;
      for (char* q = start; q < p; ++q)
        if (*q == '.' || *q == 'e' || *q == 'E') marked = true;
      if (!marked) {
        *p++ = '.';
        *p++ = '0';
      }
    }
    if (as_arrays) *p++ = ']';
  }
  return p - out;
}

// Count '\n' + 1 (array sizing for tss_parse_import without a Python
// bytes.count pass).
int64_t tss_count_lines(const char* buf, int64_t len) {
  int64_t n = 1;
  const char* p = buf;
  const char* end = buf + len;
  while ((p = (const char*)memchr(p, '\n', end - p)) != nullptr) {
    ++n;
    ++p;
  }
  return n;
}

// Scatter-append: line i appends (ts_ms[i], vals[i], ints[i]) onto
// series sids[i]; sids[i] < 0 skips the line (parse errors / rejected
// groups). One call lands a whole parsed import buffer — the per-group
// Python loop with one ctypes call per series cost ~3 s per 10M points
// at 50k series. Returns the number appended, -1 on a bad sid.
int64_t tss_append_lines(void* h, const int64_t* sids, int64_t n,
                         const int64_t* ts_ms, const double* vals,
                         const uint8_t* ints) {
  Store* s = static_cast<Store*>(h);
  int64_t written = 0;
  SeriesBuffer* buf = nullptr;
  int64_t cur = -2;  // current locked-in sid (runs are the common case)
  for (int64_t i = 0; i < n; ++i) {
    int64_t sid = sids[i];
    if (sid < 0) continue;
    if (sid != cur) {
      SeriesBuffer* nb = s->lookup(sid);
      if (buf) buf->mu.unlock();
      if (!nb) {
        s->points_written.fetch_add(written);
        return -1;
      }
      nb->mu.lock();
      buf = nb;
      cur = sid;
    }
    if (buf->sorted && !buf->ts.empty() && ts_ms[i] <= buf->ts.back())
      buf->sorted = false;
    buf->ts.push_back(ts_ms[i]);
    buf->vals.push_back(vals[i]);
    buf->is_int.push_back(ints ? ints[i] : 0);
    ++written;
  }
  if (buf) buf->mu.unlock();
  s->points_written.fetch_add(written);
  return written;
}

// Bulk text-import parser (the reference's TextImporter line format:
// "metric ts value tagk=tagv [tagk=tagv ...]"). Parallel over
// newline-aligned byte chunks:
//   per line i: ts_out[i] (raw, seconds or ms as written), val_out[i],
//   int_out[i] (the value token had integer form), err_out[i]
//   (0 = ok, -1 = blank/comment, >0 = error code), group_out[i] =
//   id of the line's distinct (metric, sorted tags) key or -1.
// rep_off/rep_len[g] give the byte range of group g's first line so
// the caller can parse metric/tag STRINGS once per distinct series
// (UID resolution is per-series, not per-point).
// Error codes: 1 too few fields (a tag is required, like the
// reference), 2 bad timestamp, 3 bad value, 4 malformed tag or too
// many tags, 5 invalid character.
// Returns the number of distinct groups, or -1 if group capacity
// (max_groups) was exceeded. nlines_out gets the number of lines seen
// (caller sizes arrays by tss_count_lines, which is always enough).
int64_t tss_parse_import(const char* buf, int64_t len, int64_t* ts_out,
                         double* val_out, uint8_t* int_out,
                         int64_t* group_out, int32_t* err_out,
                         int64_t* rep_off, int64_t* rep_len,
                         int64_t max_groups, int64_t* nlines_out,
                         int threads) {
  if (threads < 1) threads = 1;
  // chunk boundaries aligned to line starts
  std::vector<int64_t> starts;
  starts.push_back(0);
  for (int t = 1; t < threads; ++t) {
    int64_t pos = len * t / threads;
    const char* nl =
        (const char*)memchr(buf + pos, '\n', (size_t)(len - pos));
    int64_t aligned = nl ? (nl - buf) + 1 : len;
    // aligned == len would create an empty final chunk whose
    // "trailing line without newline" credit (below) belongs to the
    // chunk that actually owns the final bytes — skip it.
    if (aligned > starts.back() && aligned < len) starts.push_back(aligned);
  }
  starts.push_back(len);
  int nchunks = (int)starts.size() - 1;
  // per-chunk line counts -> global line bases
  std::vector<int64_t> nlines(nchunks), base(nchunks);
  {
    std::atomic<int> next{0};
    auto worker = [&]() {
      for (;;) {
        int c = next.fetch_add(1);
        if (c >= nchunks) break;
        int64_t cnt = 0;
        const char* p = buf + starts[c];
        const char* e = buf + starts[c + 1];
        // each line ends with '\n' except possibly the buffer's last
        while ((p = (const char*)memchr(p, '\n', e - p)) != nullptr) {
          ++cnt;
          ++p;
        }
        if (c == nchunks - 1 && len > 0 && buf[len - 1] != '\n')
          ++cnt;  // trailing line without newline
        nlines[c] = cnt;
      }
    };
    std::vector<std::thread> pool;
    for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
    worker();
    for (auto& th : pool) th.join();
  }
  int64_t total_lines = 0;
  for (int c = 0; c < nchunks; ++c) {
    base[c] = total_lines;
    total_lines += nlines[c];
  }
  *nlines_out = total_lines;
  // parse each chunk with a local group table
  std::vector<LocalGroups> locals(nchunks);
  {
    std::atomic<int> next{0};
    auto worker = [&]() {
      for (;;) {
        int c = next.fetch_add(1);
        if (c >= nchunks) break;
        parse_import_range(buf, starts[c], starts[c + 1], base[c],
                           ts_out, val_out, int_out, group_out,
                           err_out, &locals[c]);
      }
    };
    std::vector<std::thread> pool;
    for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
    worker();
    for (auto& th : pool) th.join();
  }
  // merge local tables into the global numbering and remap gids
  std::unordered_map<std::string, int64_t> global;
  std::vector<std::vector<int64_t>> remap(nchunks);
  for (int c = 0; c < nchunks; ++c) {
    remap[c].resize(locals[c].map.size());
    for (auto& kv : locals[c].map) {
      auto it = global.find(kv.first);
      int64_t gid;
      if (it == global.end()) {
        gid = (int64_t)global.size();
        if (gid >= max_groups) return -1;
        global.emplace(kv.first, gid);
        rep_off[gid] = locals[c].rep_off[kv.second];
        rep_len[gid] = locals[c].rep_len[kv.second];
      } else {
        gid = it->second;
      }
      remap[c][kv.second] = gid;
    }
  }
  {
    // local gid -> global gid, every chunk (the merge renumbers in
    // hash-iteration order even for a single chunk)
    std::atomic<int> next{0};
    auto worker = [&]() {
      for (;;) {
        int c = next.fetch_add(1);
        if (c >= nchunks) break;
        for (int64_t i = base[c]; i < base[c] + nlines[c]; ++i)
          if (group_out[i] >= 0)
            group_out[i] = remap[c][group_out[i]];
      }
    };
    std::vector<std::thread> pool;
    for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
    worker();
    for (auto& th : pool) th.join();
  }
  return (int64_t)global.size();
}

}  // extern "C"
