// Native host column store for opentsdb_tpu.
//
// The storage-engine role the reference delegates to HBase region
// servers + the asynchbase client (SURVEY.md L0): append-optimized
// per-series column buffers with lazy sort/dedupe and a parallel
// range-materialize that fills flat (series_idx, ts, value) arrays
// ready for device upload. The Python MemoryBackend is the portable
// twin; this engine removes the per-series Python loop from the
// query path (ref analogue: SaltScanner's 20-way parallel scan,
// src/core/SaltScanner.java:70 — here a thread pool over series).
//
// C ABI (ctypes-friendly), no exceptions across the boundary.
// Build: g++ -O3 -march=native -shared -fPIC -std=c++17 -pthread
//        tsdbstore.cc -o libtsdbstore.so

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <limits>
#include <mutex>
#include <shared_mutex>
#include <thread>
#include <vector>

namespace {

struct SeriesBuffer {
  std::vector<int64_t> ts;
  std::vector<double> vals;
  std::vector<uint8_t> is_int;
  bool sorted = true;
  std::mutex mu;

  void append(int64_t t, double v, uint8_t ii) {
    std::lock_guard<std::mutex> lock(mu);
    if (sorted && !ts.empty() && t <= ts.back()) sorted = false;
    ts.push_back(t);
    vals.push_back(v);
    is_int.push_back(ii);
  }

  void append_many(int64_t n, const int64_t* t, const double* v,
                   const uint8_t* ii) {
    std::lock_guard<std::mutex> lock(mu);
    for (int64_t i = 0; i < n; ++i) {
      if (sorted && !ts.empty() && t[i] <= ts.back()) sorted = false;
      ts.push_back(t[i]);
      vals.push_back(v[i]);
      is_int.push_back(ii ? ii[i] : 0);
    }
  }

  // Sort by timestamp, last-write-wins dedupe (matches the Python
  // SeriesBuffer and the reference's fix_duplicates semantics).
  void ensure_sorted_locked() {
    if (sorted) return;
    const size_t n = ts.size();
    std::vector<uint32_t> order(n);
    for (size_t i = 0; i < n; ++i) order[i] = (uint32_t)i;
    std::stable_sort(order.begin(), order.end(),
                     [&](uint32_t a, uint32_t b) { return ts[a] < ts[b]; });
    std::vector<int64_t> nts;
    std::vector<double> nvals;
    std::vector<uint8_t> nint;
    nts.reserve(n);
    nvals.reserve(n);
    nint.reserve(n);
    for (size_t i = 0; i < n; ++i) {
      uint32_t idx = order[i];
      if (!nts.empty() && nts.back() == ts[idx]) {
        nvals.back() = vals[idx];  // last write wins
        nint.back() = is_int[idx];
      } else {
        nts.push_back(ts[idx]);
        nvals.push_back(vals[idx]);
        nint.push_back(is_int[idx]);
      }
    }
    ts.swap(nts);
    vals.swap(nvals);
    is_int.swap(nint);
    sorted = true;
  }

  // [lo, hi] inclusive range bounds after sorting.
  void range_bounds(int64_t start_ms, int64_t end_ms, int64_t* lo,
                    int64_t* hi) {
    std::lock_guard<std::mutex> lock(mu);
    ensure_sorted_locked();
    *lo = std::lower_bound(ts.begin(), ts.end(), start_ms) - ts.begin();
    *hi = std::upper_bound(ts.begin(), ts.end(), end_ms) - ts.begin();
  }
};

struct Store {
  // The directory vector REALLOCATES on growth, so every indexing
  // access holds the shared lock; the SeriesBuffer objects themselves
  // are heap-stable for the store's lifetime, so captured pointers
  // stay valid after the lock drops (each buffer has its own mutex).
  std::vector<SeriesBuffer*> series;
  std::shared_mutex dir_mu;
  std::atomic<int64_t> points_written{0};

  // nullptr on a bad sid.
  SeriesBuffer* lookup(int64_t sid) {
    std::shared_lock<std::shared_mutex> lock(dir_mu);
    if (sid < 0 || sid >= (int64_t)series.size()) return nullptr;
    return series[sid];
  }

  // Validate + capture all pointers under ONE shared lock (the
  // threaded bulk paths). Returns false on any bad sid.
  bool snapshot(const int64_t* sids, int64_t n,
                std::vector<SeriesBuffer*>* out) {
    std::shared_lock<std::shared_mutex> lock(dir_mu);
    out->resize(n);
    for (int64_t i = 0; i < n; ++i) {
      if (sids[i] < 0 || sids[i] >= (int64_t)series.size())
        return false;
      (*out)[i] = series[sids[i]];
    }
    return true;
  }

  ~Store() {
    for (auto* s : series) delete s;
  }
};

}  // namespace

extern "C" {

void* tss_create() { return new Store(); }

void tss_destroy(void* h) { delete static_cast<Store*>(h); }

// Returns the new series id. Series identity (metric+tags -> sid) is
// managed by the Python wrapper; this just allocates the buffer.
int64_t tss_add_series(void* h) {
  Store* s = static_cast<Store*>(h);
  std::unique_lock<std::shared_mutex> lock(s->dir_mu);
  s->series.push_back(new SeriesBuffer());
  return (int64_t)s->series.size() - 1;
}

int64_t tss_series_count(void* h) {
  Store* s = static_cast<Store*>(h);
  std::shared_lock<std::shared_mutex> lock(s->dir_mu);
  return (int64_t)s->series.size();
}

int tss_append(void* h, int64_t sid, int64_t ts_ms, double value,
               int is_int) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  buf->append(ts_ms, value, (uint8_t)is_int);
  s->points_written.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

int tss_append_many(void* h, int64_t sid, int64_t n, const int64_t* ts,
                    const double* vals, const uint8_t* is_int) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  buf->append_many(n, ts, vals, is_int);
  s->points_written.fetch_add(n, std::memory_order_relaxed);
  return 0;
}

int64_t tss_points_written(void* h) {
  return static_cast<Store*>(h)->points_written.load();
}

// Bulk grid write (the rollup job's output path): for every row i,
// append the mask-selected cells of grid[i, :] (shared bucket_ts
// columns) onto series sids[i]. Threaded over rows; one lock take per
// row instead of per cell. Returns the number of points written, or
// -1 on any invalid sid.
int64_t tss_append_grid(void* h, const int64_t* sids, int64_t nsids,
                        const int64_t* bucket_ts, int64_t nbuckets,
                        const double* grid, const uint8_t* mask,
                        int threads) {
  Store* s = static_cast<Store*>(h);
  std::vector<SeriesBuffer*> bufs;
  if (!s->snapshot(sids, nsids, &bufs)) return -1;
  if (threads < 1) threads = 1;
  std::atomic<int64_t> next{0};
  std::atomic<int64_t> total{0};
  auto worker = [&]() {
    int64_t local = 0;
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= nsids) break;
      SeriesBuffer* buf = bufs[i];
      const double* row = grid + i * nbuckets;
      const uint8_t* m = mask + i * nbuckets;
      std::lock_guard<std::mutex> lock(buf->mu);
      for (int64_t b = 0; b < nbuckets; ++b) {
        if (!m[b]) continue;
        if (buf->sorted && !buf->ts.empty() &&
            bucket_ts[b] <= buf->ts.back())
          buf->sorted = false;
        buf->ts.push_back(bucket_ts[b]);
        buf->vals.push_back(row[b]);
        buf->is_int.push_back(0);
        ++local;
      }
    }
    total.fetch_add(local);
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& t : pool) t.join();
  s->points_written.fetch_add(total.load());
  return total.load();
}

int64_t tss_series_length(void* h, int64_t sid) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  std::lock_guard<std::mutex> lock(buf->mu);
  buf->ensure_sorted_locked();
  return (int64_t)buf->ts.size();
}

// Remove points with start_ms <= ts <= end_ms from one series; returns
// the number deleted (ref: TsdbQuery delete=true issuing
// DeleteRequests per scanned row). -1 on a bad sid.
int64_t tss_delete_range(void* h, int64_t sid, int64_t start_ms,
                         int64_t end_ms) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  std::lock_guard<std::mutex> lock(buf->mu);
  buf->ensure_sorted_locked();
  auto lo = std::lower_bound(buf->ts.begin(), buf->ts.end(), start_ms);
  auto hi = std::upper_bound(buf->ts.begin(), buf->ts.end(), end_ms);
  int64_t n = hi - lo;
  if (n > 0) {
    buf->vals.erase(buf->vals.begin() + (lo - buf->ts.begin()),
                    buf->vals.begin() + (hi - buf->ts.begin()));
    buf->is_int.erase(buf->is_int.begin() + (lo - buf->ts.begin()),
                      buf->is_int.begin() + (hi - buf->ts.begin()));
    buf->ts.erase(lo, hi);
  }
  return n;
}

// Copy one series' sorted columns into caller-provided arrays of
// capacity `cap` (from a prior tss_series_length call). Returns the
// number of elements actually copied — concurrent appends between the
// two calls can grow the buffer past cap (copy truncates) and
// concurrent deletes/dedupes can shrink it (caller trims to the
// return value); never writes past cap. -1 on a bad sid.
int64_t tss_read_series(void* h, int64_t sid, int64_t cap,
                        int64_t* ts_out, double* vals_out,
                        uint8_t* int_out) {
  Store* s = static_cast<Store*>(h);
  SeriesBuffer* buf = s->lookup(sid);
  if (!buf) return -1;
  std::lock_guard<std::mutex> lock(buf->mu);
  buf->ensure_sorted_locked();
  int64_t n = (int64_t)buf->ts.size();
  if (n > cap) n = cap;
  if (n > 0) {
    std::memcpy(ts_out, buf->ts.data(), n * sizeof(int64_t));
    std::memcpy(vals_out, buf->vals.data(), n * sizeof(double));
    if (int_out) std::memcpy(int_out, buf->is_int.data(), n);
  }
  return n;
}

// Phase 1 of materialize: per-series point counts within
// [start_ms, end_ms] (inclusive). Parallel over a thread pool — the
// reference's per-salt-bucket scanner fan-out.
int tss_count_range(void* h, const int64_t* sids, int64_t nsids,
                    int64_t start_ms, int64_t end_ms,
                    int64_t* counts_out, int threads) {
  Store* s = static_cast<Store*>(h);
  std::vector<SeriesBuffer*> bufs;
  if (!s->snapshot(sids, nsids, &bufs)) return -1;
  if (threads < 1) threads = 1;
  std::atomic<int64_t> next{0};
  auto worker = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= nsids) break;
      int64_t lo, hi;
      bufs[i]->range_bounds(start_ms, end_ms, &lo, &hi);
      counts_out[i] = hi - lo;
    }
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return 0;
}

// Phase 2: fill flat output arrays. offsets[i] must hold the exclusive
// prefix sum of the phase-1 counts and counts[i] the phase-1 count
// itself: the copy is capped at counts[i] so appends that land between
// the two phases can never overflow the caller's buffers (they are
// picked up by the next query). series_idx_out gets the *dense*
// position i (0..nsids-1), matching PointBatch.
int tss_fill_range(void* h, const int64_t* sids, int64_t nsids,
                   int64_t start_ms, int64_t end_ms,
                   const int64_t* offsets, const int64_t* counts,
                   int64_t* ts_out, double* vals_out,
                   int32_t* series_idx_out, int threads) {
  Store* s = static_cast<Store*>(h);
  std::vector<SeriesBuffer*> bufs;
  if (!s->snapshot(sids, nsids, &bufs)) return -1;
  if (threads < 1) threads = 1;
  std::atomic<int64_t> next{0};
  auto worker = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= nsids) break;
      SeriesBuffer* buf = bufs[i];
      std::lock_guard<std::mutex> lock(buf->mu);
      buf->ensure_sorted_locked();
      int64_t lo =
          std::lower_bound(buf->ts.begin(), buf->ts.end(), start_ms) -
          buf->ts.begin();
      int64_t hi =
          std::upper_bound(buf->ts.begin(), buf->ts.end(), end_ms) -
          buf->ts.begin();
      int64_t off = offsets[i];
      int64_t n = hi - lo;
      if (n > counts[i]) n = counts[i];
      if (n > 0) {
        std::memcpy(ts_out + off, buf->ts.data() + lo,
                    n * sizeof(int64_t));
        std::memcpy(vals_out + off, buf->vals.data() + lo,
                    n * sizeof(double));
        std::fill(series_idx_out + off, series_idx_out + off + n,
                  (int32_t)i);
      }
      // fewer points than counted (concurrent repair/delete): pad the
      // remainder with NaN placeholders the compute path skips
      for (int64_t j = n < 0 ? 0 : n; j < counts[i]; ++j) {
        ts_out[off + j] = start_ms;
        vals_out[off + j] = std::numeric_limits<double>::quiet_NaN();
        series_idx_out[off + j] = (int32_t)i;
      }
    }
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return 0;
}

// Fused range-scan + fixed-interval downsample pre-reduction: for
// each series i, every point with start_ms <= ts <= end_ms lands in
// bucket b = (ts - t0) / interval_ms (caller guarantees t0 <= start_ms
// and the last bucket covers end_ms), accumulating sum / count / min /
// max. Outputs are [nsids, nbuckets] row-major; cells with count 0
// hold sum 0, min +inf, max -inf (the Python wrapper NaN-fills).
// NaN stored values are skipped, matching the device bucketize's NaN
// guard (ref: Aggregators.runDouble skipping NaN). min_out/max_out may
// be null when the caller only needs sum/count. Threaded over series.
// Returns -1 on a bad sid, else 0.
//
// This removes the [N]-point materialize + host->device upload for
// simple-function downsamples: the device receives S*B cells instead
// of N points (60x smaller for 1m data in 1h buckets) and starts at
// the grid stage of the pipeline.
int tss_bucket_reduce(void* h, const int64_t* sids, int64_t nsids,
                      int64_t start_ms, int64_t end_ms, int64_t t0,
                      int64_t interval_ms, int64_t nbuckets,
                      double* sum_out, double* cnt_out, double* min_out,
                      double* max_out, int threads) {
  Store* s = static_cast<Store*>(h);
  std::vector<SeriesBuffer*> bufs;
  if (!s->snapshot(sids, nsids, &bufs)) return -1;
  if (interval_ms <= 0 || nbuckets <= 0) return -1;
  if (threads < 1) threads = 1;
  std::atomic<int64_t> next{0};
  const double inf = std::numeric_limits<double>::infinity();
  auto worker = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= nsids) break;
      double* srow = sum_out + i * nbuckets;
      double* crow = cnt_out + i * nbuckets;
      double* mnrow = min_out ? min_out + i * nbuckets : nullptr;
      double* mxrow = max_out ? max_out + i * nbuckets : nullptr;
      for (int64_t b = 0; b < nbuckets; ++b) {
        srow[b] = 0.0;
        crow[b] = 0.0;
        if (mnrow) mnrow[b] = inf;
        if (mxrow) mxrow[b] = -inf;
      }
      SeriesBuffer* buf = bufs[i];
      std::lock_guard<std::mutex> lock(buf->mu);
      buf->ensure_sorted_locked();
      int64_t lo =
          std::lower_bound(buf->ts.begin(), buf->ts.end(), start_ms) -
          buf->ts.begin();
      int64_t hi =
          std::upper_bound(buf->ts.begin(), buf->ts.end(), end_ms) -
          buf->ts.begin();
      // timestamps are sorted: resolve each bucket's point range with
      // a binary search, then accumulate over a fixed-bound inner loop
      // the compiler can vectorize (no per-point divide or
      // data-dependent exit). The NaN guard is a branchless blend.
      const int64_t* tsd = buf->ts.data();
      const double* vd = buf->vals.data();
      int64_t p = lo;
      while (p < hi) {
        // floor division (C++ '/' truncates toward zero): a point just
        // below t0 must be DROPPED like the Python twin's '//' does,
        // not folded into bucket 0
        int64_t d = tsd[p] - t0;
        int64_t b = d >= 0 ? d / interval_ms : -1;
        if (b < 0) {  // cannot happen when t0 <= start_ms; be safe
          ++p;
          continue;
        }
        if (b >= nbuckets) break;
        int64_t bucket_end = t0 + (b + 1) * interval_ms;
        int64_t pe =
            std::lower_bound(tsd + p, tsd + hi, bucket_end) - tsd;
        double sum = 0.0, cnt = 0.0;
        if (mnrow) {
          double mn = inf, mx = -inf;
          for (int64_t q = p; q < pe; ++q) {
            double v = vd[q];
            bool ok = v == v;
            sum += ok ? v : 0.0;
            cnt += ok ? 1.0 : 0.0;
            mn = (ok && v < mn) ? v : mn;
            mx = (ok && v > mx) ? v : mx;
          }
          mnrow[b] = mn;
          mxrow[b] = mx;
        } else {
          for (int64_t q = p; q < pe; ++q) {
            double v = vd[q];
            bool ok = v == v;
            sum += ok ? v : 0.0;
            cnt += ok ? 1.0 : 0.0;
          }
        }
        srow[b] = sum;
        crow[b] = cnt;
        p = pe;
      }
    }
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return 0;
}

}  // extern "C"
