"""Observability subsystem: end-to-end request tracing and
self-telemetry.

- :mod:`opentsdb_tpu.obs.trace` — low-overhead ring-buffered, sampled
  span records wrapping every stage of the three hot paths (ingest,
  query, background maintenance), with cluster trace-id propagation
  (router scatter/forward headers stitch one trace across shards), a
  slow-request log, and a persisted query-shape log for offline
  workload mining.
- :mod:`opentsdb_tpu.obs.telemetry` — the ``tsd.stats.self_interval``
  loop that ingests the TSD's own counters, gauges and stage-latency
  percentiles into its *own* store as ``tsd.*`` series, so dashboards,
  continuous queries, lifecycle policies and the cluster tier all
  apply to the TSD monitoring itself.

Surfaces: ``GET /api/trace`` (recent roots), ``GET /api/trace/<id>``
(full span tree, cluster-stitched on a router), per-stage latency
percentiles at ``/api/stats`` + ``/api/health``.
"""

from opentsdb_tpu.obs.trace import (KNOWN_SPANS, Tracer, current,
                                    record_span, trace_begin,
                                    trace_end, trace_span, use)

__all__ = ["KNOWN_SPANS", "Tracer", "current", "record_span",
           "trace_begin", "trace_end", "trace_span", "use"]
