"""Observability subsystem: end-to-end request tracing and
self-telemetry.

- :mod:`opentsdb_tpu.obs.trace` — low-overhead ring-buffered, sampled
  span records wrapping every stage of the three hot paths (ingest,
  query, background maintenance), with cluster trace-id propagation
  (router scatter/forward headers stitch one trace across shards), a
  slow-request log, and a persisted query-shape log for offline
  workload mining.
- :mod:`opentsdb_tpu.obs.telemetry` — the ``tsd.stats.self_interval``
  loop that ingests the TSD's own counters, gauges and stage-latency
  percentiles into its *own* store as ``tsd.*`` series, so dashboards,
  continuous queries, lifecycle policies and the cluster tier all
  apply to the TSD monitoring itself.
- :mod:`opentsdb_tpu.obs.openmetrics` — the ``GET /metrics``
  exposition renderer: the full stats registry in OpenMetrics text,
  histograms in native cumulative ``_bucket``/``_sum``/``_count``
  form, for the Prometheus ecosystem.
- :mod:`opentsdb_tpu.obs.profiler` — the continuous sampling
  profiler: per-thread-role folded stacks over a bounded ring,
  served flamegraph-ready at ``GET /api/profile``.
- :mod:`opentsdb_tpu.obs.slo` — per-endpoint SLO objectives and
  multi-window burn-rate gauges (``tsd.slo.*``).

Surfaces: ``GET /api/trace`` (recent roots), ``GET /api/trace/<id>``
(full span tree, cluster-stitched on a router), per-stage latency
percentiles at ``/api/stats`` + ``/api/health``, ``GET /metrics``,
``GET /api/profile``.
"""

from opentsdb_tpu.obs.trace import (KNOWN_SPANS, Tracer, current,
                                    record_span, trace_begin,
                                    trace_end, trace_span, use)

__all__ = ["KNOWN_SPANS", "Tracer", "current", "record_span",
           "trace_begin", "trace_end", "trace_span", "use"]
