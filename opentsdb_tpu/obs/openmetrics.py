"""OpenMetrics exposition: ``GET /metrics`` for the Prometheus world.

The self-telemetry pump (obs/telemetry.py) lets the TSD monitor
itself; this module lets everything ELSE monitor the TSD without
adopting its stack. One renderer walks the full stats registry —
every ``collect_stats`` provider's counters and gauges, the PR-11
latency ``Histogram``\\ s in native cumulative ``_bucket``/``_sum``/
``_count`` form, and the SLO burn-rate gauges — and emits the
OpenMetrics text format with stable ``tsd_``-prefixed names:

- record names mangle ``.``/``-`` (and anything outside
  ``[a-zA-Z0-9_:]``) to ``_``: ``tsd.datapoints.added`` →
  ``tsd_datapoints_added``;
- record tags become labels, values escaped per the spec
  (``\\`` → ``\\\\``, ``"`` → ``\\"``, newline → ``\\n``);
- counters (everything :func:`opentsdb_tpu.stats.stats.is_gauge`
  doesn't classify as a level) expose the spec-required ``_total``
  sample suffix;
- histograms render cumulative ``le``-labeled buckets (the registry's
  bucket UPPER bounds, ``+Inf`` last) with exact ``_count``/``_sum``;
- the document ends with ``# EOF``.

The renderer is read-only over snapshots: a scrape never blocks an
``add()`` beyond one bucket-list copy per histogram.
"""

from __future__ import annotations

import bisect
import re
from typing import Any

from opentsdb_tpu.stats.stats import is_gauge

CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def metric_name(raw: str) -> str:
    """Mangle one record name onto the metric-name charset; a leading
    digit gets an underscore prefix."""
    name = _NAME_BAD.sub("_", raw)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{metric_name(str(k))}="{escape_label(v)}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt(value: float) -> str:
    f = float(value)
    if f.is_integer() and abs(f) < 2 ** 53:
        return str(int(f))
    return repr(f)


def render(tsdb) -> bytes:
    """The full exposition document for one TSD (server-level
    providers — connections, admission — are registered into
    ``tsdb.stats`` by TSDServer, so their records ride along)."""
    out: list[str] = []

    # -- counters + gauges from the record stream ----------------------
    # (latency percentile records are suppressed: the same histograms
    # are served natively below)
    collector = tsdb.stats.collect(latency_percentiles=False)
    tsdb.collect_stats(collector)
    families: dict[str, list[tuple[dict[str, str], float]]] = {}
    kinds: dict[str, str] = {}
    for raw_name, value, tags in collector.records:
        fam = metric_name(raw_name)
        bare = raw_name.split(".", 1)[1] if "." in raw_name \
            else raw_name
        kind = "gauge" if is_gauge(bare) else "counter"
        # one family, one type: if any record under the name reads as
        # a gauge, the family is a gauge (summing would be wrong)
        if kinds.get(fam) == "gauge":
            kind = "gauge"
        kinds[fam] = kind
        families.setdefault(fam, []).append((dict(tags), value))
    for fam in sorted(families):
        kind = kinds[fam]
        out.append(f"# HELP {fam} stats record {fam}")
        out.append(f"# TYPE {fam} {kind}")
        suffix = "_total" if kind == "counter" else ""
        seen: dict[str, int] = {}
        for labels, value in families[fam]:
            ls = _label_str(labels)
            line = f"{fam}{suffix}{ls} {_fmt(value)}"
            # exact (family, labelset) duplicates keep the LAST value
            # (a provider re-reporting within one collect pass)
            if ls in seen:
                out[seen[ls]] = line
            else:
                seen[ls] = len(out)
                out.append(line)

    # -- histograms: native cumulative exposition ----------------------
    hist_families: dict[str, list[tuple[dict[str, str], dict]]] = {}
    for fam, labels, hist in tsdb.stats.histograms():
        hist_families.setdefault(metric_name(fam), []).append(
            (labels, hist.snapshot()))
    for fam in sorted(hist_families):
        out.append(f"# HELP {fam} latency histogram {fam}")
        out.append(f"# TYPE {fam} histogram")
        for labels, snap in hist_families[fam]:
            render_histogram(out, fam, labels, snap)

    # (SLO burn-rate gauges ride the record stream above — the
    # tracker's collect_stats emits slo.burn_rate per endpoint/slo/
    # window, classified gauge by is_gauge)

    out.append("# EOF")
    return ("\n".join(out) + "\n").encode("utf-8")


# exposition bucket ladder (ms): the registry's 1ms-linear histograms
# have ~8000 internal buckets — full fidelity belongs to the fleet
# merge (/api/stats/raw), not to a scrape body. Each ladder value maps
# to the LARGEST internal bound <= it, so every emitted cumulative
# count is EXACT for its printed `le` threshold (never interpolated).
_EXPO_LADDER = (1, 2, 3, 5, 8, 13, 21, 34, 55, 90, 150, 250, 400,
                650, 1000, 1700, 2800, 4600, 8000, 16000)


def exposition_points(bounds: list) -> list[tuple[int, float]]:
    """(internal bucket index, bound) pairs for the scrape ladder —
    always includes the last internal bound so `le=<max>` meets
    `+Inf`."""
    out: list[tuple[int, float]] = []
    for ladder in _EXPO_LADDER:
        i = bisect.bisect_right(bounds, ladder) - 1
        if i >= 0 and (not out or out[-1][0] != i):
            out.append((i, float(bounds[i])))
    last = len(bounds) - 1
    if not out or out[-1][0] != last:
        out.append((last, float(bounds[last])))
    return out


def render_histogram(out: list[str], fam: str,
                     labels: dict[str, Any], snap: dict) -> None:
    """Append one label-set's cumulative bucket series."""
    bounds, buckets = snap["bounds"], snap["buckets"]
    prev_idx = -1
    acc = 0
    for idx, bound in exposition_points(bounds):
        acc += sum(buckets[prev_idx + 1:idx + 1])
        prev_idx = idx
        ls = _label_str({**labels, "le": _fmt(bound)})
        out.append(f"{fam}_bucket{ls} {acc}")
    ls = _label_str({**labels, "le": "+Inf"})
    out.append(f"{fam}_bucket{ls} {snap['count']}")
    base = _label_str(labels)
    out.append(f"{fam}_sum{base} {_fmt(snap['sum'])}")
    out.append(f"{fam}_count{base} {snap['count']}")


__all__ = ["CONTENT_TYPE", "escape_label", "metric_name", "render",
           "render_histogram"]
