"""Continuous sampling profiler: where CPU time goes, per thread role.

The stage histograms (PR 11) say *which stage* is slow; this says
*what the code was doing* while it was slow. A single bounded
background thread samples ``sys._current_frames()`` at
``tsd.profile.hz`` (default 4 Hz — cheap enough to leave on), folds
each thread's stack into a collapsed-text line (``frame;frame;leaf``,
the flamegraph.pl / speedscope input format), classifies the thread
into a role by its name (ingest / query / fold-worker / cluster /
background / serve), and accumulates counts into a ring of per-second
buckets covering the last ``tsd.profile.ring_s`` seconds — so the
minute BEFORE an incident is queryable after the fact, no restart or
arm step needed.

Surface: ``GET /api/profile?seconds=N[&format=collapsed|json]
[&role=query]`` renders the merged window. Collapsed text prepends
the role as the root frame, so one flamegraph shows the fleet of
thread pools side by side.

Lifecycle: the sampler thread is started by :class:`TSDServer` and
joined by :meth:`stop` (called from ``TSDB.shutdown``) — the
thread-lifecycle tsdlint pass and the leak witness both hold it to
that."""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Any

LOG = logging.getLogger("obs.profiler")

#: thread-name prefix -> role (first match wins; the table mirrors the
#: thread_name_prefix/name= spellings used across the package)
_ROLE_PREFIXES: tuple[tuple[str, str], ...] = (
    ("tsd-query", "query"),          # server query worker pool
    ("tsd-subq", "query"),           # sub-query fan-out pool
    ("tsd-cluster", "cluster"),      # router scatter/forward pool
    ("cluster-", "cluster"),         # replay / backfill / retire loops
    ("tsd-stream-fold", "fold-worker"),
    ("asyncio", "ingest"),           # default-executor handlers: puts,
    #                                  telnet bursts, admin endpoints
    ("tsd-telemetry", "background"),
    ("tsd-lifecycle", "background"),
    ("tsd-warmup", "background"),
    ("wal", "background"),
    ("MainThread", "serve"),         # the asyncio event loop
)


def thread_role(name: str) -> str:
    for prefix, role in _ROLE_PREFIXES:
        if name.startswith(prefix):
            return role
    return "other"


class SamplingProfiler:
    """The sampler thread + its bounded per-second ring."""

    def __init__(self, tsdb):
        config = tsdb.config
        self.enabled = config.get_bool("tsd.profile.enable", True)
        # clamped: 0 disables, 250 Hz is already past the point where
        # the GIL-held frame walk starts to tax the workload
        self.hz = min(max(config.get_float("tsd.profile.hz", 4.0),
                          0.0), 250.0)
        self.ring_s = max(config.get_int("tsd.profile.ring_s", 60), 1)
        self.max_depth = max(config.get_int("tsd.profile.max_depth",
                                            48), 4)
        self._lock = threading.Lock()
        # (epoch second, {role: {folded stack: count}}) — maxlen
        # bounds retention to the configured window
        self._ring: deque = deque(maxlen=self.ring_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.samples = 0           # sampler wakes
        self.stacks_folded = 0     # thread stacks accumulated
        self.sample_errors = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if not self.enabled or self.hz <= 0 or self._thread is not None:
            return
        self._stop.clear()
        t = threading.Thread(target=self._loop, name="tsd-profiler",
                             daemon=True)
        self._thread = t
        t.start()
        LOG.info("sampling profiler running at %.1f Hz (%ds ring)",
                 self.hz, self.ring_s)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - profiler must outlive
                # tsdlint: allow[swallow] a failed sample is counted;
                # the profiler thread must never die mid-deployment
                self.sample_errors += 1

    # -- sampling ------------------------------------------------------

    def sample_once(self, now_s: float | None = None) -> int:
        """One pass over every live thread's current frame (manually
        callable — tests and the bench drive it deterministically).
        Returns the number of stacks folded."""
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        # sys._current_frames holds the GIL for the dict build; the
        # per-frame walk below reads immutable f_back chains
        frames = sys._current_frames()
        sec = int(now_s if now_s is not None else time.time())
        folded: list[tuple[str, str]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue  # the profiler observing itself is noise
            role = thread_role(names.get(ident, ""))
            parts: list[str] = []
            depth = 0
            while frame is not None and depth < self.max_depth:
                code = frame.f_code
                parts.append(f"{os.path.basename(code.co_filename)}"
                             f":{code.co_name}")
                frame = frame.f_back
                depth += 1
            if not parts:
                continue
            # outermost frame first — flamegraph root-to-leaf order
            folded.append((role, ";".join(reversed(parts))))
        with self._lock:
            if not self._ring or self._ring[-1][0] != sec:
                self._ring.append((sec, {}))
            bucket = self._ring[-1][1]
            for role, stack in folded:
                per = bucket.setdefault(role, {})
                per[stack] = per.get(stack, 0) + 1
            self.samples += 1
            self.stacks_folded += len(folded)
        return len(folded)

    # -- retrieval -----------------------------------------------------

    def report(self, seconds: int | None = None,
               role: str = "", now_s: float | None = None
               ) -> dict[str, dict[str, int]]:
        """Merged ``{role: {stack: count}}`` over the trailing
        ``seconds`` of the ring (clamped to the ring span)."""
        window = min(max(int(seconds or self.ring_s), 1), self.ring_s)
        now = int(now_s if now_s is not None else time.time())
        with self._lock:
            buckets = list(self._ring)
        out: dict[str, dict[str, int]] = {}
        for sec, per_role in buckets:
            if now - sec >= window:
                continue
            for r, stacks in per_role.items():
                if role and r != role:
                    continue
                acc = out.setdefault(r, {})
                for stack, n in stacks.items():
                    acc[stack] = acc.get(stack, 0) + n
        return out

    def collapsed(self, seconds: int | None = None, role: str = "",
                  now_s: float | None = None) -> str:
        """Flamegraph-ready collapsed text: one ``role;stack count``
        line per distinct stack, role as the root frame."""
        lines = []
        for r, stacks in sorted(self.report(seconds, role,
                                            now_s).items()):
            for stack, n in sorted(stacks.items()):
                lines.append(f"{r};{stack} {n}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -- observability -------------------------------------------------

    def collect_stats(self, collector) -> None:
        collector.record("profiler.samples", self.samples)
        collector.record("profiler.stacks_folded", self.stacks_folded)
        collector.record("profiler.sample_errors", self.sample_errors)

    def health_info(self) -> dict[str, Any]:
        with self._lock:
            ring_len = len(self._ring)
        return {
            "enabled": self.enabled,
            "running": self.running,
            "hz": self.hz,
            "ring_s": self.ring_s,
            "ring_filled_s": ring_len,
            "samples": self.samples,
            "stacks_folded": self.stacks_folded,
            "sample_errors": self.sample_errors,
        }


__all__ = ["SamplingProfiler", "thread_role"]
