"""SLO burn-rate derivation: "are we eating the error budget" as one
query.

Per-endpoint objectives are declared in config (``tsd.slo.*``): a
latency objective ("99% of queries answer under 1000 ms") and an
availability objective ("99.9% of queries don't shed or 5xx"). The
tracker folds every served request into per-10s buckets of
``(total, slow, errored)`` per endpoint — fed by the socket server
at response time (so admission-shed 503s and query-timeout 504s,
which never enter the HTTP router, still burn the budget and the
latency includes the queue wait), or by :meth:`HttpRpcRouter.handle`
for direct-handler callers (tests, benches) — and derives
**multi-window burn rates** on read (the Google SRE workbook shape: a
short window catches fast burns, a long window catches slow leaks)::

    burn = (bad_fraction over window) / (1 - objective)

1.0 means the error budget is being consumed exactly at the rate that
exhausts it by the end of the SLO period; alert thresholds are
typically 14.4 (fast) and ~1-6 (slow). The gauges export at
``/metrics`` (``tsd_slo_burn_rate{endpoint,slo,window}``) and in the
``slo`` section of ``/api/health``.

The bucket ring is bounded by the longest configured window, so the
tracker is O(windows) memory regardless of traffic.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

#: endpoint -> (latency_ms default, latency objective default,
#: availability objective default)
_ENDPOINT_DEFAULTS = {
    "query": (1000.0, 0.99, 0.999),
    "put": (500.0, 0.99, 0.999),
}

_BUCKET_S = 10


class SloTracker:
    """Windowed good/bad event counts + burn-rate gauges."""

    def __init__(self, config):
        self.enabled = config.get_bool("tsd.slo.enable", True)
        windows = []
        for part in config.get_string("tsd.slo.windows",
                                      "300,3600").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                w = int(part)
            except ValueError:
                continue
            if w >= _BUCKET_S:
                windows.append(w)
        self.windows_s: tuple[int, ...] = tuple(sorted(set(windows))) \
            or (300, 3600)
        self.objectives: dict[str, dict[str, float]] = {}
        for ep, (lat_ms, lat_obj, avail_obj) in \
                _ENDPOINT_DEFAULTS.items():
            self.objectives[ep] = {
                "latency_ms": config.get_float(
                    f"tsd.slo.{ep}.latency_ms", lat_ms),
                "latency_objective": _clamp_objective(config.get_float(
                    f"tsd.slo.{ep}.latency_objective", lat_obj)),
                "availability_objective": _clamp_objective(
                    config.get_float(
                        f"tsd.slo.{ep}.availability_objective",
                        avail_obj)),
            }
        self._lock = threading.Lock()
        # ring of (bucket start second, {endpoint: [total, slow, err]})
        # — bounded by the longest window
        self._buckets: deque = deque(
            maxlen=max(self.windows_s) // _BUCKET_S + 1)
        self.events = 0

    # -- feed ----------------------------------------------------------

    def record(self, endpoint: str, latency_ms: float,
               errored: bool, now_s: float | None = None) -> None:
        """One served request. ``errored`` = the availability-SLO
        violation (5xx/shed); the latency SLO additionally counts the
        request bad when it exceeded the endpoint's threshold."""
        obj = self.objectives.get(endpoint)
        if obj is None or not self.enabled:
            return
        now = int(now_s if now_s is not None else time.time())
        sec = now - now % _BUCKET_S
        slow = latency_ms > obj["latency_ms"]
        with self._lock:
            if not self._buckets or self._buckets[-1][0] != sec:
                self._buckets.append((sec, {}))
            per = self._buckets[-1][1].setdefault(endpoint, [0, 0, 0])
            per[0] += 1
            if slow:
                per[1] += 1
            if errored:
                per[2] += 1
            self.events += 1

    # -- derivation ----------------------------------------------------

    def _window_counts(self, now: int) -> dict[int, dict[str, list]]:
        """{window_s: {endpoint: [total, slow, err]}} in one pass over
        a locked snapshot of the ring."""
        with self._lock:
            buckets = list(self._buckets)
        out: dict[int, dict[str, list]] = {
            w: {} for w in self.windows_s}
        for sec, per in buckets:
            age = now - sec
            for w in self.windows_s:
                if age >= w:
                    continue
                acc = out[w]
                for ep, (total, slow, err) in per.items():
                    a = acc.setdefault(ep, [0, 0, 0])
                    a[0] += total
                    a[1] += slow
                    a[2] += err
        return out

    def burn_rates(self, now_s: float | None = None
                   ) -> dict[str, dict[str, dict[str, float]]]:
        """{endpoint: {slo: {window label: burn}}}. Windows with no
        traffic report 0.0 (no evidence of burn, not "unknown" — a
        health probe must not flap on an idle TSD)."""
        now = int(now_s if now_s is not None else time.time())
        counts = self._window_counts(now)
        out: dict[str, dict[str, dict[str, float]]] = {}
        for ep, obj in self.objectives.items():
            per_slo: dict[str, dict[str, float]] = {
                "latency": {}, "availability": {}}
            for w in self.windows_s:
                label = _window_label(w)
                total, slow, err = counts[w].get(ep, (0, 0, 0))
                per_slo["latency"][label] = _burn(
                    slow, total, obj["latency_objective"])
                per_slo["availability"][label] = _burn(
                    err, total, obj["availability_objective"])
            out[ep] = per_slo
        return out

    # -- exposition ----------------------------------------------------

    def gauges(self, now_s: float | None = None
               ) -> list[tuple[dict[str, str], float]]:
        """Flat (labels, value) burn-rate samples for /metrics."""
        out = []
        for ep, per_slo in self.burn_rates(now_s).items():
            for slo, per_w in per_slo.items():
                for label, burn in per_w.items():
                    out.append(({"endpoint": ep, "slo": slo,
                                 "window": label}, burn))
        return out

    def collect_stats(self, collector) -> None:
        if not self.enabled:
            return
        for labels, burn in self.gauges():
            collector.record("slo.burn_rate", burn, **labels)
        collector.record("slo.events", self.events)

    def health_info(self, now_s: float | None = None
                    ) -> dict[str, Any]:
        doc: dict[str, Any] = {
            "enabled": self.enabled,
            "windows_s": list(self.windows_s),
            "events": self.events,
        }
        if self.enabled:
            doc["objectives"] = {
                ep: dict(obj) for ep, obj in self.objectives.items()}
            doc["burn_rates"] = self.burn_rates(now_s)
        return doc


def _clamp_objective(x: float) -> float:
    """Objectives live strictly inside (0, 1) — 1.0 would make the
    budget zero and every burn infinite."""
    return min(max(x, 0.0), 0.999999)


def _burn(bad: int, total: int, objective: float) -> float:
    if total <= 0 or bad <= 0:
        return 0.0
    return round((bad / total) / (1.0 - objective), 4)


def _window_label(w: int) -> str:
    if w % 3600 == 0:
        return f"{w // 3600}h"
    if w % 60 == 0:
        return f"{w // 60}m"
    return f"{w}s"


__all__ = ["SloTracker"]
