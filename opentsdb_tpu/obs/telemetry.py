"""Self-telemetry: the TSD ingests its own stats as ``tsd.*`` series.

A ``tsd.stats.self_interval`` loop snapshots everything the stats
registry knows — every component counter, the gauges (WAL sync lag,
fold-worker backlog, spool depth, cache bytes — all registered
providers), and the per-stage latency percentiles — and writes them
into the TSD's *own* store through the normal
:meth:`TSDB.add_point_groups` ingest path. The payoff is that every
serving feature applies to the TSD monitoring itself: dashboards and
``/api/query`` work on ``tsd.*`` metrics, continuous queries maintain
live windows over them, lifecycle policies age them out, and on a
cluster **router** the pump forwards through the consistent-hash ring
like any other write, so the fleet's self-metrics live in the fleet.

Metric names are the collector's (already ``tsd.``-prefixed); tag
values are sanitized to the storage charset (``:`` in peer addresses
becomes ``_``). UIDs for self-metrics are minted directly — an
operator's ``tsd.core.auto_create_metrics=false`` policy governs
client traffic, not the TSD's own heartbeat.
"""

from __future__ import annotations

import logging
import math
import threading
import time

from opentsdb_tpu.obs import trace as trace_mod

LOG = logging.getLogger("obs.telemetry")

_ALLOWED_PUNCT = set("-_./")


def _sanitize(value: str) -> str:
    """Map an arbitrary label onto the tag-value charset."""
    out = "".join(c if (c.isascii() and c.isalnum())
                  or c in _ALLOWED_PUNCT else "_"
                  for c in str(value))
    return out or "_"


class SelfTelemetry:
    """The pump + its background loop. ``tsd.stats.self_interval``
    seconds between pumps; <= 0 disables the loop (``pump()`` stays
    callable — tests and operators can drive it manually)."""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        self.interval_s = tsdb.config.get_float(
            "tsd.stats.self_interval", 0.0)
        # node identity tag: every record carries host=<this node> so
        # a fleet of shards' self-series stay distinguishable when a
        # router-side query merges them (a constant tag would fold
        # every node into one series). tsd.stats.self_tag overrides;
        # default = hostname-port.
        tag = tsdb.config.get_string("tsd.stats.self_tag", "")
        if not tag:
            import platform
            tag = (f"{platform.node() or 'tsd'}-"
                   f"{tsdb.config.get_int('tsd.network.port', 4242)}")
        self.host_tag = _sanitize(tag)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.pumps = 0
        self.points_written = 0
        self.point_errors = 0
        self.pump_errors = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._stop.clear()
        t = threading.Thread(target=self._loop, name="tsd-telemetry",
                             daemon=True)
        self._thread = t
        t.start()
        LOG.info("self-telemetry pumping every %.0fs",
                 self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.pump()
            except Exception:  # noqa: BLE001 - heartbeat must survive
                # tsdlint: allow[swallow] the loop outlives any pump
                # failure; pump() already counted and logged it
                LOG.exception("self-telemetry pump failed")

    # -- the pump ------------------------------------------------------

    def snapshot(self) -> list[tuple[str, float, dict[str, str]]]:
        """One collector pass over every registered provider (the
        same records ``/api/stats`` serves), values filtered to
        finite floats and tags sanitized to the storage charset."""
        t = self.tsdb
        collector = t.stats.collect()
        t.collect_stats(collector)
        out = []
        for name, value, tags in collector.records:
            if not math.isfinite(value):
                continue
            clean = {_sanitize(k): _sanitize(v)
                     for k, v in tags.items()}
            clean.setdefault("host", self.host_tag)
            out.append((name, float(value), clean))
        return out

    def pump(self, now_s: int | None = None) -> int:
        """Ingest one snapshot; returns points written. On a router
        the points forward through the ring (the router's own store
        serves no queries); standalone/shard TSDs take the normal
        columnar group write — WAL, stream taps, lifecycle and the
        result-cache invalidation all see it like any client put."""
        t = self.tsdb
        tracer = getattr(t, "tracer", None)
        tctx = tracer.start_background("telemetry.pump") \
            if tracer is not None else None
        now = int(now_s if now_s is not None else time.time())
        written = 0
        try:
            with trace_mod.use(tctx):
                records = self.snapshot()
                cluster = t.cluster
                if cluster is not None:
                    points = [{"metric": m, "timestamp": now,
                               "value": v, "tags": tg}
                              for m, v, tg in records]
                    written, failed, _errs = \
                        cluster.forward_writes(points)
                    self.point_errors += failed
                else:
                    groups = []
                    for metric, value, tags in records:
                        # self-metrics mint their own UIDs: the
                        # auto-create policy gates CLIENT traffic,
                        # not the TSD's heartbeat
                        t.uids.metrics.get_or_create_id(metric)
                        for k, v in tags.items():
                            t.uids.tag_names.get_or_create_id(k)
                            t.uids.tag_values.get_or_create_id(v)
                        groups.append((metric, tags, [None], [now],
                                       [value]))

                    def on_error(_ref, _exc) -> None:
                        self.point_errors += 1

                    written, _errs = t.add_point_groups(
                        groups, on_error=on_error)
            self.pumps += 1
            self.points_written += written
            if tctx is not None:
                tctx.tag(points=written)
        except Exception as exc:
            self.pump_errors += 1
            if tctx is not None:
                tctx.set_error(exc)
            raise
        finally:
            if tracer is not None:
                tracer.finish(tctx)
        return written

    # -- observability -------------------------------------------------

    def collect_stats(self, collector) -> None:
        collector.record("telemetry.pumps", self.pumps)
        collector.record("telemetry.points", self.points_written)
        collector.record("telemetry.point_errors", self.point_errors)
        collector.record("telemetry.pump_errors", self.pump_errors)

    def health_info(self) -> dict:
        return {
            "interval_s": self.interval_s,
            "running": self._thread is not None
            and self._thread.is_alive(),
            "pumps": self.pumps,
            "points_written": self.points_written,
            "point_errors": self.point_errors,
            "pump_errors": self.pump_errors,
        }
