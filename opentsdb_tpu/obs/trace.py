"""Per-request distributed tracing: cheap sampled span records.

Design constraints, in priority order:

1. **Overhead is first-class.** With ``tsd.trace.enable = false`` (or
   outside a traced request) every instrumentation site costs one
   thread-local read returning ``None``. With tracing on, a span is
   two ``time.monotonic()`` calls, one small object and one
   lock-guarded list append — spans wrap request-scoped *stages*
   (decode, WAL commit wait, plan, execute, serialize), never
   per-point work. Sampling (``tsd.trace.sample`` = keep 1 in N
   request roots) gates only *retention*: every request still records
   its spans so the slow-request log can keep ANY slow trace at full
   fidelity, and the per-stage latency histograms see every request,
   not just the sampled ones.
2. **One trace spans the cluster.** The router stamps an
   ``X-TSD-Trace`` header (``trace_id:parent_span_id:sampled``) on
   every shard scatter / write forward; the shard roots its own
   subtree under the router's per-peer span and honors the router's
   sampling decision, so ``GET /api/trace/<id>`` on the router can
   stitch the full tree from every surviving shard's ring. Span ids
   carry a per-context random nonce so ids from different nodes never
   collide in a stitched tree.
3. **Slow traces are never lost.** ``tsd.query.slowlog.threshold_ms``
   forces retention of any query root past the threshold (plus a WARN
   logring entry carrying the trace id) regardless of sampling, into
   a separate bounded slow ring so a burst of normal traffic cannot
   evict the evidence.

Span names form a CLOSED registry (:data:`KNOWN_SPANS`, the
``faults.KNOWN_SITES`` idiom): starting an unregistered name raises,
and tsdlint's ``trace-sites`` pass enforces it statically (plus
reports registered-but-never-started names as stale).

The query-shape log is the explicit precursor to workload-adaptive
summaries (ROADMAP item 5 / Storyboard): each committed ``query.http``
trace appends one JSONL line — metric, filters, downsample, pixel
budget, cache outcome, per-stage breakdown — to a bounded rotating
file in ``data_dir`` for offline mining.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import secrets
import threading
import time
from collections import deque
from typing import Any

LOG = logging.getLogger("obs.trace")

# ---------------------------------------------------------------------------
# span-name registry
# ---------------------------------------------------------------------------
# Every span name started anywhere — roots and stages — must resolve
# here. tsdlint's ``trace-sites`` pass enforces it statically (an
# unregistered literal is a finding; a registered name never started
# is reported stale) and :meth:`TraceContext.begin` enforces it at
# runtime, so a typo'd stage name fails the first test that crosses it
# instead of silently recording an orphan stage.

KNOWN_SPANS: frozenset[str] = frozenset({
    # request roots
    "ingest.put",            # HTTP /api/put body
    "ingest.telnet",         # one telnet put burst
    "query.http",            # /api/query
    # background roots
    "lifecycle.sweep",       # lifecycle/manager.py sweep
    "streaming.drain",       # streaming/workers.py off-path fold drain
    "cluster.spool.replay",  # cluster/router.py spool catch-up drain
    "cluster.replica.repair",  # cluster/router.py anti-entropy pass
    "cluster.reshard.backfill",  # cluster/reshard.py moved-key copy
    "cluster.retire",        # cluster/retire.py stale-copy delete
    "cluster.gossip.push",   # cluster/gossip.py sibling push round
    "cluster.read_repair",   # cluster/router.py staged-hint drain
    "telemetry.pump",        # obs/telemetry.py self-stats ingest
    "control.loop",          # control/plane.py one control tick
    # ingest stages
    "ingest.decode",         # body parse + validate + series grouping
    "store.scatter",         # columnar store appends (+ inline taps)
    "wal.commit_wait",       # WAL group-commit fsync wait
    "stream.tap",            # continuous-query ingest tap
    # query stages
    "query.admission",       # admission + worker-queue wait
    "query.streaming_lookup",  # CQ registry try_serve
    "query.plan",            # store/tier selection, filters, groups
    "sketch.fold",           # lifecycle/manager.py demote-time
                             # quantile-sketch fold (fifth stat column)
    "query.execute",         # scan + device pipeline (parent stage)
    "query.assemble",        # result assembly incl. pixel reduce
    "query.serialize",       # response body serialization
    # cluster stages
    "cluster.scatter",       # router read fan-out (parent stage)
    "cluster.peer",          # one shard's scatter leg (error = degraded)
    "cluster.merge",         # cross-shard partial merge
    "cluster.forward",       # one shard's write-forward leg
    "cluster.spool.append",  # durable handoff of one write batch
    "cluster.wire.connect",  # binary wire negotiation (cluster/wire.py)
    "cluster.cq",            # one federated-CQ shard exchange
    "cluster.cq.pump",       # one merged cross-shard delta drain

    # background stages
    "coldstore.spill",       # lifecycle sweep's disk spill phase
})

#: wire header carrying trace identity across the cluster tier
TRACE_HEADER = "x-tsd-trace"

# id generation: trace/span ids need UNIQUENESS (across restarts and
# across cluster nodes, so stitched trees never alias), not
# unpredictability — os.urandom per request cost ~50us/trace, an
# order of magnitude over the rest of the tracer combined. One random
# process nonce + a counter gives both properties at ~1us.
_PROC_NONCE = secrets.token_hex(4)
_id_lock = threading.Lock()
_id_counter = 0


def _next_id() -> str:
    global _id_counter
    with _id_lock:
        _id_counter += 1
        n = _id_counter
    return f"{_PROC_NONCE}{n:08x}"


def parse_trace_header(value: str) -> tuple[str, str, bool] | None:
    """``trace_id:parent_span_id:sampled_flag`` -> parts, or None on
    anything malformed (a hostile header must never 500 a write)."""
    if not value or len(value) > 128:
        return None
    parts = value.split(":")
    if len(parts) != 3:
        return None
    trace_id, parent, flag = parts
    if not (1 <= len(trace_id) <= 32 and trace_id.isalnum()):
        return None
    if len(parent) > 32 or not all(
            c.isalnum() or c == "-" for c in parent):
        return None
    return trace_id, parent, flag == "1"


# ---------------------------------------------------------------------------
# thread-local current context
# ---------------------------------------------------------------------------

_local = threading.local()


def current() -> "TraceContext | None":
    """The active request's trace context on THIS thread, or None.
    Deep layers (WAL, engine, router) read this instead of threading
    a context parameter through every signature."""
    return getattr(_local, "ctx", None)


@contextlib.contextmanager
def use(ctx: "TraceContext | None"):
    """Bind ``ctx`` as the thread's current trace context for the
    scope (None is a no-op bind — instrumentation sees no context).
    Fan-out workers re-bind the parent's context so sub-query spans
    land in the right trace."""
    prev = getattr(_local, "ctx", None)
    _local.ctx = ctx
    try:
        yield ctx
    finally:
        _local.ctx = prev


def trace_begin(name: str, ctx: "TraceContext | None" = None,
                parent: str | None = None, **tags) -> "SpanHandle | None":
    """Open a span on the current (or given) context; None when
    untraced — pair with :func:`trace_end`. For straight-line regions
    with early exits prefer :func:`trace_span`."""
    c = ctx if ctx is not None else getattr(_local, "ctx", None)
    if c is None:
        return None
    return c.begin(name, parent=parent, **tags)


def trace_end(handle: "SpanHandle | None",
              error: BaseException | None = None) -> None:
    if handle is not None:
        if error is not None:
            handle.set_error(error)
        handle.finish()


@contextlib.contextmanager
def trace_span(name: str, ctx: "TraceContext | None" = None, **tags):
    """Span context manager: exceptions mark the span ``error`` and
    propagate."""
    h = trace_begin(name, ctx=ctx, **tags)
    try:
        yield h
    except BaseException as exc:
        trace_end(h, error=exc)
        raise
    else:
        trace_end(h)


def record_span(ctx: "TraceContext | None", name: str,
                start_mono: float, end_mono: float, **tags) -> None:
    """Record an already-timed span (e.g. the admission/queue wait,
    whose start predates the context)."""
    if ctx is None:
        return
    ctx.record(name, start_mono, end_mono, **tags)


# ---------------------------------------------------------------------------
# span model
# ---------------------------------------------------------------------------

class SpanRecord:
    """One finished span. Immutable once appended to its context."""

    __slots__ = ("span_id", "parent_id", "name", "start_ms",
                 "duration_ms", "status", "error", "tags")

    def __init__(self, span_id: str, parent_id: str, name: str,
                 start_ms: float, duration_ms: float,
                 status: str = "ok", error: str = "",
                 tags: dict | None = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_ms = start_ms
        self.duration_ms = duration_ms
        self.status = status
        self.error = error
        self.tags = tags or {}

    def to_json(self) -> dict[str, Any]:
        doc: dict[str, Any] = {
            "spanId": self.span_id, "parentId": self.parent_id,
            "name": self.name,
            "startMs": round(self.start_ms, 3),
            "durationMs": round(self.duration_ms, 3),
            "status": self.status,
        }
        if self.error:
            doc["error"] = self.error
        if self.tags:
            doc["tags"] = self.tags
        return doc

    @classmethod
    def from_json(cls, doc: dict) -> "SpanRecord":
        return cls(str(doc.get("spanId", "")),
                   str(doc.get("parentId", "")),
                   str(doc.get("name", "?")),
                   float(doc.get("startMs", 0.0)),
                   float(doc.get("durationMs", 0.0)),
                   str(doc.get("status", "ok")),
                   str(doc.get("error", "")),
                   doc.get("tags") or {})


class SpanHandle:
    """An OPEN span: carry tags, then :meth:`finish` to record."""

    __slots__ = ("_ctx", "span_id", "parent_id", "name", "tags",
                 "_t0", "status", "error", "_done")

    def __init__(self, ctx: "TraceContext", span_id: str,
                 parent_id: str, name: str, tags: dict):
        self._ctx = ctx
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.tags = tags
        self._t0 = time.monotonic()
        self.status = "ok"
        self.error = ""
        self._done = False

    def tag(self, **tags) -> None:
        self.tags.update(tags)

    def set_error(self, exc: BaseException | str) -> None:
        self.status = "error"
        self.error = (f"{type(exc).__name__}: {exc}"
                      if isinstance(exc, BaseException) else str(exc))

    def finish(self) -> None:
        if self._done:
            return
        self._done = True
        self._ctx._append(self, self._t0, time.monotonic())

    def __enter__(self) -> "SpanHandle":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.set_error(exc)
        self.finish()
        return False


class TraceContext:
    """One request's (or background root's) in-flight trace."""

    __slots__ = ("tracer", "trace_id", "root_name", "remote",
                 "sampled", "forced", "parent_id", "root_span_id",
                 "start_epoch_ms", "_t0", "_lock", "spans",
                 "_next_span", "_nonce", "finished", "committed",
                 "slow", "error", "tags", "dropped_spans")

    def __init__(self, tracer: "Tracer", trace_id: str,
                 root_name: str, sampled: bool, forced: bool,
                 parent_id: str = "", remote: str = ""):
        self.tracer = tracer
        self.trace_id = trace_id
        self.root_name = root_name
        self.remote = remote
        self.sampled = sampled
        self.forced = forced
        self.parent_id = parent_id
        # per-context nonce keeps span ids globally unique so a
        # stitched cross-node tree can never alias parent links
        self._nonce = _next_id()
        self.root_span_id = f"{self._nonce}-0"
        self.start_epoch_ms = time.time() * 1000.0
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        # tsdlint: allow[unbounded-growth] capped by the tracer's
        # tsd.trace.max_spans (overflow counted in spans_dropped),
        # and the context dies with its request
        self.spans: list[SpanRecord] = []
        self._next_span = 0
        self.finished = False
        self.committed = False
        self.slow = False
        self.error = ""
        self.tags: dict[str, Any] = {}
        self.dropped_spans = 0

    # -- span surface --------------------------------------------------

    def begin(self, name: str, parent: str | None = None,
              **tags) -> SpanHandle | None:
        if name not in KNOWN_SPANS:
            raise ValueError(
                f"unknown span name {name!r}; register it in "
                f"obs/trace.py KNOWN_SPANS")
        with self._lock:
            if self.finished or \
                    len(self.spans) >= self.tracer.max_spans:
                self.dropped_spans += 1
                return None
            self._next_span += 1
            sid = f"{self._nonce}-{self._next_span}"
        return SpanHandle(self, sid,
                          parent if parent is not None
                          else self.root_span_id, name, tags)

    def record(self, name: str, start_mono: float, end_mono: float,
               **tags) -> None:
        """Append an already-timed span (see :func:`record_span`)."""
        h = self.begin(name, **tags)
        if h is None:
            return
        h._t0 = start_mono
        self._append(h, start_mono, end_mono)

    def _append(self, h: SpanHandle, t0: float, t1: float) -> None:
        rec = SpanRecord(
            h.span_id, h.parent_id, h.name,
            self.start_epoch_ms + (t0 - self._t0) * 1000.0,
            (t1 - t0) * 1000.0, h.status, h.error, h.tags)
        with self._lock:
            if self.finished:
                self.dropped_spans += 1
                return
            self.spans.append(rec)

    # -- root surface --------------------------------------------------

    def tag(self, **tags) -> None:
        self.tags.update(tags)

    def set_error(self, exc: BaseException | str) -> None:
        self.error = (f"{type(exc).__name__}: {exc}"
                      if isinstance(exc, BaseException) else str(exc))

    def elapsed_ms(self) -> float:
        return (time.monotonic() - self._t0) * 1000.0


class TraceData:
    """One committed trace in the ring."""

    __slots__ = ("trace_id", "root", "spans", "slow")

    def __init__(self, trace_id: str, root: SpanRecord,
                 spans: tuple, slow: bool):
        self.trace_id = trace_id
        self.root = root
        self.spans = spans  # root first
        self.slow = slow

    def summary(self) -> dict[str, Any]:
        return {
            "traceId": self.trace_id,
            "name": self.root.name,
            "startMs": round(self.root.start_ms, 3),
            "durationMs": round(self.root.duration_ms, 3),
            "status": self.root.status,
            "error": self.root.error,
            "spans": len(self.spans),
            "slow": self.slow,
        }


def build_tree(spans: list[SpanRecord]) -> list[dict[str, Any]]:
    """Nest flat span records by parent id; orphans (parent not in
    the set — e.g. a shard subtree whose router leg was evicted)
    become additional roots so no span is ever silently dropped."""
    nodes = {s.span_id: dict(s.to_json(), children=[]) for s in spans}
    roots: list[dict] = []
    for s in spans:
        node = nodes[s.span_id]
        parent = nodes.get(s.parent_id)
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    def _sort(n):
        n["children"].sort(key=lambda c: c["startMs"])
        for c in n["children"]:
            _sort(c)
    for r in roots:
        _sort(r)
    roots.sort(key=lambda n: n["startMs"])
    return roots


# ---------------------------------------------------------------------------
# the tracer
# ---------------------------------------------------------------------------

class Tracer:
    """Owns the sampling decision, the bounded trace rings, the
    slow-request log and the query-shape log. One per TSDB."""

    def __init__(self, config, data_dir: str = "", stats=None):
        self.enabled = config.get_bool("tsd.trace.enable", True)
        # the X-TSD-Trace header is honored ONLY in shard role — it
        # is the router→shard propagation channel, not a client
        # surface: an external client sending forged headers to a
        # standalone/router TSD could otherwise bypass sampling
        # (per-request shape-log writes, ring churn) and overwrite
        # the very trace ids an operator is investigating
        self.accept_headers = config.get_string(
            "tsd.cluster.role", "") == "shard"
        self.sample_n = max(config.get_int("tsd.trace.sample", 64), 1)
        self.max_spans = max(
            config.get_int("tsd.trace.max_spans", 512), 16)
        self.slow_ms = config.get_float(
            "tsd.query.slowlog.threshold_ms", 0.0)
        self.stats = stats  # StatsCollectorRegistry (stage histograms)
        self._lock = threading.Lock()
        self._ring: deque[TraceData] = deque(
            maxlen=max(config.get_int("tsd.trace.ring", 256), 1))
        self._slow_ring: deque[TraceData] = deque(
            maxlen=max(config.get_int("tsd.trace.slow_ring", 64), 1))
        self._index: dict[str, TraceData] = {}
        self._root_count = 0
        # counters (exported via collect_stats + /api/health)
        self.traces_started = 0
        self.traces_committed = 0
        self.traces_sampled_out = 0
        self.slow_traces = 0
        self.spans_dropped = 0
        # query-shape log: bounded JSONL ring file in data_dir
        self.shape_path = ""
        if data_dir and config.get_bool("tsd.trace.shapes.enable",
                                        True):
            self.shape_path = os.path.join(data_dir,
                                           "query_shapes.jsonl")
        self.shape_max_bytes = max(
            config.get_int("tsd.trace.shapes.max_kb", 1024), 1) * 1024
        self._shape_lock = threading.Lock()
        self._shape_fh = None
        self.shape_lines = 0
        self.shape_errors = 0

    # -- root creation -------------------------------------------------

    def _sample_next(self) -> bool:
        """Deterministic 1-in-N retention: the 1st, (N+1)th, ... roots
        are kept — a counter, not a coin flip, so trace batteries (and
        the bench) reproduce exactly."""
        with self._lock:
            self._root_count += 1
            return (self._root_count - 1) % self.sample_n == 0

    def start_request(self, name: str, request=None,
                      remote: str = "") -> TraceContext | None:
        """Root a request trace, honoring an ``X-TSD-Trace`` header
        when present (cluster propagation: the upstream router made
        the sampling decision and this node's subtree must exist iff
        the router's tree does). Returns None when tracing is off."""
        if not self.enabled:
            return None
        if name not in KNOWN_SPANS:
            raise ValueError(
                f"unknown span name {name!r}; register it in "
                f"obs/trace.py KNOWN_SPANS")
        trace_id = parent_id = ""
        forced = False
        headers = getattr(request, "headers", None) \
            if self.accept_headers else None
        if headers:
            parsed = parse_trace_header(
                headers.get(TRACE_HEADER, ""))
            if parsed is not None:
                trace_id, parent_id, forced = parsed
        if trace_id:
            sampled = forced
        else:
            trace_id = _next_id()
            sampled = self._sample_next()
        ctx = TraceContext(
            self, trace_id, name, sampled, forced,
            parent_id=parent_id,
            remote=remote or getattr(request, "remote", ""))
        with self._lock:
            self.traces_started += 1
        # the admission/queue wait predates this context: synthesize
        # it from the server's receipt stamp so the trace shows where
        # a loaded TSD's queries actually wait
        received = getattr(request, "received_at", 0.0)
        if received and name == "query.http":
            record_span(ctx, "query.admission", received,
                        time.monotonic())
        return ctx

    def start_background(self, name: str, sample: bool = False,
                         **tags) -> TraceContext | None:
        """Root a background trace (sweep, spill, drain, replay).
        ``sample=True`` applies the 1-in-N retention (for
        high-frequency roots like fold drains); the default retains
        every occurrence — background roots are rare and are exactly
        what an operator goes looking for."""
        if not self.enabled:
            return None
        if name not in KNOWN_SPANS:
            raise ValueError(
                f"unknown span name {name!r}; register it in "
                f"obs/trace.py KNOWN_SPANS")
        sampled = self._sample_next() if sample else True
        ctx = TraceContext(self, _next_id(), name, sampled, False)
        if tags:
            ctx.tag(**tags)
        with self._lock:
            self.traces_started += 1
        return ctx

    def header_for(self, ctx: TraceContext,
                   span: SpanHandle | None = None) -> str:
        """The ``X-TSD-Trace`` value a downstream hop should carry:
        the hop's subtree hangs off ``span`` (this node's per-peer
        span) and inherits the retention decision.

        With a slowlog configured, QUERY hops always propagate
        flag=1: slow-retention is decided at finish, AFTER the shards
        already chose whether to keep their subtrees — without this a
        slow-but-unsampled router trace would commit locally and
        stitch an empty tree, losing exactly the evidence the
        slowlog exists for. Shard rings are bounded, so the cost is
        churn, not growth."""
        parent = span.span_id if span is not None else \
            ctx.root_span_id
        keep = ctx.sampled or ctx.forced or \
            (self.slow_ms > 0 and ctx.root_name.startswith("query"))
        return f"{ctx.trace_id}:{parent}:{'1' if keep else '0'}"

    # -- finish / commit -----------------------------------------------

    def finish(self, ctx: TraceContext | None) -> bool:
        """Close a root: feed the stage histograms, decide retention
        (sampled | propagated-sampled | slow | error), commit to the
        ring(s). Returns whether the trace was retained."""
        if ctx is None:
            return False
        with ctx._lock:
            if ctx.finished:
                return ctx.committed
            ctx.finished = True
            spans = list(ctx.spans)
            dropped = ctx.dropped_spans
        duration_ms = ctx.elapsed_ms()
        root = SpanRecord(
            ctx.root_span_id, ctx.parent_id, ctx.root_name,
            ctx.start_epoch_ms, duration_ms,
            "error" if ctx.error else "ok", ctx.error, dict(ctx.tags))
        # per-stage latency histograms see EVERY traced request —
        # sampling gates only ring retention, so /api/stats
        # percentiles are not biased toward the sampled subset
        stats = self.stats
        if stats is not None:
            stats.observe_stage(root.name, duration_ms)
            for s in spans:
                stats.observe_stage(s.name, s.duration_ms)
        slow = (self.slow_ms > 0 and duration_ms >= self.slow_ms
                and ctx.root_name.startswith("query"))
        commit = ctx.sampled or ctx.forced or slow or bool(ctx.error)
        data = TraceData(ctx.trace_id, root,
                         tuple([root] + spans), slow)
        with self._lock:
            self.spans_dropped += dropped
            if not commit:
                self.traces_sampled_out += 1
            else:
                self.traces_committed += 1
                if slow:
                    self.slow_traces += 1
                existing = self._index.get(ctx.trace_id)
                if existing is not None:
                    # a shard can serve SEVERAL legs of one trace
                    # (per-sub retries, hedged duplicates): merge the
                    # new leg's spans instead of last-write-wins,
                    # which silently lost every earlier leg's subtree
                    # from the stitched tree
                    data = TraceData(
                        ctx.trace_id, existing.root,
                        existing.spans + data.spans,
                        existing.slow or slow)
                    self._index[ctx.trace_id] = data
                    for ring in (self._ring, self._slow_ring):
                        for i, d in enumerate(ring):
                            if d is existing:
                                ring[i] = data
                                break
                        else:
                            continue
                        break
                else:
                    ring = self._slow_ring if slow else self._ring
                    if len(ring) == ring.maxlen:
                        evicted = ring[0]
                        if self._index.get(evicted.trace_id) \
                                is evicted:
                            del self._index[evicted.trace_id]
                    ring.append(data)
                    self._index[ctx.trace_id] = data
        ctx.slow = slow
        ctx.committed = commit
        if slow:
            # the WARN lands in the /logs ring; the trace id is the
            # cross-reference into /api/trace/<id>
            LOG.warning(
                "slow query trace=%s %.1fms >= slowlog threshold "
                "%.0fms (remote=%s, retained at full fidelity)",
                ctx.trace_id, duration_ms, self.slow_ms, ctx.remote)
        if commit and ctx.root_name == "query.http" and \
                self.shape_path:
            self._write_shape(ctx, root, spans)
        return commit

    # -- retrieval -----------------------------------------------------

    def get(self, trace_id: str) -> TraceData | None:
        with self._lock:
            return self._index.get(trace_id)

    def recent(self, status: str = "", min_duration_ms: float = 0.0,
               slow_only: bool = False, limit: int = 50
               ) -> list[dict[str, Any]]:
        with self._lock:
            items = list(self._slow_ring) if slow_only else \
                list(self._ring) + list(self._slow_ring)
        items.sort(key=lambda d: d.root.start_ms, reverse=True)
        out = []
        for d in items:
            if status and d.root.status != status:
                continue
            if d.root.duration_ms < min_duration_ms:
                continue
            out.append(d.summary())
            if len(out) >= max(limit, 1):
                break
        return out

    # -- query-shape log -----------------------------------------------

    def _write_shape(self, ctx: TraceContext, root: SpanRecord,
                     spans: list[SpanRecord]) -> None:
        stages: dict[str, float] = {}
        for s in spans:
            stages[s.name] = round(
                stages.get(s.name, 0.0) + s.duration_ms, 3)
        line = json.dumps({
            "ts": round(root.start_ms / 1000.0, 3),
            "traceId": ctx.trace_id,
            "durationMs": round(root.duration_ms, 3),
            "status": root.status,
            "slow": ctx.slow,
            **{k: v for k, v in root.tags.items()},
            "stages": stages,
        }) + "\n"
        try:
            with self._shape_lock:
                fh = self._shape_fh
                if fh is None:
                    fh = self._shape_fh = open(self.shape_path, "a",
                                               encoding="utf-8")
                fh.write(line)
                fh.flush()
                if fh.tell() >= self.shape_max_bytes:
                    # bounded ring: one rotation generation keeps the
                    # most recent window without unbounded growth
                    fh.close()
                    self._shape_fh = None
                    os.replace(self.shape_path,
                               self.shape_path + ".1")
                self.shape_lines += 1
        except OSError:
            # mining data must never fail (or slow) a served query
            self.shape_errors += 1

    def close(self) -> None:
        with self._shape_lock:
            if self._shape_fh is not None:
                try:
                    self._shape_fh.close()
                except OSError:  # pragma: no cover - teardown race
                    LOG.warning("query-shape log close failed")
                self._shape_fh = None

    # -- observability about the observer ------------------------------

    def collect_stats(self, collector) -> None:
        collector.record("trace.started", self.traces_started)
        collector.record("trace.committed", self.traces_committed)
        collector.record("trace.sampled_out", self.traces_sampled_out)
        collector.record("trace.slow", self.slow_traces)
        collector.record("trace.spans_dropped", self.spans_dropped)
        collector.record("trace.shape_lines", self.shape_lines)
        collector.record("trace.shape_errors", self.shape_errors)

    def health_info(self) -> dict[str, Any]:
        with self._lock:
            ring_len = len(self._ring)
            slow_len = len(self._slow_ring)
        return {
            "enabled": self.enabled,
            "sample": self.sample_n,
            "ring": ring_len,
            "slow_ring": slow_len,
            "slowlog_threshold_ms": self.slow_ms,
            "started": self.traces_started,
            "committed": self.traces_committed,
            "sampled_out": self.traces_sampled_out,
            "slow": self.slow_traces,
            "spans_dropped": self.spans_dropped,
            "shape_log": self.shape_path,
            "shape_lines": self.shape_lines,
        }
