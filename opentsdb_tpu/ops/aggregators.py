"""The aggregation engine (ref: ``src/core/Aggregators.java``).

Every reference aggregator — 16 scalar + 12 percentile variants — as a
NaN-aware *vectorized* reduction over the series axis of a
``[series, timebucket]`` array. NaN encodes "no value for this series at
this bucket" and each aggregator carries the interpolation mode the
reference uses at group-merge time (``Aggregators.Interpolation``
:38-44): LERP fills gaps by linear interpolation before reduction, ZIM
substitutes zero, MAX/MIN substitute the type extremes, PREV repeats the
previous value (pfsum). The fill itself happens in
:mod:`opentsdb_tpu.ops.interp`; reductions here just define the
per-bucket math, exactly matching the reference semantics:

- ``sum``/``zimsum``: sum of non-NaN, all-NaN -> NaN (Sum.runDouble)
- ``avg``: mean of non-NaN, all-NaN -> NaN
- ``dev``: *sample* stddev (Welford / n-1), one value -> 0, none -> NaN
- ``median``: upper median sorted[n//2] (Median.runDouble)
- ``diff``: last non-NaN minus first non-NaN, single -> 0 (Diff)
- ``count``: number of non-NaN values (Count.runDouble)
- ``first``/``last``: first/last series (in span order) with a value
- ``multiply``: product; ``squareSum``: sum of squares
- ``p50..p999``: commons-math3 Percentile LEGACY estimation
- ``ep50r3..ep999r7``: estimation types R_3 / R_7 (PercentileAgg :657)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable

import jax.numpy as jnp


class Interpolation(Enum):
    """(ref: Aggregators.Interpolation :38-44)"""
    LERP = "lerp"
    ZIM = "zim"    # zero if missing
    MAX = "max"    # type max if missing (used by mimmin)
    MIN = "min"    # type min if missing (used by mimmax)
    PREV = "prev"  # previous value if missing (pfsum)


def _valid(x):
    return ~jnp.isnan(x)


def _nan_where_empty(result, x, axis):
    return jnp.where(jnp.any(_valid(x), axis=axis), result, jnp.nan)


def agg_sum(x, axis=0):
    return _nan_where_empty(jnp.nansum(x, axis=axis), x, axis)


def agg_min(x, axis=0):
    return _nan_where_empty(
        jnp.nanmin(jnp.where(_valid(x), x, jnp.inf), axis=axis), x, axis)


def agg_max(x, axis=0):
    return _nan_where_empty(
        jnp.nanmax(jnp.where(_valid(x), x, -jnp.inf), axis=axis), x, axis)


def agg_avg(x, axis=0):
    cnt = jnp.sum(_valid(x), axis=axis)
    total = jnp.nansum(x, axis=axis)
    return jnp.where(cnt > 0, total / jnp.maximum(cnt, 1), jnp.nan)


def agg_count(x, axis=0):
    return jnp.sum(_valid(x), axis=axis).astype(x.dtype)


def agg_multiply(x, axis=0):
    return _nan_where_empty(
        jnp.prod(jnp.where(_valid(x), x, 1.0), axis=axis), x, axis)


def agg_squaresum(x, axis=0):
    return _nan_where_empty(jnp.nansum(x * x, axis=axis), x, axis)


def agg_dev(x, axis=0):
    """POPULATION standard deviation (divisor n), matching the
    reference exactly: its Welford loop over-increments n by one and
    divides M2 by that, which lands on sigma = sqrt(M2/n) — pinned by
    its own unit tests (TestAggregators.java:82-122 expects
    numpy.std(range(10000)) and {1,2} -> 0.5, both population forms).
    0 for a single value, NaN for none (ref: Aggregators.StdDev :498).
    Computed as the mean-shifted two-pass formula — vectorizable and
    cancellation-safe; clamped at 0 against rounding."""
    cnt = jnp.sum(_valid(x), axis=axis)
    safe_cnt = jnp.maximum(cnt, 1)
    mean = jnp.nansum(x, axis=axis) / safe_cnt
    centered = jnp.where(_valid(x), x - jnp.expand_dims(mean, axis), 0.0)
    m2 = jnp.sum(centered * centered, axis=axis)
    var = m2 / safe_cnt
    dev = jnp.sqrt(jnp.maximum(var, 0.0))
    return jnp.where(cnt == 0, jnp.nan, jnp.where(cnt == 1, 0.0, dev))


def _first_last_positions(x, axis):
    s = x.shape[axis]
    idx_shape = [1] * x.ndim
    idx_shape[axis] = s
    pos = jnp.arange(s).reshape(idx_shape)
    first_pos = jnp.min(jnp.where(_valid(x), pos, s), axis=axis)
    last_pos = jnp.max(jnp.where(_valid(x), pos, -1), axis=axis)
    return first_pos, last_pos


def agg_first(x, axis=0):
    first_pos, _ = _first_last_positions(x, axis)
    safe = jnp.clip(first_pos, 0, x.shape[axis] - 1)
    picked = jnp.take_along_axis(x, jnp.expand_dims(safe, axis),
                                 axis=axis).squeeze(axis)
    return jnp.where(first_pos < x.shape[axis], picked, jnp.nan)


def agg_last(x, axis=0):
    _, last_pos = _first_last_positions(x, axis)
    safe = jnp.clip(last_pos, 0, x.shape[axis] - 1)
    picked = jnp.take_along_axis(x, jnp.expand_dims(safe, axis),
                                 axis=axis).squeeze(axis)
    return jnp.where(last_pos >= 0, picked, jnp.nan)


def agg_diff(x, axis=0):
    """last non-NaN - first non-NaN; exactly one value -> 0; none -> NaN
    (ref: Aggregators.Diff :576)."""
    cnt = jnp.sum(_valid(x), axis=axis)
    d = agg_last(x, axis) - agg_first(x, axis)
    return jnp.where(cnt == 0, jnp.nan, jnp.where(cnt == 1, 0.0, d))


def agg_median(x, axis=0):
    """Upper median: sorted[n // 2] (ref: Aggregators.Median :397)."""
    s = x.shape[axis]
    sorted_x = jnp.sort(x, axis=axis)  # NaNs sort to the end
    cnt = jnp.sum(_valid(x), axis=axis)
    idx = jnp.clip(cnt // 2, 0, s - 1)
    picked = jnp.take_along_axis(sorted_x, jnp.expand_dims(idx, axis),
                                 axis=axis).squeeze(axis)
    return jnp.where(cnt > 0, picked, jnp.nan)


def percentile_along_axis(x, q: float, estimation: str, axis=0):
    """Order statistics with commons-math3 estimation semantics.

    ``legacy``: h = q(n+1)/100, clamp to [min, max], linear interp.
    ``r3``: h = q*n/100, estimate x(ceil(h - 0.5)) — nearest, half down.
    ``r7``: h = (n-1)q/100 + 1, linear interp (numpy 'linear').
    (ref: Aggregators.PercentileAgg :657 + commons-math3 Percentile)
    """
    s = x.shape[axis]
    sorted_x = jnp.sort(x, axis=axis)
    n = jnp.sum(_valid(x), axis=axis).astype(x.dtype)
    p = q / 100.0
    if estimation == "legacy":
        h = p * (n + 1)
    elif estimation == "r3":
        h = jnp.ceil(p * n - 0.5)  # 1-based nearest rank, half rounds down
    elif estimation == "r7":
        h = (n - 1) * p + 1
    else:
        raise ValueError(f"unknown estimation type {estimation!r}")
    h = jnp.clip(h, 1.0, jnp.maximum(n, 1.0))
    h_floor = jnp.floor(h)
    frac = h - h_floor
    lo_idx = jnp.clip(h_floor.astype(jnp.int32) - 1, 0, s - 1)
    hi_idx = jnp.clip(lo_idx + 1,
                      0, jnp.maximum(n.astype(jnp.int32) - 1, 0))
    hi_idx = jnp.clip(hi_idx, 0, s - 1)
    lo = jnp.take_along_axis(sorted_x, jnp.expand_dims(lo_idx, axis),
                             axis=axis).squeeze(axis)
    hi = jnp.take_along_axis(sorted_x, jnp.expand_dims(hi_idx, axis),
                             axis=axis).squeeze(axis)
    out = lo + frac * (hi - lo)
    return jnp.where(n > 0, out, jnp.nan)


@dataclass(frozen=True)
class Aggregator:
    """One aggregation function + its merge-time interpolation mode."""
    name: str
    interpolation: Interpolation
    reduce: Callable  # (x[S,B], axis) -> [B]
    percentile: float | None = None
    estimation: str | None = None

    def __call__(self, x, axis=0):
        return self.reduce(x, axis=axis)

    @property
    def is_percentile(self) -> bool:
        return self.percentile is not None

    @property
    def is_none(self) -> bool:
        return self.name == "none"


def _make_percentile(name: str, q: float, estimation: str) -> Aggregator:
    def reduce(x, axis=0, _q=q, _e=estimation):
        return percentile_along_axis(x, _q, _e, axis=axis)
    return Aggregator(name, Interpolation.LERP, reduce,
                      percentile=q, estimation=estimation)


def _agg_none(x, axis=0):
    raise RuntimeError(
        "'none' must not be aggregated; the pipeline emits raw series")


# tsdlint: allow[unbounded-growth] closed import-time registry:
# populated once by the _register decorator walk below, never at
# serve time
_REGISTRY: dict[str, Aggregator] = {}


def _register(agg: Aggregator) -> Aggregator:
    _REGISTRY[agg.name] = agg
    return agg


# Registration mirrors Aggregators.java:47-172 name-for-name.
SUM = _register(Aggregator("sum", Interpolation.LERP, agg_sum))
PFSUM = _register(Aggregator("pfsum", Interpolation.PREV, agg_sum))
MIN = _register(Aggregator("min", Interpolation.LERP, agg_min))
MAX = _register(Aggregator("max", Interpolation.LERP, agg_max))
AVG = _register(Aggregator("avg", Interpolation.LERP, agg_avg))
MEDIAN = _register(Aggregator("median", Interpolation.LERP, agg_median))
NONE = _register(Aggregator("none", Interpolation.ZIM, _agg_none))
MULTIPLY = _register(Aggregator("multiply", Interpolation.LERP, agg_multiply))
# the query-facing registry name is "mult" (Aggregators.java:183 puts
# MULTIPLY under "mult"; its display name is "multiply")
_REGISTRY["mult"] = MULTIPLY
# MovingAverage (Aggregators.java:709) is NOT in the reference registry
# either — it is only reachable through the movingAverage() expression
# function (ExpressionFactory.java:36), provided here by
# opentsdb_tpu.query.expression.core.
DEV = _register(Aggregator("dev", Interpolation.LERP, agg_dev))
DIFF = _register(Aggregator("diff", Interpolation.LERP, agg_diff))
ZIMSUM = _register(Aggregator("zimsum", Interpolation.ZIM, agg_sum))
MIMMIN = _register(Aggregator("mimmin", Interpolation.MAX, agg_min))
MIMMAX = _register(Aggregator("mimmax", Interpolation.MIN, agg_max))
SQUARESUM = _register(Aggregator("squareSum", Interpolation.ZIM,
                                 agg_squaresum))
COUNT = _register(Aggregator("count", Interpolation.ZIM, agg_count))
FIRST = _register(Aggregator("first", Interpolation.ZIM, agg_first))
LAST = _register(Aggregator("last", Interpolation.ZIM, agg_last))

for _q, _name in ((99.9, "p999"), (99.0, "p99"), (95.0, "p95"),
                  (90.0, "p90"), (75.0, "p75"), (50.0, "p50")):
    _register(_make_percentile(_name, _q, "legacy"))
    for _est in ("r3", "r7"):
        _register(_make_percentile(f"e{_name}{_est}", _q, _est))


def get(name: str) -> Aggregator:
    """(ref: Aggregators.get :222)"""
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"No such aggregator: {name}") from None


def names() -> list[str]:
    """Sorted registry names for ``/api/aggregators``."""
    return sorted(_REGISTRY)


def exists(name: str) -> bool:
    return name in _REGISTRY
