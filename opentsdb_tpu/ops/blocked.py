"""Time-blocked streaming execution for long ranges.

The reference bounds long-time-range queries by streaming hourly rows
and capping bytes (SURVEY.md §5.7); a materialize-everything array
pipeline instead hits HBM: 1M series x a week of 1m buckets is 10k
buckets — 40 GB of f32 cells. This executor streams the query in
*time blocks* of ``block_buckets`` buckets so device memory stays at
``O(S x block)`` regardless of range length — the single-chip
"context parallelism" analogue (the multi-chip time axis of
:mod:`opentsdb_tpu.parallel.sharded_pipeline` is the same idea across
devices; this is the same math across a host loop).

Rate and merge interpolation look across block edges; the carries reuse
the sharded pipeline's boundary kernels:

- pass 1 (forward): per block, bucketize -> fill-policy -> rate with
  the running prev-carry, collecting each block's boundary summaries
  ([S]-sized vectors) — grids are discarded;
- a backward scan over the pass-1 summaries yields each block's
  *next*-present carry (what LERP needs from future blocks);
- pass 2 (forward): recompute each block (bucketize+rate are cheaper
  than holding every grid), inject (prev, next) carries into
  ``_fill_with_boundaries``, group-reduce, and append the ``[G, Bb]``
  slab to the output.

Two device passes = 2x FLOPs for unbounded range length at fixed HBM —
the same trade ``jax.checkpoint`` makes for activations.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops import aggregators as aggs_mod
from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.ops import groupby as gb_mod
from opentsdb_tpu.ops.pipeline import PipelineSpec
from opentsdb_tpu.parallel.sharded_pipeline import (_block_boundaries,
                                                    _fill_with_boundaries,
                                                    _rate_with_boundary)

# default device-cell budget per block (~256 MB of f32)
DEFAULT_CELL_BUDGET = 1 << 26


def _prep_block(values, series_idx, bucket_idx, num_series, num_buckets,
                spec, fill_value):
    """bucketize + downsample fill policy (pipeline steps 1-2)."""
    from opentsdb_tpu.ops.pipeline import apply_fill_policy
    grid, cnt = ds_mod.bucketize(values, series_idx, bucket_idx,
                                 num_series, num_buckets,
                                 spec.ds_function)
    return apply_fill_policy(grid, cnt > 0, fill_value, spec)


@partial(jax.jit, static_argnames=("spec", "num_buckets"))
def _pass1_step(values, series_idx, bucket_idx, bucket_ts, rate_params,
                fill_value, rate_carry, spec: PipelineSpec,
                num_buckets: int):
    """One forward-sweep block: returns this block's boundary package.

    rate_carry = (v[S], t[S], p[S]) — the nearest present pre-rate cell
    in any earlier block (consumed by rate); the returned summaries are
    *post-rate* boundaries (consumed by interpolation fill).
    """
    grid, has_data = _prep_block(values, series_idx, bucket_idx,
                                 spec.num_series, num_buckets, spec,
                                 fill_value)
    (pre_lv, pre_lt, pre_lp), _ = _block_boundaries(grid, bucket_ts)
    if spec.rate:
        counter_max, reset_value = rate_params
        cv, ct, cp = rate_carry
        grid = _rate_with_boundary(grid, bucket_ts, spec.rate_counter,
                                   counter_max, reset_value,
                                   spec.rate_drop_resets, cv, ct, cp)
        has_data = has_data & ~jnp.isnan(grid)
    (lv, lt, lp), (fv, ft, fp) = _block_boundaries(grid, bucket_ts)
    return (pre_lv, pre_lt, pre_lp), (lv, lt, lp), (fv, ft, fp), \
        grid, has_data


@partial(jax.jit, static_argnames=("spec", "num_buckets"))
def _pass2_step(grid, has_data, bucket_ts, group_ids, prev_carry,
                next_carry, spec: PipelineSpec, num_buckets: int):
    """Fill with carries + group reduce one block -> ([G,Bb], emit)."""
    agg = aggs_mod.get(spec.agg_name)
    pv, pt, pp = prev_carry
    nv, nt, np_ = next_carry
    if spec.fill_policy == ds_mod.FillPolicy.NONE:
        filled = _fill_with_boundaries(grid, bucket_ts,
                                       agg.interpolation.value,
                                       pv, pt, pp, nv, nt, np_)
    else:
        # NAN/NULL fills emit explicit NaN points: the merge skips
        # them without interpolating (see pipeline._finish_pipeline)
        filled = grid
    result = gb_mod._group_reduce(filled, group_ids, spec.num_groups,
                                  agg.name)
    if spec.fill_policy == ds_mod.FillPolicy.NONE:
        emit = jax.ops.segment_sum(
            has_data.astype(jnp.int32), group_ids,
            num_segments=spec.num_groups) > 0
    else:
        emit = jnp.ones((spec.num_groups, grid.shape[-1]), dtype=bool)
    return result, emit


def _merge_carry(nearer, farther):
    """Combine boundary candidates: keep the nearer block's when
    present, else the farther carry (same rule as _scan_boundary)."""
    (v0, t0, p0), (v1, t1, p1) = nearer, farther
    return (np.where(p0, v0, v1), np.where(p0, t0, t1), p0 | p1)


def _empty_carry(num_series, dtype):
    return (np.zeros(num_series, dtype=dtype),
            np.zeros(num_series, dtype=dtype),
            np.zeros(num_series, dtype=bool))


def pick_block_buckets(num_series: int, num_buckets: int,
                       cell_budget: int = DEFAULT_CELL_BUDGET) -> int:
    """Largest block size keeping S x Bb under the device budget."""
    if num_series <= 0:
        return num_buckets
    return max(1, min(num_buckets, cell_budget // max(num_series, 1)))


def execute_blocked(batch_values: np.ndarray, series_idx: np.ndarray,
                    bucket_idx: np.ndarray, bucket_ts: np.ndarray,
                    group_ids: np.ndarray, spec: PipelineSpec,
                    rate_options=None, dtype=None, device=None,
                    block_buckets: int | None = None
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Streaming equivalent of :func:`opentsdb_tpu.ops.pipeline.execute`
    for ``emit_raw=False`` queries. Bit-identical results; device
    memory bounded by ``num_series x block_buckets`` cells."""
    from opentsdb_tpu.ops.rate import RateOptions
    if spec.emit_raw:
        raise ValueError("blocked execution aggregates; emit_raw "
                         "queries stream per-series instead")
    if dtype is None:
        dtype = jnp.float64 if jax.config.read("jax_enable_x64") \
            else jnp.float32
    np_dtype = np.dtype(dtype)
    ro = rate_options or RateOptions()
    s, b, g = spec.num_series, spec.num_buckets, spec.num_groups
    bb = block_buckets or pick_block_buckets(s, b)
    rate_params = (jnp.asarray(ro.counter_max, dtype),
                   jnp.asarray(ro.reset_value, dtype))
    fv = jnp.asarray(spec.fill_value, dtype)

    # host: order points by bucket so each block is one contiguous slice
    bucket_idx = np.asarray(bucket_idx)
    order = np.argsort(bucket_idx, kind="stable")
    sv = np.asarray(batch_values, dtype=np_dtype)[order]
    ssi = np.asarray(series_idx, dtype=np.int32)[order]
    sbi = bucket_idx[order]
    from opentsdb_tpu.ops.pipeline import device_bucket_ts
    bucket_ts = device_bucket_ts(bucket_ts)
    starts = [np.searchsorted(sbi, b0) for b0 in range(0, b, bb)]
    starts.append(len(sbi))
    blocks = [(b0, min(b0 + bb, b), starts[i], starts[i + 1])
              for i, b0 in enumerate(range(0, b, bb))]

    agg = aggs_mod.get(spec.agg_name)
    needs_next = agg.interpolation.value in ("lerp", "max", "min")
    put = partial(jax.device_put, device=device)

    def run_block_pass1(blk, rate_carry):
        b0, b1, p0, p1 = blk
        nb = b1 - b0
        carry_dev = tuple(put(jnp.asarray(c)) for c in rate_carry)
        return _pass1_step(
            put(jnp.asarray(sv[p0:p1])), put(jnp.asarray(ssi[p0:p1])),
            put(jnp.asarray(sbi[p0:p1] - b0)),
            put(jnp.asarray(bucket_ts[b0:b1])), rate_params, fv,
            carry_dev, spec, nb)

    # pass 1: forward sweep collecting boundary summaries
    firsts, lasts = [], []
    rate_carry = _empty_carry(s, np_dtype)
    for blk in blocks:
        pre_last, post_last, post_first, _, _ = run_block_pass1(
            blk, rate_carry)
        firsts.append(tuple(np.asarray(x) for x in post_first))
        lasts.append(tuple(np.asarray(x) for x in post_last))
        if spec.rate:
            rate_carry = _merge_carry(
                tuple(np.asarray(x) for x in pre_last), rate_carry)

    # backward scan: next-present carry per block
    n_blocks = len(blocks)
    next_carries = [None] * n_blocks
    nc = _empty_carry(s, np_dtype)
    for i in range(n_blocks - 1, -1, -1):
        next_carries[i] = nc
        if needs_next:
            nc = _merge_carry(firsts[i], nc)

    # pass 2: forward sweep computing [G, Bb] slabs
    gids_dev = put(jnp.asarray(np.asarray(group_ids, dtype=np.int32)))
    out = np.empty((g, b), dtype=np_dtype)
    emit_out = np.empty((g, b), dtype=bool)
    rate_carry = _empty_carry(s, np_dtype)
    prev_carry = _empty_carry(s, np_dtype)
    for i, blk in enumerate(blocks):
        b0, b1 = blk[0], blk[1]
        pre_last, post_last, _, grid, has_data = run_block_pass1(
            blk, rate_carry)
        result, emit = _pass2_step(
            grid, has_data, put(jnp.asarray(bucket_ts[b0:b1])),
            gids_dev,
            tuple(put(jnp.asarray(c)) for c in prev_carry),
            tuple(put(jnp.asarray(c)) for c in next_carries[i]),
            spec, b1 - b0)
        out[:, b0:b1] = np.asarray(result)
        emit_out[:, b0:b1] = np.asarray(emit)
        if spec.rate:
            rate_carry = _merge_carry(
                tuple(np.asarray(x) for x in pre_last), rate_carry)
        prev_carry = _merge_carry(
            tuple(np.asarray(x) for x in post_last), prev_carry)
    return out, emit_out
