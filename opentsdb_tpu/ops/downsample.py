"""Downsampling: time-bucket reduction ahead of aggregation.

(ref: ``src/core/Downsampler.java``, ``FillingDownsampler.java``,
``DownsamplingSpecification.java``, ``FillPolicy.java``)

The reference walks each span with a ``ValuesInInterval`` window iterator
(Downsampler.java:295), one datapoint at a time. Here the whole query
downsamples in one shot: every point of every series carries a segment
id ``series_idx * num_buckets + bucket_idx`` and a single segmented
reduction produces the dense ``[series, bucket]`` grid. Buckets a series
has no data for hold NaN; the fill policy decides what happens to them
downstream (NONE -> interpolate at merge / skip at emission; ZERO/NAN/
NULL/SCALAR -> substitute).

Calendar-aligned buckets (``1dc``, month/year intervals, timezones) get
their edges precomputed on the host (``DateTime.previousInterval``
semantics) and points are assigned by searchsorted — the kernels never
see calendar logic (SURVEY.md §7 hard part 6).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops import segment
from opentsdb_tpu.ops import aggregators as aggs_mod
from opentsdb_tpu.utils import datetime_util


class FillPolicy(Enum):
    """(ref: src/core/FillPolicy.java:22)"""
    NONE = "none"
    ZERO = "zero"
    NOT_A_NUMBER = "nan"
    NULL = "null"
    SCALAR = "scalar"

    @classmethod
    def from_string(cls, name: str) -> "FillPolicy":
        for p in cls:
            if p.value == name.lower():
                return p
        raise ValueError(f"Unrecognized fill policy: {name}")


@dataclass(frozen=True)
class DownsamplingSpecification:
    """Parsed ``interval-function[-fillpolicy]`` spec
    (ref: DownsamplingSpecification.java:82-116).

    ``interval`` may be ``0all`` (single bucket over the whole query,
    "run-all" mode) or carry a ``c`` suffix for calendar alignment
    (``1dc``). ``fill`` scalar policy is written ``scalar#<value>``.
    """
    interval_ms: int
    function: str
    fill_policy: FillPolicy = FillPolicy.NONE
    fill_value: float = float("nan")
    use_calendar: bool = False
    run_all: bool = False
    interval: int = 0
    unit: str = ""
    timezone: str | None = None
    string_interval: str = ""

    @classmethod
    def parse(cls, spec: str, timezone: str | None = None
              ) -> "DownsamplingSpecification":
        parts = spec.split("-")
        if len(parts) < 2:
            raise ValueError(
                f"Invalid downsampling specification: {spec}")
        interval_str, function = parts[0], parts[1]
        fill_policy = FillPolicy.NONE
        fill_value = float("nan")
        if len(parts) >= 3:
            fp = parts[2]
            if fp.startswith("scalar#"):
                fill_policy = FillPolicy.SCALAR
                fill_value = float(fp.split("#", 1)[1])
            else:
                fill_policy = FillPolicy.from_string(fp)
                if fill_policy == FillPolicy.ZERO:
                    fill_value = 0.0
        if not aggs_mod.exists(function):
            raise ValueError(f"No such downsampling function: {function}")
        # canonicalize registry aliases ("mult" -> "multiply")
        function = aggs_mod.get(function).name
        if interval_str in ("0all", "all"):
            return cls(interval_ms=0, function=function,
                       fill_policy=fill_policy, fill_value=fill_value,
                       run_all=True, string_interval=interval_str,
                       timezone=timezone)
        use_calendar = interval_str.endswith("c")
        if use_calendar:
            interval_str = interval_str[:-1]
        interval = datetime_util.duration_interval(interval_str)
        unit = datetime_util.duration_unit(interval_str)
        interval_ms = datetime_util.parse_duration_ms(interval_str)
        return cls(interval_ms=interval_ms, function=function,
                   fill_policy=fill_policy, fill_value=fill_value,
                   use_calendar=use_calendar, interval=interval, unit=unit,
                   timezone=timezone, string_interval=interval_str)


# ---------------------------------------------------------------------------
# bucket assignment
# ---------------------------------------------------------------------------

def fixed_bucket_edges(start_ms: int, end_ms: int,
                       interval_ms: int) -> np.ndarray:
    """Bucket start times for a fixed interval: aligned down to the
    interval like the reference aligns output timestamps
    (Downsampler timestamps are modulo-aligned)."""
    first = start_ms - (start_ms % interval_ms)
    return np.arange(first, end_ms + 1, interval_ms, dtype=np.int64)


def calendar_bucket_edges(start_ms: int, end_ms: int, interval: int,
                          unit: str, tz: str | None) -> np.ndarray:
    """Host-computed calendar bucket starts (tz/DST-aware)."""
    edges = [datetime_util.previous_interval_ms(start_ms, interval, unit, tz)]
    while edges[-1] <= end_ms:
        edges.append(datetime_util.next_interval_ms(edges[-1], interval,
                                                    unit, tz))
    return np.asarray(edges[:-1] if edges[-1] > end_ms else edges,
                      dtype=np.int64)


def assign_buckets_padded(ts2d: np.ndarray, counts: np.ndarray,
                          spec: DownsamplingSpecification,
                          start_ms: int, end_ms: int
                          ) -> tuple[np.ndarray, np.ndarray]:
    """Padded-layout bucket assignment: ``ts2d[S, Pmax]`` with per-row
    point counts. Returns ``(bucket_idx2d int32[S, Pmax] with -1 pads,
    bucket_ts int64[B])``."""
    idx, bucket_ts = assign_buckets(ts2d.reshape(-1), spec, start_ms,
                                    end_ms)
    idx = idx.reshape(ts2d.shape)
    from opentsdb_tpu.core.store import pad_mask
    idx[pad_mask(counts, ts2d.shape[1])] = -1
    return idx, bucket_ts


def assign_buckets(ts_ms: np.ndarray, spec: DownsamplingSpecification,
                   start_ms: int, end_ms: int
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: map point timestamps to bucket indices.

    Returns ``(bucket_idx int32[N], bucket_ts int64[B])``.
    """
    if spec.run_all:
        bucket_ts = np.asarray([start_ms], dtype=np.int64)
        return np.zeros(len(ts_ms), dtype=np.int32), bucket_ts
    if spec.use_calendar or spec.unit in ("n", "y"):
        edges = calendar_bucket_edges(start_ms, end_ms, spec.interval,
                                      spec.unit, spec.timezone)
        idx = np.searchsorted(edges, ts_ms, side="right") - 1
        return idx.astype(np.int32), edges
    edges = fixed_bucket_edges(start_ms, end_ms, spec.interval_ms)
    idx = ((ts_ms - edges[0]) // spec.interval_ms).astype(np.int32)
    return idx, edges


# ---------------------------------------------------------------------------
# the bucketize kernel
# ---------------------------------------------------------------------------

# downsample functions implementable from O(1) segment statistics
_SIMPLE_FNS = frozenset((
    "sum", "zimsum", "pfsum", "min", "mimmin", "max", "mimmax", "avg",
    "count", "first", "last", "multiply", "squareSum", "dev", "diff"))


@partial(jax.jit, static_argnames=("num_series", "num_buckets", "function"))
def bucketize(values, series_idx, bucket_idx, num_series: int,
              num_buckets: int, function: str):
    """Downsample a flat point batch into a dense ``[S, B]`` grid.

    Returns ``(grid[S,B] with NaN holes, count[S,B])``. This is the
    reference's whole Downsampler/FillingDownsampler pass as one fused
    XLA program over every series at once.
    """
    nseg = num_series * num_buckets
    seg_ids = series_idx.astype(jnp.int32) * num_buckets + bucket_idx
    # stored NaN values count as missing, like the reference's NaN
    # skipping in Aggregators.runDouble
    valid = ~jnp.isnan(values)
    x0 = jnp.where(valid, values, 0.0)
    cnt = segment.seg_sum(valid.astype(values.dtype), seg_ids, nseg)
    mask = cnt > 0

    if function in ("sum", "zimsum", "pfsum"):
        out = segment.seg_sum(x0, seg_ids, nseg)
    elif function in ("min", "mimmin"):
        out = segment.seg_min(jnp.where(valid, values, jnp.inf),
                              seg_ids, nseg)
    elif function in ("max", "mimmax"):
        out = segment.seg_max(jnp.where(valid, values, -jnp.inf),
                              seg_ids, nseg)
    elif function == "avg":
        out = segment.seg_sum(x0, seg_ids, nseg) / jnp.maximum(cnt, 1)
    elif function == "count":
        out = cnt.astype(values.dtype)
    elif function == "multiply":
        out = segment.seg_prod(jnp.where(valid, values, 1.0),
                               seg_ids, nseg)
    elif function == "squareSum":
        out = segment.seg_sum(x0 * x0, seg_ids, nseg)
    elif function == "first":
        out, _ = segment.seg_first_last(values, seg_ids, nseg, valid)
    elif function == "last":
        _, out = segment.seg_first_last(values, seg_ids, nseg, valid)
    elif function == "diff":
        first, last = segment.seg_first_last(values, seg_ids, nseg,
                                             valid)
        out = jnp.where(cnt == 1, 0.0, last - first)
    elif function == "dev":
        s1 = segment.seg_sum(x0, seg_ids, nseg)
        s2 = segment.seg_sum(x0 * x0, seg_ids, nseg)
        safe = jnp.maximum(cnt, 1)
        mean = s1 / safe
        # population variance (divisor n): matches agg_dev and the
        # reference's own TestAggregators expectations
        var = jnp.maximum(s2 / safe - mean * mean, 0.0)
        out = jnp.where(cnt == 1, 0.0, jnp.sqrt(var))
    elif function == "median":
        out = _bucketize_rank(values, seg_ids, nseg, 50.0, "median")
    else:
        agg = aggs_mod.get(function)
        if not agg.is_percentile:
            raise ValueError(f"unsupported downsample function {function}")
        out = _bucketize_rank(values, seg_ids, nseg, agg.percentile,
                              agg.estimation)

    grid = jnp.where(mask, out, jnp.nan).reshape(num_series, num_buckets)
    return grid, cnt.reshape(num_series, num_buckets)


# downsample functions the padded (scatter-free) kernel supports — all
# simple statistics; percentiles/median need the sort path
PADDED_FNS = frozenset(
    ("sum", "zimsum", "pfsum", "avg", "count", "squareSum", "dev",
     "min", "mimmin", "max", "mimmax", "multiply", "first", "last",
     "diff"))


def padded_supported(function: str, num_buckets: int) -> bool:
    return function in PADDED_FNS


@partial(jax.jit, static_argnames=("num_buckets", "function"))
def bucketize_padded(values2d, bucket_idx2d, num_buckets: int,
                     function: str):
    """Scatter-free downsample of the padded layout.

    ``values2d[S, P]`` (NaN pads), ``bucket_idx2d[S, P]`` int32 (-1 for
    pads) -> ``(grid[S, B] with NaN holes, count[S, B])``. Every
    statistic reduces the broadcast ``[S, P, B]`` bucket-membership
    compare over the point axis in one fused multi-output pass — XLA
    keeps the compare virtual, so the data streams from HBM once.
    (Measured on v5e at [1M, 60]x12: 1.1 ms vs 6.8 ms for an MXU
    one-hot einsum, vs 12 ms for per-bucket masked passes, vs ~9.4 ms
    for TPU scatter segment_sum.)
    """
    valid = (~jnp.isnan(values2d)) & (bucket_idx2d >= 0)
    x0 = jnp.where(valid, values2d, 0.0)
    dt = values2d.dtype
    # [S, P, B] bucket-membership (virtual under XLA fusion)
    veq = (bucket_idx2d[:, :, None]
           == jnp.arange(num_buckets, dtype=bucket_idx2d.dtype)[
               None, None, :]) & valid[:, :, None]

    def csum(x):
        return jnp.sum(jnp.where(veq, x[:, :, None], 0.0), axis=1)

    cnt = jnp.sum(veq.astype(dt), axis=1)

    if function in ("sum", "zimsum", "pfsum"):
        out = csum(x0)
    elif function == "avg":
        out = csum(x0) / jnp.maximum(cnt, 1)
    elif function == "count":
        out = cnt
    elif function == "squareSum":
        out = csum(x0 * x0)
    elif function == "dev":
        s1 = csum(x0)
        s2 = csum(x0 * x0)
        safe = jnp.maximum(cnt, 1)
        mean = s1 / safe
        # population variance (divisor n): matches agg_dev and the
        # reference's own TestAggregators expectations
        var = jnp.maximum(s2 / safe - mean * mean, 0.0)
        out = jnp.where(cnt == 1, 0.0, jnp.sqrt(var))
    elif function in ("min", "mimmin"):
        out = jnp.min(jnp.where(veq, values2d[:, :, None], jnp.inf),
                      axis=1)
    elif function in ("max", "mimmax"):
        out = jnp.max(jnp.where(veq, values2d[:, :, None], -jnp.inf),
                      axis=1)
    elif function == "multiply":
        out = jnp.prod(jnp.where(veq, values2d[:, :, None], 1.0),
                       axis=1)
    elif function in ("first", "last", "diff"):
        # rows are time-ascending, so first/last = min/max point column
        p = values2d.shape[1]
        col = jnp.arange(p, dtype=jnp.int32)[None, :, None]
        first_pos = jnp.min(jnp.where(veq, col, p), axis=1)   # [S,B]
        last_pos = jnp.max(jnp.where(veq, col, -1), axis=1)
        firstv = jnp.sum(jnp.where(
            veq & (col == first_pos[:, None, :]), x0[:, :, None], 0.0),
            axis=1)
        lastv = jnp.sum(jnp.where(
            veq & (col == last_pos[:, None, :]), x0[:, :, None], 0.0),
            axis=1)
        if function == "first":
            out = firstv
        elif function == "last":
            out = lastv
        else:  # diff: single point -> 0 (ref: Aggregators.Diff)
            out = lastv - firstv
    else:
        raise ValueError(
            f"padded path does not support downsample fn {function!r}")
    grid = jnp.where(cnt > 0, out, jnp.nan)
    return grid, cnt


def _bucketize_rank(values, seg_ids, nseg, q: float, estimation: str):
    """Percentile/median per (series, bucket) via one lexicographic sort
    (segment.segment_sort_ranks) — no ragged loops."""
    sorted_vals, _, starts, counts = segment.segment_sort_ranks(
        values, seg_ids, nseg)
    n = counts.astype(values.dtype)
    p = q / 100.0
    if estimation == "median":
        # upper median: 1-based rank n//2 + 1 (ref: Median sorted[n/2])
        h = jnp.floor(n / 2) + 1
    elif estimation == "legacy":
        h = jnp.clip(p * (n + 1), 1.0, jnp.maximum(n, 1.0))
    elif estimation == "r3":
        h = jnp.clip(jnp.ceil(p * n - 0.5), 1.0, jnp.maximum(n, 1.0))
    elif estimation == "r7":
        h = jnp.clip((n - 1) * p + 1, 1.0, jnp.maximum(n, 1.0))
    else:
        raise ValueError(f"unknown estimation {estimation!r}")
    if estimation in ("r3", "median"):
        h = jnp.floor(h)  # pure rank select, no interpolation
    return segment.select_rank(sorted_vals, starts, counts, h)


def apply_fill(grid, spec: DownsamplingSpecification):
    """Substitute NaN holes per fill policy (NONE leaves NaN for the
    interpolation stage; NULL stays NaN and is handled at serialization)."""
    if spec.fill_policy == FillPolicy.ZERO:
        return jnp.where(jnp.isnan(grid), 0.0, grid)
    if spec.fill_policy == FillPolicy.SCALAR:
        return jnp.where(jnp.isnan(grid), spec.fill_value, grid)
    return grid
