"""Group-by aggregation over the series axis.

(ref: ``src/core/TsdbQuery.java:916-1045`` GroupByAndAggregateCB builds
SpanGroups keyed by concatenated group-by tagv UIDs; each SpanGroup then
runs the AggregationIterator merge loop lazily during serialization)

Here a group is a segment id per series: after interpolation fill
(:mod:`opentsdb_tpu.ops.interp`), one segment reduction over axis 0 of
the ``[series, bucket]`` grid aggregates every group and every bucket at
once. Order-statistic aggregators (median / percentiles) use a single
lexicographic ``lax.sort`` keyed by (group, NaN-last, value) — the
across-series analogue of the bucketize sort path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from opentsdb_tpu.ops import aggregators as aggs_mod
from opentsdb_tpu.ops.interp import fill_gaps


def _seg(fn, data, ids, num, **kw):
    return fn(data, ids, num_segments=num, indices_are_sorted=False, **kw)


# One-hot matmul budget: the MXU contraction beats segment_sum's
# scatter lowering by ~300x at query shapes (measured 0.03 ms vs
# 9.4 ms on [1e6, 12] -> [100, 12]), but S*G must stay bounded so the
# (fused, never materialized) one-hot contraction doesn't explode.
_MATMUL_GROUP_MAX_ELEMS = 2 * 10**9


def _group_sum(data, group_ids, num_groups: int,
               prefer_segment: bool = False):
    """Segment-sum over the series axis: data[S,B] -> [G,B].

    Lowered as a one-hot MXU contraction when S*G permits; TPU scatter
    (segment_sum) otherwise. ``prefer_segment`` (host-CPU placement)
    forces the scatter lowering: XLA:CPU grinds the one-hot dot at
    cells*groups flops (~1 s at [114688, 32] x 1024) while its
    segment_sum is a linear pass (~3 ms at the same shape).
    """
    if prefer_segment:
        return _seg(jax.ops.segment_sum, data, group_ids, num_groups)
    s = data.shape[0]
    if s * num_groups <= _MATMUL_GROUP_MAX_ELEMS:
        onehot = jax.nn.one_hot(group_ids, num_groups, dtype=data.dtype)
        return jax.lax.dot_general(
            onehot, data, (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)
    return _seg(jax.ops.segment_sum, data, group_ids, num_groups)


# chunked-broadcast VPU budget: the [C, G, B] masked tensor per chunk
_CHUNK_CELL_BUDGET = 10_000_000

_CHUNK_REDUCERS = {"min": (jnp.min, jnp.inf),
                   "max": (jnp.max, -jnp.inf),
                   "prod": (jnp.prod, 1.0)}


def _group_extremum(data, group_ids, num_groups: int, mode: str,
                    prefer_segment: bool = False):
    """Non-linear segment reduction (min/max/prod) over the series
    axis: data[S,B] -> [G,B], with missing cells pre-filled by the
    caller with the reduction's identity.

    TPU scatter (segment_min/max/prod) serializes per element (~9 ms
    at [1M, 12] -> 100 groups); a chunked broadcast-membership compare
    reduced twice (within chunk, then across chunks) runs ~3-6x faster
    while the total compare count S*G*B stays bounded. Falls back to
    scatter for very large group counts where the broadcast's G-factor
    loses.
    """
    red, fill = _CHUNK_REDUCERS[mode]
    s, b = data.shape
    if prefer_segment or s * num_groups * b > _MATMUL_GROUP_MAX_ELEMS:
        segf = {"min": jax.ops.segment_min,
                "max": jax.ops.segment_max,
                "prod": jax.ops.segment_prod}[mode]
        return _seg(segf, data, group_ids, num_groups)
    c = max(1, min(s, _CHUNK_CELL_BUDGET // max(1, num_groups * b)))
    pad = (-s) % c
    if pad:
        data = jnp.concatenate(
            [data, jnp.full((pad, b), fill, data.dtype)], axis=0)
        group_ids = jnp.concatenate(
            [group_ids,
             jnp.full((pad,), -1, group_ids.dtype)])
    n = data.shape[0]
    dc = data.reshape(n // c, c, b)
    ic = group_ids.reshape(n // c, c)
    eq = ic[:, :, None] == jnp.arange(
        num_groups, dtype=group_ids.dtype)[None, None, :]
    masked = jnp.where(eq[:, :, :, None], dc[:, :, None, :], fill)
    return red(red(masked, axis=1), axis=0)


@partial(jax.jit, static_argnames=("num_groups", "agg_name",
                                   "prefer_segment"))
def _group_reduce(filled, group_ids, num_groups: int, agg_name: str,
                  prefer_segment: bool = False):
    """Aggregate filled[S,B] into [G,B] per ``agg_name``. NaN = missing.

    ``prefer_segment`` routes every segmented reduction through scatter
    lowering (host-CPU placement; see _group_sum)."""
    gsum = partial(_group_sum, prefer_segment=prefer_segment)
    gext = partial(_group_extremum, prefer_segment=prefer_segment)
    valid = ~jnp.isnan(filled)
    x0 = jnp.where(valid, filled, 0.0)
    cnt = gsum(valid.astype(filled.dtype), group_ids, num_groups)
    any_valid = cnt > 0

    if agg_name in ("sum", "zimsum", "pfsum"):
        out = gsum(x0, group_ids, num_groups)
    elif agg_name == "avg":
        out = gsum(x0, group_ids, num_groups) / jnp.maximum(cnt, 1)
    elif agg_name == "count":
        out = cnt
    elif agg_name in ("min", "mimmin"):
        out = gext(jnp.where(valid, filled, jnp.inf),
                   group_ids, num_groups, "min")
        out = jnp.where(jnp.isinf(out) & (out > 0), jnp.nan, out)
        # mimmin holes filled with +inf are valid contributions; a group
        # where *everything* is +inf has no real data
        any_valid = any_valid & ~jnp.isnan(out)
    elif agg_name in ("max", "mimmax"):
        out = gext(jnp.where(valid, filled, -jnp.inf),
                   group_ids, num_groups, "max")
        out = jnp.where(jnp.isinf(out) & (out < 0), jnp.nan, out)
        any_valid = any_valid & ~jnp.isnan(out)
    elif agg_name == "multiply":
        out = gext(jnp.where(valid, filled, 1.0),
                   group_ids, num_groups, "prod")
    elif agg_name == "squareSum":
        out = gsum(x0 * x0, group_ids, num_groups)
    elif agg_name == "dev":
        s1 = gsum(x0, group_ids, num_groups)
        mean = s1 / jnp.maximum(cnt, 1)
        centered = jnp.where(valid, filled - mean[group_ids], 0.0)
        m2 = gsum(centered * centered, group_ids, num_groups)
        # population variance (divisor n) — see agg_dev
        var = m2 / jnp.maximum(cnt, 1)
        out = jnp.where(cnt == 1, 0.0, jnp.sqrt(jnp.maximum(var, 0.0)))
    elif agg_name in ("first", "last", "diff"):
        s = filled.shape[0]
        pos = jnp.arange(s, dtype=jnp.int32)[:, None]
        first_pos = _seg(jax.ops.segment_min,
                         jnp.where(valid, pos, s), group_ids, num_groups)
        last_pos = _seg(jax.ops.segment_max,
                        jnp.where(valid, pos, -1), group_ids, num_groups)
        fsafe = jnp.clip(first_pos, 0, s - 1)
        lsafe = jnp.clip(last_pos, 0, s - 1)
        first_val = jnp.take_along_axis(filled, fsafe, axis=0)
        last_val = jnp.take_along_axis(filled, lsafe, axis=0)
        if agg_name == "first":
            out = first_val
        elif agg_name == "last":
            out = last_val
        else:  # diff: exactly one value -> 0 (ref: Aggregators.Diff)
            out = jnp.where(cnt == 1, 0.0, last_val - first_val)
    else:
        agg = aggs_mod.get(agg_name)
        if agg_name == "median":
            q, est = 50.0, "median"
        elif agg.is_percentile:
            q, est = agg.percentile, agg.estimation
        else:
            raise ValueError(f"unsupported group aggregator {agg_name}")
        out = _group_rank(filled, valid, cnt, group_ids, num_groups, q, est)
    return jnp.where(any_valid, out, jnp.nan)


def _group_rank(filled, valid, cnt, group_ids, num_groups, q: float,
                est: str):
    """Order statistics per (group, bucket) via one lax.sort along the
    series axis keyed lexicographically by (group, NaN-last, value)."""
    s, b = filled.shape
    gkey = jnp.broadcast_to(group_ids[:, None], (s, b)).astype(jnp.int32)
    # lax.sort's total order puts NaN after every number, so missing
    # cells land at the end of their group without a separate NaN key
    _, sorted_vals = jax.lax.sort((gkey, filled), num_keys=2,
                                  dimension=0)
    sizes = jax.ops.segment_sum(jnp.ones_like(group_ids), group_ids,
                                num_groups)
    starts = jnp.cumsum(sizes) - sizes  # [G]
    n = cnt  # [G,B] valid counts
    p = q / 100.0
    if est == "median":
        h = jnp.floor(n / 2) + 1
    elif est == "legacy":
        h = jnp.clip(p * (n + 1), 1.0, jnp.maximum(n, 1.0))
    elif est == "r3":
        h = jnp.floor(jnp.clip(jnp.ceil(p * n - 0.5), 1.0,
                               jnp.maximum(n, 1.0)))
    elif est == "r7":
        h = jnp.clip((n - 1) * p + 1, 1.0, jnp.maximum(n, 1.0))
    else:
        raise ValueError(f"unknown estimation {est!r}")
    h_floor = jnp.floor(h)
    frac = (h - h_floor) if est in ("legacy", "r7") else jnp.zeros_like(h)
    lo_off = jnp.clip(h_floor.astype(jnp.int32) - 1, 0, None)
    max_off = jnp.maximum(n.astype(jnp.int32) - 1, 0)
    hi_off = jnp.minimum(lo_off + 1, max_off)
    lo_row = jnp.clip(starts[:, None] + jnp.minimum(lo_off, max_off),
                      0, s - 1)
    hi_row = jnp.clip(starts[:, None] + hi_off, 0, s - 1)
    lo = jnp.take_along_axis(sorted_vals, lo_row, axis=0)
    hi = jnp.take_along_axis(sorted_vals, hi_row, axis=0)
    return lo + frac * (hi - lo)


def group_aggregate(grid, bucket_ts, group_ids, num_groups: int,
                    agg: aggs_mod.Aggregator, interpolate: bool = True,
                    prefer_segment: bool = False):
    """The reference's SpanGroup.iterator + AggregationIterator pass:
    interpolation fill per the aggregator's mode, then one segmented
    reduction over the series axis. grid[S,B] -> [G,B].

    ``interpolate=False`` for NAN/NULL downsample fill policies: the
    reference's FillingDownsampler emits explicit NaN points there, so
    the merge loop sees a point (and skips its NaN value) instead of a
    gap — cross-series interpolation never triggers."""
    filled = (fill_gaps(grid, bucket_ts, agg.interpolation.value)
              if interpolate else grid)
    return _group_reduce(filled, group_ids, num_groups, agg.name,
                         prefer_segment=prefer_segment)
