"""Device kernels for the histogram/sketch query path.

(ref: ``src/core/HistogramAggregationIterator.java:319`` — query-time
bucket-wise SUM merge — and ``SimpleHistogram.percentile`` :133)

A batch of histogram datapoints becomes a dense ``[N, NB]`` count
matrix. Merging histograms across series/timestamps is a segment-sum
over the leading axis — lowered as a one-hot MXU contraction like the
scalar group-by (:func:`opentsdb_tpu.ops.groupby._group_sum`) — and
percentile extraction is a vectorized cumsum + rank compare over the
bucket axis. This is BASELINE.json config 4 (p99/p999 over 1M series,
histogram path) as one fused XLA program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("num_segments",))
def merge_histograms(counts, seg_ids, num_segments: int):
    """Bucket-wise SUM of histogram rows into segments.

    counts [N, NB] f32, seg_ids [N] i32 -> [num_segments, NB].
    """
    onehot = jax.nn.one_hot(seg_ids, num_segments, dtype=counts.dtype)
    return jax.lax.dot_general(
        onehot, counts, (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)


@partial(jax.jit, static_argnames=())
def percentiles_from_merged(merged, mids, qs):
    """merged [S, NB] counts, mids [NB] bucket midpoints, qs [Q]
    (percentiles 0-100) -> [Q, S] values.

    Midpoint convention of SimpleHistogram.percentile (:133): the
    bucket whose cumulative count crosses ``total * q/100``
    contributes its midpoint; empty segments produce 0.
    """
    totals = merged.sum(axis=1)                      # [S]
    cum = jnp.cumsum(merged, axis=1)                 # [S, NB]
    target = totals[None, :] * (qs[:, None] / 100.0)  # [Q, S]
    # rank index per (q, segment): number of buckets with cum < target
    idx = jnp.sum(cum[None, :, :] < target[:, :, None], axis=2)
    idx = jnp.clip(idx, 0, mids.shape[0] - 1)
    out = mids[idx]
    return jnp.where(totals[None, :] > 0, out, 0.0)


def histogram_percentile_pipeline(counts: np.ndarray,
                                  seg_ids: np.ndarray,
                                  num_segments: int,
                                  bounds: np.ndarray,
                                  qs: list[float]) -> np.ndarray:
    """Host entry: merge + percentile in one device round-trip.

    counts [N, NB] float, seg_ids [N] (group * T + ts_idx),
    bounds [NB+1] -> [Q, num_segments].

    N and num_segments are geometrically shape-bucketed (ops.shapes)
    before jit: point counts and group*T products drift query to
    query, and an unbucketed first histogram query pays a multi-second
    compile (r4 bench_e2e config-4 cold was 2.5s). Zero-count pad rows
    route to a dummy segment that is trimmed from the output.
    """
    from opentsdb_tpu.ops import shapes
    rows, nb = counts.shape
    target = shapes.shape_bucket(rows)
    seg_pad = shapes.shape_bucket(num_segments + 1)
    if target != rows:
        if isinstance(counts, jax.Array):
            # device-resident (HBM cache hit): pad on device, never a
            # host round trip
            counts = jnp.pad(counts, ((0, target - rows), (0, 0)))
        else:
            counts = shapes.pad_2d_host(np.asarray(counts), target,
                                        nb, 0.0)
    n_seg = len(seg_ids)
    if n_seg != target:
        # pad rows (pre-padded cached counts, or the pad above) route
        # to a dummy segment trimmed from the output
        seg_ids = np.concatenate(
            [np.asarray(seg_ids),
             np.full(target - n_seg, num_segments, dtype=np.int32)])
    mids = ((np.asarray(bounds[:-1]) + np.asarray(bounds[1:])) / 2.0)
    merged = merge_histograms(
        jnp.asarray(counts, dtype=jnp.float32),
        jnp.asarray(seg_ids, dtype=jnp.int32), seg_pad)
    out = percentiles_from_merged(
        merged, jnp.asarray(mids, dtype=jnp.float32),
        jnp.asarray(np.asarray(qs, dtype=np.float32)))
    return np.asarray(out)[:, :num_segments]
