"""Merge-time interpolation as vectorized gap filling.

(ref: ``src/core/AggregationIterator.java:27-119`` — the O(1)-space
k-way merge that linearly interpolates each span at timestamps where
other spans have data)

On the ``[series, bucket]`` grid the same semantics become a masked fill
along the time axis: for every NaN hole *between* a series' first and
last values, substitute per the aggregator's interpolation mode; outside
that range the series contributes nothing (stays NaN), exactly like a
span that is exhausted or not yet started in the reference's merge loop.

The prev/next-valid-index machinery is two cumulative scans — XLA
compiles them to fast parallel prefix ops on TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from opentsdb_tpu.ops.aggregators import Interpolation


def _prev_valid_idx(mask):
    """[S,B] -> per cell, index of the nearest valid cell at or before it
    (-1 if none)."""
    b = mask.shape[-1]
    idx = jnp.where(mask, jnp.arange(b, dtype=jnp.int32), -1)
    return jax.lax.cummax(idx, axis=mask.ndim - 1)


def _next_valid_idx(mask):
    """[S,B] -> per cell, index of nearest valid cell at or after it
    (B if none). ``reverse=True`` scans right-to-left in place — the
    flip/scan/flip spelling materializes two reversed copies of the
    grid (measured 4.6 ms vs 0.8 ms at [1M, 12])."""
    b = mask.shape[-1]
    idx = jnp.where(mask, jnp.arange(b, dtype=jnp.int32), b)
    return jax.lax.cummin(idx, axis=mask.ndim - 1, reverse=True)


# Unrolled-select budget: reading the grid B times (one fused pass per
# bucket) beats TPU's per-element gather lowering of take_along_axis by
# ~17x at query shapes, but the S*B*B read traffic must stay bounded.
_SELECT_GATHER_MAX_ELEMS = 2 * 10**8
_SELECT_GATHER_MAX_B = 128


def _gather_minor(grid, idx):
    """``grid[s, idx[s, b]]`` along the minor axis.

    take_along_axis lowers to per-element gathers on TPU (measured
    134 ms on a [1e6, 12] grid vs 8 ms for B fused selects), so small
    bucket counts use an unrolled select chain instead; XLA fuses it
    into one pass over the grid per bucket.

    NOTE: only suitable for cheap lookups (e.g. single-column boundary
    summaries). The hot fill/rate kernels use
    :func:`carry_prev`/:func:`carry_next` instead — the select chain
    stops fusing around B=14 on TPU and falls off a 15x cliff
    (measured 88 ms -> 1.5 s at [1M, 13] -> [1M, 14]).
    """
    s, b = grid.shape
    if b <= _SELECT_GATHER_MAX_B and s * b * b <= _SELECT_GATHER_MAX_ELEMS:
        out = jnp.zeros_like(grid)
        for k in range(b):
            out = jnp.where(idx == k, grid[:, k:k + 1], out)
        return out
    return jnp.take_along_axis(grid, idx, axis=-1)


def _nearest_present_scan(arrays, mask, reverse: bool):
    """'Nearest present wins' associative scan along the minor axis.

    The combiner is direction-independent: jax flips the sequence for
    ``reverse=True``, so in SCAN order the right/newer segment always
    holds the nearer candidates and wins where present.
    """
    def combine(a, b):
        bp = b[-1]
        out = tuple(jnp.where(bp, xb, xa)
                    for xa, xb in zip(a[:-1], b[:-1]))
        return out + (a[-1] | bp,)

    # associative_scan's reverse path requires a non-negative axis
    return jax.lax.associative_scan(combine, tuple(arrays) + (mask,),
                                    axis=mask.ndim - 1,
                                    reverse=reverse)


def carry_prev(arrays, mask):
    """For each cell along the minor axis: the values of ``arrays`` at
    the nearest PRESENT cell at-or-before it, plus that presence flag.

    A log2(B)-step ``lax.associative_scan`` — no gathers at all, so
    the cost is O(S B log B) instead of the select chain's O(S B^2)
    with its B>=14 fusion cliff (measured 88 ms -> 1.5 s at [1M, 13]
    -> [1M, 14])."""
    return _nearest_present_scan(arrays, mask, reverse=False)


def carry_next(arrays, mask):
    """Reverse twin of :func:`carry_prev`: nearest present cell
    at-or-after."""
    return _nearest_present_scan(arrays, mask, reverse=True)


def shift_prev(arrays, fill_values):
    """Shift each [S, B] array one column right (making an inclusive
    prev-carry exclusive: 'strictly before'), filling column 0."""
    return tuple(
        jnp.concatenate([jnp.full_like(a[:, :1], fv), a[:, :-1]],
                        axis=-1)
        for a, fv in zip(arrays, fill_values))


@partial(jax.jit, static_argnames=("mode",))
def fill_gaps(grid, bucket_ts, mode: str):
    """Fill NaN holes of ``grid[S,B]`` per interpolation ``mode``.

    - ``lerp``: linear interpolation against ``bucket_ts`` between each
      series' first and last valid cells; NaN outside.
    - ``zim``: 0 for every hole (ZeroIfMissing, Aggregators ZIM).
    - ``max`` / ``min``: +inf / -inf for holes *between* first and last
      valid (type extremes, used by mimmin/mimmax); NaN outside.
    - ``prev``: repeat previous valid value (PREV / pfsum); NaN before
      the first valid cell.

    Returns the filled grid (still [S,B]); cells a series can never
    contribute to stay NaN so downstream reductions skip them.
    """
    mask = ~jnp.isnan(grid)
    if mode == Interpolation.ZIM.value:
        return jnp.where(mask, grid, 0.0)

    gz = jnp.where(mask, grid, 0.0)  # scans must not propagate NaN
    if mode == Interpolation.PREV.value:
        prev_val, has_prev = carry_prev((gz,), mask)
        return jnp.where(mask, grid,
                         jnp.where(has_prev, prev_val, jnp.nan))

    ts_row = jnp.broadcast_to(bucket_ts[None, :], grid.shape)
    v0, t0, has0 = carry_prev((gz, ts_row), mask)
    v1, t1, has1 = carry_next((gz, ts_row), mask)
    in_range = has0 & has1
    if mode in (Interpolation.MAX.value, Interpolation.MIN.value):
        extreme = jnp.inf if mode == Interpolation.MAX.value else -jnp.inf
        return jnp.where(mask, grid,
                         jnp.where(in_range, extreme, jnp.nan))

    if mode != Interpolation.LERP.value:
        raise ValueError(f"unknown interpolation mode {mode!r}")
    # integer ts diffs before the float cast (exact under int32
    # relative offsets, see pipeline.device_bucket_ts)
    t = bucket_ts[None, :]
    num = (t - t0).astype(grid.dtype)
    den = (t1 - t0).astype(grid.dtype)
    lerped = v0 + (v1 - v0) * num / jnp.where(den > 0, den, 1.0)
    return jnp.where(mask, grid, jnp.where(in_range, lerped, jnp.nan))
