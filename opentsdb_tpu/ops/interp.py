"""Merge-time interpolation as vectorized gap filling.

(ref: ``src/core/AggregationIterator.java:27-119`` — the O(1)-space
k-way merge that linearly interpolates each span at timestamps where
other spans have data)

On the ``[series, bucket]`` grid the same semantics become a masked fill
along the time axis: for every NaN hole *between* a series' first and
last values, substitute per the aggregator's interpolation mode; outside
that range the series contributes nothing (stays NaN), exactly like a
span that is exhausted or not yet started in the reference's merge loop.

The prev/next-valid-index machinery is two cumulative scans — XLA
compiles them to fast parallel prefix ops on TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from opentsdb_tpu.ops.aggregators import Interpolation


def _prev_valid_idx(mask):
    """[S,B] -> per cell, index of the nearest valid cell at or before it
    (-1 if none)."""
    b = mask.shape[-1]
    idx = jnp.where(mask, jnp.arange(b, dtype=jnp.int32), -1)
    return jax.lax.cummax(idx, axis=mask.ndim - 1)


def _next_valid_idx(mask):
    """[S,B] -> per cell, index of nearest valid cell at or after it
    (B if none)."""
    b = mask.shape[-1]
    idx = jnp.where(mask, jnp.arange(b, dtype=jnp.int32), b)
    return jnp.flip(jax.lax.cummin(jnp.flip(idx, -1), axis=mask.ndim - 1), -1)


@partial(jax.jit, static_argnames=("mode",))
def fill_gaps(grid, bucket_ts, mode: str):
    """Fill NaN holes of ``grid[S,B]`` per interpolation ``mode``.

    - ``lerp``: linear interpolation against ``bucket_ts`` between each
      series' first and last valid cells; NaN outside.
    - ``zim``: 0 for every hole (ZeroIfMissing, Aggregators ZIM).
    - ``max`` / ``min``: +inf / -inf for holes *between* first and last
      valid (type extremes, used by mimmin/mimmax); NaN outside.
    - ``prev``: repeat previous valid value (PREV / pfsum); NaN before
      the first valid cell.

    Returns the filled grid (still [S,B]); cells a series can never
    contribute to stay NaN so downstream reductions skip them.
    """
    mask = ~jnp.isnan(grid)
    if mode == Interpolation.ZIM.value:
        return jnp.where(mask, grid, 0.0)

    nb = grid.shape[-1]
    prev_idx = _prev_valid_idx(mask)
    if mode == Interpolation.PREV.value:
        safe_prev = jnp.clip(prev_idx, 0, nb - 1)
        prev_val = jnp.take_along_axis(grid, safe_prev, axis=-1)
        return jnp.where(mask, grid,
                         jnp.where(prev_idx >= 0, prev_val, jnp.nan))

    next_idx = _next_valid_idx(mask)
    in_range = (prev_idx >= 0) & (next_idx < nb)
    if mode in (Interpolation.MAX.value, Interpolation.MIN.value):
        extreme = jnp.inf if mode == Interpolation.MAX.value else -jnp.inf
        return jnp.where(mask, grid,
                         jnp.where(in_range, extreme, jnp.nan))

    if mode != Interpolation.LERP.value:
        raise ValueError(f"unknown interpolation mode {mode!r}")
    safe_prev = jnp.clip(prev_idx, 0, nb - 1)
    safe_next = jnp.clip(next_idx, 0, nb - 1)
    v0 = jnp.take_along_axis(grid, safe_prev, axis=-1)
    v1 = jnp.take_along_axis(grid, safe_next, axis=-1)
    ts = bucket_ts.astype(grid.dtype)
    t = ts[None, :]
    t0 = ts[safe_prev]
    t1 = ts[safe_next]
    dt = jnp.where(t1 > t0, t1 - t0, 1.0)
    lerped = v0 + (v1 - v0) * (t - t0) / dt
    return jnp.where(mask, grid, jnp.where(in_range, lerped, jnp.nan))
