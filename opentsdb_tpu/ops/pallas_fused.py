"""Pallas TPU kernel: the fused dense query pipeline in ONE pass.

The XLA dense path (:func:`opentsdb_tpu.ops.pipeline.run_pipeline_dense`)
compiles to a reshape-reduction followed by ``jax.ops.segment_sum`` for
the group stage. On TPU the segment reduction lowers to a scatter-add —
a serialized, VPU-hostile op. This kernel replaces the whole chain
(downsample -> rate -> group reduce) with a single ``pallas_call`` in
which EVERY reduction is a matmul on the **MXU** (the systolic array):

- downsample: ``x[TILE_S, P] @ A[P, B]`` where ``A`` is the
  host-precomputed bucket-membership matrix (1 or 1/k per cell; one-hot
  columns for first/last);
- rate: the first-difference operator is linear, so its shift matrix
  ``R`` (I with -1 superdiagonal) and the 1/dt scaling are folded into
  ``A``/``bias`` on the host — no in-kernel shifts;
- group-by: ``onehot(group_ids)[G, TILE_S] @ grid[TILE_S, B]``
  accumulated across series tiles (one-hot segment-reduction-as-matmul).

The ``[S, P]`` value matrix is streamed HBM -> VMEM one series tile at a
time — a single full pass over the data, everything else rides the MXU.

Scope: used for *complete* regular-cadence data (no NaN holes) — the
monitoring-data common case and the benchmark shape (BASELINE.json
configs). With no holes, merge interpolation
(AggregationIterator.java:27-119) is a no-op, so the kernel is
numerically identical to the general path; the caller
(:func:`opentsdb_tpu.ops.pipeline.execute`) verifies completeness and
falls back otherwise. Golden tests: ``tests/test_pallas_fused.py``.

On non-TPU backends the kernel runs in interpreter mode so the CPU test
matrix exercises the same code path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

# downsample functions expressible as a matmul against a membership
# matrix on complete data (min/max need order statistics -> XLA path)
_DS_FNS = frozenset(("sum", "zimsum", "pfsum", "avg", "count", "first",
                     "last"))
# group aggregators expressible as an accumulated matmul
_AGG_FNS = frozenset(("sum", "zimsum", "pfsum", "avg", "count",
                      "squareSum"))

_VMEM_BUDGET = 6 * 1024 * 1024  # per-tile VMEM budget for the value block


def supported(spec, dtype) -> bool:
    """Can the kernel run this (ds_function, agg, rate) combination?"""
    if spec.ds_function not in _DS_FNS or spec.agg_name not in _AGG_FNS:
        return False
    if spec.emit_raw:
        return False
    if spec.rate and (spec.rate_counter or spec.rate_drop_resets):
        return False
    if jnp.dtype(dtype) == jnp.float64 and \
            jax.default_backend() == "tpu":
        return False  # MXU has no f64
    return True


def _tile_s(s: int, p: int, itemsize: int) -> int:
    # 1024 measured fastest on v5e for the benchmark shape (P=64):
    # fewer grid steps than 256 (amortizes per-step overhead ~3x),
    # while 2048+ degrades (VMEM pressure from the [G, TILE_S] one-hot
    # and worse MXU scheduling). Halve only to respect the VMEM budget
    # for long point axes.
    tile = 1024
    while tile > 8 and tile * p * itemsize > _VMEM_BUDGET:
        tile //= 2
    return max(8, min(tile, -(-s // 8) * 8))


def _build_operators(spec, k: int, bucket_ts: np.ndarray, dtype):
    """Host-side: fold downsample + rate + dt scaling into
    (A [P, B], bias [1, B])."""
    b = spec.num_buckets
    p = b * k
    fn = spec.ds_function
    m = np.zeros((p, b), dtype=dtype)
    bias = np.zeros((1, b), dtype=dtype)
    cols = np.arange(b)
    if fn in ("sum", "zimsum", "pfsum"):
        for j in range(b):
            m[j * k:(j + 1) * k, j] = 1.0
    elif fn == "avg":
        for j in range(b):
            m[j * k:(j + 1) * k, j] = 1.0 / k
    elif fn == "first":
        m[cols * k, cols] = 1.0
    elif fn == "last":
        m[cols * k + k - 1, cols] = 1.0
    elif fn == "count":
        bias[0, :] = float(k)  # complete data: every bucket holds k pts
    else:  # pragma: no cover - guarded by supported()
        raise ValueError(fn)
    if spec.rate:
        # rate[b] = (ds[b] - ds[b-1]) / dt[b]: fold the difference
        # operator R (I with -1 on the superdiagonal) AND the 1/dt
        # scaling into A/bias on the host; column 0 scales to 0 to
        # stand in for the dropped first bucket (finalizer turns it
        # into NaN / ZIM-zero).
        r = np.eye(b, dtype=np.float64)
        r[cols[:-1], cols[1:]] = -1.0
        ts = np.asarray(bucket_ts, dtype=np.float64)
        dt = np.ones(b, dtype=np.float64)
        if b > 1:
            d = (ts[1:] - ts[:-1]) / 1000.0  # ms -> s (RateSpan dv/dt)
            d[d <= 0] = 1.0  # _rate_kernel clamps non-positive dt
            dt[1:] = d
        inv = 1.0 / dt
        inv[0] = 0.0
        m = (m.astype(np.float64) @ r * inv[None, :]).astype(dtype)
        bias = (bias.astype(np.float64) @ r * inv[None, :]).astype(dtype)
    return m, bias


def _kernel(vals_ref, gid_ref, a_ref, bias_ref, acc_ref, *,
            g: int, square: bool):
    """One series tile: (x @ A) + bias, then one-hot matmul."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    tile_s = vals_ref.shape[0]
    # HIGHEST precision: the MXU otherwise rounds f32 operands to bf16
    # (measured 0.6% error on rate queries); 6-pass bf16 is f32-exact
    # and the kernel is bandwidth-bound, so the extra MXU passes are
    # hidden behind the HBM stream
    t = jnp.dot(vals_ref[:], a_ref[:],
                preferred_element_type=acc_ref.dtype,
                precision=jax.lax.Precision.HIGHEST)
    t = t + bias_ref[:]
    if square:
        t = t * t
    # one-hot [G, TILE_S]: padded rows carry gid -1 -> all-zero columns
    gid = gid_ref[:].reshape(1, tile_s)
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (g, tile_s), 0)
              == gid).astype(t.dtype)
    acc_ref[:] += jnp.dot(onehot, t,
                          preferred_element_type=acc_ref.dtype,
                          precision=jax.lax.Precision.HIGHEST)


@partial(jax.jit, static_argnames=("spec", "tile_s", "interpret"))
def _run(values2d, group_ids_padded, a_mat, bias, group_sizes,
         spec, tile_s: int, interpret: bool):
    s_pad, p = values2d.shape
    b, g = spec.num_buckets, spec.num_groups
    dtype = values2d.dtype
    kern = partial(_kernel, g=g, square=(spec.agg_name == "squareSum"))
    acc = pl.pallas_call(
        kern,
        grid=(s_pad // tile_s,),
        in_specs=[
            pl.BlockSpec((tile_s, p), lambda i: (i, 0)),
            pl.BlockSpec((tile_s, 1), lambda i: (i, 0)),
            pl.BlockSpec((p, b), lambda i: (0, 0)),
            pl.BlockSpec((1, b), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((g, b), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((g, b), dtype),
        interpret=interpret,
    )(values2d, group_ids_padded, a_mat, bias)

    # finalize [G,B] (cheap; stays in the same jit program)
    sizes = group_sizes[:, None].astype(dtype)  # [G,1] series per group
    full_cnt = jnp.broadcast_to(sizes, (g, b))
    cnt = full_cnt
    if spec.rate:
        cnt = cnt.at[:, 0].set(0.0)
    agg = spec.agg_name
    # ZIM-interpolation aggregators (Aggregators.java:92-113) fill every
    # hole — including the rate-dropped first bucket — with a *valid* 0,
    # so their effective count never drops.
    zim = agg in ("zimsum", "count", "squareSum")
    eff_cnt = full_cnt if zim else cnt
    if agg in ("sum", "zimsum", "pfsum", "squareSum"):
        out = acc
    elif agg == "avg":
        out = acc / jnp.maximum(eff_cnt, 1.0)
    elif agg == "count":
        out = eff_cnt
    else:  # pragma: no cover - guarded by supported()
        raise ValueError(agg)
    any_valid = eff_cnt > 0
    result = jnp.where(any_valid, out, jnp.nan)
    from opentsdb_tpu.ops import downsample as ds_mod
    if spec.fill_policy == ds_mod.FillPolicy.NONE:
        # emission follows pre-fill presence (has_data in
        # _finish_pipeline): the rate-dropped bucket never emits even
        # for ZIM aggregators
        emit = cnt > 0
    else:
        emit = jnp.ones((g, b), dtype=bool)
    return result, emit


def prepare(values2d: np.ndarray, bucket_ts: np.ndarray,
            group_ids: np.ndarray, spec, k: int, dtype=jnp.float32,
            device=None):
    """Host prep: pad, fold operators, upload. Returns
    (device_args, tile_s, interpret) ready for :func:`_run` — split out
    so callers timing steady-state compute can upload once."""
    np_dtype = np.dtype(dtype)
    s, p = values2d.shape
    tile_s = _tile_s(s, p, np_dtype.itemsize)
    s_pad = -(-s // tile_s) * tile_s
    vals = np.zeros((s_pad, p), dtype=np_dtype)
    vals[:s] = values2d
    gids = np.full((s_pad, 1), -1, dtype=np.int32)
    gids[:s, 0] = group_ids
    a_mat, bias = _build_operators(spec, k, bucket_ts, np_dtype)
    sizes = np.bincount(group_ids, minlength=spec.num_groups) \
        .astype(np.int32)
    put = partial(jax.device_put, device=device)
    args = (put(jnp.asarray(vals)), put(jnp.asarray(gids)),
            put(jnp.asarray(a_mat)), put(jnp.asarray(bias)),
            put(jnp.asarray(sizes)))
    interpret = jax.default_backend() != "tpu"
    return args, tile_s, interpret


def fused_dense_pipeline(values2d: np.ndarray, bucket_ts: np.ndarray,
                         group_ids: np.ndarray, spec, k: int,
                         dtype=jnp.float32, device=None):
    """Host entry mirroring :func:`pipeline.run_pipeline_dense` for
    complete data. values2d [S, P] (no NaN), bucket_ts [B] ms,
    group_ids [S] -> (result [G,B] np, emit [G,B] np)."""
    args, tile_s, interpret = prepare(values2d, bucket_ts, group_ids,
                                      spec, k, dtype, device)
    result, emit = _run(*args, spec, tile_s, interpret)
    return np.asarray(result), np.asarray(emit)
