"""Pallas TPU kernel: the fused dense query pipeline in ONE pass.

The XLA dense path (:func:`opentsdb_tpu.ops.pipeline.run_pipeline_dense`)
compiles to a reshape-reduction followed by ``jax.ops.segment_sum`` for
the group stage. On TPU the segment reduction lowers to a scatter-add —
a serialized, VPU-hostile op. This kernel replaces the whole chain
(downsample -> rate -> group reduce) in a single ``pallas_call``:

- **layout**: the value matrix is streamed as ``[P, S]`` (time-major),
  NOT ``[S, P]``. XLA stores TPU arrays (8, 128)-lane-tiled, so an
  ``[S, P]`` f32 array with P = 60 pads 60 -> 128 lanes in HBM and the
  kernel would stream ~2x the logical bytes. Time-major puts the huge
  series axis on the 128-lane dimension (near-zero padding).
- downsample: ``A01[B, P] @ x[P, TILE]`` where ``A01`` is the
  host-built bucket-membership matrix with entries in {0, 1} (one-hot
  rows for first/last); the 1/k average scale is applied afterwards on
  the VPU so ``A01`` stays *exactly representable in bfloat16*;
  min/max downsample runs as a VPU reshape-reduction instead.
- rate: explicit first-difference on the ``[B, TILE]`` downsampled
  block (sublane shift + multiply by host-precomputed 1/dt), which also
  supports counter rollover correction + reset_value — nonlinear ops a
  folded matmul cannot express.
- group-by, **span path** (default): series are sorted by group id at
  prepare time (a one-time device gather), so each TILE covers at most
  ``_SPAN_MAX`` distinct groups. The kernel computes one masked VPU
  *lane* reduction per span slot — no matmul at all — and accumulates
  each partial into its ``[G, B]`` VMEM accumulator row via a masked
  iota broadcast, so the whole execution stays one device launch.
  Measured v5e roofline: the one-hot alternative is MXU-*load*-bound
  (the ``[G, TILE]`` one-hot is the loaded operand; only B=12 columns
  stream per loaded tile, so each exact pass costs ~0.18 ms on the
  1M-series benchmark shape — 3 passes ≈ the whole HBM stream budget),
  while the span kernel runs at the HBM roofline (~850 GB/s effective,
  2x the one-hot kernel) and is f32-exact end to end (no bf16 anywhere
  in the group stage).
- group-by, **one-hot fallback**: when the sorted layout still puts
  more than ``_SPAN_MAX`` groups in one tile (many tiny groups),
  ``onehot(group_ids)[G, TILE] @ t[B, TILE]^T`` accumulated across
  series tiles (one-hot segment-reduction-as-matmul).

**Precision**: the MXU rounds f32 operands to bf16 (measured 0.6%
error). ``Precision.HIGHEST`` fixes that at 6 passes per dot and cost
r02 23% of throughput. Instead, since one operand of every dot (A01 /
onehot) is exact in bf16, only the value operand needs splitting:
``x = hi + mid + lo`` with three bf16 terms carries all 24 f32 mantissa
bits, so three 1-pass dots accumulated in f32 are f32-exact — half the
MXU passes of HIGHEST. On non-TPU backends (interpreter mode, the CPU
test matrix) the dots run unsplit in the compute dtype, keeping golden
tests exact.

Scope: used for *complete* regular-cadence data (no NaN holes) — the
monitoring-data common case and the benchmark shape (BASELINE.json
configs). With no holes, merge interpolation
(AggregationIterator.java:27-119) is a no-op, so the kernel is
numerically identical to the general path; the caller
(:func:`opentsdb_tpu.ops.pipeline.execute`) verifies completeness and
falls back otherwise. ``rate_drop_resets`` stays on the XLA path: the
dropped points re-open NaN holes mid-pipeline. Golden tests:
``tests/test_pallas_fused.py``.
"""

from __future__ import annotations

import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

# downsample functions the kernel computes on complete data: matmul
# against an exact {0,1} membership matrix, VPU reshape-reductions for
# min/max, or a constant for count
_MATMUL_FNS = frozenset(("sum", "zimsum", "pfsum", "avg", "first",
                         "last"))
_MINMAX_FNS = frozenset(("min", "mimmin", "max", "mimmax"))
_DS_FNS = _MATMUL_FNS | _MINMAX_FNS | {"count"}
# group aggregators expressible as an accumulated sum
_AGG_FNS = frozenset(("sum", "zimsum", "pfsum", "avg", "count",
                      "squareSum"))

_VMEM_BUDGET = 10 * 1024 * 1024  # working-set budget per grid step
_MAX_GROUPS = 4096               # onehot [G, TILE] VMEM guard
# span path: max distinct groups one tile of the group-sorted layout
# may cover; above this the one-hot kernel takes over
_SPAN_MAX = 8
# span path: per-tile accumulate does _SPAN_MAX masked [G, B] row
# broadcasts on the VPU — gate the group count so that stays trivial
_SPAN_GROUP_MAX = 1024


def supported(spec, dtype) -> bool:
    """Can the kernel run this (ds_function, agg, rate) combination?"""
    if spec.ds_function not in _DS_FNS or spec.agg_name not in _AGG_FNS:
        return False
    if spec.emit_raw or spec.num_groups > _MAX_GROUPS:
        return False
    if spec.rate and spec.rate_drop_resets:
        return False  # re-opens NaN holes mid-pipeline
    if jnp.dtype(dtype) == jnp.float64 and \
            jax.default_backend() == "tpu":
        return False  # MXU has no f64
    return True


def _span_fixed_bytes(g: int, b: int, itemsize: int) -> int:
    """Tile-independent VMEM the span kernel holds: the [G, B]
    accumulator plus the masked [G, B] update temp."""
    return g * b * itemsize * 2


def _tile_s(s: int, p: int, g: int, itemsize: int,
            span: bool = False, b: int = 0) -> int:
    """Lane-dim series tile. 8192 measured fastest on v5e for the
    benchmark shape (P=60): the [P, TILE] stream block + its three bf16
    split terms must fit the VMEM working set alongside the
    double-buffered input — plus, for the one-hot kernel only, the
    [G, TILE] one-hot. The span kernel instead holds a tile-INDEPENDENT
    [G, B] accumulator + update temp, budgeted as a fixed subtraction
    (prepare() gates the span path off entirely when that fixed cost
    crowds out the stream tiles)."""
    tile = 8192
    onehot_bytes = 0 if span else g * 2
    fixed = _span_fixed_bytes(g, b, itemsize) if span else 0
    while tile > 128 and \
            fixed + tile * (p * (2 * itemsize + 3 * 2) + onehot_bytes) \
            > _VMEM_BUDGET:
        tile //= 2
    return max(128, min(tile, -(-s // 128) * 128))


def _build_membership(spec, k: int, dtype):
    """Host-side: the {0,1} bucket-membership matrix A01 [B, P], exact
    in bf16. (The 1/k average post-scale lives in the kernel: it must
    apply AFTER the split dots so the matrix stays exact.)"""
    b = spec.num_buckets
    p = b * k
    fn = spec.ds_function
    m = np.zeros((b, p), dtype=dtype)
    cols = np.arange(b)
    if fn in ("sum", "zimsum", "pfsum", "avg"):
        for j in range(b):
            m[j, j * k:(j + 1) * k] = 1.0
    elif fn == "first":
        m[cols, cols * k] = 1.0
    elif fn == "last":
        m[cols, cols * k + k - 1] = 1.0
    # count / min / max: matrix unused
    return m


def _build_inv_dt(spec, bucket_ts: np.ndarray, dtype) -> np.ndarray:
    """Host-side: 1/dt seconds per bucket for the rate stage, column 0
    zeroed (the dropped first bucket; finalizer masks it)."""
    b = spec.num_buckets
    ts = np.asarray(bucket_ts, dtype=np.float64)
    dt = np.ones(b, dtype=np.float64)
    if b > 1:
        d = (ts[1:] - ts[:-1]) / 1000.0  # ms -> s (RateSpan dv/dt)
        d[d <= 0] = 1.0  # _rate_kernel clamps non-positive dt
        dt[1:] = d
    inv = 1.0 / dt
    inv[0] = 0.0
    return inv.reshape(b, 1).astype(dtype)


def _split3(x, acc_dtype):
    """x (f32) -> three bf16 terms carrying all 24 mantissa bits."""
    hi = x.astype(jnp.bfloat16)
    r = x - hi.astype(acc_dtype)
    mid = r.astype(jnp.bfloat16)
    lo = (r - mid.astype(acc_dtype)).astype(jnp.bfloat16)
    return hi, mid, lo


def _dot_exact(exact_operand, x, split: bool, acc_dtype,
               dims=(((1,), (0,)), ((), ()))):
    """exact_operand . x (dot_general ``dims``, default plain matmul)
    with f32-class accuracy: ``exact_operand`` is exactly representable
    in bf16 (0/1 entries), so only ``x`` needs the 3-term bf16 split on
    the MXU (3 single-pass dots vs HIGHEST's 6). Unsplit in interpreter
    mode / f64."""
    if not split:
        return jax.lax.dot_general(exact_operand, x, dims,
                                   preferred_element_type=acc_dtype)
    out = None
    for part in _split3(x, acc_dtype):
        d = jax.lax.dot_general(exact_operand, part, dims,
                                preferred_element_type=acc_dtype)
        out = d if out is None else out + d
    return out


def _tile_transform(x, a_ref, inv_ref, rp_ref, *, spec, k: int,
                    split: bool, dtype):
    """Shared per-tile chain: downsample [P,T] -> t [B,T], optional
    rate (incl. counter rollover / reset_value), optional square.
    Identical op order in both kernels so their t agrees bitwise."""
    tile = x.shape[1]
    b = spec.num_buckets
    fn = spec.ds_function

    if fn in _MATMUL_FNS:
        t = _dot_exact(a_ref[:], x, split, dtype)
        if fn == "avg":
            t = t * dtype.type(1.0 / k)
    elif fn == "count":
        t = jnp.full((b, tile), float(k), dtype)
    else:  # min / max family: VPU reshape-reduction over k sub-rows
        xr = x.reshape(b, k, tile)
        if fn in ("min", "mimmin"):
            t = jnp.min(xr, axis=1)
        else:
            t = jnp.max(xr, axis=1)

    # rate: explicit first difference over the bucket (sublane) axis;
    # complete data means the previous present point is always the
    # previous bucket. inv_ref[0] == 0 kills the dropped first bucket.
    if spec.rate:
        t_prev = jnp.concatenate([t[0:1], t[:-1]], axis=0)
        delta = t - t_prev
        if spec.rate_counter:
            # RateSpan.java:150-170 rollover correction
            counter_max = rp_ref[0, 0]
            delta = jnp.where(delta < 0, counter_max - t_prev + t,
                              delta)
        t = delta * inv_ref[:]
        if spec.rate_counter:
            # reset_value: corrected rates above threshold emit 0
            reset_value = rp_ref[0, 1]
            t = jnp.where((reset_value > 0) & (t > reset_value),
                          dtype.type(0.0), t)

    if spec.agg_name == "squareSum":
        t = t * t
    return t


def _kernel(vals_ref, gid_ref, a_ref, inv_ref, rp_ref, acc_ref, *,
            spec, k: int, g: int, split: bool):
    """One-hot fallback kernel: transform the series tile, then a
    one-hot group matmul accumulated into acc [G, B]. rp_ref [1, 2]
    carries (counter_max, reset_value) as traced values so per-query
    rate options never force a Mosaic recompile."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = vals_ref[:]                              # [P, TILE]
    dtype = acc_ref.dtype
    t = _tile_transform(x, a_ref, inv_ref, rp_ref, spec=spec, k=k,
                        split=split, dtype=dtype)

    # group reduce: onehot [G, TILE] (exact in bf16; padded series
    # carry gid -1 -> all-zero columns) against t^T
    gid = gid_ref[:]                             # [1, TILE]
    tile = x.shape[1]
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (g, tile), 0)
              == gid)
    onehot = onehot.astype(jnp.bfloat16 if split else dtype)
    # onehot [G, T] . t [B, T] contracting T -> [G, B]
    acc_ref[:] += _dot_exact(onehot, t, split, dtype,
                             dims=(((1,), (1,)), ((), ())))


def _kernel_span(vals_ref, gid_ref, a_ref, inv_ref, rp_ref, sp_ref,
                 acc_ref, *, spec, k: int, g: int, split: bool):
    """Span kernel (group-sorted layout): transform the series tile,
    then one masked VPU lane-reduction per span slot, accumulated
    straight into the [G, B] VMEM accumulator via a masked row
    broadcast (iota == span_gid). No group matmul, no separate
    segment-sum kernel — one device launch per execution, which also
    minimizes the inter-kernel gaps a multi-tenant device can steal."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = vals_ref[:]                              # [P, TILE]
    dtype = acc_ref.dtype
    b = spec.num_buckets
    t = _tile_transform(x, a_ref, inv_ref, rp_ref, spec=spec, k=k,
                        split=split, dtype=dtype)
    gid = gid_ref[:]                             # [1, TILE]
    sp = sp_ref[0]                               # [1, _SPAN_MAX]
    rows = jax.lax.broadcasted_iota(jnp.int32, (g, 1), 0)
    upd = jnp.zeros((g, b), dtype)
    for j in range(_SPAN_MAX):
        spj = sp[0:1, j:j + 1]                   # [1, 1]
        m = (gid == spj)                         # [1, TILE]
        part = jnp.sum(jnp.where(m, t, dtype.type(0.0)),
                       axis=1)[None, :]          # [1, B]
        # sentinel id g (empty slot / padded series) matches no row
        upd = upd + jnp.where(rows == spj, part, dtype.type(0.0))
    acc_ref[:] += upd


def _finalize(acc, group_sizes, spec, dtype):
    """Shared [G, B] finalizer: aggregator division / counts and the
    emission mask (fill-policy NONE follows pre-fill presence)."""
    g, b = spec.num_groups, spec.num_buckets
    sizes = group_sizes[:, None].astype(dtype)  # [G,1] series per group
    full_cnt = jnp.broadcast_to(sizes, (g, b))
    cnt = full_cnt
    if spec.rate:
        cnt = cnt.at[:, 0].set(0.0)
    agg = spec.agg_name
    # ZIM-interpolation aggregators (Aggregators.java:92-113) fill every
    # hole — including the rate-dropped first bucket — with a *valid* 0,
    # so their effective count never drops.
    zim = agg in ("zimsum", "count", "squareSum")
    eff_cnt = full_cnt if zim else cnt
    if agg in ("sum", "zimsum", "pfsum", "squareSum"):
        out = acc
    elif agg == "avg":
        out = acc / jnp.maximum(eff_cnt, 1.0)
    elif agg == "count":
        out = eff_cnt
    else:  # pragma: no cover - guarded by supported()
        raise ValueError(agg)
    any_valid = eff_cnt > 0
    result = jnp.where(any_valid, out, jnp.nan)
    from opentsdb_tpu.ops import downsample as ds_mod
    if spec.fill_policy == ds_mod.FillPolicy.NONE:
        # emission follows pre-fill presence (has_data in
        # _finish_pipeline): the rate-dropped bucket never emits even
        # for ZIM aggregators
        emit = cnt > 0
    else:
        emit = jnp.ones((g, b), dtype=bool)
    return result, emit


@partial(jax.jit,
         static_argnames=("spec", "tile_s", "interpret", "force_split"))
def _run(*arrays, spec, tile_s: int, interpret: bool,
         rate_params=None, force_split: bool = False):
    """Execute prepared device arrays -> (result [G,B], emit [G,B]).

    ``arrays`` comes from :func:`prepare`:
      5 elements (values_t, gids_row, a_mat, inv_dt, group_sizes)
        -> one-hot kernel;
      6 elements (+ spans [NT, 1, _SPAN_MAX])
        -> span kernel (group-sorted layout).
    """
    span = len(arrays) == 6
    values_t, group_ids_row, a_mat, inv_dt, group_sizes = arrays[:5]
    p, s_pad = values_t.shape
    b, g = spec.num_buckets, spec.num_groups
    k = p // b
    dtype = values_t.dtype
    split = (force_split or not interpret) and dtype == jnp.float32
    if rate_params is None:
        rate_params = jnp.asarray([[float(2**64 - 1), 0.0]], dtype)
    nt = s_pad // tile_s
    in_specs = [
        pl.BlockSpec((p, tile_s), lambda i: (0, i)),
        pl.BlockSpec((1, tile_s), lambda i: (0, i)),
        pl.BlockSpec((b, p), lambda i: (0, 0)),
        pl.BlockSpec((b, 1), lambda i: (0, 0)),
        pl.BlockSpec((1, 2), lambda i: (0, 0)),
    ]
    operands = (values_t, group_ids_row, a_mat, inv_dt, rate_params)
    if span:
        kern = partial(_kernel_span, spec=spec, k=k, g=g, split=split)
        in_specs.append(
            pl.BlockSpec((1, 1, _SPAN_MAX), lambda i: (i, 0, 0)))
        operands = operands + (arrays[5],)
    else:
        kern = partial(_kernel, spec=spec, k=k, g=g, split=split)
    acc = pl.pallas_call(
        kern,
        grid=(nt,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((g, b), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((g, b), dtype),
        interpret=interpret,
    )(*operands)
    return _finalize(acc, group_sizes, spec, dtype)


@partial(jax.jit, donate_argnums=(0,))
def _transpose(values2d):
    """[S_pad, P] -> [P, S_pad] on device: one HBM round trip, vs the
    2x stream penalty every query execution would otherwise pay (see
    module docstring on lane tiling)."""
    return values2d.T


@partial(jax.jit, donate_argnums=(0,))
def _gather_transpose(values2d, order):
    """[S_pad, P] -> sorted [P, S_pad] on device: the group-sort gather
    fused with the transpose (one extra HBM round trip at prepare time;
    steady-state executions then stream the sorted layout for free)."""
    return values2d[order].T


# sort orders keyed by group-id content: fused_dense_pipeline runs
# prepare() per query, and a repeated dashboard query re-sorting the
# same (often 1M-long) group vector pays an O(S log S) host argsort
# each time for an identical permutation. Byte-bounded + locked: the
# TSD's query thread pool calls prepare() concurrently, and a 1M-series
# permutation is ~4 MB of host RAM per entry.
_ORDER_CACHE: "dict[tuple, np.ndarray | None]" = {}
_ORDER_CACHE_MAX_BYTES = 32 * 1024 * 1024
_ORDER_CACHE_LOCK = threading.Lock()
_order_cache_bytes = 0


def _sort_order(gids: np.ndarray):
    """Stable group-sort permutation (None = already sorted), memoized
    on the group-id content digest."""
    global _order_cache_bytes
    from opentsdb_tpu.query.device_cache import array_digest
    key = (array_digest(np.ascontiguousarray(gids)), len(gids))
    with _ORDER_CACHE_LOCK:
        if key in _ORDER_CACHE:
            return _ORDER_CACHE[key]
    order = None if np.all(gids[1:] >= gids[:-1]) else \
        np.argsort(gids, kind="stable").astype(np.int32)
    nbytes = 0 if order is None else order.nbytes
    with _ORDER_CACHE_LOCK:
        while _ORDER_CACHE and \
                _order_cache_bytes + nbytes > _ORDER_CACHE_MAX_BYTES:
            _, old = _ORDER_CACHE.popitem()
            _order_cache_bytes -= 0 if old is None else old.nbytes
        if key not in _ORDER_CACHE:
            _ORDER_CACHE[key] = order
            _order_cache_bytes += nbytes
    return order


def _span_layout(group_ids: np.ndarray, s_pad: int, tile_s: int,
                 g: int):
    """Try the group-sorted span layout. Returns (order | None,
    spans [NT, 1, _SPAN_MAX] i32, gids_sorted_padded [s_pad] i32) or
    None when some tile would cover more than ``_SPAN_MAX`` distinct
    groups or the group count exceeds ``_SPAN_GROUP_MAX`` (many tiny
    groups — the one-hot kernel handles those better). Empty span
    slots and padded series carry the sentinel id ``g``, which matches
    no accumulator row."""
    if g > _SPAN_GROUP_MAX:
        return None
    gids = np.asarray(group_ids, dtype=np.int32)
    s = len(gids)
    nt = s_pad // tile_s
    order = _sort_order(gids) if s else np.zeros(0, dtype=np.int32)
    gsorted = gids if order is None else gids[order]
    gpad = np.full(s_pad, g, np.int32)
    gpad[:s] = gsorted
    gt = gpad.reshape(nt, tile_s)
    spans = np.full((nt, _SPAN_MAX), g, np.int32)
    for i in range(nt):
        u = np.unique(gt[i])
        u = u[u != g]  # padded series need no slot: the sentinel id
        #               already matches no accumulator row
        if len(u) > _SPAN_MAX:
            return None
        spans[i, :len(u)] = u
    return order, spans.reshape(nt, 1, _SPAN_MAX), gpad


def prepare(values2d: np.ndarray, bucket_ts: np.ndarray,
            group_ids: np.ndarray, spec, k: int, dtype=jnp.float32,
            device=None, force_split: bool = False,
            allow_span: bool = True):
    """Host prep: pad, build operators, upload, sort+transpose on
    device. Returns (device_args, tile_s, interpret) ready for
    :func:`_run` — split out so callers timing steady-state compute can
    upload once. ``len(device_args) == 6`` means the span layout was
    selected (see :func:`_run`)."""
    np_dtype = np.dtype(dtype)
    s, p = values2d.shape
    # span viability: its [G, B] accumulator + update temp are
    # tile-independent, so a many-bucket query near the group cap must
    # fall to one-hot BEFORE Mosaic hits the VMEM wall at runtime
    if _span_fixed_bytes(spec.num_groups, spec.num_buckets,
                         np_dtype.itemsize) > _VMEM_BUDGET // 2:
        allow_span = False
    # try the span layout at its own (larger) VMEM-budget tile first;
    # recompute with the one-hot term only on fallback
    tile_s = _tile_s(s, p, spec.num_groups, np_dtype.itemsize,
                     span=allow_span, b=spec.num_buckets)
    s_pad = -(-s // tile_s) * tile_s
    interpret = jax.default_backend() != "tpu"
    split = (force_split or not interpret) and np_dtype == np.float32
    a_mat = _build_membership(
        spec, k, np.float32 if split else np_dtype)
    a_dev = jnp.asarray(a_mat, dtype=jnp.bfloat16 if split else dtype)
    inv_dt = _build_inv_dt(spec, bucket_ts, np_dtype)
    sizes = np.bincount(group_ids, minlength=spec.num_groups) \
        .astype(np.int32)
    put = partial(jax.device_put, device=device)

    vals = np.zeros((s_pad, p), dtype=np_dtype)
    vals[:s] = values2d

    span = _span_layout(group_ids, s_pad, tile_s, spec.num_groups) \
        if allow_span else None
    if span is not None:
        order, spans, gpad = span
        if order is None:
            vals_t = _transpose(put(jnp.asarray(vals)))
        else:
            # padded rows already sit past every real series; the
            # gather only permutes the first s rows
            order_full = np.concatenate(
                [order, np.arange(s, s_pad, dtype=np.int32)])
            vals_t = _gather_transpose(put(jnp.asarray(vals)),
                                       put(jnp.asarray(order_full)))
        args = (vals_t, put(jnp.asarray(gpad.reshape(1, s_pad))),
                put(a_dev), put(jnp.asarray(inv_dt)),
                put(jnp.asarray(sizes)), put(jnp.asarray(spans)))
        return args, tile_s, interpret

    if allow_span:
        # span layout unavailable: redo the tile budget with the
        # one-hot [G, TILE] term the fallback kernel materializes
        tile_s = _tile_s(s, p, spec.num_groups, np_dtype.itemsize,
                         span=False)
        s_pad = -(-s // tile_s) * tile_s
        vals = np.zeros((s_pad, p), dtype=np_dtype)
        vals[:s] = values2d
    gids = np.full((1, s_pad), -1, dtype=np.int32)
    gids[0, :s] = group_ids
    vals_t = _transpose(put(jnp.asarray(vals)))
    args = (vals_t, put(jnp.asarray(gids)), put(a_dev),
            put(jnp.asarray(inv_dt)), put(jnp.asarray(sizes)))
    return args, tile_s, interpret


def fused_dense_pipeline(values2d: np.ndarray, bucket_ts: np.ndarray,
                         group_ids: np.ndarray, spec, k: int,
                         dtype=jnp.float32, device=None,
                         rate_options=None):
    """Host entry mirroring :func:`pipeline.run_pipeline_dense` for
    complete data. values2d [S, P] (no NaN), bucket_ts [B] ms,
    group_ids [S] -> (result [G,B] np, emit [G,B] np)."""
    args, tile_s, interpret = prepare(values2d, bucket_ts, group_ids,
                                      spec, k, dtype, device)
    cm = float(rate_options.counter_max) if rate_options else \
        float(2**64 - 1)
    rv = float(rate_options.reset_value) if rate_options else 0.0
    rp = jnp.asarray([[cm, rv]], dtype)
    result, emit = _run(*args, spec=spec, tile_s=tile_s,
                        interpret=interpret, rate_params=rp)
    return np.asarray(result), np.asarray(emit)
