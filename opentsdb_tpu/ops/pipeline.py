"""The fused query pipeline: downsample -> rate -> interpolate ->
aggregate -> group-by as ONE jit-compiled array program.

This inverts the reference's architecture (SURVEY.md §7): OpenTSDB pulls
one datapoint at a time through an iterator chain interleaved with
serialization (``SpanGroup.iterator`` -> ``AggregationIterator`` ->
``Downsampler`` -> ``RateSpan``, ref AggregationIterator.java:253-280);
here the whole working set is materialized as a flat point batch and the
entire chain compiles to a handful of fused XLA ops over a
``[series, bucket]`` grid. The per-query shapes (S, B, G, N) are traced
once per shape bucket and cached by XLA.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops import aggregators as aggs_mod
from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.ops import groupby as gb_mod
from opentsdb_tpu.ops.rate import RateOptions, _rate_kernel


_SHARED_NAN = float("nan")


@dataclass(frozen=True)
class PipelineSpec:
    """Static (trace-time) configuration of one sub-query's compute."""
    num_series: int
    num_buckets: int
    num_groups: int
    ds_function: str          # downsample function ('sum', 'avg', ...)
    agg_name: str             # group aggregator name ('sum', 'p99', ...)
    fill_policy: ds_mod.FillPolicy = ds_mod.FillPolicy.NONE
    fill_value: float = _SHARED_NAN
    rate: bool = False
    rate_counter: bool = False
    rate_drop_resets: bool = False
    emit_raw: bool = False    # agg 'none': emit per-series, skip group stage
    # True when this program is placed on the host CPU backend (the
    # host-tail path): the group stage then lowers to segment ops
    # instead of the one-hot MXU contraction — measured 3 ms vs 1.0 s
    # at [114688, 32] x 1024 groups on one CPU core, while on TPU the
    # MXU contraction wins by ~300x. Static, so host and device
    # programs compile separately.
    host: bool = False
    # True when the CALLER verified every (series, bucket) cell holds
    # a real value (no pads, no NaNs — the regular-cadence dashboard
    # case): cross-series interpolation and the per-group emission
    # reduction are provably no-ops and are skipped (fill_gaps alone
    # is ~190 ms of a [114688, 30] host-tail query on one core).
    complete: bool = False

    def __post_init__(self):
        # CPython >= 3.10 hashes each NaN object by identity, so a spec
        # built with a fresh float("nan") never compares/hashes equal to
        # the previous query's spec and the jit cache (static arg) would
        # recompile on EVERY query. Canonicalize to one shared NaN.
        if isinstance(self.fill_value, float) and \
                self.fill_value != self.fill_value:
            object.__setattr__(self, "fill_value", _SHARED_NAN)


@partial(jax.jit, static_argnames=("spec",))
def run_pipeline(values, series_idx, bucket_idx, bucket_ts, group_ids,
                 rate_params, fill_value, spec: PipelineSpec):
    """values[N] f32/f64, series_idx[N] i32, bucket_idx[N] i32,
    bucket_ts[B] i64, group_ids[S] i32, rate_params = (counter_max,
    reset_value) -> (result[G,B] or [S,B], emit_mask same shape).

    NaN in the result means "no value" (fill policy NONE/NULL);
    ``emit_mask`` marks buckets that exist in the output per the
    reference's emission rules (union of contributing series' buckets
    for NONE, everything otherwise).
    """
    s, b = spec.num_series, spec.num_buckets

    # 1. downsample: flat points -> [S,B] grid with NaN holes
    grid, cnt = ds_mod.bucketize(values, series_idx, bucket_idx, s, b,
                                 spec.ds_function)
    return _finish_pipeline(grid, cnt > 0, bucket_ts, group_ids,
                            rate_params, fill_value, spec)


@partial(jax.jit, static_argnames=("spec", "pts_per_bucket"))
def run_pipeline_dense(values2d, bucket_ts, group_ids, rate_params,
                       fill_value, spec: PipelineSpec,
                       pts_per_bucket: int):
    """Regular-cadence fast path: every series has the same P
    timestamps and each bucket covers exactly ``pts_per_bucket``
    consecutive points, so downsampling is a dense reshape reduction
    (``[S, B, k]`` over the last axis) — no scatter at all. This is the
    common shape of monitoring data (fixed collection interval) and the
    layout the benchmarks use; wall-clock is pure memory bandwidth.

    values2d: [S, P] with NaN for missing points, P = B * k.
    """
    s, b, k = spec.num_series, spec.num_buckets, pts_per_bucket
    x = values2d.reshape(s, b, k)
    valid = ~jnp.isnan(x)
    cnt = jnp.sum(valid, axis=-1)
    fn = spec.ds_function
    if fn in ("sum", "zimsum", "pfsum"):
        out = jnp.nansum(x, axis=-1)
    elif fn == "avg":
        out = jnp.nansum(x, axis=-1) / jnp.maximum(cnt, 1)
    elif fn in ("min", "mimmin"):
        out = jnp.min(jnp.where(valid, x, jnp.inf), axis=-1)
    elif fn in ("max", "mimmax"):
        out = jnp.max(jnp.where(valid, x, -jnp.inf), axis=-1)
    elif fn == "count":
        out = cnt.astype(values2d.dtype)
    elif fn == "last":
        idx = jnp.max(jnp.where(valid, jnp.arange(k), -1), axis=-1)
        out = jnp.take_along_axis(
            x, jnp.clip(idx, 0, k - 1)[..., None], axis=-1)[..., 0]
    elif fn == "first":
        idx = jnp.min(jnp.where(valid, jnp.arange(k), k), axis=-1)
        out = jnp.take_along_axis(
            x, jnp.clip(idx, 0, k - 1)[..., None], axis=-1)[..., 0]
    else:
        raise ValueError(
            f"dense path does not support downsample fn {fn!r}")
    grid = jnp.where(cnt > 0, out, jnp.nan)
    return _finish_pipeline(grid, cnt > 0, bucket_ts, group_ids,
                            rate_params, fill_value, spec)


@partial(jax.jit, static_argnames=("spec",))
def run_pipeline_padded(values2d, bucket_idx2d, bucket_ts, group_ids,
                        rate_params, fill_value, spec: PipelineSpec):
    """Irregular-data fast path over the row-padded layout
    (:class:`opentsdb_tpu.core.store.PaddedBatch`): scatter-free
    bucketization (see :func:`opentsdb_tpu.ops.downsample.bucketize_padded`),
    then the shared rate/interpolate/aggregate tail.

    values2d: [S, Pmax] NaN-padded; bucket_idx2d: [S, Pmax] int32 with
    -1 marking pads.
    """
    grid, cnt = ds_mod.bucketize_padded(values2d, bucket_idx2d,
                                        spec.num_buckets,
                                        spec.ds_function)
    return _finish_pipeline(grid, cnt > 0, bucket_ts, group_ids,
                            rate_params, fill_value, spec)


def apply_fill_policy(grid, has_data, fill_value, spec: "PipelineSpec"):
    """Downsample fill policy: ZERO/SCALAR substitute before rate,
    matching FillingDownsampler feeding RateSpan. Shared by the full
    and the time-blocked (ops.blocked) executors."""
    if spec.fill_policy == ds_mod.FillPolicy.ZERO:
        grid = jnp.where(jnp.isnan(grid), 0.0, grid)
        has_data = jnp.ones_like(has_data)
    elif spec.fill_policy == ds_mod.FillPolicy.SCALAR:
        grid = jnp.where(jnp.isnan(grid), fill_value, grid)
        has_data = jnp.ones_like(has_data)
    return grid, has_data


def _finish_pipeline(grid, has_data, bucket_ts, group_ids, rate_params,
                     fill_value, spec: PipelineSpec):
    g, b = spec.num_groups, spec.num_buckets

    # 2. downsample fill policy
    grid, has_data = apply_fill_policy(grid, has_data, fill_value, spec)

    # 3. rate conversion per series (ref: Downsampler -> RateSpan order)
    if spec.rate:
        counter_max, reset_value = rate_params
        grid = _rate_kernel(grid, bucket_ts, spec.rate_counter,
                            counter_max, reset_value,
                            spec.rate_drop_resets)
        has_data = has_data & ~jnp.isnan(grid)

    if spec.emit_raw:
        return grid, has_data

    # 4.+5. interpolate at merge + aggregate over series within groups.
    # NAN/NULL fill policies emit explicit NaN points, which the
    # reference's merge loop skips WITHOUT interpolating (runDouble NaN
    # guard); only fill NONE leaves true gaps that interpolate.
    agg = aggs_mod.get(spec.agg_name)
    interpolate = spec.fill_policy == ds_mod.FillPolicy.NONE \
        and not spec.complete
    result = gb_mod.group_aggregate(grid, bucket_ts, group_ids, g, agg,
                                    interpolate=interpolate,
                                    prefer_segment=spec.host)

    # emission: fill NONE emits the union of the group's series' buckets
    # (plain Downsampler skips empty buckets); any other policy emits
    # every bucket (FillingDownsampler semantics). A verified-complete
    # grid emits everywhere by construction (every group has >= 1
    # member series and every cell is filled).
    if spec.complete and not spec.rate:
        emit = jnp.ones((g, b), dtype=bool)
    elif spec.fill_policy == ds_mod.FillPolicy.NONE:
        emit = gb_mod._group_sum(
            has_data.astype(grid.dtype), group_ids, g,
            prefer_segment=spec.host) > 0
    else:
        emit = jnp.ones((g, b), dtype=bool)
    return result, emit


@partial(jax.jit, static_argnames=("spec",))
def run_pipeline_grid(grid, has_data, bucket_ts, group_ids, rate_params,
                      fill_value, spec: PipelineSpec):
    """Tail entry for host-pre-bucketized data: the storage engine's
    fused range-scan already produced the ``[S, B]`` downsample grid
    (NaN holes), so the trace starts at the fill/rate/aggregate chain —
    no per-point upload at all."""
    return _finish_pipeline(grid, has_data, bucket_ts, group_ids,
                            rate_params, fill_value, spec)


def pipeline_dtype():
    """The compute dtype every host entry uses (f64 only under x64)."""
    return jnp.float64 if jax.config.read("jax_enable_x64") \
        else jnp.float32


def as_operand(x, dtype=None):
    """Prepare one jit operand without touching the default device.

    Host values are numpy-cast and handed to jit as-is — jax places
    them WITH the call's committed operands, so they never materialize
    on the default device first. (``jnp.asarray`` would: on a
    tunneled/remote accelerator that eager materialization costs a
    per-operand round trip, and when the computation is bound for the
    host CPU backend the data would travel host -> accelerator -> host
    for nothing.) Device arrays pass through, cast on their own
    device."""
    if isinstance(x, jax.Array):
        return x if dtype is None or x.dtype == jnp.dtype(dtype) \
            else x.astype(dtype)
    return np.asarray(x, dtype=dtype)


def put_grid(grid, has_data, device=None):
    """Upload a [S, B] grid + presence mask once, in the compute dtype
    — callers cache the returned DEVICE arrays so repeated queries
    skip the host scan and the transfer entirely."""
    dtype = pipeline_dtype()
    return (jax.device_put(as_operand(grid, dtype), device=device),
            jax.device_put(as_operand(has_data, bool), device=device))


def _pad_2d(arr, s_pad: int, b_pad: int, fill):
    """Pad a [S, B] array to [s_pad, b_pad]. DEVICE arrays pad on
    device (an eager jnp.pad — never a host round trip: the engine's
    grids are often HBM-resident from the native reduce or the device
    cache, and pulling 1M-series grids through a tunneled host costs
    seconds); host arrays pad in numpy."""
    from opentsdb_tpu.ops import shapes
    s, b = arr.shape
    if (s_pad, b_pad) == (s, b):
        return arr
    if isinstance(arr, jax.Array):
        return jnp.pad(arr, ((0, s_pad - s), (0, b_pad - b)),
                       constant_values=fill)
    return shapes.pad_2d_host(arr, s_pad, b_pad, fill)


def _bucket_dims_and_aux(bucket_ts, group_ids, spec: PipelineSpec,
                         s: int, b: int):
    """Shared shape-bucketing of one grid query: returns
    (s_pad, b_pad, padded bucket_ts, padded group_ids, padded spec)."""
    from opentsdb_tpu.ops import shapes
    from dataclasses import replace
    g = spec.num_groups
    s_pad = shapes.shape_bucket(s)
    b_pad = shapes.shape_bucket(b)
    g_pad = shapes.shape_bucket(g + 1)  # room for the dummy group
    bts = shapes.pad_bucket_ts(np.asarray(bucket_ts), b_pad)
    gids = shapes.pad_group_ids(np.asarray(group_ids), s_pad, g)
    return s_pad, b_pad, bts, gids, replace(
        spec, num_series=s_pad, num_buckets=b_pad, num_groups=g_pad)


def bucket_grid_shapes(grid, has_data, bucket_ts, group_ids,
                       spec: PipelineSpec):
    """Pad (S, B, G) up to geometric shape buckets (ops.shapes) so
    repeat traffic with drifting shapes hits a bounded jit-program
    set. Returns (grid, has_data, bucket_ts, group_ids, spec_padded);
    callers trim the result back to the true (G, B) / (S, B)."""
    s, b = grid.shape
    s_pad, b_pad, bts, gids, pspec = _bucket_dims_and_aux(
        bucket_ts, group_ids, spec, s, b)
    gp = _pad_2d(grid, s_pad, b_pad, np.nan)
    hp = _pad_2d(has_data, s_pad, b_pad, False)
    return gp, hp, bts, gids, pspec


def execute_grid(grid: np.ndarray, has_data: np.ndarray,
                 bucket_ts: np.ndarray, group_ids: np.ndarray,
                 spec: PipelineSpec,
                 rate_options: RateOptions | None = None,
                 dtype=None, device=None
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Host entry over a pre-bucketized [S, B] grid -> (result, emit).
    Shapes are geometrically bucketed (ops.shapes) before jit."""
    if dtype is None:
        dtype = pipeline_dtype()
    ro = rate_options or RateOptions()
    s, b, g = spec.num_series, spec.num_buckets, spec.num_groups
    grid, has_data, bucket_ts, group_ids, pspec = bucket_grid_shapes(
        grid if isinstance(grid, jax.Array) else np.asarray(grid),
        has_data if isinstance(has_data, jax.Array)
        else np.asarray(has_data), bucket_ts, group_ids, spec)
    put = partial(jax.device_put, device=device)
    rate_params = (as_operand(ro.counter_max, dtype),
                   as_operand(ro.reset_value, dtype))
    # the grid is the committed operand deciding placement; everything
    # else rides along as numpy (no eager default-device round trips)
    result, emit = run_pipeline_grid(
        put(as_operand(grid, dtype)),
        put(as_operand(has_data, bool)),
        as_operand(device_bucket_ts(bucket_ts)),
        as_operand(group_ids, np.int32),
        rate_params, as_operand(spec.fill_value, dtype), pspec)
    rows = s if spec.emit_raw else g
    return (np.asarray(result)[:rows, :b],
            np.asarray(emit)[:rows, :b])


def avg_divide_grid(grid_sum, grid_cnt, xp=jnp):
    """The rollup-average derivation shared by the single-device trace
    (:func:`run_pipeline_avg_div`) and the mesh path's host-side
    divide (engine._avg_rollup_pipeline): SUM-tier cells / COUNT-tier
    cells where both tiers have data (ref: RollupSpan agg-prefixed
    sum+count qualifiers). Returns (grid, valid_mask)."""
    valid = (~xp.isnan(grid_sum)) & (~xp.isnan(grid_cnt)) \
        & (grid_cnt > 0)
    grid = xp.where(valid, grid_sum / xp.where(valid, grid_cnt, 1.0),
                    xp.nan)
    return grid, valid


@partial(jax.jit, static_argnames=("spec",))
def run_pipeline_avg_div(grid_sum, grid_cnt, bucket_ts, group_ids,
                         rate_params, fill_value, spec: PipelineSpec):
    """Tail entry for the avg-rollup derivation: divides a bucketized
    SUM-tier grid by a bucketized COUNT-tier grid in-trace (no host
    round-trip for the [S,B] grids), then runs the shared
    rate/interpolate/aggregate chain."""
    grid, valid = avg_divide_grid(grid_sum, grid_cnt, xp=jnp)
    return _finish_pipeline(grid, valid, bucket_ts, group_ids,
                            rate_params, fill_value, spec)


def execute_avg_divide(grid_sum, grid_cnt, bucket_ts: np.ndarray,
                       group_ids: np.ndarray, spec: PipelineSpec,
                       rate_options: RateOptions | None = None,
                       dtype=None, device=None
                       ) -> tuple[np.ndarray, np.ndarray]:
    """Host entry: sum/count tier grids (device arrays straight from
    ``bucketize`` are fine) -> (result, emit). Shapes are geometrically
    bucketed (ops.shapes) before jit."""
    if dtype is None:
        dtype = pipeline_dtype()
    ro = rate_options or RateOptions()
    s, b, g = spec.num_series, spec.num_buckets, spec.num_groups
    s_pad, b_pad, bts_p, gids_p, pspec = _bucket_dims_and_aux(
        bucket_ts, group_ids, spec, grid_sum.shape[0],
        grid_sum.shape[1])
    gsum = _pad_2d(grid_sum, s_pad, b_pad, np.nan)
    gcnt = _pad_2d(grid_cnt, s_pad, b_pad, np.nan)
    put = partial(jax.device_put, device=device)
    rate_params = (as_operand(ro.counter_max, dtype),
                   as_operand(ro.reset_value, dtype))
    result, emit = run_pipeline_avg_div(
        put(as_operand(gsum, dtype)),
        put(as_operand(gcnt, dtype)),
        as_operand(device_bucket_ts(bts_p)),
        as_operand(gids_p, np.int32),
        rate_params, as_operand(spec.fill_value, dtype), pspec)
    rows = s if spec.emit_raw else g
    return (np.asarray(result)[:rows, :b],
            np.asarray(emit)[:rows, :b])


_DENSE_FNS = frozenset(("sum", "zimsum", "pfsum", "avg", "min", "mimmin",
                        "max", "mimmax", "count", "first", "last"))


def detect_dense(num_series: int, num_buckets: int,
                 series_idx: np.ndarray, bucket_idx: np.ndarray,
                 ds_function: str) -> int | None:
    """Detect the regular-cadence layout: every series contributes the
    same P points in the same bucket pattern, with each bucket covering
    exactly k = P / B consecutive points. Returns k, or None.
    """
    if ds_function not in _DENSE_FNS:
        return None
    n = len(series_idx)
    if num_series == 0 or n == 0 or n % num_series != 0:
        return None
    p = n // num_series
    if p % num_buckets != 0:
        return None
    k = p // num_buckets
    sgrid = series_idx.reshape(num_series, p)
    if not (sgrid == np.arange(num_series, dtype=sgrid.dtype)[:, None]).all():
        return None
    bgrid = bucket_idx.reshape(num_series, p)
    expected = np.repeat(np.arange(num_buckets, dtype=bgrid.dtype), k)
    if not (bgrid == expected[None, :]).all():
        return None
    return k


# traffic budget for the padded einsum contraction: S * Pmax * B cells
_PADDED_EINSUM_MAX_CELLS = 2 * 10**9


def detect_regular_padded(counts: np.ndarray, bucket_idx2d: np.ndarray,
                          num_buckets: int) -> int | None:
    """Regular-cadence check on the padded layout: every row full to the
    same P with the identical k-contiguous bucket pattern. Returns k
    (points per bucket) or None."""
    if len(counts) == 0:
        return None
    # tsdlint: allow[kernel-hygiene] ONE scalar probe per call (the
    # first row's count), not a per-element pull
    p = int(counts[0])
    if p == 0 or not (counts == p).all() or \
            bucket_idx2d.shape[1] != p or p % num_buckets != 0:
        return None
    k = p // num_buckets
    expected = np.repeat(np.arange(num_buckets, dtype=bucket_idx2d.dtype),
                         k)
    if not (bucket_idx2d[0] == expected).all():
        return None
    if not (bucket_idx2d == bucket_idx2d[0]).all():
        return None
    return k


def flatten_padded(values2d: np.ndarray, bucket_idx2d: np.ndarray,
                   counts: np.ndarray
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Padded -> flat (values, series_idx, bucket_idx) for the scatter
    and blocked executors."""
    from opentsdb_tpu.core.store import pad_mask
    mask = ~pad_mask(counts, values2d.shape[1])
    series_idx = np.repeat(
        np.arange(values2d.shape[0], dtype=np.int32),
        counts.astype(np.int64))
    return (values2d[mask], series_idx,
            bucket_idx2d[mask].astype(np.int32))


def device_bucket_ts(bucket_ts: np.ndarray) -> np.ndarray:
    """Bucket timestamps in device form: relative int32 ms offsets.

    Absolute epoch-ms values (~1.4e12) overflow int32, and TPU runtimes
    have no int64/float64 — uploading raw int64 silently truncates and
    corrupts every rate/lerp time delta. The kernels only ever use ts
    DIFFERENCES, so relative offsets are exact. Spans too long for
    int32 ms (> ~24 days) degrade to float (f32 on TPU: <= 128 ms
    rounding at the far end, negligible against the wide buckets such
    spans imply).
    """
    rel = np.asarray(bucket_ts, dtype=np.int64)
    if len(rel):
        rel = rel - rel[0]
    if len(rel) == 0 or rel[-1] < 2**31:
        return rel.astype(np.int32)
    return rel.astype(np.float64)


def _run_dense_or_pallas(values2d, bucket_ts, group_ids, spec, k, ro,
                         rate_params, fv, dtype, device,
                         use_pallas: bool) -> tuple[np.ndarray, np.ndarray]:
    """Regular-cadence execution: the fused Pallas kernel when the data
    and op combination allow it, the XLA dense reshape path otherwise.
    Shared by :func:`execute` and :func:`execute_auto`."""
    if use_pallas and not ro.drop_resets:
        from opentsdb_tpu.ops import pallas_fused
        if pallas_fused.supported(spec, dtype) \
                and not np.isnan(values2d).any():
            try:
                return pallas_fused.fused_dense_pipeline(
                    values2d, np.asarray(bucket_ts),
                    np.asarray(group_ids), spec, k, dtype=dtype,
                    device=device, rate_options=ro)
            except Exception:  # noqa: BLE001
                # Mosaic compile/runtime failure -> the XLA dense path
                # computes the same thing; log and degrade
                import logging
                logging.getLogger(__name__).warning(
                    "pallas fused kernel failed; falling back to "
                    "the XLA dense path", exc_info=True)
    put = partial(jax.device_put, device=device)
    result, emit = run_pipeline_dense(
        put(as_operand(values2d, dtype)),
        as_operand(device_bucket_ts(bucket_ts)),
        as_operand(group_ids, np.int32),
        rate_params, fv, spec, k)
    return np.asarray(result), np.asarray(emit)


def execute_auto(padded, bucket_idx2d: np.ndarray,
                 bucket_ts: np.ndarray, group_ids: np.ndarray,
                 spec: PipelineSpec,
                 rate_options: RateOptions | None = None,
                 dtype=None, device=None,
                 use_pallas: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Host entry over a :class:`~opentsdb_tpu.core.store.PaddedBatch`:
    picks pallas/dense for regular data, the scatter-free padded kernel
    for irregular data it supports, and the flat scatter path otherwise.
    """
    if dtype is None:
        dtype = pipeline_dtype()
    ro = rate_options or RateOptions()
    values2d = np.asarray(padded.values2d)
    counts = np.asarray(padded.counts)
    k = detect_regular_padded(counts, np.asarray(bucket_idx2d),
                              spec.num_buckets)
    put = partial(jax.device_put, device=device)
    rate_params = (as_operand(ro.counter_max, dtype),
                   as_operand(ro.reset_value, dtype))
    fv = as_operand(spec.fill_value, dtype)
    if k is not None and spec.ds_function in _DENSE_FNS:
        return _run_dense_or_pallas(values2d, bucket_ts, group_ids,
                                    spec, k, ro, rate_params, fv,
                                    dtype, device, use_pallas)
    cells = values2d.shape[0] * values2d.shape[1] * spec.num_buckets
    if ds_mod.padded_supported(spec.ds_function, spec.num_buckets) \
            and cells <= _PADDED_EINSUM_MAX_CELLS:
        result, emit = run_pipeline_padded(
            put(as_operand(values2d, dtype)),
            as_operand(bucket_idx2d, np.int32),
            as_operand(device_bucket_ts(bucket_ts)),
            as_operand(group_ids, np.int32),
            rate_params, fv, spec)
        return np.asarray(result), np.asarray(emit)
    values, series_idx, bucket_idx = flatten_padded(
        values2d, np.asarray(bucket_idx2d), counts)
    return execute(values, series_idx, bucket_idx, bucket_ts, group_ids,
                   spec, rate_options, dtype=dtype, device=device,
                   use_pallas=use_pallas)


@dataclass(frozen=True)
class PreparedBatch:
    """Device-resident upload of one sub-query's point data, ready to
    execute repeatedly — the engine caches these so a warm query pays
    neither the host materialize nor the transfer (which dominates on
    shared/tunneled devices).

    kind 'dense': arrays = (values2d,), k = points per bucket;
    kind 'padded': arrays = (values2d, bucket_idx2d);
    kind 'flat': arrays = (values, series_idx, bucket_idx).

    ``pad`` = (s_pad, b_pad): the geometric shape buckets the arrays
    were padded to at upload (ops.shapes) — run_prepared swaps them
    into the spec and trims the result, bounding the compile space.
    """
    kind: str
    arrays: tuple
    k: int | None = None
    pad: tuple | None = None

    @property
    def nbytes(self) -> int:
        return sum(getattr(a, "nbytes", 0) for a in self.arrays)


def _pad_rows(arr2d: np.ndarray, s_pad: int, fill) -> np.ndarray:
    s, p = arr2d.shape
    if s_pad == s:
        return arr2d
    out = np.full((s_pad, p), fill, dtype=arr2d.dtype)
    out[:s] = arr2d
    return out


def prepare_auto(padded, bucket_idx2d: np.ndarray, spec: PipelineSpec,
                 dtype=None, device=None) -> PreparedBatch:
    """Layout-detect + upload a PaddedBatch (the same dispatch rules as
    :func:`execute_auto`, minus the pallas micro-path). Shapes pad to
    geometric buckets (ops.shapes): NaN rows for extra series, -1
    bucket sentinels for extra point columns."""
    from opentsdb_tpu.ops import shapes
    if dtype is None:
        dtype = pipeline_dtype()
    put = partial(jax.device_put, device=device)
    values2d = np.asarray(padded.values2d)
    counts = np.asarray(padded.counts)
    bucket_idx2d = np.asarray(bucket_idx2d)
    s, b = spec.num_series, spec.num_buckets
    s_pad = shapes.shape_bucket(s)
    k = detect_regular_padded(counts, bucket_idx2d, spec.num_buckets)
    if k is not None and spec.ds_function in _DENSE_FNS:
        return PreparedBatch(
            "dense",
            (put(as_operand(_pad_rows(values2d, s_pad, np.nan),
                            dtype)),),
            k, pad=(s_pad, b))
    cells = s_pad * values2d.shape[1] * spec.num_buckets
    if ds_mod.padded_supported(spec.ds_function, spec.num_buckets) \
            and cells <= _PADDED_EINSUM_MAX_CELLS:
        return PreparedBatch(
            "padded",
            (put(as_operand(_pad_rows(values2d, s_pad, np.nan),
                            dtype)),
             put(as_operand(_pad_rows(bucket_idx2d, s_pad, -1),
                            np.int32))),
            pad=(s_pad, b))
    values, series_idx, bucket_idx = flatten_padded(
        values2d, bucket_idx2d, counts)
    return prepare_flat(values, series_idx, bucket_idx, spec,
                        dtype=dtype, device=device)


def prepare_flat(values: np.ndarray, series_idx: np.ndarray,
                 bucket_idx: np.ndarray, spec: PipelineSpec,
                 dtype=None, device=None) -> PreparedBatch:
    """Layout-detect + upload a flat point batch, padded to geometric
    shape buckets (dummy points land on a padded series row and a
    padded bucket column, both trimmed by run_prepared)."""
    from opentsdb_tpu.ops import shapes
    if dtype is None:
        dtype = pipeline_dtype()
    put = partial(jax.device_put, device=device)
    s, b = spec.num_series, spec.num_buckets
    s_pad = shapes.shape_bucket(s)
    k = detect_dense(spec.num_series, spec.num_buckets,
                     np.asarray(series_idx), np.asarray(bucket_idx),
                     spec.ds_function)
    if k is not None:
        values2d = np.asarray(values).reshape(spec.num_series, -1)
        return PreparedBatch(
            "dense",
            (put(as_operand(_pad_rows(values2d, s_pad, np.nan),
                            dtype)),),
            k, pad=(s_pad, b))
    n = len(values)
    s_pad = shapes.shape_bucket(s + 1)
    b_pad = shapes.shape_bucket(b + 1)
    n_pad = shapes.shape_bucket(n)
    v = np.zeros(n_pad, dtype=np.asarray(values).dtype)
    v[:n] = values
    si = np.full(n_pad, s_pad - 1, dtype=np.int32)
    si[:n] = series_idx
    bi = np.full(n_pad, b_pad - 1, dtype=np.int32)
    bi[:n] = bucket_idx
    return PreparedBatch(
        "flat", (put(as_operand(v, dtype)),
                 put(si), put(bi)),
        pad=(s_pad, b_pad))


def run_prepared(prep: PreparedBatch, bucket_ts: np.ndarray,
                 group_ids: np.ndarray, spec: PipelineSpec,
                 rate_options: RateOptions | None = None,
                 dtype=None) -> tuple[np.ndarray, np.ndarray]:
    """Execute a (possibly cached) PreparedBatch -> (result, emit),
    trimming off the shape-bucket padding the prepare step added.
    Placement follows the PreparedBatch's committed device arrays
    (decided by prepare_* at upload); the small per-query operands
    ride along as numpy."""
    from dataclasses import replace
    from opentsdb_tpu.ops import shapes
    if dtype is None:
        dtype = pipeline_dtype()
    ro = rate_options or RateOptions()
    s, b, g = spec.num_series, spec.num_buckets, spec.num_groups
    if prep.pad is not None:
        s_pad, b_pad = prep.pad
        g_pad = shapes.shape_bucket(g + 1)
        bucket_ts = shapes.pad_bucket_ts(
            np.asarray(bucket_ts), b_pad)
        group_ids = shapes.pad_group_ids(np.asarray(group_ids),
                                         s_pad, g)
        spec = replace(spec, num_series=s_pad, num_buckets=b_pad,
                       num_groups=g_pad)
    rate_params = (as_operand(ro.counter_max, dtype),
                   as_operand(ro.reset_value, dtype))
    fv = as_operand(spec.fill_value, dtype)
    # numpy operands ride with the committed prepared arrays — no
    # eager default-device materialization per query
    bts = as_operand(device_bucket_ts(bucket_ts))
    gids = as_operand(group_ids, np.int32)
    if prep.kind == "dense":
        result, emit = run_pipeline_dense(
            prep.arrays[0], bts, gids, rate_params, fv, spec, prep.k)
    elif prep.kind == "padded":
        result, emit = run_pipeline_padded(
            prep.arrays[0], prep.arrays[1], bts, gids, rate_params,
            fv, spec)
    else:
        result, emit = run_pipeline(
            prep.arrays[0], prep.arrays[1], prep.arrays[2], bts, gids,
            rate_params, fv, spec)
    rows = s if spec.emit_raw else g
    return np.asarray(result)[:rows, :b], np.asarray(emit)[:rows, :b]


def execute(batch_values: np.ndarray, series_idx: np.ndarray,
            bucket_idx: np.ndarray, bucket_ts: np.ndarray,
            group_ids: np.ndarray, spec: PipelineSpec,
            rate_options: RateOptions | None = None,
            dtype=None, device=None,
            use_pallas: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Host entry: upload, run, download. Returns (result, emit_mask).

    Automatically takes the dense reshape path when the batch is
    regular-cadence (see :func:`detect_dense`), and within it the
    fused Pallas kernel (:mod:`opentsdb_tpu.ops.pallas_fused`) when the
    data is complete and the op combination is MXU-reducible."""
    if dtype is None:
        dtype = pipeline_dtype()
    ro = rate_options or RateOptions()
    put = partial(jax.device_put, device=device)
    rate_params = (as_operand(ro.counter_max, dtype),
                   as_operand(ro.reset_value, dtype))
    fv = as_operand(spec.fill_value, dtype)
    k = detect_dense(spec.num_series, spec.num_buckets,
                     np.asarray(series_idx), np.asarray(bucket_idx),
                     spec.ds_function)
    if k is not None:
        values2d = np.asarray(batch_values).reshape(spec.num_series, -1)
        return _run_dense_or_pallas(values2d, bucket_ts, group_ids,
                                    spec, k, ro, rate_params, fv,
                                    dtype, device, use_pallas)
    values = put(as_operand(batch_values, dtype))
    result, emit = run_pipeline(
        values,
        as_operand(series_idx, np.int32),
        as_operand(bucket_idx, np.int32),
        as_operand(device_bucket_ts(bucket_ts)),
        as_operand(group_ids, np.int32),
        rate_params, fv, spec)
    return np.asarray(result), np.asarray(emit)
