"""The fused query pipeline: downsample -> rate -> interpolate ->
aggregate -> group-by as ONE jit-compiled array program.

This inverts the reference's architecture (SURVEY.md §7): OpenTSDB pulls
one datapoint at a time through an iterator chain interleaved with
serialization (``SpanGroup.iterator`` -> ``AggregationIterator`` ->
``Downsampler`` -> ``RateSpan``, ref AggregationIterator.java:253-280);
here the whole working set is materialized as a flat point batch and the
entire chain compiles to a handful of fused XLA ops over a
``[series, bucket]`` grid. The per-query shapes (S, B, G, N) are traced
once per shape bucket and cached by XLA.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops import aggregators as aggs_mod
from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.ops import groupby as gb_mod
from opentsdb_tpu.ops.rate import RateOptions, _rate_kernel


@dataclass(frozen=True)
class PipelineSpec:
    """Static (trace-time) configuration of one sub-query's compute."""
    num_series: int
    num_buckets: int
    num_groups: int
    ds_function: str          # downsample function ('sum', 'avg', ...)
    agg_name: str             # group aggregator name ('sum', 'p99', ...)
    fill_policy: ds_mod.FillPolicy = ds_mod.FillPolicy.NONE
    fill_value: float = float("nan")
    rate: bool = False
    rate_counter: bool = False
    rate_drop_resets: bool = False
    emit_raw: bool = False    # agg 'none': emit per-series, skip group stage


@partial(jax.jit, static_argnames=("spec",))
def run_pipeline(values, series_idx, bucket_idx, bucket_ts, group_ids,
                 rate_params, fill_value, spec: PipelineSpec):
    """values[N] f32/f64, series_idx[N] i32, bucket_idx[N] i32,
    bucket_ts[B] i64, group_ids[S] i32, rate_params = (counter_max,
    reset_value) -> (result[G,B] or [S,B], emit_mask same shape).

    NaN in the result means "no value" (fill policy NONE/NULL);
    ``emit_mask`` marks buckets that exist in the output per the
    reference's emission rules (union of contributing series' buckets
    for NONE, everything otherwise).
    """
    s, b, g = spec.num_series, spec.num_buckets, spec.num_groups

    # 1. downsample: flat points -> [S,B] grid with NaN holes
    grid, cnt = ds_mod.bucketize(values, series_idx, bucket_idx, s, b,
                                 spec.ds_function)
    has_data = cnt > 0

    # 2. downsample fill policy (ZERO/SCALAR substitute before rate,
    #    matching FillingDownsampler feeding RateSpan)
    if spec.fill_policy == ds_mod.FillPolicy.ZERO:
        grid = jnp.where(jnp.isnan(grid), 0.0, grid)
        has_data = jnp.ones_like(has_data)
    elif spec.fill_policy == ds_mod.FillPolicy.SCALAR:
        grid = jnp.where(jnp.isnan(grid), fill_value, grid)
        has_data = jnp.ones_like(has_data)

    # 3. rate conversion per series (ref: Downsampler -> RateSpan order)
    if spec.rate:
        counter_max, reset_value = rate_params
        grid = _rate_kernel(grid, bucket_ts, spec.rate_counter,
                            counter_max, reset_value,
                            spec.rate_drop_resets)
        has_data = has_data & ~jnp.isnan(grid)

    if spec.emit_raw:
        return grid, has_data

    # 4.+5. interpolate at merge + aggregate over series within groups
    agg = aggs_mod.get(spec.agg_name)
    result = gb_mod.group_aggregate(grid, bucket_ts, group_ids, g, agg)

    # emission: fill NONE emits the union of the group's series' buckets
    # (plain Downsampler skips empty buckets); any other policy emits
    # every bucket (FillingDownsampler semantics)
    if spec.fill_policy == ds_mod.FillPolicy.NONE:
        emit = jax.ops.segment_sum(has_data.astype(jnp.int32), group_ids,
                                   num_segments=g) > 0
    else:
        emit = jnp.ones((g, b), dtype=bool)
    return result, emit


def execute(batch_values: np.ndarray, series_idx: np.ndarray,
            bucket_idx: np.ndarray, bucket_ts: np.ndarray,
            group_ids: np.ndarray, spec: PipelineSpec,
            rate_options: RateOptions | None = None,
            dtype=None, device=None) -> tuple[np.ndarray, np.ndarray]:
    """Host entry: upload, run, download. Returns (result, emit_mask)."""
    if dtype is None:
        dtype = jnp.float64 if jax.config.read("jax_enable_x64") \
            else jnp.float32
    ro = rate_options or RateOptions()
    put = partial(jax.device_put, device=device)
    values = put(jnp.asarray(batch_values, dtype=dtype))
    rate_params = (jnp.asarray(ro.counter_max, dtype=dtype),
                   jnp.asarray(ro.reset_value, dtype=dtype))
    result, emit = run_pipeline(
        values,
        put(jnp.asarray(series_idx, dtype=jnp.int32)),
        put(jnp.asarray(bucket_idx, dtype=jnp.int32)),
        put(jnp.asarray(bucket_ts)),
        put(jnp.asarray(group_ids, dtype=jnp.int32)),
        rate_params,
        jnp.asarray(spec.fill_value, dtype=dtype),
        spec)
    return np.asarray(result), np.asarray(emit)
