"""Rate / counter conversion (ref: ``src/core/RateSpan.java:21``,
``RateOptions.java:27``).

First difference dv/dt (per second) between a series' successive
*present* points, vectorized over the ``[series, bucket]`` grid: each
present cell looks up the previous present cell of its own series via a
cumulative-max index scan, so holes (NaN) are skipped exactly like the
reference's iterator skips to the prior datapoint.

Counter semantics (RateOptions):
- ``counter``: negative delta means rollover; corrected rate =
  (counter_max - prev + cur) / dt (RateSpan.java:150-170)
- ``drop_resets``: drop the rolled-over point instead
- ``reset_value``: corrected rates above this emit 0

The first present point of every series has no predecessor and produces
no rate (masked to NaN).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from opentsdb_tpu.ops.interp import carry_prev, shift_prev


@dataclass(frozen=True)
class RateOptions:
    """(ref: RateOptions.java:27-52)"""
    counter: bool = False
    counter_max: float = float(2**64 - 1)  # Long.MAX in ref; u64 here
    reset_value: float = 0.0
    drop_resets: bool = False

    @classmethod
    def parse(cls, spec: str | None) -> "RateOptions":
        """Parse the query-string form ``rate{counter[,max[,reset]]}``
        (ref: QueryRpc parseRateOptions)."""
        if not spec or spec == "rate":
            return cls()
        if not (spec.startswith("rate{") and spec.endswith("}")):
            raise ValueError(f"invalid rate options: {spec}")
        parts = spec[5:-1].split(",")
        counter = parts[0] in ("counter", "dropcounter")
        drop = parts[0] == "dropcounter"
        counter_max = float(2**64 - 1)
        reset = 0.0
        if len(parts) >= 2 and parts[1]:
            # tsdlint: allow[kernel-hygiene] rate-SPEC string parse
            # (once per query), not an array element pull
            counter_max = float(parts[1])
        if len(parts) >= 3 and parts[2]:
            # tsdlint: allow[kernel-hygiene] spec parse, see above
            reset = float(parts[2])
        return cls(counter=counter, counter_max=counter_max,
                   reset_value=reset, drop_resets=drop)

    def to_json(self) -> dict:
        return {"counter": self.counter, "counterMax": self.counter_max,
                "resetValue": self.reset_value,
                "dropResets": self.drop_resets}


@partial(jax.jit, static_argnames=("counter", "drop_resets"))
def _rate_kernel(grid, bucket_ts, counter: bool, counter_max,
                 reset_value, drop_resets: bool):
    mask = ~jnp.isnan(grid)
    # previous present cell, *strictly* before each cell: an inclusive
    # 'nearest present' associative scan shifted one column right (no
    # gathers — see interp.carry_prev on the B>=14 select-chain cliff)
    t_cur = bucket_ts[None, :]
    ts_row = jnp.broadcast_to(t_cur, grid.shape)
    gz = jnp.where(mask, grid, 0.0)
    pv, pt, pp = carry_prev((gz, ts_row), mask)
    v_prev, t_prev, has_prev = shift_prev(
        (pv, pt, pp), (0.0, 0, False))
    # difference timestamps BEFORE any float cast: bucket_ts arrives as
    # small relative offsets (device_bucket_ts) so integer diffs are
    # exact even on TPU where int64/float64 are unavailable
    dt_sec = (t_cur - t_prev).astype(grid.dtype) / 1000.0
    dt_sec = jnp.where(dt_sec > 0, dt_sec, 1.0)
    delta = grid - v_prev
    rate = delta / dt_sec
    if counter:
        rolled = delta < 0
        corrected = (counter_max - v_prev + grid) / dt_sec
        rate = jnp.where(rolled, corrected, rate)
        if drop_resets:
            rate = jnp.where(rolled, jnp.nan, rate)
        # reset_value: corrected rates above threshold emit 0
        rate = jnp.where(
            (reset_value > 0) & (rate > reset_value), 0.0, rate)
    return jnp.where(mask & has_prev, rate, jnp.nan)


def compute_rate(grid, bucket_ts, options: RateOptions):
    """Apply rate conversion to a [S,B] grid. Returns a same-shape grid;
    the first present point of each series becomes NaN (dropped)."""
    return _rate_kernel(grid, bucket_ts, options.counter,
                        jnp.asarray(options.counter_max, grid.dtype),
                        jnp.asarray(options.reset_value, grid.dtype),
                        options.drop_resets)
