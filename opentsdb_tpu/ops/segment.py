"""Segmented-reduction primitives over flat point batches.

The TPU build's replacement for the reference's pull-based iterator
pipeline: a flat batch of points ``(values[N], seg_ids[N])`` is reduced
into ``num_segments`` slots in one XLA scatter/segment op. Segment ids
are ``series_idx * num_buckets + bucket_idx``, so one call downsamples
every series of a query simultaneously (ref: the per-point inner loop in
``src/core/Downsampler.java:295`` ValuesInInterval).

Points arrive sorted by (series, time) from the column store, so
``indices_are_sorted=True`` lets XLA lower to a faster segmented scan.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def seg_sum(values, seg_ids, num_segments, sorted_ids=True):
    return jax.ops.segment_sum(values, seg_ids, num_segments,
                               indices_are_sorted=sorted_ids)


def seg_count(values, seg_ids, num_segments, sorted_ids=True):
    return jax.ops.segment_sum(jnp.ones_like(values), seg_ids, num_segments,
                               indices_are_sorted=sorted_ids)


def seg_min(values, seg_ids, num_segments, sorted_ids=True):
    return jax.ops.segment_min(values, seg_ids, num_segments,
                               indices_are_sorted=sorted_ids)


def seg_max(values, seg_ids, num_segments, sorted_ids=True):
    return jax.ops.segment_max(values, seg_ids, num_segments,
                               indices_are_sorted=sorted_ids)


def seg_prod(values, seg_ids, num_segments, sorted_ids=True):
    return jax.ops.segment_prod(values, seg_ids, num_segments,
                                indices_are_sorted=sorted_ids)


def seg_sumsq(values, seg_ids, num_segments, sorted_ids=True):
    return jax.ops.segment_sum(values * values, seg_ids, num_segments,
                               indices_are_sorted=sorted_ids)


def seg_first_last(values, seg_ids, num_segments, valid=None,
                   sorted_ids=True):
    """(first, last) value per segment, relying on within-segment time
    order of the batch (the store materializes time-sorted points).
    ``valid`` masks out NaN points (they are skipped, not selected)."""
    n = values.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    big = jnp.iinfo(jnp.int32).max
    if valid is not None:
        first_cand = jnp.where(valid, pos, big)
        last_cand = jnp.where(valid, pos, -1)
    else:
        first_cand = pos
        last_cand = pos
    first_pos = jax.ops.segment_min(first_cand, seg_ids, num_segments,
                                    indices_are_sorted=sorted_ids)
    last_pos = jax.ops.segment_max(last_cand, seg_ids, num_segments,
                                   indices_are_sorted=sorted_ids)
    has_any = (first_pos != big) & (last_pos >= 0)
    safe_first = jnp.where(has_any, jnp.clip(first_pos, 0,
                                             max(n - 1, 0)), 0)
    safe_last = jnp.where(has_any, jnp.clip(last_pos, 0, max(n - 1, 0)),
                          0)
    if n == 0:
        z = jnp.zeros((num_segments,), dtype=values.dtype)
        return z, z
    return values[safe_first], values[safe_last]


def segment_sort_ranks(values, seg_ids, num_segments):
    """Sort ``values`` within segments, returning (sorted_values,
    sorted_seg_ids, segment_starts, segment_valid_counts).

    Lowered as one ``lax.sort`` with (seg_id, value) lexicographic keys —
    the TPU-friendly formulation of per-bucket percentile/median
    downsampling (no ragged loops; one big bitonic sort on the MXU-adjacent
    sort unit). NaN values sort to the end of their segment and are
    excluded from the valid counts, so rank selection skips them.
    """
    # lax.sort's total order puts NaN after every number, so NaN points
    # sort to the end of their segment with no extra key
    sorted_ids, sorted_vals = jax.lax.sort((seg_ids, values), num_keys=2)
    valid = (~jnp.isnan(values)).astype(seg_ids.dtype)
    counts = jax.ops.segment_sum(valid, seg_ids, num_segments)
    totals = jax.ops.segment_sum(jnp.ones_like(seg_ids), seg_ids,
                                 num_segments)
    starts = jnp.cumsum(totals) - totals
    return sorted_vals, sorted_ids, starts, counts


def select_rank(sorted_vals, starts, counts, h):
    """Gather per-segment order statistics at (1-based, fractional) rank
    positions ``h[num_segments]`` with linear interpolation between
    neighbors — the vectorized core of every percentile estimation type.
    Segments with count 0 return NaN.
    """
    n = sorted_vals.shape[0]
    h_floor = jnp.floor(h)
    frac = h - h_floor
    lo_idx = jnp.clip(h_floor.astype(jnp.int32) - 1, 0, None)
    hi_idx = jnp.clip(lo_idx + 1, None, jnp.maximum(counts - 1, 0))
    lo_idx = jnp.clip(lo_idx, 0, jnp.maximum(counts - 1, 0))
    lo = sorted_vals[jnp.clip(starts + lo_idx, 0, max(n - 1, 0))]
    hi = sorted_vals[jnp.clip(starts + hi_idx, 0, max(n - 1, 0))]
    out = lo + frac * (hi - lo)
    return jnp.where(counts > 0, out, jnp.nan)
