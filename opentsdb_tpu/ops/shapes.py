"""Geometric shape bucketing: a bounded XLA compile space.

Every jitted pipeline entry specializes on (S, B, G, N); production
traffic varies all four continuously, so without bucketing each new
series count or window length pays a multi-second XLA compile
mid-query (r02's BENCH_E2E max_ms hit 16 s against p50s of hundreds of
ms). Rounding each dimension UP to the next value of the form
``{1, 1.25, 1.5, 1.75} x 2^k`` caps the distinct programs per
dimension at ~4 log2(range) (~80 for a 1M span) while wasting at most
25% padding — the same trick as bucketed sequence lengths in serving
stacks.

Padded series rows are NaN (no contribution) and belong to a dummy
trailing group; padded buckets extend bucket_ts monotonically and trim
off the result. Callers slice back to the true (G, B) so bucketing is
invisible to everything above.
"""

from __future__ import annotations

import numpy as np

_FRACTIONS = (4, 5, 6, 7)  # x/4: 1, 1.25, 1.5, 1.75


def shape_bucket(n: int, min_size: int = 8) -> int:
    """Smallest value >= n of the form {4,5,6,7} * 2^k (k >= 0),
    floored at ``min_size``."""
    n = max(int(n), min_size)
    if n <= min_size:
        return min_size
    k = max(int(n - 1).bit_length() - 3, 0)
    while True:
        for f in _FRACTIONS:
            cand = f << k
            if cand >= n:
                return cand
        k += 1


def pad_bucket_ts(bucket_ts: np.ndarray, target: int) -> np.ndarray:
    """Monotonic tail extension (same contract as the sharded
    pipeline's halo padding)."""
    bts = np.asarray(bucket_ts)
    need = target - len(bts)
    if need <= 0:
        return bts
    step = int(bts[-1] - bts[-2]) if len(bts) > 1 else 1000
    extra = bts[-1] + step * np.arange(1, need + 1, dtype=bts.dtype)
    return np.concatenate([bts, extra])


def pad_2d_host(arr: np.ndarray, s_pad: int, b_pad: int,
                fill) -> np.ndarray:
    """Host-side [S, B] -> [s_pad, b_pad] padding. The engine pads
    grids ONCE when they are built/cached so warm queries touch no
    per-query pad at all (an eager device pad per query costs a full
    RPC round trip on tunneled backends)."""
    s, b = arr.shape
    if (s_pad, b_pad) == (s, b):
        return arr
    out = np.full((s_pad, b_pad), fill, dtype=arr.dtype)
    out[:s, :b] = arr
    return out


def pad_group_ids(group_ids: np.ndarray, s_pad: int,
                  num_groups: int) -> np.ndarray:
    """Group ids padded with the dummy trailing group."""
    gids = np.full(s_pad, num_groups, dtype=np.int32)
    gids[:len(group_ids)] = group_ids
    return gids
