"""Columnar fold of raw point columns into per-cell quantile sketches.

The sketch twin of the rollup / stream-fold scatter kernels: one
vectorized pass turns flat ``(cell, value)`` columns into sparse
per-(cell, sign, bucket-index) counts — the entire fold is a
``np.unique`` over an ``[N, 3]`` key matrix plus per-cell reduceats —
and each cell's slice materializes directly as a canonical
:class:`~opentsdb_tpu.sketch.ddsketch.DDSketch`. Demotion uses it to
preserve percentiles past the demote boundary; the query path uses it
to fold the live raw tail; streaming CQs use it for their sketch
channel.

Host-side numpy by design (same placement as ``stream_fold``): the
fold runs in the lifecycle sweeper / fold workers / query tails, not
on the device pipeline.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.sketch.ddsketch import (DDSketch, MIN_INDEXABLE,
                                          _merge_store)

# key-matrix "kind" column: ascending value order within a cell
_KIND_NEG, _KIND_ZERO, _KIND_POS = 0, 1, 2


def fold_cells(ts_ms: np.ndarray, values: np.ndarray, cell_ms: int,
               alpha: float, max_buckets: int | None = None,
               faults=None) -> dict[int, DDSketch]:
    """Fold flat point columns into one sketch per time cell.

    ``cell_ts = ts - ts % cell_ms`` (the tier bucket rule). NaNs are
    skipped. Returns ``{cell_ts: DDSketch}`` — each sketch is in
    canonical form, so folding a cell's points here is bit-equal to
    ``DDSketch.add_values`` over the same points. ``faults`` is the
    owning TSDB's injector (site ``sketch.fold``), None in kernels
    detached from a TSDB.
    """
    if faults is not None:
        faults.check("sketch.fold")
    ts = np.asarray(ts_ms, dtype=np.int64)
    v = np.asarray(values, dtype=np.float64)
    keep = np.isfinite(v)
    if not keep.all():
        ts, v = ts[keep], v[keep]
    if not len(v):
        return {}
    cells = ts - ts % cell_ms

    proto = DDSketch(alpha)
    kind = np.full(len(v), _KIND_ZERO, dtype=np.int64)
    key = np.zeros(len(v), dtype=np.int64)
    pos = v > MIN_INDEXABLE
    neg = v < -MIN_INDEXABLE
    if pos.any():
        kind[pos] = _KIND_POS
        key[pos] = proto._keys(v[pos])
    if neg.any():
        kind[neg] = _KIND_NEG
        # negative store sorts ascending by index; ascending VALUE is
        # descending index, so flip the sort key to keep one lexsort
        key[neg] = -proto._keys(-v[neg])

    mat = np.stack([cells, kind, key], axis=1)
    rows, inv, counts = np.unique(mat, axis=0, return_inverse=True,
                                  return_counts=True)
    order = np.argsort(cells, kind="stable")
    out: dict[int, DDSketch] = {}
    cell_col = rows[:, 0]
    starts = np.nonzero(np.concatenate(
        [[True], cell_col[1:] != cell_col[:-1]]))[0]
    bounds = np.append(starts, len(cell_col))
    # per-cell exact extrema from the value columns
    v_sorted_cells = cells[order]
    v_sorted = v[order]
    c_starts = np.nonzero(np.concatenate(
        [[True], v_sorted_cells[1:] != v_sorted_cells[:-1]]))[0]
    cell_min = np.minimum.reduceat(v_sorted, c_starts)
    cell_max = np.maximum.reduceat(v_sorted, c_starts)
    cell_ids = v_sorted_cells[c_starts]
    extrema = {int(c): (float(lo), float(hi)) for c, lo, hi
               in zip(cell_ids, cell_min, cell_max)}

    # tsdlint: allow[kernel-hygiene] per-CELL materialization (trip
    # count = distinct time cells, bounded by span/cell_ms, never by
    # point count); the per-point fold above is one np.unique pass
    for si in range(len(starts)):
        lo, hi = bounds[si], bounds[si + 1]
        # tsdlint: allow[kernel-hygiene] one scalar probe per cell
        cell = int(cell_col[lo])
        sk = DDSketch(alpha)
        r = rows[lo:hi]
        c = counts[lo:hi].astype(np.float64)
        negm = r[:, 1] == _KIND_NEG
        zm = r[:, 1] == _KIND_ZERO
        posm = r[:, 1] == _KIND_POS
        if negm.any():
            # un-flip the sort key; re-sort ascending by true index
            nidx = (-r[negm, 2]).astype(np.int32)
            o = np.argsort(nidx)
            sk.neg_idx, sk.neg_cnt = nidx[o], c[negm][o]
        if zm.any():
            sk.zero_count = float(c[zm].sum())
        if posm.any():
            sk.pos_idx = r[posm, 2].astype(np.int32)
            sk.pos_cnt = c[posm]
        sk.count = float(c.sum())
        sk.min, sk.max = extrema[cell]
        if max_buckets:
            sk.collapse(max_buckets)
        out[cell] = sk
    return out


def fold_series_cells(series_idx: np.ndarray, ts_ms: np.ndarray,
                      values: np.ndarray, cell_ms: int, alpha: float,
                      max_buckets: int | None = None, faults=None
                      ) -> dict[tuple[int, int], DDSketch]:
    """Per-(series, cell) fold of a flat materialized batch: offsets
    each series into a disjoint cell namespace so ONE ``fold_cells``
    pass covers every series, then splits the keys back out. Used by
    demotion, where a batch holds all demoting series of a metric."""
    ts = np.asarray(ts_ms, dtype=np.int64)
    sidx = np.asarray(series_idx, dtype=np.int64)
    if not len(ts):
        return {}
    # cells are bucket-aligned and non-negative in practice; offset by
    # series into disjoint ranges wide enough for the batch's span
    base = int(ts.min()) - int(ts.min()) % cell_ms
    span = (int(ts.max()) - base) // cell_ms + 1
    keyed = (ts - ts % cell_ms - base) // cell_ms + sidx * span
    folded = fold_cells(keyed, values, 1, alpha, max_buckets,
                        faults=faults)
    return {(int(k // span), base + int(k % span) * cell_ms): sk
            for k, sk in folded.items()}


def merge_sorted_counts(idx_a, cnt_a, idx_b, cnt_b):
    """Re-export of the canonical store merge for kernel callers."""
    return _merge_store(idx_a, cnt_a, idx_b, cnt_b)
