"""Batched fold / window-combine kernels for the streaming engine v2.

One shared partial array (sum/count/min/max per (series, bucket) cell,
:mod:`opentsdb_tpu.streaming.plan`) is maintained by ONE vectorized
scatter fold per ingest batch and then serves every continuous query
attached to it — the multi-query plan-sharing core: fold cost is per
*partial array*, not per standing query, so N same-metric dashboards
cost one fold.

The window combines layer on the same decomposition rule the rollup
tiers use (``rollup/job.py``: sums of sums, counts of counts, mins of
mins, maxs of maxs; ``avg`` derives as sum/count at read time):

- :func:`combine_stride` — a view whose downsample interval is a
  multiple of the shared base interval derives its buckets by
  combining ``stride`` contiguous base buckets (downsample-divisible
  plan sharing).
- :func:`combine_sliding` — sliding windows: each output bucket
  aggregates the ``k`` trailing buckets ending at it (window size =
  k x interval, slide = interval). Windowed sums use an explicit
  window view (not cumsum differences) so summation order matches a
  direct per-window fold bit for bit.
- :func:`combine_hopping` — hopping windows (slide > interval): the
  trailing-``k`` combine of :func:`combine_sliding` subsampled to
  the slide-aligned output columns, so a hopping bucket is bit-equal
  to the sliding bucket at the same edge.
- :func:`session_grid` — session-gap windows: consecutive non-empty
  buckets whose edge distance is <= ``gap_ms`` merge into one
  session; the session aggregate lands on the session's FIRST bucket
  edge, other buckets are empty. The combine runs as ONE flat
  reduceat over every (row, bucket) cell (:func:`session_grid_flat`)
  so per-tag session partials — where rows explode to user
  cardinality — close sessions in one pass, not S python loops.

All kernels are host-side numpy by design: they run off the ingest
path on the shared fold workers (or in a dashboard-sized serve tail),
matching the placement idiom of the v1 incremental plans — the
device pipeline stays reserved for the batch engine's large scans.
"""

from __future__ import annotations

import numpy as np
from numpy.lib.stride_tricks import sliding_window_view

STATS = ("sum", "count", "min", "max")


def scatter_fold(sums: np.ndarray, cnts: np.ndarray, mins: np.ndarray,
                 maxs: np.ndarray, slots: np.ndarray, cols: np.ndarray,
                 vals: np.ndarray) -> None:
    """Fold one batch of points into the shared partial ring IN
    PLACE: one unbuffered scatter per stat channel. ``slots`` are
    member row indices, ``cols`` ring columns, ``vals`` the values —
    all filtered to live buckets by the caller."""
    np.add.at(sums, (slots, cols), vals)
    np.add.at(cnts, (slots, cols), 1.0)
    np.minimum.at(mins, (slots, cols), vals)
    np.maximum.at(maxs, (slots, cols), vals)


def combine_stride(sums: np.ndarray, cnts: np.ndarray,
                   mins: np.ndarray, maxs: np.ndarray, stride: int):
    """[S, B*stride] base-bucket channels -> [S, B] view-bucket
    channels by combining each run of ``stride`` contiguous base
    buckets (sum/sum/min/max — exact for the decomposable stats)."""
    if stride <= 1:
        return sums, cnts, mins, maxs
    s, n = sums.shape
    b = n // stride

    def rs(a):
        return a.reshape(s, b, stride)

    return (rs(sums).sum(axis=2), rs(cnts).sum(axis=2),
            rs(mins).min(axis=2), rs(maxs).max(axis=2))


def combine_sliding(sums: np.ndarray, cnts: np.ndarray,
                    mins: np.ndarray, maxs: np.ndarray, k: int):
    """Trailing-window combine: output bucket ``j`` aggregates input
    buckets ``max(0, j-k+1) .. j`` (leading outputs see a clipped
    window). Identity channels pad with 0 / +-inf so a clipped window
    equals a direct fold over its available buckets."""
    if k <= 1:
        return sums, cnts, mins, maxs
    s = sums.shape[0]

    def trail(a, fill, reduce):
        pad = np.concatenate(
            [np.full((s, k - 1), fill, dtype=a.dtype), a], axis=1)
        return reduce(sliding_window_view(pad, k, axis=1), -1)

    return (trail(sums, 0.0, np.sum), trail(cnts, 0.0, np.sum),
            trail(mins, np.inf, np.min), trail(maxs, -np.inf, np.max))


def combine_hopping(sums: np.ndarray, cnts: np.ndarray,
                    mins: np.ndarray, maxs: np.ndarray, k: int,
                    sel: np.ndarray):
    """Hopping-window combine: output bucket ``sel[j]`` aggregates
    the ``k`` trailing input buckets ending at it — the trailing
    combine of :func:`combine_sliding` subsampled to the
    slide-aligned columns ``sel``, so a hopping bucket is bit-equal
    to the sliding bucket at the same edge (slide == interval is
    exactly sliding; the caller enforces slide > interval)."""
    s, c, mn, mx = combine_sliding(sums, cnts, mins, maxs, k)
    return s[:, sel], c[:, sel], mn[:, sel], mx[:, sel]


def session_grid_flat(sums: np.ndarray, cnts: np.ndarray,
                      mins: np.ndarray, maxs: np.ndarray,
                      edges: np.ndarray, gap_ms: int):
    """Session-gap combine over EVERY row in one flat pass: the
    non-empty (row, bucket) cells enumerate in row-major order, a
    session break falls on every row change and every within-row
    edge gap > ``gap_ms``, and one ``reduceat`` per stat channel
    folds each segment onto its first bucket. Element order within a
    segment matches the per-row walk exactly, so results are
    bit-identical to reducing each row independently — but a
    million-session partial closes in one kernel call."""
    out_s = np.zeros_like(sums)
    out_c = np.zeros_like(cnts)
    out_min = np.full_like(mins, np.inf)
    out_max = np.full_like(maxs, -np.inf)
    rows, cols = np.nonzero(cnts > 0)
    if not len(rows):
        return out_s, out_c, out_min, out_max
    e = edges[cols]
    brk = np.empty(len(rows), dtype=bool)
    brk[0] = True
    # a new session starts on a new row or where the edge gap
    # exceeds gap_ms (the cross-row diff is masked by the row break)
    brk[1:] = (rows[1:] != rows[:-1]) | ((e[1:] - e[:-1]) > gap_ms)
    starts = np.nonzero(brk)[0]
    r0, c0 = rows[starts], cols[starts]
    out_s[r0, c0] = np.add.reduceat(sums[rows, cols], starts)
    out_c[r0, c0] = np.add.reduceat(cnts[rows, cols], starts)
    out_min[r0, c0] = np.minimum.reduceat(mins[rows, cols], starts)
    out_max[r0, c0] = np.maximum.reduceat(maxs[rows, cols], starts)
    return out_s, out_c, out_min, out_max


def session_grid(sums: np.ndarray, cnts: np.ndarray, mins: np.ndarray,
                 maxs: np.ndarray, edges: np.ndarray, gap_ms: int):
    """Session-gap combine: per series, runs of non-empty buckets
    whose consecutive edge distance is <= ``gap_ms`` merge into one
    session whose aggregate lands on the run's FIRST bucket; every
    other bucket comes back empty. Sessions are delimited within the
    supplied range (a session truncated by the range edge aggregates
    its visible part). Thin alias of :func:`session_grid_flat` —
    kept as the view-combine entry point."""
    return session_grid_flat(sums, cnts, mins, maxs, edges, gap_ms)
