"""Batched fold / window-combine kernels for the streaming engine v2.

One shared partial array (sum/count/min/max per (series, bucket) cell,
:mod:`opentsdb_tpu.streaming.plan`) is maintained by ONE vectorized
scatter fold per ingest batch and then serves every continuous query
attached to it — the multi-query plan-sharing core: fold cost is per
*partial array*, not per standing query, so N same-metric dashboards
cost one fold.

The window combines layer on the same decomposition rule the rollup
tiers use (``rollup/job.py``: sums of sums, counts of counts, mins of
mins, maxs of maxs; ``avg`` derives as sum/count at read time):

- :func:`combine_stride` — a view whose downsample interval is a
  multiple of the shared base interval derives its buckets by
  combining ``stride`` contiguous base buckets (downsample-divisible
  plan sharing).
- :func:`combine_sliding` — sliding windows: each output bucket
  aggregates the ``k`` trailing buckets ending at it (window size =
  k x interval, slide = interval). Windowed sums use an explicit
  window view (not cumsum differences) so summation order matches a
  direct per-window fold bit for bit.
- :func:`session_grid` — session-gap windows: consecutive non-empty
  buckets whose edge distance is <= ``gap_ms`` merge into one
  session; the session aggregate lands on the session's FIRST bucket
  edge, other buckets are empty.

All kernels are host-side numpy by design: they run off the ingest
path on the shared fold workers (or in a dashboard-sized serve tail),
matching the placement idiom of the v1 incremental plans — the
device pipeline stays reserved for the batch engine's large scans.
"""

from __future__ import annotations

import numpy as np
from numpy.lib.stride_tricks import sliding_window_view

STATS = ("sum", "count", "min", "max")


def scatter_fold(sums: np.ndarray, cnts: np.ndarray, mins: np.ndarray,
                 maxs: np.ndarray, slots: np.ndarray, cols: np.ndarray,
                 vals: np.ndarray) -> None:
    """Fold one batch of points into the shared partial ring IN
    PLACE: one unbuffered scatter per stat channel. ``slots`` are
    member row indices, ``cols`` ring columns, ``vals`` the values —
    all filtered to live buckets by the caller."""
    np.add.at(sums, (slots, cols), vals)
    np.add.at(cnts, (slots, cols), 1.0)
    np.minimum.at(mins, (slots, cols), vals)
    np.maximum.at(maxs, (slots, cols), vals)


def combine_stride(sums: np.ndarray, cnts: np.ndarray,
                   mins: np.ndarray, maxs: np.ndarray, stride: int):
    """[S, B*stride] base-bucket channels -> [S, B] view-bucket
    channels by combining each run of ``stride`` contiguous base
    buckets (sum/sum/min/max — exact for the decomposable stats)."""
    if stride <= 1:
        return sums, cnts, mins, maxs
    s, n = sums.shape
    b = n // stride

    def rs(a):
        return a.reshape(s, b, stride)

    return (rs(sums).sum(axis=2), rs(cnts).sum(axis=2),
            rs(mins).min(axis=2), rs(maxs).max(axis=2))


def combine_sliding(sums: np.ndarray, cnts: np.ndarray,
                    mins: np.ndarray, maxs: np.ndarray, k: int):
    """Trailing-window combine: output bucket ``j`` aggregates input
    buckets ``max(0, j-k+1) .. j`` (leading outputs see a clipped
    window). Identity channels pad with 0 / +-inf so a clipped window
    equals a direct fold over its available buckets."""
    if k <= 1:
        return sums, cnts, mins, maxs
    s = sums.shape[0]

    def trail(a, fill, reduce):
        pad = np.concatenate(
            [np.full((s, k - 1), fill, dtype=a.dtype), a], axis=1)
        return reduce(sliding_window_view(pad, k, axis=1), -1)

    return (trail(sums, 0.0, np.sum), trail(cnts, 0.0, np.sum),
            trail(mins, np.inf, np.min), trail(maxs, -np.inf, np.max))


def session_grid(sums: np.ndarray, cnts: np.ndarray, mins: np.ndarray,
                 maxs: np.ndarray, edges: np.ndarray, gap_ms: int):
    """Session-gap combine: per series, runs of non-empty buckets
    whose consecutive edge distance is <= ``gap_ms`` merge into one
    session whose aggregate lands on the run's FIRST bucket; every
    other bucket comes back empty. Sessions are delimited within the
    supplied range (a session truncated by the range edge aggregates
    its visible part)."""
    out_s = np.zeros_like(sums)
    out_c = np.zeros_like(cnts)
    out_min = np.full_like(mins, np.inf)
    out_max = np.full_like(maxs, -np.inf)
    present = cnts > 0
    # tsdlint: allow[kernel-hygiene] per-SERIES orchestration (the
    # per-bucket combine inside is reduceat-vectorized); flattening
    # the session stitch across rows is the ROADMAP item-4
    # per-tag-session work, where S explodes to user cardinality
    for s in range(sums.shape[0]):
        idx = np.nonzero(present[s])[0]
        if not len(idx):
            continue
        # a new session starts where the edge gap exceeds gap_ms
        breaks = np.diff(edges[idx]) > gap_ms
        starts = np.concatenate([[0], np.nonzero(breaks)[0] + 1])
        first = idx[starts]
        out_s[s, first] = np.add.reduceat(sums[s, idx], starts)
        out_c[s, first] = np.add.reduceat(cnts[s, idx], starts)
        out_min[s, first] = np.minimum.reduceat(mins[s, idx], starts)
        out_max[s, first] = np.maximum.reduceat(maxs[s, idx], starts)
    return out_s, out_c, out_min, out_max
