"""Pixel-aware serve-path downsampling: M4 and MinMaxLTTB.

A dashboard chart is ``W`` pixels wide; shipping more than ~4 points
per pixel column per series is pure wire and serialization waste — the
browser rasterizes them onto the same column (tsdownsample, PAPERS.md;
M4: Jugel et al., VLDB 2014). These kernels reduce the engine's FINAL
per-group output — after downsample/fill/rate/interpolate/aggregate —
to the points a ``W``-px line rendering actually needs.

Both operators are point *selections*: they compute a boolean KEEP
mask over the engine's columnar ``[S, B]`` result/emit grids (the same
dense layout every bucketed kernel in :mod:`opentsdb_tpu.ops` speaks),
and the serve path applies ``emit &= keep`` ahead of result assembly.
No value or timestamp is ever modified — which is what makes M4
error-free for line rendering: every pixel column's min, max, first
and last real point survives, so the rasterized polyline is
pixel-identical to the full-resolution one.

- **M4** — per (series row, pixel column): keep the first and last
  emitted points and the (earliest) min and max among non-NaN emitted
  points. <= 4 points per occupied pixel. NaN points (fill-policy
  holes emitted as gaps) keep their first/last per pixel so gap
  boundaries survive.
- **MinMaxLTTB** — the tsdownsample composition: a vectorized MinMax
  preselection into ``ratio * n_out`` bins feeds classic
  Largest-Triangle-Three-Buckets, emitting <= ``n_out`` points per
  series (global first/last always kept). Smoother than M4 for
  single-line charts; not error-free, so M4 is the default.

Everything is one pass of column-segment reductions
(``np.minimum.reduceat`` over the pixel partition of the bucket axis —
the host twin of the tiled ``bucket_reduce`` idiom; these grids are
host-resident by the time result assembly runs, a few thousand columns
by a few hundred groups, so the reduction costs microseconds).
"""

from __future__ import annotations

import numpy as np

# supported pixel-reduction operators (query surface: `pixelFn` /
# `downsample=<N>px-<fn>`)
PIXEL_FNS = ("m4", "minmaxlttb")
DEFAULT_PIXEL_FN = "m4"
# strict-validation cap: wider than any real display, small enough
# that a typo'd pixel count cannot allocate absurd bin tables
MAX_PIXELS = 65536
# MinMaxLTTB preselection ratio (tsdownsample's default)
MINMAX_RATIO = 4


def assign_pixels(bucket_ts: np.ndarray, start_ms: int, end_ms: int,
                  pixels: int) -> np.ndarray:
    """Map output timestamps to pixel columns: ``pixels`` equal time
    bins over the query window ``[start_ms, end_ms]`` (the chart's
    x-axis). Returns int64[B], ascending because ``bucket_ts`` is.
    Timestamps outside the window (the aligned-down first bucket)
    clip into the edge columns."""
    span = max(int(end_ms) - int(start_ms), 1)
    idx = (bucket_ts.astype(np.int64) - int(start_ms)) * pixels // span
    return np.clip(idx, 0, pixels - 1)


def _pixel_starts(pixel_idx: np.ndarray, pixels: int, b: int
                  ) -> tuple[np.ndarray, np.ndarray]:
    """reduceat segment starts for the pixel partition + the mask of
    pixels that own at least one bucket column. reduceat of an EMPTY
    segment returns the next segment's first element — every consumer
    must invalidate unoccupied pixels.

    The table is TRIMMED to the last pixel owning data (it may be
    shorter than ``pixels``): pixels past the last data column — a
    query window ending after the data does — would get a segment
    start == ``b``, which reduceat rejects, and clipping such a start
    instead would steal the final column from the last real pixel's
    segment (the next start is that segment's END). Trimmed-away
    pixels are empty by construction, identical to being invalidated.
    Consumers size their per-pixel tables off ``len(starts)``, never
    the requested pixel count."""
    # tsdlint: allow[kernel-hygiene] one scalar probe per call (the
    # last data-owning pixel), not a per-element pull
    n_eff = min(pixels, int(pixel_idx[-1]) + 1)
    starts = np.searchsorted(pixel_idx, np.arange(n_eff))
    occupied = np.diff(starts, append=b) > 0
    return starts, occupied


def _minmax_cols(values2d: np.ndarray, emit2d: np.ndarray,
                 idx: np.ndarray, starts: np.ndarray,
                 occupied: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-segment earliest columns achieving the min and the max over
    emitted non-NaN values (±inf are legal extremes; tie -> earliest
    column, matching a first-wins scan). Sentinel ``b`` = no
    candidate. Shared by M4 and the MinMaxLTTB preselection — same
    semantics over different bin tables."""
    b = values2d.shape[1]
    col = np.arange(b, dtype=np.int64)[None, :]
    sent = b
    valued = emit2d & ~np.isnan(values2d)
    pmin = np.minimum.reduceat(
        np.where(valued, values2d, np.inf), starts, axis=1)
    pmax = np.maximum.reduceat(
        np.where(valued, values2d, -np.inf), starts, axis=1)
    is_min = valued & (values2d == pmin[:, idx])
    is_max = valued & (values2d == pmax[:, idx])
    min_col = np.minimum.reduceat(
        np.where(is_min, col, sent), starts, axis=1)
    max_col = np.minimum.reduceat(
        np.where(is_max, col, sent), starts, axis=1)
    min_col[:, ~occupied] = sent
    max_col[:, ~occupied] = sent
    return min_col, max_col


def _scatter_keep(keep: np.ndarray, cols: np.ndarray,
                  sentinel: int) -> None:
    """Set keep[row, cols[row, p]] for every valid (non-sentinel)
    selection in one scatter."""
    rows, _ = np.nonzero(cols != sentinel)
    keep[rows, cols[cols != sentinel]] = True


def m4_keep_mask(values2d: np.ndarray, emit2d: np.ndarray,
                 pixel_idx: np.ndarray, pixels: int) -> np.ndarray:
    """M4 selection mask over ``[S, B]`` grids.

    Per (row, pixel): the first and last emitted columns, and the
    earliest columns achieving the min and the max over emitted
    non-NaN values. Exactness contract (oracle-tested): for every row
    and pixel, the kept set CONTAINS that pixel's first/last emitted
    point and its min/max, and nothing outside the pixel's emitted
    points.
    """
    s, b = values2d.shape
    keep = np.zeros((s, b), dtype=bool)
    if s == 0 or b == 0 or pixels <= 0:
        return keep
    starts, occupied = _pixel_starts(pixel_idx, pixels, b)
    col = np.arange(b, dtype=np.int64)[None, :]
    sent = b  # "no candidate" sentinel, > any real column

    # first/last emitted column per pixel (NaN points included: gap
    # boundaries are part of the drawn line)
    first_col = np.minimum.reduceat(
        np.where(emit2d, col, sent), starts, axis=1)
    last_col = np.maximum.reduceat(
        np.where(emit2d, col, -1), starts, axis=1)
    min_col, max_col = _minmax_cols(values2d, emit2d, pixel_idx,
                                    starts, occupied)

    # pixels owning zero bucket columns carry reduceat garbage (the
    # next pixel's first element): invalidate before scattering
    first_col[:, ~occupied] = sent
    last_col[:, ~occupied] = -1

    _scatter_keep(keep, first_col, sent)
    _scatter_keep(keep, min_col, sent)
    _scatter_keep(keep, max_col, sent)
    _scatter_keep(keep, last_col, -1)
    return keep


def minmaxlttb_keep_mask(values2d: np.ndarray, emit2d: np.ndarray,
                         bucket_ts: np.ndarray, start_ms: int,
                         end_ms: int, pixels: int,
                         ratio: int = MINMAX_RATIO) -> np.ndarray:
    """MinMaxLTTB selection mask: MinMax preselection into
    ``ratio * pixels`` bins, then LTTB over the candidates down to
    <= ``pixels`` points per row (global first/last always kept).

    The LTTB stage walks the ``pixels - 2`` interior time bins once,
    vectorized across rows (each step is a [S, bin-width] argmax of
    triangle areas against the previously selected point and the next
    bin's candidate centroid — the classic formulation, tsdownsample
    §3). NaN points are never LTTB candidates; rows whose bin has no
    candidate select nothing there.
    """
    s, b = values2d.shape
    keep = np.zeros((s, b), dtype=bool)
    if s == 0 or b == 0 or pixels <= 0:
        return keep
    if b <= pixels:
        # already under budget: LTTB of n <= n_out is the identity
        return emit2d.copy()

    # --- global first/last emitted point per row: LTTB anchors
    first_g = np.where(emit2d.any(axis=1),
                       np.argmax(emit2d, axis=1), -1)
    last_g = np.where(emit2d.any(axis=1),
                      b - 1 - np.argmax(emit2d[:, ::-1], axis=1), -1)
    rows_ok = first_g >= 0
    keep[rows_ok, first_g[rows_ok]] = True
    keep[rows_ok, last_g[rows_ok]] = True
    if pixels <= 2:
        # a 1-2 point budget leaves no interior bins: the anchors ARE
        # the answer (emitting everything here would hand a 2px
        # sparkline the full-resolution response)
        return keep

    # --- stage 1: MinMax preselection (the m4 min/max machinery over
    # a finer bin table)
    pre_bins = min(max(ratio, 1) * pixels, b)
    pre_idx = assign_pixels(bucket_ts, start_ms, end_ms, pre_bins)
    starts, occupied = _pixel_starts(pre_idx, pre_bins, b)
    sent = b  # _minmax_cols' "no candidate" sentinel
    min_col, max_col = _minmax_cols(values2d, emit2d, pre_idx,
                                    starts, occupied)
    cand = np.zeros((s, b), dtype=bool)
    _scatter_keep(cand, min_col, sent)
    _scatter_keep(cand, max_col, sent)

    # --- stage 2: LTTB over the candidates, `pixels - 2` interior
    # bins between the window edges
    n_bins = pixels - 2
    bin_idx = assign_pixels(bucket_ts, start_ms, end_ms, n_bins)
    bstarts, boccupied = _pixel_starts(bin_idx, n_bins, b)
    bends = np.append(bstarts[1:], b)
    # x in float seconds relative to the window (well-conditioned for
    # the area arithmetic)
    x = (bucket_ts.astype(np.float64) - float(start_ms)) / 1e3
    # the anchors must not double as bin selections
    cand[rows_ok, first_g[rows_ok]] = False
    cand[rows_ok, last_g[rows_ok]] = False
    y = np.where(cand, values2d, np.nan)
    # per-bin candidate counts + centroids (the "next bucket average");
    # reduceat over bool saturates, so count over int
    ccount = np.add.reduceat(cand.astype(np.int64), bstarts, axis=1)
    cnt = np.maximum(ccount, 1)
    cx = np.add.reduceat(np.where(cand, x[None, :], 0.0),
                         bstarts, axis=1) / cnt
    cy = np.add.reduceat(np.where(cand, y, 0.0), bstarts, axis=1) / cnt
    has_cand = ccount > 0
    has_cand[:, ~boccupied] = False

    prev_x = np.where(rows_ok, x[np.maximum(first_g, 0)], 0.0)
    prev_y = np.where(rows_ok,
                      values2d[np.arange(s), np.maximum(first_g, 0)],
                      0.0)
    prev_y = np.where(np.isnan(prev_y), 0.0, prev_y)
    last_x = x[np.maximum(last_g, 0)]
    last_y = values2d[np.arange(s), np.maximum(last_g, 0)]
    last_y = np.where(np.isnan(last_y), 0.0, last_y)
    arange_s = np.arange(s)
    n_eff = len(bstarts)  # trailing data-less bins are trimmed away
    for k in range(n_eff):
        # tsdlint: allow[kernel-hygiene] O(pixel budget) LTTB bin
        # walk — bounded by the requested pixels (<= a few thousand),
        # never by point count; the candidate min/max preselect above
        # already reduced per-element work vectorially
        lo, hi = int(bstarts[k]), int(bends[k])
        if hi <= lo:
            continue
        rows = np.nonzero(has_cand[:, k])[0]
        if not len(rows):
            continue
        # next anchor: the following bin's centroid, else the last point
        nk = k + 1
        if nk < n_eff:
            nx = np.where(has_cand[rows, nk], cx[rows, nk],
                          last_x[rows])
            ny = np.where(has_cand[rows, nk], cy[rows, nk],
                          last_y[rows])
        else:
            nx, ny = last_x[rows], last_y[rows]
        xs = x[lo:hi][None, :]
        ys = y[rows, lo:hi]
        area = np.abs(
            (prev_x[rows, None] - nx[:, None]) * (ys - prev_y[rows, None])
            - (prev_x[rows, None] - xs) * (ny[:, None] - prev_y[rows, None]))
        area = np.where(np.isnan(ys), -1.0, area)
        pick = np.argmax(area, axis=1)
        sel = lo + pick
        keep[rows, sel] = True
        prev_x[rows] = x[sel]
        prev_y[rows] = values2d[rows, sel]
    return keep


def keep_mask(values2d: np.ndarray, emit2d: np.ndarray,
              bucket_ts: np.ndarray, start_ms: int, end_ms: int,
              pixels: int, fn: str = DEFAULT_PIXEL_FN
              ) -> np.ndarray | None:
    """The serve-path entry point: a keep mask for ``emit &= keep``,
    or None when the reduction is a guaranteed no-op (every point
    already fits the pixel budget for M4's 4-slots-per-pixel bound)."""
    if pixels <= 0:
        return None
    b = values2d.shape[1]
    if fn == "m4":
        if b <= pixels:
            # <= 1 bucket column per pixel: M4 keeps everything
            return None
        pixel_idx = assign_pixels(bucket_ts, start_ms, end_ms, pixels)
        return m4_keep_mask(values2d, emit2d, pixel_idx, pixels)
    if fn == "minmaxlttb":
        return minmaxlttb_keep_mask(values2d, emit2d, bucket_ts,
                                    start_ms, end_ms, pixels)
    raise ValueError(f"unknown pixel downsample fn {fn!r}")


def reduce_dps(dps: list, start_ms: int, end_ms: int, pixels: int,
               fn: str = DEFAULT_PIXEL_FN) -> list:
    """Pixel-reduce an already-assembled ``[(ts_ms, value), ...]`` row
    (percentile rows are emitted post-assembly, outside the ``[S, B]``
    grids the serve path reduces) by running the same kernels over a
    one-row grid. Returns the kept dps, original list when the budget
    keeps everything."""
    if pixels <= 0 or len(dps) <= 1:
        return dps
    ts = np.asarray([int(t) for t, _ in dps], dtype=np.int64)
    vals = np.asarray([float(v) for _, v in dps], dtype=np.float64)
    keep = keep_mask(vals[None, :], np.ones((1, len(dps)), dtype=bool),
                     ts, start_ms, end_ms, pixels, fn)
    if keep is None:
        return dps
    row = keep[0]
    return [dp for i, dp in enumerate(dps) if row[i]]


def naive_m4_reference(ts_ms: np.ndarray, vals: np.ndarray,
                       emit: np.ndarray, start_ms: int, end_ms: int,
                       pixels: int) -> set[int]:
    """Reference M4 for the oracle battery: a direct per-pixel scan of
    ONE series, returning the set of kept column indices. Deliberately
    written as the obvious O(B) loop — the vectorized kernel must
    reproduce it exactly."""
    span = max(int(end_ms) - int(start_ms), 1)
    by_pixel: dict[int, list[int]] = {}
    # tsdlint: allow[kernel-hygiene] DELIBERATELY scalar: this is the
    # naive oracle the viz test battery checks the vectorized kernel
    # against — rewriting it vectorized would test a kernel with
    # itself; never called on the serve path
    for i in range(len(ts_ms)):
        if not emit[i]:
            continue
        # tsdlint: allow[kernel-hygiene] naive oracle, see above
        p = (int(ts_ms[i]) - int(start_ms)) * pixels // span
        p = min(max(p, 0), pixels - 1)
        by_pixel.setdefault(p, []).append(i)
    kept: set[int] = set()
    for cols in by_pixel.values():
        kept.add(cols[0])
        kept.add(cols[-1])
        valued = [i for i in cols if not np.isnan(vals[i])]
        if valued:
            vmin = min(vals[i] for i in valued)
            vmax = max(vals[i] for i in valued)
            kept.add(next(i for i in valued if vals[i] == vmin))
            kept.add(next(i for i in valued if vals[i] == vmax))
    return kept
