"""Multi-host (DCN) deployment of the query mesh.

The reference scales beyond one JVM by running many stateless TSDs
behind a load balancer, all talking to one HBase cluster over TCP
(SURVEY.md §5.8). The TPU-native equivalent is multi-host JAX: one
process per host, ``jax.distributed.initialize`` for rendezvous, and a
single global ('series', 'time') mesh spanning every chip.

Axis placement is deliberate (the scaling-book recipe — put the
chatty collective on the fast interconnect):

- the **series** axis (salt analogue) lays out over each host's LOCAL
  chips: group-by reductions cross this axis with ``psum`` every query,
  and those collectives ride **ICI**;
- the **time** axis spans **hosts over DCN**: time blocks are almost
  independent — only rate/interpolation boundary halos (two [S]-sized
  vectors per block edge, ``sharded_pipeline._scan_boundary``) cross
  it, so the slow link carries the least traffic.

Write routing mirrors the reference's "any TSD accepts any write"
model: every host ingests into its local shard of the series axis;
:func:`series_home` tells a collector (or a fronting LB) which host
owns a series so ingest can avoid cross-host forwarding entirely —
the analogue of region-aware routing in asynchbase.
"""

from __future__ import annotations

import logging

import jax
import numpy as np
from jax.sharding import Mesh

LOG = logging.getLogger(__name__)

_initialized = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               initialization_timeout: int = 120) -> None:
    """Join the multi-host rendezvous (no-op when single-process).

    Mirrors ``jax.distributed.initialize``; on TPU pods the arguments
    are auto-detected from the environment, so ``initialize()`` with no
    arguments is the common call. A dead coordinator fails the boot
    within ``initialization_timeout`` seconds (same bounded-failure
    posture as :func:`initialize_from_config`).
    """
    global _initialized
    if num_processes is not None and num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address, num_processes, process_id,
        initialization_timeout=initialization_timeout)
    _initialized = True


def initialize_from_config(config) -> bool:
    """The TSD launcher's DCN entry point: when
    ``tsd.mesh.coordinator`` is configured, join the multi-process
    rendezvous before any JAX backend touch. Idempotent; returns True
    when running multi-process.

    Launch (one line per host, ref-analogue: many stateless TSDs
    behind one LB, RpcManager.java:274-327)::

        tsdb tsd --tsd.mesh.coordinator=host0:9255 \\
                 --tsd.mesh.num_processes=2 --tsd.mesh.process_id=0 \\
                 --tsd.query.mesh=auto

    On TPU pods num_processes/process_id may be omitted (the TPU
    runtime provides them); on CPU/GPU fleets both are required.
    """
    global _initialized
    coordinator = config.get_string("tsd.mesh.coordinator", "")
    if not coordinator:
        return False
    if _initialized:
        return True
    kwargs: dict = {"coordinator_address": coordinator}
    num_processes = config.get_int("tsd.mesh.num_processes", 0)
    process_id = config.get_int("tsd.mesh.process_id", -1)
    if num_processes > 0:
        kwargs["num_processes"] = num_processes
    if process_id >= 0:
        kwargs["process_id"] = process_id
    # a dead coordinator must fail the boot loudly within a bounded
    # window, not hang it (the same handled-failure posture as the
    # bench watchdog; jax default is 300s)
    kwargs["initialization_timeout"] = config.get_int(
        "tsd.mesh.init_timeout", 120)
    jax.distributed.initialize(**kwargs)
    _initialized = True
    LOG.info("jax.distributed up: process %d/%d, %d global devices",
             jax.process_index(), jax.process_count(),
             len(jax.devices()))
    return True


def is_distributed() -> bool:
    return _initialized


def put_global(x, sharding):
    """Upload a host array onto a (possibly multi-process) sharding.

    Single-process shardings take the plain ``jax.device_put`` fast
    path. Multi-process shardings use ``jax.make_array_from_callback``
    — each process supplies its addressable shards from its own
    (identical, SPMD) host copy. device_put would instead run a
    cross-process value-equality check that (a) allgathers every upload
    over DCN and (b) rejects NaN padding (NaN != NaN), which the
    query grids are full of.
    """
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    xnp = np.asarray(x)
    return jax.make_array_from_callback(xnp.shape, sharding,
                                        lambda idx: xnp[idx])


def to_host(x) -> np.ndarray:
    """Bring a device array to host numpy, gathering across processes
    when its shards span hosts (single-process: plain np.asarray).
    Every process receives the full array — the SPMD analogue of each
    TSD serializing the complete query response."""
    if hasattr(x, "is_fully_addressable") and \
            not x.is_fully_addressable and \
            not x.sharding.is_fully_replicated:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x,
                                                            tiled=True))
    return np.asarray(x)


def multihost_device_grid(devices=None,
                          num_hosts: int | None = None) -> np.ndarray:
    """Arrange devices into a [local_chips, hosts] grid.

    Rows (axis 0, 'series') hold chips of the same host — ICI
    neighbors; columns (axis 1, 'time') cross hosts — DCN. On real
    multi-process runs hosts are identified by ``device.process_index``;
    for single-process testing (the 8-virtual-device CPU matrix)
    ``num_hosts`` splits the flat device list into equal fake hosts.
    """
    devs = list(devices if devices is not None else jax.devices())
    by_host: dict[int, list] = {}
    if num_hosts is None:
        for d in devs:
            by_host.setdefault(getattr(d, "process_index", 0),
                               []).append(d)
        if len(by_host) == 1 and num_hosts is None:
            # single process: one "host", all chips local
            return np.asarray(devs).reshape(len(devs), 1)
    else:
        if len(devs) % num_hosts:
            raise ValueError(
                f"{len(devs)} devices do not split into {num_hosts} hosts")
        per = len(devs) // num_hosts
        for h in range(num_hosts):
            by_host[h] = devs[h * per:(h + 1) * per]
    counts = {len(v) for v in by_host.values()}
    if len(counts) != 1:
        raise ValueError(f"uneven chips per host: {by_host}")
    hosts = sorted(by_host)
    grid = np.empty((counts.pop(), len(hosts)), dtype=object)
    for col, h in enumerate(hosts):
        grid[:, col] = by_host[h]
    return grid


def make_multihost_mesh(devices=None,
                        num_hosts: int | None = None) -> Mesh:
    """A ('series', 'time') mesh with series=ICI-local, time=DCN."""
    return Mesh(multihost_device_grid(devices, num_hosts),
                ("series", "time"))


def series_home(series_shard: int, mesh: Mesh) -> int:
    """Which process/host owns a series shard's ingest
    (ref-analogue: asynchbase region-aware write routing).

    Series shards are distributed round-robin over the series axis;
    every host holds the full series axis locally (the time axis is
    what crosses hosts), so the owner is the process of the device at
    ``[shard % series_size, 0]``.
    """
    series_size = mesh.shape["series"]
    dev = np.asarray(mesh.devices)[series_shard % series_size, 0]
    return getattr(dev, "process_index", 0)
