"""Multi-host (DCN) deployment of the query mesh.

The reference scales beyond one JVM by running many stateless TSDs
behind a load balancer, all talking to one HBase cluster over TCP
(SURVEY.md §5.8). The TPU-native equivalent is multi-host JAX: one
process per host, ``jax.distributed.initialize`` for rendezvous, and a
single global ('series', 'time') mesh spanning every chip.

Axis placement is deliberate (the scaling-book recipe — put the
chatty collective on the fast interconnect):

- the **series** axis (salt analogue) lays out over each host's LOCAL
  chips: group-by reductions cross this axis with ``psum`` every query,
  and those collectives ride **ICI**;
- the **time** axis spans **hosts over DCN**: time blocks are almost
  independent — only rate/interpolation boundary halos (two [S]-sized
  vectors per block edge, ``sharded_pipeline._scan_boundary``) cross
  it, so the slow link carries the least traffic.

Write routing mirrors the reference's "any TSD accepts any write"
model: every host ingests into its local shard of the series axis;
:func:`series_home` tells a collector (or a fronting LB) which host
owns a series so ingest can avoid cross-host forwarding entirely —
the analogue of region-aware routing in asynchbase.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join the multi-host rendezvous (no-op when single-process).

    Mirrors ``jax.distributed.initialize``; on TPU pods the arguments
    are auto-detected from the environment, so ``initialize()`` with no
    arguments is the common call.
    """
    if num_processes is not None and num_processes <= 1:
        return
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id)


def multihost_device_grid(devices=None,
                          num_hosts: int | None = None) -> np.ndarray:
    """Arrange devices into a [local_chips, hosts] grid.

    Rows (axis 0, 'series') hold chips of the same host — ICI
    neighbors; columns (axis 1, 'time') cross hosts — DCN. On real
    multi-process runs hosts are identified by ``device.process_index``;
    for single-process testing (the 8-virtual-device CPU matrix)
    ``num_hosts`` splits the flat device list into equal fake hosts.
    """
    devs = list(devices if devices is not None else jax.devices())
    by_host: dict[int, list] = {}
    if num_hosts is None:
        for d in devs:
            by_host.setdefault(getattr(d, "process_index", 0),
                               []).append(d)
        if len(by_host) == 1 and num_hosts is None:
            # single process: one "host", all chips local
            return np.asarray(devs).reshape(len(devs), 1)
    else:
        if len(devs) % num_hosts:
            raise ValueError(
                f"{len(devs)} devices do not split into {num_hosts} hosts")
        per = len(devs) // num_hosts
        for h in range(num_hosts):
            by_host[h] = devs[h * per:(h + 1) * per]
    counts = {len(v) for v in by_host.values()}
    if len(counts) != 1:
        raise ValueError(f"uneven chips per host: {by_host}")
    hosts = sorted(by_host)
    grid = np.empty((counts.pop(), len(hosts)), dtype=object)
    for col, h in enumerate(hosts):
        grid[:, col] = by_host[h]
    return grid


def make_multihost_mesh(devices=None,
                        num_hosts: int | None = None) -> Mesh:
    """A ('series', 'time') mesh with series=ICI-local, time=DCN."""
    return Mesh(multihost_device_grid(devices, num_hosts),
                ("series", "time"))


def series_home(series_shard: int, mesh: Mesh) -> int:
    """Which process/host owns a series shard's ingest
    (ref-analogue: asynchbase region-aware write routing).

    Series shards are distributed round-robin over the series axis;
    every host holds the full series axis locally (the time axis is
    what crosses hosts), so the owner is the process of the device at
    ``[shard % series_size, 0]``.
    """
    series_size = mesh.shape["series"]
    dev = np.asarray(mesh.devices)[series_shard % series_size, 0]
    return getattr(dev, "process_index", 0)
