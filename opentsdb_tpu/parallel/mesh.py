"""Device mesh construction.

The reference scales by (a) 20-way salt-bucket scan fan-out inside one
TSD (SaltScanner.java:70) and (b) stateless TSD scale-out behind a load
balancer. The TPU build maps both onto one ``jax.sharding.Mesh``:

- ``series`` axis — the salt axis: series are hashed onto devices the
  same way row keys are hashed into salt buckets (RowKey.java:141).
  Group-by reductions cross this axis via ``psum`` over ICI.
- ``time`` axis — long time ranges split into blocks (the reference's
  hourly-row streaming + rollup tiers, SURVEY.md §5.7); rate and
  interpolation exchange boundary halos over this axis like sequence /
  context parallelism exchanges activations.

Multi-host deployments extend the same mesh over DCN: ``jax.devices()``
spanning hosts needs no code changes (pjit/shard_map are SPMD-global).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_series: int | None = None, n_time: int = 1,
              devices=None) -> Mesh:
    """Build a ('series', 'time') mesh over the available devices."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    total = devs.size
    if n_series is None:
        n_series = total // n_time
    if n_series * n_time != total:
        raise ValueError(
            f"mesh {n_series}x{n_time} != {total} devices")
    return Mesh(devs.reshape(n_series, n_time), ("series", "time"))


def mesh_from_spec(spec: str, devices=None) -> Mesh | None:
    """Parse the ``tsd.query.mesh`` config value into a query mesh.

    Accepted forms:

    - ``""`` — multi-chip execution off (single-device pipeline)
    - ``"auto"`` — every visible device on the series axis (None when
      only one device exists: shard_map overhead buys nothing there)
    - ``"series:N"`` / ``"series:N,time:M"`` — explicit shape; uses the
      first N*M devices

    This is the TSD's knob for the reference's fixed 20-way salt
    fan-out (Const.java:127 SALT_BUCKETS): the device mesh replaces the
    salt-bucket scanner pool.
    """
    shape = parse_mesh_spec(spec)
    if shape is None:
        return None
    devs = list(devices if devices is not None else jax.devices())
    if shape == "auto":
        if len(devs) <= 1:
            return None
        return make_mesh(len(devs), 1, devices=devs)
    n_series, n_time = shape
    need = n_series * n_time
    if need > len(devs):
        raise ValueError(
            f"tsd.query.mesh={spec!r} wants {need} devices, "
            f"{len(devs)} available")
    return make_mesh(n_series, n_time, devices=devs[:need])


def parse_mesh_spec(spec: str) -> tuple[int, int] | str | None:
    """Validate a ``tsd.query.mesh`` string WITHOUT touching devices:
    returns (n_series, n_time), the string ``"auto"``, or None for
    off. Called eagerly at TSDB construction so a typo fails at boot,
    not as an HTTP 500 on the first query."""
    spec = (spec or "").strip().lower()
    if not spec:
        return None
    if spec == "auto":
        return "auto"
    n_series = n_time = 1
    for part in spec.split(","):
        axis, _, n = part.partition(":")
        axis = axis.strip()
        if axis not in ("series", "time"):
            raise ValueError(
                f"unknown mesh axis {axis!r} in tsd.query.mesh={spec!r} "
                "(expected 'auto' or 'series:N[,time:M]')")
        try:
            count = int(n)
        except ValueError:
            raise ValueError(
                f"bad device count {n!r} for axis {axis!r} in "
                f"tsd.query.mesh={spec!r}") from None
        if count < 1:
            raise ValueError(
                f"axis {axis!r} needs >= 1 device in "
                f"tsd.query.mesh={spec!r}")
        if axis == "series":
            n_series = count
        else:
            n_time = count
    return n_series, n_time
