"""Device mesh construction.

The reference scales by (a) 20-way salt-bucket scan fan-out inside one
TSD (SaltScanner.java:70) and (b) stateless TSD scale-out behind a load
balancer. The TPU build maps both onto one ``jax.sharding.Mesh``:

- ``series`` axis — the salt axis: series are hashed onto devices the
  same way row keys are hashed into salt buckets (RowKey.java:141).
  Group-by reductions cross this axis via ``psum`` over ICI.
- ``time`` axis — long time ranges split into blocks (the reference's
  hourly-row streaming + rollup tiers, SURVEY.md §5.7); rate and
  interpolation exchange boundary halos over this axis like sequence /
  context parallelism exchanges activations.

Multi-host deployments extend the same mesh over DCN: ``jax.devices()``
spanning hosts needs no code changes (pjit/shard_map are SPMD-global).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_series: int | None = None, n_time: int = 1,
              devices=None) -> Mesh:
    """Build a ('series', 'time') mesh over the available devices."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    total = devs.size
    if n_series is None:
        n_series = total // n_time
    if n_series * n_time != total:
        raise ValueError(
            f"mesh {n_series}x{n_time} != {total} devices")
    return Mesh(devs.reshape(n_series, n_time), ("series", "time"))
