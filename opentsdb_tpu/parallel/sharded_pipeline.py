"""Multi-chip query pipeline: shard_map over a ('series', 'time') mesh.

The distributed design (SURVEY.md §2.11, §5.8):

- **series axis** — the salt axis. Each device owns a hash-bucket of
  series (exactly the reference's SaltScanner partitioning,
  RowKey.java:141) and bucketizes/rates/fills them locally. Group-by
  aggregation crosses the axis with ``psum``/``pmin``/``pmax`` over ICI
  — replacing the TreeMap merge of 20 scanner callbacks
  (SaltScanner.java:463-536). Order-statistic aggregators (median/
  percentiles/first/last/diff/multiply) ``all_gather`` the filled grid
  instead, paying ICI bandwidth only when the math truly needs global
  order.
- **time axis** — long ranges split into bucket blocks (the analogue of
  sequence/context parallelism). Rate conversion and LERP interpolation
  need the nearest present value *across* block boundaries; these carries
  propagate with a log-step ppermute prefix scan (Hillis-Steele over the
  'time' axis), the TSDB version of ring-attention halo exchange.

The kernels reuse the single-chip segment primitives unchanged — only
the cross-device combines live here.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.ops.aggregators import Interpolation
from opentsdb_tpu.ops import aggregators as aggs_mod
from opentsdb_tpu.ops.interp import (_gather_minor, _next_valid_idx,
                                     _prev_valid_idx)
from opentsdb_tpu.ops.pipeline import PipelineSpec

# aggregators whose group reduction crosses the series axis with
# psum/pmin/pmax partials and so keep per-device memory at
# [S_loc, B_loc]; everything else all_gathers the full series axis
# (engine sizing decisions key off this too)
REDUCIBLE_AGGS = frozenset((
    "sum", "zimsum", "pfsum", "avg", "count", "min", "max", "mimmin",
    "mimmax", "squareSum", "dev"))


# ---------------------------------------------------------------------------
# cross-block carries (time axis)
# ---------------------------------------------------------------------------

def _scan_boundary(val, ts, present, axis_name: str, n_shards: int,
                   reverse: bool):
    """Exclusive 'nearest-present' scan across mesh axis ``axis_name``.

    Every shard contributes its own boundary candidate (val, ts, present)
    — the last present cell per series for a forward scan, first for a
    reverse scan — and receives the nearest present candidate among all
    shards strictly before (after, if reverse) it. log2(n) ppermute
    rounds (Hillis-Steele).
    """
    if n_shards == 1:
        absent = jnp.zeros_like(present)
        return jnp.zeros_like(val), jnp.zeros_like(ts), absent

    def shift(x, d):
        if reverse:
            perm = [(i, i - d) for i in range(d, n_shards)]
        else:
            perm = [(i, i + d) for i in range(n_shards - d)]
        return jax.lax.ppermute(x, axis_name, perm)

    v, t, p = val, ts, present
    d = 1
    while d < n_shards:
        vin, tin, pin = shift(v, d), shift(t, d), shift(p, d)
        # keep own (nearer) when present, else take incoming (farther)
        v = jnp.where(p, v, vin)
        t = jnp.where(p, t, tin)
        p = p | pin
        d *= 2
    # shift by one to make the scan exclusive
    return shift(v, 1), shift(t, 1), shift(p, 1)


def _block_boundaries(grid, bucket_ts):
    """Per-series (last_val, last_ts, present) and (first_val, first_ts,
    present) of this time block."""
    mask = ~jnp.isnan(grid)
    nb = grid.shape[-1]
    prev_idx = _prev_valid_idx(mask)[:, -1]          # last present idx
    next_idx = _next_valid_idx(mask)[:, 0]           # first present idx
    has_last = prev_idx >= 0
    has_first = next_idx < nb
    lp = jnp.clip(prev_idx, 0, nb - 1)
    fp = jnp.clip(next_idx, 0, nb - 1)
    ts = bucket_ts.astype(grid.dtype)
    ts_row = jnp.broadcast_to(ts[None, :], grid.shape)
    # fused select chains, not per-element TPU gathers (interp._gather_minor)
    return ((_gather_minor(grid, lp[:, None])[:, 0],
             _gather_minor(ts_row, lp[:, None])[:, 0], has_last),
            (_gather_minor(grid, fp[:, None])[:, 0],
             _gather_minor(ts_row, fp[:, None])[:, 0], has_first))


def _fill_with_boundaries(grid, bucket_ts, mode: str,
                          prev_v, prev_t, prev_p,
                          next_v, next_t, next_p):
    """fill_gaps with per-series cross-block boundary carries."""
    mask = ~jnp.isnan(grid)
    if mode == Interpolation.ZIM.value:
        return jnp.where(mask, grid, 0.0)
    nb = grid.shape[-1]
    ts = bucket_ts.astype(grid.dtype)
    ts_row = jnp.broadcast_to(ts[None, :], grid.shape)
    pidx = _prev_valid_idx(mask)
    has_lp = pidx >= 0
    sp = jnp.clip(pidx, 0, nb - 1)
    v0_local = _gather_minor(grid, sp)
    t0_local = _gather_minor(ts_row, sp)
    v0 = jnp.where(has_lp, v0_local, prev_v[:, None])
    t0 = jnp.where(has_lp, t0_local, prev_t[:, None])
    has0 = has_lp | prev_p[:, None]
    if mode == Interpolation.PREV.value:
        return jnp.where(mask, grid, jnp.where(has0, v0, jnp.nan))
    nidx = _next_valid_idx(mask)
    has_ln = nidx < nb
    sn = jnp.clip(nidx, 0, nb - 1)
    v1_local = _gather_minor(grid, sn)
    t1_local = _gather_minor(ts_row, sn)
    v1 = jnp.where(has_ln, v1_local, next_v[:, None])
    t1 = jnp.where(has_ln, t1_local, next_t[:, None])
    has1 = has_ln | next_p[:, None]
    in_range = has0 & has1
    if mode in (Interpolation.MAX.value, Interpolation.MIN.value):
        extreme = jnp.inf if mode == Interpolation.MAX.value else -jnp.inf
        return jnp.where(mask, grid, jnp.where(in_range, extreme, jnp.nan))
    if mode != Interpolation.LERP.value:
        raise ValueError(f"unknown interpolation mode {mode!r}")
    t = ts[None, :]
    dt = jnp.where(t1 > t0, t1 - t0, 1.0)
    lerped = v0 + (v1 - v0) * (t - t0) / dt
    return jnp.where(mask, grid, jnp.where(in_range, lerped, jnp.nan))


def _rate_with_boundary(grid, bucket_ts, counter: bool, counter_max,
                        reset_value, drop_resets: bool,
                        carry_v, carry_t, carry_p):
    """Rate kernel with the previous block's last-present carry."""
    mask = ~jnp.isnan(grid)
    nb = grid.shape[-1]
    prev_at = _prev_valid_idx(mask)
    shifted = jnp.concatenate(
        [jnp.full(prev_at.shape[:-1] + (1,), -1, prev_at.dtype),
         prev_at[..., :-1]], axis=-1)
    has_local = shifted >= 0
    sp = jnp.clip(shifted, 0, nb - 1)
    ts = bucket_ts.astype(grid.dtype)
    ts_row = jnp.broadcast_to(ts[None, :], grid.shape)
    v_prev = jnp.where(has_local, _gather_minor(grid, sp),
                       carry_v[:, None])
    t_prev = jnp.where(has_local, _gather_minor(ts_row, sp),
                       carry_t[:, None])
    has_prev = has_local | carry_p[:, None]
    dt_sec = (ts[None, :] - t_prev) / 1000.0
    dt_sec = jnp.where(dt_sec > 0, dt_sec, 1.0)
    delta = grid - v_prev
    rate = delta / dt_sec
    if counter:
        rolled = delta < 0
        corrected = (counter_max - v_prev + grid) / dt_sec
        rate = jnp.where(rolled, corrected, rate)
        if drop_resets:
            rate = jnp.where(rolled, jnp.nan, rate)
        rate = jnp.where((reset_value > 0) & (rate > reset_value), 0.0,
                         rate)
    return jnp.where(mask & has_prev, rate, jnp.nan)


# ---------------------------------------------------------------------------
# cross-shard group reduction (series axis)
# ---------------------------------------------------------------------------

def _group_reduce_psum(filled, group_ids, num_groups: int, agg_name: str,
                       axis_name: str):
    """Partial segment reduction per shard + collective combine.

    Per-shard reductions use the single-chip primitives (one-hot MXU
    contraction for sums, chunked broadcast for extrema — both measured
    ~3-40x faster than TPU scatter, see ops.groupby); only the
    psum/pmin/pmax combine is collective."""
    from opentsdb_tpu.ops.groupby import _group_extremum, _group_sum
    valid = ~jnp.isnan(filled)
    x0 = jnp.where(valid, filled, 0.0)

    def seg(x):
        return _group_sum(x, group_ids, num_groups)

    cnt = jax.lax.psum(seg(valid.astype(filled.dtype)), axis_name)
    if agg_name in ("sum", "zimsum", "pfsum"):
        out = jax.lax.psum(seg(x0), axis_name)
    elif agg_name == "avg":
        out = jax.lax.psum(seg(x0), axis_name) / jnp.maximum(cnt, 1)
    elif agg_name == "count":
        out = cnt
    elif agg_name in ("min", "mimmin"):
        part = _group_extremum(jnp.where(valid, filled, jnp.inf),
                               group_ids, num_groups, "min")
        out = jax.lax.pmin(part, axis_name)
        out = jnp.where(jnp.isinf(out) & (out > 0), jnp.nan, out)
    elif agg_name in ("max", "mimmax"):
        part = _group_extremum(jnp.where(valid, filled, -jnp.inf),
                               group_ids, num_groups, "max")
        out = jax.lax.pmax(part, axis_name)
        out = jnp.where(jnp.isinf(out) & (out < 0), jnp.nan, out)
    elif agg_name == "squareSum":
        out = jax.lax.psum(seg(x0 * x0), axis_name)
    elif agg_name == "dev":
        s1 = jax.lax.psum(seg(x0), axis_name)
        s2 = jax.lax.psum(seg(x0 * x0), axis_name)
        mean = s1 / jnp.maximum(cnt, 1)
        var = jnp.maximum(s2 / jnp.maximum(cnt, 1) - mean * mean, 0.0) \
            * (jnp.maximum(cnt, 1) / jnp.maximum(cnt - 1, 1))
        out = jnp.where(cnt == 1, 0.0, jnp.sqrt(var))
    else:
        raise ValueError(f"{agg_name} is not psum-reducible")
    return jnp.where(cnt > 0, out, jnp.nan)


# ---------------------------------------------------------------------------
# the sharded step
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ShardedBatch:
    """Host-prepared, device-ready inputs for the sharded pipeline.

    Shapes (Ds = series shards, Dt = time shards):
    - values/series_idx/bucket_idx: [Ds, Dt, Npad] — per-shard point
      lists, padded with bucket_idx == B_loc (a dummy bucket slot)
    - bucket_ts: [B_pad] (split over 'time')
    - group_ids: [Ds * S_loc] (split over 'series'), dummy group == G
    """
    values: np.ndarray
    series_idx: np.ndarray
    bucket_idx: np.ndarray
    bucket_ts: np.ndarray
    group_ids: np.ndarray
    s_loc: int
    b_loc: int
    num_groups: int  # real groups (dummy excluded)


def build_sharded_step(mesh: Mesh, spec: PipelineSpec, s_loc: int,
                       b_loc: int):
    """Compile the multi-chip query step for the given mesh and shapes.

    Returns a jitted fn(values, series_idx, bucket_idx, bucket_ts,
    group_ids, rate_params, fill_value) -> (result[G+1, B_pad],
    emit[G+1, B_pad]) with result sharded over 'time'.
    """
    n_series_shards, n_time_shards = (mesh.shape["series"],
                                      mesh.shape["time"])
    agg = aggs_mod.get(spec.agg_name)
    interp_mode = agg.interpolation.value
    g_padded = spec.num_groups + 1  # trailing dummy group for padding

    def step(values, series_idx, bucket_idx, bucket_ts, group_ids,
             rate_params, fill_value):
        # local blocks: [1, 1, Npad] / [B_loc] / [S_loc]
        vals = values.reshape(-1)
        sidx = series_idx.reshape(-1)
        bidx = bucket_idx.reshape(-1)
        bts = bucket_ts
        gids = group_ids

        # 1. local bucketize into [S_loc, B_loc + 1] (last = padding)
        grid, cnt = ds_mod.bucketize(vals, sidx, bidx, s_loc, b_loc + 1,
                                     spec.ds_function)
        grid = grid[:, :b_loc]
        cnt = cnt[:, :b_loc]
        has_data = cnt > 0

        if spec.fill_policy == ds_mod.FillPolicy.ZERO:
            grid = jnp.where(jnp.isnan(grid), 0.0, grid)
            has_data = jnp.ones_like(has_data)
        elif spec.fill_policy == ds_mod.FillPolicy.SCALAR:
            grid = jnp.where(jnp.isnan(grid), fill_value, grid)
            has_data = jnp.ones_like(has_data)

        # 2. rate with cross-block carry over the 'time' axis
        if spec.rate:
            (lv, lt, lp), _ = _block_boundaries(grid, bts)
            cv, ct, cp = _scan_boundary(lv, lt, lp, "time",
                                        n_time_shards, reverse=False)
            counter_max, reset_value = rate_params
            grid = _rate_with_boundary(
                grid, bts, spec.rate_counter, counter_max, reset_value,
                spec.rate_drop_resets, cv, ct, cp)
            has_data = has_data & ~jnp.isnan(grid)

        if spec.emit_raw:
            return grid, has_data

        # 3. interpolation fill with halo carries both directions.
        # Only fill NONE leaves true gaps that interpolate at merge;
        # NAN/NULL emit explicit NaN points that the reference's merge
        # loop skips WITHOUT interpolating, and ZERO/SCALAR were
        # substituted in step 1 (mirrors pipeline._finish_pipeline).
        if spec.fill_policy == ds_mod.FillPolicy.NONE:
            (lv, lt, lp), (fv, ft, fp) = _block_boundaries(grid, bts)
            pv, pt, pp = _scan_boundary(lv, lt, lp, "time",
                                        n_time_shards, reverse=False)
            nv, nt, npp = _scan_boundary(fv, ft, fp, "time",
                                         n_time_shards, reverse=True)
            filled = _fill_with_boundaries(grid, bts, interp_mode,
                                           pv, pt, pp, nv, nt, npp)
        else:
            filled = grid

        # 4. group aggregation across the 'series' axis
        if spec.agg_name in REDUCIBLE_AGGS:
            result = _group_reduce_psum(filled, gids, g_padded,
                                        spec.agg_name, "series")
        else:
            full = jax.lax.all_gather(filled, "series", axis=0,
                                      tiled=True)
            gids_full = jax.lax.all_gather(gids, "series", axis=0,
                                           tiled=True)
            from opentsdb_tpu.ops.groupby import _group_reduce
            result = _group_reduce(full, gids_full, g_padded,
                                   spec.agg_name)

        if spec.fill_policy == ds_mod.FillPolicy.NONE:
            # segment_sum: empty segments give 0 (segment_max gives INT_MIN
            # which breaks the cross-shard psum)
            emit = jax.lax.psum(
                jax.ops.segment_sum(has_data.astype(jnp.int32), gids,
                                    num_segments=g_padded), "series") > 0
        else:
            emit = jnp.ones((g_padded, b_loc), dtype=bool)
        return result, emit

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(P("series", "time", None), P("series", "time", None),
                  P("series", "time", None), P("time"), P("series"),
                  P(), P()),
        out_specs=(P(None, "time"), P(None, "time"))
        if not spec.emit_raw else (P("series", "time"),
                                   P("series", "time")),
        check_vma=False)
    return jax.jit(sharded)


# ---------------------------------------------------------------------------
# host-side sharding prep
# ---------------------------------------------------------------------------

def prepare_sharded_batch(values: np.ndarray, series_idx: np.ndarray,
                          bucket_idx: np.ndarray, bucket_ts: np.ndarray,
                          group_ids: np.ndarray, num_series: int,
                          num_groups: int, n_series_shards: int,
                          n_time_shards: int) -> ShardedBatch:
    """Partition a flat point batch onto the mesh.

    Series land on series-shards in contiguous *blocks* (shard =
    series_idx // s_loc): after an all_gather over the series axis the
    rows come back in natural series order, which the order-sensitive
    aggregators (first/last/diff pick the lowest/highest series index,
    matching the reference's span order) depend on. Buckets split into
    contiguous time blocks. Point lists are padded per (Ds, Dt) cell to
    the max cell population.
    """
    s_loc = -(-num_series // n_series_shards)
    b = len(bucket_ts)
    b_loc = -(-b // n_time_shards)
    b_pad = b_loc * n_time_shards

    # pad bucket_ts monotonically so halo timestamps stay ordered
    if b_pad > b:
        step = int(bucket_ts[-1] - bucket_ts[-2]) if b > 1 else 1000
        extra = bucket_ts[-1] + step * np.arange(1, b_pad - b + 1)
        bucket_ts = np.concatenate([bucket_ts, extra])

    series_shard = series_idx // s_loc
    local_series = series_idx % s_loc
    time_shard = bucket_idx // b_loc
    local_bucket = bucket_idx % b_loc

    # per-cell padding
    cell_id = series_shard.astype(np.int64) * n_time_shards + time_shard
    order = np.argsort(cell_id, kind="stable")
    counts = np.bincount(cell_id, minlength=n_series_shards * n_time_shards)
    npad = max(int(counts.max()), 1) if len(cell_id) else 1
    ds, dt = n_series_shards, n_time_shards
    pvals = np.zeros((ds, dt, npad), dtype=values.dtype)
    psidx = np.zeros((ds, dt, npad), dtype=np.int32)
    pbidx = np.full((ds, dt, npad), b_loc, dtype=np.int32)  # dummy bucket
    pos = 0
    for cell in range(ds * dt):
        c = counts[cell]
        if c == 0:
            continue
        sel = order[pos:pos + c]
        i, j = divmod(cell, dt)
        pvals[i, j, :c] = values[sel]
        psidx[i, j, :c] = local_series[sel]
        pbidx[i, j, :c] = local_bucket[sel]
        pos += c

    # group ids: [Ds * S_loc]; block layout keeps natural series order
    # (row shard*s_loc+loc == global sid); padding -> dummy group G
    gids = np.full(ds * s_loc, num_groups, dtype=np.int32)
    gids[:num_series] = group_ids

    return ShardedBatch(pvals, psidx, pbidx,
                        bucket_ts.astype(np.int64), gids, s_loc, b_loc,
                        num_groups)


@lru_cache(maxsize=128)
def _compiled_step(mesh: Mesh, spec: PipelineSpec, s_loc: int,
                   b_loc: int):
    """Per-(mesh, spec, shape) cache: build_sharded_step returns a new
    closure every call, so jax.jit alone would re-trace every query."""
    return build_sharded_step(mesh, spec, s_loc, b_loc)


def run_sharded(mesh: Mesh, spec: PipelineSpec, batch: ShardedBatch,
                rate_options=None, dtype=None):
    """Execute the sharded step; returns host (result[G,B], emit[G,B])
    trimmed of padding."""
    from opentsdb_tpu.ops.pipeline import device_bucket_ts
    from opentsdb_tpu.ops.rate import RateOptions
    if dtype is None:
        dtype = jnp.float64 if jax.config.read("jax_enable_x64") \
            else jnp.float32
    ro = rate_options or RateOptions()
    step = _compiled_step(mesh, spec, batch.s_loc, batch.b_loc)
    rate_params = (jnp.asarray(ro.counter_max, dtype),
                   jnp.asarray(ro.reset_value, dtype))
    # relative ms offsets: absolute epoch-ms int64 would truncate on
    # TPU (no device int64); the kernels only use ts differences
    result, emit = step(jnp.asarray(batch.values, dtype),
                        jnp.asarray(batch.series_idx),
                        jnp.asarray(batch.bucket_idx),
                        jnp.asarray(device_bucket_ts(batch.bucket_ts)),
                        jnp.asarray(batch.group_ids),
                        rate_params,
                        jnp.asarray(spec.fill_value, dtype))
    result = np.asarray(result)
    emit = np.asarray(emit)
    b = spec.num_buckets
    return result[:batch.num_groups, :b], emit[:batch.num_groups, :b]
