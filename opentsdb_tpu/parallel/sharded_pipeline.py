"""Multi-chip query pipeline: shard_map over a ('series', 'time') mesh.

The distributed design (SURVEY.md §2.11, §5.8):

- **series axis** — the salt axis. Each device owns a hash-bucket of
  series (exactly the reference's SaltScanner partitioning,
  RowKey.java:141) and bucketizes/rates/fills them locally. Group-by
  aggregation crosses the axis with ``psum``/``pmin``/``pmax`` over ICI
  — replacing the TreeMap merge of 20 scanner callbacks
  (SaltScanner.java:463-536). Order-statistic aggregators (median/
  percentiles/first/last/diff/multiply) ``all_gather`` the filled grid
  instead, paying ICI bandwidth only when the math truly needs global
  order.
- **time axis** — long ranges split into bucket blocks (the analogue of
  sequence/context parallelism). Rate conversion and LERP interpolation
  need the nearest present value *across* block boundaries; these carries
  propagate with a log-step ppermute prefix scan (Hillis-Steele over the
  'time' axis), the TSDB version of ring-attention halo exchange.

The kernels reuse the single-chip segment primitives unchanged — only
the cross-device combines live here.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # jax < 0.6 exposes it under experimental, where
    # the replication check is spelled check_rep instead of check_vma
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_old(*args, **kwargs)

from opentsdb_tpu.parallel.distributed import to_host as _to_host

from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.ops.aggregators import Interpolation
from opentsdb_tpu.ops import aggregators as aggs_mod
from opentsdb_tpu.ops.interp import (_gather_minor, _next_valid_idx,
                                     _prev_valid_idx)
from opentsdb_tpu.ops.pipeline import PipelineSpec

# aggregators whose group reduction crosses the series axis with
# psum/pmin/pmax partials and so keep per-device memory at
# [S_loc, B_loc]
REDUCIBLE_AGGS = frozenset((
    "sum", "zimsum", "pfsum", "avg", "count", "min", "max", "mimmin",
    "mimmax", "squareSum", "dev"))


# [G, B, BINS] histogram cell cap for the distributed percentile path
# (f32: 2^25 cells = 128 MB per device); beyond it the reduction falls
# back to all_gather — with that many groups each group holds few
# series, which is exactly when gathering is the cheaper shape
PERCENTILE_HIST_MAX_CELLS = 1 << 25


def _hist_eligible(num_groups: int, num_buckets: int) -> bool:
    return (num_groups * num_buckets * PERCENTILE_BINS
            <= PERCENTILE_HIST_MAX_CELLS)


def agg_mesh_class(agg_name: str) -> str:
    """Memory class of an aggregator's cross-shard reduction:
    'safe' — per-device O(S_loc x B) (psum partials / edge candidates);
    'pct' — histogram psum, safe iff the [G, B, BINS] partial fits
    (:func:`_hist_eligible` — the per-query shape decides);
    'gather' — all_gathers the series axis (diff/multiply)."""
    if agg_name in REDUCIBLE_AGGS or agg_name in ("first", "last"):
        return "safe"
    if agg_name == "median" or \
            aggs_mod.get(agg_name).percentile is not None:
        return "pct"
    return "gather"


def mesh_memory_safe(agg_name: str, num_groups: int | None = None,
                     num_buckets: int | None = None) -> bool:
    """True when the mesh reduction keeps per-device memory at
    O(S_loc x B) — engine sizing (device-cell budgets) keys off this.
    Percentiles qualify only while their [G, B, BINS] histogram
    partial fits :data:`PERCENTILE_HIST_MAX_CELLS`."""
    cls = agg_mesh_class(agg_name)
    if cls == "safe":
        return True
    if cls == "pct":
        if num_groups is None or num_buckets is None:
            return False  # unknown shape: be conservative
        return _hist_eligible(num_groups + 1, num_buckets)
    return False


# ---------------------------------------------------------------------------
# cross-block carries (time axis)
# ---------------------------------------------------------------------------

def _pad_bts_tail(bts: np.ndarray, target_len: int) -> np.ndarray:
    """Monotonic tail padding of bucket timestamps (extrapolating the
    last step so halo/carry timestamps stay ordered)."""
    bts = np.asarray(bts)
    need = target_len - len(bts)
    if need <= 0:
        return bts
    step = int(bts[-1] - bts[-2]) if len(bts) > 1 else 1000
    extra = bts[-1] + step * np.arange(1, need + 1, dtype=bts.dtype)
    return np.concatenate([bts, extra])


def _scan_boundary(val, ts, present, axis_name: str, n_shards: int,
                   reverse: bool):
    """Exclusive 'nearest-present' scan across mesh axis ``axis_name``.

    Every shard contributes its own boundary candidate (val, ts, present)
    — the last present cell per series for a forward scan, first for a
    reverse scan — and receives the nearest present candidate among all
    shards strictly before (after, if reverse) it. log2(n) ppermute
    rounds (Hillis-Steele).
    """
    if n_shards == 1:
        absent = jnp.zeros_like(present)
        return jnp.zeros_like(val), jnp.zeros_like(ts), absent

    def shift(x, d):
        if reverse:
            perm = [(i, i - d) for i in range(d, n_shards)]
        else:
            perm = [(i, i + d) for i in range(n_shards - d)]
        return jax.lax.ppermute(x, axis_name, perm)

    v, t, p = val, ts, present
    d = 1
    while d < n_shards:
        vin, tin, pin = shift(v, d), shift(t, d), shift(p, d)
        # keep own (nearer) when present, else take incoming (farther)
        v = jnp.where(p, v, vin)
        t = jnp.where(p, t, tin)
        p = p | pin
        d *= 2
    # shift by one to make the scan exclusive
    return shift(v, 1), shift(t, 1), shift(p, 1)


def _block_boundaries(grid, bucket_ts):
    """Per-series (last_val, last_ts, present) and (first_val, first_ts,
    present) of this time block."""
    mask = ~jnp.isnan(grid)
    nb = grid.shape[-1]
    prev_idx = _prev_valid_idx(mask)[:, -1]          # last present idx
    next_idx = _next_valid_idx(mask)[:, 0]           # first present idx
    has_last = prev_idx >= 0
    has_first = next_idx < nb
    lp = jnp.clip(prev_idx, 0, nb - 1)
    fp = jnp.clip(next_idx, 0, nb - 1)
    ts = bucket_ts.astype(grid.dtype)
    ts_row = jnp.broadcast_to(ts[None, :], grid.shape)
    # fused select chains, not per-element TPU gathers (interp._gather_minor)
    return ((_gather_minor(grid, lp[:, None])[:, 0],
             _gather_minor(ts_row, lp[:, None])[:, 0], has_last),
            (_gather_minor(grid, fp[:, None])[:, 0],
             _gather_minor(ts_row, fp[:, None])[:, 0], has_first))


def _fill_with_boundaries(grid, bucket_ts, mode: str,
                          prev_v, prev_t, prev_p,
                          next_v, next_t, next_p):
    """fill_gaps with per-series cross-block boundary carries
    (associative nearest-present scans — no gathers; see
    interp.carry_prev on the select-chain cliff)."""
    from opentsdb_tpu.ops.interp import carry_next, carry_prev
    mask = ~jnp.isnan(grid)
    if mode == Interpolation.ZIM.value:
        return jnp.where(mask, grid, 0.0)
    ts = bucket_ts.astype(grid.dtype)
    ts_row = jnp.broadcast_to(ts[None, :], grid.shape)
    gz = jnp.where(mask, grid, 0.0)
    v0_l, t0_l, has_lp = carry_prev((gz, ts_row), mask)
    v0 = jnp.where(has_lp, v0_l, prev_v[:, None])
    t0 = jnp.where(has_lp, t0_l, prev_t[:, None])
    has0 = has_lp | prev_p[:, None]
    if mode == Interpolation.PREV.value:
        return jnp.where(mask, grid, jnp.where(has0, v0, jnp.nan))
    v1_l, t1_l, has_ln = carry_next((gz, ts_row), mask)
    v1 = jnp.where(has_ln, v1_l, next_v[:, None])
    t1 = jnp.where(has_ln, t1_l, next_t[:, None])
    has1 = has_ln | next_p[:, None]
    in_range = has0 & has1
    if mode in (Interpolation.MAX.value, Interpolation.MIN.value):
        extreme = jnp.inf if mode == Interpolation.MAX.value else -jnp.inf
        return jnp.where(mask, grid, jnp.where(in_range, extreme, jnp.nan))
    if mode != Interpolation.LERP.value:
        raise ValueError(f"unknown interpolation mode {mode!r}")
    t = ts[None, :]
    dt = jnp.where(t1 > t0, t1 - t0, 1.0)
    lerped = v0 + (v1 - v0) * (t - t0) / dt
    return jnp.where(mask, grid, jnp.where(in_range, lerped, jnp.nan))


def _rate_with_boundary(grid, bucket_ts, counter: bool, counter_max,
                        reset_value, drop_resets: bool,
                        carry_v, carry_t, carry_p):
    """Rate kernel with the previous block's last-present carry
    (associative scans, no gathers)."""
    from opentsdb_tpu.ops.interp import carry_prev, shift_prev
    mask = ~jnp.isnan(grid)
    ts = bucket_ts.astype(grid.dtype)
    ts_row = jnp.broadcast_to(ts[None, :], grid.shape)
    gz = jnp.where(mask, grid, 0.0)
    pv, pt, pp = carry_prev((gz, ts_row), mask)
    v_loc, t_loc, has_local = shift_prev((pv, pt, pp),
                                         (0.0, 0.0, False))
    v_prev = jnp.where(has_local, v_loc, carry_v[:, None])
    t_prev = jnp.where(has_local, t_loc, carry_t[:, None])
    has_prev = has_local | carry_p[:, None]
    dt_sec = (ts[None, :] - t_prev) / 1000.0
    dt_sec = jnp.where(dt_sec > 0, dt_sec, 1.0)
    delta = grid - v_prev
    rate = delta / dt_sec
    if counter:
        rolled = delta < 0
        corrected = (counter_max - v_prev + grid) / dt_sec
        rate = jnp.where(rolled, corrected, rate)
        if drop_resets:
            rate = jnp.where(rolled, jnp.nan, rate)
        rate = jnp.where((reset_value > 0) & (rate > reset_value), 0.0,
                         rate)
    return jnp.where(mask & has_prev, rate, jnp.nan)


# ---------------------------------------------------------------------------
# cross-shard group reduction (series axis)
# ---------------------------------------------------------------------------

def _group_reduce_psum(filled, group_ids, num_groups: int, agg_name: str,
                       axis_name: str):
    """Partial segment reduction per shard + collective combine.

    Per-shard reductions use the single-chip primitives (one-hot MXU
    contraction for sums, chunked broadcast for extrema — both measured
    ~3-40x faster than TPU scatter, see ops.groupby); only the
    psum/pmin/pmax combine is collective."""
    from opentsdb_tpu.ops.groupby import _group_extremum, _group_sum
    valid = ~jnp.isnan(filled)
    x0 = jnp.where(valid, filled, 0.0)

    def seg(x):
        return _group_sum(x, group_ids, num_groups)

    cnt = jax.lax.psum(seg(valid.astype(filled.dtype)), axis_name)
    if agg_name in ("sum", "zimsum", "pfsum"):
        out = jax.lax.psum(seg(x0), axis_name)
    elif agg_name == "avg":
        out = jax.lax.psum(seg(x0), axis_name) / jnp.maximum(cnt, 1)
    elif agg_name == "count":
        out = cnt
    elif agg_name in ("min", "mimmin"):
        part = _group_extremum(jnp.where(valid, filled, jnp.inf),
                               group_ids, num_groups, "min")
        out = jax.lax.pmin(part, axis_name)
        out = jnp.where(jnp.isinf(out) & (out > 0), jnp.nan, out)
    elif agg_name in ("max", "mimmax"):
        part = _group_extremum(jnp.where(valid, filled, -jnp.inf),
                               group_ids, num_groups, "max")
        out = jax.lax.pmax(part, axis_name)
        out = jnp.where(jnp.isinf(out) & (out < 0), jnp.nan, out)
    elif agg_name == "squareSum":
        out = jax.lax.psum(seg(x0 * x0), axis_name)
    elif agg_name == "dev":
        # Two-pass mean-shifted variance, matching the single-chip
        # agg_dev exactly (ops/aggregators.py agg_dev): psum the raw
        # sums for the GLOBAL mean, then psum the locally centered
        # squares.  The one-pass E[x^2]-E[x]^2 form cancels
        # catastrophically in f32 when mean >> std (e.g. counters near
        # 1e7) and diverged from the single-device path.
        s1 = jax.lax.psum(seg(x0), axis_name)
        mean = s1 / jnp.maximum(cnt, 1)                     # [G, B]
        centered = jnp.where(valid, filled - mean[group_ids, :], 0.0)
        m2 = jax.lax.psum(seg(centered * centered), axis_name)
        # population variance (divisor n) to match agg_dev / the
        # reference's own TestAggregators expectations
        var = m2 / jnp.maximum(cnt, 1)
        out = jnp.where(cnt == 1, 0.0, jnp.sqrt(jnp.maximum(var, 0.0)))
    else:
        raise ValueError(f"{agg_name} is not psum-reducible")
    return jnp.where(cnt > 0, out, jnp.nan)


# number of histogram bins for distributed percentile estimation; the
# documented estimator error is (per-group value range) / BINS / 2
PERCENTILE_BINS = 512


def _order_stat_from_hist(counts, cum, lo, width, k):
    """Estimate the k-th (1-based, [G,B]) order statistic from a
    per-cell histogram via grouped-data interpolation: position within
    the rank-crossing bin = (k - cum_before - 0.5) / bin_count."""
    bins = counts.shape[-1]
    kk = jnp.clip(k, 1.0, None)
    idx = jnp.argmax(cum >= kk[..., None], axis=-1)        # [G, B]
    cnt_in = jnp.take_along_axis(counts, idx[..., None],
                                 axis=-1)[..., 0]
    cum_at = jnp.take_along_axis(cum, idx[..., None], axis=-1)[..., 0]
    cum_before = cum_at - cnt_in
    within = jnp.clip((kk - cum_before - 0.5)
                      / jnp.maximum(cnt_in, 1.0), 0.0, 1.0)
    pos = (idx.astype(lo.dtype) + within) / bins
    return lo + pos * width


def _group_percentile_hist(filled, group_ids, num_groups: int, q: float,
                           estimation: str, axis_name: str):
    """Distributed percentile WITHOUT gathering the series axis
    (VERDICT r02 #5): per-shard bucketed histograms + psum, the
    TPU-native translation of the reference's mergeable
    SimpleHistogram.percentile (SimpleHistogram.java:133). Per-device
    memory stays O(S_loc x B + G x B x BINS).

    Bin edges are LINEAR between the group's global min/max per
    (g, b) cell (two cheap psum-combined segment extrema) —
    log-spacing cannot represent arbitrary-sign data. The rank ``h``
    follows the exact path's commons-math3 convention
    (:func:`opentsdb_tpu.ops.aggregators.percentile_along_axis`) and
    the two adjacent order statistics are estimated by grouped-data
    interpolation inside their rank-crossing bins, so the documented
    estimator error is <= the per-cell value range / PERCENTILE_BINS.
    """
    valid = ~jnp.isnan(filled)
    s_loc, b = filled.shape
    from opentsdb_tpu.ops.groupby import _group_extremum, _group_sum
    lo = _group_extremum(jnp.where(valid, filled, jnp.inf),
                         group_ids, num_groups, "min")
    lo = jax.lax.pmin(lo, axis_name)                       # [G, B]
    hi = _group_extremum(jnp.where(valid, filled, -jnp.inf),
                         group_ids, num_groups, "max")
    hi = jax.lax.pmax(hi, axis_name)
    width = jnp.maximum(hi - lo, 1e-30)
    # per-cell bin index under its own group's range
    cell_lo = lo[group_ids]                                # [S_loc, B]
    cell_w = width[group_ids]
    frac = (filled - cell_lo) / cell_w
    bins = jnp.clip((frac * PERCENTILE_BINS).astype(jnp.int32), 0,
                    PERCENTILE_BINS - 1)
    # scatter counts into [G * B * BINS]
    col = jnp.broadcast_to(jnp.arange(b, dtype=jnp.int32)[None, :],
                           filled.shape)
    flat_idx = (group_ids[:, None] * b + col) * PERCENTILE_BINS + bins
    counts = jax.ops.segment_sum(
        valid.reshape(-1).astype(filled.dtype),
        flat_idx.reshape(-1),
        num_segments=num_groups * b * PERCENTILE_BINS)
    counts = jax.lax.psum(
        counts.reshape(num_groups, b, PERCENTILE_BINS), axis_name)
    n = counts.sum(axis=-1)                                # [G, B]
    # rank h per the exact path's estimation convention
    p = q / 100.0
    if estimation == "legacy":
        h = p * (n + 1)
    elif estimation == "r3":
        h = jnp.ceil(p * n - 0.5)
    elif estimation == "upper-median":
        # Aggregators.Median :397 — sorted[n // 2], no interpolation
        h = jnp.floor(n / 2) + 1
    else:  # r7
        h = (n - 1) * p + 1
    h = jnp.clip(h, 1.0, jnp.maximum(n, 1.0))
    h_floor = jnp.floor(h)
    hfrac = h - h_floor
    cum = jnp.cumsum(counts, axis=-1)
    est_lo = _order_stat_from_hist(counts, cum, lo, width, h_floor)
    est_hi = _order_stat_from_hist(counts, cum, lo, width,
                                   jnp.minimum(h_floor + 1, n))
    est = est_lo + hfrac * (est_hi - est_lo)
    # exact degenerate case: zero range
    est = jnp.where(width <= 1e-30, lo, est)
    return jnp.where(n > 0, est, jnp.nan)


def _group_edge_pick(filled, group_ids, num_groups: int, pick: str,
                     s_loc: int, axis_name: str):
    """Distributed first/last: value of the globally lowest/highest
    present series index per (g, b). Each shard reduces to [G, B]
    candidates; the cross-shard combine gathers only those (tiny)."""
    valid = ~jnp.isnan(filled)
    shard = jax.lax.axis_index(axis_name)
    dtype = filled.dtype
    # global series index as float (exact below 2^24 series in f32 —
    # far past the realistic series-axis size of one mesh)
    gidx = (shard * s_loc
            + jnp.arange(s_loc, dtype=jnp.int32))[:, None].astype(dtype)
    gidx = jnp.broadcast_to(gidx, filled.shape)
    from opentsdb_tpu.ops.groupby import _group_extremum, _group_sum
    if pick == "first":
        key = jnp.where(valid, gidx, jnp.inf)
        cand_idx = _group_extremum(key, group_ids, num_groups, "min")
    else:
        key = jnp.where(valid, gidx, -jnp.inf)
        cand_idx = _group_extremum(key, group_ids, num_groups, "max")
    # value at the candidate index: match rows, reduce (match unique)
    match = (gidx == cand_idx[group_ids]) & valid
    cand_val = _group_sum(jnp.where(match, filled, 0.0), group_ids,
                          num_groups)
    # cross-shard: gather [Ds, G, B] candidates, pick best index
    idx_all = jax.lax.all_gather(cand_idx, axis_name, axis=0)
    val_all = jax.lax.all_gather(cand_val, axis_name, axis=0)
    sel = (jnp.argmin(idx_all, axis=0) if pick == "first"
           else jnp.argmax(idx_all, axis=0))
    best = jnp.take_along_axis(idx_all, sel[None], axis=0)[0]
    out = jnp.take_along_axis(val_all, sel[None], axis=0)[0]
    return jnp.where(jnp.isinf(best), jnp.nan, out)


def _group_reduce_distributed(filled, group_ids, num_groups: int,
                              agg_name: str, axis_name: str,
                              s_loc: int | None = None):
    """Cross-shard group reduction for aggregators outside
    REDUCIBLE_AGGS, keeping per-device memory sublinear in the global
    series count wherever the math allows:

    - percentiles (p*/ep*) and median: bucketed-histogram psum
      (documented estimator error, see _group_percentile_hist);
    - first/last: per-shard edge candidates + tiny [Ds, G, B] gather;
    - diff/multiply (rare): all_gather fallback — the only remaining
      full-axis gathers.
    """
    agg = aggs_mod.get(agg_name)
    if (agg.percentile is not None or agg_name == "median") and \
            _hist_eligible(num_groups, filled.shape[-1]):
        q = agg.percentile if agg.percentile is not None else 50.0
        est = ("upper-median" if agg_name == "median"
               else getattr(agg, "estimation", None) or "r7")
        return _group_percentile_hist(filled, group_ids, num_groups,
                                      q, est, axis_name)
    if agg_name in ("first", "last") and s_loc is not None:
        return _group_edge_pick(filled, group_ids, num_groups,
                                agg_name, s_loc, axis_name)
    full = jax.lax.all_gather(filled, axis_name, axis=0, tiled=True)
    gids_full = jax.lax.all_gather(group_ids, axis_name, axis=0,
                                   tiled=True)
    from opentsdb_tpu.ops.groupby import _group_reduce
    return _group_reduce(full, gids_full, num_groups, agg_name)


# ---------------------------------------------------------------------------
# the sharded step
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ShardedBatch:
    """Host-prepared, device-ready inputs for the sharded pipeline.

    Shapes (Ds = series shards, Dt = time shards):
    - values/series_idx/bucket_idx: [Ds, Dt, Npad] — per-shard point
      lists, padded with bucket_idx == B_loc (a dummy bucket slot)
    - bucket_ts: [B_pad] (split over 'time')
    - group_ids: [Ds * S_loc] (split over 'series'), dummy group == G
    """
    values: np.ndarray
    series_idx: np.ndarray
    bucket_idx: np.ndarray
    bucket_ts: np.ndarray
    group_ids: np.ndarray
    s_loc: int
    b_loc: int
    num_groups: int  # real groups (dummy excluded)


def build_sharded_step(mesh: Mesh, spec: PipelineSpec, s_loc: int,
                       b_loc: int):
    """Compile the multi-chip query step for the given mesh and shapes.

    Returns a jitted fn(values, series_idx, bucket_idx, bucket_ts,
    group_ids, rate_params, fill_value) -> (result[G+1, B_pad],
    emit[G+1, B_pad]) with result sharded over 'time'.
    """
    n_series_shards, n_time_shards = (mesh.shape["series"],
                                      mesh.shape["time"])
    agg = aggs_mod.get(spec.agg_name)
    interp_mode = agg.interpolation.value
    g_padded = spec.num_groups + 1  # trailing dummy group for padding

    def step(values, series_idx, bucket_idx, bucket_ts, group_ids,
             rate_params, fill_value):
        # local blocks: [1, 1, Npad] / [B_loc] / [S_loc]
        vals = values.reshape(-1)
        sidx = series_idx.reshape(-1)
        bidx = bucket_idx.reshape(-1)
        bts = bucket_ts
        gids = group_ids

        # 1. local bucketize into [S_loc, B_loc + 1] (last = padding)
        grid, cnt = ds_mod.bucketize(vals, sidx, bidx, s_loc, b_loc + 1,
                                     spec.ds_function)
        grid = grid[:, :b_loc]
        cnt = cnt[:, :b_loc]
        has_data = cnt > 0

        if spec.fill_policy == ds_mod.FillPolicy.ZERO:
            grid = jnp.where(jnp.isnan(grid), 0.0, grid)
            has_data = jnp.ones_like(has_data)
        elif spec.fill_policy == ds_mod.FillPolicy.SCALAR:
            grid = jnp.where(jnp.isnan(grid), fill_value, grid)
            has_data = jnp.ones_like(has_data)

        # 2. rate with cross-block carry over the 'time' axis
        if spec.rate:
            (lv, lt, lp), _ = _block_boundaries(grid, bts)
            cv, ct, cp = _scan_boundary(lv, lt, lp, "time",
                                        n_time_shards, reverse=False)
            counter_max, reset_value = rate_params
            grid = _rate_with_boundary(
                grid, bts, spec.rate_counter, counter_max, reset_value,
                spec.rate_drop_resets, cv, ct, cp)
            has_data = has_data & ~jnp.isnan(grid)

        if spec.emit_raw:
            return grid, has_data

        # 3. interpolation fill with halo carries both directions.
        # Only fill NONE leaves true gaps that interpolate at merge;
        # NAN/NULL emit explicit NaN points that the reference's merge
        # loop skips WITHOUT interpolating, and ZERO/SCALAR were
        # substituted in step 1 (mirrors pipeline._finish_pipeline).
        if spec.fill_policy == ds_mod.FillPolicy.NONE:
            (lv, lt, lp), (fv, ft, fp) = _block_boundaries(grid, bts)
            pv, pt, pp = _scan_boundary(lv, lt, lp, "time",
                                        n_time_shards, reverse=False)
            nv, nt, npp = _scan_boundary(fv, ft, fp, "time",
                                         n_time_shards, reverse=True)
            filled = _fill_with_boundaries(grid, bts, interp_mode,
                                           pv, pt, pp, nv, nt, npp)
        else:
            filled = grid

        # 4. group aggregation across the 'series' axis
        if spec.agg_name in REDUCIBLE_AGGS:
            result = _group_reduce_psum(filled, gids, g_padded,
                                        spec.agg_name, "series")
        else:
            result = _group_reduce_distributed(
                filled, gids, g_padded, spec.agg_name, "series",
                s_loc=s_loc)

        if spec.fill_policy == ds_mod.FillPolicy.NONE:
            # segment_sum: empty segments give 0 (segment_max gives INT_MIN
            # which breaks the cross-shard psum)
            emit = jax.lax.psum(
                jax.ops.segment_sum(has_data.astype(jnp.int32), gids,
                                    num_segments=g_padded), "series") > 0
        else:
            emit = jnp.ones((g_padded, b_loc), dtype=bool)
        return result, emit

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(P("series", "time", None), P("series", "time", None),
                  P("series", "time", None), P("time"), P("series"),
                  P(), P()),
        out_specs=(P(None, "time"), P(None, "time"))
        if not spec.emit_raw else (P("series", "time"),
                                   P("series", "time")),
        check_vma=False)
    return jax.jit(sharded)


# ---------------------------------------------------------------------------
# host-side sharding prep
# ---------------------------------------------------------------------------

def prepare_sharded_batch(values: np.ndarray, series_idx: np.ndarray,
                          bucket_idx: np.ndarray, bucket_ts: np.ndarray,
                          group_ids: np.ndarray, num_series: int,
                          num_groups: int, n_series_shards: int,
                          n_time_shards: int) -> ShardedBatch:
    """Partition a flat point batch onto the mesh.

    Series land on series-shards in contiguous *blocks* (shard =
    series_idx // s_loc): after an all_gather over the series axis the
    rows come back in natural series order, which the order-sensitive
    aggregators (first/last/diff pick the lowest/highest series index,
    matching the reference's span order) depend on. Buckets split into
    contiguous time blocks. Point lists are padded per (Ds, Dt) cell to
    the max cell population.
    """
    s_loc = -(-num_series // n_series_shards)
    b = len(bucket_ts)
    b_loc = -(-b // n_time_shards)
    b_pad = b_loc * n_time_shards

    # pad bucket_ts monotonically so halo timestamps stay ordered
    bucket_ts = _pad_bts_tail(bucket_ts, b_pad)

    series_shard = series_idx // s_loc
    local_series = series_idx % s_loc
    time_shard = bucket_idx // b_loc
    local_bucket = bucket_idx % b_loc

    # per-cell padding
    cell_id = series_shard.astype(np.int64) * n_time_shards + time_shard
    order = np.argsort(cell_id, kind="stable")
    counts = np.bincount(cell_id, minlength=n_series_shards * n_time_shards)
    npad = max(int(counts.max()), 1) if len(cell_id) else 1
    ds, dt = n_series_shards, n_time_shards
    pvals = np.zeros((ds, dt, npad), dtype=values.dtype)
    psidx = np.zeros((ds, dt, npad), dtype=np.int32)
    pbidx = np.full((ds, dt, npad), b_loc, dtype=np.int32)  # dummy bucket
    pos = 0
    for cell in range(ds * dt):
        c = counts[cell]
        if c == 0:
            continue
        sel = order[pos:pos + c]
        i, j = divmod(cell, dt)
        pvals[i, j, :c] = values[sel]
        psidx[i, j, :c] = local_series[sel]
        pbidx[i, j, :c] = local_bucket[sel]
        pos += c

    # group ids: [Ds * S_loc]; block layout keeps natural series order
    # (row shard*s_loc+loc == global sid); padding -> dummy group G
    gids = np.full(ds * s_loc, num_groups, dtype=np.int32)
    gids[:num_series] = group_ids

    return ShardedBatch(pvals, psidx, pbidx,
                        bucket_ts.astype(np.int64), gids, s_loc, b_loc,
                        num_groups)


@lru_cache(maxsize=128)
def _compiled_step(mesh: Mesh, spec: PipelineSpec, s_loc: int,
                   b_loc: int):
    """Per-(mesh, spec, shape) cache: build_sharded_step returns a new
    closure every call, so jax.jit alone would re-trace every query."""
    return build_sharded_step(mesh, spec, s_loc, b_loc)


# ---------------------------------------------------------------------------
# blocked (streaming) execution over the mesh — VERDICT r02 #4: the
# carry-chained block scan as a shard_map program, so over-budget long
# ranges keep the fan-out instead of degrading to one device
# ---------------------------------------------------------------------------

def _combine_carry(scan_v, scan_t, scan_p, host_v, host_t, host_p):
    """Nearest-present = the intra-block scan when it found one, else
    the host-chained carry from earlier blocks."""
    v = jnp.where(scan_p, scan_v, host_v)
    t = jnp.where(scan_p, scan_t, host_t)
    return v, t, scan_p | host_p


def _last_across_time(v, t, p, n_time_shards: int):
    """The block-global LAST present candidate per series: each time
    shard contributes its local last; the highest-indexed present shard
    wins. all_gather is fine — candidates are [S_loc] vectors."""
    if n_time_shards == 1:
        return v, t, p
    vs = jax.lax.all_gather(v, "time", axis=0)   # [Dt, S_loc]
    ts = jax.lax.all_gather(t, "time", axis=0)
    ps = jax.lax.all_gather(p, "time", axis=0)
    # scan shards from last to first, keeping the first present
    out_v, out_t, out_p = vs[-1], ts[-1], ps[-1]
    for i in range(n_time_shards - 2, -1, -1):
        out_v = jnp.where(out_p, out_v, vs[i])
        out_t = jnp.where(out_p, out_t, ts[i])
        out_p = out_p | ps[i]
    return out_v, out_t, out_p


def _first_across_time(v, t, p, n_time_shards: int):
    if n_time_shards == 1:
        return v, t, p
    vs = jax.lax.all_gather(v, "time", axis=0)
    ts = jax.lax.all_gather(t, "time", axis=0)
    ps = jax.lax.all_gather(p, "time", axis=0)
    out_v, out_t, out_p = vs[0], ts[0], ps[0]
    for i in range(1, n_time_shards):
        out_v = jnp.where(out_p, out_v, vs[i])
        out_t = jnp.where(out_p, out_t, ts[i])
        out_p = out_p | ps[i]
    return out_v, out_t, out_p


def build_sharded_blocked_step(mesh: Mesh, spec: PipelineSpec,
                               s_loc: int, b_loc: int,
                               summary_only: bool = False):
    """One time-BLOCK of the streaming scan, sharded over the mesh.

    Mirrors ``ops.blocked``'s per-block work (bucketize -> fill policy
    -> rate -> interpolation fill -> group reduce) with three kinds of
    carries composed:
    - intra-block, across 'time' shards: ppermute prefix scans
      (:func:`_scan_boundary`), as in :func:`build_sharded_step`;
    - across blocks: host-chained (prev-rate, prev-fill, next-fill)
      [S]-vectors fed in sharded over 'series' and combined wherever
      the intra-block scan found nothing;
    - outgoing: the block's own boundary summaries (pre-rate last,
      post-rate last, post-rate first), reduced across 'time' shards,
      returned sharded over 'series' for the host to chain.

    ``summary_only`` builds the light pass-1 variant: bucketize +
    rate + boundary summaries with the fill/group-reduce stages
    omitted (the two-pass structure of ``ops.blocked``).

    Returns fn(values, sidx, bidx, bts, gids, rate_params, fill_value,
    rate_carry3, prev_carry3, next_carry3) ->
    (result[G+1, b_pad], emit, pre_last3, post_last3, post_first3),
    with result/emit zero-size placeholders in summary mode.
    """
    n_time_shards = mesh.shape["time"]
    agg = aggs_mod.get(spec.agg_name)
    interp_mode = agg.interpolation.value
    g_padded = spec.num_groups + 1

    def step(values, series_idx, bucket_idx, bucket_ts, group_ids,
             rate_params, fill_value, rate_carry, prev_carry,
             next_carry):
        vals = values.reshape(-1)
        sidx = series_idx.reshape(-1)
        bidx = bucket_idx.reshape(-1)
        bts = bucket_ts
        gids = group_ids

        grid, cnt = ds_mod.bucketize(vals, sidx, bidx, s_loc, b_loc + 1,
                                     spec.ds_function)
        grid = grid[:, :b_loc]
        cnt = cnt[:, :b_loc]
        has_data = cnt > 0
        if spec.fill_policy == ds_mod.FillPolicy.ZERO:
            grid = jnp.where(jnp.isnan(grid), 0.0, grid)
            has_data = jnp.ones_like(has_data)
        elif spec.fill_policy == ds_mod.FillPolicy.SCALAR:
            grid = jnp.where(jnp.isnan(grid), fill_value, grid)
            has_data = jnp.ones_like(has_data)

        # pre-rate block-last summary (chains the NEXT block's rate)
        (plv, plt, plp), _ = _block_boundaries(grid, bts)
        pre_last = _last_across_time(plv, plt, plp, n_time_shards)

        if spec.rate:
            (lv, lt, lp), _ = _block_boundaries(grid, bts)
            sv, st, sp = _scan_boundary(lv, lt, lp, "time",
                                        n_time_shards, reverse=False)
            cv, ct, cp = _combine_carry(sv, st, sp, *rate_carry)
            counter_max, reset_value = rate_params
            grid = _rate_with_boundary(
                grid, bts, spec.rate_counter, counter_max, reset_value,
                spec.rate_drop_resets, cv, ct, cp)
            has_data = has_data & ~jnp.isnan(grid)

        # post-rate boundary summaries for the host chain
        (lv, lt, lp), (fv, ft, fp) = _block_boundaries(grid, bts)
        post_last = _last_across_time(lv, lt, lp, n_time_shards)
        post_first = _first_across_time(fv, ft, fp, n_time_shards)

        if summary_only:
            z = jnp.zeros((g_padded, 0), grid.dtype)
            return (z, z.astype(bool), pre_last, post_last,
                    post_first)

        if spec.fill_policy == ds_mod.FillPolicy.NONE:
            pv, pt, pp = _scan_boundary(lv, lt, lp, "time",
                                        n_time_shards, reverse=False)
            nv, nt, npp = _scan_boundary(fv, ft, fp, "time",
                                         n_time_shards, reverse=True)
            pv, pt, pp = _combine_carry(pv, pt, pp, *prev_carry)
            nv, nt, npp = _combine_carry(nv, nt, npp, *next_carry)
            filled = _fill_with_boundaries(grid, bts, interp_mode,
                                           pv, pt, pp, nv, nt, npp)
        else:
            filled = grid

        if spec.agg_name in REDUCIBLE_AGGS:
            result = _group_reduce_psum(filled, gids, g_padded,
                                        spec.agg_name, "series")
        else:
            result = _group_reduce_distributed(
                filled, gids, g_padded, spec.agg_name, "series",
                s_loc=s_loc)

        if spec.fill_policy == ds_mod.FillPolicy.NONE:
            emit = jax.lax.psum(
                jax.ops.segment_sum(has_data.astype(jnp.int32), gids,
                                    num_segments=g_padded),
                "series") > 0
        else:
            emit = jnp.ones((g_padded, b_loc), dtype=bool)
        return result, emit, pre_last, post_last, post_first

    c3 = (P("series"), P("series"), P("series"))
    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(P("series", "time", None), P("series", "time", None),
                  P("series", "time", None), P("time"), P("series"),
                  P(), P(), c3, c3, c3),
        out_specs=(P(None, "time"), P(None, "time"), c3, c3, c3),
        check_vma=False)
    return jax.jit(sharded)


@lru_cache(maxsize=64)
def _compiled_blocked_step(mesh: Mesh, spec: PipelineSpec, s_loc: int,
                           b_loc: int, summary_only: bool = False):
    return build_sharded_blocked_step(mesh, spec, s_loc, b_loc,
                                      summary_only)


def execute_blocked_sharded(mesh: Mesh, batch_values: np.ndarray,
                            series_idx: np.ndarray,
                            bucket_idx: np.ndarray,
                            bucket_ts: np.ndarray,
                            group_ids: np.ndarray, spec: PipelineSpec,
                            rate_options=None, dtype=None,
                            block_buckets: int | None = None
                            ) -> tuple[np.ndarray, np.ndarray]:
    """Streaming twin of :func:`opentsdb_tpu.ops.blocked.execute_blocked`
    running every block over the mesh: per-DEVICE memory is
    O(S_loc x block), so the budget scales with the fan-out instead of
    collapsing to one device (ref: the 20 SaltScanners stream
    concurrently, SaltScanner.java:463-536).

    Same two-pass structure as ``execute_blocked``: interpolating
    aggregators need each block's NEXT-present carry accumulated over
    ALL later blocks, so a light summary pass (bucketize + rate +
    boundaries, no fill/reduce) sweeps forward first and a backward
    host scan chains the next-carries; non-interpolating aggregators
    skip pass 1 entirely (a single full sweep suffices)."""
    from opentsdb_tpu.ops.blocked import _empty_carry, _merge_carry
    from opentsdb_tpu.ops.pipeline import device_bucket_ts
    from opentsdb_tpu.ops.rate import RateOptions
    if spec.emit_raw:
        raise ValueError("blocked execution aggregates; emit_raw "
                         "queries stream per-series instead")
    if dtype is None:
        dtype = jnp.float64 if jax.config.read("jax_enable_x64") \
            else jnp.float32
    np_dtype = np.dtype(dtype)
    ro = rate_options or RateOptions()
    s, b, g = spec.num_series, spec.num_buckets, spec.num_groups
    ds_shards = mesh.shape["series"]
    dt_shards = mesh.shape["time"]
    s_loc = -(-s // ds_shards)
    s_pad = s_loc * ds_shards
    from opentsdb_tpu.ops.blocked import pick_block_buckets
    # per-device cells = (s_pad/Ds) x (bb/Dt): the global budget for
    # pick_block_buckets scales by the whole mesh
    bb = block_buckets or pick_block_buckets(
        s_pad, b,
        DEFAULT_CELL_BUDGET_PER_DEVICE * ds_shards * dt_shards)
    # block size must split evenly over the time shards
    bb = max(dt_shards, (bb // dt_shards) * dt_shards)
    rate_params = (jnp.asarray(ro.counter_max, dtype),
                   jnp.asarray(ro.reset_value, dtype))
    fv = jnp.asarray(spec.fill_value, dtype)

    bucket_idx = np.asarray(bucket_idx)
    order = np.argsort(bucket_idx, kind="stable")
    sv_ = np.asarray(batch_values, dtype=np_dtype)[order]
    ssi = np.asarray(series_idx, dtype=np.int32)[order]
    sbi = bucket_idx[order]
    dev_bts = np.asarray(device_bucket_ts(bucket_ts))
    starts = [int(np.searchsorted(sbi, b0)) for b0 in range(0, b, bb)]
    starts.append(len(sbi))
    blocks = [(b0, min(b0 + bb, b), starts[i], starts[i + 1])
              for i, b0 in enumerate(range(0, b, bb))]

    agg = aggs_mod.get(spec.agg_name)
    needs_next = agg.interpolation.value in ("lerp", "max", "min")
    b_loc = bb // dt_shards
    step = _compiled_blocked_step(mesh, spec, s_loc, b_loc)

    gids_full = np.full(s_pad, g, dtype=np.int32)
    gids_full[:s] = group_ids

    # memoized per-block batches: the two-pass sweep (needs_next) must
    # not repeat the host-side per-cell packing loop — the memo is the
    # same order of memory as the already-resident sorted point arrays
    _block_memo: dict[int, ShardedBatch] = {}

    def shard_block(i, blk):
        sb = _block_memo.get(i)
        if sb is None:
            b0, b1, p0, p1 = blk
            sb = _block_memo[i] = prepare_sharded_batch(
                sv_[p0:p1], ssi[p0:p1], sbi[p0:p1] - b0,
                _pad_bts_tail(dev_bts[b0:b1], bb),
                gids_full, s_pad, g, ds_shards, dt_shards)
        return sb

    # explicit global uploads so the path works when the mesh spans
    # processes (plain jnp.asarray/jit auto-put would hit device_put's
    # cross-process value check — see distributed.put_global)
    from jax.sharding import NamedSharding
    from opentsdb_tpu.parallel.distributed import put_global
    sh3 = NamedSharding(mesh, P("series", "time", None))
    sht = NamedSharding(mesh, P("time"))
    shs = NamedSharding(mesh, P("series"))

    def carry_dev(c):
        return tuple(put_global(np.asarray(x), shs) for x in c)

    def run(i, blk, which, rate_carry, prev_carry, next_carry):
        sb = shard_block(i, blk)
        return which(
            put_global(np.asarray(sb.values, np_dtype), sh3),
            put_global(sb.series_idx, sh3),
            put_global(sb.bucket_idx, sh3),
            put_global(sb.bucket_ts, sht),
            put_global(gids_full, shs), rate_params, fv,
            carry_dev(rate_carry), carry_dev(prev_carry),
            carry_dev(next_carry))

    empty = _empty_carry(s_pad, np_dtype)
    n_blocks = len(blocks)
    next_carries = [empty] * n_blocks
    if needs_next and n_blocks > 1:
        # pass 1 (light): forward sweep collecting each block's
        # first-present summary, then a backward host scan accumulating
        # the next-carry over ALL later blocks (a gap spanning whole
        # blocks must still interpolate; ops.blocked does the same)
        sstep = _compiled_blocked_step(mesh, spec, s_loc, b_loc,
                                       summary_only=True)
        firsts = []
        rate_carry = empty
        for i, blk in enumerate(blocks):
            _, _, pre_last, _, post_first = run(i, blk, sstep,
                                                rate_carry, empty,
                                                empty)
            firsts.append(tuple(_to_host(x) for x in post_first))
            if spec.rate:
                rate_carry = _merge_carry(
                    tuple(_to_host(x) for x in pre_last), rate_carry)
        nc = empty
        for i in range(n_blocks - 1, -1, -1):
            next_carries[i] = nc
            nc = _merge_carry(firsts[i], nc)

    # pass 2: full sweep with both carries chained
    out = np.empty((g, b), dtype=np_dtype)
    emit_out = np.empty((g, b), dtype=bool)
    rate_carry = empty
    prev_carry = empty
    for i, blk in enumerate(blocks):
        res, emit, pre_last, post_last, _ = run(
            i, blk, step, rate_carry, prev_carry, next_carries[i])
        b0, b1 = blk[0], blk[1]
        nb = b1 - b0
        out[:, b0:b1] = _to_host(res)[:g, :nb]
        emit_out[:, b0:b1] = _to_host(emit)[:g, :nb]
        if spec.rate:
            rate_carry = _merge_carry(
                tuple(_to_host(x) for x in pre_last), rate_carry)
        prev_carry = _merge_carry(
            tuple(_to_host(x) for x in post_last), prev_carry)
    return out, emit_out


# per-DEVICE cell budget for the sharded blocked scan (f32 cells)
DEFAULT_CELL_BUDGET_PER_DEVICE = 1 << 26


def sharded_device_args(mesh: Mesh, batch: ShardedBatch, dtype):
    """Upload a ShardedBatch with its mesh shardings attached, so a
    repeat query can reuse the HBM-resident copies (the mesh twin of
    the single-device prepared-batch cache)."""
    from jax.sharding import NamedSharding
    from opentsdb_tpu.ops.pipeline import device_bucket_ts
    from opentsdb_tpu.parallel.distributed import put_global as put
    s3 = NamedSharding(mesh, P("series", "time", None))
    return (put(np.asarray(batch.values, np.dtype(dtype)), s3),
            put(batch.series_idx, s3),
            put(batch.bucket_idx, s3),
            put(device_bucket_ts(batch.bucket_ts),
                NamedSharding(mesh, P("time"))),
            put(batch.group_ids,
                NamedSharding(mesh, P("series"))))


def run_sharded_device(mesh: Mesh, spec: PipelineSpec, device_args,
                       s_loc: int, b_loc: int, num_groups: int,
                       rate_options=None, dtype=None):
    """Execute the sharded step over pre-uploaded device args."""
    from opentsdb_tpu.ops.rate import RateOptions
    if dtype is None:
        dtype = jnp.float64 if jax.config.read("jax_enable_x64") \
            else jnp.float32
    ro = rate_options or RateOptions()
    step = _compiled_step(mesh, spec, s_loc, b_loc)
    rate_params = (jnp.asarray(ro.counter_max, dtype),
                   jnp.asarray(ro.reset_value, dtype))
    result, emit = step(*device_args, rate_params,
                        jnp.asarray(spec.fill_value, dtype))
    result = _to_host(result)
    emit = _to_host(emit)
    b = spec.num_buckets
    return result[:num_groups, :b], emit[:num_groups, :b]


def run_sharded(mesh: Mesh, spec: PipelineSpec, batch: ShardedBatch,
                rate_options=None, dtype=None):
    """Execute the sharded step; returns host (result[G,B], emit[G,B])
    trimmed of padding."""
    if dtype is None:
        dtype = jnp.float64 if jax.config.read("jax_enable_x64") \
            else jnp.float32
    # relative ms offsets: absolute epoch-ms int64 would truncate on
    # TPU (no device int64); the kernels only use ts differences
    args = sharded_device_args(mesh, batch, dtype)
    return run_sharded_device(mesh, spec, args, batch.s_loc,
                              batch.b_loc, batch.num_groups,
                              rate_options, dtype)


# ---------------------------------------------------------------------------
# grid-tail step: storage-side bucketized [S, B] grids on the mesh
# (fill -> rate -> interpolate -> reduce; no bucketize)
# ---------------------------------------------------------------------------

def build_sharded_grid_step(mesh: Mesh, spec: PipelineSpec, s_loc: int,
                            b_loc: int):
    """Steps 2-4 of :func:`build_sharded_step` over a pre-bucketized
    grid sharded P('series', 'time') — the mesh twin of
    :func:`opentsdb_tpu.ops.pipeline.run_pipeline_grid`, so the
    storage engine's native [S, B] reduction feeds the mesh directly
    instead of being flattened back to points and re-bucketized."""
    n_time_shards = mesh.shape["time"]
    agg = aggs_mod.get(spec.agg_name)
    interp_mode = agg.interpolation.value
    g_padded = spec.num_groups + 1

    def step(grid, has_data, bucket_ts, group_ids, rate_params,
             fill_value):
        bts = bucket_ts
        gids = group_ids
        if spec.fill_policy == ds_mod.FillPolicy.ZERO:
            grid = jnp.where(jnp.isnan(grid), 0.0, grid)
            has_data = jnp.ones_like(has_data)
        elif spec.fill_policy == ds_mod.FillPolicy.SCALAR:
            grid = jnp.where(jnp.isnan(grid), fill_value, grid)
            has_data = jnp.ones_like(has_data)
        if spec.rate:
            (lv, lt, lp), _ = _block_boundaries(grid, bts)
            cv, ct, cp = _scan_boundary(lv, lt, lp, "time",
                                        n_time_shards, reverse=False)
            counter_max, reset_value = rate_params
            grid = _rate_with_boundary(
                grid, bts, spec.rate_counter, counter_max, reset_value,
                spec.rate_drop_resets, cv, ct, cp)
            has_data = has_data & ~jnp.isnan(grid)
        if spec.emit_raw:
            return grid, has_data
        if spec.fill_policy == ds_mod.FillPolicy.NONE:
            (lv, lt, lp), (fv, ft, fp) = _block_boundaries(grid, bts)
            pv, pt, pp = _scan_boundary(lv, lt, lp, "time",
                                        n_time_shards, reverse=False)
            nv, nt, npp = _scan_boundary(fv, ft, fp, "time",
                                         n_time_shards, reverse=True)
            filled = _fill_with_boundaries(grid, bts, interp_mode,
                                           pv, pt, pp, nv, nt, npp)
        else:
            filled = grid
        if spec.agg_name in REDUCIBLE_AGGS:
            result = _group_reduce_psum(filled, gids, g_padded,
                                        spec.agg_name, "series")
        else:
            result = _group_reduce_distributed(
                filled, gids, g_padded, spec.agg_name, "series",
                s_loc=s_loc)
        if spec.fill_policy == ds_mod.FillPolicy.NONE:
            emit = jax.lax.psum(
                jax.ops.segment_sum(has_data.astype(jnp.int32), gids,
                                    num_segments=g_padded),
                "series") > 0
        else:
            emit = jnp.ones((g_padded, b_loc), dtype=bool)
        return result, emit

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(P("series", "time"), P("series", "time"), P("time"),
                  P("series"), P(), P()),
        out_specs=(P(None, "time"), P(None, "time"))
        if not spec.emit_raw else (P("series", "time"),
                                   P("series", "time")),
        check_vma=False)
    return jax.jit(sharded)


@lru_cache(maxsize=128)
def _compiled_grid_step(mesh: Mesh, spec: PipelineSpec, s_loc: int,
                        b_loc: int):
    return build_sharded_grid_step(mesh, spec, s_loc, b_loc)


def prepare_sharded_grid(mesh: Mesh, grid: np.ndarray,
                         has_data: np.ndarray, bucket_ts: np.ndarray,
                         dtype=None):
    """Pad + upload a host [S, B] grid with mesh shardings. Returns
    (data_args, s_loc, b_loc, s_pad) for :func:`run_sharded_grid`. The
    device arrays are what the engine's grid cache holds under a mesh
    — HBM-resident AND pre-sharded. Group ids are deliberately NOT
    part of them: the same data answers queries with different
    group-bys (see :func:`sharded_grid_gids`)."""
    from jax.sharding import NamedSharding
    from opentsdb_tpu.ops.pipeline import device_bucket_ts
    if dtype is None:
        dtype = jnp.float64 if jax.config.read("jax_enable_x64") \
            else jnp.float32
    ds_, dt_ = mesh.shape["series"], mesh.shape["time"]
    s, b = grid.shape
    s_loc = -(-s // ds_)
    b_loc = -(-b // dt_)
    s_pad, b_pad = s_loc * ds_, b_loc * dt_
    g = np.full((s_pad, b_pad), np.nan, dtype=np.dtype(dtype))
    g[:s, :b] = grid
    h = np.zeros((s_pad, b_pad), dtype=bool)
    h[:s, :b] = has_data
    bts = _pad_bts_tail(np.asarray(bucket_ts, dtype=np.int64), b_pad)
    from opentsdb_tpu.parallel.distributed import put_global as put
    s2 = NamedSharding(mesh, P("series", "time"))
    args = (put(g, s2), put(h, s2),
            put(device_bucket_ts(bts),
                NamedSharding(mesh, P("time"))))
    return args, s_loc, b_loc, s_pad


def sharded_grid_gids(mesh: Mesh, group_ids: np.ndarray, s_pad: int,
                      num_groups: int):
    """Per-query group-id upload (tiny [S_pad] vector)."""
    from jax.sharding import NamedSharding
    from opentsdb_tpu.parallel.distributed import put_global
    gids = np.full(s_pad, num_groups, dtype=np.int32)
    gids[:len(group_ids)] = group_ids
    return put_global(gids, NamedSharding(mesh, P("series")))


def run_sharded_grid(mesh: Mesh, spec: PipelineSpec, device_args,
                     s_loc: int, b_loc: int, num_groups: int,
                     rate_options=None, dtype=None):
    """Execute the grid-tail step over pre-uploaded sharded grids."""
    from opentsdb_tpu.ops.rate import RateOptions
    if dtype is None:
        dtype = jnp.float64 if jax.config.read("jax_enable_x64") \
            else jnp.float32
    ro = rate_options or RateOptions()
    step = _compiled_grid_step(mesh, spec, s_loc, b_loc)
    rate_params = (jnp.asarray(ro.counter_max, dtype),
                   jnp.asarray(ro.reset_value, dtype))
    result, emit = step(*device_args, rate_params,
                        jnp.asarray(spec.fill_value, dtype))
    result = _to_host(result)
    emit = _to_host(emit)
    b = spec.num_buckets
    rows = spec.num_series if spec.emit_raw else num_groups
    return result[:rows, :b], emit[:rows, :b]
