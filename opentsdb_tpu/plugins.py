"""The 12 plugin extension points of the TSD (ref: SURVEY.md §2.4,
``src/tsd/RTPublisher.java:39``, ``StorageExceptionHandler.java:31``,
``RpcPlugin.java:36``, ``HttpRpcPlugin.java:40``,
``HttpSerializer.java:93``, ``src/core/WriteableDataPointFilterPlugin``,
``src/uid/UniqueIdFilterPlugin``, ``src/tsd/MetaDataCache.java:29``,
``src/tools/StartupPlugin``, ``src/search/SearchPlugin.java:51``,
``src/auth/Authentication.java:36``, ``HistogramDataPointCodec``).

All plugins load through :mod:`opentsdb_tpu.utils.plugin` (dotted-path
classes in config, the ServiceLoader analogue) and share the reference
ABI lifecycle: no-arg construction, ``initialize(tsdb)``, ``shutdown()``,
``version()``, ``collect_stats(collector)``.

The histogram codec ABI lives in :mod:`opentsdb_tpu.core.histogram`
(HistogramCodec) and the auth ABI in :mod:`opentsdb_tpu.auth.simple`;
the other ten are defined here.
"""

from __future__ import annotations

from typing import Any


class Plugin:
    """Shared lifecycle (every reference plugin ABI repeats these)."""

    def initialize(self, tsdb) -> None:  # noqa: B027
        """Called once after construction; raise to abort startup
        (ref: each ABI's initialize contract)."""

    def shutdown(self) -> None:  # noqa: B027
        """Graceful shutdown hook."""

    def version(self) -> str:
        return "2.4.0"

    def collect_stats(self, collector) -> None:  # noqa: B027
        """Report plugin metrics into /api/stats."""


class RTPublisher(Plugin):
    """Real-time datapoint fan-out (ref: RTPublisher.java:39): every
    stored point / annotation is offered to the publisher (e.g. to feed
    a message bus). Failures must not block the write path."""

    def publish_data_point(self, metric: str, timestamp: int,
                           value, tags: dict[str, str],
                           tsuid: str) -> None:
        raise NotImplementedError

    def publish_aggregate_point(self, metric: str, timestamp: int,
                                value, tags: dict[str, str],
                                tsuid: str) -> None:  # noqa: B027
        """Rollup points (ref: RTPublisher.publishAggregatePoint)."""

    def publish_histogram_point(self, metric: str, timestamp: int,
                                raw_data: bytes,
                                tags: dict[str, str],
                                tsuid: str) -> None:  # noqa: B027
        pass

    def publish_annotation(self, annotation) -> None:  # noqa: B027
        pass


class StorageExceptionHandler(Plugin):
    """Requeue datapoints dropped by storage errors
    (ref: StorageExceptionHandler.java:31 handleError)."""

    def handle_error(self, datapoint: dict, error: Exception) -> None:
        raise NotImplementedError


class WriteableDataPointFilterPlugin(Plugin):
    """Gate/mutate incoming datapoints before storage
    (ref: src/core/WriteableDataPointFilterPlugin.java;
    TSDB.java:1262 allowDataPoint call site)."""

    def filter_data_points(self) -> bool:
        """Whether this filter wants the per-point callback."""
        return True

    def allow_data_point(self, metric: str, timestamp: int, value,
                         tags: dict[str, str]) -> bool:
        raise NotImplementedError


class UniqueIdFilterPlugin(Plugin):
    """Gate UID auto-assignment (ref: src/uid/UniqueIdFilterPlugin.java):
    called before a never-seen metric/tagk/tagv is given a UID."""

    def fill_uid_cache(self) -> bool:
        return True

    def allow_uid_assignment(self, kind: str, value: str, metric: str,
                             tags: dict[str, str] | None) -> bool:
        raise NotImplementedError


class UniqueIdWhitelistFilter(UniqueIdFilterPlugin):
    """Regex whitelist implementation
    (ref: src/uid/UniqueIdWhitelistFilter.java:37): comma-separated
    patterns per UID kind; a value must match at least one pattern."""

    def initialize(self, tsdb) -> None:
        import re
        cfg = tsdb.config if hasattr(tsdb, "config") else tsdb
        self._patterns = {}
        for kind, key in (("metric", "tsd.uidfilter.metric_patterns"),
                          ("tagk", "tsd.uidfilter.tagk_patterns"),
                          ("tagv", "tsd.uidfilter.tagv_patterns")):
            raw = cfg.get_string(key, "")
            self._patterns[kind] = [re.compile(p.strip())
                                    for p in raw.split(",") if p.strip()]

    def allow_uid_assignment(self, kind: str, value: str, metric: str,
                             tags: dict[str, str] | None) -> bool:
        pats = self._patterns.get(kind) or []
        if not pats:
            return True
        return any(p.search(value) for p in pats)


class MetaDataCache(Plugin):
    """External TSMeta counter/cache service bridge
    (ref: src/tsd/MetaDataCache.java:29); called on every write instead
    of the built-in meta tracking when configured."""

    def increment_and_get_counter(self, tsuid: str) -> None:
        raise NotImplementedError


class StartupPlugin(Plugin):
    """Hooks around daemon boot (ref: src/tools/StartupPlugin.java;
    TSDMain.java:251): initialize(config) runs before the TSDB exists,
    set_ready(tsdb) once the server socket is bound."""

    def initialize(self, config) -> None:  # noqa: B027
        pass

    def set_ready(self, tsdb) -> None:  # noqa: B027
        pass


class RpcPlugin(Plugin):
    """Arbitrary protocol servers sharing the TSD process
    (ref: RpcPlugin.java:36) — e.g. a kafka consumer. Started after the
    main server binds, stopped at shutdown."""


class HttpRpcPlugin(Plugin):
    """Extra HTTP endpoints under /plugin/<path>
    (ref: HttpRpcPlugin.java:40, RpcManager tsd.http.rpc.plugins)."""

    def path(self) -> str:
        """Route under /plugin/ this handler owns."""
        raise NotImplementedError

    def execute(self, tsdb, request) -> Any:
        """Return an HttpResponse for the request."""
        raise NotImplementedError


class SearchPlugin(Plugin):
    """External index bridge (ref: SearchPlugin.java:51): receives
    TSMeta/UIDMeta/annotation upserts and deletes, answers
    /api/search queries, and may rewrite queries (resolveTSQuery)."""

    def index_ts_meta(self, meta) -> None:  # noqa: B027
        pass

    def delete_ts_meta(self, tsuid: str) -> None:  # noqa: B027
        pass

    def index_uid_meta(self, meta) -> None:  # noqa: B027
        pass

    def delete_uid_meta(self, meta) -> None:  # noqa: B027
        pass

    def index_annotation(self, note) -> None:  # noqa: B027
        pass

    def delete_annotation(self, note) -> None:  # noqa: B027
        pass

    def execute_query(self, query_type: str, query: dict) -> dict:
        raise NotImplementedError

    def resolve_ts_query(self, ts_query):
        """Optionally rewrite a TSQuery (ref: resolveTSQuery :152)."""
        return ts_query


class HttpSerializerPlugin:
    """Alternate wire formats (ref: HttpSerializer.java:93). Subclass
    :class:`opentsdb_tpu.tsd.json_serializer.HttpJsonSerializer` and
    override the parse_*/format_* pairs; select with
    ``tsd.http.serializer.plugin``."""
