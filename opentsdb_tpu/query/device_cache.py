"""Device-resident grid cache: HBM as the tier/block cache.

The reference keeps hot HBase blocks in the region server's block
cache so repeated scans don't touch disk; the TPU-native analogue
keeps the query's pre-bucketized ``[S, B]`` grids resident in device
HBM so repeated queries over the same window don't re-scan the host
store or re-upload (host->device transfer is the dominant cost of a
warm query — on shared/tunneled devices by an order of magnitude).

Entries are keyed by the exact reduction parameters and invalidated by
the store's mutation version (every write or delete bumps it), so a
hit is always bit-identical to a fresh scan. Bounded LRU by device
bytes (``tsd.query.device_cache_mb``).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any


def array_digest(arr) -> bytes:
    """Content fingerprint of an index array (sids, group_ids)."""
    return hashlib.blake2b(memoryview(arr), digest_size=16).digest()


class DeviceGridCache:
    """LRU of device arrays keyed by (reduction params, store version).

    Also reused (with ``stat_prefix``) as the host-RAM prepared-batch
    cache for host-tail queries — same keying/invalidations, separate
    byte pool."""

    def __init__(self, max_bytes: int, stat_prefix: str =
                 "query.devicecache"):
        self.stat_prefix = stat_prefix
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        # key -> (version, arrays: tuple, meta: dict, nbytes: int)
        self._entries: OrderedDict[Any, tuple] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get(self, key, version):
        """(arrays, meta) on hit with a matching version, else None."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry[0] != version:
                if entry is not None:  # stale: the store changed
                    self._bytes -= entry[3]
                    del self._entries[key]
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[1], entry[2]

    @staticmethod
    def _entry_nbytes(a) -> int:
        if a is None:
            return 0
        inner = getattr(a, "arrays", None)  # PreparedBatch
        if inner is not None:
            return sum(getattr(x, "nbytes", 0) for x in inner)
        return getattr(a, "nbytes", 0)

    def put(self, key, version, arrays: tuple, meta: dict) -> None:
        nbytes = sum(self._entry_nbytes(a) for a in arrays)
        if nbytes > self.max_bytes:
            return  # larger than the whole cache: don't thrash
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[3]
            self._entries[key] = (version, arrays, meta, nbytes)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and self._entries:
                _, (_, _, _, nb) = self._entries.popitem(last=False)
                self._bytes -= nb

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def collect_stats(self, collector) -> None:
        collector.record(f"{self.stat_prefix}.bytes", self._bytes)
        collector.record(f"{self.stat_prefix}.entries",
                         len(self._entries))
        collector.record(f"{self.stat_prefix}.hits", self.hits)
        collector.record(f"{self.stat_prefix}.misses", self.misses)
