"""The query engine (ref: ``src/core/TsdbQuery.java:64``).

Compiles one validated :class:`TSQuery` into the array pipeline:

1. resolve metric + filters against the UID tables
   (``configureFromQuery`` :434)
2. vectorized series selection over the metric's tag index
   (replaces scanner row-regex + post-scan filters, ``findSpans`` :795)
3. group-key construction from group-by tagv ids
   (``GroupByAndAggregateCB`` :916-1045)
4. time-grid construction: downsample buckets, or the union of distinct
   timestamps when no downsample is given (the reference's
   AggregationIterator emits at the union of span timestamps)
5. one fused device pipeline per sub-query
   (:mod:`opentsdb_tpu.ops.pipeline`)
6. result assembly with the reference's tags/aggregateTags semantics
   (SpanGroup: tags = identical k=v across all series; aggregateTags =
   keys present everywhere with differing values)
"""

from __future__ import annotations

import logging
import time
from dataclasses import replace
from typing import Any, Sequence

import numpy as np

from opentsdb_tpu.core import store as store_mod
from opentsdb_tpu.core.store import TimeSeriesStore
from opentsdb_tpu.obs import trace as trace_mod
from opentsdb_tpu.obs.trace import trace_begin, trace_end, trace_span
from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.ops.blocked import (DEFAULT_CELL_BUDGET,
                                      execute_blocked,
                                      pick_block_buckets)
from opentsdb_tpu.ops.pipeline import (PipelineSpec, execute,
                                       execute_auto, execute_avg_divide,
                                       flatten_padded)
from opentsdb_tpu.query import filters as filters_mod
from opentsdb_tpu.query.limits import QueryLimitExceeded
from opentsdb_tpu.query.model import (BadRequestError, TSQuery,
                                      TSSubQuery,
                                      effective_pixels as
                                      model_effective_pixels)
from opentsdb_tpu.stats.stats import QueryStat, QueryStats
from opentsdb_tpu.utils.faults import DegradedError

LOG = logging.getLogger("query.engine")


class QueryResult:
    """One output group — the analogue of one ``DataPoints`` object.

    ``dps`` (the (ts_ms, value) tuple list) is LAZY when the engine
    produced the columnar ``dps_arrays`` twin: a wildcard group-by
    response has thousands of groups and the serializer formats
    straight from the arrays, so eagerly zipping per-group Python
    tuple lists taxed every large query for a list most consumers
    never read. Reading ``.dps`` materializes on first touch;
    size checks should use :attr:`num_dps` (doesn't materialize)."""

    __slots__ = ("metric", "tags", "aggregated_tags", "tsuids",
                 "annotations", "global_annotations",
                 "sub_query_index", "dps_arrays", "_dps", "sketches")

    def __init__(self, metric: str, tags: dict, aggregated_tags: list,
                 dps: list | None = None, tsuids: list | None = None,
                 annotations: list | None = None,
                 global_annotations: list | None = None,
                 sub_query_index: int = 0, dps_arrays: Any = None):
        self.metric = metric
        self.tags = tags
        self.aggregated_tags = aggregated_tags
        self._dps = dps
        self.tsuids = tsuids if tsuids is not None else []
        self.annotations = annotations if annotations is not None \
            else []
        self.global_annotations = global_annotations \
            if global_annotations is not None else []
        self.sub_query_index = sub_query_index
        self.dps_arrays = dps_arrays
        # percentile partials (cluster scatter): [(ts_ms, sketch
        # bytes)] per output bucket, merged exactly by the router
        self.sketches = None

    @property
    def dps(self) -> list:
        if self._dps is None:
            if self.dps_arrays is None:
                self._dps = []
            else:
                ts_arr, vals = self.dps_arrays
                self._dps = list(zip(ts_arr.tolist(), vals.tolist()))
        return self._dps

    @dps.setter
    def dps(self, value: list) -> None:
        self._dps = value

    @property
    def num_dps(self) -> int:
        if self._dps is not None:
            return len(self._dps)
        if self.dps_arrays is None:
            return 0
        return len(self.dps_arrays[0])

    def with_sub_index(self, index: int) -> "QueryResult":
        """A shallow twin carrying a different ``sub_query_index`` —
        the result-cache hit path re-labels shared results when the
        same sub-query content sits at a different position in the
        requesting TSQuery (the cache key excludes the index)."""
        if self.sub_query_index == index:
            return self
        twin = QueryResult(
            self.metric, self.tags, self.aggregated_tags,
            dps=self._dps, tsuids=self.tsuids,
            annotations=self.annotations,
            global_annotations=self.global_annotations,
            sub_query_index=index, dps_arrays=self.dps_arrays)
        twin.sketches = self.sketches
        return twin

    def cache_copy(self) -> "QueryResult":
        """Detached twin for the result cache: shares the immutable
        columnar payload but NOT the lazily-materialized ``_dps``
        tuple list — a consumer touching ``.dps`` (~100 bytes/point)
        fattens only its own request-scoped copy, so a cached entry's
        real footprint stays what ``results_nbytes`` charged against
        the byte budget. ``_dps`` is kept only when it IS the payload
        (no columnar twin)."""
        twin = QueryResult(
            self.metric, self.tags, self.aggregated_tags,
            dps=self._dps if self.dps_arrays is None else None,
            tsuids=self.tsuids, annotations=self.annotations,
            global_annotations=self.global_annotations,
            sub_query_index=self.sub_query_index,
            dps_arrays=self.dps_arrays)
        twin.sketches = self.sketches
        return twin

    def __repr__(self) -> str:  # debugging/test output only
        return (f"QueryResult(metric={self.metric!r}, "
                f"tags={self.tags!r}, "
                f"aggregated_tags={self.aggregated_tags!r}, "
                f"num_dps={self.num_dps})")


class NoSuchMetricError(BadRequestError):
    pass


class TagMatrix:
    """Columnar per-series tags for one sub-query's selected series.

    ``vids[i, j]`` is the tagv id of tag key ``kids[j]`` on series i, or
    -1 when the series lacks that key. Every engine consumer of
    per-series tags (group keys, SpanGroup common-tag semantics,
    explicit_tags, tsuids) reads this matrix with array ops — the
    previous list-of-dicts walk cost ~0.4 s per 200k series and showed
    up directly in the north-star query budget.
    """

    __slots__ = ("kids", "vids")

    def __init__(self, kids: np.ndarray, vids: np.ndarray):
        self.kids = kids        # int64 [K] sorted distinct tagk ids
        self.vids = vids        # int64 [S, K]; -1 = key absent

    @classmethod
    def from_triples(cls, sids: np.ndarray, triples: np.ndarray,
                     kids: np.ndarray | None = None) -> "TagMatrix":
        """Build from the metric index's (sid, kid, vid) rows; triples
        for sids outside ``sids`` are ignored. ``kids`` optionally fixes
        the column space (for cross-store alignment)."""
        sids = np.asarray(sids, dtype=np.int64)
        if kids is None:
            kids = (np.unique(triples[:, 1]) if len(triples)
                    else np.empty(0, dtype=np.int64))
        vids = np.full((len(sids), len(kids)), -1, dtype=np.int64)
        if len(triples) and len(sids) and len(kids):
            order = np.argsort(sids, kind="stable")
            ssorted = sids[order]
            pos = np.searchsorted(ssorted, triples[:, 0])
            pos = np.minimum(pos, len(ssorted) - 1)
            keep = ssorted[pos] == triples[:, 0]
            kcol = np.searchsorted(kids, triples[:, 1])
            kcol_ok = np.minimum(kcol, len(kids) - 1)
            keep &= kids[kcol_ok] == triples[:, 1]
            rows = order[pos[keep]]
            vids[rows, kcol_ok[keep]] = triples[keep, 2]
        return cls(kids, vids)

    @classmethod
    def from_pairs(cls, tag_tuples: Sequence[Sequence[tuple[int, int]]]
                   ) -> "TagMatrix":
        """Build from per-series ((kid, vid), ...) tuples (small paths:
        tsuid queries, histogram series)."""
        rows = [(i, kid, vid) for i, tags in enumerate(tag_tuples)
                for kid, vid in tags]
        triples = (np.asarray(rows, dtype=np.int64).reshape(-1, 3)
                   if rows else np.empty((0, 3), dtype=np.int64))
        return cls.from_triples(np.arange(len(tag_tuples)), triples)

    @property
    def num_series(self) -> int:
        return self.vids.shape[0]

    def col(self, kid: int) -> np.ndarray | None:
        """[S] tagv ids for one key (-1 absent), or None if no series
        has the key at all."""
        j = int(np.searchsorted(self.kids, kid))
        if j < len(self.kids) and self.kids[j] == kid:
            return self.vids[:, j]
        return None

    def select(self, mask_or_idx) -> "TagMatrix":
        return TagMatrix(self.kids, self.vids[mask_or_idx])

    def tags_of(self, i: int) -> list[tuple[int, int]]:
        """Series i's present (kid, vid) pairs, kid-ascending."""
        row = self.vids[i]
        return [(int(k), int(v)) for k, v in zip(self.kids, row)
                if v >= 0]


def _store_id(store) -> int:
    """Monotonic per-process store identity for cache keys. id(store)
    could alias a freed store whose address was reused with a
    coincidentally equal (points_written, mutation_epoch)."""
    return getattr(store, "instance_id", id(store))


# default padded [S, B] cell count below which the pipeline tail runs
# on the host CPU backend instead of the accelerator
HOST_TAIL_DEFAULT_CELLS = 1 << 20
# and the [S, B] x G work-product cap for RANK-class aggregators
# (median/percentiles): their group stage sorts/broadcasts with a
# G-factor that a single-core host grinds through slowly.
HOST_TAIL_DEFAULT_CELLGROUPS = 1 << 25
# LINEAR aggregators (sum/min/max/avg/dev/count/... — everything the
# pipeline reduces with segment ops) get a larger cells-only budget:
# with PipelineSpec.host=True the group stage lowers to segment
# scatter, an O(cells) pass (measured 3 ms at [114688, 32] x 1024
# groups on one CPU core, vs 1.0 s for the one-hot contraction the
# old cells*groups cap modeled). A 1000-group dashboard over 100k
# series is host-served: its wall time on a tunneled accelerator is
# two RPC round trips (~0.5 s), not compute.
HOST_TAIL_DEFAULT_CELLS_LINEAR = 1 << 23


def _rank_class_agg(agg_name: str) -> bool:
    """median / exact & estimated percentiles: the group stage is a
    sort with a G-broadcast, not a segment reduction."""
    if agg_name == "median":
        return True
    try:
        from opentsdb_tpu.ops import aggregators as _aggs
        return bool(_aggs.get(agg_name).is_percentile)
    except Exception:  # noqa: BLE001 - unknown agg: be conservative
        return True


def host_tail_device(config, padded_cells: int,
                     padded_groups: int = 1,
                     linear_agg: bool = False):
    """Device override for small-query tails.

    For rank-class aggregators: below ``tsd.query.host_tail_max_cells``
    AND ``cells * groups`` below ``tsd.query.host_tail_max_cellgroups``.
    For linear (segment-reducible) aggregators: below
    ``tsd.query.host_tail_max_cells_linear`` — no group factor, the
    host group stage is O(cells) segment scatter (see
    HOST_TAIL_DEFAULT_CELLS_LINEAR). All dims are shape-bucket-PADDED,
    so the decision is deterministic per compiled-shape class and
    warmup can pre-compile the same programs.

    A dashboard-sized query's wall time on a remote or tunneled
    accelerator is dominated by per-query RPC round trips, not compute
    — the reference serves this class straight from the local JVM heap
    (ref: QueryRpc.java:128 -> TsdbQuery compute in-process). Set a key
    to -1 to disable; 0 means the default. Mesh queries never take
    this path (sharded data is already device-resident). Returns a
    committed CPU ``jax.Device`` or None (= use the default device)."""
    if linear_agg:
        limit = config.get_int(
            "tsd.query.host_tail_max_cells_linear", 0) \
            or HOST_TAIL_DEFAULT_CELLS_LINEAR
        if limit < 0 or padded_cells > limit:
            return None
    else:
        limit = config.get_int("tsd.query.host_tail_max_cells", 0) \
            or HOST_TAIL_DEFAULT_CELLS
        glimit = config.get_int(
            "tsd.query.host_tail_max_cellgroups", 0) \
            or HOST_TAIL_DEFAULT_CELLGROUPS
        if limit < 0 or glimit < 0 or padded_cells > limit \
                or padded_cells * max(padded_groups, 1) > glimit:
            return None
    import jax
    try:
        return jax.devices("cpu")[0]
    except RuntimeError:  # pragma: no cover - cpu platform disabled
        return None


def host_tail_for_dims(config, s: int, b: int, num_groups: int,
                       emit_raw: bool = False,
                       agg_name: str = "p99"):
    """:func:`host_tail_device` from RAW query dims — the ONE place the
    decision inputs are shape-bucketed, shared by the engine paths and
    tsd.warmup so a warmed placement cannot drift from the engine's
    (ADVICE r04). emit_raw has no group contraction: group factor 1.
    ``agg_name`` picks the linear vs rank-class budget; the default is
    a rank-class name so legacy callers keep the conservative rule."""
    from opentsdb_tpu.ops import shapes as _shapes
    return host_tail_device(
        config,
        _shapes.shape_bucket(s) * _shapes.shape_bucket(b),
        1 if emit_raw else _shapes.shape_bucket(num_groups + 1),
        linear_agg=not _rank_class_agg(agg_name))


def compact_row_labels(mat: np.ndarray) -> tuple[np.ndarray, int]:
    """``np.unique(mat, axis=0, return_inverse=True)`` equivalent via
    per-column factorization — the void-dtype row sort behind
    unique(axis=0) is ~10x slower at 1M rows. Labels preserve the
    lexicographic row order (the reference's ByteMap group-key order).
    """
    n_rows, n_cols = mat.shape
    if n_cols == 0 or n_rows == 0:
        return (np.zeros(n_rows, dtype=np.int32),
                1 if n_rows else 0)
    labels = None
    count = 1
    for j in range(n_cols):
        u, inv = np.unique(mat[:, j], return_inverse=True)
        if labels is None:
            labels, count = inv.astype(np.int64), len(u)
        else:
            # composite stays < count * len(u) <= n_rows^2: int64-safe,
            # re-compacted each step so it never grows further
            labels = labels * len(u) + inv
            u2, labels = np.unique(labels, return_inverse=True)
            count = len(u2)
    return labels.astype(np.int32), count


class _UidNameCache:
    """Memoized UID->name lookups for result assembly (one cache per
    query; group loops hit the same few names over and over)."""

    def __init__(self, registry):
        self._reg = registry
        # tsdlint: allow[unbounded-growth] one cache per query,
        # garbage with the query; bounded by its result's UID count
        self._cache: dict[int, str] = {}

    def __call__(self, uid: int) -> str:
        name = self._cache.get(uid)
        if name is None:
            name = self._cache[uid] = self._reg.get_name(uid)
        return name


# Padded-layout guards: padding inflation is bounded by the skew factor
# (pad cells per real point) once batches are big enough to matter, and
# by an absolute S*Pmax cell ceiling (host RAM).
_PADDED_SKEW_FACTOR = 4
_PADDED_MIN_CELLS = 10_000_000
_PADDED_ABS_MAX_CELLS = 500_000_000


class QueryEngine:
    """(ref: TsdbQuery; one instance per TSQuery execution)"""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        self._filter_eval = filters_mod.FilterEvaluator(tsdb.uids)

    # ------------------------------------------------------------------
    # graceful degradation: the device circuit breaker
    # ------------------------------------------------------------------

    def _device_degraded(self) -> bool:
        """True while the device-pipeline breaker is OPEN (inside its
        reset window): tails must not dispatch to the accelerator.
        Read-only (``blocking``) — the half-open probe transition
        belongs to :meth:`_run_device`'s dispatch gate alone."""
        breaker = self.tsdb.device_breaker
        return breaker is not None and breaker.blocking()

    @staticmethod
    def _host_cpu():
        """The committed host CPU device every degraded fallback pins
        to (one definition so the fallback discipline cannot drift
        between the point/grid/avg paths)."""
        import jax
        return jax.devices("cpu")[0]

    def _tail_device(self, s: int, b: int, num_groups: int,
                     emit_raw: bool, agg_name: str):
        """:func:`host_tail_for_dims` + the degraded override: an OPEN
        breaker pins the tail to the host CPU backend (the
        always-available in-process compute path — the analogue of the
        reference answering straight from the JVM heap)."""
        if self._device_degraded():
            if not self.tsdb.config.get_bool(
                    "tsd.query.degraded.host_fallback", True):
                raise DegradedError(
                    "device pipeline circuit breaker is open and "
                    "host fallback is disabled "
                    "(tsd.query.degraded.host_fallback)")
            try:
                return self._host_cpu()
            except RuntimeError:  # pragma: no cover - no cpu backend
                return None
        return host_tail_for_dims(self.tsdb.config, s, b, num_groups,
                                  emit_raw, agg_name)

    def _run_device(self, compute, host_retry=None,
                    on_device: bool = True):
        """Run a pipeline tail under the device circuit breaker.

        ``compute`` is the already-placed dispatch; accelerator
        failures count toward ``tsd.query.breaker.*`` and — when a
        ``host_retry`` twin exists — the query is re-answered on the
        host CPU backend instead of surfacing a 500. ``on_device=False``
        (tail already pinned to the host) bypasses the breaker
        entirely: a host success says nothing about accelerator
        health, so it must not close an open breaker.

        Failure classification is deliberately coarse: any exception
        from the dispatch (including prepare/cache code, which can
        fail for data-shaped reasons) counts toward the breaker. A
        repeatable non-device error can therefore trip it spuriously —
        the half-open probe bounds that cost to one reset window, and
        the fallback answer is still correct (same kernels, host
        placement)."""
        if not on_device:
            return compute()
        faults = getattr(self.tsdb, "faults", None)
        breaker = self.tsdb.device_breaker
        if breaker is not None and not breaker.allow():
            # OPEN breaker: never touch the failing device. Paths
            # whose placement happens up front (_tail_device) don't
            # reach here; this guards the mesh/blocked/cache-hit
            # dispatches, which otherwise would hammer the device for
            # the whole reset window.
            if host_retry is not None and self.tsdb.config.get_bool(
                    "tsd.query.degraded.host_fallback", True):
                breaker.fallbacks += 1
                return host_retry()
            raise DegradedError(
                "device pipeline circuit breaker is open and this "
                "query has no host fallback")
        try:
            if faults is not None:
                faults.check("device.compile")
            out = compute()
        except Exception as exc:  # noqa: BLE001
            if breaker is not None:
                breaker.record_failure()
            if host_retry is None or not self.tsdb.config.get_bool(
                    "tsd.query.degraded.host_fallback", True):
                raise
            LOG.warning("device pipeline failed (%s: %s); answering "
                        "on the host CPU backend",
                        type(exc).__name__, exc)
            if breaker is not None:
                breaker.fallbacks += 1
            return host_retry()
        if breaker is not None:
            breaker.record_success()
        return out

    # ------------------------------------------------------------------

    def run(self, ts_query: TSQuery,
            stats: QueryStats | None = None) -> list[QueryResult]:
        subs = ts_query.queries
        if len(subs) > 1 and not ts_query.delete:
            # delete=true stays serial: a sub's delete_range shifts
            # the per-series buffers IN PLACE while a parallel sibling
            # may still hold live views into them (scanned-and-deleted
            # semantics make the order matter too)
            pool = self.tsdb.query_fanout_pool
            if pool is not None:
                return self._run_fanout(ts_query, subs, stats, pool)
        results: list[QueryResult] = []
        for sub in subs:
            results.extend(self._run_sub_cached(ts_query, sub, stats))
        return results

    def _run_fanout(self, tsq: TSQuery, subs, stats,
                    pool) -> list[QueryResult]:
        """Dispatch independent sub-queries in parallel and join.

        Per-sub result ordering is preserved (results concatenate in
        sub order regardless of completion order) and per-sub
        QueryStats attribution is intact: every sub records into the
        shared (now lock-guarded) QueryStats. The first sub runs on
        the calling thread — it already holds a worker slot of the
        server's _query_pool, and idling it while children queue
        would waste exactly one unit of the fan-out budget. On error,
        the earliest failing sub (in sub order) wins after every
        in-flight sibling has been joined — a still-running future
        must not outlive its TSQuery."""
        # fan-out workers run on other threads: re-bind the parent
        # request's trace context so sub-query spans land in the trace
        tctx = trace_mod.current()
        futures = [pool.submit(self._run_sub_traced, tctx, tsq, sub,
                               stats)
                   for sub in subs[1:]]
        results: list[QueryResult] = []
        first_err: BaseException | None = None
        try:
            results.extend(self._run_sub_cached(tsq, subs[0], stats))
        except BaseException as exc:  # noqa: BLE001 - joined below
            first_err = exc
        for fut in futures:
            try:
                out = fut.result()
            except BaseException as exc:  # noqa: BLE001
                if first_err is None:
                    first_err = exc
            else:
                if first_err is None:
                    results.extend(out)
        if first_err is not None:
            raise first_err
        return results

    def _run_sub_traced(self, tctx, tsq: TSQuery, sub: TSSubQuery,
                        stats: QueryStats | None) -> list[QueryResult]:
        """Fan-out entry: bind the parent request's trace context on
        this worker thread, then run the sub normally."""
        with trace_mod.use(tctx):
            return self._run_sub_cached(tsq, sub, stats)

    def _run_sub_timed(self, tsq: TSQuery, sub: TSSubQuery,
                       stats: QueryStats | None) -> list[QueryResult]:
        """One real engine execution under the ``query.execute`` span
        (scan + device pipeline + assembly; cache hits never get
        here) — the span feeds the ``query.execute`` stage histogram
        exported with percentiles at /api/stats."""
        with trace_span("query.execute", sub=sub.index,
                        metric=sub.metric or "<tsuid>"):
            return self._run_sub(tsq, sub, stats)

    def _run_sub_cached(self, tsq: TSQuery, sub: TSSubQuery,
                        stats: QueryStats | None) -> list[QueryResult]:
        """One sub-query through the serve-path result cache: hits
        skip the engine entirely, misses single-flight (concurrent
        identical queries share ONE execution and a failed leader
        poisons nothing)."""
        from opentsdb_tpu.query import result_cache as rc_mod
        # continuous-query live windows come FIRST: a registered
        # standing query answers its dashboard window from maintained
        # partial aggregates — fresher than any cache entry (it
        # reflects every acknowledged write) and immune to the
        # epoch-invalidation that evicts cached live queries under
        # ingest. Streaming failures always fall through to the
        # batch path — the feeder can shed, never 500.
        streaming = self.tsdb._streaming
        if streaming is not None and not tsq.delete:
            try:
                with trace_span("query.streaming_lookup",
                                sub=sub.index):
                    served = streaming.try_serve(tsq, sub, self)
            except (BadRequestError, QueryLimitExceeded):
                raise  # semantic errors the batch path would raise too
            except Exception as exc:  # noqa: BLE001 - shed to batch
                LOG.warning("streaming serve failed (%s: %s); "
                            "answering from the batch engine",
                            type(exc).__name__, exc)
                served = None
            if served is not None:
                if stats:
                    stats.add_stat(QueryStat.STREAMING_HIT, 1)
                return served
        cache = self.tsdb.result_cache
        if cache is None:
            return self._run_sub_timed(tsq, sub, stats)
        plan = rc_mod.cache_plan(tsq, sub, self.tsdb.config)
        if plan is None:
            cache.count_bypass()
            return self._run_sub_timed(tsq, sub, stats)
        key, ttl_ms = plan
        # the version MUST be captured before compute: a write landing
        # mid-execution then leaves the entry already-stale instead of
        # wrongly fresh (see QueryResultCache.get_or_compute)
        version = self._sub_version(sub)
        value, outcome = cache.get_or_compute(
            key, version,
            lambda: self._run_sub_timed(tsq, sub, stats),
            ttl_ms)
        if stats and outcome != rc_mod.MISS:
            stats.add_stat(
                QueryStat.RESULT_CACHE_HIT
                if outcome == rc_mod.HIT
                else QueryStat.RESULT_CACHE_COALESCED, 1)
        if value and value[0].sub_query_index != sub.index:
            value = [r.with_sub_index(sub.index) for r in value]
        return value

    def _sub_version(self, sub: TSSubQuery) -> tuple:
        """Invalidation version over the stores THIS sub-query's plan
        reads — not the whole TSDB — so dashboards answered from a
        rollup tier keep hitting while raw ingest streams in (and vice
        versa). Tier selection is re-derived per lookup, so a write
        that flips the selection (e.g. the first point landing in a
        previously-empty tier) changes the selected store identity and
        misses naturally. Falls back to the conservative whole-TSDB
        :meth:`TSDB.serve_version` when selection itself fails (the
        engine will surface the same error to the caller)."""
        t = self.tsdb
        ann = getattr(t.annotations, "version", 0)
        if sub.percentiles:
            parts = ["hist", t._histogram_version,
                     t.histogram_store.points_written,
                     t.histogram_store.mutation_epoch, ann]
            # the sketch path also reads the raw tail, the sketch
            # tier, and (through it) the cold segments
            lc = t.lifecycle
            if lc is not None and lc.sketches is not None:
                cold = lc.coldstore
                parts += [t.store.points_written,
                          getattr(t.store, "mutation_epoch", 0),
                          lc.sketches.cells_folded,
                          lc.sketches.cells_spilled,
                          cold.mutation_epoch
                          if cold is not None else 0]
            else:
                parts += [t.store.points_written,
                          getattr(t.store, "mutation_epoch", 0)]
            return tuple(parts)
        try:
            (store, _metric, _sids, _scale, avg_count_store,
             _ds) = self._select_store(sub)
        except Exception:  # noqa: BLE001 - compute re-raises for real
            return ("all", t.serve_version(), ann)
        parts = ["sel", ann, _store_id(store), store.points_written,
                 getattr(store, "mutation_epoch", 0)]
        if avg_count_store is not None:
            # the avg-over-budget branch in _run_sub may still swap to
            # the RAW store mid-plan; cover both outcomes
            raw = t.store
            parts += [_store_id(avg_count_store),
                      avg_count_store.points_written,
                      getattr(avg_count_store, "mutation_epoch", 0),
                      _store_id(raw), raw.points_written,
                      getattr(raw, "mutation_epoch", 0)]
        return tuple(parts)

    # ------------------------------------------------------------------

    def _run_sub(self, tsq: TSQuery, sub: TSSubQuery,
                 stats: QueryStats | None) -> list[QueryResult]:
        t0 = time.monotonic()
        uids = self.tsdb.uids
        if sub.percentiles:
            from opentsdb_tpu.query.histogram_engine import \
                run_histogram_subquery
            from opentsdb_tpu.sketch.query import (merge_pct_rows,
                                                   run_sketch_percentiles)
            partials = bool(getattr(tsq, "sketch_partials", False))
            sk_rows = run_sketch_percentiles(self.tsdb, tsq, sub,
                                             partials=partials)
            if partials:
                # cluster scatter: the shard hands back mergeable
                # sketch partials, never locally-extracted quantiles.
                # Disabled sketches 400 honestly — an empty partial
                # would make the router's merged answer silently wrong
                if sk_rows is None:
                    raise BadRequestError(
                        "sketch partials requested but the sketch "
                        "subsystem is disabled (tsd.sketch.enable)")
                return sk_rows
            hist_rows = run_histogram_subquery(self.tsdb, tsq, sub)
            if sk_rows is not None:
                # live arena rows + spilled/demoted sketch history
                # splice by group (disjoint time windows)
                hist_rows = merge_pct_rows(hist_rows, sk_rows)
            # `_pct_<q>` rows are plain emitted rows once assembled, so
            # the pixel budget applies post-assembly like every other
            # producer (the router reduces merged partials itself)
            px, pfn = model_effective_pixels(tsq, sub)
            if px and not tsq.delete:
                from opentsdb_tpu.ops.visual_downsample import reduce_dps
                for row in hist_rows:
                    row.dps = reduce_dps(row.dps, tsq.start_ms,
                                         tsq.end_ms, px, pfn)
            return hist_rows
        # planning stage span: tier selection, filter evaluation,
        # group construction (ended at every exit of the stage — an
        # unfinished handle on an error path simply isn't recorded;
        # the enclosing query.execute span still carries the error)
        _h_plan = trace_begin("query.plan", sub=sub.index)
        (store, metric_name, sids, rollup_scale,
         avg_count_store, ds_fn_override) = self._select_store(sub)
        budget = self.tsdb.config.get_int(
            "tsd.query.max_device_cells", 0) or DEFAULT_CELL_BUDGET
        if avg_count_store is not None:
            # the sum/count grid division materializes [S, B] whole;
            # oversized ranges go to the raw streaming path instead —
            # but only when raw data actually exists (rolled-up data
            # may outlive its raw source), else an expensive exact
            # answer beats a cheap empty one
            b_est = ((tsq.end_ms - tsq.start_ms)
                     // max(sub.ds_spec.interval_ms, 1)) + 2
            if len(sids) * b_est > budget:
                raw_sids = self.tsdb.store.series_ids_for_metric(
                    uids.metrics.get_id(sub.metric))
                if len(raw_sids):
                    avg_count_store = None
                    store = self.tsdb.store
                    sids = raw_sids
        if len(sids) == 0:
            trace_end(_h_plan)
            return []
        if stats:
            stats.add_stat(QueryStat.ROWS_PRE_FILTER, len(sids))

        # --- filters -> series mask (ref: findSpans post-scan filters)
        sids, tag_mat = self._apply_filters(store, sub, sids)
        if len(sids) == 0:
            trace_end(_h_plan)
            return []
        if tsq.replica_sel is not None and sub.metric:
            # replicated-router scatter: keep only series whose
            # replica set this request was assigned (cluster/replica),
            # so each series is read by exactly one replica
            # cluster-wide and merged partials never double-count
            from opentsdb_tpu.cluster import replica as replica_mod
            keep = np.asarray(replica_mod.series_mask(
                tsq.replica_sel, sub.metric,
                (tag_mat.tags_of(i) for i in range(len(sids))),
                _UidNameCache(uids.tag_names),
                _UidNameCache(uids.tag_values)), dtype=bool)
            if not keep.all():
                sids = sids[keep]
                tag_mat = tag_mat.select(keep)
            if len(sids) == 0:
                trace_end(_h_plan)
                return []
        if stats:
            stats.add_stat(QueryStat.STRING_TO_UID_TIME,
                           (time.monotonic() - t0) * 1e3)
            stats.add_stat(QueryStat.ROWS_POST_FILTER, len(sids))
            stats.add_stat(QueryStat.UID_PAIRS_RESOLVED,
                           int((tag_mat.vids >= 0).sum()))

        # --- group construction (ref: GroupByAndAggregateCB :916)
        gb_tagks = sorted({f.tagk for f in sub.filters if f.group_by})
        gb_kids = []
        for k in gb_tagks:
            try:
                gb_kids.append(uids.tag_names.get_id(k))
            except LookupError:
                trace_end(_h_plan)
                return []
        group_ids, num_groups = self._group_ids(tag_mat, gb_kids)
        emit_raw = sub.agg.is_none
        if emit_raw:
            group_ids = np.arange(len(sids), dtype=np.int32)
            num_groups = len(sids)
        if _h_plan is not None:
            _h_plan.tag(series=len(sids), groups=num_groups)
        trace_end(_h_plan)

        if avg_count_store is not None:
            out = self._avg_rollup_pipeline(
                store, avg_count_store, sids, tsq, sub, metric_name,
                group_ids, num_groups, emit_raw, stats)
            if out is None:
                return []
            result, emit, bucket_ts = out
            return self._build_results(
                tsq, sub, metric_name, sids, tag_mat, group_ids,
                num_groups, gb_kids, bucket_ts, result, emit)

        # --- pre-bucketized grid fast path: for fixed-interval simple
        # downsample functions the storage engine reduces the window to
        # the [S, B] grid in one native pass, so the device never sees
        # per-point data (SURVEY §7: HBM/transfer bandwidth is the
        # bottleneck; here the "scan" IS the downsample)
        out = self._grid_pipeline(store, sids, tsq, sub, metric_name,
                                  group_ids, num_groups, emit_raw,
                                  rollup_scale, budget, stats,
                                  ds_fn_override)
        if out is not None:
            result, emit, bucket_ts = out
            if result is None:
                return []
            return self._build_results(
                tsq, sub, metric_name, sids, tag_mat, group_ids,
                num_groups, gb_kids, bucket_ts, result, emit)

        # --- device-prepared batch cache: a warm repeat of the same
        # (store, series set, window, downsample) skips materialize AND
        # the upload — the data lives in HBM already (the point-path
        # twin of _grid_pipeline's resident grids)
        mesh = self.tsdb.query_mesh
        prep_cache = (self.tsdb.device_grid_cache
                      if rollup_scale == 1.0 else None)
        prep = pkey = pver = None
        if prep_cache is not None:
            from opentsdb_tpu.query.device_cache import array_digest
            from opentsdb_tpu.parallel.sharded_pipeline import \
                agg_mesh_class
            # the aggregator's memory CLASS is part of the key: the
            # use_blocked verdict depends on it (mesh_scale), and a hit
            # must imply the cold path would have taken the same
            # (non-blocked) branch — an entry cached by a psum-safe
            # aggregator must not serve an all_gather one past its
            # unscaled budget
            acls = agg_mesh_class(sub.agg.name)
            if acls == "pct":
                # histogram eligibility (and so the budget verdict)
                # depends on the group count too
                acls = ("pct", num_groups)
            if mesh is None:
                # single-device: the linear-vs-rank PLACEMENT class is
                # the key dimension — a host-pool entry cached by a
                # linear agg must not serve a rank-class query whose
                # budget would have placed it on the accelerator
                # (their group stages differ by orders of magnitude on
                # one CPU core). The rank-class budget is
                # cells * groups, so the bucketed group count is part
                # of the key — two group-by cardinalities of the same
                # series set must not share a placement (mirrors the
                # mesh ('pct', num_groups) key above)
                if not _rank_class_agg(sub.agg.name):
                    acls = "lin"
                else:
                    from opentsdb_tpu.ops import shapes as _shapes
                    acls = ("rank",
                            _shapes.shape_bucket(num_groups + 1))
            pkey = ("prep", _store_id(store),
                    array_digest(np.ascontiguousarray(sids)),
                    tsq.start_ms, tsq.end_ms, sub.downsample or "union",
                    getattr(sub.ds_spec, "timezone", None), mesh,
                    acls)
            pver = (store.points_written,
                    getattr(store, "mutation_epoch", 0))
            # degraded (breaker open): skip the DEVICE pool — a hit
            # would re-dispatch to the failing accelerator; host-pool
            # hits below remain valid
            hit = None if self._device_degraded() \
                else prep_cache.get(pkey, pver)
            if hit is None:
                # host-tail twin: same key space, host-RAM pool
                hcache = self.tsdb.host_prep_cache
                if hcache is not None:
                    hit = hcache.get(pkey, pver)
            if hit is not None:
                try:
                    return self._run_prep_hit(
                        hit, mesh, store, sids, tsq, sub, metric_name,
                        tag_mat, group_ids, num_groups, gb_kids,
                        emit_raw, stats)
                except (BadRequestError, QueryLimitExceeded):
                    raise
                except Exception as exc:  # noqa: BLE001
                    # a warm entry failing on the device must not make
                    # warm queries 500 while cold ones fall back:
                    # breaker bookkeeping already happened inside
                    # _run_device — drop to the cold path below, which
                    # carries the full host-fallback discipline
                    LOG.warning("cached device batch failed (%s: %s); "
                                "re-running the query cold",
                                type(exc).__name__, exc)

        # --- materialize + time grid (row-padded layout: the ragged ->
        # dense transposition happens inside materialize, so the device
        # path never needs a scatter; see PaddedBatch). Skewed batches
        # (one dense series among many sparse ones would blow S * Pmax
        # up quadratically) stay on the flat layout.
        t1 = time.monotonic()
        counts = store.count_range(sids, tsq.start_ms, tsq.end_ms)
        total = int(counts.sum())
        pmax = int(counts.max()) if len(counts) else 0
        cells = len(sids) * pmax
        use_padded = total > 0 and \
            cells <= max(_PADDED_SKEW_FACTOR * total,
                         _PADDED_MIN_CELLS) and \
            cells <= _PADDED_ABS_MAX_CELLS
        if use_padded:
            padded = store.materialize_padded(sids, tsq.start_ms,
                                              tsq.end_ms)
            num_points = total
        else:
            padded = None
            batch = store.materialize(sids, tsq.start_ms, tsq.end_ms)
            num_points = batch.num_points
        self._record_scan(stats, (time.monotonic() - t1) * 1e3,
                          num_points, len(sids))
        # byte/dp guardrails (ref: SaltScanner budget enforcement via
        # QueryLimitOverride)
        self.tsdb.query_limits.check(metric_name, num_points)
        if tsq.delete and hasattr(store, "delete_range"):
            # scanned-and-deleted semantics: the response still carries
            # the data just removed (ref: TsdbQuery delete=true turning
            # scans into DeleteRequests after collection)
            store.delete_range(sids, tsq.start_ms, tsq.end_ms)
        if num_points == 0:
            return []
        bucket_idx2d = bucket_idx = None
        grid_complete = False
        if sub.ds_spec is not None:
            ds_function = ds_fn_override or sub.ds_spec.function
            fill_policy = sub.ds_spec.fill_policy
            fill_value = sub.ds_spec.fill_value
            if padded is not None:
                bucket_idx2d, bucket_ts = ds_mod.assign_buckets_padded(
                    padded.ts2d, padded.counts, sub.ds_spec,
                    tsq.start_ms, tsq.end_ms)
            else:
                bucket_idx, bucket_ts = ds_mod.assign_buckets(
                    batch.ts_ms, sub.ds_spec, tsq.start_ms, tsq.end_ms)
        else:
            # union-of-timestamps grid: every distinct input timestamp
            # is an output point, like the reference's merge iterator
            ds_function = "sum"  # one point per (series, ts) after dedupe
            fill_policy = ds_mod.FillPolicy.NONE
            fill_value = float("nan")
            if padded is not None:
                pad = store_mod.pad_mask(padded.counts,
                                         padded.ts2d.shape[1])
                # regular-cadence fast path: when every series carries
                # the SAME timestamp row (the monitoring-data common
                # case), the union IS row 0 — one vectorized equality
                # check replaces the 3M-element sort np.unique costs
                # (~160 ms at 100k x 30)
                if not pad.any() and len(padded.ts2d) and \
                        (padded.ts2d == padded.ts2d[0]).all():
                    row0 = padded.ts2d[0]
                    # strictly increasing => no duplicate timestamps,
                    # exactly what np.unique would have produced
                    if (np.diff(row0) > 0).all():
                        bucket_ts = row0.copy()
                        bucket_idx2d = np.broadcast_to(
                            np.arange(len(row0), dtype=np.int32),
                            padded.ts2d.shape).copy()
                        # every cell verified present: the pipeline
                        # may skip interpolation/emission no-ops
                        # (PipelineSpec.complete). Pure DATA property
                        # here; the per-QUERY carve-out (drop_resets
                        # punches per-series holes) applies at spec
                        # build so cached entries stay query-agnostic.
                        grid_complete = not np.isnan(
                            padded.values2d).any()
                    else:
                        bucket_ts = None
                else:
                    bucket_ts = None
                if bucket_ts is None:
                    bucket_ts, inverse = np.unique(
                        padded.ts2d.reshape(-1), return_inverse=True)
                    bucket_idx2d = inverse.reshape(padded.ts2d.shape) \
                        .astype(np.int32)
                    bucket_idx2d[pad] = -1
                if pad.any():
                    # drop union slots only pad sentinels produced
                    used = np.zeros(len(bucket_ts), dtype=bool)
                    used[bucket_idx2d[~pad]] = True
                    remap = np.cumsum(used) - 1
                    bucket_ts = bucket_ts[used]
                    bucket_idx2d = np.where(
                        bucket_idx2d >= 0, remap[bucket_idx2d], -1
                    ).astype(np.int32)
            else:
                bucket_ts, bucket_idx = np.unique(batch.ts_ms,
                                                  return_inverse=True)
                bucket_idx = bucket_idx.astype(np.int32)

        # --- device pipeline
        t2 = time.monotonic()
        # the mesh raises the streaming threshold only when every
        # device truly holds S_loc x B_loc cells (see mesh_scale use
        # below); the blocked verdict must precede the host-tail
        # placement so an over-budget range never lands on the host
        from opentsdb_tpu.parallel.sharded_pipeline import \
            mesh_memory_safe
        n_mesh = int(np.prod(list(mesh.shape.values()))) \
            if mesh is not None else 1
        mesh_scale = n_mesh if mesh_memory_safe(
            sub.agg.name, num_groups, len(bucket_ts)) else 1
        use_blocked = not emit_raw and \
            len(sids) * len(bucket_ts) > budget * mesh_scale
        # host-tail placement for the point/union path: the same
        # tunneled-RPC argument as _grid_pipeline's (a group-by
        # dashboard's warm latency on a tunneled device is two RPC
        # round trips, not compute). B for union queries is the
        # distinct-timestamp count — data-dependent, so unlike the
        # grid path this placement class is not warmup-predictable;
        # the persistent compile cache absorbs the one-off compiles.
        host_dev = None
        if mesh is None and not use_blocked:
            host_dev = self._tail_device(
                len(sids), len(bucket_ts), num_groups, emit_raw,
                sub.agg.name)
        spec = PipelineSpec(
            num_series=len(sids), num_buckets=len(bucket_ts),
            num_groups=num_groups, ds_function=ds_function,
            agg_name=sub.agg.name, fill_policy=fill_policy,
            fill_value=fill_value, rate=sub.rate,
            rate_counter=sub.rate_options.counter,
            rate_drop_resets=sub.rate_options.drop_resets,
            emit_raw=emit_raw, host=host_dev is not None,
            complete=grid_complete
            and not (sub.rate and sub.rate_options.drop_resets))
        if rollup_scale != 1.0:
            if padded is not None:
                padded = padded._replace(values2d=padded.values2d
                                         * rollup_scale)
            else:
                batch = batch._replace(values=batch.values
                                       * rollup_scale)
        if padded is not None and (use_blocked or mesh is not None):
            values, series_idx, bucket_idx = flatten_padded(
                padded.values2d, bucket_idx2d, padded.counts)
        elif use_blocked or mesh is not None:
            values, series_idx = batch.values, batch.series_idx
        # the host-retry twin for the single-device paths below: on a
        # device-pipeline failure (or an armed device fault) the same
        # tail re-runs pinned to the host CPU backend — a degraded
        # answer instead of a 500. Mesh and blocked executions have no
        # in-process twin; their failures count toward the breaker and
        # propagate.
        host_retry = None
        if mesh is None and not use_blocked:
            def host_retry():
                from opentsdb_tpu.ops.pipeline import (prepare_auto,
                                                       prepare_flat,
                                                       run_prepared)
                cpu = self._host_cpu()
                hspec = replace(spec, host=True)
                if padded is not None:
                    prep = prepare_auto(padded, bucket_idx2d, hspec,
                                        device=cpu)
                else:
                    prep = prepare_flat(batch.values, batch.series_idx,
                                        bucket_idx, hspec, device=cpu)
                return run_prepared(prep, bucket_ts, group_ids, hspec,
                                    sub.rate_options)
        if use_blocked:
            # long-range streaming: bound memory at [S x block] cells
            # (SURVEY.md §5.7 time-axis blocking)
            if mesh is not None:
                # the carry-chained block scan runs AS a shard_map
                # program: each block keeps the mesh fan-out and the
                # per-DEVICE budget is O(S_loc x block) — the analogue
                # of the 20 SaltScanners streaming concurrently
                # (SaltScanner.java:463-536)
                from opentsdb_tpu.parallel.sharded_pipeline import \
                    execute_blocked_sharded
                result, emit = self._run_device(
                    lambda: execute_blocked_sharded(
                        mesh, values, series_idx, bucket_idx,
                        bucket_ts, group_ids, spec, sub.rate_options,
                        block_buckets=pick_block_buckets(
                            len(sids), len(bucket_ts),
                            budget * mesh_scale)))
            else:
                result, emit = self._run_device(
                    lambda: execute_blocked(
                        values, series_idx, bucket_idx, bucket_ts,
                        group_ids, spec, sub.rate_options,
                        block_buckets=pick_block_buckets(
                            len(sids), len(bucket_ts), budget)))
        elif mesh is not None:
            # multi-chip: shard the point batch over the
            # ('series','time') mesh — the salt-scanner fan-out/merge
            # as XLA collectives (SaltScanner.java:70, SURVEY §2.11).
            # The sharded device arrays are cached (minus the per-query
            # group ids) so a warm repeat skips materialize AND upload.
            from opentsdb_tpu.ops.pipeline import pipeline_dtype
            from opentsdb_tpu.parallel.sharded_pipeline import (
                prepare_sharded_batch, run_sharded_device,
                sharded_device_args)
            def mesh_compute():
                sbatch = prepare_sharded_batch(
                    values, series_idx, bucket_idx, bucket_ts,
                    group_ids, spec.num_series, spec.num_groups,
                    mesh.shape["series"], mesh.shape["time"])
                margs = sharded_device_args(mesh, sbatch,
                                            pipeline_dtype())
                if prep_cache is not None and pkey is not None:
                    prep_cache.put(
                        pkey, pver, margs[:4],
                        {"num_points": num_points,
                         "bucket_ts": bucket_ts,
                         "ds_function": ds_function,
                         "fill_policy": fill_policy,
                         "fill_value": fill_value,
                         "s_loc": sbatch.s_loc, "b_loc": sbatch.b_loc,
                         "s_pad": sbatch.s_loc * mesh.shape["series"]})
                return run_sharded_device(
                    mesh, spec, margs, sbatch.s_loc, sbatch.b_loc,
                    num_groups, sub.rate_options)

            result, emit = self._run_device(mesh_compute)
        elif host_dev is not None:
            # host tail: place on the CPU backend; cached in the
            # host-RAM pool (NOT the device cache — host entries must
            # never evict HBM-resident grids) so warm repeats skip
            # materialize + union-grid construction
            from opentsdb_tpu.ops.pipeline import (prepare_auto,
                                                   prepare_flat,
                                                   run_prepared)
            if padded is not None:
                prep = prepare_auto(padded, bucket_idx2d, spec,
                                    device=host_dev)
            else:
                prep = prepare_flat(batch.values, batch.series_idx,
                                    bucket_idx, spec, device=host_dev)
            hcache = self.tsdb.host_prep_cache \
                if rollup_scale == 1.0 else None
            if hcache is not None and pkey is not None:
                hcache.put(pkey, pver, (prep,), {
                    "num_points": num_points, "bucket_ts": bucket_ts,
                    "ds_function": ds_function,
                    "fill_policy": fill_policy,
                    "fill_value": fill_value, "host": True,
                    "complete": grid_complete})
            result, emit = self._run_device(
                lambda: run_prepared(prep, bucket_ts, group_ids,
                                     spec, sub.rate_options),
                on_device=False)
        elif prep_cache is not None:
            # upload once, cache the device-resident batch, execute
            from opentsdb_tpu.ops.pipeline import (prepare_auto,
                                                   prepare_flat,
                                                   run_prepared)

            def cached_compute():
                if padded is not None:
                    prep = prepare_auto(padded, bucket_idx2d, spec)
                else:
                    prep = prepare_flat(batch.values,
                                        batch.series_idx,
                                        bucket_idx, spec)
                prep_cache.put(pkey, pver, (prep,), {
                    "num_points": num_points, "bucket_ts": bucket_ts,
                    "ds_function": ds_function,
                    "fill_policy": fill_policy,
                    "fill_value": fill_value})
                return run_prepared(prep, bucket_ts, group_ids, spec,
                                    sub.rate_options)

            result, emit = self._run_device(cached_compute, host_retry)
        elif padded is not None:
            result, emit = self._run_device(
                lambda: execute_auto(
                    padded, bucket_idx2d, bucket_ts, group_ids, spec,
                    sub.rate_options), host_retry)
        else:
            result, emit = self._run_device(
                lambda: execute(
                    batch.values, batch.series_idx, bucket_idx,
                    bucket_ts, group_ids, spec, sub.rate_options),
                host_retry)
        if stats:
            stats.add_stat(QueryStat.COMPUTE_TIME,
                           (time.monotonic() - t2) * 1e3)

        # --- assemble output groups
        return self._build_results(
            tsq, sub, metric_name, sids, tag_mat, group_ids,
            num_groups, gb_kids, bucket_ts, result, emit)

    # ------------------------------------------------------------------

    def _run_prep_hit(self, hit, mesh, store, sids, tsq, sub,
                      metric_name, tag_mat, group_ids, num_groups,
                      gb_kids, emit_raw, stats) -> list[QueryResult]:
        """Serve one sub-query from a warm prepared-batch cache entry
        (device pool or its host-RAM twin). Raising is allowed: the
        caller falls back to the cold path on device failure."""
        cached_args, pmeta = hit
        bucket_ts = pmeta["bucket_ts"]
        num_points = pmeta["num_points"]
        self.tsdb.query_limits.check(metric_name, num_points)
        t2 = time.monotonic()
        spec = PipelineSpec(
            num_series=len(sids), num_buckets=len(bucket_ts),
            num_groups=num_groups, ds_function=pmeta["ds_function"],
            agg_name=sub.agg.name, fill_policy=pmeta["fill_policy"],
            fill_value=pmeta["fill_value"], rate=sub.rate,
            rate_counter=sub.rate_options.counter,
            rate_drop_resets=sub.rate_options.drop_resets,
            emit_raw=emit_raw,
            host=pmeta.get("host", False),
            complete=pmeta.get("complete", False)
            and not (sub.rate and sub.rate_options.drop_resets))
        if mesh is not None:
            # HBM-resident pre-sharded batch: only the tiny per-query
            # group-id vector uploads
            from opentsdb_tpu.parallel.sharded_pipeline import (
                run_sharded_device, sharded_grid_gids)
            gids_dev = sharded_grid_gids(
                mesh, group_ids, pmeta["s_pad"], num_groups)
            result, emit = self._run_device(
                lambda: run_sharded_device(
                    mesh, spec, cached_args + (gids_dev,),
                    pmeta["s_loc"], pmeta["b_loc"], num_groups,
                    sub.rate_options))
        else:
            (prep,) = cached_args
            from opentsdb_tpu.ops.pipeline import run_prepared
            result, emit = self._run_device(
                lambda: run_prepared(prep, bucket_ts, group_ids,
                                     spec, sub.rate_options),
                on_device=not spec.host)
        # stats and delete only after the dispatch succeeded: a device
        # failure falls back to the COLD path, which must still find
        # the data (scanned-and-deleted semantics) and must not see
        # DPS_POST_FILTER double-counted
        if stats:
            stats.add_stat(QueryStat.DPS_POST_FILTER, num_points)
            stats.add_stat(QueryStat.COMPUTE_TIME,
                           (time.monotonic() - t2) * 1e3)
        if tsq.delete and hasattr(store, "delete_range"):
            store.delete_range(sids, tsq.start_ms, tsq.end_ms)
        return self._build_results(
            tsq, sub, metric_name, sids, tag_mat, group_ids,
            num_groups, gb_kids, bucket_ts, result, emit)

    def _select_store(self, sub: TSSubQuery):
        """Pick raw store or a rollup tier (ref: TsdbQuery rollup
        best-match :143-150 with ROLLUP_USAGE fallback :750).
        Returns (store, metric_name, sids, rollup_scale,
        avg_count_store, ds_fn_override).

        ``avg_count_store`` is the COUNT-tier store when an ``avg``
        downsample is being answered from rollups: the reference
        derives rollup averages as SUM cells / COUNT cells
        (RollupConfig, RollupSpan agg-prefixed qualifiers); here the
        sum tier is the primary store and the count tier rides along
        for the grid division (``_avg_rollup_grid``).

        ``ds_fn_override`` replaces the downsample function when the
        tier's cells already carry the statistic: a ``count``
        downsample over the COUNT tier must SUM the stored counts,
        not count cells (ref: Downsampler.java:213 — the rollup-query
        COUNT branch accumulates nextValueCount()).
        """
        uids = self.tsdb.uids
        if sub.tsuids:
            return self._tsuid_store(sub)  # 6-tuple
        try:
            metric_id = uids.metrics.get_id(sub.metric)
        except LookupError:
            raise NoSuchMetricError(
                f"No such name for 'metrics': '{sub.metric}'") from None
        store = self.tsdb.store
        rollup_scale = 1.0
        avg_count_store = None
        ds_fn_override = None
        usage = (sub.rollup_usage or "ROLLUP_NOFALLBACK").upper()
        # a metric whose FIRST lifecycle demotion is in flight has
        # partial tier cells but no boundary yet: raw still holds
        # every point, so it is the only fully-correct source until
        # the boundary publishes and stitching takes over
        lc = self.tsdb.lifecycle
        lc_pin_raw = lc is not None and \
            lc.first_demotion_in_flight(metric_id)
        if (self.tsdb.rollup_store is not None and sub.ds_spec is not None
                and not sub.ds_spec.run_all and usage != "ROLLUP_RAW"
                and not lc_pin_raw):
            tier = self.tsdb.rollup_config.best_match(
                sub.ds_spec.interval_ms)
            agg_fn = sub.ds_spec.function
            rs = self.tsdb.rollup_store
            # cold segments ARE tier data: a tier whose RAM store was
            # fully spilled (and emptied) must still win selection, or
            # the on-disk history becomes unreachable. Lazy — the
            # common has_data()=True case never pays the name resolve
            # + segment-list scan (short-circuiting `or`).
            def has_cold():
                return (tier is not None and lc is not None
                        and lc.has_cold(metric_id, tier.interval))
            if tier is not None and agg_fn in ("sum", "count", "min",
                                               "max"):
                if rs.has_data(tier.interval, agg_fn) or has_cold():
                    store = self._maybe_stitch(
                        rs.tier(tier.interval, agg_fn), metric_id,
                        tier.interval, agg_fn)
                    if agg_fn == "count":
                        ds_fn_override = "sum"
            elif tier is not None and agg_fn == "avg" \
                    and (rs.has_data(tier.interval, "sum")
                         or has_cold()) \
                    and (rs.has_data(tier.interval, "count")
                         or has_cold()):
                store = self._maybe_stitch(
                    rs.tier(tier.interval, "sum"), metric_id,
                    tier.interval, "sum")
                avg_count_store = self._maybe_stitch(
                    rs.tier(tier.interval, "count"), metric_id,
                    tier.interval, "count")
        sids = store.series_ids_for_metric(metric_id)
        if store is not self.tsdb.store and len(sids) == 0 and \
                usage in ("ROLLUP_FALLBACK", "ROLLUP_FALLBACK_RAW"):
            store = self.tsdb.store
            sids = store.series_ids_for_metric(metric_id)
            avg_count_store = None
            ds_fn_override = None
        return (store, sub.metric, sids, rollup_scale, avg_count_store,
                ds_fn_override)

    def _maybe_stitch(self, tier_store, metric_id: int, interval: str,
                      agg: str):
        """Replace a selected tier store with the lifecycle manager's
        stitched view (tier history before the demotion boundary +
        raw tail after it) when the metric has a boundary; a metric
        that was never demoted keeps plain tier serving."""
        lc = self.tsdb.lifecycle
        if lc is None:
            return tier_store
        return lc.stitched(metric_id, interval, agg, tier_store) \
            or tier_store

    @staticmethod
    def _record_scan(stats, ms: float, num_points: int,
                     n_rows: int) -> None:
        """Storage-scan stat points (ref: the per-scanner stats block,
        QueryStats.java:137-151 — 'storage' here is the host column
        store, a column ≙ a stored point, a row ≙ a series)."""
        if not stats:
            return
        stats.add_stat(QueryStat.MATERIALIZE_TIME, ms)
        stats.add_stat(QueryStat.QUERY_SCAN_TIME, ms)
        stats.add_stat(QueryStat.HBASE_TIME, ms)
        stats.add_stat(QueryStat.DPS_POST_FILTER, num_points)
        stats.add_stat(QueryStat.COLUMNS_FROM_STORAGE, num_points)
        stats.add_stat(QueryStat.ROWS_FROM_STORAGE, n_rows)
        # 17 bytes per stored point: int64 ts + float64 value + flag
        stats.add_stat(QueryStat.BYTES_FROM_STORAGE, num_points * 17)
        stats.add_stat(QueryStat.SUCCESSFUL_SCAN, 1)

    # downsample functions the native pre-reduction can serve: linear
    # bucket statistics (sum/count/min/max; avg is sum over count)
    _GRID_FNS = frozenset(("sum", "zimsum", "pfsum", "count", "min",
                           "mimmin", "max", "mimmax", "avg"))

    def _grid_eligible(self, sub: TSSubQuery) -> bool:
        spec = sub.ds_spec
        return (spec is not None and not spec.run_all
                and not spec.use_calendar and spec.unit not in ("n", "y")
                and spec.function in self._GRID_FNS
                and spec.interval_ms > 0
                and self.tsdb.config.get_bool("tsd.query.grid_reduce",
                                              True))

    def _grid_pipeline(self, store, sids: np.ndarray, tsq: TSQuery,
                       sub: TSSubQuery, metric_name: str,
                       group_ids: np.ndarray, num_groups: int,
                       emit_raw: bool, rollup_scale: float, budget: int,
                       stats, ds_fn_override: str | None = None):
        """Storage-side downsample: one fused native pass produces the
        [S, B] grid (ref analogue: the scan + Downsampler stages of
        TsdbQuery.java:795 + Downsampler.java:28 collapsed into the
        storage engine), then the device runs only the
        fill/rate/interpolate/aggregate tail. Returns None when
        ineligible (caller falls through to the point paths), or
        (result, emit, bucket_ts) with result=None for no data."""
        if not self._grid_eligible(sub) or rollup_scale != 1.0:
            return None
        ds_spec = sub.ds_spec
        bucket_ts = ds_mod.fixed_bucket_edges(
            tsq.start_ms, tsq.end_ms, ds_spec.interval_ms)
        b = len(bucket_ts)
        mesh = self.tsdb.query_mesh
        if len(sids) * b > budget:
            return None  # blocked streaming handles the oversized case
        fn = ds_fn_override or ds_spec.function
        want_minmax = fn in ("min", "mimmin", "max", "mimmax")
        # small grids run the tail on the host CPU backend; decision is
        # per padded-shape class, matching warmup's pre-compiles
        host_dev = None
        if mesh is None:
            host_dev = self._tail_device(len(sids), b, num_groups,
                                         emit_raw, sub.agg.name)
        # device-resident cache: a warm repeat of this reduction skips
        # the host scan AND the upload (HBM ≙ HBase block cache).
        # Under a mesh the cached value is the pre-SHARDED device args
        # (grid + mask + bucket_ts + gids placed per the mesh specs).
        # Host-tail queries skip it: their native re-scan costs
        # milliseconds, and host-RAM entries must not evict
        # HBM-resident grids whose re-upload the cache exists to avoid
        # (nor report host bytes as device bytes).
        cache = self.tsdb.device_grid_cache if host_dev is None \
            else None
        ckey = cver = None
        grid = has_data = None
        mesh_args = mesh_meta = None
        if cache is not None:
            from opentsdb_tpu.query.device_cache import array_digest
            ckey = ("grid", _store_id(store), array_digest(
                np.ascontiguousarray(sids)), tsq.start_ms, tsq.end_ms,
                int(bucket_ts[0]), ds_spec.interval_ms, b, fn, mesh)
            cver = (store.points_written,
                    getattr(store, "mutation_epoch", 0))
            hit = cache.get(ckey, cver)
            if hit is not None:
                if mesh is not None:
                    mesh_args, mesh_meta = hit
                    num_points = mesh_meta["num_points"]
                    grid = True  # skip the host scan below
                else:
                    (grid, has_data), meta = hit
                    num_points = meta["num_points"]
        t1 = time.monotonic()
        if grid is None:
            sums, cnts, mins, maxs = store.bucket_reduce(
                sids, tsq.start_ms, tsq.end_ms, int(bucket_ts[0]),
                ds_spec.interval_ms, b, want_minmax=want_minmax)
            num_points = int(cnts.sum())
        self._record_scan(stats, (time.monotonic() - t1) * 1e3,
                          num_points, len(sids))
        self.tsdb.query_limits.check(metric_name, num_points)
        if tsq.delete and hasattr(store, "delete_range"):
            store.delete_range(sids, tsq.start_ms, tsq.end_ms)
        if num_points == 0:
            return (None, None, bucket_ts)
        if grid is None:
            present = cnts > 0
            if fn in ("sum", "zimsum", "pfsum"):
                grid = np.where(present, sums, np.nan)
            elif fn == "count":
                grid = np.where(present, cnts, np.nan)
            elif fn == "avg":
                grid = np.where(present, sums / np.maximum(cnts, 1.0),
                                np.nan)
            elif fn in ("min", "mimmin"):
                grid = np.where(present, mins, np.nan)
            else:  # max, mimmax
                grid = np.where(present, maxs, np.nan)
            has_data = present
            # pad to the geometric shape buckets NOW (host numpy,
            # once): cached device grids are pre-padded, warm queries
            # never pay a per-query device pad, and — on BOTH the
            # single-device and mesh paths — compiled programs are
            # keyed on bucketed shapes, so warmup's pre-compiles and
            # repeat queries of the same class actually hit
            from opentsdb_tpu.ops import shapes
            s0, b0 = grid.shape
            sp = shapes.shape_bucket(s0)
            bp = shapes.shape_bucket(b0)
            grid = shapes.pad_2d_host(grid, sp, bp, np.nan)
            has_data = shapes.pad_2d_host(has_data, sp, bp, False)
            if cache is not None and mesh is None:
                from opentsdb_tpu.ops.pipeline import put_grid
                grid, has_data = put_grid(grid, has_data)
                cache.put(ckey, cver, (grid, has_data),
                          {"num_points": num_points})
        t2 = time.monotonic()
        spec = PipelineSpec(
            num_series=len(sids), num_buckets=b, num_groups=num_groups,
            # the grid TAIL never reads ds_function (downsampling
            # already happened storage-side) but it IS part of the jit
            # static key — normalize it so sum/avg/min/... grid queries
            # share one compiled program per shape bucket (and the
            # server warmup covers them all)
            ds_function="avg", agg_name=sub.agg.name,
            fill_policy=ds_spec.fill_policy,
            fill_value=ds_spec.fill_value, rate=sub.rate,
            rate_counter=sub.rate_options.counter,
            rate_drop_resets=sub.rate_options.drop_resets,
            emit_raw=emit_raw, host=host_dev is not None)
        if mesh is not None:
            # the grid-TAIL step runs straight on the mesh (no
            # flatten-to-points re-bucketize), and the pre-sharded
            # device grids are cached — mesh queries get the same
            # warm-repeat behavior as single-device ones. Shapes are
            # geometrically bucketed exactly like execute_grid does
            # (bucket_grid_shapes), so the compiled shard_map program
            # set is bounded and tsd.tpu.warmup's mesh pre-compiles
            # are the programs real queries hit.
            from opentsdb_tpu.ops import shapes
            from opentsdb_tpu.ops.pipeline import _bucket_dims_and_aux
            from opentsdb_tpu.parallel.sharded_pipeline import (
                prepare_sharded_grid, run_sharded_grid,
                sharded_grid_gids)
            # dims from the RAW query shape (grid may be `True` on a
            # mesh-cache hit): identical to the fresh-grid pad above,
            # since shape_bucket is idempotent
            s_bk, b_bk, bts_bk, gids_bk, pspec = _bucket_dims_and_aux(
                bucket_ts, group_ids, spec,
                shapes.shape_bucket(len(sids)),
                shapes.shape_bucket(len(bucket_ts)))
            if mesh_args is None:
                data_args, s_loc, b_loc, s_pad = prepare_sharded_grid(
                    mesh, np.asarray(grid), np.asarray(has_data),
                    bts_bk)
                if cache is not None:
                    cache.put(ckey, cver, data_args,
                              {"num_points": num_points,
                               "s_loc": s_loc, "b_loc": b_loc,
                               "s_pad": s_pad})
            else:
                data_args = mesh_args
                s_loc = mesh_meta["s_loc"]
                b_loc = mesh_meta["b_loc"]
                s_pad = mesh_meta["s_pad"]
            gids_dev = sharded_grid_gids(mesh, gids_bk, s_pad,
                                         pspec.num_groups)
            host_retry = None
            if isinstance(grid, np.ndarray):
                # fresh (non-cache-hit) grid: the single-device host
                # tail can re-answer the same reduction on failure
                def host_retry():
                    from opentsdb_tpu.ops.pipeline import execute_grid
                    return execute_grid(
                        grid, has_data, bucket_ts, group_ids,
                        replace(spec, host=True), sub.rate_options,
                        device=self._host_cpu())
            result, emit = self._run_device(
                lambda: run_sharded_grid(
                    mesh, pspec, data_args + (gids_dev,), s_loc,
                    b_loc, num_groups, sub.rate_options), host_retry)
            rows = len(sids) if emit_raw else num_groups
            result = result[:rows, :len(bucket_ts)]
            emit = emit[:rows, :len(bucket_ts)]
        else:
            from opentsdb_tpu.ops.pipeline import execute_grid

            def host_retry():
                return execute_grid(grid, has_data, bucket_ts,
                                    group_ids,
                                    replace(spec, host=True),
                                    sub.rate_options,
                                    device=self._host_cpu())

            result, emit = self._run_device(
                lambda: execute_grid(grid, has_data, bucket_ts,
                                     group_ids, spec,
                                     sub.rate_options,
                                     device=host_dev),
                host_retry, on_device=host_dev is None)
        if stats:
            stats.add_stat(QueryStat.COMPUTE_TIME,
                           (time.monotonic() - t2) * 1e3)
        return result, emit, bucket_ts

    def _avg_rollup_pipeline(self, sum_store, cnt_store,
                             sids: np.ndarray, tsq: TSQuery,
                             sub: TSSubQuery, metric_name: str,
                             group_ids: np.ndarray, num_groups: int,
                             emit_raw: bool, stats):
        """Answer an ``avg`` downsample from rollup tiers: bucketized
        SUM cells divided by bucketized COUNT cells — the true weighted
        average, not a mean of per-tier-point averages (ref: RollupSpan
        reading agg-prefixed sum+count qualifiers from one row).
        Returns (result, emit, bucket_ts) or None for no data."""
        t1 = time.monotonic()
        # count series aligned to sum series by (metric, tags)
        # identity — computed lazily: a device-cache hit never needs it
        csids = present = None

        def align():
            nonlocal csids, present
            if csids is None:
                csids = _match_series_by_tags(
                    sum_store, cnt_store, sids,
                    sum_store.series(int(sids[0])).metric_id)
                present = np.nonzero(csids >= 0)[0]
            return csids, present

        ds_spec = sub.ds_spec
        fixed = (not ds_spec.run_all and not ds_spec.use_calendar
                 and ds_spec.unit not in ("n", "y")
                 and ds_spec.interval_ms > 0)
        host_dev = None
        if fixed:
            # native pre-reduction: both tiers collapse to [S, B] sums
            # in one storage pass each — no per-point upload
            bucket_ts = ds_mod.fixed_bucket_edges(
                tsq.start_ms, tsq.end_ms, ds_spec.interval_ms)
            s, b = len(sids), len(bucket_ts)
            t0_ms = int(bucket_ts[0])
            mesh = self.tsdb.query_mesh
            if mesh is None:
                host_dev = self._tail_device(s, b, num_groups,
                                             emit_raw, sub.agg.name)
            # host-tail queries skip the device cache (see
            # _grid_pipeline: cheap native re-scan; host RAM must not
            # evict HBM-resident grids)
            cache = self.tsdb.device_grid_cache \
                if mesh is None and host_dev is None else None
            ckey = cver = None
            gs = gc = None
            if cache is not None:
                from opentsdb_tpu.query.device_cache import \
                    array_digest
                ckey = ("avgdiv", _store_id(sum_store),
                        _store_id(cnt_store),
                        array_digest(np.ascontiguousarray(sids)),
                        tsq.start_ms, tsq.end_ms, t0_ms,
                        ds_spec.interval_ms, b)
                cver = (sum_store.points_written,
                        getattr(sum_store, "mutation_epoch", 0),
                        cnt_store.points_written,
                        getattr(cnt_store, "mutation_epoch", 0))
                hit = cache.get(ckey, cver)
                if hit is not None:
                    (gs, gc), meta = hit
                    num_points = meta["num_points"]
            if gs is None:
                csids, present = align()
                sum_s, cnt_s, _, _ = sum_store.bucket_reduce(
                    sids, tsq.start_ms, tsq.end_ms, t0_ms,
                    ds_spec.interval_ms, b)
                if len(present) == s:
                    sum_c, cnt_c, _, _ = cnt_store.bucket_reduce(
                        csids, tsq.start_ms, tsq.end_ms, t0_ms,
                        ds_spec.interval_ms, b)
                else:
                    sum_c = np.zeros((s, b))
                    cnt_c = np.zeros((s, b))
                    if len(present):
                        sc, cc, _, _ = cnt_store.bucket_reduce(
                            csids[present], tsq.start_ms, tsq.end_ms,
                            t0_ms, ds_spec.interval_ms, b)
                        sum_c[present] = sc
                        cnt_c[present] = cc
                num_points = int(cnt_s.sum() + cnt_c.sum())
                # write NaN holes in place (np.where would copy 4x
                # ~100MB at 1M series)
                sum_s[cnt_s == 0] = np.nan
                sum_c[cnt_c == 0] = np.nan
                gs, gc = sum_s, sum_c
                if self.tsdb.query_mesh is None:
                    # pre-pad to the shape buckets (host, once; the
                    # cache then holds padded device grids — no
                    # per-query device pads on the warm path)
                    from opentsdb_tpu.ops import shapes
                    sp = shapes.shape_bucket(s)
                    bp = shapes.shape_bucket(b)
                    gs = shapes.pad_2d_host(gs, sp, bp, np.nan)
                    gc = shapes.pad_2d_host(gc, sp, bp, np.nan)
                if cache is not None and num_points:
                    from opentsdb_tpu.ops.pipeline import pipeline_dtype
                    import jax
                    import jax.numpy as jnp
                    dt = pipeline_dtype()
                    gs = jax.device_put(jnp.asarray(gs, dtype=dt))
                    gc = jax.device_put(jnp.asarray(gc, dtype=dt))
                    cache.put(ckey, cver, (gs, gc),
                              {"num_points": num_points})
        else:
            csids, present = align()
            batch_s = sum_store.materialize(sids, tsq.start_ms,
                                            tsq.end_ms)
            batch_c = cnt_store.materialize(csids[present],
                                            tsq.start_ms, tsq.end_ms)
            num_points = batch_s.num_points + batch_c.num_points
        self._record_scan(stats, (time.monotonic() - t1) * 1e3,
                          num_points, len(sids))
        self.tsdb.query_limits.check(metric_name, num_points)
        if tsq.delete:
            csids, present = align()
            sum_store.delete_range(sids, tsq.start_ms, tsq.end_ms)
            cnt_store.delete_range(csids[present], tsq.start_ms,
                                   tsq.end_ms)
        if num_points == 0:
            return None
        t2 = time.monotonic()
        if not fixed:
            if batch_s.num_points == 0:
                return None
            bidx_s, bucket_ts = ds_mod.assign_buckets(
                batch_s.ts_ms, sub.ds_spec, tsq.start_ms, tsq.end_ms)
            bidx_c, _ = ds_mod.assign_buckets(
                batch_c.ts_ms, sub.ds_spec, tsq.start_ms, tsq.end_ms)
            s, b = len(sids), len(bucket_ts)
            # both grids stay on device: bucketize returns device
            # arrays and the division happens in the same trace
            gs, _ = ds_mod.bucketize(batch_s.values, batch_s.series_idx,
                                     bidx_s, s, b, "sum")
            sidx_c = present[batch_c.series_idx].astype(np.int32)
            gc, _ = ds_mod.bucketize(batch_c.values, sidx_c, bidx_c, s,
                                     b, "sum")
        spec = PipelineSpec(
            num_series=s, num_buckets=b, num_groups=num_groups,
            ds_function="avg", agg_name=sub.agg.name,
            fill_policy=sub.ds_spec.fill_policy,
            fill_value=sub.ds_spec.fill_value, rate=sub.rate,
            rate_counter=sub.rate_options.counter,
            rate_drop_resets=sub.rate_options.drop_resets,
            emit_raw=emit_raw, host=host_dev is not None)
        mesh = self.tsdb.query_mesh
        if mesh is not None:
            # divide host-side, then run the rate/fill/agg tail over
            # the mesh with one point per present grid cell (bucketize
            # of a single-point cell reproduces the cell exactly)
            from opentsdb_tpu.ops.pipeline import avg_divide_grid
            avg, valid = avg_divide_grid(np.asarray(gs), np.asarray(gc),
                                         xp=np)
            valid = np.asarray(valid)
            sidx2, bidx2 = np.nonzero(valid)
            result, emit = self._run_device(
                lambda: self._mesh_execute(
                    mesh, spec, avg[valid], sidx2.astype(np.int32),
                    bidx2.astype(np.int32), bucket_ts, group_ids,
                    sub.rate_options))
        else:
            def host_retry():
                return execute_avg_divide(
                    gs, gc, bucket_ts, group_ids,
                    replace(spec, host=True), sub.rate_options,
                    device=self._host_cpu())

            result, emit = self._run_device(
                lambda: execute_avg_divide(
                    gs, gc, bucket_ts, group_ids, spec,
                    sub.rate_options, device=host_dev),
                host_retry, on_device=host_dev is None)
        if stats:
            stats.add_stat(QueryStat.COMPUTE_TIME,
                           (time.monotonic() - t2) * 1e3)
        return result, emit, bucket_ts

    def _mesh_execute(self, mesh, spec, values, series_idx, bucket_idx,
                      bucket_ts, group_ids, rate_options):
        """Run one sub-query's compute over the configured device mesh
        (series axis ≙ salt buckets, time axis ≙ long-range blocking;
        ref: SaltScanner.java:70, TsdbQuery.java:795)."""
        from opentsdb_tpu.parallel.sharded_pipeline import (
            prepare_sharded_batch, run_sharded)
        batch = prepare_sharded_batch(
            values, series_idx, bucket_idx, bucket_ts, group_ids,
            spec.num_series, spec.num_groups, mesh.shape["series"],
            mesh.shape["time"])
        return run_sharded(mesh, spec, batch, rate_options)

    def _tsuid_store(self, sub: TSSubQuery):
        """Resolve explicit TSUID hex strings to series ids
        (ref: TsdbQuery tsuid query path)."""
        uids = self.tsdb.uids
        store = self.tsdb.store
        mw = uids.metrics.width
        kw = uids.tag_names.width
        vw = uids.tag_values.width
        sids = []
        metric_name = None
        for tsuid in sub.tsuids:
            raw = bytes.fromhex(tsuid)
            metric_id = int.from_bytes(raw[:mw], "big")
            tags = []
            pos = mw
            while pos < len(raw):
                kid = int.from_bytes(raw[pos:pos + kw], "big")
                vid = int.from_bytes(raw[pos + kw:pos + kw + vw], "big")
                tags.append((kid, vid))
                pos += kw + vw
            name = uids.metrics.get_name(metric_id)
            if metric_name is None:
                metric_name = name
            elif name != metric_name:
                raise BadRequestError(
                    "Multiple metrics in the same tsuid query")
            key = (metric_id, tuple(sorted(tags)))
            sid = store._key_to_sid.get(key)
            if sid is not None:
                sids.append(sid)
        return (store, metric_name or "", np.asarray(
            sids, dtype=np.int64), 1.0, None, None)

    # ------------------------------------------------------------------

    def _apply_filters(self, store: TimeSeriesStore, sub: TSSubQuery,
                       sids: np.ndarray
                       ) -> tuple[np.ndarray, TagMatrix]:
        metric_id = store.series(int(sids[0])).metric_id
        idx = store.metric_index(metric_id)
        if idx is not None and not sub.tsuids:
            idx_sids, triples = idx.arrays()
            # per-(store, metric) matrix cache: the index is
            # append-only, so the series count versions it
            tm_cache = self.tsdb._tagmat_cache
            tm_key = (_store_id(store), metric_id)
            hit = tm_cache.get(tm_key)
            if hit is not None and hit[0] == len(idx_sids) \
                    and sids is idx_sids:
                tags = hit[1]
            else:
                tags = TagMatrix.from_triples(sids, triples)
                if sids is idx_sids:
                    tm_cache[tm_key] = (len(idx_sids), tags)
        else:
            # tsuid queries name few series; a record walk is fine here
            rows = []
            for s in sids:
                rec = store.series(int(s))
                for kid, vid in rec.tags:
                    rows.append((rec.series_id, kid, vid))
            triples = (np.asarray(rows, dtype=np.int64).reshape(-1, 3)
                       if rows else np.empty((0, 3), dtype=np.int64))
            tags = TagMatrix.from_triples(sids, triples)
        if sub.filters:
            mask = self._filter_eval.apply(sub.filters, sids, triples)
            sids = sids[mask]
            tags = tags.select(mask)
        if sub.explicit_tags and sub.filters:
            # keep series whose tag-KEY set equals the filters' key set
            # (ref: explicit_tags pruning in findSpans)
            filter_keys = set()
            for f in sub.filters:
                try:
                    filter_keys.add(
                        self.tsdb.uids.tag_names.get_id(f.tagk))
                except LookupError:
                    pass
            fk = np.asarray(sorted(filter_keys), dtype=np.int64)
            if len(np.setdiff1d(fk, tags.kids)):
                # a required key no series carries: nothing matches
                keep = np.zeros(len(sids), dtype=bool)
            else:
                in_filter = np.isin(tags.kids, fk)
                keep = ((tags.vids >= 0) == in_filter[None, :]) \
                    .all(axis=1)
            sids = sids[keep]
            tags = tags.select(keep)
        return sids, tags

    @staticmethod
    def _group_ids(tags: TagMatrix, gb_kids: list[int]
                   ) -> tuple[np.ndarray, int]:
        """Group id per series + group count. Group key = tuple of
        group-by tagv ids; ids come out ordered by concatenated tagv id,
        matching the reference's ByteMap ordering of group keys
        (ref: GroupByAndAggregateCB, TsdbQuery.java:995-1036)."""
        if not gb_kids:
            return np.zeros(tags.num_series, dtype=np.int32), 1
        mat = np.empty((tags.num_series, len(gb_kids)), dtype=np.int64)
        for j, k in enumerate(gb_kids):
            col = tags.col(k)
            mat[:, j] = col if col is not None else -1
        return compact_row_labels(mat)

    # ------------------------------------------------------------------

    def _build_results(self, tsq, sub, metric_name, sids, tags,
                       group_ids, num_groups, gb_kids, bucket_ts,
                       result, emit) -> list[QueryResult]:
        from opentsdb_tpu.query.model import effective_pixels as _epx
        with trace_span("query.assemble", sub=sub.index,
                        groups=num_groups,
                        pixels=_epx(tsq, sub)[0]):
            return self._build_results_inner(
                tsq, sub, metric_name, sids, tags, group_ids,
                num_groups, gb_kids, bucket_ts, result, emit)

    def _build_results_inner(self, tsq, sub, metric_name, sids, tags,
                             group_ids, num_groups, gb_kids,
                             bucket_ts, result, emit
                             ) -> list[QueryResult]:
        uids = self.tsdb.uids
        out: list[QueryResult] = []
        # one device->host fetch; per-group row indexing of a device
        # array would round-trip per group
        result = np.asarray(result)
        emit = np.asarray(emit, dtype=bool)
        # pixel-aware output reduction (ops/visual_downsample): the
        # FINAL serve-path stage, after downsample/fill/rate/
        # interpolate/aggregate — a keep-mask intersection, so every
        # emitted point below is a real computed point. Applies to
        # every producer funneling through here (grid / point / avg /
        # prep-hit / streaming plan.serve), keyed off the REQUESTING
        # sub-query, so a pixel-less standing plan still serves a
        # pixel-budgeted pull correctly.
        from opentsdb_tpu.query.model import effective_pixels
        px, px_fn = effective_pixels(tsq, sub)
        if px and not tsq.delete:
            from opentsdb_tpu.ops import visual_downsample as vd
            keep = vd.keep_mask(result, emit, np.asarray(bucket_ts),
                                tsq.start_ms, tsq.end_ms, px, px_fn)
            if keep is not None:
                emit = emit & keep
        fetch_annotations = not tsq.no_annotations and \
            self.tsdb.annotations.has_any()
        # output timestamps precomputed once for every group
        bucket_ts = np.asarray(bucket_ts, dtype=np.int64)
        ts_out = (bucket_ts if tsq.ms_resolution
                  else (bucket_ts // 1000) * 1000)
        # group membership via one sort (the per-gid nonzero scan was
        # O(G*S) — quadratic under wildcard group-by)
        order = np.argsort(group_ids, kind="stable")
        sorted_gids = group_ids[order]
        gid_range = np.arange(num_groups, dtype=group_ids.dtype)
        starts = np.searchsorted(sorted_gids, gid_range, side="left")
        ends = np.searchsorted(sorted_gids, gid_range, side="right")
        # SpanGroup tag semantics for ALL groups in two segment
        # reductions: a key with min vid >= 0 is present on every
        # member; min == max means one distinct value
        kname = _UidNameCache(uids.tag_names)
        vname = _UidNameCache(uids.tag_values)
        k_cnt = tags.vids.shape[1]
        if k_cnt and len(order):
            v_sorted = tags.vids[order]
            # clip so reduceat never indexes past the end; an empty
            # group's row is garbage but its gid is skipped below
            seg = np.minimum(starts, len(order) - 1)
            minv = np.minimum.reduceat(v_sorted, seg, axis=0)
            maxv = np.maximum.reduceat(v_sorted, seg, axis=0)
        else:
            minv = maxv = np.empty((num_groups, 0), dtype=np.int64)
        metric_id = None
        if tsq.show_tsuids or sub.tsuids or fetch_annotations:
            try:
                metric_id = uids.metrics.get_id(metric_name)
            except LookupError:
                metric_id = None
        # emit extraction for ALL groups in one nonzero pass: under
        # wildcard group-by (1000+ groups) the per-group
        # nonzero/slice/asarray loop was the second-largest host cost
        # of the whole query after serialization
        e_gidx, e_bidx = np.nonzero(emit)
        e_starts = np.searchsorted(e_gidx, gid_range, side="left")
        e_ends = np.searchsorted(e_gidx, gid_range, side="right")
        e_ts = ts_out[e_bidx]
        e_vals = np.asarray(result[e_gidx, e_bidx], dtype=np.float64)
        for gid in range(num_groups):
            members = order[starts[gid]:ends[gid]]
            if len(members) == 0:
                continue
            lo_e, hi_e = e_starts[gid], e_ends[gid]
            if lo_e == hi_e:
                continue
            dps_arrays = (e_ts[lo_e:hi_e], e_vals[lo_e:hi_e])
            g_tags: dict[str, str] = {}
            agg_tags: list[str] = []
            for j in range(k_cnt):
                lo = minv[gid, j]
                if lo < 0:
                    continue  # key absent on some member: vanishes
                if lo == maxv[gid, j]:
                    g_tags[kname(int(tags.kids[j]))] = vname(int(lo))
                else:
                    agg_tags.append(kname(int(tags.kids[j])))
            tsuids = []
            if (tsq.show_tsuids or sub.tsuids) and metric_id is not None:
                for m in members:
                    tsuids.append(uids.tsuid(
                        metric_id, tags.tags_of(m)).hex().upper())
            annotations = []
            if fetch_annotations and metric_id is not None:
                start_s = tsq.start_ms // 1000
                end_s = tsq.end_ms // 1000
                for m in members:
                    tsuid_hex = uids.tsuid(
                        metric_id, tags.tags_of(m)).hex().upper()
                    annotations.extend(
                        self.tsdb.annotations.range(tsuid_hex,
                                                    start_s, end_s))
            global_annotations = []
            if tsq.global_annotations:
                global_annotations = self.tsdb.annotations.global_range(
                    tsq.start_ms // 1000, tsq.end_ms // 1000)
            out.append(QueryResult(
                metric=metric_name, tags=g_tags,
                aggregated_tags=agg_tags,
                tsuids=tsuids, annotations=annotations,
                global_annotations=global_annotations,
                sub_query_index=sub.index, dps_arrays=dps_arrays))
        return out


def _match_series_by_tags(src_store, dst_store, sids: np.ndarray,
                          metric_id: int) -> np.ndarray:
    """For each src-store series id, the dst-store series id with the
    identical (metric, tags) key, or -1 — fully vectorized (the rollup
    avg path aligns the count tier to the sum tier this way; a
    dict-lookup walk costs seconds at 1M series).

    Exact match: both stores' tag matrices are built over the union key
    space, so equal rows <=> equal tag sets (ref: RollupSpan reading
    sum+count qualifiers of one row — same series identity)."""
    dst_sids = dst_store.series_ids_for_metric(metric_id)
    if len(dst_sids) == 0 or len(sids) == 0:
        return np.full(len(sids), -1, dtype=np.int64)
    _, src_triples = src_store.metric_index(metric_id).arrays()
    _, dst_triples = dst_store.metric_index(metric_id).arrays()
    kids = np.union1d(
        np.unique(src_triples[:, 1]) if len(src_triples)
        else np.empty(0, dtype=np.int64),
        np.unique(dst_triples[:, 1]) if len(dst_triples)
        else np.empty(0, dtype=np.int64))
    a = TagMatrix.from_triples(sids, src_triples, kids=kids).vids
    b = TagMatrix.from_triples(dst_sids, dst_triples, kids=kids).vids
    both = np.concatenate([a, b], axis=0)
    labels, _ = compact_row_labels(both)
    la, lb = labels[:len(a)], labels[len(a):]
    order = np.argsort(lb, kind="stable")
    lb_sorted = lb[order]
    pos = np.searchsorted(lb_sorted, la)
    pos_c = np.minimum(pos, len(lb_sorted) - 1)
    hit = lb_sorted[pos_c] == la
    return np.where(hit, dst_sids[order[pos_c]], -1)


def _common_tags(tags: TagMatrix, members: np.ndarray, uids
                 ) -> tuple[dict[str, str], list[str]]:
    """SpanGroup semantics for ONE group (small paths — the engine's
    main loop computes all groups at once in ``_build_results``):
    ``tags`` = k=v pairs identical across every member series;
    ``aggregateTags`` = keys present everywhere with differing values
    (keys missing from some series vanish)."""
    sub = tags.vids[members]
    out_tags: dict[str, str] = {}
    agg_tags: list[str] = []
    for j, kid in enumerate(tags.kids):
        col = sub[:, j]
        lo = int(col.min()) if len(col) else -1
        if lo < 0:
            continue
        kname = uids.tag_names.get_name(int(kid))
        if lo == int(col.max()):
            out_tags[kname] = uids.tag_values.get_name(lo)
        else:
            agg_tags.append(kname)
    return out_tags, agg_tags
