"""The query engine (ref: ``src/core/TsdbQuery.java:64``).

Compiles one validated :class:`TSQuery` into the array pipeline:

1. resolve metric + filters against the UID tables
   (``configureFromQuery`` :434)
2. vectorized series selection over the metric's tag index
   (replaces scanner row-regex + post-scan filters, ``findSpans`` :795)
3. group-key construction from group-by tagv ids
   (``GroupByAndAggregateCB`` :916-1045)
4. time-grid construction: downsample buckets, or the union of distinct
   timestamps when no downsample is given (the reference's
   AggregationIterator emits at the union of span timestamps)
5. one fused device pipeline per sub-query
   (:mod:`opentsdb_tpu.ops.pipeline`)
6. result assembly with the reference's tags/aggregateTags semantics
   (SpanGroup: tags = identical k=v across all series; aggregateTags =
   keys present everywhere with differing values)
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from opentsdb_tpu.core import store as store_mod
from opentsdb_tpu.core.store import TimeSeriesStore
from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.ops.blocked import (DEFAULT_CELL_BUDGET,
                                      execute_blocked,
                                      pick_block_buckets)
from opentsdb_tpu.ops.pipeline import (PipelineSpec, execute,
                                       execute_auto, execute_avg_divide,
                                       flatten_padded)
from opentsdb_tpu.query import filters as filters_mod
from opentsdb_tpu.query.model import BadRequestError, TSQuery, TSSubQuery
from opentsdb_tpu.stats.stats import QueryStat, QueryStats


@dataclass
class QueryResult:
    """One output group — the analogue of one ``DataPoints`` object."""
    metric: str
    tags: dict[str, str]
    aggregated_tags: list[str]
    dps: list[tuple[int, float]]          # (ts_ms, value)
    tsuids: list[str] = field(default_factory=list)
    annotations: list = field(default_factory=list)
    global_annotations: list = field(default_factory=list)
    sub_query_index: int = 0


class NoSuchMetricError(BadRequestError):
    pass


# Padded-layout guards: padding inflation is bounded by the skew factor
# (pad cells per real point) once batches are big enough to matter, and
# by an absolute S*Pmax cell ceiling (host RAM).
_PADDED_SKEW_FACTOR = 4
_PADDED_MIN_CELLS = 10_000_000
_PADDED_ABS_MAX_CELLS = 500_000_000


class QueryEngine:
    """(ref: TsdbQuery; one instance per TSQuery execution)"""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        self._filter_eval = filters_mod.FilterEvaluator(tsdb.uids)

    # ------------------------------------------------------------------

    def run(self, ts_query: TSQuery,
            stats: QueryStats | None = None) -> list[QueryResult]:
        results: list[QueryResult] = []
        for sub in ts_query.queries:
            results.extend(self._run_sub(ts_query, sub, stats))
        return results

    # ------------------------------------------------------------------

    def _run_sub(self, tsq: TSQuery, sub: TSSubQuery,
                 stats: QueryStats | None) -> list[QueryResult]:
        t0 = time.monotonic()
        uids = self.tsdb.uids
        if sub.percentiles:
            from opentsdb_tpu.query.histogram_engine import \
                run_histogram_subquery
            return run_histogram_subquery(self.tsdb, tsq, sub)
        (store, metric_name, sids, rollup_scale,
         avg_count_store) = self._select_store(sub)
        budget = self.tsdb.config.get_int(
            "tsd.query.max_device_cells", 0) or DEFAULT_CELL_BUDGET
        if avg_count_store is not None:
            # the sum/count grid division materializes [S, B] whole;
            # oversized ranges go to the raw streaming path instead —
            # but only when raw data actually exists (rolled-up data
            # may outlive its raw source), else an expensive exact
            # answer beats a cheap empty one
            b_est = ((tsq.end_ms - tsq.start_ms)
                     // max(sub.ds_spec.interval_ms, 1)) + 2
            if len(sids) * b_est > budget:
                raw_sids = self.tsdb.store.series_ids_for_metric(
                    uids.metrics.get_id(sub.metric))
                if len(raw_sids):
                    avg_count_store = None
                    store = self.tsdb.store
                    sids = raw_sids
        if len(sids) == 0:
            return []

        # --- filters -> series mask (ref: findSpans post-scan filters)
        sids, series_tags = self._apply_filters(store, sub, sids)
        if len(sids) == 0:
            return []
        if stats:
            stats.add_stat(QueryStat.STRING_TO_UID_TIME,
                           (time.monotonic() - t0) * 1e3)

        # --- group construction (ref: GroupByAndAggregateCB :916)
        gb_tagks = sorted({f.tagk for f in sub.filters if f.group_by})
        gb_kids = []
        for k in gb_tagks:
            try:
                gb_kids.append(uids.tag_names.get_id(k))
            except LookupError:
                return []
        group_ids, group_keys = self._group_ids(series_tags, gb_kids)
        emit_raw = sub.agg.is_none
        if emit_raw:
            group_ids = np.arange(len(sids), dtype=np.int32)
            group_keys = [(i,) for i in range(len(sids))]
        num_groups = len(group_keys)

        if avg_count_store is not None:
            out = self._avg_rollup_pipeline(
                store, avg_count_store, sids, tsq, sub, metric_name,
                group_ids, num_groups, emit_raw, stats)
            if out is None:
                return []
            result, emit, bucket_ts = out
            return self._build_results(
                tsq, sub, metric_name, sids, series_tags, group_ids,
                group_keys, gb_kids, bucket_ts, result, emit)

        # --- materialize + time grid (row-padded layout: the ragged ->
        # dense transposition happens inside materialize, so the device
        # path never needs a scatter; see PaddedBatch). Skewed batches
        # (one dense series among many sparse ones would blow S * Pmax
        # up quadratically) stay on the flat layout.
        t1 = time.monotonic()
        counts = store.count_range(sids, tsq.start_ms, tsq.end_ms)
        total = int(counts.sum())
        pmax = int(counts.max()) if len(counts) else 0
        cells = len(sids) * pmax
        use_padded = total > 0 and \
            cells <= max(_PADDED_SKEW_FACTOR * total,
                         _PADDED_MIN_CELLS) and \
            cells <= _PADDED_ABS_MAX_CELLS
        if use_padded:
            padded = store.materialize_padded(sids, tsq.start_ms,
                                              tsq.end_ms)
            num_points = total
        else:
            padded = None
            batch = store.materialize(sids, tsq.start_ms, tsq.end_ms)
            num_points = batch.num_points
        if stats:
            stats.add_stat(QueryStat.MATERIALIZE_TIME,
                           (time.monotonic() - t1) * 1e3)
            stats.add_stat(QueryStat.DPS_POST_FILTER, num_points)
        # byte/dp guardrails (ref: SaltScanner budget enforcement via
        # QueryLimitOverride)
        self.tsdb.query_limits.check(metric_name, num_points)
        if tsq.delete and hasattr(store, "delete_range"):
            # scanned-and-deleted semantics: the response still carries
            # the data just removed (ref: TsdbQuery delete=true turning
            # scans into DeleteRequests after collection)
            store.delete_range(sids, tsq.start_ms, tsq.end_ms)
        if num_points == 0:
            return []
        bucket_idx2d = bucket_idx = None
        if sub.ds_spec is not None:
            ds_function = sub.ds_spec.function
            fill_policy = sub.ds_spec.fill_policy
            fill_value = sub.ds_spec.fill_value
            if padded is not None:
                bucket_idx2d, bucket_ts = ds_mod.assign_buckets_padded(
                    padded.ts2d, padded.counts, sub.ds_spec,
                    tsq.start_ms, tsq.end_ms)
            else:
                bucket_idx, bucket_ts = ds_mod.assign_buckets(
                    batch.ts_ms, sub.ds_spec, tsq.start_ms, tsq.end_ms)
        else:
            # union-of-timestamps grid: every distinct input timestamp
            # is an output point, like the reference's merge iterator
            ds_function = "sum"  # one point per (series, ts) after dedupe
            fill_policy = ds_mod.FillPolicy.NONE
            fill_value = float("nan")
            if padded is not None:
                pad = store_mod.pad_mask(padded.counts,
                                         padded.ts2d.shape[1])
                bucket_ts, inverse = np.unique(padded.ts2d.reshape(-1),
                                               return_inverse=True)
                bucket_idx2d = inverse.reshape(padded.ts2d.shape) \
                    .astype(np.int32)
                bucket_idx2d[pad] = -1
                if pad.any():
                    # drop union slots only pad sentinels produced
                    used = np.zeros(len(bucket_ts), dtype=bool)
                    used[bucket_idx2d[~pad]] = True
                    remap = np.cumsum(used) - 1
                    bucket_ts = bucket_ts[used]
                    bucket_idx2d = np.where(
                        bucket_idx2d >= 0, remap[bucket_idx2d], -1
                    ).astype(np.int32)
            else:
                bucket_ts, bucket_idx = np.unique(batch.ts_ms,
                                                  return_inverse=True)
                bucket_idx = bucket_idx.astype(np.int32)

        # --- device pipeline
        t2 = time.monotonic()
        spec = PipelineSpec(
            num_series=len(sids), num_buckets=len(bucket_ts),
            num_groups=num_groups, ds_function=ds_function,
            agg_name=sub.agg.name, fill_policy=fill_policy,
            fill_value=fill_value, rate=sub.rate,
            rate_counter=sub.rate_options.counter,
            rate_drop_resets=sub.rate_options.drop_resets,
            emit_raw=emit_raw)
        if rollup_scale != 1.0:
            if padded is not None:
                padded = padded._replace(values2d=padded.values2d
                                         * rollup_scale)
            else:
                batch = batch._replace(values=batch.values
                                       * rollup_scale)
        mesh = self.tsdb.query_mesh
        # the mesh raises the streaming threshold only when every
        # device truly holds S_loc x B_loc cells: non-psum-reducible
        # aggregators all_gather the full series axis (sharded step),
        # so their per-device footprint stays [S, B] and the budget
        # must not scale
        from opentsdb_tpu.parallel.sharded_pipeline import REDUCIBLE_AGGS
        n_mesh = int(np.prod(list(mesh.shape.values()))) \
            if mesh is not None else 1
        mesh_scale = n_mesh if sub.agg.name in REDUCIBLE_AGGS else 1
        use_blocked = not emit_raw and \
            len(sids) * len(bucket_ts) > budget * mesh_scale
        if padded is not None and (use_blocked or mesh is not None):
            values, series_idx, bucket_idx = flatten_padded(
                padded.values2d, bucket_idx2d, padded.counts)
        elif use_blocked or mesh is not None:
            values, series_idx = batch.values, batch.series_idx
        if use_blocked:
            # long-range streaming: bound HBM at [S x block] cells
            # (SURVEY.md §5.7 time-axis blocking)
            result, emit = execute_blocked(
                values, series_idx, bucket_idx, bucket_ts,
                group_ids, spec, sub.rate_options,
                block_buckets=pick_block_buckets(
                    len(sids), len(bucket_ts), budget))
        elif mesh is not None:
            # multi-chip: shard the point batch over the
            # ('series','time') mesh — the salt-scanner fan-out/merge
            # as XLA collectives (SaltScanner.java:70, SURVEY §2.11)
            result, emit = self._mesh_execute(
                mesh, spec, values, series_idx, bucket_idx, bucket_ts,
                group_ids, sub.rate_options)
        elif padded is not None:
            result, emit = execute_auto(
                padded, bucket_idx2d, bucket_ts, group_ids, spec,
                sub.rate_options)
        else:
            result, emit = execute(
                batch.values, batch.series_idx, bucket_idx, bucket_ts,
                group_ids, spec, sub.rate_options)
        if stats:
            stats.add_stat(QueryStat.COMPUTE_TIME,
                           (time.monotonic() - t2) * 1e3)

        # --- assemble output groups
        return self._build_results(
            tsq, sub, metric_name, sids, series_tags, group_ids,
            group_keys, gb_kids, bucket_ts, result, emit)

    # ------------------------------------------------------------------

    def _select_store(self, sub: TSSubQuery
                      ) -> tuple[TimeSeriesStore, str, np.ndarray, float,
                                 TimeSeriesStore | None]:
        """Pick raw store or a rollup tier (ref: TsdbQuery rollup
        best-match :143-150 with ROLLUP_USAGE fallback :750).

        The last element is the COUNT-tier store when an ``avg``
        downsample is being answered from rollups: the reference
        derives rollup averages as SUM cells / COUNT cells
        (RollupConfig, RollupSpan agg-prefixed qualifiers); here the
        sum tier is the primary store and the count tier rides along
        for the grid division (``_avg_rollup_grid``).
        """
        uids = self.tsdb.uids
        if sub.tsuids:
            return self._tsuid_store(sub)
        try:
            metric_id = uids.metrics.get_id(sub.metric)
        except LookupError:
            raise NoSuchMetricError(
                f"No such name for 'metrics': '{sub.metric}'") from None
        store = self.tsdb.store
        rollup_scale = 1.0
        avg_count_store = None
        usage = (sub.rollup_usage or "ROLLUP_NOFALLBACK").upper()
        if (self.tsdb.rollup_store is not None and sub.ds_spec is not None
                and not sub.ds_spec.run_all and usage != "ROLLUP_RAW"):
            tier = self.tsdb.rollup_config.best_match(
                sub.ds_spec.interval_ms)
            agg_fn = sub.ds_spec.function
            rs = self.tsdb.rollup_store
            if tier is not None and agg_fn in ("sum", "count", "min",
                                               "max"):
                if rs.has_data(tier.interval, agg_fn):
                    store = rs.tier(tier.interval, agg_fn)
            elif tier is not None and agg_fn == "avg" \
                    and rs.has_data(tier.interval, "sum") \
                    and rs.has_data(tier.interval, "count"):
                store = rs.tier(tier.interval, "sum")
                avg_count_store = rs.tier(tier.interval, "count")
        sids = store.series_ids_for_metric(metric_id)
        if store is not self.tsdb.store and len(sids) == 0 and \
                usage in ("ROLLUP_FALLBACK", "ROLLUP_FALLBACK_RAW"):
            store = self.tsdb.store
            sids = store.series_ids_for_metric(metric_id)
            avg_count_store = None
        return store, sub.metric, sids, rollup_scale, avg_count_store

    def _avg_rollup_pipeline(self, sum_store, cnt_store,
                             sids: np.ndarray, tsq: TSQuery,
                             sub: TSSubQuery, metric_name: str,
                             group_ids: np.ndarray, num_groups: int,
                             emit_raw: bool, stats):
        """Answer an ``avg`` downsample from rollup tiers: bucketized
        SUM cells divided by bucketized COUNT cells — the true weighted
        average, not a mean of per-tier-point averages (ref: RollupSpan
        reading agg-prefixed sum+count qualifiers from one row).
        Returns (result, emit, bucket_ts) or None for no data."""
        t1 = time.monotonic()
        batch_s = sum_store.materialize(sids, tsq.start_ms, tsq.end_ms)
        # count series aligned to sum series by (metric, tags) identity
        csids = np.full(len(sids), -1, dtype=np.int64)
        for i, sid in enumerate(sids):
            rec = sum_store.series(int(sid))
            c = cnt_store._key_to_sid.get(
                (rec.metric_id, tuple(sorted(rec.tags))))
            if c is not None:
                csids[i] = c
        present = np.nonzero(csids >= 0)[0]
        batch_c = cnt_store.materialize(csids[present], tsq.start_ms,
                                        tsq.end_ms)
        num_points = batch_s.num_points + batch_c.num_points
        if stats:
            stats.add_stat(QueryStat.MATERIALIZE_TIME,
                           (time.monotonic() - t1) * 1e3)
            stats.add_stat(QueryStat.DPS_POST_FILTER, num_points)
        self.tsdb.query_limits.check(metric_name, num_points)
        if tsq.delete:
            sum_store.delete_range(sids, tsq.start_ms, tsq.end_ms)
            cnt_store.delete_range(csids[present], tsq.start_ms,
                                   tsq.end_ms)
        if batch_s.num_points == 0:
            return None
        t2 = time.monotonic()
        bidx_s, bucket_ts = ds_mod.assign_buckets(
            batch_s.ts_ms, sub.ds_spec, tsq.start_ms, tsq.end_ms)
        bidx_c, _ = ds_mod.assign_buckets(
            batch_c.ts_ms, sub.ds_spec, tsq.start_ms, tsq.end_ms)
        s, b = len(sids), len(bucket_ts)
        # both grids stay on device: bucketize returns device arrays
        # and the division happens in the same trace as the tail
        gs, _ = ds_mod.bucketize(batch_s.values, batch_s.series_idx,
                                 bidx_s, s, b, "sum")
        sidx_c = present[batch_c.series_idx].astype(np.int32)
        gc, _ = ds_mod.bucketize(batch_c.values, sidx_c, bidx_c, s, b,
                                 "sum")
        spec = PipelineSpec(
            num_series=s, num_buckets=b, num_groups=num_groups,
            ds_function="avg", agg_name=sub.agg.name,
            fill_policy=sub.ds_spec.fill_policy,
            fill_value=sub.ds_spec.fill_value, rate=sub.rate,
            rate_counter=sub.rate_options.counter,
            rate_drop_resets=sub.rate_options.drop_resets,
            emit_raw=emit_raw)
        mesh = self.tsdb.query_mesh
        if mesh is not None:
            # divide host-side, then run the rate/fill/agg tail over
            # the mesh with one point per present grid cell (bucketize
            # of a single-point cell reproduces the cell exactly)
            from opentsdb_tpu.ops.pipeline import avg_divide_grid
            avg, valid = avg_divide_grid(np.asarray(gs), np.asarray(gc),
                                         xp=np)
            valid = np.asarray(valid)
            sidx2, bidx2 = np.nonzero(valid)
            result, emit = self._mesh_execute(
                mesh, spec, avg[valid], sidx2.astype(np.int32),
                bidx2.astype(np.int32), bucket_ts, group_ids,
                sub.rate_options)
        else:
            result, emit = execute_avg_divide(
                gs, gc, bucket_ts, group_ids, spec, sub.rate_options)
        if stats:
            stats.add_stat(QueryStat.COMPUTE_TIME,
                           (time.monotonic() - t2) * 1e3)
        return result, emit, bucket_ts

    def _mesh_execute(self, mesh, spec, values, series_idx, bucket_idx,
                      bucket_ts, group_ids, rate_options):
        """Run one sub-query's compute over the configured device mesh
        (series axis ≙ salt buckets, time axis ≙ long-range blocking;
        ref: SaltScanner.java:70, TsdbQuery.java:795)."""
        from opentsdb_tpu.parallel.sharded_pipeline import (
            prepare_sharded_batch, run_sharded)
        batch = prepare_sharded_batch(
            values, series_idx, bucket_idx, bucket_ts, group_ids,
            spec.num_series, spec.num_groups, mesh.shape["series"],
            mesh.shape["time"])
        return run_sharded(mesh, spec, batch, rate_options)

    def _tsuid_store(self, sub: TSSubQuery):
        """Resolve explicit TSUID hex strings to series ids
        (ref: TsdbQuery tsuid query path)."""
        uids = self.tsdb.uids
        store = self.tsdb.store
        mw = uids.metrics.width
        kw = uids.tag_names.width
        vw = uids.tag_values.width
        sids = []
        metric_name = None
        for tsuid in sub.tsuids:
            raw = bytes.fromhex(tsuid)
            metric_id = int.from_bytes(raw[:mw], "big")
            tags = []
            pos = mw
            while pos < len(raw):
                kid = int.from_bytes(raw[pos:pos + kw], "big")
                vid = int.from_bytes(raw[pos + kw:pos + kw + vw], "big")
                tags.append((kid, vid))
                pos += kw + vw
            name = uids.metrics.get_name(metric_id)
            if metric_name is None:
                metric_name = name
            elif name != metric_name:
                raise BadRequestError(
                    "Multiple metrics in the same tsuid query")
            key = (metric_id, tuple(sorted(tags)))
            sid = store._key_to_sid.get(key)
            if sid is not None:
                sids.append(sid)
        return store, metric_name or "", np.asarray(
            sids, dtype=np.int64), 1.0, None

    # ------------------------------------------------------------------

    def _apply_filters(self, store: TimeSeriesStore, sub: TSSubQuery,
                       sids: np.ndarray
                       ) -> tuple[np.ndarray, list[dict[int, int]]]:
        recs = [store.series(int(s)) for s in sids]
        if sub.filters:
            metric_id = recs[0].metric_id
            idx = store.metric_index(metric_id)
            if idx is not None and store is self.tsdb.store \
                    and not sub.tsuids:
                _, triples = idx.arrays()
            else:
                rows = []
                for rec in recs:
                    for kid, vid in rec.tags:
                        rows.append((rec.series_id, kid, vid))
                triples = (np.asarray(rows, dtype=np.int64).reshape(-1, 3)
                           if rows else np.empty((0, 3), dtype=np.int64))
            mask = self._filter_eval.apply(sub.filters, sids, triples)
            sids = sids[mask]
            recs = [r for r, m in zip(recs, mask) if m]
        if sub.explicit_tags and sub.filters:
            filter_keys = set()
            for f in sub.filters:
                try:
                    filter_keys.add(
                        self.tsdb.uids.tag_names.get_id(f.tagk))
                except LookupError:
                    pass
            keep = [i for i, r in enumerate(recs)
                    if {k for k, _ in r.tags} == filter_keys]
            sids = sids[keep]
            recs = [recs[i] for i in keep]
        series_tags = [dict(r.tags) for r in recs]
        return sids, series_tags

    @staticmethod
    def _group_ids(series_tags: list[dict[int, int]], gb_kids: list[int]
                   ) -> tuple[np.ndarray, list[tuple]]:
        """Group key = tuple of group-by tagv ids (ref: the concatenated
        tagv UID group key, TsdbQuery.java:995-1036)."""
        if not gb_kids:
            return (np.zeros(len(series_tags), dtype=np.int32), [()])
        # columnar [S, K] key matrix + one sort-based unique: group ids
        # come out ordered by concatenated tagv id, matching the
        # reference's ByteMap ordering of group keys
        # (TsdbQuery.java:995-1036); a per-series tuple/dict walk costs
        # ~0.4 s at 200k series
        mat = np.empty((len(series_tags), len(gb_kids)), dtype=np.int64)
        for j, k in enumerate(gb_kids):
            mat[:, j] = np.fromiter((t.get(k, -1) for t in series_tags),
                                    dtype=np.int64,
                                    count=len(series_tags))
        uniq, inverse = np.unique(mat, axis=0, return_inverse=True)
        keys = [tuple(int(v) for v in row) for row in uniq]
        return inverse.astype(np.int32), keys

    # ------------------------------------------------------------------

    def _build_results(self, tsq, sub, metric_name, sids, series_tags,
                       group_ids, group_keys, gb_kids, bucket_ts,
                       result, emit) -> list[QueryResult]:
        uids = self.tsdb.uids
        out: list[QueryResult] = []
        ms_res = tsq.ms_resolution
        fetch_annotations = not tsq.no_annotations and \
            self.tsdb.annotations.has_any()
        for gid in range(len(group_keys)):
            members = np.nonzero(group_ids == gid)[0]
            if len(members) == 0:
                continue
            row = result[gid]
            erow = emit[gid]
            dps = _emit_dps(bucket_ts, row, erow, ms_res)
            if not dps:
                continue
            tags, agg_tags = _common_tags(
                [series_tags[m] for m in members], uids)
            tsuids = []
            if tsq.show_tsuids or sub.tsuids:
                for m in members:
                    rec_tags = sorted(series_tags[m].items())
                    metric_id = uids.metrics.get_id(metric_name)
                    tsuids.append(
                        uids.tsuid(metric_id, rec_tags).hex().upper())
            annotations = []
            if fetch_annotations:
                start_s = tsq.start_ms // 1000
                end_s = tsq.end_ms // 1000
                try:
                    metric_id = uids.metrics.get_id(metric_name)
                    for m in members:
                        tsuid_hex = uids.tsuid(
                            metric_id,
                            sorted(series_tags[m].items())).hex().upper()
                        annotations.extend(
                            self.tsdb.annotations.range(tsuid_hex,
                                                        start_s, end_s))
                except LookupError:
                    pass
            global_annotations = []
            if tsq.global_annotations:
                global_annotations = self.tsdb.annotations.global_range(
                    tsq.start_ms // 1000, tsq.end_ms // 1000)
            out.append(QueryResult(
                metric=metric_name, tags=tags, aggregated_tags=agg_tags,
                dps=dps, tsuids=tsuids, annotations=annotations,
                global_annotations=global_annotations,
                sub_query_index=sub.index))
        return out


def _emit_dps(bucket_ts, row, erow, ms_resolution: bool
              ) -> list[tuple[int, float]]:
    """Compress (value,emit) arrays into the output point list."""
    emit_idx = np.nonzero(erow)[0]
    dps = []
    for b in emit_idx:
        v = row[b]
        ts = int(bucket_ts[b])
        dps.append((ts if ms_resolution else (ts // 1000) * 1000,
                    float(v)))
    return dps


def _common_tags(tag_dicts: list[dict[int, int]], uids
                 ) -> tuple[dict[str, str], list[str]]:
    """SpanGroup semantics: ``tags`` = k=v pairs identical across every
    series; ``aggregateTags`` = keys present in every series with
    differing values (keys missing from some series vanish)."""
    common_keys = set(tag_dicts[0])
    for t in tag_dicts[1:]:
        common_keys &= set(t)
    tags: dict[str, str] = {}
    agg_tags: list[str] = []
    for k in sorted(common_keys):
        vals = {t[k] for t in tag_dicts}
        kname = uids.tag_names.get_name(k)
        if len(vals) == 1:
            tags[kname] = uids.tag_values.get_name(next(iter(vals)))
        else:
            agg_tags.append(kname)
    return tags, agg_tags
