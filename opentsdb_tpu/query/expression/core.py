"""Expression evaluation core (ref: ``src/query/expression/``).

The reference evaluates cross-metric arithmetic with time-synced
iterators (``ExpressionIterator.java:40``, ``TimeSyncedIterator``,
``IntersectionIterator``/``UnionIterator``) pulling one timestamp at a
time. Here a variable is a :class:`SeriesFrame` — a dense
``[series, time]`` matrix on a shared timestamp grid — and every
expression/function is a vectorized numpy/JAX op. Set joins
(intersection/union on tag sets, ref ``SetOperator``) become row
alignment by tag-key.

Functions mirror ``ExpressionFactory.java:32-38``: alias, scale,
absolute, movingAverage, highestCurrent, highestMax, timeShift,
sumSeries, diffSeries, multiplySeries, divideSeries.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from opentsdb_tpu.query.engine import QueryResult


@dataclass
class SeriesFrame:
    """A set of series on one timestamp grid: the array form of one
    sub-query result (one row per output group)."""
    ts: np.ndarray                      # [T] ms
    values: np.ndarray                  # [S, T], NaN = missing
    tags: list[dict[str, str]]          # per row
    agg_tags: list[list[str]] = field(default_factory=list)
    metric: str = ""

    @classmethod
    def from_results(cls, results: list[QueryResult]) -> "SeriesFrame":
        if not results:
            return cls(np.empty(0, dtype=np.int64),
                       np.empty((0, 0)), [], [], "")
        all_ts = sorted({ts for r in results for ts, _ in r.dps})
        ts_index = {t: i for i, t in enumerate(all_ts)}
        values = np.full((len(results), len(all_ts)), np.nan)
        for i, r in enumerate(results):
            for ts, v in r.dps:
                values[i, ts_index[ts]] = v
        return cls(np.asarray(all_ts, dtype=np.int64), values,
                   [dict(r.tags) for r in results],
                   [list(r.aggregated_tags) for r in results],
                   results[0].metric)

    def to_results(self, metric: str | None = None,
                   sub_query_index: int = 0) -> list[QueryResult]:
        out = []
        for i in range(self.values.shape[0]):
            dps = [(int(t), float(v))
                   for t, v in zip(self.ts, self.values[i])
                   if not np.isnan(v)]
            out.append(QueryResult(
                metric=metric or self.metric,
                tags=self.tags[i] if i < len(self.tags) else {},
                aggregated_tags=(self.agg_tags[i]
                                 if i < len(self.agg_tags) else []),
                dps=dps, sub_query_index=sub_query_index))
        return out

    def copy_with(self, values: np.ndarray,
                  metric: str | None = None) -> "SeriesFrame":
        return SeriesFrame(self.ts, values, self.tags, self.agg_tags,
                           metric if metric is not None else self.metric)

    @property
    def num_series(self) -> int:
        return self.values.shape[0]


def align_frames(a: SeriesFrame, b: SeriesFrame, operator: str = "union"
                 ) -> tuple[SeriesFrame, SeriesFrame]:
    """Join two frames on series tags and timestamp union
    (ref: IntersectionIterator / UnionIterator set joins)."""
    # timestamp union grid
    all_ts = np.union1d(a.ts, b.ts)

    def regrid(f: SeriesFrame) -> np.ndarray:
        out = np.full((f.num_series, len(all_ts)), np.nan)
        idx = np.searchsorted(all_ts, f.ts)
        out[:, idx] = f.values
        return out

    av, bv = regrid(a), regrid(b)
    key = lambda tags: tuple(sorted(tags.items()))
    a_keys = {key(t): i for i, t in enumerate(a.tags)}
    b_keys = {key(t): i for i, t in enumerate(b.tags)}
    if operator == "intersection":
        keys = [k for k in a_keys if k in b_keys]
    else:  # union
        keys = list(dict.fromkeys(list(a_keys) + list(b_keys)))
    # Only genuinely scalar-like single-series frames broadcast against
    # the other side: a fully-aggregated result has an empty tag dict.
    # A tagged single-series frame goes through the keyed join below so
    # intersection honors tag-set semantics (ref IntersectionIterator).
    a_scalar = a.num_series == 1 and not (a.tags and a.tags[0])
    b_scalar = b.num_series == 1 and not (b.tags and b.tags[0])
    if a_scalar and b.num_series > 1:
        keys = list(b_keys)
        a_rows = np.zeros(len(keys), dtype=int)
        b_rows = np.asarray([b_keys[k] for k in keys])
        tags = [dict(k) for k in keys]
        return (SeriesFrame(all_ts, av[a_rows], tags, b.agg_tags,
                            a.metric),
                SeriesFrame(all_ts, bv[b_rows], tags, b.agg_tags,
                            b.metric))
    if b_scalar and a.num_series > 1:
        keys = list(a_keys)
        b_rows = np.zeros(len(keys), dtype=int)
        av2 = np.stack([av[a_keys[k]] for k in keys]) if keys else av
        tags = [dict(k) for k in keys]
        return (SeriesFrame(all_ts, av2, tags, a.agg_tags, a.metric),
                SeriesFrame(all_ts, bv[b_rows], tags, a.agg_tags,
                            b.metric))
    an = np.full((len(keys), len(all_ts)), np.nan)
    bn = np.full((len(keys), len(all_ts)), np.nan)
    agg_tags: list[list[str]] = []
    for i, k in enumerate(keys):
        row_agg: list[str] = []
        if k in a_keys:
            an[i] = av[a_keys[k]]
            if a_keys[k] < len(a.agg_tags):
                row_agg = list(a.agg_tags[a_keys[k]])
        if k in b_keys:
            bn[i] = bv[b_keys[k]]
            if not row_agg and b_keys[k] < len(b.agg_tags):
                row_agg = list(b.agg_tags[b_keys[k]])
        agg_tags.append(row_agg)
    tags = [dict(k) for k in keys]
    return (SeriesFrame(all_ts, an, tags, agg_tags, a.metric),
            SeriesFrame(all_ts, bn, tags, agg_tags, b.metric))


def binary_op(a: SeriesFrame, b: SeriesFrame, op: str,
              operator: str = "union",
              fill_missing: float = 0.0) -> SeriesFrame:
    """Elementwise arithmetic after join. Missing values substitute
    ``fill_missing`` (the reference's NumericFillPolicy default ZERO)."""
    aa, bb = align_frames(a, b, operator)
    av = np.where(np.isnan(aa.values), fill_missing, aa.values)
    bv = np.where(np.isnan(bb.values), fill_missing, bb.values)
    both_missing = np.isnan(aa.values) & np.isnan(bb.values)
    if op == "+":
        out = av + bv
    elif op == "-":
        out = av - bv
    elif op == "*":
        out = av * bv
    elif op == "/":
        with np.errstate(divide="ignore", invalid="ignore"):
            out = np.where(bv == 0, 0.0, av / bv)
    else:
        raise ValueError(f"unknown operator {op!r}")
    out = np.where(both_missing, np.nan, out)
    return aa.copy_with(out)


def scalar_op(a: SeriesFrame, scalar: float, op: str,
              scalar_left: bool = False) -> SeriesFrame:
    v = a.values
    if op == "+":
        out = scalar + v if scalar_left else v + scalar
    elif op == "-":
        out = scalar - v if scalar_left else v - scalar
    elif op == "*":
        out = v * scalar
    elif op == "/":
        with np.errstate(divide="ignore", invalid="ignore"):
            out = np.where(v == 0, 0.0, scalar / v) if scalar_left \
                else v / scalar
    else:
        raise ValueError(f"unknown operator {op!r}")
    return a.copy_with(out)


# ---------------------------------------------------------------------------
# gexp function library (ref: ExpressionFactory.java:32-38)
# ---------------------------------------------------------------------------

def fn_absolute(frame: SeriesFrame) -> SeriesFrame:
    return frame.copy_with(np.abs(frame.values))


def fn_scale(frame: SeriesFrame, factor: float) -> SeriesFrame:
    return frame.copy_with(frame.values * factor)


def fn_alias(frame: SeriesFrame, name: str) -> SeriesFrame:
    return frame.copy_with(frame.values, metric=name)


def fn_moving_average(frame: SeriesFrame, window: str) -> SeriesFrame:
    """(ref: MovingAverage.java:709) window = point count or time
    duration like '1m'."""
    from opentsdb_tpu.utils import datetime_util
    v = frame.values
    out = np.full_like(v, np.nan)
    if isinstance(window, str) and window and not window.isdigit():
        win_ms = datetime_util.parse_duration_ms(window)
        ts = frame.ts
        for t in range(v.shape[1]):
            # trailing window [t - win, t): inclusive lower edge
            lo = np.searchsorted(ts, ts[t] - win_ms, side="left")
            if lo < t:
                seg = v[:, lo:t]
                with np.errstate(invalid="ignore"):
                    out[:, t] = np.nanmean(seg, axis=1)
    else:
        n = int(window)
        for t in range(v.shape[1]):
            lo = max(0, t - n)
            if lo < t:
                seg = v[:, lo:t]
                with np.errstate(invalid="ignore"):
                    out[:, t] = np.nanmean(seg, axis=1)
    return frame.copy_with(np.where(np.isnan(out), 0.0, out))


def fn_highest_current(frame: SeriesFrame, count: int) -> SeriesFrame:
    """Top-N series by most recent value (ref: HighestCurrent)."""
    if frame.num_series == 0:
        return frame
    last_vals = np.full(frame.num_series, -np.inf)
    for i in range(frame.num_series):
        valid = ~np.isnan(frame.values[i])
        if valid.any():
            last_vals[i] = frame.values[i][valid][-1]
    top = np.argsort(-last_vals, kind="stable")[:int(count)]
    return SeriesFrame(frame.ts, frame.values[top],
                       [frame.tags[i] for i in top],
                       [frame.agg_tags[i] for i in top
                        if i < len(frame.agg_tags)], frame.metric)


def fn_highest_max(frame: SeriesFrame, count: int) -> SeriesFrame:
    if frame.num_series == 0:
        return frame
    with np.errstate(invalid="ignore"):
        maxes = np.where(np.all(np.isnan(frame.values), axis=1), -np.inf,
                         np.nanmax(np.where(np.isnan(frame.values),
                                            -np.inf, frame.values),
                                   axis=1))
    top = np.argsort(-maxes, kind="stable")[:int(count)]
    return SeriesFrame(frame.ts, frame.values[top],
                       [frame.tags[i] for i in top],
                       [frame.agg_tags[i] for i in top
                        if i < len(frame.agg_tags)], frame.metric)


def fn_time_shift(frame: SeriesFrame, interval: str) -> SeriesFrame:
    """Shift series forward in time (ref: TimeShift)."""
    from opentsdb_tpu.utils import datetime_util
    shift_ms = datetime_util.parse_duration_ms(interval)
    return SeriesFrame(frame.ts + shift_ms, frame.values, frame.tags,
                       frame.agg_tags, frame.metric)


def _reduce_series(frames: list[SeriesFrame], op: str) -> SeriesFrame:
    acc = frames[0]
    for f in frames[1:]:
        acc = binary_op(acc, f, op)
    return acc


def fn_sum_series(*frames: SeriesFrame) -> SeriesFrame:
    return _reduce_series(list(frames), "+")


def fn_diff_series(*frames: SeriesFrame) -> SeriesFrame:
    return _reduce_series(list(frames), "-")


def fn_multiply_series(*frames: SeriesFrame) -> SeriesFrame:
    return _reduce_series(list(frames), "*")


def fn_divide_series(*frames: SeriesFrame) -> SeriesFrame:
    return _reduce_series(list(frames), "/")


GEXP_FUNCTIONS: dict[str, Callable] = {
    "absolute": fn_absolute,
    "scale": fn_scale,
    "alias": fn_alias,
    "movingAverage": fn_moving_average,
    "highestCurrent": fn_highest_current,
    "highestMax": fn_highest_max,
    "timeShift": fn_time_shift,
    "sumSeries": fn_sum_series,
    "diffSeries": fn_diff_series,
    "multiplySeries": fn_multiply_series,
    "divideSeries": fn_divide_series,
    # aliases registered by the reference factory
    # (ExpressionFactory.java:37-57: shift, sum, difference, multiply,
    # divide map to the same implementations)
    "shift": fn_time_shift,
    "sum": fn_sum_series,
    "difference": fn_diff_series,
    "multiply": fn_multiply_series,
    "divide": fn_divide_series,
}


# ---------------------------------------------------------------------------
# infix expression parser (ref: Expressions.java infix parse + the
# JavaCC grammar src/parser.jj used by SyntaxChecker)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>\d+\.\d+|\.\d+|\d+)|(?P<id>[A-Za-z_][\w.\-]*)"
    r"|(?P<op>[+\-*/()]))")


class InfixParser:
    """Tiny recursive-descent parser for ``a + b * 2`` style expressions
    over named variables. ``join_operator`` and ``fill_missing`` carry
    the expression's pojo Join / NumericFillPolicy settings into every
    binary join (ref: pojo/Join.java SetOperator,
    expression/NumericFillPolicy.java)."""

    def __init__(self, text: str, join_operator: str = "union",
                 fill_missing: float = 0.0):
        self.tokens = self._tokenize(text)
        self.pos = 0
        self.join_operator = join_operator
        self.fill_missing = fill_missing

    @staticmethod
    def _tokenize(text: str):
        tokens = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if not m or m.end() == pos:
                if text[pos:].strip():
                    raise ValueError(
                        f"bad expression near: {text[pos:]!r}")
                break
            if m.group("num"):
                tokens.append(("num", float(m.group("num"))))
            elif m.group("id"):
                tokens.append(("id", m.group("id")))
            else:
                tokens.append(("op", m.group("op")))
            pos = m.end()
        return tokens

    def parse(self, variables: dict[str, SeriesFrame]) -> SeriesFrame:
        result = self._expr(variables)
        if self.pos != len(self.tokens):
            raise ValueError("trailing tokens in expression")
        if isinstance(result, float):
            raise ValueError("expression must reference a variable")
        return result

    def _peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) \
            else (None, None)

    def _expr(self, variables):
        left = self._term(variables)
        while self._peek() == ("op", "+") or self._peek() == ("op", "-"):
            op = self.tokens[self.pos][1]
            self.pos += 1
            right = self._term(variables)
            left = self._apply(left, right, op)
        return left

    def _term(self, variables):
        left = self._factor(variables)
        while self._peek() == ("op", "*") or self._peek() == ("op", "/"):
            op = self.tokens[self.pos][1]
            self.pos += 1
            right = self._factor(variables)
            left = self._apply(left, right, op)
        return left

    def _factor(self, variables):
        kind, val = self._peek()
        if kind == "op" and val == "(":
            self.pos += 1
            inner = self._expr(variables)
            if self._peek() != ("op", ")"):
                raise ValueError("missing ')'")
            self.pos += 1
            return inner
        if kind == "op" and val == "-":
            self.pos += 1
            inner = self._factor(variables)
            if isinstance(inner, float):
                return -inner
            return scalar_op(inner, -1.0, "*")
        if kind == "num":
            self.pos += 1
            return val
        if kind == "id":
            self.pos += 1
            if val not in variables:
                raise ValueError(f"unknown variable {val!r}")
            return variables[val]
        raise ValueError(f"unexpected token {val!r}")

    def _apply(self, left, right, op):
        if isinstance(left, float) and isinstance(right, float):
            return {"+": left + right, "-": left - right,
                    "*": left * right,
                    "/": left / right if right else 0.0}[op]
        if isinstance(left, float):
            return scalar_op(right, left, op, scalar_left=True)
        if isinstance(right, float):
            return scalar_op(left, right, op)
        return binary_op(left, right, op,
                         operator=self.join_operator,
                         fill_missing=self.fill_missing)


def evaluate_expression(text: str,
                        variables: dict[str, SeriesFrame],
                        join_operator: str = "union",
                        fill_missing: float = 0.0) -> SeriesFrame:
    return InfixParser(text, join_operator,
                       fill_missing).parse(variables)
