"""The ``/api/query/exp`` and ``/api/query/gexp`` endpoints.

(ref: ``src/tsd/QueryExecutor.java:85`` — topo-sorted ExpressionIterator
DAG; ``QueryRpc.java:113`` gexp routing; the POJO request model
``src/query/pojo/Query.java:33``)
"""

from __future__ import annotations

import json
import re

from opentsdb_tpu.query import filters as filters_mod
from opentsdb_tpu.query.expression.core import (GEXP_FUNCTIONS,
                                                SeriesFrame,
                                                evaluate_expression)
from opentsdb_tpu.query.model import (BadRequestError, TSQuery, TSSubQuery,
                                      _validate_pixel_fn,
                                      _validate_pixels,
                                      parse_uri_subquery)


# ---------------------------------------------------------------------------
# /api/query/gexp  (ref: QueryRpc gexp handling)
# ---------------------------------------------------------------------------

def handle_gexp(router, request):
    from opentsdb_tpu.tsd.http_api import HttpResponse
    exprs = request.params.get("exp", [])
    if not exprs:
        raise BadRequestError("Missing parameter exp")
    start = request.param("start")
    if not start:
        raise BadRequestError("Missing start time")
    end = request.param("end")

    all_results = []
    for i, expr in enumerate(exprs):
        frame = _eval_gexp(router.tsdb, expr, start, end)
        results = frame.to_results(sub_query_index=i)
        all_results.extend(results)
    tsq = TSQuery(start=start, end=end, queries=[])
    tsq.start_ms, tsq.end_ms = 0, 1  # already applied per sub-eval
    tsq.ms_resolution = request.flag("ms")
    body = router.serializer.format_query(tsq, all_results)
    return HttpResponse(200, body)


def _eval_gexp(tsdb, expr: str, start: str, end: str | None
               ) -> SeriesFrame:
    """Recursively evaluate a gexp: ``func(args...)`` over m-type
    sub-query leaves."""
    expr = expr.strip()
    m = re.match(r"^(\w+)\((.*)\)$", expr, re.DOTALL)
    if m and m.group(1) in GEXP_FUNCTIONS:
        fname = m.group(1)
        args = _split_args(m.group(2))
        fn = GEXP_FUNCTIONS[fname]
        evaluated = []
        for arg in args:
            arg = arg.strip()
            if re.fullmatch(r"-?\d+(\.\d+)?", arg):
                evaluated.append(float(arg))
            elif re.fullmatch(r"'[^']*'|\"[^\"]*\"", arg):
                evaluated.append(arg[1:-1])
            elif re.fullmatch(r"\d+[smhdwny]", arg):
                evaluated.append(arg)
            else:
                evaluated.append(_eval_gexp(tsdb, arg, start, end))
        return fn(*evaluated)
    # leaf: an m-type sub-query
    sub = parse_uri_subquery(expr)
    tsq = TSQuery(start=start, end=end, queries=[sub])
    tsq.validate()
    results = tsdb.new_query().run(tsq)
    return SeriesFrame.from_results(results)


def _split_args(body: str) -> list[str]:
    """Split on commas not inside parens/braces."""
    args, depth, cur = [], 0, []
    for c in body:
        if c in "({[":
            depth += 1
        elif c in ")}]":
            depth -= 1
        if c == "," and depth == 0:
            args.append("".join(cur))
            cur = []
        else:
            cur.append(c)
    if cur or not args:
        args.append("".join(cur))
    return args


def _reduce_frame(frame: SeriesFrame, window_ms: tuple[int, int],
                  px: int, fn: str) -> SeriesFrame:
    """Pixel-budget selection over one output frame: per-series keep
    masks from the shared kernels (``ops/visual_downsample``), then a
    timestamp column survives when ANY series keeps it — exp emits
    row-per-timestamp union rows, so column selection is the only
    shape-preserving reduction. Bounded by ~4·px kept columns per
    series for M4 (px per series for minmaxlttb)."""
    import numpy as np

    from opentsdb_tpu.ops import visual_downsample as vd
    emit = np.ones(frame.values.shape, dtype=bool)
    keep = vd.keep_mask(frame.values, emit, frame.ts,
                        window_ms[0], window_ms[1], px,
                        fn or vd.DEFAULT_PIXEL_FN)
    if keep is None:
        return frame
    col = keep.any(axis=0)
    return SeriesFrame(frame.ts[col], frame.values[:, col],
                       frame.tags, frame.agg_tags, frame.metric)


# ---------------------------------------------------------------------------
# /api/query/exp  (ref: QueryExecutor.java:222 + pojo model)
# ---------------------------------------------------------------------------

def handle_exp(router, request):
    from opentsdb_tpu.tsd.http_api import HttpResponse
    if request.method != "POST":
        raise BadRequestError("/api/query/exp requires POST")
    obj = request.json_object(default={})
    tsdb = router.tsdb

    time_spec = obj.get("time") or {}
    start = str(time_spec.get("start", ""))
    end = time_spec.get("end")
    aggregator = time_spec.get("aggregator", "sum")
    # pixel-aware output reduction (PR 7 follow-up): exp assembles its
    # own rows, bypassing the engine's _build_results, so the budget
    # applies HERE — after the expression DAG evaluates. Reducing the
    # metric INPUTS instead would change the arithmetic (an expression
    # over M4-selected subsets is not the M4 selection of the
    # expression). Query-level ``pixels``/``pixelFn`` ride at the top
    # of the body; a per-output override wins (the per-sub rule).
    q_px = _validate_pixels(obj.get("pixels") or 0, "pixels")
    q_fn = _validate_pixel_fn(obj.get("pixelFn") or "", "pixelFn")
    def _ds_string(downsampler, where: str) -> str | None:
        """pojo Downsampler object -> "interval-agg[-fill]" string
        (ref: pojo/Downsampler.java). Strings pass through for the
        convenience form; anything else is a clean 400."""
        if not downsampler:
            return None
        if isinstance(downsampler, str):
            return downsampler
        if not isinstance(downsampler, dict):
            raise BadRequestError(
                f"{where} must be an object with "
                "interval/aggregator (ref: pojo/Downsampler.java)")
        spec = (f"{downsampler.get('interval')}-"
                f"{downsampler.get('aggregator', 'avg')}")
        fp_obj = downsampler.get("fillPolicy") or {}
        if not isinstance(fp_obj, dict):
            raise BadRequestError(
                f"{where}.fillPolicy must be an object")
        fp = fp_obj.get("policy")
        if fp:
            spec += f"-{fp}"
        return spec

    ds_spec = _ds_string(time_spec.get("downsampler"),
                         "time.downsampler")

    # named filter sets (ref: pojo/Filter.java)
    filter_sets: dict[str, list] = {}
    for f in obj.get("filters") or []:
        if not isinstance(f, dict):
            raise BadRequestError("each filter must be an object")
        tags = f.get("tags") or []
        if not isinstance(tags, list) or not all(
                isinstance(t, dict) for t in tags):
            raise BadRequestError(
                "filter tags must be an array of objects")
        filter_sets[f.get("id", "")] = [
            filters_mod.build_filter(t) for t in tags]

    # time-spec rate applies to every metric unless overridden
    time_rate = bool(time_spec.get("rate", False))
    time_rate_options = time_spec.get("rateOptions")

    # metrics: id -> sub-query (ref: pojo/Metric.java incl. per-metric
    # rate/rateOptions)
    variables: dict[str, SeriesFrame] = {}
    metric_meta: dict[str, dict] = {}
    window_ms: tuple[int, int] | None = None
    for mspec in obj.get("metrics") or []:
        if not isinstance(mspec, dict):
            raise BadRequestError("each metric must be an object")
        mid = mspec.get("id")
        if not mid:
            raise BadRequestError("metric missing id")
        sub = TSSubQuery.from_json({
            "metric": mspec.get("metric"),
            "aggregator": mspec.get("aggregator") or aggregator,
            "downsample": _ds_string(
                mspec.get("downsampler"),
                f"metrics[{mid}].downsampler") or ds_spec,
            "rate": mspec.get("rate", time_rate),
            "rateOptions": (mspec.get("rateOptions")
                            or time_rate_options),
        })
        sub.filters = list(filter_sets.get(mspec.get("filter", ""),
                                           []))
        tsq = TSQuery(start=start, end=end, queries=[sub])
        tsq.validate()
        window_ms = (tsq.start_ms, tsq.end_ms)
        results = tsdb.new_query().run(tsq)
        variables[mid] = SeriesFrame.from_results(results)
        metric_meta[mid] = mspec

    # expressions DAG: evaluate in dependency order
    # (ref: QueryExecutor jgrapht topo sort :31-35)
    exprs = {e.get("id"): e for e in obj.get("expressions") or []}
    resolved: dict[str, SeriesFrame] = {}

    def resolve(eid: str, seen: tuple = ()):
        if eid in resolved:
            return resolved[eid]
        if eid in seen:
            raise BadRequestError(f"circular expression reference: {eid}")
        spec = exprs[eid]
        scope = dict(variables)
        for dep in exprs:
            if dep != eid and dep in spec.get("expr", ""):
                scope[dep] = resolve(dep, seen + (eid,))
        # per-expression join + fill (ref: pojo/Join.java SetOperator,
        # pojo/Expression.java fillPolicy -> NumericFillPolicy)
        join = spec.get("join") or {}
        operator = str(join.get("operator") or "union").lower()
        if operator not in ("union", "intersection"):
            raise BadRequestError(
                f"unknown join operator {operator!r}")
        fp = spec.get("fillPolicy") or {}
        if not isinstance(fp, dict):
            raise BadRequestError(
                f"expression {eid} fillPolicy must be an object")
        policy = str(fp.get("policy") or "zero").lower()
        if policy in ("nan", "null"):
            fill = float("nan")
        elif policy == "scalar":
            fill = float(fp.get("value", 0))
        elif policy == "zero":
            fill = 0.0
        else:
            raise BadRequestError(f"unknown fill policy {policy!r}")
        frame = evaluate_expression(spec.get("expr", ""), scope,
                                    join_operator=operator,
                                    fill_missing=fill)
        if not bool(join.get("includeAggTags", True)):
            frame = SeriesFrame(frame.ts, frame.values, frame.tags,
                                [[] for _ in range(frame.num_series)],
                                frame.metric)
        resolved[eid] = frame
        return frame

    outputs = obj.get("outputs") or [{"id": eid} for eid in exprs]
    out_results = []
    for i, ospec in enumerate(outputs):
        oid = ospec.get("id")
        if oid in exprs:
            frame = resolve(oid)
        elif oid in variables:
            frame = variables[oid]
        else:
            raise BadRequestError(f"unknown output id {oid!r}")
        opx = _validate_pixels(ospec.get("pixels") or 0,
                               f"outputs[{oid}].pixels")
        ofn = _validate_pixel_fn(ospec.get("pixelFn") or "",
                                 f"outputs[{oid}].pixelFn")
        px = opx or q_px
        if px and window_ms is not None and len(frame.ts):
            frame = _reduce_frame(frame, window_ms, px, ofn or q_fn)
        dps_rows = []
        for t_idx, ts in enumerate(frame.ts):
            row = [int(ts)]
            row.extend(
                None if (v != v) else (int(v) if float(v).is_integer()
                                       else float(v))
                for v in frame.values[:, t_idx])
            dps_rows.append(row)
        # the output alias renames the emitted series metric (ref:
        # pojo/Output.java alias consumed by QueryExecutor's serdes)
        alias = ospec.get("alias")
        out_results.append({
            "id": oid,
            "alias": alias,
            "dps": dps_rows,
            "dpsMeta": {
                "firstTimestamp": int(frame.ts[0]) if len(frame.ts)
                else 0,
                "lastTimestamp": int(frame.ts[-1]) if len(frame.ts)
                else 0,
                "setCount": frame.num_series,
                "series": frame.num_series,
            },
            "meta": [{"index": 0, "metrics": ["timestamp"]}] + [
                {"index": s + 1,
                 "metrics": [alias or frame.metric],
                 "commonTags": frame.tags[s]
                 if s < len(frame.tags) else {},
                 "aggregatedTags": (frame.agg_tags[s]
                                    if s < len(frame.agg_tags) else [])}
                for s in range(frame.num_series)],
        })
    body = json.dumps({"outputs": out_results, "query": obj},
                      separators=(",", ":")).encode()
    return HttpResponse(200, body)
