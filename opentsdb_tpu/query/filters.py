"""Tag-value filters (ref: ``src/query/filter/TagVFilter.java`` and
subclasses).

All 9 reference filter types: literal_or, iliteral_or, not_literal_or,
not_iliteral_or, wildcard, iwildcard, regexp, not_key — with the same
``type(expr)`` shorthand grammar and the old-style tag-map conversion
(``*`` -> wildcard group-by, ``a|b`` -> literal_or group-by, exact value
-> literal_or non-grouping; ref TagVFilter.tagsToFilters).

Evaluation is vectorized: instead of the reference's per-row
``match(tags)`` callbacks post-scan (SaltScanner.java:660-692), a filter
resolves the set of matching tagv UIDs once (string predicates run over
the distinct tag values of the metric, typically tiny compared to the
series count) and then the series mask is a numpy ``isin`` over the
metric's columnar tag index.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Callable, Sequence

import numpy as np

_FILTER_RE = re.compile(r"^(\w+)\((.*)\)$", re.DOTALL)


class TagVFilter:
    """(ref: TagVFilter.java:70)"""

    filter_name = ""
    groupby_default = False

    def __init__(self, tagk: str, filter_expr: str, group_by: bool = False):
        if not tagk:
            raise ValueError("missing tag key")
        self.tagk = tagk
        self.filter_expr = filter_expr
        self.group_by = group_by or self.groupby_default
        self.post_init()

    def post_init(self) -> None:
        pass

    # string predicate over candidate tag values; None => value-independent
    def match_value(self, value: str) -> bool:
        raise NotImplementedError

    @property
    def match_absent(self) -> bool:
        """True when series *lacking* the tag key match (not_key)."""
        return False

    @property
    def includes_present(self) -> bool:
        """True when series having the key may match."""
        return True

    def to_json(self) -> dict:
        return {"tagk": self.tagk, "filter": self.filter_expr,
                "type": self.filter_name, "groupBy": self.group_by}

    def __repr__(self) -> str:
        return (f"{self.filter_name}(tagk={self.tagk}, "
                f"filter={self.filter_expr}, group_by={self.group_by})")

    def __eq__(self, other) -> bool:
        return (type(self) is type(other) and self.tagk == other.tagk
                and self.filter_expr == other.filter_expr
                and self.group_by == other.group_by)

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.tagk, self.filter_expr,
                     self.group_by))


class TagVLiteralOrFilter(TagVFilter):
    """``literal_or(v1|v2)`` (ref: TagVLiteralOrFilter.java:35)"""
    filter_name = "literal_or"
    case_insensitive = False

    def post_init(self) -> None:
        if not self.filter_expr:
            raise ValueError("empty literal_or filter")
        values = self.filter_expr.split("|")
        self._literals = {v.lower() if self.case_insensitive else v
                          for v in values if v}

    def match_value(self, value: str) -> bool:
        v = value.lower() if self.case_insensitive else value
        return v in self._literals

    @property
    def literals(self) -> set[str]:
        return set(self._literals)


class TagVILiteralOrFilter(TagVLiteralOrFilter):
    filter_name = "iliteral_or"
    case_insensitive = True


class TagVNotLiteralOrFilter(TagVLiteralOrFilter):
    filter_name = "not_literal_or"

    def match_value(self, value: str) -> bool:
        return not super().match_value(value)


class TagVNotILiteralOrFilter(TagVILiteralOrFilter):
    filter_name = "not_iliteral_or"

    def match_value(self, value: str) -> bool:
        return not super().match_value(value)


class TagVWildcardFilter(TagVFilter):
    """``wildcard(*web*)`` — ``*`` globs, case sensitive
    (ref: TagVWildcardFilter.java:34)"""
    filter_name = "wildcard"
    case_insensitive = False

    def post_init(self) -> None:
        expr = self.filter_expr
        if not expr or "*" not in expr:
            raise ValueError(
                f"wildcard filter must contain '*': {expr!r}")
        if self.case_insensitive:
            expr = expr.lower()
        self._regex = re.compile(fnmatch.translate(expr))
        self.matches_all = expr.strip("*") == ""

    def match_value(self, value: str) -> bool:
        if self.matches_all:
            return True
        v = value.lower() if self.case_insensitive else value
        return self._regex.match(v) is not None


class TagVIWildcardFilter(TagVWildcardFilter):
    filter_name = "iwildcard"
    case_insensitive = True


class TagVRegexFilter(TagVFilter):
    """``regexp(pattern)`` (ref: TagVRegexFilter.java:28)"""
    filter_name = "regexp"

    def post_init(self) -> None:
        self._regex = re.compile(self.filter_expr)
        self.matches_all = self.filter_expr in (".*", "^.*", ".*$", "^.*$")

    def match_value(self, value: str) -> bool:
        return self._regex.match(value) is not None


class TagVNotKeyFilter(TagVFilter):
    """Matches series that do NOT have the tag key at all
    (ref: TagVNotKeyFilter.java:10). Cannot group by."""
    filter_name = "not_key"

    def post_init(self) -> None:
        if self.filter_expr:
            raise ValueError(
                "Filter value must be null or empty for not_key")
        if self.group_by:
            raise ValueError("cannot group by with a not_key filter")

    def match_value(self, value: str) -> bool:
        return False

    @property
    def match_absent(self) -> bool:
        return True

    @property
    def includes_present(self) -> bool:
        return False


_FILTER_TYPES: dict[str, type[TagVFilter]] = {
    cls.filter_name: cls for cls in (
        TagVLiteralOrFilter, TagVILiteralOrFilter, TagVNotLiteralOrFilter,
        TagVNotILiteralOrFilter, TagVWildcardFilter, TagVIWildcardFilter,
        TagVRegexFilter, TagVNotKeyFilter)
}


def get_filter(tagk: str, expr: str, group_by: bool = False) -> TagVFilter:
    """Parse ``type(value)`` shorthand, or bare value / ``a|b`` / ``*``
    old-style (ref: TagVFilter.getFilter :199-260 + tagsToFilters)."""
    m = _FILTER_RE.match(expr)
    if m:
        ftype, fexpr = m.group(1), m.group(2)
        cls = _FILTER_TYPES.get(ftype)
        if cls is None:
            raise ValueError(f"Unrecognized filter type: {ftype}")
        return cls(tagk, fexpr, group_by)
    # old-style tag values
    if expr == "*" or "*" in expr:
        return TagVIWildcardFilter(tagk, expr, group_by)
    if "|" in expr:
        return TagVLiteralOrFilter(tagk, expr, group_by)
    return TagVLiteralOrFilter(tagk, expr, group_by)


def build_filter(obj: dict) -> TagVFilter:
    """From the 2.x JSON form {type, tagk, filter, groupBy}."""
    ftype = obj.get("type", "")
    cls = _FILTER_TYPES.get(ftype)
    if cls is None:
        raise ValueError(f"Unrecognized filter type: {ftype}")
    return cls(obj.get("tagk", ""), obj.get("filter", ""),
               bool(obj.get("groupBy", False)))


def tags_to_filters(tags: dict[str, str]) -> list[TagVFilter]:
    """Old-style v1 tag map -> filters (ref: TagVFilter.tagsToFilters):
    ``*``/wildcards and ``a|b`` group by; exact values only filter."""
    out = []
    for tagk, expr in tags.items():
        group_by = "*" in expr or "|" in expr or expr.startswith(
            ("wildcard(", "iwildcard(", "literal_or(", "iliteral_or(",
             "regexp("))
        out.append(get_filter(tagk, expr, group_by=group_by))
    return out


def filter_types() -> dict[str, dict]:
    """Metadata for ``/api/config/filters`` (ref: RpcManager)."""
    docs = {
        "literal_or": ("Accepts one or more exact values and matches if "
                       "the series contains any of them. Case sensitive.",
                       "host=literal_or(web01|web02)"),
        "iliteral_or": ("Accepts one or more exact values and matches if "
                        "the series contains any of them. Case insensitive.",
                        "host=iliteral_or(web01|web02)"),
        "not_literal_or": ("Accepts one or more exact values and matches "
                           "if the series does NOT contain any of them. "
                           "Case sensitive.", "host=not_literal_or(web01)"),
        "not_iliteral_or": ("Accepts one or more exact values and matches "
                            "if the series does NOT contain any of them. "
                            "Case insensitive.",
                            "host=not_iliteral_or(web01)"),
        "wildcard": ("Performs pre, post and in-fix glob matching of "
                     "values. Case sensitive.", "host=wildcard(web*)"),
        "iwildcard": ("Performs pre, post and in-fix glob matching of "
                      "values. Case insensitive.", "host=iwildcard(web*)"),
        "regexp": ("Provides full, POSIX compliant regular expression "
                   "using the built in Java Pattern class.",
                   "host=regexp(.*)"),
        "not_key": ("Skips any time series with the given tag key, "
                    "regardless of the value.", "host=not_key()"),
    }
    return {name: {"description": d, "examples": e}
            for name, (d, e) in docs.items()}


class FilterEvaluator:
    """Vectorized filter application over a metric's columnar tag index."""

    def __init__(self, uids):
        self._uids = uids

    def matching_tagv_ids(self, filt: TagVFilter,
                          candidate_ids: np.ndarray) -> np.ndarray:
        """Run the string predicate over distinct candidate tagv ids."""
        tagv = self._uids.tag_values
        keep = [vid for vid in candidate_ids.tolist()
                if filt.match_value(tagv.get_name(int(vid)))]
        return np.asarray(keep, dtype=np.int64)

    def apply(self, filters: Sequence[TagVFilter], sids: np.ndarray,
              tag_triples: np.ndarray) -> np.ndarray:
        """Return the boolean keep-mask over ``sids``.

        ``tag_triples`` is the metric index's [T,3] (sid, tagk, tagv).
        Every filter must pass — same-key and cross-key filters all AND
        together (ref: TsdbQuery/SaltScanner filter chain semantics).
        """
        if len(sids) == 0:
            return np.zeros(0, dtype=bool)
        keep = np.ones(len(sids), dtype=bool)
        # vectorized sid -> position mapping (a Python dict walk over
        # the triples costs ~0.4 s at 200k series)
        order = np.argsort(sids, kind="stable")
        sorted_sids = sids[order]
        by_key: dict[str, list[TagVFilter]] = {}
        for f in filters:
            by_key.setdefault(f.tagk, []).append(f)
        for tagk, flist in by_key.items():
            try:
                kid = self._uids.tag_names.get_id(tagk)
            except LookupError:
                # unknown tag key: only not_key filters can match
                if not all(f.match_absent for f in flist):
                    return np.zeros(len(sids), dtype=bool)
                continue
            rows = tag_triples[tag_triples[:, 1] == kid]
            has_key = np.zeros(len(sids), dtype=bool)
            series_tagv = np.full(len(sids), -1, dtype=np.int64)
            ins = np.searchsorted(sorted_sids, rows[:, 0])
            ins_c = np.minimum(ins, len(sids) - 1)
            valid = sorted_sids[ins_c] == rows[:, 0]
            pos = order[ins_c[valid]]
            has_key[pos] = True
            series_tagv[pos] = rows[valid, 2]
            key_mask = np.ones(len(sids), dtype=bool)
            for f in flist:
                if f.match_absent and not f.includes_present:
                    fmask = ~has_key
                else:
                    cand = np.unique(series_tagv[has_key])
                    matched = self.matching_tagv_ids(f, cand)
                    fmask = has_key & np.isin(series_tagv, matched)
                # same-key filters AND together like the reference's
                # per-key chain (all must pass)
                key_mask &= fmask
            keep &= key_mask
        return keep
