"""Histogram / percentile query path.

(ref: ``TsdbQuery.isHistogramQuery`` :776 routes queries with
``percentiles`` set to the HistogramSpan/HistogramAggregationIterator
pipeline; merge is bucket-wise SUM, then ``SimpleHistogram.percentile``)

TPU formulation: the histogram points of all series in the window stack
into a dense ``[points, buckets]`` count matrix; merge-by-timestamp and
group-by are segment-sums over the leading axis, and percentile
extraction is a vectorized cumsum + searchsorted over the bucket axis —
see :func:`percentiles_from_counts`.

Downsampling (ref: ``HistogramDownsampler.java`` wrapping each span
before the group merge): histogram aggregation is bucket-wise SUM both
across series and across time (``HistogramAggregation.java:20`` — SUM is
the only defined merge), so downsample-then-merge collapses into ONE
segment-sum keyed by (group, time-bucket) — the time axis just uses
downsample bucket indices instead of distinct-timestamp indices.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.query.model import BadRequestError, TSQuery, TSSubQuery


def percentiles_from_counts(counts: np.ndarray, bounds: np.ndarray,
                            qs: list[float]) -> np.ndarray:
    """counts[T, nbuckets], bounds[nbuckets+1] -> [len(qs), T].

    Midpoint convention matches SimpleHistogram.percentile (:133): the
    bucket whose cumulative count crosses rank contributes its midpoint.
    """
    totals = counts.sum(axis=1)  # [T]
    cum = np.cumsum(counts, axis=1)  # [T, B]
    mids = (bounds[:-1] + bounds[1:]) / 2.0
    out = np.empty((len(qs), counts.shape[0]), dtype=np.float64)
    for qi, q in enumerate(qs):
        target = totals * (q / 100.0)
        idx = np.sum(cum < target[:, None], axis=1)
        idx = np.clip(idx, 0, len(mids) - 1)
        out[qi] = np.where(totals > 0, mids[idx], 0.0)
    return out


def _time_axis(point_ts: np.ndarray, tsq: TSQuery, sub: TSSubQuery
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(time_idx[N], ts_out[T], in_range[N]) for the histogram batch:
    downsample bucket indices when the sub-query has a downsample spec
    (ref: HistogramDownsampler), else one slot per distinct timestamp
    (ref: the raw HistogramAggregationIterator union merge)."""
    if sub.ds_spec is not None:
        from opentsdb_tpu.ops import downsample as ds_mod
        bucket_idx, bucket_ts = ds_mod.assign_buckets(
            point_ts, sub.ds_spec, tsq.start_ms, tsq.end_ms)
        bucket_idx = np.asarray(bucket_idx)
        bucket_ts = np.asarray(bucket_ts)
        # points are pre-filtered to the window, but guard the bucket
        # range anyway (assign_buckets assumes in-range input)
        return (bucket_idx, bucket_ts,
                (bucket_idx >= 0) & (bucket_idx < len(bucket_ts)))
    ts_sorted, ts_idx = np.unique(point_ts, return_inverse=True)
    return ts_idx, ts_sorted, np.ones(len(point_ts), dtype=bool)


def run_histogram_subquery(tsdb, tsq: TSQuery, sub: TSSubQuery) -> list:
    """Execute a percentile sub-query over stored histogram datapoints."""
    from opentsdb_tpu.query.engine import QueryEngine, TagMatrix
    uids = tsdb.uids
    try:
        metric_id = uids.metrics.get_id(sub.metric)
    except LookupError:
        raise BadRequestError(
            f"No such name for 'metrics': '{sub.metric}'") from None
    store = tsdb.histogram_store
    sids = store.series_ids_for_metric(metric_id)
    if len(sids) == 0:
        return []
    # filters reuse the scalar evaluator over the histogram store's index
    from opentsdb_tpu.query.filters import FilterEvaluator
    idx = store.metric_index(metric_id)
    _, triples = idx.arrays()
    tag_mat = TagMatrix.from_triples(sids, triples)
    if sub.filters:
        mask = FilterEvaluator(uids).apply(sub.filters, sids, triples)
        sids = sids[mask]
        tag_mat = tag_mat.select(mask)
        if len(sids) == 0:
            return []

    gb_kids = sorted({uids.tag_names.get_id(f.tagk)
                      for f in sub.filters if f.group_by
                      and uids.tag_names.has_name(f.tagk)})
    group_ids, num_groups = QueryEngine._group_ids(tag_mat, gb_kids)

    # collect the window's histogram points as one flat [N, NB] batch.
    # The collected batch (counts matrix device-resident) is cached by
    # write version: the per-series object walk and the upload are the
    # whole cost at scale (ref analogue: scan result block caching).
    cache = tsdb.device_grid_cache
    ckey = cver = None
    counts = point_sidx = point_ts_arr = None
    bounds: tuple | None = None
    if cache is not None:
        from opentsdb_tpu.query.device_cache import array_digest
        ckey = ("hist", array_digest(np.ascontiguousarray(sids)),
                tsq.start_ms, tsq.end_ms)
        cver = tsdb._histogram_version
        hit = cache.get(ckey, cver)
        if hit is not None:
            (counts,), meta = hit
            point_sidx = meta["point_sidx"]
            point_ts_arr = meta["point_ts"]
            bounds = meta["bounds"]
    if counts is None:
        # columnar arena slice (no per-point or per-series Python):
        # membership + window masks over flat arrays, one fancy-index
        # gather for the rows (ref analogue: SaltScanner streaming
        # histogram cells; HistogramSpan assembly collapses into this).
        # Snapshots are captured under the lock (the append-side lock);
        # see HistogramArena._Sub.snapshot for why the views stay
        # stable afterwards.
        with tsdb._histogram_lock:
            arena = tsdb._histogram_arenas.get(metric_id)
            snaps = [(s.bounds, *s.snapshot())
                     for s in arena.groups.values()] if arena else []
        if not snaps:
            return []
        order = np.argsort(sids, kind="stable")
        sorted_sids = np.asarray(sids)[order]

        def member_mask(ts_a, sid_a):
            pos = np.searchsorted(sorted_sids, sid_a)
            pos = np.clip(pos, 0, len(sorted_sids) - 1)
            return pos, ((sorted_sids[pos] == sid_a)
                         & (ts_a >= tsq.start_ms)
                         & (ts_a <= tsq.end_ms))

        masked = [(snap, *member_mask(snap[1], snap[2]))
                  for snap in snaps]
        active = [(snap, pos, m) for snap, pos, m in masked
                  if m.any()]
        if not active:
            return []
        if len(active) > 1:
            # bounds genuinely disagree INSIDE the window: host merge
            # path with per-slot bounds checks. A bounds class with no
            # points in the window must not disable the device path
            # (a single stray historic migration would otherwise
            # penalize every future query).
            return _run_mixed_bounds(tsdb, tsq, sub, active, sids,
                                     tag_mat, group_ids, num_groups)
        (bounds, ts_a, sid_a, rows), pos, member = active[0]
        counts = rows[member]
        # index into the caller's sids array (group_ids aligns to it)
        point_sidx = order[pos[member]].astype(np.int64)
        point_ts_arr = ts_a[member]
        if cache is not None:
            import jax
            import jax.numpy as jnp
            from opentsdb_tpu.ops import shapes
            # cache the counts matrix PRE-PADDED to its shape bucket:
            # warm queries then skip both the pad alloc and the
            # re-upload (histogram_percentile_pipeline pads seg_ids to
            # the row count)
            n_pad = shapes.shape_bucket(len(counts))
            counts = shapes.pad_2d_host(counts, n_pad,
                                        counts.shape[1], 0.0)
            counts = jax.device_put(
                jnp.asarray(counts, dtype=jnp.float32))
            cache.put(ckey, cver, (counts,), {
                "point_sidx": point_sidx, "point_ts": point_ts_arr,
                "bounds": bounds})

    # device path (uniform bounds): merge = one-hot MXU contraction,
    # percentiles = cumsum + rank compare — ops.histogram_kernels.
    # The time axis is downsample buckets when ds_spec is set
    # (HistogramDownsampler parity), else the distinct-timestamp union.
    from opentsdb_tpu.ops.histogram_kernels import \
        histogram_percentile_pipeline
    time_idx, ts_out_arr, in_range = _time_axis(point_ts_arr, tsq, sub)
    gvec = np.asarray(group_ids, dtype=np.int64)[point_sidx]
    if not in_range.all():
        # partial-range: filter the REAL rows (cached counts may carry
        # shape-bucket padding past len(point_sidx))
        counts = np.asarray(counts)[:len(point_sidx)][in_range]
        gvec = gvec[in_range]
        time_idx = time_idx[in_range]
    if len(gvec) == 0:
        return []
    num_ts = len(ts_out_arr)
    seg = (gvec * num_ts + time_idx).astype(np.int32)
    pcts = histogram_percentile_pipeline(
        counts, seg, num_groups * num_ts, np.asarray(bounds),
        sub.percentiles)                       # [Q, G*T]
    pcts = pcts.reshape(len(sub.percentiles), num_groups, num_ts)
    present = np.bincount(seg, minlength=num_groups * num_ts) \
        .reshape(num_groups, num_ts) > 0

    return _emit_groups(tsdb, tsq, sub, tag_mat, group_ids, num_groups,
                        ts_out_arr, present, pcts)


def _emit_groups(tsdb, tsq, sub, tag_mat, group_ids, num_groups,
                 ts_arr, present, pcts) -> list:
    """Shared emission: one QueryResult per (group, percentile)."""
    from opentsdb_tpu.query.engine import QueryResult, _common_tags
    uids = tsdb.uids
    order = np.argsort(group_ids, kind="stable")
    sorted_gids = group_ids[order]
    gid_range = np.arange(num_groups, dtype=group_ids.dtype)
    starts = np.searchsorted(sorted_gids, gid_range, side="left")
    ends = np.searchsorted(sorted_gids, gid_range, side="right")
    ts_list = (ts_arr if tsq.ms_resolution
               else (ts_arr // 1000) * 1000).tolist()
    out = []
    for gid in range(num_groups):
        members = order[starts[gid]:ends[gid]]
        if len(members) == 0 or not present[gid].any():
            continue
        tags, agg_tags = _common_tags(tag_mat, members, uids)
        sel = np.nonzero(present[gid])[0]
        for qi, q in enumerate(sub.percentiles):
            vals = pcts[qi, gid, sel].tolist()
            dps = [(ts_list[t], v) for t, v in zip(sel.tolist(), vals)]
            out.append(QueryResult(
                metric=f"{sub.metric}_pct_{q:g}", tags=tags,
                aggregated_tags=agg_tags, dps=dps,
                sub_query_index=sub.index))
    return out


def _run_mixed_bounds(tsdb, tsq, sub, active, sids, tag_mat, group_ids,
                      num_groups) -> list:
    """Host fallback when the window's histograms disagree on bucket
    bounds: per-group merge keyed on the output timestamp, each slot
    keeping its own bounds (the reference merges Histogram objects per
    emitted timestamp; bounds must agree across series AT one ts — ref
    HistogramAggregationIterator). Slot assignment and per-point group
    ids are computed ONCE per bounds-class; the per-group work is a
    mask + segment-sum, no per-point Python.

    ``active`` carries pre-masked snapshots:
    [((bounds, ts, sid, rows), pos, window_member_mask), ...].
    """
    from opentsdb_tpu.query.engine import QueryResult, _common_tags
    from opentsdb_tpu.ops import downsample as ds_mod
    uids = tsdb.uids
    sids = np.asarray(sids)
    sid_order = np.argsort(sids, kind="stable")
    sorted_sids = sids[sid_order]
    gids_sorted = np.asarray(group_ids)[sid_order]

    # per bounds-class precompute: filtered points, their group ids,
    # and their output slot (group-independent)
    pre = []
    for (bounds, ts_a, sid_a, rows), _pos, m in active:
        ts_f, sid_f, rows_f = ts_a[m], sid_a[m], rows[m]
        pos = np.searchsorted(sorted_sids, sid_f)
        point_gid = gids_sorted[np.clip(pos, 0, len(sorted_sids) - 1)]
        if sub.ds_spec is not None:
            bidx, bts = ds_mod.assign_buckets(
                ts_f, sub.ds_spec, tsq.start_ms, tsq.end_ms)
            bidx = np.asarray(bidx)
            bts = np.asarray(bts)
            ok = (bidx >= 0) & (bidx < len(bts))
            slots = bts[np.clip(bidx, 0, len(bts) - 1)]
            ts_f, rows_f = ts_f[ok], rows_f[ok]
            point_gid, slots = point_gid[ok], slots[ok]
        else:
            slots = ts_f
        pre.append((bounds, point_gid, slots, rows_f))

    # one argsort for per-group member recovery (same pattern as
    # _emit_groups; an == scan per group would be O(G x S))
    gid_order = np.argsort(group_ids, kind="stable")
    gids_in_order = np.asarray(group_ids)[gid_order]
    gid_range = np.arange(num_groups, dtype=np.asarray(group_ids).dtype)
    g_starts = np.searchsorted(gids_in_order, gid_range, side="left")
    g_ends = np.searchsorted(gids_in_order, gid_range, side="right")

    out = []
    for gid in range(num_groups):
        merged: dict[int, tuple[tuple, np.ndarray]] = {}
        for b, point_gid, slots_all, rows_f in pre:
            gmask = point_gid == gid
            if not gmask.any():
                continue
            slots = slots_all[gmask]
            uniq, inv = np.unique(slots, return_inverse=True)
            acc = np.zeros((len(uniq), rows_f.shape[1]),
                           dtype=np.float64)
            np.add.at(acc, inv, rows_f[gmask])
            for k, slot in enumerate(uniq.tolist()):
                if slot in merged:
                    b0, prev = merged[slot]
                    if b0 != b:
                        raise BadRequestError(
                            "cannot merge histograms with different "
                            f"buckets at timestamp {slot}")
                    merged[slot] = (b0, prev + acc[k])
                else:
                    merged[slot] = (b, acc[k])
        if not merged:
            continue
        members = gid_order[g_starts[gid]:g_ends[gid]]
        ts_sorted = sorted(merged)
        pcts = np.stack([
            percentiles_from_counts(
                merged[t][1][None, :],
                np.asarray(merged[t][0], dtype=np.float64),
                sub.percentiles)[:, 0]
            for t in ts_sorted], axis=1)       # [Q, T]
        tags, agg_tags = _common_tags(tag_mat, members, uids)
        for qi, q in enumerate(sub.percentiles):
            dps = [((t // 1000) * 1000 if not tsq.ms_resolution else t,
                    float(pcts[qi, ti]))
                   for ti, t in enumerate(ts_sorted)]
            out.append(QueryResult(
                metric=f"{sub.metric}_pct_{q:g}", tags=tags,
                aggregated_tags=agg_tags, dps=dps,
                sub_query_index=sub.index))
    return out
