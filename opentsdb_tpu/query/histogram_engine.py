"""Histogram / percentile query path.

(ref: ``TsdbQuery.isHistogramQuery`` :776 routes queries with
``percentiles`` set to the HistogramSpan/HistogramAggregationIterator
pipeline; merge is bucket-wise SUM, then ``SimpleHistogram.percentile``)

TPU formulation: the histogram points of all series in the window stack
into a dense ``[points, buckets]`` count matrix; merge-by-timestamp and
group-by are segment-sums over the leading axis, and percentile
extraction is a vectorized cumsum + searchsorted over the bucket axis —
see :func:`percentiles_from_counts`.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.query.model import BadRequestError, TSQuery, TSSubQuery


def percentiles_from_counts(counts: np.ndarray, bounds: np.ndarray,
                            qs: list[float]) -> np.ndarray:
    """counts[T, nbuckets], bounds[nbuckets+1] -> [len(qs), T].

    Midpoint convention matches SimpleHistogram.percentile (:133): the
    bucket whose cumulative count crosses rank contributes its midpoint.
    """
    totals = counts.sum(axis=1)  # [T]
    cum = np.cumsum(counts, axis=1)  # [T, B]
    mids = (bounds[:-1] + bounds[1:]) / 2.0
    out = np.empty((len(qs), counts.shape[0]), dtype=np.float64)
    for qi, q in enumerate(qs):
        target = totals * (q / 100.0)
        idx = np.sum(cum < target[:, None], axis=1)
        idx = np.clip(idx, 0, len(mids) - 1)
        out[qi] = np.where(totals > 0, mids[idx], 0.0)
    return out


def run_histogram_subquery(tsdb, tsq: TSQuery, sub: TSSubQuery) -> list:
    """Execute a percentile sub-query over stored histogram datapoints."""
    from opentsdb_tpu.query.engine import QueryResult, _common_tags
    uids = tsdb.uids
    try:
        metric_id = uids.metrics.get_id(sub.metric)
    except LookupError:
        raise BadRequestError(
            f"No such name for 'metrics': '{sub.metric}'") from None
    store = tsdb.histogram_store
    sids = store.series_ids_for_metric(metric_id)
    if len(sids) == 0:
        return []
    # filters reuse the scalar evaluator over the histogram store's index
    from opentsdb_tpu.query.filters import FilterEvaluator
    if sub.filters:
        idx = store.metric_index(metric_id)
        _, triples = idx.arrays()
        mask = FilterEvaluator(uids).apply(sub.filters, sids, triples)
        sids = sids[mask]
        if len(sids) == 0:
            return []
    series_tags = [dict(store.series(int(s)).tags) for s in sids]

    gb_kids = sorted({uids.tag_names.get_id(f.tagk)
                      for f in sub.filters if f.group_by
                      and uids.tag_names.has_name(f.tagk)})
    from opentsdb_tpu.query.engine import QueryEngine
    group_ids, group_keys = QueryEngine._group_ids(series_tags, gb_kids)

    out = []
    for gid in range(len(group_keys)):
        members = [i for i in range(len(sids)) if group_ids[i] == gid]
        if not members:
            continue
        # merge member histograms by timestamp (bucket-wise SUM)
        merged: dict[int, np.ndarray] = {}
        bounds = None
        for i in members:
            for ts_ms, hist in tsdb._histogram_series.get(int(sids[i]), []):
                if not (tsq.start_ms <= ts_ms <= tsq.end_ms):
                    continue
                arr = hist.counts_array()
                if bounds is None:
                    bounds = np.asarray(hist.bounds, dtype=np.float64)
                if ts_ms in merged:
                    merged[ts_ms] = merged[ts_ms] + arr
                else:
                    merged[ts_ms] = arr
        if not merged or bounds is None:
            continue
        ts_sorted = sorted(merged)
        counts = np.stack([merged[t] for t in ts_sorted])
        pcts = percentiles_from_counts(counts, bounds, sub.percentiles)
        tags, agg_tags = _common_tags(
            [series_tags[m] for m in members], uids)
        for qi, q in enumerate(sub.percentiles):
            dps = [((t // 1000) * 1000 if not tsq.ms_resolution else t,
                    float(pcts[qi, ti]))
                   for ti, t in enumerate(ts_sorted)]
            out.append(QueryResult(
                metric=f"{sub.metric}_pct_{q:g}", tags=tags,
                aggregated_tags=agg_tags, dps=dps,
                sub_query_index=sub.index))
    return out
