"""Histogram / percentile query path.

(ref: ``TsdbQuery.isHistogramQuery`` :776 routes queries with
``percentiles`` set to the HistogramSpan/HistogramAggregationIterator
pipeline; merge is bucket-wise SUM, then ``SimpleHistogram.percentile``)

TPU formulation: the histogram points of all series in the window stack
into a dense ``[points, buckets]`` count matrix; merge-by-timestamp and
group-by are segment-sums over the leading axis, and percentile
extraction is a vectorized cumsum + searchsorted over the bucket axis —
see :func:`percentiles_from_counts`.

Downsampling (ref: ``HistogramDownsampler.java`` wrapping each span
before the group merge): histogram aggregation is bucket-wise SUM both
across series and across time (``HistogramAggregation.java:20`` — SUM is
the only defined merge), so downsample-then-merge collapses into ONE
segment-sum keyed by (group, time-bucket) — the time axis just uses
downsample bucket indices instead of distinct-timestamp indices.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.query.model import BadRequestError, TSQuery, TSSubQuery


def percentiles_from_counts(counts: np.ndarray, bounds: np.ndarray,
                            qs: list[float]) -> np.ndarray:
    """counts[T, nbuckets], bounds[nbuckets+1] -> [len(qs), T].

    Midpoint convention matches SimpleHistogram.percentile (:133): the
    bucket whose cumulative count crosses rank contributes its midpoint.
    """
    totals = counts.sum(axis=1)  # [T]
    cum = np.cumsum(counts, axis=1)  # [T, B]
    mids = (bounds[:-1] + bounds[1:]) / 2.0
    out = np.empty((len(qs), counts.shape[0]), dtype=np.float64)
    for qi, q in enumerate(qs):
        target = totals * (q / 100.0)
        idx = np.sum(cum < target[:, None], axis=1)
        idx = np.clip(idx, 0, len(mids) - 1)
        out[qi] = np.where(totals > 0, mids[idx], 0.0)
    return out


def _time_axis(point_ts: np.ndarray, tsq: TSQuery, sub: TSSubQuery
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(time_idx[N], ts_out[T], in_range[N]) for the histogram batch:
    downsample bucket indices when the sub-query has a downsample spec
    (ref: HistogramDownsampler), else one slot per distinct timestamp
    (ref: the raw HistogramAggregationIterator union merge)."""
    if sub.ds_spec is not None:
        from opentsdb_tpu.ops import downsample as ds_mod
        bucket_idx, bucket_ts = ds_mod.assign_buckets(
            point_ts, sub.ds_spec, tsq.start_ms, tsq.end_ms)
        bucket_idx = np.asarray(bucket_idx)
        bucket_ts = np.asarray(bucket_ts)
        # points are pre-filtered to the window, but guard the bucket
        # range anyway (assign_buckets assumes in-range input)
        return (bucket_idx, bucket_ts,
                (bucket_idx >= 0) & (bucket_idx < len(bucket_ts)))
    ts_sorted, ts_idx = np.unique(point_ts, return_inverse=True)
    return ts_idx, ts_sorted, np.ones(len(point_ts), dtype=bool)


def run_histogram_subquery(tsdb, tsq: TSQuery, sub: TSSubQuery) -> list:
    """Execute a percentile sub-query over stored histogram datapoints."""
    from opentsdb_tpu.query.engine import QueryEngine, TagMatrix
    uids = tsdb.uids
    try:
        metric_id = uids.metrics.get_id(sub.metric)
    except LookupError:
        raise BadRequestError(
            f"No such name for 'metrics': '{sub.metric}'") from None
    store = tsdb.histogram_store
    sids = store.series_ids_for_metric(metric_id)
    if len(sids) == 0:
        return []
    # filters reuse the scalar evaluator over the histogram store's index
    from opentsdb_tpu.query.filters import FilterEvaluator
    idx = store.metric_index(metric_id)
    _, triples = idx.arrays()
    tag_mat = TagMatrix.from_triples(sids, triples)
    if sub.filters:
        mask = FilterEvaluator(uids).apply(sub.filters, sids, triples)
        sids = sids[mask]
        tag_mat = tag_mat.select(mask)
        if len(sids) == 0:
            return []

    gb_kids = sorted({uids.tag_names.get_id(f.tagk)
                      for f in sub.filters if f.group_by
                      and uids.tag_names.has_name(f.tagk)})
    group_ids, num_groups = QueryEngine._group_ids(tag_mat, gb_kids)

    # collect the window's histogram points as one flat [N, NB] batch.
    # The collected batch (counts matrix device-resident) is cached by
    # write version: the per-series object walk and the upload are the
    # whole cost at scale (ref analogue: scan result block caching).
    cache = tsdb.device_grid_cache
    ckey = cver = None
    counts = point_sidx = point_ts_arr = None
    bounds: tuple | None = None
    if cache is not None:
        from opentsdb_tpu.query.device_cache import array_digest
        ckey = ("hist", array_digest(np.ascontiguousarray(sids)),
                tsq.start_ms, tsq.end_ms)
        cver = tsdb._histogram_version
        hit = cache.get(ckey, cver)
        if hit is not None:
            (counts,), meta = hit
            point_sidx = meta["point_sidx"]
            point_ts_arr = meta["point_ts"]
            bounds = meta["bounds"]
    if counts is None:
        point_counts: list[np.ndarray] = []
        point_sidx_l: list[int] = []
        point_ts_l: list[int] = []
        uniform = True
        with tsdb._histogram_lock:
            series_pts = [list(tsdb._histogram_series.get(int(s), []))
                          for s in sids]
        for i in range(len(sids)):
            for ts_ms, hist in series_pts[i]:
                if not (tsq.start_ms <= ts_ms <= tsq.end_ms):
                    continue
                b = tuple(hist.bounds)
                if bounds is None:
                    bounds = b
                elif b != bounds:
                    uniform = False
                point_counts.append(hist.counts_array())
                point_sidx_l.append(i)
                point_ts_l.append(ts_ms)
        if not point_counts or bounds is None:
            return []
        if not uniform:
            return _run_mixed_bounds(tsdb, tsq, sub, series_pts,
                                     tag_mat, group_ids, num_groups)
        counts = np.stack(point_counts)
        point_sidx = np.asarray(point_sidx_l, dtype=np.int64)
        point_ts_arr = np.asarray(point_ts_l, dtype=np.int64)
        if cache is not None:
            import jax
            import jax.numpy as jnp
            counts = jax.device_put(
                jnp.asarray(counts, dtype=jnp.float32))
            cache.put(ckey, cver, (counts,), {
                "point_sidx": point_sidx, "point_ts": point_ts_arr,
                "bounds": bounds})

    # device path (uniform bounds): merge = one-hot MXU contraction,
    # percentiles = cumsum + rank compare — ops.histogram_kernels.
    # The time axis is downsample buckets when ds_spec is set
    # (HistogramDownsampler parity), else the distinct-timestamp union.
    from opentsdb_tpu.ops.histogram_kernels import \
        histogram_percentile_pipeline
    time_idx, ts_out_arr, in_range = _time_axis(point_ts_arr, tsq, sub)
    gvec = np.asarray(group_ids, dtype=np.int64)[point_sidx]
    if not in_range.all():
        counts = np.asarray(counts)[in_range]
        gvec = gvec[in_range]
        time_idx = time_idx[in_range]
    if counts.shape[0] == 0:
        return []
    num_ts = len(ts_out_arr)
    seg = (gvec * num_ts + time_idx).astype(np.int32)
    pcts = histogram_percentile_pipeline(
        counts, seg, num_groups * num_ts, np.asarray(bounds),
        sub.percentiles)                       # [Q, G*T]
    pcts = pcts.reshape(len(sub.percentiles), num_groups, num_ts)
    present = np.bincount(seg, minlength=num_groups * num_ts) \
        .reshape(num_groups, num_ts) > 0

    return _emit_groups(tsdb, tsq, sub, tag_mat, group_ids, num_groups,
                        ts_out_arr, present, pcts)


def _emit_groups(tsdb, tsq, sub, tag_mat, group_ids, num_groups,
                 ts_arr, present, pcts) -> list:
    """Shared emission: one QueryResult per (group, percentile)."""
    from opentsdb_tpu.query.engine import QueryResult, _common_tags
    uids = tsdb.uids
    order = np.argsort(group_ids, kind="stable")
    sorted_gids = group_ids[order]
    gid_range = np.arange(num_groups, dtype=group_ids.dtype)
    starts = np.searchsorted(sorted_gids, gid_range, side="left")
    ends = np.searchsorted(sorted_gids, gid_range, side="right")
    ts_list = (ts_arr if tsq.ms_resolution
               else (ts_arr // 1000) * 1000).tolist()
    out = []
    for gid in range(num_groups):
        members = order[starts[gid]:ends[gid]]
        if len(members) == 0 or not present[gid].any():
            continue
        tags, agg_tags = _common_tags(tag_mat, members, uids)
        sel = np.nonzero(present[gid])[0]
        for qi, q in enumerate(sub.percentiles):
            vals = pcts[qi, gid, sel].tolist()
            dps = [(ts_list[t], v) for t, v in zip(sel.tolist(), vals)]
            out.append(QueryResult(
                metric=f"{sub.metric}_pct_{q:g}", tags=tags,
                aggregated_tags=agg_tags, dps=dps,
                sub_query_index=sub.index))
    return out


def _run_mixed_bounds(tsdb, tsq, sub, series_pts, tag_mat, group_ids,
                      num_groups) -> list:
    """Host fallback when histograms in the window disagree on bucket
    bounds: per-group dict merge like the reference's iterator chain.
    With a downsample spec, points merge into their downsample bucket
    (bounds must agree within a bucket, like the reference's
    HistogramDownsampler SUM over one interval)."""
    from opentsdb_tpu.query.engine import QueryResult, _common_tags
    from opentsdb_tpu.ops import downsample as ds_mod
    uids = tsdb.uids
    order = np.argsort(group_ids, kind="stable")
    sorted_gids = group_ids[order]
    gid_range = np.arange(num_groups, dtype=group_ids.dtype)
    starts = np.searchsorted(sorted_gids, gid_range, side="left")
    ends = np.searchsorted(sorted_gids, gid_range, side="right")
    out = []
    for gid in range(num_groups):
        members = order[starts[gid]:ends[gid]]
        if len(members) == 0:
            continue
        # merge per output timestamp, each keeping its own bucket
        # bounds (the reference merges Histogram objects per emitted
        # timestamp; bounds only need to agree across series AT one ts)
        merged: dict[int, tuple[tuple, np.ndarray]] = {}
        for i in members:
            pts = series_pts[int(i)]
            if not pts:
                continue
            ts_arr = np.asarray([t for t, _ in pts], dtype=np.int64)
            ok = (ts_arr >= tsq.start_ms) & (ts_arr <= tsq.end_ms)
            if sub.ds_spec is not None:
                bidx, bts = ds_mod.assign_buckets(
                    ts_arr, sub.ds_spec, tsq.start_ms, tsq.end_ms)
                bidx = np.asarray(bidx)
                bts = np.asarray(bts)
                ok &= (bidx >= 0) & (bidx < len(bts))
                slot_ts = np.where(ok, bts[np.clip(bidx, 0,
                                                   len(bts) - 1)], -1)
            else:
                slot_ts = np.where(ok, ts_arr, -1)
            for (_, hist), slot in zip(pts, slot_ts.tolist()):
                if slot < 0:
                    continue
                arr = hist.counts_array()
                b = tuple(hist.bounds)
                if slot in merged:
                    b0, acc = merged[slot]
                    if b0 != b:
                        raise BadRequestError(
                            "cannot merge histograms with different "
                            f"buckets at timestamp {slot}")
                    merged[slot] = (b0, acc + arr)
                else:
                    merged[slot] = (b, arr)
        if not merged:
            continue
        ts_sorted = sorted(merged)
        pcts = np.stack([
            percentiles_from_counts(
                merged[t][1][None, :],
                np.asarray(merged[t][0], dtype=np.float64),
                sub.percentiles)[:, 0]
            for t in ts_sorted], axis=1)       # [Q, T]
        tags, agg_tags = _common_tags(tag_mat, members, uids)
        for qi, q in enumerate(sub.percentiles):
            dps = [((t // 1000) * 1000 if not tsq.ms_resolution else t,
                    float(pcts[qi, ti]))
                   for ti, t in enumerate(ts_sorted)]
            out.append(QueryResult(
                metric=f"{sub.metric}_pct_{q:g}", tags=tags,
                aggregated_tags=agg_tags, dps=dps,
                sub_query_index=sub.index))
    return out
