"""Histogram / percentile query path.

(ref: ``TsdbQuery.isHistogramQuery`` :776 routes queries with
``percentiles`` set to the HistogramSpan/HistogramAggregationIterator
pipeline; merge is bucket-wise SUM, then ``SimpleHistogram.percentile``)

TPU formulation: the histogram points of all series in the window stack
into a dense ``[points, buckets]`` count matrix; merge-by-timestamp and
group-by are segment-sums over the leading axis, and percentile
extraction is a vectorized cumsum + searchsorted over the bucket axis —
see :func:`percentiles_from_counts`.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.query.model import BadRequestError, TSQuery, TSSubQuery


def percentiles_from_counts(counts: np.ndarray, bounds: np.ndarray,
                            qs: list[float]) -> np.ndarray:
    """counts[T, nbuckets], bounds[nbuckets+1] -> [len(qs), T].

    Midpoint convention matches SimpleHistogram.percentile (:133): the
    bucket whose cumulative count crosses rank contributes its midpoint.
    """
    totals = counts.sum(axis=1)  # [T]
    cum = np.cumsum(counts, axis=1)  # [T, B]
    mids = (bounds[:-1] + bounds[1:]) / 2.0
    out = np.empty((len(qs), counts.shape[0]), dtype=np.float64)
    for qi, q in enumerate(qs):
        target = totals * (q / 100.0)
        idx = np.sum(cum < target[:, None], axis=1)
        idx = np.clip(idx, 0, len(mids) - 1)
        out[qi] = np.where(totals > 0, mids[idx], 0.0)
    return out


def run_histogram_subquery(tsdb, tsq: TSQuery, sub: TSSubQuery) -> list:
    """Execute a percentile sub-query over stored histogram datapoints."""
    from opentsdb_tpu.query.engine import QueryResult, _common_tags
    uids = tsdb.uids
    try:
        metric_id = uids.metrics.get_id(sub.metric)
    except LookupError:
        raise BadRequestError(
            f"No such name for 'metrics': '{sub.metric}'") from None
    store = tsdb.histogram_store
    sids = store.series_ids_for_metric(metric_id)
    if len(sids) == 0:
        return []
    # filters reuse the scalar evaluator over the histogram store's index
    from opentsdb_tpu.query.filters import FilterEvaluator
    if sub.filters:
        idx = store.metric_index(metric_id)
        _, triples = idx.arrays()
        mask = FilterEvaluator(uids).apply(sub.filters, sids, triples)
        sids = sids[mask]
        if len(sids) == 0:
            return []
    series_tags = [dict(store.series(int(s)).tags) for s in sids]

    gb_kids = sorted({uids.tag_names.get_id(f.tagk)
                      for f in sub.filters if f.group_by
                      and uids.tag_names.has_name(f.tagk)})
    from opentsdb_tpu.query.engine import QueryEngine
    group_ids, group_keys = QueryEngine._group_ids(series_tags, gb_kids)

    # collect the window's histogram points as one flat [N, NB] batch
    point_counts: list[np.ndarray] = []
    point_group: list[int] = []
    point_ts: list[int] = []
    bounds: tuple | None = None
    uniform = True
    for i in range(len(sids)):
        for ts_ms, hist in tsdb._histogram_series.get(int(sids[i]), []):
            if not (tsq.start_ms <= ts_ms <= tsq.end_ms):
                continue
            b = tuple(hist.bounds)
            if bounds is None:
                bounds = b
            elif b != bounds:
                uniform = False
            point_counts.append(hist.counts_array())
            point_group.append(int(group_ids[i]))
            point_ts.append(ts_ms)
    if not point_counts or bounds is None:
        return []
    if not uniform:
        return _run_mixed_bounds(tsdb, tsq, sub, sids, series_tags,
                                 group_ids, group_keys)

    # device path (uniform bounds): merge = one-hot MXU contraction,
    # percentiles = cumsum + rank compare — ops.histogram_kernels
    from opentsdb_tpu.ops.histogram_kernels import \
        histogram_percentile_pipeline
    ts_sorted, ts_idx = np.unique(np.asarray(point_ts, dtype=np.int64),
                                  return_inverse=True)
    num_ts = len(ts_sorted)
    num_groups = len(group_keys)
    gvec = np.asarray(point_group, dtype=np.int64)
    seg = (gvec * num_ts + ts_idx).astype(np.int32)
    counts = np.stack(point_counts)
    pcts = histogram_percentile_pipeline(
        counts, seg, num_groups * num_ts, np.asarray(bounds),
        sub.percentiles)                       # [Q, G*T]
    pcts = pcts.reshape(len(sub.percentiles), num_groups, num_ts)
    present = np.bincount(seg, minlength=num_groups * num_ts) \
        .reshape(num_groups, num_ts) > 0

    out = []
    for gid in range(num_groups):
        members = [i for i in range(len(sids)) if group_ids[i] == gid]
        if not members or not present[gid].any():
            continue
        tags, agg_tags = _common_tags(
            [series_tags[m] for m in members], uids)
        for qi, q in enumerate(sub.percentiles):
            dps = [((int(t) // 1000) * 1000 if not tsq.ms_resolution
                    else int(t), float(pcts[qi, gid, ti]))
                   for ti, t in enumerate(ts_sorted)
                   if present[gid, ti]]
            out.append(QueryResult(
                metric=f"{sub.metric}_pct_{q:g}", tags=tags,
                aggregated_tags=agg_tags, dps=dps,
                sub_query_index=sub.index))
    return out


def _run_mixed_bounds(tsdb, tsq, sub, sids, series_tags, group_ids,
                      group_keys) -> list:
    """Host fallback when histograms in the window disagree on bucket
    bounds: per-group dict merge like the reference's iterator chain."""
    from opentsdb_tpu.query.engine import QueryResult, _common_tags
    uids = tsdb.uids
    out = []
    for gid in range(len(group_keys)):
        members = [i for i in range(len(sids)) if group_ids[i] == gid]
        if not members:
            continue
        # merge per timestamp, each timestamp keeping its own bucket
        # bounds (the reference merges Histogram objects per emitted
        # timestamp; bounds only need to agree across series AT one ts)
        merged: dict[int, tuple[tuple, np.ndarray]] = {}
        for i in members:
            for ts_ms, hist in tsdb._histogram_series.get(int(sids[i]), []):
                if not (tsq.start_ms <= ts_ms <= tsq.end_ms):
                    continue
                arr = hist.counts_array()
                b = tuple(hist.bounds)
                if ts_ms in merged:
                    b0, acc = merged[ts_ms]
                    if b0 != b:
                        raise BadRequestError(
                            "cannot merge histograms with different "
                            f"buckets at timestamp {ts_ms}")
                    merged[ts_ms] = (b0, acc + arr)
                else:
                    merged[ts_ms] = (b, arr)
        if not merged:
            continue
        ts_sorted = sorted(merged)
        pcts = np.stack([
            percentiles_from_counts(
                merged[t][1][None, :],
                np.asarray(merged[t][0], dtype=np.float64),
                sub.percentiles)[:, 0]
            for t in ts_sorted], axis=1)       # [Q, T]
        tags, agg_tags = _common_tags(
            [series_tags[m] for m in members], uids)
        for qi, q in enumerate(sub.percentiles):
            dps = [((t // 1000) * 1000 if not tsq.ms_resolution else t,
                    float(pcts[qi, ti]))
                   for ti, t in enumerate(ts_sorted)]
            out.append(QueryResult(
                metric=f"{sub.metric}_pct_{q:g}", tags=tags,
                aggregated_tags=agg_tags, dps=dps,
                sub_query_index=sub.index))
    return out
