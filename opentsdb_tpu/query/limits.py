"""Per-metric query guardrails
(ref: ``src/query/QueryLimitOverride.java:52``).

Default byte / datapoint caps come from config
(``tsd.query.limits.bytes.default`` / ``.data_points.default``, 0 =
disabled); per-metric overrides are regex-matched items loaded from a
JSON file (``tsd.query.limits.overrides.config``) that is re-read when
its mtime changes, checked at most every
``tsd.query.limits.overrides.interval`` seconds (the reference reloads
on a HashedWheelTimer; polling the mtime on access is the asyncio-free
equivalent).

Enforcement happens in the query engine right after the scan phase
counts points (the analogue of SaltScanner's per-scanner byte/dp
accounting, ``SaltScanner.java:660``): bytes are estimated at 16 per
point (8B timestamp + 8B value column), since storage here is a native
column arena, not HBase cells.

Override file format (same fields as QueryLimitOverrideItem)::

    [{"regex": "^sys\\..*", "byteLimit": 0, "dataPointsLimit": 1000}]
"""

from __future__ import annotations

import json
import os
import re
import time

BYTES_PER_DP = 16


class QueryLimitExceeded(RuntimeError):
    """(ref: the IllegalStateException raised by SaltScanner when a
    query blows its byte/dp budget)"""


class QueryLimitOverride:
    """(ref: QueryLimitOverride.java:90)"""

    def __init__(self, config):
        self.default_byte_limit = config.get_int(
            "tsd.query.limits.bytes.default", 0)
        self.default_data_points_limit = config.get_int(
            "tsd.query.limits.data_points.default", 0)
        if self.default_byte_limit < 0:
            raise ValueError("The default byte limit cannot be negative")
        if self.default_data_points_limit < 0:
            raise ValueError(
                "The default data points limit cannot be negative")
        self.file_location = config.get_string(
            "tsd.query.limits.overrides.config", "")
        self.reload_interval = config.get_int(
            "tsd.query.limits.overrides.interval", 0)
        self._overrides: list[tuple[re.Pattern, int, int]] = []
        self._loaded_mtime = 0.0
        self._next_check = 0.0
        if self.file_location:
            self._load()

    # -- file loading ---------------------------------------------------

    def _load(self) -> None:
        try:
            mtime = os.path.getmtime(self.file_location)
        except OSError:
            return
        if mtime == self._loaded_mtime:
            return
        try:
            with open(self.file_location, encoding="utf-8") as fh:
                items = json.load(fh)
        except (OSError, ValueError):
            # keep serving the previous overrides (ref: loadFromFile
            # logs and returns on parse errors)
            return
        overrides = []
        for item in items:
            regex = item.get("regex", "")
            if not regex:
                continue
            overrides.append((re.compile(regex),
                              int(item.get("byteLimit", 0)),
                              int(item.get("dataPointsLimit", 0))))
        self._overrides = overrides
        self._loaded_mtime = mtime

    def _maybe_reload(self) -> None:
        if not self.file_location or self.reload_interval <= 0:
            return
        now = time.monotonic()
        if now >= self._next_check:
            self._next_check = now + self.reload_interval
            self._load()

    # -- lookups (ref: getByteLimit :137 / getDataPointLimit :158) ------

    def get_byte_limit(self, metric: str) -> int:
        self._maybe_reload()
        if metric:
            for pattern, byte_limit, _ in self._overrides:
                if pattern.search(metric):
                    return byte_limit
        return self.default_byte_limit

    def get_data_point_limit(self, metric: str) -> int:
        self._maybe_reload()
        if metric:
            for pattern, _, dp_limit in self._overrides:
                if pattern.search(metric):
                    return dp_limit
        return self.default_data_points_limit

    # -- enforcement ----------------------------------------------------

    def check(self, metric: str, num_points: int) -> None:
        """Raise QueryLimitExceeded when the scan result for ``metric``
        exceeds its datapoint or (estimated) byte budget."""
        dp_limit = self.get_data_point_limit(metric)
        if dp_limit > 0 and num_points > dp_limit:
            raise QueryLimitExceeded(
                f"Sorry, you have attempted to fetch more than our "
                f"limit of {dp_limit} data points for metric "
                f"{metric!r} (got {num_points}). Please try "
                f"filtering using more tags or decrease your time "
                f"range.")
        byte_limit = self.get_byte_limit(metric)
        est = num_points * BYTES_PER_DP
        if byte_limit > 0 and est > byte_limit:
            raise QueryLimitExceeded(
                f"Sorry, you have attempted to fetch more than our "
                f"limit of {byte_limit} bytes for metric {metric!r} "
                f"(estimated {est}). Please try filtering using more "
                f"tags or decrease your time range.")
