"""Query model: the public JSON/URI query surface
(ref: ``src/core/TSQuery.java:44``, ``TSSubQuery.java:48``).

Validation semantics follow ``TSQuery.validateAndSetQuery``: start time
required, aggregator required per sub-query, one of metric|tsuids
required, times normalized to ms, end defaulting to now.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from opentsdb_tpu.ops import aggregators as aggs_mod
from opentsdb_tpu.ops.downsample import DownsamplingSpecification
from opentsdb_tpu.ops.rate import RateOptions
from opentsdb_tpu.query import filters as filters_mod
from opentsdb_tpu.utils import datetime_util


class BadRequestError(ValueError):
    """400-level query errors (ref: src/tsd/BadRequestException.java)."""


def _validate_pixels(raw, where: str) -> int:
    """Strict pixel-budget validation: a positive integer up to
    MAX_PIXELS, 400 on anything else (no reference equivalent — the
    pixel-aware serve-path operator is new surface, so nonsense must
    not silently pass through as 'no reduction')."""
    from opentsdb_tpu.ops.visual_downsample import MAX_PIXELS
    if raw is None or raw == 0:
        return 0
    if isinstance(raw, bool) or isinstance(raw, float) or \
            not isinstance(raw, (int, str)):
        raise BadRequestError(f"Invalid {where}: {raw!r} "
                              "(want a positive integer pixel count)")
    if isinstance(raw, str):
        # same strict digit rule as put-value parsing (PR 6): int()
        # silently accepts underscores and unicode digits; leading
        # zeros ("0800") are rejected as probable typos, not parsed
        if not (raw.isascii() and raw.isdigit()) or \
                (len(raw) > 1 and raw[0] == "0"):
            raise BadRequestError(
                f"Invalid {where}: {raw!r} "
                "(want a positive integer pixel count)")
    px = int(raw)
    if px == 0:
        return 0  # an explicit 0 turns the reduction off
    if px < 0 or px > MAX_PIXELS:
        raise BadRequestError(
            f"Invalid {where}: {raw!r} (want 0..{MAX_PIXELS})")
    return px


def _validate_pixel_fn(raw, where: str) -> str:
    from opentsdb_tpu.ops.visual_downsample import PIXEL_FNS
    if not raw:
        return ""
    fn = str(raw).lower()
    if fn not in PIXEL_FNS:
        raise BadRequestError(
            f"Invalid {where}: {raw!r} "
            f"(supported: {', '.join(PIXEL_FNS)})")
    return fn


def effective_pixels(tsq, sub) -> tuple[int, str]:
    """The pixel budget one sub-query's output is reduced under: the
    per-sub option wins over the query-level one; the operator
    defaults to M4 (error-free for line rendering). (0, ...) = off."""
    from opentsdb_tpu.ops.visual_downsample import DEFAULT_PIXEL_FN
    px = sub.pixels or tsq.pixels
    fn = sub.pixel_fn or tsq.pixel_fn or DEFAULT_PIXEL_FN
    return (px, fn) if px else (0, fn)


@dataclass
class TSSubQuery:
    """(ref: TSSubQuery.java:48-104)"""
    aggregator: str = ""
    metric: str | None = None
    tsuids: list[str] = field(default_factory=list)
    downsample: str | None = None
    rate: bool = False
    rate_options: RateOptions = field(default_factory=RateOptions)
    filters: list[filters_mod.TagVFilter] = field(default_factory=list)
    explicit_tags: bool = False
    percentiles: list[float] = field(default_factory=list)
    rollup_usage: str = "ROLLUP_NOFALLBACK"
    index: int = 0
    # pixel-aware output reduction (ops/visual_downsample): 0 = off /
    # inherit the query-level budget; fn "" = inherit / default (m4)
    pixels: int = 0
    pixel_fn: str = ""
    # populated during validation
    agg: aggs_mod.Aggregator | None = None
    ds_spec: DownsamplingSpecification | None = None

    def validate(self, timezone: str | None = None,
                 use_calendar: bool = False) -> None:
        if not self.aggregator:
            raise BadRequestError(
                "Missing the aggregation function")
        self.pixels = _validate_pixels(self.pixels, "pixels")
        self.pixel_fn = _validate_pixel_fn(self.pixel_fn, "pixelFn")
        try:
            self.agg = aggs_mod.get(self.aggregator)
        except KeyError as e:
            raise BadRequestError(e.args[0]) from None
        if not self.metric and not self.tsuids:
            raise BadRequestError(
                "Missing the metric or tsuids, provide at least one")
        if self.downsample:
            try:
                self.ds_spec = DownsamplingSpecification.parse(
                    self.downsample, timezone)
            except ValueError as e:
                raise BadRequestError(str(e)) from None
            if use_calendar and not self.ds_spec.run_all:
                # the query-level useCalendar flag aligns every
                # downsample to calendar boundaries, like the 'c'
                # interval suffix (ref: TSQuery useCalendar ->
                # DownsamplingSpecification.useCalendar)
                import dataclasses
                self.ds_spec = dataclasses.replace(
                    self.ds_spec, use_calendar=True)

    def identity_key(self) -> tuple:
        """Value identity excluding ``index`` (ref: TSSubQuery
        equals/hashCode, used by parseQuery's duplicate filter)."""
        return (self.aggregator, self.metric, tuple(self.tsuids),
                self.downsample, self.rate,
                (self.rate_options.counter,
                 self.rate_options.counter_max,
                 self.rate_options.reset_value,
                 self.rate_options.drop_resets),
                tuple(repr(f.to_json()) for f in self.filters),
                self.explicit_tags, tuple(self.percentiles),
                self.rollup_usage)

    @classmethod
    def from_json(cls, obj: dict[str, Any], index: int = 0) -> "TSSubQuery":
        filters = [filters_mod.build_filter(f)
                   for f in obj.get("filters", [])]
        if obj.get("tags"):
            filters.extend(filters_mod.tags_to_filters(obj["tags"]))
        rate_opts = RateOptions()
        if obj.get("rateOptions"):
            ro = obj["rateOptions"]
            rate_opts = RateOptions(
                counter=bool(ro.get("counter", False)),
                counter_max=float(ro.get("counterMax", 2**64 - 1)),
                reset_value=float(ro.get("resetValue", 0)),
                drop_resets=bool(ro.get("dropResets", False)))
        return cls(
            aggregator=obj.get("aggregator", ""),
            metric=obj.get("metric"),
            tsuids=list(obj.get("tsuids") or []),
            downsample=obj.get("downsample"),
            rate=bool(obj.get("rate", False)),
            rate_options=rate_opts,
            filters=filters,
            explicit_tags=bool(obj.get("explicitTags", False)),
            percentiles=[float(p) for p in obj.get("percentiles") or []],
            rollup_usage=obj.get("rollupUsage", "ROLLUP_NOFALLBACK"),
            pixels=obj.get("pixels") or 0,
            pixel_fn=obj.get("pixelFn") or "",
            index=index)

    def to_json(self) -> dict[str, Any]:
        return {
            "aggregator": self.aggregator,
            "metric": self.metric,
            "tsuids": self.tsuids or None,
            "downsample": self.downsample,
            "rate": self.rate,
            "rateOptions": (self.rate_options.to_json()
                            if self.rate else None),
            "filters": [f.to_json() for f in self.filters],
            "explicitTags": self.explicit_tags,
            "index": self.index,
            **({"rollupUsage": self.rollup_usage}
               if self.rollup_usage != "ROLLUP_NOFALLBACK" else {}),
            **({"percentiles": list(self.percentiles)}
               if self.percentiles else {}),
            **({"pixels": self.pixels} if self.pixels else {}),
            **({"pixelFn": self.pixel_fn} if self.pixel_fn else {}),
        }


@dataclass
class TSQuery:
    """(ref: TSQuery.java:44)"""
    start: str = ""
    end: str | None = None
    queries: list[TSSubQuery] = field(default_factory=list)
    timezone: str | None = None
    no_annotations: bool = False
    global_annotations: bool = False
    ms_resolution: bool = False
    show_tsuids: bool = False
    show_summary: bool = False
    show_stats: bool = False
    show_query: bool = False
    delete: bool = False
    use_calendar: bool = False
    # query-level pixel budget (``downsample=<N>px[-<fn>]`` URI param /
    # top-level ``pixels``/``pixelFn`` JSON keys); per-sub options win
    pixels: int = 0
    pixel_fn: str = ""
    # replicated-router scatter assignment (``replicaSel`` JSON key,
    # normalized by cluster/replica.parse_sel): the engine keeps only
    # series whose replica set this request was assigned, so RF > 1
    # reads never double-count. None on every client-facing query.
    replica_sel: dict | None = None
    # cluster-internal (``sketchPartials`` JSON key): a router asking
    # a shard for mergeable quantile-sketch partials instead of
    # locally-extracted percentile values. Never set client-side.
    sketch_partials: bool = False
    # populated during validation
    start_ms: int = 0
    end_ms: int = 0

    def validate(self, now_ms: int | None = None) -> "TSQuery":
        """(ref: TSQuery.validateAndSetQuery)"""
        if not self.start:
            raise BadRequestError("Missing start time")
        self.start_ms = datetime_util.parse_datetime_ms(
            self.start, self.timezone, now_ms)
        if self.end:
            self.end_ms = datetime_util.parse_datetime_ms(
                self.end, self.timezone, now_ms)
        else:
            import time as _t
            self.end_ms = (now_ms if now_ms is not None
                           else int(_t.time() * 1000))
        if self.end_ms <= self.start_ms:
            raise BadRequestError(
                "end time must be greater than the start time")
        if not self.queries:
            raise BadRequestError("Missing queries")
        self.pixels = _validate_pixels(self.pixels, "downsample pixels")
        self.pixel_fn = _validate_pixel_fn(self.pixel_fn, "pixelFn")
        for i, sub in enumerate(self.queries):
            sub.index = i
            sub.validate(self.timezone, self.use_calendar)
        return self

    def dedupe_queries(self) -> "TSQuery":
        """Collapse duplicate sub-queries, first occurrence wins.

        Applied by the /api/query URI handler ONLY (ref:
        QueryRpc.parseQuery :617 rebuilds through a LinkedHashSet;
        POST bodies keep duplicates — parseQueryV1 has no such filter
        — and /q must keep them so per-index ``o=`` options align)."""
        seen: set = set()
        deduped = []
        for sub in self.queries:
            # pixels ride along OUTSIDE identity_key (the streaming
            # registry matches registered plans on content identity —
            # the same maintained partials serve any pixel budget, the
            # reduction applies at result assembly) but two subs that
            # differ only in pixel budget are NOT duplicates here
            key = (sub.identity_key(), sub.pixels, sub.pixel_fn)
            if key in seen:
                continue
            seen.add(key)
            deduped.append(sub)
        self.queries = deduped
        return self

    @classmethod
    def from_json(cls, obj: dict[str, Any]) -> "TSQuery":
        if not isinstance(obj, dict):
            raise BadRequestError("query must be a JSON object")
        raw_queries = obj.get("queries") or []
        if not isinstance(raw_queries, list) or not all(
                isinstance(q, dict) for q in raw_queries):
            raise BadRequestError(
                "queries must be an array of sub-query objects")
        queries = [TSSubQuery.from_json(q, i)
                   for i, q in enumerate(raw_queries)]
        replica_sel = None
        if obj.get("replicaSel") is not None:
            # local import: cluster/replica imports this module
            from opentsdb_tpu.cluster.replica import parse_sel
            replica_sel = parse_sel(obj["replicaSel"])
        return cls(
            replica_sel=replica_sel,
            start=str(obj.get("start", "")),
            end=(str(obj["end"]) if obj.get("end") not in (None, "")
                 else None),
            queries=queries,
            timezone=obj.get("timezone"),
            no_annotations=bool(obj.get("noAnnotations", False)),
            global_annotations=bool(obj.get("globalAnnotations", False)),
            ms_resolution=bool(obj.get("msResolution")
                               or obj.get("ms", False)),
            show_tsuids=bool(obj.get("showTSUIDs", False)),
            show_summary=bool(obj.get("showSummary", False)),
            show_stats=bool(obj.get("showStats", False)),
            show_query=bool(obj.get("showQuery", False)),
            delete=bool(obj.get("delete", False)),
            use_calendar=bool(obj.get("useCalendar", False)),
            pixels=obj.get("pixels") or 0,
            pixel_fn=obj.get("pixelFn") or "",
            sketch_partials=bool(obj.get("sketchPartials", False)),
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "start": self.start, "end": self.end,
            "timezone": self.timezone,
            "queries": [q.to_json() for q in self.queries],
            "noAnnotations": self.no_annotations,
            "globalAnnotations": self.global_annotations,
            "msResolution": self.ms_resolution,
            "showTSUIDs": self.show_tsuids,
            **({"pixels": self.pixels} if self.pixels else {}),
            **({"pixelFn": self.pixel_fn} if self.pixel_fn else {}),
            **({"replicaSel": {
                "peers": list(self.replica_sel["peers"]),
                "vnodes": self.replica_sel["vnodes"],
                "rf": self.replica_sel["rf"],
                "sets": [list(t)
                         for t in self.replica_sel["sets"]],
                **({"invert": True}
                   if self.replica_sel.get("invert") else {})}}
               if self.replica_sel else {}),
        }


def parse_uri_subquery(spec: str, index: int = 0) -> TSSubQuery:
    """Parse the URI form ``agg:[interval-ds:][rate[{...}]:]metric{tags}[{filters}]``
    (ref: QueryRpc.parseMTypeSubQuery)."""
    parts = spec.split(":")
    if len(parts) < 2:
        raise BadRequestError(f"Invalid parameter m={spec!r}")
    aggregator = parts[0]
    metric_part = parts[-1]
    sub = TSSubQuery(aggregator=aggregator, index=index)
    for middle in parts[1:-1]:
        if middle.startswith("rate"):
            sub.rate = True
            sub.rate_options = RateOptions.parse(middle)
        elif middle == "explicit_tags":
            # (ref: QueryRpc.parseQueryMTypeWExplicit — the URI form
            # agg:explicit_tags:[ds:][rate:]metric)
            sub.explicit_tags = True
        elif middle.lower().startswith("percentile"):
            # percentile[98,99.9] histogram-query section (ref:
            # QueryRpc.parsePercentiles :887-903, tolerant of spaces)
            import re as _re2
            pm = _re2.match(r"^percentiles?\s*\[\s*([^\]]*?)\s*\]$",
                            middle, _re2.IGNORECASE)
            if not pm:
                raise BadRequestError(
                    f"Malformatted percentile query parameter: "
                    f"{middle!r}")
            try:
                sub.percentiles = [float(p)
                                   for p in pm.group(1).split(",") if
                                   p.strip()]
            except ValueError:
                raise BadRequestError(
                    f"Malformatted percentile query parameter: "
                    f"{middle!r}") from None
            if not sub.percentiles:
                # 'percentile[]' must not silently degrade to a
                # non-histogram query (ref: parsePercentiles rejects)
                raise BadRequestError(
                    f"Malformatted percentile query parameter: "
                    f"{middle!r}")
        elif middle == "":
            continue
        else:
            sub.downsample = middle
    # metric{groupby-tags}{filter-tags}
    import re as _re
    m = _re.match(r"^([^{]+)(\{[^}]*\})?(\{[^}]*\})?$", metric_part)
    if not m:
        raise BadRequestError(f"Invalid metric: {metric_part!r}")
    sub.metric = m.group(1)

    def _parse_tagset(blob: str | None, group_by: bool):
        if not blob:
            return
        body = blob[1:-1].strip()
        if not body:
            return
        for pair in body.split(","):
            k, _, v = pair.partition("=")
            if not k or not v:
                raise BadRequestError(f"Invalid tag spec: {pair!r}")
            if group_by:
                sub.filters.append(
                    filters_mod.get_filter(k.strip(), v.strip(),
                                           group_by=True))
            else:
                f = filters_mod.get_filter(k.strip(), v.strip())
                f.group_by = False
                sub.filters.append(f)

    # first {...} groups by, second {...} filters only (2.2+ semantics)
    if m.group(2) and m.group(3):
        _parse_tagset(m.group(2), True)
        _parse_tagset(m.group(3), False)
    elif m.group(2):
        # single tagset: old-style conversion decides group-by per value
        body = m.group(2)[1:-1].strip()
        if body:
            tag_map = {}
            for pair in body.split(","):
                k, _, v = pair.partition("=")
                if not k or not v:
                    raise BadRequestError(f"Invalid tag spec: {pair!r}")
                tag_map[k.strip()] = v.strip()
            sub.filters.extend(filters_mod.tags_to_filters(tag_map))
    return sub


def parse_uri_tsuid_subquery(spec: str, index: int = 0) -> TSSubQuery:
    """Parse the URI form ``agg:[interval-ds:][rate:]tsuid1,tsuid2``
    (ref: QueryRpc.parseTsuidTypeSubQuery)."""
    parts = spec.split(":")
    if len(parts) < 2 or len(parts) > 5:
        raise BadRequestError(f"Invalid parameter tsuids={spec!r}")
    sub = TSSubQuery(aggregator=parts[0], index=index)
    for middle in parts[1:-1]:
        if middle.startswith("rate"):
            sub.rate = True
            sub.rate_options = RateOptions.parse(middle)
        elif middle:
            sub.downsample = middle
    sub.tsuids = [t.strip().upper() for t in parts[-1].split(",")
                  if t.strip()]
    if not sub.tsuids:
        raise BadRequestError(f"Invalid parameter tsuids={spec!r}")
    return sub


def parse_uri_pixels(spec: str) -> tuple[int, str]:
    """Parse the ``downsample=<N>px[-<fn>]`` URI form (e.g.
    ``1500px``, ``800px-minmaxlttb``); strict — anything that is not a
    pixel spec is a 400, not a silent no-op."""
    import re as _re
    m = _re.match(r"^(\d+)px(?:-([a-z0-9]+))?$", spec.strip().lower())
    if not m:
        raise BadRequestError(
            f"Invalid downsample parameter: {spec!r} "
            "(want <pixels>px or <pixels>px-<m4|minmaxlttb>)")
    px = _validate_pixels(m.group(1), "downsample pixels")
    fn = _validate_pixel_fn(m.group(2), "downsample pixel fn")
    return px, fn


def parse_uri_query(params: dict[str, list[str]]) -> TSQuery:
    """Parse ``/api/query?start=...&m=...`` URI params
    (ref: QueryRpc.parseQuery)."""
    def first(key, default=None):
        vals = params.get(key)
        return vals[0] if vals else default

    # tsuid sub-queries come FIRST, like the reference's parseQuery,
    # so mixed tsuids+m requests keep the same output indices
    queries = [parse_uri_tsuid_subquery(spec, i)
               for i, spec in enumerate(params.get("tsuids", []))]
    queries += [parse_uri_subquery(spec, len(queries) + i)
                for i, spec in enumerate(params.get("m", []))]
    pixels, pixel_fn = (parse_uri_pixels(first("downsample"))
                        if first("downsample") is not None else (0, ""))
    return TSQuery(
        start=first("start", ""),
        end=first("end"),
        queries=queries,
        timezone=first("tz"),
        use_calendar=first("use_calendar",
                           first("useCalendar", "false"))
        in ("true", ""),
        no_annotations=first("no_annotations", "false") == "true",
        global_annotations=first("global_annotations", "false") == "true",
        ms_resolution=first("ms", first("ms_resolution", "false"))
        in ("true", ""),
        show_tsuids=first("show_tsuids", "false") == "true",
        show_summary=first("show_summary", "false") == "true",
        show_query=first("show_query", "false") == "true",
        pixels=pixels,
        pixel_fn=pixel_fn,
    )
