"""Serve-path query result cache with single-flight coalescing.

The reference caches rendered graphs on disk keyed by the query hash
and serves them until they go stale (``GraphHandler.java`` —
``isDiskCacheHit`` + the end-time-relative ``computeMaxAge`` rule).
Here the cached unit is the engine's *result groups* (the
``list[QueryResult]`` one sub-query produces), so every repeated
dashboard refresh skips the whole scan -> device pipeline -> assembly
chain and pays only serialization.

Correctness model (never serve stale data):

- Entries are keyed by a canonical tuple of the normalized
  TSQuery/sub-query (window, timezone/calendar flags, output flags,
  and :meth:`TSSubQuery.identity_key`) — see :func:`cache_plan`.
- Every lookup carries the owning TSDB's *serve version*: a tuple of
  ``(points_written, mutation_epoch)`` counters over every store a
  query can read (raw + every rollup tier + preagg + histogram
  arenas + annotations). A version mismatch is a miss and evicts the
  entry, so ANY write/delete/rollup/preagg write invalidates
  implicitly — the ``mutation_epoch`` the store grew "for read-side
  caches" (core/store.py) finally has its consumer.
- Relative-time queries (``end=now`` and friends) can never match
  exactly — their resolved window moves every request — so they are
  keyed on the raw time strings plus a TTL-quantized window bucket,
  and a hit is additionally bounded by a staleness TTL derived from
  the downsample interval (the reference's GraphHandler staleness
  rule: a 5m-downsampled dashboard may be served up to 5m stale).

Single-flight: concurrent identical queries (same key) block on ONE
execution — the leader computes and populates, waiters share the
result object, and a failed leader propagates its error to every
waiter WITHOUT populating the cache (an error is never cached).

Sharded LRU bounded by an estimated byte budget
(``tsd.query.cache.mb``); knobs live under ``tsd.query.cache.*``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable

# lookup outcomes (also recorded as per-query stat points)
HIT = "hit"
MISS = "miss"
COALESCED = "coalesced"

_MISSING = object()


def _is_relative(spec: str | None) -> bool:
    """True when a start/end time string re-resolves against *now*
    (ref: DateTime.parseDateTimeString relative forms)."""
    if spec is None or spec == "":
        return True  # an absent end defaults to now
    s = str(spec).strip().lower()
    return s.endswith("-ago") or s.startswith("now")


def cache_plan(tsq, sub, config) -> tuple[tuple, float] | None:
    """(key, ttl_ms) for one sub-query, or None when it must bypass
    the cache. ``ttl_ms`` is 0 for absolute windows (version
    invalidation only).

    The key folds in every TSQuery field that shapes a sub-query's
    result groups (window, tz/calendar, ms rounding, tsuids flag,
    annotation flags) plus the sub-query's value identity — but NOT
    ``sub.index``, so the same sub shared by different dashboards
    still hits (the engine re-labels ``sub_query_index`` on hit)."""
    if tsq.delete:
        return None  # scanned-and-deleted: running IS the side effect
    relative = _is_relative(tsq.start) or _is_relative(tsq.end)
    ttl_ms = 0.0
    if relative:
        spec = sub.ds_spec
        if spec is not None and not spec.run_all \
                and spec.interval_ms > 0:
            ttl_max = config.get_float("tsd.query.cache.ttl_max_s",
                                       300.0)
            ttl_ms = min(float(spec.interval_ms), ttl_max * 1000.0)
        else:
            ttl_ms = config.get_float(
                "tsd.query.cache.ttl_relative_s", 0.0) * 1000.0
        if ttl_ms <= 0:
            return None
        # TTL-quantized window bucket: requests inside one bucket
        # share an entry (staleness <= ttl by construction); far-apart
        # "1h-ago" queries can never collide on the raw strings alone
        window = ("rel", tsq.start, tsq.end,
                  int(tsq.start_ms // ttl_ms),
                  int(tsq.end_ms // ttl_ms))
    else:
        window = (tsq.start_ms, tsq.end_ms)
    # the pixel budget shapes the cached result groups (the keep-mask
    # intersection happens before assembly), so it is part of the key:
    # cached and fresh answers for the same budget agree, and a
    # full-resolution entry can never serve a pixel-budgeted request
    from opentsdb_tpu.cluster.replica import sel_cache_key
    from opentsdb_tpu.query.model import effective_pixels
    # the replica assignment shapes the result (which series this
    # request reads): two scatters over different assignments of the
    # same query must never share a shard-side entry
    # sketch_partials flips percentile subs between extracted
    # quantile rows and serialized sketch partials: a shard serving
    # both router scatters and direct clients must never cross them
    key = (window, tsq.timezone, tsq.use_calendar, tsq.ms_resolution,
           tsq.show_tsuids, tsq.no_annotations, tsq.global_annotations,
           tsq.sketch_partials,
           sub.identity_key(), effective_pixels(tsq, sub),
           sel_cache_key(tsq.replica_sel))
    return key, ttl_ms


def detach(value):
    """Per-result ``cache_copy`` snapshots (see
    ``QueryResult.cache_copy``): applied on PUT so the entry never
    pins a consumer's lazily-materialized point list, and on HIT so a
    consumer can only ever fatten its own request-scoped copies —
    either way the entry's real footprint stays what
    :func:`results_nbytes` charged. Objects without the hook pass
    through unchanged."""
    return [r.cache_copy() if hasattr(r, "cache_copy") else r
            for r in value]


def results_nbytes(results) -> int:
    """Estimated host bytes held by one cached value (a
    ``list[QueryResult]``): array payloads + per-group overhead."""
    total = 512
    for r in results:
        total += 256
        arrays = getattr(r, "dps_arrays", None)
        if arrays is not None:
            total += sum(getattr(a, "nbytes", 0) for a in arrays)
        else:
            dps = getattr(r, "_dps", None)
            if dps:
                total += 48 * len(dps)
        total += 64 * (len(getattr(r, "tsuids", ()) or ())
                       + len(getattr(r, "annotations", ()) or ()))
    return total


class _Flight:
    """One in-flight computation shared by leader + waiters.
    ``version`` is the LEADER's serve version: a waiter that captured
    a newer one must not share the result (read-after-write)."""

    __slots__ = ("event", "value", "error", "version")

    def __init__(self, version) -> None:
        self.event = threading.Event()
        self.value = None
        self.error: BaseException | None = None
        self.version = version


class _Shard:
    __slots__ = ("lock", "entries", "nbytes", "hits")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        # key -> (version, value, nbytes, created_monotonic)
        self.entries: OrderedDict[Any, tuple] = OrderedDict()
        self.nbytes = 0
        # hit counting lives here, under the lock already held on the
        # hot path — a process-global stats mutex would re-serialize
        # exactly the lookups the sharding parallelizes
        self.hits = 0


class QueryResultCache:
    """Sharded, byte-bounded, epoch-invalidated LRU of query results
    with single-flight coalescing (see module docstring)."""

    def __init__(self, max_bytes: int, shards: int = 8,
                 stat_prefix: str = "query.resultcache",
                 clock: Callable[[], float] = time.monotonic):
        self.max_bytes = max(int(max_bytes), 1)
        self.stat_prefix = stat_prefix
        self._clock = clock
        n = max(int(shards), 1)
        self._shards = [_Shard() for _ in range(n)]
        self._shard_budget = max(self.max_bytes // n, 1)
        self._flight_lock = threading.Lock()
        self._inflight: dict[Any, _Flight] = {}
        # slow-path counters (misses run a compute, the rest are
        # rare); the hot-path hit counter is per-shard
        self._stats_lock = threading.Lock()
        self.misses = 0
        self.coalesced = 0
        self.evicted = 0
        self.bypasses = 0
        self.gated = 0
        # optional per-tenant insert gate (control-plane QoS): called
        # with the entry's byte size before insertion; False = serve
        # the result but don't retain it. Attached by the governor —
        # None keeps the hot path at one attribute read.
        self.insert_gate: Callable[[int], bool] | None = None

    # ------------------------------------------------------------------

    def _shard(self, key) -> _Shard:
        return self._shards[hash(key) % len(self._shards)]

    def _count(self, field: str, n: int = 1) -> None:
        with self._stats_lock:
            setattr(self, field, getattr(self, field) + n)

    def count_bypass(self) -> None:
        """An uncacheable query went straight to the engine."""
        self._count("bypasses")

    @property
    def hits(self) -> int:
        return sum(s.hits for s in self._shards)

    @property
    def total_bytes(self) -> int:
        return sum(s.nbytes for s in self._shards)

    @property
    def total_entries(self) -> int:
        return sum(len(s.entries) for s in self._shards)

    # ------------------------------------------------------------------

    def _get(self, key, version, ttl_ms: float):
        shard = self._shard(key)
        with shard.lock:
            entry = shard.entries.get(key)
            if entry is not None:
                ver_mismatch = entry[0] != version
                ttl_stale = ttl_ms > 0 and \
                    (self._clock() - entry[3]) * 1000.0 > ttl_ms
                if not ver_mismatch and not ttl_stale:
                    shard.entries.move_to_end(key)
                    shard.hits += 1
                    return entry[1]
                # aged out, or a write landed: drop it so the byte
                # accounting never carries dead weight — EXCEPT when
                # the resident entry is strictly NEWER than this
                # caller's captured version (a reader that captured
                # its version just before a write must not destroy
                # the entry the post-write reader populated; serve
                # versions are monotonic, so newer wins)
                evict = ttl_stale
                if ver_mismatch and not evict:
                    try:
                        evict = not entry[0] > version
                    except TypeError:
                        evict = True  # incomparable shapes: replace
                if evict:
                    shard.nbytes -= entry[2]
                    del shard.entries[key]
        return _MISSING

    def _put(self, key, version, value) -> None:
        nbytes = results_nbytes(value)
        if nbytes > self._shard_budget:
            return  # bigger than a whole shard: don't thrash
        gate = self.insert_gate
        if gate is not None:
            try:
                admitted = gate(nbytes)
            except Exception:  # tsdlint: allow[swallow] a broken tenant gate must degrade to plain caching, never fail the query that computed the value
                admitted = True
            if not admitted:
                self._count("gated")
                return  # over-budget tenant: serve, don't retain
        shard = self._shard(key)
        evicted = 0
        with shard.lock:
            old = shard.entries.get(key)
            if old is not None:
                try:
                    if old[0] > version:
                        # the resident entry was computed under a
                        # NEWER version: this put would be dead on
                        # arrival (no future reader can match it)
                        return
                except TypeError:
                    pass
                del shard.entries[key]
                shard.nbytes -= old[2]
            shard.entries[key] = (version, value, nbytes, self._clock())
            shard.nbytes += nbytes
            while shard.nbytes > self._shard_budget and shard.entries:
                _, (_, _, nb, _) = shard.entries.popitem(last=False)
                shard.nbytes -= nb
                evicted += 1
        if evicted:
            self._count("evicted", evicted)

    # ------------------------------------------------------------------

    def get_or_compute(self, key, version, compute: Callable[[], Any],
                       ttl_ms: float = 0.0) -> tuple[Any, str]:
        """Return ``(value, outcome)`` where outcome is one of
        :data:`HIT` / :data:`MISS` / :data:`COALESCED`.

        Exactly one caller per key runs ``compute`` at a time; its
        result populates the cache under ``version`` (captured by the
        caller BEFORE compute, so a write landing mid-compute leaves
        the entry already-stale rather than wrongly fresh). A leader
        that raises propagates the error to itself and every waiter
        and caches nothing."""
        value = self._get(key, version, ttl_ms)
        if value is not _MISSING:
            return detach(value), HIT
        with self._flight_lock:
            # the leader may have completed between the miss above and
            # this lock: re-check before joining/starting a flight
            value = self._get(key, version, ttl_ms)
            if value is not _MISSING:
                return detach(value), HIT
            flight = self._inflight.get(key)
            leader = flight is None
            if leader:
                flight = self._inflight[key] = _Flight(version)
        if not leader:
            flight.event.wait()
            if flight.version != version:
                # the leader started BEFORE a write this caller must
                # observe (its version is older): sharing its result
                # would break read-after-write. The flight is complete
                # (popped before the event is set), so re-entering
                # either hits a fresh entry or leads a new flight.
                return self.get_or_compute(key, version, compute,
                                           ttl_ms)
            # hits + misses + coalesced + bypasses partition lookups:
            # a waiter is coalesced, success or not
            self._count("coalesced")
            if flight.error is not None:
                raise flight.error
            return flight.value, COALESCED
        self._count("misses")
        try:
            value = compute()
        except BaseException as exc:
            flight.error = exc
            raise
        else:
            flight.value = value
            try:
                self._put(key, version, detach(value))
            except Exception:  # noqa: BLE001 - put is best-effort
                # tsdlint: allow[swallow] cache bookkeeping must never
                # fail the query; the waiters still share flight.value
                pass
            return value, MISS
        finally:
            # ALWAYS complete the flight — a dead entry in _inflight
            # would hang every future query for this key forever
            with self._flight_lock:
                self._inflight.pop(key, None)
            flight.event.set()

    # ------------------------------------------------------------------
    # explicit probe/populate pair — the cluster router's seam
    # ------------------------------------------------------------------

    def lookup(self, key, version, ttl_ms: float = 0.0):
        """Plain probe without single-flight: a detached copy of the
        entry, or ``None``. The cluster router gathers results from
        the NETWORK, where an answer can come back *degraded* (a shard
        was dead/hung/tripped) — :meth:`get_or_compute` caches every
        successful compute unconditionally, which cannot express "this
        succeeded but must not be retained". The router probes here
        and populates via :meth:`store` only for complete answers, so
        a ``shardsDegraded`` partial never outlives the outage it
        reports and the next complete answer repopulates the entry."""
        value = self._get(key, version, ttl_ms)
        if value is _MISSING:
            self._count("misses")
            return None
        return detach(value)

    def store(self, key, version, value) -> None:
        """Populate for :meth:`lookup` users (detached exactly like
        the :meth:`get_or_compute` put; best-effort — bookkeeping
        trouble must never fail the query that computed ``value``)."""
        try:
            self._put(key, version, detach(value))
        except Exception:  # noqa: BLE001 - put is best-effort
            # tsdlint: allow[swallow] populate must never fail the
            # query that computed the value (same rule as the
            # single-flight put above)
            pass

    # ------------------------------------------------------------------

    def clear(self) -> None:
        for shard in self._shards:
            with shard.lock:
                shard.entries.clear()
                shard.nbytes = 0

    def collect_stats(self, collector) -> None:
        collector.record(f"{self.stat_prefix}.bytes", self.total_bytes)
        collector.record(f"{self.stat_prefix}.entries",
                         self.total_entries)
        collector.record(f"{self.stat_prefix}.hits", self.hits)
        collector.record(f"{self.stat_prefix}.misses", self.misses)
        collector.record(f"{self.stat_prefix}.coalesced",
                         self.coalesced)
        collector.record(f"{self.stat_prefix}.evicted", self.evicted)
        collector.record(f"{self.stat_prefix}.bypasses", self.bypasses)
        collector.record(f"{self.stat_prefix}.gated", self.gated)

    def health_info(self) -> dict[str, Any]:
        return {
            "enabled": True,
            "bytes": self.total_bytes,
            "max_bytes": self.max_bytes,
            "entries": self.total_entries,
            "shards": len(self._shards),
            "hits": self.hits,
            "misses": self.misses,
            "coalesced": self.coalesced,
            "evicted": self.evicted,
            "bypasses": self.bypasses,
            "inflight": len(self._inflight),
        }
