"""Rollup tier configuration (ref: ``src/rollup/RollupConfig.java:60``,
``RollupInterval.java:32``).

A rollup tier = one downsampling interval materialized ahead of query
time (e.g. raw -> 1m -> 1h). The reference maps tiers to extra HBase
tables; here each tier is its own :class:`~opentsdb_tpu.core.store.TimeSeriesStore`
keyed additionally by aggregator (sum/count/min/max — the four the
reference writes, from which avg is derived at query time as sum/count).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from opentsdb_tpu.utils import datetime_util

# Aggregator <-> numeric id mapping used in rollup cell qualifiers
# (ref: RollupConfig.java aggregationIds :261-287).
DEFAULT_AGG_IDS = {"sum": 0, "count": 1, "min": 2, "max": 3}


@dataclass
class RollupInterval:
    """(ref: RollupInterval.java:32)"""
    table: str
    pre_aggregation_table: str
    interval: str          # e.g. "1m", "1h"
    row_span: str = "1d"   # "1h" | "1d" | "1m"(month) | "1y"
    default_interval: bool = False
    interval_ms: int = field(init=False)

    def __post_init__(self) -> None:
        self.interval_ms = datetime_util.parse_duration_ms(self.interval)

    @property
    def unit(self) -> str:
        return datetime_util.duration_unit(self.interval)


class RollupConfig:
    """(ref: RollupConfig.java:60)"""

    def __init__(self, intervals: list[RollupInterval],
                 agg_ids: dict[str, int] | None = None):
        if not intervals:
            raise ValueError("rollup config needs at least one interval")
        self.intervals = sorted(intervals, key=lambda iv: iv.interval_ms)
        self.agg_ids = dict(agg_ids or DEFAULT_AGG_IDS)
        self.id_to_agg = {v: k for k, v in self.agg_ids.items()}
        self._by_interval = {iv.interval: iv for iv in self.intervals}

    @classmethod
    def default(cls) -> "RollupConfig":
        return cls([
            RollupInterval("tsdb-rollup-1m", "tsdb-rollup-agg-1m", "1m", "1d"),
            RollupInterval("tsdb-rollup-1h", "tsdb-rollup-agg-1h", "1h", "1y",
                           default_interval=True),
        ])

    @classmethod
    def from_file(cls, path: str) -> "RollupConfig":
        with open(path, encoding="utf-8") as fh:
            return cls.from_json(json.load(fh))

    @classmethod
    def from_json(cls, obj) -> "RollupConfig":
        """Accepts the reference's JSON shape: either a bare list of
        interval objects (2.4 style) or ``{"intervals": [...],
        "aggregationIds": {...}}``."""
        if isinstance(obj, list):
            intervals_json, agg_ids = obj, None
        else:
            intervals_json = obj.get("intervals", [])
            agg_ids = obj.get("aggregationIds")
        intervals = [
            RollupInterval(
                table=iv.get("table", f"tsdb-rollup-{iv['interval']}"),
                pre_aggregation_table=iv.get(
                    "preAggregationTable",
                    f"tsdb-rollup-agg-{iv['interval']}"),
                interval=iv["interval"],
                row_span=iv.get("rowSpan", "1d"),
                default_interval=bool(iv.get("defaultInterval", False)),
            ) for iv in intervals_json
        ]
        return cls(intervals, agg_ids)

    def get_interval(self, interval: str) -> RollupInterval:
        try:
            return self._by_interval[interval]
        except KeyError:
            raise ValueError(f"no rollup tier for interval {interval!r}"
                             ) from None

    def best_match(self, interval_ms: int) -> RollupInterval | None:
        """Largest tier whose interval divides the query's downsample
        interval (ref: TsdbQuery rollup best-match :143-150). Returns
        None when raw data must be used."""
        best = None
        for iv in self.intervals:
            if iv.interval_ms <= interval_ms and \
                    interval_ms % iv.interval_ms == 0:
                best = iv
        return best

    def to_json(self) -> dict:
        return {
            "intervals": [
                {"table": iv.table,
                 "preAggregationTable": iv.pre_aggregation_table,
                 "interval": iv.interval, "rowSpan": iv.row_span,
                 "defaultInterval": iv.default_interval}
                for iv in self.intervals],
            "aggregationIds": self.agg_ids,
        }
