"""The rollup job: batch pre-aggregation of raw data into tiers.

The reference has NO in-repo rollup compactor — rollups are written by
external jobs through the TSD API (SURVEY.md §2.3, TSDB.java:1320).
The TPU build ships one: for every series, the raw points of a time
range are segment-reduced into each tier's buckets with the same
bucketize kernel the query path uses (one fused XLA program per
(tier, aggregator)), then written into the tier stores. This is
BASELINE.json config 5 ("rollup compaction job: 24h@1s raw -> 1m/1h
tiers").

Batching: series are processed in chunks so the device working set
stays bounded (time-blocking is inherited from the chunked
materialize); all four standard rollup aggregations (sum/count/min/max
— avg derives as sum/count at query time, ref RollupConfig) compute
from ONE pass over the points.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.rollup.config import RollupConfig

ROLLUP_AGGS = ("sum", "count", "min", "max")


def run_rollup_job(tsdb, start_ms: int, end_ms: int,
                   intervals: list[str] | None = None,
                   series_chunk: int = 100_000,
                   progress=None) -> dict[str, int]:
    """Materialize rollup tiers for all raw data in [start_ms, end_ms].

    Returns {interval: points_written}.
    """
    if tsdb.rollup_store is None:
        raise RuntimeError("rollups are not enabled")
    config: RollupConfig = tsdb.rollup_config
    tiers = ([config.get_interval(iv) for iv in intervals]
             if intervals else config.intervals)
    written: dict[str, int] = {iv.interval: 0 for iv in tiers}

    all_sids = np.concatenate(
        [tsdb.store.series_ids_for_metric(mid)
         for mid in tsdb.store.metric_ids()]
        or [np.empty(0, dtype=np.int64)])
    for lo in range(0, len(all_sids), series_chunk):
        chunk = all_sids[lo:lo + series_chunk]
        batch = tsdb.store.materialize(chunk, start_ms, end_ms)
        if batch.num_points == 0:
            continue
        for tier in tiers:
            spec = ds_mod.DownsamplingSpecification(
                interval_ms=tier.interval_ms, function="sum")
            bucket_idx, bucket_ts = ds_mod.assign_buckets(
                batch.ts_ms, spec, start_ms, end_ms)
            grids = {}
            for agg in ROLLUP_AGGS:
                grid, _ = ds_mod.bucketize(
                    np.asarray(batch.values), batch.series_idx,
                    bucket_idx, batch.num_series, len(bucket_ts), agg)
                grids[agg] = np.asarray(grid)
            for agg in ROLLUP_AGGS:
                store = tsdb.rollup_store.tier(tier.interval, agg)
                grid = grids[agg]
                for si, sid in enumerate(chunk):
                    rec = tsdb.store.series(int(sid))
                    row = grid[si]
                    mask = ~np.isnan(row)
                    if not mask.any():
                        continue
                    rsid = store.get_or_create_series(rec.metric_id,
                                                      rec.tags)
                    store.append_many(rsid, bucket_ts[mask], row[mask])
                    written[tier.interval] += int(mask.sum())
        if progress is not None:
            progress(min(lo + series_chunk, len(all_sids)),
                     len(all_sids))
    return written
