"""The rollup job: batch pre-aggregation of raw data into tiers.

The reference has NO in-repo rollup compactor — rollups are written by
external jobs through the TSD API (SURVEY.md §2.3, TSDB.java:1320).
The TPU build ships one. This is BASELINE.json config 5 ("rollup
compaction job: 24h@1s raw -> 1m/1h tiers across 10M series").

Design (TPU-first):

- the raw window is processed in (series_chunk x time_window) tiles so
  the device working set stays bounded regardless of range length
  (time windows are the job-side analogue of the query path's
  ``ops.blocked`` streaming);
- each tile computes all four rollup aggregations (sum/count/min/max —
  avg derives as sum/count at query time, ref RollupConfig) in ONE
  jitted program over one pass of the data, using the scatter-free
  padded kernel (:func:`opentsdb_tpu.ops.downsample.bucketize_padded`);
- coarser tiers whose interval is a small multiple of the finest
  reduce the finest tier's grids hierarchically on device (1h sum =
  sum of 1m sums, 1h min = min of 1m mins, ...) — no second pass over
  the raw data. Non-nesting or very coarse tiers take their own pass.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.rollup.config import RollupConfig, RollupInterval

ROLLUP_AGGS = ("sum", "count", "min", "max")

# device cell budget per tile and bucket cap per window. Wider windows
# amortize per-dispatch latency (the dominant cost on relayed devices);
# the cap bounds the [S, B] output grids and the coarsen one-hot.
_TILE_CELL_BUDGET = 64_000_000
_MAX_WINDOW_BUCKETS = 360


@partial(jax.jit, static_argnames=("num_buckets",))
def _rollup_tile(values2d, bucket_idx2d, num_buckets: int):
    """One tile -> stacked [4, S, B] grids (sum/count/min/max order).
    XLA dedupes the shared count contraction across the four calls."""
    grids = [ds_mod.bucketize_padded(values2d, bucket_idx2d,
                                     num_buckets, agg)[0]
             for agg in ROLLUP_AGGS]
    return jnp.stack(grids)


@partial(jax.jit, static_argnames=("num_buckets", "k"))
def _rollup_tile_dense(values2d, num_buckets: int, k: int):
    """Regular-cadence tile (every row full, k points per bucket): all
    four aggregations from [S, B, k] reshape reductions — no bucket
    compare tensor, one pass over the data per statistic. This is the
    fixed-collection-interval common case and the BASELINE config-5
    shape."""
    s = values2d.shape[0]
    x = values2d.reshape(s, num_buckets, k)
    valid = ~jnp.isnan(x)
    cnt = jnp.sum(valid, axis=-1).astype(values2d.dtype)
    sums = jnp.nansum(x, axis=-1)
    mins = jnp.min(jnp.where(valid, x, jnp.inf), axis=-1)
    maxs = jnp.max(jnp.where(valid, x, -jnp.inf), axis=-1)
    empty = cnt == 0
    return jnp.stack([
        jnp.where(empty, jnp.nan, sums),
        jnp.where(empty, jnp.nan, cnt),
        jnp.where(empty, jnp.nan, mins),
        jnp.where(empty, jnp.nan, maxs),
    ])


@partial(jax.jit, static_argnames=("num_coarse",))
def _coarsen(grids, coarse_idx, num_coarse: int):
    """[4, S, Bf] + fine->coarse bucket map [Bf] -> [4, S, Bc].

    Hierarchical reduction: coarse sum = sum of fine sums, count = sum
    of counts, min = min of mins, max = max of maxes. The mapping is
    host-computed from bucket timestamps, so coarse buckets stay
    aligned to their own interval and partial buckets at the window
    edges still materialize. NaN marks empty fine buckets.
    """
    onehot = jax.nn.one_hot(coarse_idx, num_coarse, dtype=grids.dtype)
    hi = jax.lax.Precision.HIGHEST

    def csum(x):
        return jnp.einsum("sb,bc->sc", jnp.where(jnp.isnan(x), 0.0, x),
                          onehot, precision=hi)

    sums = csum(grids[0])
    cnts = csum(grids[1])
    # broadcast membership [Bf, Bc] -> one fused reduce per extremum
    # (a per-coarse-bucket Python loop unrolls Bc passes)
    eq = coarse_idx[:, None] == jnp.arange(num_coarse,
                                           dtype=coarse_idx.dtype)[None, :]
    m_min = eq[None, :, :] & ~jnp.isnan(grids[2])[:, :, None]
    mins = jnp.min(jnp.where(m_min, grids[2][:, :, None], jnp.inf),
                   axis=1)
    m_max = eq[None, :, :] & ~jnp.isnan(grids[3])[:, :, None]
    maxs = jnp.max(jnp.where(m_max, grids[3][:, :, None], -jnp.inf),
                   axis=1)
    empty = cnts == 0
    nan = jnp.nan
    return jnp.stack([
        jnp.where(empty, nan, sums),
        jnp.where(empty, nan, cnts),
        jnp.where(empty, nan, mins),
        jnp.where(empty, nan, maxs),
    ])


def _chunk_tier_sids(tsdb, tiers: list[RollupInterval], chunk
                     ) -> dict[tuple[str, str], np.ndarray]:
    """Raw sid -> tier-store sid for every (tier, agg), computed ONCE
    per series chunk (the mapping is window-invariant, so the window
    loop must not pay per-series Python work)."""
    recs = [tsdb.store.series(int(sid)) for sid in chunk]
    out = {}
    for tier in tiers:
        for agg in ROLLUP_AGGS:
            store = tsdb.rollup_store.tier(tier.interval, agg)
            out[(tier.interval, agg)] = np.fromiter(
                (store.get_or_create_series(r.metric_id, r.tags)
                 for r in recs), dtype=np.int64, count=len(recs))
    return out


def _write_outs(tsdb, rsid_map, outs, written: dict[str, int]) -> None:
    """Fetch dispatched device grids and write them to the tier
    stores. Kept separate from dispatch so the NEXT window's device
    work is already in flight while this one's results download and
    write (the fetch is the only blocking step)."""
    for tier, bucket_ts, g_dev, row_off in outs:
        _write_grids(tsdb, tier, rsid_map, bucket_ts,
                     np.asarray(g_dev), row_off, written)


def _write_grids(tsdb, tier: RollupInterval, rsid_map, bucket_ts,
                 grids: np.ndarray, row_off: int,
                 written: dict[str, int]) -> None:
    """Bulk-write all four aggregations (store.append_grid: one C++
    threaded pass per agg on the native backend). All four grids share
    one NaN pattern (a bucket is NaN iff its count is 0), so a single
    [S, B] mask serves every agg. ``row_off`` positions grid row 0
    within the sweep's chunk (series-split tiles cover a sub-range)."""
    mask = ~np.isnan(grids[1])  # count grid
    any_rows = mask.any(axis=1)
    if not any_rows.any():
        return
    rows = np.nonzero(any_rows)[0]
    sub_mask = mask[rows]
    for ai, agg in enumerate(ROLLUP_AGGS):
        store = tsdb.rollup_store.tier(tier.interval, agg)
        rsids = rsid_map[(tier.interval, agg)][row_off + rows]
        n = store.append_grid(rsids, np.asarray(bucket_ts),
                              grids[ai][rows], sub_mask)
        written[tier.interval] += n


# the irregular tile reduces a broadcast [S, P, B] membership tensor,
# so its cell count stays bounded by splitting wide windows (or, when
# the nested-tier lcm forbids narrower windows, the series axis)
_PADDED_TILE_MAX_CELLS = 500_000_000
# sub-window bucket cap used when re-tiling an oversized irregular tile
_SPLIT_WINDOW_BUCKETS = 64


def _split_window(tsdb, chunk, row_off: int, start_ms: int,
                  end_ms: int, base: RollupInterval,
                  nested: list[RollupInterval]) -> list:
    """Re-tile an oversized irregular window: narrower coarse-aligned
    sub-windows when the nested-tier lcm allows, else halve the series
    axis (each half may split further)."""
    factors = [t.interval_ms // base.interval_ms for t in nested]
    sub_buckets = _window_buckets(factors, cap=_SPLIT_WINDOW_BUCKETS)
    cur_buckets = (end_ms - start_ms) // base.interval_ms + 1
    outs = []
    if sub_buckets < cur_buckets:
        sub_ms = base.interval_ms * sub_buckets
        t0 = start_ms - (start_ms % sub_ms)
        while t0 <= end_ms:
            outs.extend(_rollup_window(
                tsdb, chunk, row_off, max(t0, start_ms),
                min(t0 + sub_ms - 1, end_ms), base, nested,
                can_split=False))
            t0 += sub_ms
        return outs
    half = len(chunk) // 2
    if half == 0:
        # single series still over the cap: dispatch as-is
        return _rollup_window(tsdb, chunk, row_off, start_ms, end_ms,
                              base, nested, can_split=False)
    outs.extend(_rollup_window(tsdb, chunk[:half], row_off, start_ms,
                               end_ms, base, nested))
    outs.extend(_rollup_window(tsdb, chunk[half:], row_off + half,
                               start_ms, end_ms, base, nested))
    return outs


def _rollup_window(tsdb, chunk, row_off: int, start_ms: int,
                   end_ms: int, base: RollupInterval,
                   nested: list[RollupInterval],
                   can_split: bool = True) -> list:
    """One (series chunk x time window) tile: base tier from raw, then
    nested tiers by on-device coarsening. DISPATCHES the device work
    and returns ``[(tier, bucket_ts, device_grids, row_off), ...]``
    without blocking — the tile grids never round-trip to the host
    between bucketize and coarsen."""
    if can_split:
        # pre-split clearly-irregular oversized windows from counts
        # alone, BEFORE paying the big materialize (equal counts are
        # near-certainly the regular fast path, which builds no
        # membership tensor; the post-detect check below backstops the
        # equal-but-irregular edge)
        counts = tsdb.store.count_range(chunk, start_ms, end_ms)
        pmax = int(counts.max()) if len(counts) else 0
        nb_est = (end_ms - start_ms) // base.interval_ms + 1
        if pmax and int(counts.min()) != pmax and \
                len(chunk) * pmax * nb_est > _PADDED_TILE_MAX_CELLS:
            return _split_window(tsdb, chunk, row_off, start_ms,
                                 end_ms, base, nested)
    padded = tsdb.store.materialize_padded(chunk, start_ms, end_ms)
    if padded.num_points == 0:
        return []
    spec = ds_mod.DownsamplingSpecification(
        interval_ms=base.interval_ms, function="sum")
    bucket_idx2d, bucket_ts = ds_mod.assign_buckets_padded(
        padded.ts2d, padded.counts, spec, start_ms, end_ms)
    dtype = jnp.float64 if jax.config.read("jax_enable_x64") \
        else jnp.float32
    from opentsdb_tpu.ops.pipeline import detect_regular_padded
    k = detect_regular_padded(np.asarray(padded.counts),
                              np.asarray(bucket_idx2d), len(bucket_ts))
    if k is not None:
        g_dev = _rollup_tile_dense(
            jnp.asarray(padded.values2d, dtype=dtype),
            len(bucket_ts), k)
    else:
        cells = (padded.values2d.shape[0] * padded.values2d.shape[1]
                 * len(bucket_ts))
        if can_split and cells > _PADDED_TILE_MAX_CELLS:
            return _split_window(tsdb, chunk, row_off, start_ms,
                                 end_ms, base, nested)
        g_dev = _rollup_tile(
            jnp.asarray(padded.values2d, dtype=dtype),
            jnp.asarray(bucket_idx2d, dtype=jnp.int32), len(bucket_ts))
    outs = [(base, bucket_ts, g_dev, row_off)]
    for tier in nested:
        coarse_edges = ds_mod.fixed_bucket_edges(
            int(bucket_ts[0]), int(bucket_ts[-1]), tier.interval_ms)
        coarse_idx = ((bucket_ts - coarse_edges[0])
                      // tier.interval_ms).astype(np.int32)
        cg_dev = _coarsen(g_dev, jnp.asarray(coarse_idx),
                          len(coarse_edges))
        outs.append((tier, coarse_edges, cg_dev, row_off))
    return outs


def _rollup_window_native(tsdb, chunk, row_off: int, start_ms: int,
                          end_ms: int, base: RollupInterval,
                          nested: list[RollupInterval]) -> list:
    """Storage-side tile: the C++ fused range-scan produces the base
    tier's sum/count/min/max grids directly (``tss_bucket_reduce``),
    and nested tiers coarsen by reshape reductions on the host — the
    raw points never leave the storage arena. On hosts feeding a
    remote/tunneled device this beats the device tiles by the full
    transfer cost (the job is a pure reduction; there is no reuse to
    amortize an upload against). Same output contract as
    :func:`_rollup_window`."""
    bucket_ts = ds_mod.fixed_bucket_edges(start_ms, end_ms,
                                          base.interval_ms)
    b = len(bucket_ts)
    sums, cnts, mins, maxs = tsdb.store.bucket_reduce(
        chunk, start_ms, end_ms, int(bucket_ts[0]), base.interval_ms,
        b, want_minmax=True)
    if not cnts.any():
        return []
    outs = []

    def finalize(s_, c_, mn_, mx_, tier, bts):
        empty = c_ == 0
        outs.append((tier, bts, np.stack([
            np.where(empty, np.nan, s_), np.where(empty, np.nan, c_),
            np.where(empty, np.nan, mn_),
            np.where(empty, np.nan, mx_)]), row_off))

    finalize(sums, cnts, mins, maxs, base, bucket_ts)
    for tier in nested:
        f = tier.interval_ms // base.interval_ms
        coarse_edges = ds_mod.fixed_bucket_edges(
            int(bucket_ts[0]), int(bucket_ts[-1]), tier.interval_ms)
        # align the base-bucket axis to the coarse grid, pad the tail,
        # and reduce [S, Bc, f]; empty raw cells carry the reduction
        # identities (0 for sum/count, +/-inf for min/max) so they
        # vanish in the coarse cells
        off = int((bucket_ts[0] - coarse_edges[0]) // base.interval_ms)
        pad_hi = len(coarse_edges) * f - (off + b)
        s = len(chunk)

        def pad(a, fill):
            return np.pad(a, ((0, 0), (off, pad_hi)),
                          constant_values=fill)

        finalize(pad(sums, 0.0).reshape(s, -1, f).sum(axis=2),
                 pad(cnts, 0.0).reshape(s, -1, f).sum(axis=2),
                 pad(mins, np.inf).reshape(s, -1, f).min(axis=2),
                 pad(maxs, -np.inf).reshape(s, -1, f).max(axis=2),
                 tier, coarse_edges)
    return outs


def _window_buckets(nested_factors: list[int],
                    cap: int = _MAX_WINDOW_BUCKETS) -> int:
    """Buckets of the base tier per window: a multiple of every nested
    factor (so coarsening never straddles a window edge), capped.
    Sweep callers guarantee lcm(factors) <= _MAX_WINDOW_BUCKETS; with
    a smaller cap (the irregular split) the result may exceed it."""
    lcm = 1
    for f in nested_factors:
        lcm = math.lcm(lcm, f)
    return lcm * max(1, cap // lcm)


def run_rollup_job(tsdb, start_ms: int, end_ms: int,
                   intervals: list[str] | None = None,
                   series_chunk: int | None = None,
                   progress=None,
                   series_ids=None) -> dict[str, int]:
    """Materialize rollup tiers for all raw data in [start_ms, end_ms].

    ``series_ids`` optionally restricts the job to a subset of raw
    series (the lifecycle manager demotes one metric at a time);
    default is every series of every metric.

    Returns {interval: points_written}.
    """
    if tsdb.rollup_store is None:
        raise RuntimeError("rollups are not enabled")
    config: RollupConfig = tsdb.rollup_config
    tiers = ([config.get_interval(iv) for iv in intervals]
             if intervals else config.intervals)
    tiers = sorted(tiers, key=lambda t: t.interval_ms)
    written: dict[str, int] = {iv.interval: 0 for iv in tiers}
    if not tiers:
        return written
    finest = tiers[0]
    # greedily nest coarser tiers under the finest pass while the LCM
    # of their base-interval factors keeps one window within the
    # bucket cap (the padded min/max kernel unrolls per bucket, and
    # chunk sizing assumes the cap); the rest take their own raw pass
    nested: list[RollupInterval] = []
    lcm = 1
    for t in tiers[1:]:
        if t.interval_ms % finest.interval_ms:
            continue
        f = t.interval_ms // finest.interval_ms
        if math.lcm(lcm, f) <= _MAX_WINDOW_BUCKETS:
            nested.append(t)
            lcm = math.lcm(lcm, f)
    direct = [t for t in tiers[1:] if t not in nested]

    if series_ids is not None:
        all_sids = np.asarray(series_ids, dtype=np.int64)
    else:
        all_sids = np.concatenate(
            [tsdb.store.series_ids_for_metric(mid)
             for mid in tsdb.store.metric_ids()]
            or [np.empty(0, dtype=np.int64)])
    if len(all_sids):
        # skip series with no raw data in the job window up front:
        # _chunk_tier_sids get_or_creates a tier series per (tier, agg)
        # per raw series, so a sparse range would otherwise permanently
        # allocate empty tier series (memory + snapshot growth)
        counts = np.asarray(
            tsdb.store.count_range(all_sids, start_ms, end_ms))
        all_sids = all_sids[counts > 0]
    # sweeps: finest pass feeds nested tiers by coarsening; each
    # non-nesting tier scans the raw data itself
    sweeps = [(finest, nested)] + [(t, []) for t in direct]
    total_work = len(all_sids) * len(sweeps)
    done = 0
    # storage-side reduction by default (tss_bucket_reduce — no
    # device transfer); tsd.rollups.job.device forces the device tiles
    use_native = (hasattr(tsdb.store, "bucket_reduce") and not
                  tsdb.config.get_bool("tsd.rollups.job.device"))

    for base, sub in sweeps:
        factors = [t.interval_ms // base.interval_ms for t in sub]
        win_ms = base.interval_ms * _window_buckets(factors)
        if series_chunk is None:
            # size the chunk for THIS sweep's window (direct tiers
            # have wider windows), assuming up to 1s cadence
            win_pts = max(1, win_ms // 1000)
            chunk_sz = max(1, _TILE_CELL_BUDGET // win_pts)
        else:
            chunk_sz = series_chunk
        for lo in range(0, len(all_sids), chunk_sz):
            chunk = all_sids[lo:lo + chunk_sz]
            rsid_map = _chunk_tier_sids(tsdb, [base] + sub, chunk)
            # windows align to their own width (a multiple of every
            # nested tier's interval) so no coarse bucket straddles
            # two windows — a straddle would write the same coarse ts
            # twice and lose one half to last-write-wins dedup.
            # One window's device work stays in flight while the
            # previous window's results download and write.
            pending = None
            t0 = start_ms - (start_ms % win_ms)
            while t0 <= end_ms:
                if use_native:
                    outs = _rollup_window_native(
                        tsdb, chunk, 0, max(t0, start_ms),
                        min(t0 + win_ms - 1, end_ms), base, sub)
                else:
                    outs = _rollup_window(tsdb, chunk, 0,
                                          max(t0, start_ms),
                                          min(t0 + win_ms - 1, end_ms),
                                          base, sub)
                if pending:
                    _write_outs(tsdb, rsid_map, pending, written)
                pending = outs
                t0 += win_ms
            if pending:
                _write_outs(tsdb, rsid_map, pending, written)
            done += len(chunk)
            if progress is not None:
                progress(done, total_work)
    return written
