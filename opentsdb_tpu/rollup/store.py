"""Rollup tier storage.

One :class:`TimeSeriesStore` per (tier, aggregator), mirroring the
reference's per-tier HBase tables with agg-prefixed qualifiers
(ref: ``src/rollup/RollupUtils.java:120-178``). Written either by the
external-job API (``TSDB.add_aggregate_point``, ref TSDB.java:1320) or
by the in-framework rollup job (:mod:`opentsdb_tpu.rollup.job`) — which
the reference lacks (SURVEY.md §2.3: "rollups are written by external
jobs"); the TPU build ships one as a jitted segmented reduction.
"""

from __future__ import annotations

import threading
from typing import Sequence

from opentsdb_tpu.core.store import PointBatch, TimeSeriesStore
from opentsdb_tpu.rollup.config import RollupConfig


class RollupStore:
    def __init__(self, config: RollupConfig, store_factory=None,
                 fault_injector=None):
        self.config = config
        # tier stores come from the same backend factory as the raw
        # store (native C++ by default) — the rollup job's bulk grid
        # writes were 15x slower through the portable Python store
        self._factory = store_factory or TimeSeriesStore
        # scans of tier/preagg stores carry their own fault site
        # ("rollup.store") so a degraded rollup tier is distinguishable
        # from a degraded raw store; lazily-created tiers are wired the
        # moment they exist (ROADMAP open item)
        self.fault_injector = fault_injector
        # guards _tiers shape: writers create tiers lazily while query
        # threads snapshot the dict for the serve version
        self._tiers_lock = threading.Lock()
        # (interval, agg) -> store
        # tsdlint: allow[unbounded-growth] keyed by configured rollup
        # tier (interval, agg) pairs — a handful, fixed by config
        self._tiers: dict[tuple[str, str], TimeSeriesStore] = {}
        self._preagg = self._new_store()
        # (interval, agg) -> (mutation_epoch, points_written, result)
        # tsdlint: allow[unbounded-growth] same (interval, agg)
        # keyspace as _tiers — bounded by configured tiers
        self._has_data_cache: dict[tuple[str, str], tuple] = {}

    def _new_store(self) -> TimeSeriesStore:
        store = self._factory()
        store.fault_injector = self.fault_injector
        store.fault_site = "rollup.store"
        return store

    def tier(self, interval: str, agg: str) -> TimeSeriesStore:
        agg = agg.lower()
        if agg not in self.config.agg_ids:
            raise ValueError(
                f"unsupported rollup aggregator {agg!r} "
                f"(supported: {sorted(self.config.agg_ids)})")
        self.config.get_interval(interval)  # validate tier exists
        key = (interval, agg)
        store = self._tiers.get(key)
        if store is None:
            with self._tiers_lock:
                store = self._tiers.get(key)
                if store is None:
                    store = self._tiers[key] = self._new_store()
        return store

    def version(self) -> tuple:
        """Write/delete version over every tier + the preagg store,
        including the tier COUNT (a tier springing into existence can
        flip tier selection for queries that previously read raw).
        Consumed by the serve-path result cache via
        :meth:`TSDB.serve_version`."""
        with self._tiers_lock:
            tiers = list(self._tiers.items())
        parts: list = [len(tiers), self._preagg.points_written,
                       getattr(self._preagg, "mutation_epoch", 0)]
        for key, store in sorted(tiers):
            parts.append((key, store.points_written,
                          getattr(store, "mutation_epoch", 0)))
        return tuple(parts)

    def add_point(self, interval: str, agg: str, metric_id: int,
                  tag_ids: Sequence[tuple[int, int]], ts_ms: int,
                  value: float) -> None:
        store = self.tier(interval, agg)
        sid = store.get_or_create_series(metric_id, tag_ids)
        store.append(sid, ts_ms, value)

    def add_preagg_point(self, metric_id: int,
                         tag_ids: Sequence[tuple[int, int]], ts_ms: int,
                         value: float) -> None:
        sid = self._preagg.get_or_create_series(metric_id, tag_ids)
        self._preagg.append(sid, ts_ms, value)

    def preagg_store(self) -> TimeSeriesStore:
        return self._preagg

    def has_data(self, interval: str, agg: str) -> bool:
        """O(1) in steady state: points_written is a cheap counter on
        both backends while total_points() walks every series (seconds
        at 1M series) and this check runs on EVERY query's tier
        selection. Writes only ever add data, so a True verdict stays
        valid until a destructive op bumps mutation_epoch — only then
        does the expensive emptiness walk rerun (a tier fully emptied
        by delete=true must stop winning tier selection)."""
        key = (interval, agg.lower())
        store = self._tiers.get(key)
        if store is None:
            return False
        pw = store.points_written
        if pw == 0:
            return False
        ep = getattr(store, "mutation_epoch", 0)
        cached = self._has_data_cache.get(key)
        if cached is not None and cached[0] == ep:
            if cached[2]:
                return True
            if pw == cached[1]:
                return False
            # writes landed since the False verdict: data exists now
            self._has_data_cache[key] = (ep, pw, True)
            return True
        res = store.total_points() > 0
        self._has_data_cache[key] = (ep, pw, res)
        return res
