"""Time-series lookup + last-datapoint queries.

(ref: ``src/search/TimeSeriesLookup.java:83`` — scan-based tsdb-meta
lookup behind ``/api/search/lookup``; ``src/meta/TSUIDQuery.java:51`` —
``getLastPoint``/``getLastWriteTimes`` behind ``/api/query/last``)

Here the store's per-metric tag index makes both direct dictionary
walks: no scans needed.
"""

from __future__ import annotations

from typing import Any

from opentsdb_tpu.core import tags as tags_mod


def time_series_lookup(tsdb, metric: str, tags: list[tuple[str, str]],
                       limit: int = 25, use_meta: bool = False
                       ) -> dict[str, Any]:
    """(ref: TimeSeriesLookup.lookup)"""
    uids = tsdb.uids
    results = []
    metric_ids = []
    if metric and metric != "*":
        try:
            metric_ids = [uids.metrics.get_id(metric)]
        except LookupError:
            metric_ids = []
    else:
        metric_ids = tsdb.store.metric_ids()
    # resolve tag constraints
    want: list[tuple[int | None, int | None]] = []
    for k, v in tags:
        try:
            kid = uids.tag_names.get_id(k) if k and k != "*" else None
            vid = uids.tag_values.get_id(v) if v and v != "*" else None
        except LookupError:
            return {"type": "LOOKUP", "metric": metric or "*",
                    "limit": limit, "time": 0, "results": [],
                    "totalResults": 0}
        want.append((kid, vid))
    total = 0
    for mid in metric_ids:
        for sid in tsdb.store.series_ids_for_metric(mid):
            rec = tsdb.store.series(int(sid))
            tag_map = dict(rec.tags)
            ok = True
            for kid, vid in want:
                if kid is not None and vid is not None:
                    if tag_map.get(kid) != vid:
                        ok = False
                        break
                elif kid is not None:
                    if kid not in tag_map:
                        ok = False
                        break
                elif vid is not None:
                    if vid not in tag_map.values():
                        ok = False
                        break
            if not ok:
                continue
            total += 1
            if len(results) < limit:
                results.append({
                    "tsuid": uids.tsuid(rec.metric_id,
                                        rec.tags).hex().upper(),
                    "metric": uids.metrics.get_name(rec.metric_id),
                    "tags": {uids.tag_names.get_name(k):
                             uids.tag_values.get_name(v)
                             for k, v in rec.tags},
                })
    return {"type": "LOOKUP", "metric": metric or "*", "limit": limit,
            "time": 0, "results": results, "totalResults": total}


def last_data_points(tsdb, specs: list[dict], back_scan: int = 0,
                     resolve: bool = True) -> list[dict]:
    """(ref: TSUIDQuery.getLastPoint :161)"""
    uids = tsdb.uids
    out = []
    # back_scan bounds how far back the "last" point may be (ref:
    # TSUIDQuery back_scan hours — a series whose newest point is
    # older than the window reports nothing); one cutoff per request
    min_ts = 0
    if back_scan > 0:
        import time as _t
        min_ts = int((_t.time() - back_scan * 3600) * 1000)
    for spec in specs:
        sids = []
        metric = ""
        if spec.get("tsuids"):
            for tsuid in spec["tsuids"]:
                sid, metric = _sid_from_tsuid(tsdb, tsuid)
                if sid is not None:
                    sids.append(sid)
        else:
            m = spec.get("metric") or spec.get("uri") or ""
            metric, tag_map = tags_mod.parse_with_metric(m)
            try:
                mid = uids.metrics.get_id(metric)
            except LookupError:
                continue
            want = {}
            skip = False
            for k, v in tag_map.items():
                try:
                    want[uids.tag_names.get_id(k)] = \
                        uids.tag_values.get_id(v)
                except LookupError:
                    skip = True
                    break
            if skip:
                continue
            for sid in tsdb.store.series_ids_for_metric(mid):
                rec = tsdb.store.series(int(sid))
                tag_map2 = dict(rec.tags)
                if all(tag_map2.get(k) == v for k, v in want.items()):
                    sids.append(int(sid))
        for sid in sids:
            rec = tsdb.store.series(sid)
            ts, vals = rec.buffer.view()
            if len(ts) == 0 or int(ts[-1]) < min_ts:
                continue
            v = float(vals[-1])
            point: dict[str, Any] = {
                "timestamp": int(ts[-1]),
                "value": str(int(v)) if v.is_integer() else str(v),
                "tsuid": uids.tsuid(rec.metric_id,
                                    rec.tags).hex().upper(),
            }
            if resolve:
                point["metric"] = uids.metrics.get_name(rec.metric_id)
                point["tags"] = {uids.tag_names.get_name(k):
                                 uids.tag_values.get_name(v2)
                                 for k, v2 in rec.tags}
            out.append(point)
    return out


def _sid_from_tsuid(tsdb, tsuid: str):
    uids = tsdb.uids
    raw = bytes.fromhex(tsuid)
    mw, kw, vw = (uids.metrics.width, uids.tag_names.width,
                  uids.tag_values.width)
    mid = int.from_bytes(raw[:mw], "big")
    tags = []
    pos = mw
    while pos < len(raw):
        tags.append((int.from_bytes(raw[pos:pos + kw], "big"),
                     int.from_bytes(raw[pos + kw:pos + kw + vw], "big")))
        pos += kw + vw
    key = (mid, tuple(sorted(tags)))
    sid = tsdb.store._key_to_sid.get(key)
    try:
        metric = uids.metrics.get_name(mid)
    except LookupError:
        metric = ""
    return sid, metric
