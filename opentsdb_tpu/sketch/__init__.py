"""Mergeable quantile sketches (ROADMAP item 1).

A DDSketch-style relative-error quantile sketch that acts as the
fifth tier stat column: lifecycle demotion folds raw points into
per-cell sketches, cold segments persist them as a blob column,
the stitched read merges the three zones, the cluster router merges
per-shard partials, streaming CQs keep a sketch channel, and
``/api/stats/fleet`` merges latency sketches instead of bucket
ladders. See README "Quantile sketches" for the accuracy contract.
"""

from opentsdb_tpu.sketch.ddsketch import DDSketch

__all__ = ["DDSketch"]
