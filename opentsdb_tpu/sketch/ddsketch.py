"""DDSketch: a mergeable quantile sketch with relative-error bounds.

Chosen over KLL because its guarantee is *relative* (a q-quantile
estimate within ``alpha`` of the true value, for any q) which is the
right contract for latency-shaped data, its merge is a plain per-bucket
count addition (exactly associative and commutative as long as counts
stay integral, which they do below 2^53 in float64), and its state is
tiny and trivially serializable. KLL's rank-error guarantee is stronger
in the tails only if you keep raw samples around; its merge involves
randomized compaction, which would break the "router merge is bit-equal
to a single-node sketch" property this subsystem promises.

State is canonical: sparse sorted (bucket_index, count) parallel arrays
for the positive and negative stores plus a zero count, exact running
count/min/max. Because merge unions indices and adds integral counts,
any merge order over the same multiset of points produces the *same*
canonical state, hence the same serialized bytes and the same extracted
quantiles — merging per-shard partials at the router is bit-equal to
folding all points on one node.

Bucket ``i`` covers ``(gamma^(i-1), gamma^i]`` with
``gamma = (1 + alpha) / (1 - alpha)``; the estimate for a bucket is the
midpoint ``2 * gamma^i / (gamma + 1)``, within ``alpha`` relative error
of every value in the bucket. Values in ``[-MIN_INDEXABLE,
MIN_INDEXABLE]`` land in the zero bucket (estimate 0.0); negatives
mirror into their own store. NaNs are skipped at fold time.

Collapsing (``tsd.sketch.max_buckets``) only ever happens at *fold*
time, never at merge time: a merge of uncollapsed sketches is exact, so
distribution over shards/tiers cannot change the answer.
"""

from __future__ import annotations

import base64
import math
import struct

import numpy as np

# values at or below this magnitude are not indexable (log would
# explode the index range) and count as exact zeros
MIN_INDEXABLE = 1e-12

DEFAULT_ALPHA = 0.01
DEFAULT_MAX_BUCKETS = 4096

_MAGIC = b"DDSK"
_VERSION = 1
# magic, version u8, pad, n_pos u16... use u32s for safety:
# alpha f64, zero f64, count f64, min f64, max f64, n_pos u32, n_neg u32
_HDR = struct.Struct("<4sBxxxdddddII")


class SketchError(ValueError):
    """Raised on alpha mismatch or a corrupt serialized sketch."""


class DDSketch:
    """One mergeable quantile sketch. Not thread-safe; callers own
    locking (the stores that hold sketches guard them)."""

    __slots__ = ("alpha", "gamma", "_lg", "pos_idx", "pos_cnt",
                 "neg_idx", "neg_cnt", "zero_count", "count",
                 "min", "max")

    def __init__(self, alpha: float = DEFAULT_ALPHA):
        if not (0.0 < alpha < 1.0):
            raise SketchError(f"alpha out of range: {alpha!r}")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._lg = math.log(self.gamma)
        self.pos_idx = np.empty(0, dtype=np.int32)
        self.pos_cnt = np.empty(0, dtype=np.float64)
        self.neg_idx = np.empty(0, dtype=np.int32)
        self.neg_cnt = np.empty(0, dtype=np.float64)
        self.zero_count = 0.0
        self.count = 0.0
        self.min = math.inf
        self.max = -math.inf

    # ------------------------------------------------------------------
    # folding
    # ------------------------------------------------------------------

    def _keys(self, mags: np.ndarray) -> np.ndarray:
        """Bucket indices for positive magnitudes (vectorized)."""
        return np.ceil(np.log(mags) / self._lg).astype(np.int32)

    def add_values(self, values: np.ndarray) -> None:
        """Fold a column of raw values (NaNs skipped) into the sketch."""
        v = np.asarray(values, dtype=np.float64)
        v = v[np.isfinite(v)]
        if not len(v):
            return
        pos = v > MIN_INDEXABLE
        neg = v < -MIN_INDEXABLE
        nzero = int(len(v) - int(pos.sum()) - int(neg.sum()))
        if nzero:
            self.zero_count += nzero
        if pos.any():
            idx, cnt = np.unique(self._keys(v[pos]), return_counts=True)
            self.pos_idx, self.pos_cnt = _merge_store(
                self.pos_idx, self.pos_cnt, idx, cnt.astype(np.float64))
        if neg.any():
            idx, cnt = np.unique(self._keys(-v[neg]), return_counts=True)
            self.neg_idx, self.neg_cnt = _merge_store(
                self.neg_idx, self.neg_cnt, idx, cnt.astype(np.float64))
        self.count += len(v)
        self.min = min(self.min, float(v.min()))
        self.max = max(self.max, float(v.max()))

    def add(self, value: float) -> None:
        self.add_values(np.asarray([value]))

    def add_weighted(self, values: np.ndarray,
                     weights: np.ndarray) -> None:
        """Fold pre-counted values (histogram bucket midpoints with
        their counts). Rows with non-finite values or non-positive
        weights are skipped."""
        v = np.asarray(values, dtype=np.float64)
        w = np.asarray(weights, dtype=np.float64)
        keep = np.isfinite(v) & (w > 0)
        v, w = v[keep], w[keep]
        if not len(v):
            return
        pos = v > MIN_INDEXABLE
        neg = v < -MIN_INDEXABLE
        zero = ~pos & ~neg
        if zero.any():
            self.zero_count += float(w[zero].sum())
        for mask, flip, store in ((pos, 1.0, "pos"), (neg, -1.0,
                                                      "neg")):
            if not mask.any():
                continue
            idx, inv = np.unique(self._keys(flip * v[mask]),
                                 return_inverse=True)
            cnt = np.zeros(len(idx), dtype=np.float64)
            np.add.at(cnt, inv, w[mask])
            if store == "pos":
                self.pos_idx, self.pos_cnt = _merge_store(
                    self.pos_idx, self.pos_cnt, idx, cnt)
            else:
                self.neg_idx, self.neg_cnt = _merge_store(
                    self.neg_idx, self.neg_cnt, idx, cnt)
        self.count += float(w.sum())
        self.min = min(self.min, float(v.min()))
        self.max = max(self.max, float(v.max()))

    # ------------------------------------------------------------------
    # merge
    # ------------------------------------------------------------------

    def merge(self, other: "DDSketch") -> None:
        """Exact in-place merge (per-bucket count addition). Merge
        order cannot change the resulting canonical state."""
        if other.count == 0:
            return
        if abs(other.alpha - self.alpha) > 1e-12:
            raise SketchError(
                f"alpha mismatch: {self.alpha} vs {other.alpha}")
        self.pos_idx, self.pos_cnt = _merge_store(
            self.pos_idx, self.pos_cnt, other.pos_idx, other.pos_cnt)
        self.neg_idx, self.neg_cnt = _merge_store(
            self.neg_idx, self.neg_cnt, other.neg_idx, other.neg_cnt)
        self.zero_count += other.zero_count
        self.count += other.count
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    def copy(self) -> "DDSketch":
        out = DDSketch(self.alpha)
        out.pos_idx = self.pos_idx.copy()
        out.pos_cnt = self.pos_cnt.copy()
        out.neg_idx = self.neg_idx.copy()
        out.neg_cnt = self.neg_cnt.copy()
        out.zero_count = self.zero_count
        out.count = self.count
        out.min = self.min
        out.max = self.max
        return out

    # ------------------------------------------------------------------
    # collapsing (fold-time only)
    # ------------------------------------------------------------------

    def collapse(self, max_buckets: int) -> None:
        """Bound memory by folding the *lowest* buckets of whichever
        store is largest into its lowest kept bucket (the standard
        DDSketch policy: the relative-error guarantee survives for
        every quantile whose value lands at or above the collapse
        point — in latency data, all the ones anybody asks for).
        Called at fold time only; merges never collapse."""
        while len(self.pos_idx) + len(self.neg_idx) > max_buckets:
            # the negative store's lowest-magnitude buckets are the
            # *highest* values of that store; collapsing must eat the
            # lowest VALUES overall, which for negatives means the
            # highest magnitudes (largest indices)
            if len(self.neg_idx) == 1:
                # last negative bucket: fold toward the zero bucket
                self.zero_count += float(self.neg_cnt[0])
                self.neg_idx = self.neg_idx[:0]
                self.neg_cnt = self.neg_cnt[:0]
            elif len(self.neg_idx):
                keep = len(self.neg_idx) - 1
                self.neg_cnt[keep - 1] += self.neg_cnt[keep]
                self.neg_idx = self.neg_idx[:keep]
                self.neg_cnt = self.neg_cnt[:keep]
            else:
                cnt0 = float(self.pos_cnt[0])
                self.pos_idx = self.pos_idx[1:]
                self.pos_cnt = self.pos_cnt[1:].copy()
                if len(self.pos_cnt):
                    self.pos_cnt[0] += cnt0
                else:
                    self.zero_count += cnt0

    # ------------------------------------------------------------------
    # extraction
    # ------------------------------------------------------------------

    def _bucket_value(self, idx: int) -> float:
        return 2.0 * (self.gamma ** idx) / (self.gamma + 1.0)

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` (percent, 0..100) — NaN when empty.
        Within ``alpha`` relative error of the true quantile of the
        folded population (exact for min/max and the zero bucket)."""
        if self.count == 0:
            return math.nan
        rank = (q / 100.0) * (self.count - 1.0)
        cum = 0.0
        # ascending value order: negatives from the most negative
        # (largest index) up, then zero, then positives ascending
        for i in range(len(self.neg_idx) - 1, -1, -1):
            cum += float(self.neg_cnt[i])
            if cum > rank:
                return self._clamp(-self._bucket_value(
                    int(self.neg_idx[i])))
        cum += self.zero_count
        if cum > rank:
            return self._clamp(0.0)
        for i in range(len(self.pos_idx)):
            cum += float(self.pos_cnt[i])
            if cum > rank:
                return self._clamp(self._bucket_value(
                    int(self.pos_idx[i])))
        return self.max

    def quantiles(self, qs) -> list[float]:
        return [self.quantile(q) for q in qs]

    def _clamp(self, v: float) -> float:
        return min(max(v, self.min), self.max)

    # ------------------------------------------------------------------
    # serialization (deterministic little-endian binary)
    # ------------------------------------------------------------------

    def to_bytes(self) -> bytes:
        head = _HDR.pack(_MAGIC, _VERSION, self.alpha, self.zero_count,
                         self.count, self.min, self.max,
                         len(self.pos_idx), len(self.neg_idx))
        return b"".join((
            head,
            np.ascontiguousarray(self.pos_idx, dtype="<i4").tobytes(),
            np.ascontiguousarray(self.pos_cnt, dtype="<f8").tobytes(),
            np.ascontiguousarray(self.neg_idx, dtype="<i4").tobytes(),
            np.ascontiguousarray(self.neg_cnt, dtype="<f8").tobytes(),
        ))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "DDSketch":
        if len(blob) < _HDR.size:
            raise SketchError("sketch blob truncated")
        (magic, ver, alpha, zero, count, mn, mx,
         n_pos, n_neg) = _HDR.unpack_from(blob)
        if magic != _MAGIC or ver != _VERSION:
            raise SketchError(
                f"bad sketch header {magic!r} v{ver}")
        need = _HDR.size + 12 * (n_pos + n_neg)
        if len(blob) != need:
            raise SketchError(
                f"sketch blob length {len(blob)} != {need}")
        out = cls(alpha)
        off = _HDR.size
        out.pos_idx = np.frombuffer(blob, "<i4", n_pos, off) \
            .astype(np.int32)
        off += 4 * n_pos
        out.pos_cnt = np.frombuffer(blob, "<f8", n_pos, off) \
            .astype(np.float64)
        off += 8 * n_pos
        out.neg_idx = np.frombuffer(blob, "<i4", n_neg, off) \
            .astype(np.int32)
        off += 4 * n_neg
        out.neg_cnt = np.frombuffer(blob, "<f8", n_neg, off) \
            .astype(np.float64)
        out.zero_count = zero
        out.count = count
        out.min = mn
        out.max = mx
        return out

    def to_b64(self) -> str:
        return base64.b64encode(self.to_bytes()).decode("ascii")

    @classmethod
    def from_b64(cls, text: str) -> "DDSketch":
        return cls.from_bytes(base64.b64decode(text))

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"DDSketch(alpha={self.alpha}, count={self.count}, "
                f"buckets={len(self.pos_idx) + len(self.neg_idx)})")


def _merge_store(idx_a: np.ndarray, cnt_a: np.ndarray,
                 idx_b: np.ndarray, cnt_b: np.ndarray
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Union two sorted sparse (index, count) stores, adding counts of
    shared indices. Output is sorted unique — the canonical form."""
    if not len(idx_a):
        return idx_b.astype(np.int32), cnt_b.astype(np.float64)
    if not len(idx_b):
        return idx_a, cnt_a
    all_idx = np.concatenate([idx_a, idx_b])
    all_cnt = np.concatenate([cnt_a, cnt_b])
    uniq, inv = np.unique(all_idx, return_inverse=True)
    cnt = np.zeros(len(uniq), dtype=np.float64)
    np.add.at(cnt, inv, all_cnt)
    return uniq.astype(np.int32), cnt


def merge_all(sketches, alpha: float | None = None) -> DDSketch:
    """Merge an iterable of sketches into a fresh one (the identity
    sketch when empty — callers supply alpha for that case)."""
    it = iter(sketches)
    first = next(it, None)
    if first is None:
        return DDSketch(alpha if alpha is not None else DEFAULT_ALPHA)
    out = first.copy()
    for s in it:
        out.merge(s)
    return out
