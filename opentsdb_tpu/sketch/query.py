"""Sketch-backed percentile queries over regular (scalar) metrics.

Before the fifth stat column existed, ``percentiles`` on a scalar
metric answered [] (no histogram arenas) and demoted/cold history had
no percentile story at all — the stat columns keep sum/count/min/max
only. This path serves ``sub.percentiles`` from quantile sketches
merged across the stitched three-way read
(:func:`opentsdb_tpu.lifecycle.stitch.sketch_zone_read`):

- cold segment sketch blobs for ``[start, spill_b)``,
- the in-RAM sketch tier for ``[spill_b, demote_b)``,
- a vectorized fold of the raw tail for ``[demote_b, end]``.

Semantics match the histogram percentile path: per (group, time
bucket), the POPULATION percentile of every point the bucket covers,
emitted as ``{metric}_pct_{q:g}`` rows. Accuracy: raw-tail buckets are
sketch-exact over the points (within the DDSketch alpha bound of the
exact order statistic); demoted/cold buckets answer from cells folded
at demotion time — same bound, over the same points the tier cells
aggregate.

``partials=True`` (the cluster scatter) skips quantile extraction and
returns one row per group carrying the serialized per-bucket sketches;
the router merges shard partials exactly (canonical DDSketch state is
merge-order independent, so the merged result is bit-equal to a
single node folding all shards' points) and extracts quantiles once.

Histogram metrics take the arena engine for live windows; their
spilled history (arena rows converted to sketches on spill) comes
back through the cold zone here and the engine splices the two row
sets — see :func:`merge_pct_rows`.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.query.model import BadRequestError, TSQuery, TSSubQuery
from opentsdb_tpu.sketch.ddsketch import DDSketch, SketchError


def _config_sketch(tsdb) -> tuple[bool, float, int]:
    cfg = tsdb.config
    return (cfg.get_bool("tsd.sketch.enable", True),
            cfg.get_float("tsd.sketch.alpha", 0.01),
            cfg.get_int("tsd.sketch.max_buckets", 4096))


def documented_alpha(tsdb) -> float:
    """The sketch's documented relative-error bound (config alpha)."""
    return _config_sketch(tsdb)[1]


def _bucket_of(ts: np.ndarray, tsq: TSQuery, sub: TSSubQuery
               ) -> tuple[np.ndarray, np.ndarray]:
    """(slot_ts[N], in_range[N]): output bucket timestamp per input
    timestamp — downsample buckets when the sub has a ds spec (the
    histogram engine's time-axis rule), else the timestamp itself."""
    ts = np.asarray(ts, dtype=np.int64)
    if sub.ds_spec is None or not len(ts):
        return ts, np.ones(len(ts), dtype=bool)
    from opentsdb_tpu.ops import downsample as ds_mod
    bidx, bts = ds_mod.assign_buckets(ts, sub.ds_spec, tsq.start_ms,
                                      tsq.end_ms)
    bidx = np.asarray(bidx)
    bts = np.asarray(bts, dtype=np.int64)
    ok = (bidx >= 0) & (bidx < len(bts))
    return bts[np.clip(bidx, 0, max(len(bts) - 1, 0))], ok


def _names_of_sids(store, uids, sids) -> dict[tuple, int]:
    """tag-NAMES tuple -> position in ``sids`` (the identity cold
    segments and sketch cells key by). Unresolvable series are
    skipped — their cells can't be attributed anyway."""
    out: dict[tuple, int] = {}
    for i, sid in enumerate(np.asarray(sids).tolist()):
        rec = store.series(int(sid))
        try:
            names = tuple(sorted((uids.tag_names.get_name(k),
                                  uids.tag_values.get_name(v))
                                 for k, v in rec.tags))
        except LookupError:
            continue
        out[names] = i
    return out


def run_sketch_percentiles(tsdb, tsq: TSQuery, sub: TSSubQuery,
                           partials: bool = False) -> list | None:
    """Serve one percentile sub-query from sketches. Returns None when
    the sketch path is disabled (``tsd.sketch.enable = false``) — the
    caller keeps the pre-sketch behavior — else a (possibly empty)
    list of QueryResults."""
    enabled, alpha, max_buckets = _config_sketch(tsdb)
    if not enabled:
        return None
    uids = tsdb.uids
    try:
        mid = uids.metrics.get_id(sub.metric)
    except LookupError:
        raise BadRequestError(
            f"No such name for 'metrics': '{sub.metric}'") from None
    hsids = tsdb.histogram_store.series_ids_for_metric(mid)
    if len(hsids):
        return _run_over_store(tsdb, tsq, sub, tsdb.histogram_store,
                               mid, alpha, max_buckets, partials,
                               hist=True)
    return _run_over_store(tsdb, tsq, sub, tsdb.store, mid, alpha,
                           max_buckets, partials, hist=False)


def _run_over_store(tsdb, tsq, sub, store, mid, alpha, max_buckets,
                    partials, hist):
    from opentsdb_tpu.query.engine import QueryEngine, TagMatrix
    from opentsdb_tpu.query.filters import FilterEvaluator
    uids = tsdb.uids
    sids = store.series_ids_for_metric(mid)
    if len(sids) == 0:
        return []
    idx = store.metric_index(mid)
    _, triples = idx.arrays()
    tag_mat = TagMatrix.from_triples(sids, triples)
    if sub.filters:
        mask = FilterEvaluator(uids).apply(sub.filters, sids, triples)
        sids = sids[mask]
        tag_mat = tag_mat.select(mask)
        if len(sids) == 0:
            return []
    gb_kids = sorted({uids.tag_names.get_id(f.tagk)
                      for f in sub.filters if f.group_by
                      and uids.tag_names.has_name(f.tagk)})
    group_ids, num_groups = QueryEngine._group_ids(tag_mat, gb_kids)
    gvec = np.asarray(group_ids, dtype=np.int64)

    # ---- gather the three zones as (sid_pos, cell_ts, sketch) ------
    if hist:
        items, raw_rng, cold_ok = _hist_zones(tsdb, tsq, sub, mid,
                                              alpha, max_buckets,
                                              partials)
    else:
        from opentsdb_tpu.lifecycle.stitch import sketch_zone_read
        items, raw_rng, cold_ok = sketch_zone_read(
            tsdb, sub.metric, mid, tsq.start_ms, tsq.end_ms)

    # (group, output bucket) accumulators
    acc: dict[tuple[int, int], DDSketch] = {}

    def _fold_in(gid: int, slot: int, sk: DDSketch) -> None:
        cur = acc.get((gid, slot))
        if cur is None:
            acc[(gid, slot)] = sk
        else:
            try:
                cur.merge(sk)
            except SketchError:
                pass  # alpha changed under old cells: skip, serve rest

    if items:
        pos_of = _names_of_sids(store, uids, sids)
        cell_ts = np.asarray([c[1] for c in items], dtype=np.int64)
        slots, ok = _bucket_of(cell_ts, tsq, sub)
        for j, (tags, _cts, sk) in enumerate(items):
            i = pos_of.get(tuple(tags))
            if i is None or not ok[j]:
                continue  # filtered out, or out of the bucket grid
            _fold_in(int(gvec[i]), int(slots[j]), sk)

    if raw_rng is not None and not hist:
        from opentsdb_tpu.ops import sketch_fold
        batch = tsdb.store.materialize(sids, raw_rng[0], raw_rng[1])
        if batch.num_points:
            slots, ok = _bucket_of(batch.ts_ms, tsq, sub)
            sidx = np.asarray(batch.series_idx, dtype=np.int64)
            vals = np.asarray(batch.values, dtype=np.float64)
            if not ok.all():
                sidx, slots, vals = sidx[ok], slots[ok], vals[ok]
            folded = sketch_fold.fold_series_cells(
                gvec[sidx], slots, vals, 1, alpha, max_buckets)
            for (gid, slot), sk in folded.items():
                _fold_in(int(gid), int(slot), sk)

    if not acc:
        return []
    return _emit(tsdb, tsq, sub, tag_mat, group_ids, num_groups, acc,
                 partials, cold_ok)


def _hist_zones(tsdb, tsq, sub, mid, alpha, max_buckets, partials):
    """Zones for a histogram metric: cold sketch rows (the arena
    spill's output) plus — in partials mode only — the live arena
    rows converted through bucket midpoints (the same convention
    ``percentiles_from_counts`` extracts with), so a shard can hand
    the router mergeable partials. Batch (non-partials) queries serve
    live arenas through the exact arena engine instead."""
    from opentsdb_tpu.lifecycle.stitch import guarded_sketch_rows
    lc = tsdb.lifecycle
    cold = getattr(lc, "coldstore", None) if lc is not None else None
    spill_b = cold.spill_boundary(
        tsdb.uids.metrics.get_name(mid)) if cold is not None else 0
    items: list = []
    cold_ok = True
    if cold is not None and spill_b and tsq.start_ms < spill_b:
        rows, cold_ok = guarded_sketch_rows(
            cold, sub.metric, tsq.start_ms,
            min(tsq.end_ms, spill_b - 1))
        for tags, cts, blob in rows:
            try:
                items.append((tags, cts, DDSketch.from_bytes(blob)))
            except (SketchError, ValueError):
                cold_ok = False
    if partials:
        items.extend(arena_sketch_items(
            tsdb, mid, max(tsq.start_ms, spill_b), tsq.end_ms, alpha,
            max_buckets))
    return items, None, cold_ok


def arena_sketch_items(tsdb, mid: int, start_ms: int, end_ms: int,
                       alpha: float, max_buckets: int) -> list:
    """Live histogram arena rows as ``(tags_names, ts, DDSketch)``:
    each row's bucket counts fold at the bucket midpoints (the value
    ``percentiles_from_counts`` would emit for any rank landing in the
    bucket), so extraction from the sketch answers within alpha of the
    arena engine's midpoint convention."""
    if start_ms > end_ms:
        return []
    with tsdb._histogram_lock:
        arena = tsdb._histogram_arenas.get(mid)
        snaps = [(s.bounds, *s.snapshot())
                 for s in arena.groups.values()] if arena else []
    if not snaps:
        return []
    uids = tsdb.uids
    store = tsdb.histogram_store
    names_of: dict[int, tuple | None] = {}
    out = []
    for bounds, ts_a, sid_a, rows in snaps:
        b = np.asarray(bounds, dtype=np.float64)
        mids = (b[:-1] + b[1:]) / 2.0
        m = (ts_a >= start_ms) & (ts_a <= end_ms)
        if not m.any():
            continue
        for ts, sid, counts in zip(ts_a[m].tolist(),
                                   sid_a[m].tolist(),
                                   np.asarray(rows)[m]):
            if sid not in names_of:
                try:
                    rec = store.series(int(sid))
                    names_of[sid] = tuple(sorted(
                        (uids.tag_names.get_name(k),
                         uids.tag_values.get_name(v))
                        for k, v in rec.tags))
                except LookupError:
                    names_of[sid] = None
            names = names_of[sid]
            if names is None:
                continue
            sk = DDSketch(alpha)
            sk.add_weighted(mids, counts)
            if max_buckets:
                sk.collapse(max_buckets)
            if sk.count:
                out.append((names, int(ts), sk))
    return out


def _emit(tsdb, tsq, sub, tag_mat, group_ids, num_groups, acc,
          partials, cold_ok):
    from opentsdb_tpu.query.engine import QueryResult, _common_tags
    uids = tsdb.uids
    order = np.argsort(group_ids, kind="stable")
    sorted_gids = np.asarray(group_ids)[order]
    gid_range = np.arange(num_groups,
                          dtype=np.asarray(group_ids).dtype)
    starts = np.searchsorted(sorted_gids, gid_range, side="left")
    ends = np.searchsorted(sorted_gids, gid_range, side="right")
    by_gid: dict[int, list[tuple[int, DDSketch]]] = {}
    for (gid, slot), sk in acc.items():
        by_gid.setdefault(gid, []).append((slot, sk))
    out = []
    for gid in range(num_groups):
        slots = by_gid.get(gid)
        if not slots:
            continue
        members = order[starts[gid]:ends[gid]]
        if len(members) == 0:
            continue
        slots.sort(key=lambda p: p[0])
        tags, agg_tags = _common_tags(tag_mat, members, uids)
        if partials:
            r = QueryResult(metric=sub.metric, tags=tags,
                            aggregated_tags=agg_tags, dps=[],
                            sub_query_index=sub.index)
            r.sketches = [(t, sk.to_bytes()) for t, sk in slots]
            out.append(r)
            continue
        ts_list = [t if tsq.ms_resolution else (t // 1000) * 1000
                   for t, _ in slots]
        for q in sub.percentiles:
            dps = [(ts_list[k], float(sk.quantile(q)))
                   for k, (_t, sk) in enumerate(slots)]
            out.append(QueryResult(
                metric=f"{sub.metric}_pct_{q:g}", tags=tags,
                aggregated_tags=agg_tags, dps=dps,
                sub_query_index=sub.index))
    return out


def merge_pct_rows(a: list, b: list) -> list:
    """Splice two percentile row sets covering disjoint time windows
    (live arena rows + spilled-history sketch rows) by (metric, tags,
    sub index): dps concatenate and re-sort; rows unique to either
    side pass through. Later values win exact-timestamp collisions
    (live data over spilled history — only possible mid-sweep)."""
    if not a:
        return b
    if not b:
        return a
    keyed: dict[tuple, object] = {}
    out = []
    for r in a:
        key = (r.metric, tuple(sorted(r.tags.items())),
               r.sub_query_index)
        keyed[key] = r
        out.append(r)
    for r in b:
        key = (r.metric, tuple(sorted(r.tags.items())),
               r.sub_query_index)
        cur = keyed.get(key)
        if cur is None:
            keyed[key] = r
            out.append(r)
            continue
        merged = dict(cur.dps)
        merged.update(dict(r.dps))
        cur.dps = sorted(merged.items())
    return out
