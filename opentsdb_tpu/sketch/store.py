"""In-RAM sketch tier: per-cell quantile sketches of demoted raw data.

The fifth stat column's middle zone. Lifecycle demotion folds the raw
points it is about to purge into per-(series, cell) sketches here
(cells at the metric's finest demote-tier interval); the spill moves
cells below the spill boundary into the cold segment's sketch blob
column and drops them from RAM; the query path merges the three zones
(cold blobs, these cells, a raw-tail fold) per group and bucket.

Keys are metric NAME + sorted tag name pairs — stable across restarts
(same rule as ``lifecycle.json``), so the sidecar persistence file
(``sketches.bin``, JSON with base64 sketch blobs, atomic replace)
reloads cleanly into a fresh process. Persistence is written by the
sweeper *before* it purges the raw points a fold covered — the same
durable-first ordering the spill uses.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import threading

from opentsdb_tpu.sketch.ddsketch import DDSketch

LOG = logging.getLogger("sketch.store")

_FILE_VERSION = 1


class SketchTierStore:
    """Holds ``metric name -> {tags: {cell_ts: DDSketch}}`` plus the
    metric's cell width. All access is under one lock — folds happen
    on the sweeper, reads snapshot lists out."""

    def __init__(self, path: str = "", alpha: float = 0.01,
                 max_buckets: int = 4096):
        self.path = path
        self.alpha = float(alpha)
        self.max_buckets = int(max_buckets)
        self._lock = threading.Lock()
        # tsdlint: allow[unbounded-growth] keyed by policied metric
        # name; cells are bounded below by the spill boundary (spill
        # moves them to disk) and above by the demote boundary
        self._metrics: dict[str, dict] = {}
        # counters (stats surface)
        self.points_folded = 0
        self.cells_folded = 0
        self.cells_spilled = 0
        self.save_errors = 0

    # ------------------------------------------------------------------
    # fold side (lifecycle sweeper)
    # ------------------------------------------------------------------

    def merge_cells(self, metric: str, cell_ms: int, items) -> int:
        """Merge ``(tags_names_tuple, cell_ts, DDSketch)`` items into
        the metric's cells (exact DDSketch merge on collision).
        Returns cells touched."""
        n = 0
        with self._lock:
            ent = self._metrics.setdefault(
                metric, {"cell_ms": int(cell_ms), "series": {}})
            ent["cell_ms"] = int(cell_ms)
            series = ent["series"]
            for tags, cell_ts, sk in items:
                cells = series.setdefault(tuple(tags), {})
                cur = cells.get(int(cell_ts))
                if cur is None:
                    cells[int(cell_ts)] = sk
                else:
                    cur.merge(sk)
                self.points_folded += int(sk.count)
                n += 1
            self.cells_folded += n
        return n

    # ------------------------------------------------------------------
    # read side (query path / spill)
    # ------------------------------------------------------------------

    def cell_ms(self, metric: str) -> int:
        with self._lock:
            ent = self._metrics.get(metric)
            return int(ent["cell_ms"]) if ent else 0

    def cells(self, metric: str, start_ms: int, end_ms: int
              ) -> list[tuple[tuple, int, DDSketch]]:
        """Snapshot of ``(tags, cell_ts, sketch-copy)`` rows whose
        cell_ts falls in [start_ms, end_ms]. Copies so callers merge
        freely without mutating the store."""
        out = []
        with self._lock:
            ent = self._metrics.get(metric)
            if not ent:
                return out
            for tags, cells in ent["series"].items():
                for cts, sk in cells.items():
                    if start_ms <= cts <= end_ms:
                        out.append((tags, cts, sk.copy()))
        return out

    def blob_for(self, metric: str, tags, cell_ts: int
                 ) -> bytes | None:
        with self._lock:
            ent = self._metrics.get(metric)
            if not ent:
                return None
            cells = ent["series"].get(tuple(tags))
            if not cells:
                return None
            sk = cells.get(int(cell_ts))
            return sk.to_bytes() if sk is not None else None

    def has_cells(self, metric: str) -> bool:
        with self._lock:
            ent = self._metrics.get(metric)
            return bool(ent and any(ent["series"].values()))

    # ------------------------------------------------------------------
    # purge side
    # ------------------------------------------------------------------

    def delete_before(self, metric: str, cutoff_ms: int,
                      spilled: bool = False) -> int:
        """Drop cells whose WHOLE window [T, T+cell_ms) sits before
        ``cutoff_ms`` — the tier purge's cell-window rule. ``spilled``
        attributes the drop to a spill (counted separately) rather
        than retention."""
        dropped = 0
        with self._lock:
            ent = self._metrics.get(metric)
            if not ent:
                return 0
            iv = int(ent["cell_ms"])
            for tags in list(ent["series"]):
                cells = ent["series"][tags]
                dead = [t for t in cells if t + iv <= cutoff_ms]
                for t in dead:
                    del cells[t]
                dropped += len(dead)
                if not cells:
                    del ent["series"][tags]
            if not ent["series"]:
                del self._metrics[metric]
        if spilled:
            self.cells_spilled += dropped
        return dropped

    # ------------------------------------------------------------------
    # persistence (sidecar file, atomic replace)
    # ------------------------------------------------------------------

    def save(self) -> None:
        """Best-effort atomic persist — a failed save means the cells
        folded since the last good save are re-derived only if their
        raw points still exist; the sweeper therefore saves BEFORE it
        purges raw."""
        if not self.path:
            return
        with self._lock:
            doc = {"version": _FILE_VERSION, "metrics": {
                metric: {
                    "cell_ms": ent["cell_ms"],
                    "series": [
                        {"tags": [list(p) for p in tags],
                         "cells": [[cts, base64.b64encode(
                             sk.to_bytes()).decode("ascii")]
                            for cts, sk in sorted(cells.items())]}
                        for tags, cells in sorted(
                            ent["series"].items())],
                } for metric, ent in self._metrics.items()}}
        try:
            from opentsdb_tpu.core.persist import _atomic_write
            _atomic_write(self.path,
                          json.dumps(doc,
                                     separators=(",", ":")).encode())
        except OSError as exc:  # pragma: no cover - disk trouble
            self.save_errors += 1
            LOG.warning("could not persist sketch cells: %s", exc)

    def load(self) -> None:
        if not self.path or not os.path.isfile(self.path):
            return
        try:
            with open(self.path, encoding="utf-8") as fh:
                doc = json.load(fh)
            metrics = {}
            for metric, ent in (doc.get("metrics") or {}).items():
                series = {}
                for srow in ent.get("series", ()):
                    tags = tuple(tuple(p) for p in srow["tags"])
                    series[tags] = {
                        int(cts): DDSketch.from_b64(b64)
                        for cts, b64 in srow.get("cells", ())}
                metrics[metric] = {"cell_ms": int(ent["cell_ms"]),
                                   "series": series}
        except (OSError, ValueError, KeyError) as exc:
            LOG.warning("could not load sketch cells from %s: %s",
                        self.path, exc)
            return
        with self._lock:
            self._metrics = metrics

    # ------------------------------------------------------------------
    # stats
    # ------------------------------------------------------------------

    def describe(self) -> dict:
        with self._lock:
            cells = sum(len(c) for ent in self._metrics.values()
                        for c in ent["series"].values())
            return {"metrics": len(self._metrics), "cells": cells,
                    "pointsFolded": self.points_folded,
                    "cellsFolded": self.cells_folded,
                    "cellsSpilled": self.cells_spilled,
                    "saveErrors": self.save_errors}
