"""Observability (ref: ``src/stats/``).

- :class:`StatsCollector` — push-style visitor every component implements
  ``collect_stats(collector)`` against (ref: StatsCollector.java:35).
- :class:`Histogram` — fixed-bucket latency histogram with percentile
  extraction (ref: src/stats/Histogram.java:38).
- :class:`QueryStats` — per-query trace threaded through the read path,
  with a registry of running/completed queries for ``/api/stats/query``
  (ref: src/stats/QueryStats.java:58).
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from enum import Enum
from typing import Any


class DuplicateQueryError(ValueError):
    """An identical query is already in flight from the same endpoint
    and ``tsd.query.allow_simultaneous_duplicates`` is off (ref:
    QueryException from QueryStats.java:263)."""


class StatsCollector:
    """(ref: StatsCollector.java:35) Collects ``name value tags`` records."""

    def __init__(self, prefix: str = "tsd"):
        self.prefix = prefix
        # tsdlint: allow[unbounded-growth] one collector per stats
        # snapshot — it lives for a single collect() pass
        self.records: list[tuple[str, float, dict[str, str]]] = []
        self._extra_tags: dict[str, str] = {}

    def add_extra_tag(self, key: str, value: str) -> None:
        self._extra_tags[key] = value

    def clear_extra_tag(self, key: str) -> None:
        self._extra_tags.pop(key, None)

    def record(self, name: str, value: float, **tags: str) -> None:
        all_tags = dict(self._extra_tags)
        all_tags.update({k: str(v) for k, v in tags.items()})
        self.records.append((f"{self.prefix}.{name}", float(value), all_tags))

    def lines(self) -> list[str]:
        """Telnet ``stats`` output format: ``name timestamp value k=v ...``"""
        now = int(time.time())
        out = []
        for name, value, tags in self.records:
            tag_str = " ".join(f"{k}={v}" for k, v in sorted(tags.items()))
            val = int(value) if float(value).is_integer() else value
            out.append(f"{name} {now} {val}"
                       + (f" {tag_str}" if tag_str else ""))
        return out

    def as_json(self) -> list[dict[str, Any]]:
        now = int(time.time())
        return [{"metric": name, "timestamp": now, "value": value,
                 "tags": tags} for name, value, tags in self.records]


#: percentile points exported for every latency histogram
LATENCY_PCTS = (("p50", 50.0), ("p95", 95.0), ("p99", 99.0),
                ("p999", 99.9))


class StatsCollectorRegistry:
    """Aggregates collect_stats providers; owned by the TSDB.

    Also owns the latency histograms: the request-level
    ``latency_put``/``latency_query`` pair (fed by the server per
    request) and the per-STAGE map fed by the tracer for every traced
    request (``wal.commit_wait``, ``query.execute``,
    ``cluster.merge``, ``query.serialize``, ... — one histogram per
    registered span name that actually fires). All export
    p50/p95/p99/p999 at ``/api/stats`` (``tsd.latency.*``) and
    ``/api/health``."""

    def __init__(self) -> None:
        # tsdlint: allow[unbounded-growth] one registration per
        # component at construction — bounded by the component count
        self._providers: list[Any] = []
        # 1ms linear buckets (not the reference's 100ms): these now
        # EXPORT percentiles, and a bucket-upper-bound percentile
        # over 100ms buckets would report p50=100 for every
        # single-digit-ms workload — a 30x misreading
        self.latency_put = Histogram(16000, 2, 1)
        self.latency_query = Histogram(16000, 2, 1)
        self._stage_lock = threading.Lock()
        # tsdlint: allow[unbounded-growth] keyed by span stage name —
        # the CLOSED obs.trace.KNOWN_SPANS registry (runtime-raised
        # and tsdlint-gated), so the keyspace cannot grow unchecked
        self.stage_latency: dict[str, Histogram] = {}

    def register(self, provider: Any) -> None:
        self._providers.append(provider)

    def observe_stage(self, stage: str, ms: float) -> None:
        """Record one stage latency (ms). Histograms are created on
        first observation; the population is bounded by the closed
        span-name registry (obs/trace.py KNOWN_SPANS)."""
        h = self.stage_latency.get(stage)
        if h is None:
            with self._stage_lock:
                h = self.stage_latency.setdefault(
                    stage, Histogram(16000, 2, 1))
        h.add(ms)

    def _stage_snapshot(self) -> dict[str, Histogram]:
        """Iteration-safe copy: observe_stage inserts first-seen
        stages concurrently, and iterating the live dict would raise
        'dictionary changed size during iteration' mid-/api/stats."""
        with self._stage_lock:
            return dict(self.stage_latency)

    def latency_summary(self) -> dict[str, Any]:
        """Percentile summaries for /api/health."""
        out: dict[str, Any] = {
            "put": self.latency_put.percentiles(),
            "query": self.latency_query.percentiles(),
        }
        stages = {}
        for name, h in sorted(self._stage_snapshot().items()):
            if h.count:
                stages[name] = h.percentiles()
        out["stages"] = stages
        return out

    def histograms(self) -> "list[tuple[str, dict[str, str], Histogram]]":
        """Every histogram this registry owns, with its exposition
        identity ``(family name, labels, histogram)`` — the ONE
        enumeration the ``/metrics`` renderer and the per-node
        ``/api/stats/raw`` fleet-merge source both walk (tsdlint's
        ``histogram-export`` pass checks that every ``Histogram``
        constructed in the package is reachable from here or from the
        renderer directly)."""
        out: list[tuple[str, dict[str, str], Histogram]] = [
            ("tsd_request_latency_ms", {"op": "put"},
             self.latency_put),
            ("tsd_request_latency_ms", {"op": "query"},
             self.latency_query),
        ]
        # direct load (not via _stage_snapshot): the histogram-export
        # pass proves reachability lexically, and this method IS the
        # reachability evidence for the stage registry
        with self._stage_lock:
            stages = dict(self.stage_latency)
        for stage, h in sorted(stages.items()):
            out.append(("tsd_stage_latency_ms", {"stage": stage}, h))
        return out

    def collect(self, prefix: str = "tsd",
                latency_percentiles: bool = True) -> StatsCollector:
        collector = StatsCollector(prefix)
        for p in self._providers:
            p.collect_stats(collector)
        if not latency_percentiles:
            # the /metrics renderer serves the SAME histograms in
            # native cumulative-bucket form — percentile records
            # would double-export them under a second name
            return collector
        # latency percentiles ride the same record stream so
        # /api/stats, telnet `stats` and the self-telemetry pump all
        # see them without extra plumbing
        named = [("latency.put", self.latency_put),
                 ("latency.query", self.latency_query)]
        named += [(f"latency.{name}", h)
                  for name, h in sorted(
                      self._stage_snapshot().items())]
        for name, hist in named:
            if not hist.count:
                continue
            vals = hist.percentile_many(
                [q for _l, q in LATENCY_PCTS])
            for (label, _q), v in zip(LATENCY_PCTS, vals):
                collector.record(name, v, pct=label)
            collector.record(f"{name}.count", hist.count)
        return collector


class Histogram:
    """Exponentially-bucketed histogram (ref: src/stats/Histogram.java:38).

    Buckets are linear (width ``interval``) up to ``cutoff``, then double
    per bucket — same shape as the reference's constructor
    ``Histogram(max, num_linear? , interval)`` usage for latencies.
    """

    def __init__(self, max_value: int = 16000, num_bands: int = 2,
                 interval: int = 100):
        self.interval = interval
        self.max_value = max_value
        n_linear = max(1, (max_value // (2 ** (num_bands - 1))) // interval)
        self.bounds: list[int] = [interval * (i + 1) for i in range(n_linear)]
        while self.bounds[-1] < max_value:
            self.bounds.append(min(self.bounds[-1] * 2, max_value))
        self.buckets = [0] * (len(self.bounds) + 1)
        self.count = 0
        # running sum of observed values: the OpenMetrics ``_sum``
        # series — fleet merges add sums like they add bucket counts
        self.sum = 0.0
        self._lock = threading.Lock()
        # companion quantile sketch: relative-error percentiles that
        # merge across nodes even when bucket tables differ — the
        # fleet merge's escape hatch for mixed-build fleets (see
        # cluster/fleet.py). Rides the snapshot as a base64 field.
        from opentsdb_tpu.sketch.ddsketch import DDSketch
        self._sketch = DDSketch()

    def add(self, value: float) -> None:
        # bisect_left: first bound >= value, i.e. the first bucket
        # whose `value <= bound` test passes — identical placement to
        # a linear scan at O(log n) per observation
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.buckets[min(idx, len(self.buckets) - 1)] += 1
            self.count += 1
            self.sum += value
            self._sketch.add(value)

    def snapshot(self) -> dict[str, Any]:
        """Consistent copy of the raw state — the wire form the
        ``/metrics`` renderer and the fleet bucket-merge consume
        (bounds are construction-time constants; counts/sum are read
        under the lock so a snapshot is never torn mid-``add``)."""
        with self._lock:
            return {"bounds": list(self.bounds),
                    "buckets": list(self.buckets),
                    "count": self.count, "sum": self.sum,
                    "sketch": self._sketch.to_b64()}

    def percentile(self, pct: float) -> float:
        """(ref: Histogram.percentile)"""
        if not 0 < pct <= 100:
            raise ValueError(f"invalid percentile {pct}")
        return self.percentile_many([pct])[0]

    def percentile_many(self, pcts: "list[float]") -> "list[float]":
        """All requested percentiles from ONE cumulative pass over a
        snapshot of the buckets — the scan runs OUTSIDE the lock (a
        stats/health collection walking thousands of 1ms buckets
        per-percentile under the lock would repeatedly block
        hot-path ``add()`` calls)."""
        with self._lock:
            count = self.count
            buckets = list(self.buckets)  # C-level copy
        return percentiles_from_buckets(self.bounds, buckets, count,
                                        pcts)

    def percentiles(self) -> dict[str, float]:
        """The standard export points + the sample count."""
        vals = self.percentile_many([q for _l, q in LATENCY_PCTS])
        out = {label: v for (label, _q), v in zip(LATENCY_PCTS, vals)}
        out["count"] = self.count
        return out

    def print_ascii(self) -> str:
        lines = []
        lo = 0
        for i, c in enumerate(self.buckets[:-1]):
            lines.append(f"[{lo}-{self.bounds[i]}): {c}")
            lo = self.bounds[i]
        lines.append(f"[{lo}-inf): {self.buckets[-1]}")
        return "\n".join(lines)


def percentiles_from_buckets(bounds: "list[int]", buckets: "list[int]",
                             count: int,
                             pcts: "list[float]") -> "list[float]":
    """Bucket-upper-bound percentiles in one cumulative pass — shared
    by :meth:`Histogram.percentile_many` and the fleet bucket-merge,
    so a fleet percentile over summed buckets is BIT-identical to the
    same observations landing in one histogram (both read the same
    bound for the same cumulative rank)."""
    if count == 0:
        return [0.0] * len(pcts)
    targets = sorted((count * p / 100.0, j) for j, p in enumerate(pcts))
    out = [0.0] * len(pcts)
    acc = 0
    t = 0
    last_bound = len(bounds) - 1
    for i, c in enumerate(buckets):
        acc += c
        while t < len(targets) and acc >= targets[t][0]:
            out[targets[t][1]] = float(bounds[min(i, last_bound)])
            t += 1
        if t >= len(targets):
            break
    for k in range(t, len(targets)):
        out[targets[k][1]] = float(bounds[-1])
    return out


def merge_histogram_snapshots(snaps: "list[dict]") -> "dict | None":
    """Element-wise bucket/count/sum merge of :meth:`Histogram.
    snapshot` documents sharing one bound table (every histogram in
    the package uses the same 1ms construction, so per-shard
    snapshots of the same stage always merge). Returns None on an
    empty list or mismatched bounds — the caller reports the node
    instead of producing a silently wrong distribution."""
    merged: dict | None = None
    for s in snaps:
        bounds = s.get("bounds")
        buckets = s.get("buckets")
        if not isinstance(bounds, list) or not isinstance(
                buckets, list) or len(buckets) != len(bounds) + 1:
            return None
        if merged is None:
            merged = {"bounds": list(bounds),
                      "buckets": list(buckets),
                      "count": int(s.get("count", 0)),
                      "sum": float(s.get("sum", 0.0))}
            continue
        if bounds != merged["bounds"]:
            return None
        mb = merged["buckets"]
        for i, c in enumerate(buckets):
            mb[i] += int(c)
        merged["count"] += int(s.get("count", 0))
        merged["sum"] += float(s.get("sum", 0.0))
    return merged


# ---------------------------------------------------------------------------
# counter-vs-gauge classification (exposition + fleet merge)
# ---------------------------------------------------------------------------
# The push-style record stream carries no type information, so the
# OpenMetrics renderer and the fleet aggregator share one advisory
# classification: a GAUGE is a point-in-time level (summing it across
# nodes or scrapes is meaningless); everything else is a monotonic
# counter. Exact names first, then substring markers for the families
# (`*_bytes`, `*pending*`, ...) the codebase consistently uses for
# levels. Misclassification is cosmetic for Prometheus (TYPE line);
# for fleet merges it decides sum-vs-min/max presentation only.

_GAUGE_NAMES: frozenset[str] = frozenset({
    "admission.inflight",
    "cluster.epoch",
    "cluster.rf",
    "datapoints.memory",
    "uptime.seconds",
    "wal.sync_lag",          # records not yet fsynced: a level
    "wal.records_per_sync",  # a ratio, not a count
    "wal.degraded",          # 0/1 flag
})

_GAUGE_MARKERS: tuple[str, ...] = (
    "_bytes", ".bytes", "pending", "backlog", "depth",
    "inflight", "entries", "resident", "uptime",
    ".lag", "_size", ".size", "open_", ".open", "_open", "queue",
    "interval", "cache-size", "burn_rate",
)


def is_gauge(name: str) -> bool:
    """Advisory: True when the record named ``name`` (without the
    collector prefix) reads as a level rather than a monotonic
    count. A ``*_total``/``*.total`` name is a counter no matter
    what substring it also contains (``query.payload.bytes_total``
    is a monotonic byte count, not a level)."""
    if name.endswith("_total") or name.endswith(".total"):
        return False
    if name in _GAUGE_NAMES:
        return True
    return any(m in name for m in _GAUGE_MARKERS)


class QueryStat(Enum):
    """Stat points recorded along the read path
    (ref: QueryStats.java QueryStat enum :132)."""
    COMPILATION_TIME = "compilationTime"
    UID_TO_STRING_TIME = "uidToStringTime"
    STRING_TO_UID_TIME = "stringToUidTime"
    SCANNER_TIME = "scannerTime"
    SCANNER_UID_TO_STRING_TIME = "scannerUidToStringTime"
    MATERIALIZE_TIME = "materializeTime"
    DEVICE_TRANSFER_TIME = "deviceTransferTime"
    COMPUTE_TIME = "computeTime"
    AGGREGATION_TIME = "aggregationTime"
    GROUP_BY_TIME = "groupByTime"
    SERIALIZATION_TIME = "serializationTime"
    TOTAL_TIME = "totalTime"
    ROWS_SCANNED = "rowsScanned"
    DPS_PRE_FILTER = "dpsPreFilter"
    DPS_POST_FILTER = "dpsPostFilter"
    EMITTED_DPS = "emittedDPs"
    MAX_HBM_BYTES = "maxHbmBytes"
    # storage stats — TPU mapping: "storage" is the host column store,
    # a column ≙ a stored point, a row ≙ a series
    COLUMNS_FROM_STORAGE = "columnsFromStorage"
    ROWS_FROM_STORAGE = "rowsFromStorage"
    BYTES_FROM_STORAGE = "bytesFromStorage"
    SUCCESSFUL_SCAN = "successfulScan"
    ROWS_PRE_FILTER = "rowsPreFilter"
    ROWS_POST_FILTER = "rowsPostFilter"
    COMPACTION_TIME = "compactionTime"      # lazy sort/dedupe (N/A: 0)
    HBASE_TIME = "hbaseTime"                # storage engine wait
    UID_PAIRS_RESOLVED = "uidPairsResolved"
    SCANNER_MERGE_TIME = "saltScannerMergeTime"
    QUERY_SCAN_TIME = "queryScanTime"
    NAN_DPS = "nanDPs"
    PROCESSING_PRE_WRITE_TIME = "processingPreWriteTime"
    # serve-path result cache outcomes (no reference equivalent: the
    # reference's graph cache lives outside QueryStats entirely)
    RESULT_CACHE_HIT = "resultCacheHit"
    RESULT_CACHE_COALESCED = "resultCacheCoalesced"
    # served from a continuous query's maintained live windows
    # (opentsdb_tpu/streaming/) — no store scan, tail-only compute
    STREAMING_HIT = "streamingHit"
    # serve-path payload observability: response body bytes actually
    # written for this query (materialized or streamed), and the
    # pixel budget its output was reduced under (0 = full resolution)
    PAYLOAD_BYTES = "payloadBytes"
    DOWNSAMPLE_PIXELS = "downsamplePixels"


# time-based stats that get the reference's derived max*/avg* twins in
# /api/stats/query output (one logical scanner here, so max == avg ==
# the base value; consumers of the reference's schema still find them)
_DERIVED_TIMES = {
    "hbaseTime": ("maxHBaseTime", "avgHBaseTime"),
    "scannerTime": ("maxScannerTime", "avgScannerTime"),
    "uidToStringTime": ("maxUidToStringTime", "avgUidToStringTime"),
    "compactionTime": ("maxCompactionTime", "avgCompactionTime"),
    "scannerUidToStringTime": ("maxScannerUidtoStringTime",
                               "avgScannerUidToStringTime"),
    "saltScannerMergeTime": ("maxSaltScannerMergeTime",
                             "avgSaltScannerMergeTime"),
    "queryScanTime": ("maxQueryScanTime", "avgQueryScanTime"),
    "aggregationTime": ("maxAggregationTime", "avgAggregationTime"),
    "serializationTime": ("maxSerializationTime",
                          "avgSerializationTime"),
}


class ServePayloadStats:
    """Aggregate serve-path payload counters: total response bytes,
    serialization milliseconds and response count across every
    /api/query answered by this process, so the wire-size effect of
    pixel-aware downsampling is measurable in production (not just in
    bench) — exported at ``/api/stats`` and ``/api/health``."""

    __slots__ = ("_lock", "payload_bytes", "serialization_ms",
                 "responses", "pixel_responses")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.payload_bytes = 0
        self.serialization_ms = 0.0
        self.responses = 0
        self.pixel_responses = 0

    def record(self, nbytes: int, ser_ms: float,
               pixels: int = 0) -> None:
        with self._lock:
            self.payload_bytes += int(nbytes)
            self.serialization_ms += float(ser_ms)
            self.responses += 1
            if pixels:
                self.pixel_responses += 1

    def collect_stats(self, collector) -> None:
        collector.record("query.payload.bytes_total",
                         self.payload_bytes)
        collector.record("query.payload.serialization_ms_total",
                         self.serialization_ms)
        collector.record("query.payload.responses", self.responses)
        collector.record("query.payload.pixel_responses",
                         self.pixel_responses)

    def health_info(self) -> dict[str, Any]:
        n = max(self.responses, 1)
        return {
            "responses": self.responses,
            "pixel_responses": self.pixel_responses,
            "payload_bytes_total": self.payload_bytes,
            "payload_bytes_avg": round(self.payload_bytes / n, 1),
            "serialization_ms_total": round(self.serialization_ms, 1),
            "serialization_ms_avg": round(
                self.serialization_ms / n, 3),
        }


class QueryStats:
    """Per-query trace (ref: QueryStats.java:58). Register on start,
    mark complete on finish; recent queries are browsable at
    ``/api/stats/query``."""

    _running: "dict[int, QueryStats]" = {}
    _completed: "deque[QueryStats]" = deque(maxlen=50)
    _registry_lock = threading.Lock()
    _next_id = 0

    def __init__(self, remote: str = "", query: Any = None,
                 allow_duplicates: bool = True):
        self.remote = remote
        self.query = query
        self.start_ns = time.monotonic_ns()
        self.start_time = time.time()
        # tsdlint: allow[unbounded-growth] per-query stats object,
        # garbage with its response; keys are the QueryStat enum
        self.stats: dict[str, float] = {}
        # sub-queries of one TSQuery may record concurrently (the
        # engine's parallel fan-out); the dict read-modify-write in
        # add_stat must not lose updates
        self._stats_lock = threading.Lock()
        self.executed = False
        # identity for the duplicate check: endpoint + query content
        # (ref: QueryStats.java:70-73 — "hash is the remote + query").
        # Computed only when duplicates are restricted — serializing
        # the whole TSQuery per request would tax the default hot path
        # for a comparison nothing performs.
        self.dup_key = None
        if not allow_duplicates:
            try:
                qjson = query.to_json() if query is not None else None
            except Exception:  # noqa: BLE001
                qjson = repr(query)
            self.dup_key = (remote, repr(qjson))
        with QueryStats._registry_lock:
            if not allow_duplicates and any(
                    r.dup_key == self.dup_key
                    for r in QueryStats._running.values()):
                # (ref: QueryStats ctor :263 throws QueryException when
                # ENABLE_DUPLICATES is off — surfaced as a 400)
                raise DuplicateQueryError(
                    "Query is already executing for endpoint: "
                    f"{remote}")
            QueryStats._next_id += 1
            self.query_id = QueryStats._next_id
            QueryStats._running[self.query_id] = self

    def add_stat(self, stat: QueryStat, value: float) -> None:
        with self._stats_lock:
            self.stats[stat.value] = \
                self.stats.get(stat.value, 0.0) + value

    def mark_serialization_successful(self) -> None:
        """The query produced a response (ref: the reference flips
        ``executed`` only on serialization success)."""
        self.executed = True
        self._complete()

    def mark_complete(self) -> None:
        """Move to the completed registry WITHOUT claiming success —
        the finally-path for failed queries (``executed`` stays
        False so /api/stats/query shows the failure)."""
        self._complete()

    def _complete(self) -> None:
        with QueryStats._registry_lock:
            if QueryStats._running.pop(self.query_id, None) is None:
                return  # already completed
            self.stats[QueryStat.TOTAL_TIME.value] = (
                (time.monotonic_ns() - self.start_ns) / 1e6)
            QueryStats._completed.append(self)

    def to_json(self) -> dict[str, Any]:
        stats = dict(self.stats)
        for base, (mx, avg) in _DERIVED_TIMES.items():
            if base in stats:
                stats.setdefault(mx, stats[base])
                stats.setdefault(avg, stats[base])
        return {
            "queryId": self.query_id,
            "remote": self.remote,
            "queryStartTimestamp": int(self.start_time * 1000),
            "executed": self.executed,
            "stats": stats,
            "query": (self.query.to_json()
                      if hasattr(self.query, "to_json") else None),
        }

    @classmethod
    def running_and_completed(cls) -> dict[str, list[dict[str, Any]]]:
        with cls._registry_lock:
            return {
                "running": [q.to_json() for q in cls._running.values()],
                "completed": [q.to_json() for q in cls._completed],
            }
