"""Continuous-query subsystem: standing TSQueries maintained
incrementally under ingest (registry + incremental window folds + SSE
push transport). See :mod:`opentsdb_tpu.streaming.registry`."""

from opentsdb_tpu.streaming.registry import (ContinuousQuery,
                                             ContinuousQueryRegistry)

__all__ = ["ContinuousQuery", "ContinuousQueryRegistry"]
