"""Event-time layer over the streaming v2 shared-partial engine.

Streaming v2 (:mod:`opentsdb_tpu.streaming.plan`) is PROCESSING-time
correct: late points refold wherever the ring still covers them and
silently drop past its horizon, and nothing tells a consumer whether
a window it just read is final. This package makes the engine
event-time correct, in three pieces:

- :mod:`.watermark` — the per-CQ watermark/lateness policy
  (``{"watermark": {"allowedLateness": "5m"}}`` on registration):
  the ring grows extra lateness columns so in-lateness points REFOLD
  into already-published windows (counted, republished through the
  normal dirty-bucket path), points past the watermark drop and
  count — never silently — and every pull/SSE result carries a
  completeness marker (watermark position, refold/drop counters,
  window finality).
- :mod:`.sessions` — session windows keyed by a tag
  (``{"type": "session", "gap": "2m", "by": "user"}``): one
  :class:`~opentsdb_tpu.streaming.eventtime.sessions.SessionPartial`
  folds millions of concurrent per-user sessions as ONE columnar
  scatter over a shared per-metric ring — rows key by the tag VALUE,
  not the series — with gap-close decided by the watermark.
- hopping windows (slide > interval) live in the core window machinery
  (:class:`~opentsdb_tpu.streaming.plan.WindowSpec` +
  :func:`~opentsdb_tpu.ops.stream_fold.combine_hopping`) as the
  generalization of the existing sliding view-time combine.

Cross-shard federation of all of the above — per-shard shared
partials merged by the router over the binary wire — lives in
:mod:`opentsdb_tpu.cluster.cq`.
"""

from opentsdb_tpu.streaming.eventtime.sessions import SessionPartial
from opentsdb_tpu.streaming.eventtime.watermark import (
    WatermarkPolicy, completeness_marker)

__all__ = ["SessionPartial", "WatermarkPolicy", "completeness_marker"]
