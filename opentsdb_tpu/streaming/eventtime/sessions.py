"""Per-tag session partials: millions of sessions, one scatter.

A session window ``{"type": "session", "gap": "2m", "by": "user"}``
asks for one session timeline PER VALUE of one tag — the
millions-of-users scenario. Keying the shared ring by series would
explode rows to (users x every other tag combination) and stitch
each user's sessions across rows at serve time; instead
:class:`SessionPartial` keys its rows by the ``by`` tag's VALUE id:

- every member series maps to the row of its ``user`` value, so the
  per-batch fold stays the SAME single columnar scatter the base
  partial runs — N series belonging to one user simply collide into
  one row, which is exactly the per-user aggregate the session
  semantics want;
- ``_tag_pairs`` holds one ``(kid, vid)`` pair per row, so the
  existing group/serve machinery (TagMatrix, group-by, result
  assembly) sees a perfectly ordinary membership where each "series"
  IS one user;
- bootstrap scans ALL member series and scatter-combines their
  per-series grids into the user rows (sums add, extremes fold), so
  a freshly registered CQ answers identically to the folds that
  follow;
- gap-close is driven by the watermark:
  :meth:`~opentsdb_tpu.streaming.plan.SharedPartial.session_stats`
  closes a row's session once the watermark passes its last active
  bucket by more than the gap, and the completeness marker carries
  the open/closed counts.

Session-by-tag partials never share with generic views (the registry
builds their identity key from the session tag too), never tier-seed
(sessions are a live-window surface; pre-boundary history is not
stitched into user rows), and refuse percentile views (the sketch
channel is per-series).
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.streaming.plan import SharedPartial


class SessionPartial(SharedPartial):
    """A :class:`SharedPartial` whose rows are tag values, not
    series (see module docstring). ``_sids`` holds one
    representative series per row purely for the result-assembly
    surface (tsuids/annotations are never requested on this path);
    ``_member_sids`` remembers every admitted series for re-seeds."""

    def __init__(self, tsdb, metric: str, filters: list,
                 interval_ms: int, n_windows: int, by_tag: str):
        super().__init__(tsdb, metric, filters, interval_ms,
                         n_windows)
        self.by_tag = by_tag
        self._by_kid: int | None = None
        self._vid_rows: dict[int, int] = {}   # tag value id -> row
        self._member_sids: list[int] = []

    def _session_kid(self) -> int | None:
        if self._by_kid is None:
            try:
                self._by_kid = self.tsdb.uids.tag_names.get_id(
                    self.by_tag)
            except LookupError:
                # the tag key has no UID yet, so no series can carry
                # it either; retried on the next admit
                return None
        return self._by_kid

    def _reset_members_locked(self) -> None:
        super()._reset_members_locked()
        self._vid_rows.clear()
        self._member_sids = []

    def _seed_tier_views(self):
        return None  # sessions seed from the raw store only

    def _admit_locked(self, sid: int,
                      check_filters: bool = True) -> int:
        slot = self._slots.get(sid)
        if slot is not None:
            return slot
        rec = self.tsdb.store.series(sid)
        if self.metric_id is None:
            try:
                self.metric_id = self.tsdb.uids.metrics.get_id(
                    self.metric)
            except LookupError:
                return -1
        if rec.metric_id != self.metric_id:
            self._slots[sid] = -1
            return -1
        if check_filters and self.filters:
            triples = (np.asarray(
                [(sid, k, v) for k, v in rec.tags],
                dtype=np.int64).reshape(-1, 3)
                if rec.tags else np.empty((0, 3), dtype=np.int64))
            mask = self._filter_eval.apply(
                self.filters, np.asarray([sid], dtype=np.int64),
                triples)
            if not bool(mask[0]):
                self._slots[sid] = -1
                return -1
        kid = self._session_kid()
        vid = None
        if kid is not None:
            for k, v in rec.tags:
                if k == kid:
                    vid = v
                    break
        if vid is None:
            # a series without the session tag can never join a
            # session (tags are series identity: this is permanent)
            self._slots[sid] = -1
            return -1
        row = self._vid_rows.get(vid)
        if row is None:
            row = len(self._sids)
            self._grow_to(row + 1)
            self._vid_rows[vid] = row
            self._sids.append(sid)            # representative only
            self._tag_pairs.append(((kid, vid),))
            self.member_seq += 1
        self._slots[sid] = row
        self._member_sids.append(sid)
        return row

    def _seed_scan(self, cols: np.ndarray, start_edge: int, iv: int,
                   w: int, seeded) -> None:
        """Scan EVERY member series, then scatter-combine the
        per-series grids into the user rows — sums/counts add,
        extremes fold — so the seeded ring equals what folding the
        same points would have produced (same ops, same cells)."""
        if not self._member_sids:
            return
        sid_arr = np.asarray(self._member_sids, dtype=np.int64)
        span_end = int(start_edge + w * iv - 1)
        sums, cnts, mins, maxs = self.tsdb.store.bucket_reduce(
            sid_arr, int(start_edge), span_end, int(start_edge), iv,
            w, want_minmax=True)
        rows = np.asarray(
            [self._slots[int(s)] for s in self._member_sids],
            dtype=np.int64)
        self._grow_to(len(self._sids))
        present = cnts > 0
        rr = np.repeat(rows, w)
        cc = np.tile(cols, len(rows))
        np.add.at(self._sum, (rr, cc), sums.reshape(-1))
        np.add.at(self._cnt, (rr, cc), cnts.reshape(-1))
        np.minimum.at(self._min, (rr, cc),
                      np.where(present, mins, np.inf).reshape(-1))
        np.maximum.at(self._max, (rr, cc),
                      np.where(present, maxs, -np.inf).reshape(-1))
        self.bootstrap_points += int(cnts.sum())

    def info(self):
        out = super().info()
        out["sessionBy"] = self.by_tag
        out["sessionRows"] = len(self._vid_rows)
        out["memberSeries"] = len(self._member_sids)
        return out


__all__ = ["SessionPartial"]
