"""Watermark/lateness policy + completeness markers for standing CQs.

The policy is ONE number — allowed lateness — but it changes three
contracts at once:

- **ring sizing**: registration adds ``lateness_buckets`` trailing
  columns per view, so every bucket inside the allowed-lateness
  horizon stays resident and a late point REFOLDS into its (already
  published) window through the normal fold scatter; the dirty-bucket
  path then republishes it over SSE like any other fold.
- **finality**: the watermark is the newest folded event time minus
  the allowed lateness. Once it passes a bucket's end, that bucket is
  final — later points into it are dropped AND counted
  (``late_dropped``), never folded and never silent
  (:meth:`opentsdb_tpu.streaming.plan.SharedPartial.fold`).
- **surfacing**: every pull (``GET .../result``) and SSE frame of a
  policy-carrying CQ carries a completeness marker built here —
  watermark position, refold/drop counters, whether the emitted range
  is final, and open/closed session counts for session views. The
  marker builder runs under the ``stream.watermark`` fault site: an
  armed fault degrades the PULL to a structured 503 (the registry
  maps it) and the PUSH to a ``{"degraded": true}`` marker — results
  without a trustworthy marker are refused or flagged, not passed
  off as complete.

A policy also REMOVES the CQ from the ``/api/query`` pull fast path:
a strict-lateness partial drops late points the raw store accepted,
so it can no longer answer batch queries value-identically. Pull
consumers use the ``.../result`` surface, where the marker tells them
what they got.
"""

from __future__ import annotations

from typing import Any

from opentsdb_tpu.query.model import BadRequestError
from opentsdb_tpu.utils import datetime_util


class WatermarkPolicy:
    """Validated per-CQ lateness policy (``None`` means the legacy
    processing-time contract: refold anywhere in the ring, drop only
    at the ring horizon, no markers)."""

    __slots__ = ("lateness_ms",)

    def __init__(self, lateness_ms: int):
        self.lateness_ms = int(lateness_ms)

    @classmethod
    def from_json(cls, obj) -> "WatermarkPolicy | None":
        if obj in (None, {}):
            return None
        if not isinstance(obj, dict):
            raise BadRequestError("watermark must be an object")
        raw = obj.get("allowedLateness")
        if not raw:
            raise BadRequestError(
                "watermark requires 'allowedLateness' (e.g. \"5m\")")
        try:
            ms = datetime_util.parse_duration_ms(str(raw))
        except ValueError as e:
            raise BadRequestError(str(e)) from None
        if ms <= 0:
            raise BadRequestError(
                f"allowedLateness {raw!r} must be positive")
        return cls(ms)

    def lateness_buckets(self, interval_ms: int) -> int:
        """Extra trailing ring columns that keep the full allowed-
        lateness horizon resident at ``interval_ms`` granularity."""
        return -(-self.lateness_ms // int(interval_ms))

    def to_json(self) -> dict[str, Any]:
        return {"allowedLatenessMs": self.lateness_ms}


def completeness_marker(registry, cq, end_ms: int) -> dict[str, Any]:
    """The completeness marker for one policy-carrying CQ's emitted
    results ending at ``end_ms``: the joint watermark (minimum over
    the CQ's distinct partials — a range is only as final as its
    least-advanced fold), the lateness bound, the cumulative
    refold/drop counters, and per-session-view gap-close counts.

    Runs the ``stream.watermark`` fault site FIRST: callers must
    treat a raised fault as "marker unavailable" (503 the pull, flag
    the push) — never emit results silently stripped of their
    completeness contract."""
    faults = getattr(registry.tsdb, "faults", None)
    if faults is not None:
        faults.check("stream.watermark")
    policy = cq.policy
    wm: int | None = None
    dropped = refolded = 0
    sessions_open = sessions_closed = 0
    have_sessions = False
    seen: set[int] = set()
    for view in cq.plans:
        g = view.shared
        with g.lock:
            w = g.watermark_ms()
            if id(g) not in seen:
                seen.add(id(g))
                wm = w if wm is None else min(wm, w)
                dropped += g.late_dropped
                refolded += g.late_refolded
            if view.window.kind == "session":
                have_sessions = True
                o, c = g.session_stats(view.window.gap_ms, w)
                sessions_open += o
                sessions_closed += c
    wm = int(wm or 0)
    out: dict[str, Any] = {
        "watermarkMs": wm,
        "latenessMs": policy.lateness_ms,
        "lateRefolded": refolded,
        "lateDropped": dropped,
        # every bucket ending at or before the watermark is final; a
        # range whose end the watermark has passed cannot change
        "complete": wm >= int(end_ms),
    }
    if have_sessions:
        out["sessionsOpen"] = sessions_open
        out["sessionsClosed"] = sessions_closed
    return out


__all__ = ["WatermarkPolicy", "completeness_marker"]
