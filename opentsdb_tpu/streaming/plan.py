"""Shared incremental window state + per-query views (streaming v2).

v1 compiled every continuous sub-query into its own independent
partial array and folded it inline on the write path. v2 splits that
into two layers:

- :class:`SharedPartial` — ONE ring of per-series sum/count/min/max
  partials per canonical sub-plan identity ``(metric, membership
  filters, base downsample interval)``. Every continuous query over
  the same metric whose filters match and whose downsample interval
  is a multiple of the base attaches to the same array, so one
  vectorized scatter fold (:mod:`opentsdb_tpu.ops.stream_fold`)
  serves N dashboards. The ingest tap is an O(1) columnar append
  into the partial's pending buffer (its own small lock, never the
  fold lock); folding happens off-path on the shared worker pool
  (:mod:`opentsdb_tpu.streaming.workers`) or lazily at serve time.
- :class:`PlanView` — one per registered sub-query: derives its
  downsampled grid from the shared channels (stride combine for
  divisible intervals), applies its window type (tumbling, sliding,
  session-gap — view-time combines over the tumbling partials, the
  same sum/count/min/max decomposition the rollup tiers use), then
  runs ONLY the existing fill/rate/interpolate/aggregate tail
  (:func:`opentsdb_tpu.ops.pipeline.execute_grid`). Tumbling views
  stay value-identical to a cold batch ``/api/query`` over the same
  bucket-aligned range; sliding/session views are push/fetch
  surfaces (they are not expressible as a plain TSQuery).

Bootstrap seeds the ring with one ``bucket_reduce`` pass. When the
metric has a lifecycle demotion boundary inside the ring's horizon,
the pre-boundary part seeds from the rollup/cold tiers through the
four per-stat :class:`~opentsdb_tpu.lifecycle.stitch.StitchedStore`
views (sums from the sum tier, counts from the count tier, extremes
from min/max) instead of declining those windows to the batch engine
— tier cells nest exactly inside the plan's buckets when the tier
interval divides the base interval and the boundary is tier-aligned.

Windows live in a ring of ``n_windows`` columns keyed by
``(bucket_ts // interval) % n_windows``; a point landing in a newer
bucket than a column holds tumbles that column (reset + re-key), and
points older than the ring's horizon are dropped and counted (they
can no longer affect any servable window).
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.ops import stream_fold
from opentsdb_tpu.query import filters as filters_mod
from opentsdb_tpu.query.model import BadRequestError, TSSubQuery
from opentsdb_tpu.utils import datetime_util

# downsample functions whose bucket statistic decomposes into the
# sum/count/min/max partials this plan maintains (avg = sum / count) —
# mirrors the rollup tier decomposition AND the engine's _GRID_FNS, so
# every continuous query is also batch-grid-eligible
DECOMPOSABLE_DS = frozenset(("sum", "zimsum", "pfsum", "count", "min",
                             "mimmin", "max", "mimmax", "avg"))

_GROW = 64  # initial / doubling row capacity for the partial arrays

# per-statistic tier stores one demoted interval spans (rollup/job.py)
_TIER_AGGS = ("sum", "count", "min", "max")

WINDOW_KINDS = ("tumbling", "sliding", "hopping", "session")


class WindowSpec:
    """Window type of one continuous query: tumbling (default),
    sliding (``{"type": "sliding", "size": "5m"}`` — size must be a
    multiple of the downsample interval; each emitted bucket
    aggregates the trailing ``size`` of history, sliding by one
    interval), hopping (``{"type": "hopping", "size": "10m",
    "slide": "5m"}`` — the sliding combine emitting only every
    ``slide``-aligned bucket; slide > interval generalizes the
    sliding view's slide == interval) or session-gap
    (``{"type": "session", "gap": "2m"}`` — gap must be a multiple
    of the interval; buckets closer than the gap merge into one
    session stamped at its first bucket; an optional ``"by"`` tag
    key folds sessions PER TAG VALUE over one shared partial — the
    millions-of-users scenario, :mod:`opentsdb_tpu.streaming.
    eventtime.sessions`)."""

    __slots__ = ("kind", "size_ms", "gap_ms", "slide_ms", "by_tag")

    def __init__(self, kind: str = "tumbling", size_ms: int = 0,
                 gap_ms: int = 0, slide_ms: int = 0,
                 by_tag: str | None = None):
        self.kind = kind
        self.size_ms = int(size_ms)
        self.gap_ms = int(gap_ms)
        self.slide_ms = int(slide_ms)
        self.by_tag = by_tag

    @classmethod
    def from_json(cls, obj, interval_ms: int) -> "WindowSpec":
        """Validate one ``window`` object against a sub-query's
        downsample interval; raises :class:`BadRequestError`."""
        if obj in (None, {}):
            return cls()
        if not isinstance(obj, dict):
            raise BadRequestError("window must be an object")
        kind = str(obj.get("type", "tumbling"))
        if kind not in WINDOW_KINDS:
            raise BadRequestError(
                f"unknown window type {kind!r} "
                f"(supported: {', '.join(WINDOW_KINDS)})")

        def duration(key: str) -> int:
            raw = obj.get(key)
            if not raw:
                raise BadRequestError(
                    f"{kind} window requires {key!r} (e.g. \"5m\")")
            try:
                ms = datetime_util.parse_duration_ms(str(raw))
            except ValueError as e:
                raise BadRequestError(str(e)) from None
            if ms <= 0 or ms % interval_ms:
                raise BadRequestError(
                    f"window {key} {raw!r} must be a positive "
                    f"multiple of the downsample interval "
                    f"({interval_ms} ms)")
            return ms

        if kind == "sliding":
            size = duration("size")
            if size <= interval_ms:
                raise BadRequestError(
                    "sliding window size must exceed the downsample "
                    "interval (equal would be tumbling)")
            return cls("sliding", size_ms=size)
        if kind == "hopping":
            size = duration("size")
            slide = duration("slide")
            if slide <= interval_ms:
                raise BadRequestError(
                    "hopping window slide must exceed the downsample "
                    "interval (equal would be sliding)")
            if size <= slide:
                raise BadRequestError(
                    "hopping window size must exceed its slide "
                    "(equal would be a coarser tumbling window)")
            return cls("hopping", size_ms=size, slide_ms=slide)
        if kind == "session":
            by = obj.get("by")
            if by is not None and (not isinstance(by, str) or not by):
                raise BadRequestError(
                    "session window 'by' must be a non-empty tag key")
            return cls("session", gap_ms=duration("gap"), by_tag=by)
        return cls()

    def lead_for(self, interval_ms: int) -> int:
        """Extra trailing-history buckets a full leading window
        needs (sliding/hopping: the trailing combine reaches
        ``size`` back from each emitted bucket)."""
        return (self.size_ms // interval_ms - 1) \
            if self.kind in ("sliding", "hopping") else 0

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {"type": self.kind}
        if self.size_ms:
            out["sizeMs"] = self.size_ms
        if self.gap_ms:
            out["gapMs"] = self.gap_ms
        if self.slide_ms:
            out["slideMs"] = self.slide_ms
        if self.by_tag:
            out["by"] = self.by_tag
        return out


def filter_identity(sub: TSSubQuery) -> tuple:
    """Canonical MEMBERSHIP identity of a sub-query's filter set: the
    ``groupBy`` flag only affects result grouping (a view-time
    concern), not which series belong to the partial array — so two
    queries differing only in groupBy share one fold."""
    keys = []
    for f in sub.filters:
        j = dict(f.to_json())
        j.pop("groupBy", None)
        keys.append(repr(sorted(j.items())))
    return tuple(sorted(keys))


class SharedPartial:
    """One shared partial-aggregate window ring (see module
    docstring). Thread-safe: fold/serve state mutates under ``lock``;
    the ingest tap's pending buffer has its own ``_pending_lock`` so
    an O(1) enqueue never waits on a fold in progress; drains are
    serialized by ``_drain_lock`` so chunks fold in arrival order."""

    def __init__(self, tsdb, metric: str, filters: list,
                 interval_ms: int, n_windows: int):
        self.tsdb = tsdb
        self.metric = metric
        self.filters = filters
        self.metric_id: int | None = None
        self.interval_ms = int(interval_ms)
        self.n_windows = int(n_windows)
        self.lock = threading.RLock()
        self._pending_lock = threading.Lock()
        self._drain_lock = threading.Lock()
        self._filter_eval = filters_mod.FilterEvaluator(tsdb.uids)
        # views attached to this partial (mutated under ``lock``);
        # folds push dirty buckets to every view's changed-set
        self.views: list[PlanView] = []
        # membership: sid -> row slot (-1 = evaluated, not a member)
        self._slots: dict[int, int] = {}
        self._sids: list[int] = []
        self._tag_pairs: list[tuple] = []  # row -> ((kid, vid), ...)
        w = self.n_windows
        cap = _GROW
        self._sum = np.zeros((cap, w))
        self._cnt = np.zeros((cap, w))
        self._min = np.full((cap, w), np.inf)
        self._max = np.full((cap, w), -np.inf)
        # optional fifth channel: per-(row, column) quantile sketches,
        # maintained only while a percentile view is attached
        # (``want_sketch``). ``sketch_from_ms`` is the oldest bucket
        # edge the channel covers exactly — serves reaching further
        # back shed to the batch engine
        self.want_sketch = False
        self._sketch: dict[tuple[int, int], Any] = {}
        self.sketch_from_ms = 0
        self.win_ts = np.full(w, -1, dtype=np.int64)
        # the oldest bucket edge every ring column still covers; a
        # request starting before it cannot be served incrementally
        self.covered_from_ms = 0
        # newest folded timestamp: absolute-range serves past it are
        # exact (nothing newer exists to diverge on)
        self.max_ts_ms = 0
        # newest LIVE-FOLDED event time, the watermark's sole input:
        # unlike max_ts_ms it is never seeded from wall clock or
        # bootstrap scans (a watermark is only emitted after the
        # events that advanced it), so a freshly registered policy CQ
        # finalizes nothing until real folds advance it — and is
        # monotone across ring rebuilds (final stays final). Folds
        # STAGE the advance; the drain loop commits it once per pass
        # (commit_watermark), so a write batch the ingest tap chunked
        # per series folds wholly against the PRE-batch watermark —
        # otherwise the first series' newest point would mass-drop
        # every later series' older half as "late"
        self.wm_event_ms = 0
        self._wm_staged_ms = 0
        # versions: folds invalidate view tail caches, membership
        # changes invalidate the group structures
        self.fold_seq = 0
        self.member_seq = 0
        # event-time lateness policy (streaming/eventtime): 0 = the
        # legacy contract (late points refold anywhere the ring still
        # covers, drop only past the ring horizon). A positive bound
        # FINALIZES buckets once the watermark (newest folded event
        # time minus the bound) passes their end — later points into
        # them drop and count, never silently mutate a final window.
        # Set once at registration (the policy is part of the shared
        # partial's identity, so attached views always agree).
        self.lateness_ms = 0
        # counters (read by the registry's stats/health export)
        self.points_folded = 0
        self.folds = 0
        self.late_dropped = 0
        self.late_refolded = 0
        self.preboundary_dropped = 0
        self.bootstrap_points = 0
        self.backpressure_dropped = 0
        # pending (sids, ts_ms, values) chunks offered by the ingest
        # tap; folded in batches off the hot write path. Single
        # points ride the scalar list — building three 1-element
        # numpy arrays per point costs more than the rest of the tap
        # combined, so take_pending columnarizes them in one shot
        self._pending: list[tuple] = []
        self._pending_scalars: list[tuple] = []
        self.pending_points = 0
        self.needs_rebuild = False
        # tier-seeded bootstrap state: when the ring's horizon reaches
        # behind the metric's demotion boundary AND a tier interval
        # nests in the base interval, bootstrap seeds the pre-boundary
        # part from the stitched rollup/cold tiers; folds then drop
        # pre-boundary backfills (stitched batch reads ignore them
        # too — the documented backfill-behind-boundary divergence)
        self.tier_seeded = False
        self.seed_boundary_ms = 0
        self._seed_interval: str | None = None
        # the read-set's mutation epochs at bootstrap: deletes,
        # repairs and lifecycle sweeps bump them, and partials cannot
        # "unfold" removed points — the registry forces a rebuild on
        # mismatch before serving. Known limitation (documented):
        # DUPLICATE writes (same series+timestamp rewritten) fold
        # additively while the store dedupes last-write-wins; they do
        # not bump the epoch, so the divergence persists until a
        # tumble or rebuild. The reference treats duplicate writes as
        # an error condition (tsd.storage.fix_duplicates), so this
        # trades exactness on an abnormal workload for an O(1) write
        # path.
        self.store_epoch: tuple = (-1,)

    # ------------------------------------------------------------------
    # identity / attachment
    # ------------------------------------------------------------------

    def compatible_with(self, interval_ms: int) -> bool:
        """Downsample-divisible: a view whose interval is a multiple
        of the base derives its buckets by stride combine."""
        return interval_ms % self.interval_ms == 0

    def attach(self, view: "PlanView") -> None:
        with self.lock:
            self.views.append(view)

    def detach(self, view: "PlanView") -> bool:
        """Remove one view; returns True when no views remain (the
        registry then drops the whole partial)."""
        with self.lock:
            if view in self.views:
                self.views.remove(view)
            return not self.views

    # ------------------------------------------------------------------
    # epochs
    # ------------------------------------------------------------------

    def _epoch_now(self) -> tuple:
        """Mutation epochs of everything this partial was seeded
        from: the raw store always; plus the cold store and the four
        per-stat tier stores when tier-seeded (a cold quarantine or a
        tier delete must force a rebuild exactly like a raw one)."""
        parts = [getattr(self.tsdb.store, "mutation_epoch", 0)]
        if self.tier_seeded and self._seed_interval is not None:
            lc = getattr(self.tsdb, "lifecycle", None)
            cold = getattr(lc, "coldstore", None) \
                if lc is not None else None
            parts.append(cold.mutation_epoch if cold is not None else 0)
            rs = self.tsdb.rollup_store
            if rs is not None:
                for agg in _TIER_AGGS:
                    parts.append(getattr(
                        rs.tier(self._seed_interval, agg),
                        "mutation_epoch", 0))
        return tuple(parts)

    def epoch_changed(self) -> bool:
        return self.store_epoch != self._epoch_now()

    # ------------------------------------------------------------------
    # bootstrap: one batch scan seeds the partials, then folds keep up
    # ------------------------------------------------------------------

    def _seed_tier_views(self):
        """The four per-stat stitched views to seed from, or None
        when the horizon holds no demoted history (or no configured
        tier nests in the base interval: those windows keep shedding
        to the batch engine, the v1 behavior)."""
        t = self.tsdb
        lc = getattr(t, "lifecycle", None)
        rs = getattr(t, "rollup_store", None)
        if lc is None or rs is None or self.metric_id is None:
            return None
        boundary = lc.demote_boundary(self.metric_id)
        if not boundary or self.covered_from_ms >= boundary:
            return None
        best = None
        for iv in t.rollup_config.intervals:
            if iv.interval_ms <= self.interval_ms \
                    and self.interval_ms % iv.interval_ms == 0 \
                    and boundary % iv.interval_ms == 0:
                # coarsest nesting tier: fewest cells to reduce
                if best is None or iv.interval_ms > best.interval_ms:
                    best = iv
        if best is None:
            return None
        views = {}
        for agg in _TIER_AGGS:
            st = lc.stitched(self.metric_id, best.interval, agg,
                             rs.tier(best.interval, agg))
            if st is None:
                return None
            views[agg] = st
        return views, boundary, best.interval

    def _reset_members_locked(self) -> None:
        """Clear membership for a re-seed (caller holds ``lock``);
        subclasses with extra membership maps extend this."""
        self._slots.clear()
        self._sids = []
        self._tag_pairs = []

    def _seed_scan(self, cols: np.ndarray, start_edge: int, iv: int,
                   w: int, seeded) -> None:
        """Seed the ring channels from the store for the admitted
        members (caller holds ``lock``; membership was just rebuilt).
        Subclasses that key rows by something other than series
        (per-tag session partials) override the scatter."""
        if not len(self._sids):
            return
        sid_arr = np.asarray(self._sids, dtype=np.int64)
        span_end = int(start_edge + w * iv - 1)
        if seeded is not None:
            # channel-wise tier seed: each stitched view
            # combines its cold + tier + raw-tail parts over
            # the SAME bucket grid, so sums of sums / counts
            # of counts / extremes of extremes are exact
            views = seeded[0]
            sums = views["sum"].bucket_reduce(
                sid_arr, int(start_edge), span_end,
                int(start_edge), iv, w)[0]
            cnts = views["count"].bucket_reduce(
                sid_arr, int(start_edge), span_end,
                int(start_edge), iv, w)[0]
            mins = views["min"].bucket_reduce(
                sid_arr, int(start_edge), span_end,
                int(start_edge), iv, w, want_minmax=True)[2]
            maxs = views["max"].bucket_reduce(
                sid_arr, int(start_edge), span_end,
                int(start_edge), iv, w, want_minmax=True)[3]
        else:
            sums, cnts, mins, maxs = self.tsdb.store.bucket_reduce(
                sid_arr, int(start_edge), span_end,
                int(start_edge), iv, w, want_minmax=True)
        s = len(sid_arr)
        self._grow_to(s)
        self._sum[:s, cols] = sums
        self._cnt[:s, cols] = cnts
        present = cnts > 0
        self._min[:s, cols] = np.where(present, mins, np.inf)
        self._max[:s, cols] = np.where(present, maxs, -np.inf)
        self.bootstrap_points += int(cnts.sum())

    def bootstrap(self, now_ms: int,
                  n_windows: int | None = None) -> None:
        """Seed the window ring from the store: one fused
        ``bucket_reduce`` pass over the horizon produces exactly the
        sum/count/min/max partials the folds maintain afterwards.
        When demoted history falls inside the horizon, the stitched
        tier views supply it channel-wise (see module docstring).

        Takes ``_drain_lock`` BEFORE ``lock`` (the drain path's
        order): a drainer holding taken-but-unfolded chunks must
        finish before the re-scan, or its late folds would
        double-count points the scan already seeded."""
        with self._drain_lock, self.lock:
            if n_windows is not None:
                self.n_windows = int(n_windows)
            iv, w = self.interval_ms, self.n_windows
            last_edge = now_ms - now_ms % iv
            start_edge = last_edge - (w - 1) * iv
            edges = start_edge + np.arange(w, dtype=np.int64) * iv
            cols = ((edges // iv) % w).astype(np.int64)
            self.win_ts = np.full(w, -1, dtype=np.int64)
            self.win_ts[cols] = edges
            self._reset_members_locked()
            if self._sum.shape[1] != w:
                cap = self._sum.shape[0]
                self._sum = np.zeros((cap, w))
                self._cnt = np.zeros((cap, w))
                self._min = np.full((cap, w), np.inf)
                self._max = np.full((cap, w), -np.inf)
            else:
                self._sum[:] = 0.0
                self._cnt[:] = 0.0
                self._min[:] = np.inf
                self._max[:] = -np.inf
            with self._pending_lock:
                self._pending = []
                self._pending_scalars = []
                self.pending_points = 0
            for v in self.views:
                v.invalidate_caches()
            self._sketch = {}
            self.sketch_from_ms = int(start_edge)
            self.covered_from_ms = int(start_edge)
            self.max_ts_ms = int(now_ms)
            self.tier_seeded = False
            self.seed_boundary_ms = 0
            self._seed_interval = None
            uids = self.tsdb.uids
            try:
                self.metric_id = uids.metrics.get_id(self.metric)
            except LookupError:
                self.metric_id = None  # metric not written yet
                self.store_epoch = self._epoch_now()
                self.member_seq += 1
                self.fold_seq += 1
                return
            # epochs BEFORE the scan: a concurrent mutation during the
            # scan leaves the partial already-stale, never wrongly
            # fresh
            seeded = self._seed_tier_views()
            if seeded is not None:
                self.tier_seeded = True
                self.seed_boundary_ms = seeded[1]
                self._seed_interval = seeded[2]
            self.store_epoch = self._epoch_now()
            store = self.tsdb.store
            sids = store.series_ids_for_metric(self.metric_id)
            if len(sids) and self.filters:
                idx = store.metric_index(self.metric_id)
                _, triples = idx.arrays()
                mask = self._filter_eval.apply(self.filters, sids,
                                               triples)
                sids = sids[mask]
            for sid in np.asarray(sids).tolist():
                self._admit_locked(int(sid), check_filters=False)
            self._seed_scan(cols, int(start_edge), iv, w, seeded)
            if self.want_sketch and len(self._sids):
                self._seed_sketch_locked(
                    int(start_edge), int(start_edge + w * iv - 1))
            self.member_seq += 1
            self.fold_seq += 1

    def ensure_horizon(self, n_windows: int, anchor_ms: int) -> bool:
        """Grow the ring to at least ``n_windows`` columns (a newly
        attached view needs a longer horizon) and re-seed. Returns
        True when a re-bootstrap ran. Caller handles exceptions (a
        failed re-seed leaves ``needs_rebuild`` set). The size change
        applies INSIDE the re-bootstrap (under the drain+fold locks):
        a fold must never see a ring size its arrays don't match."""
        with self.lock:
            newest = int(self.win_ts.max())
            anchor = max(anchor_ms, newest if newest > 0 else 0)
            if n_windows <= self.n_windows:
                return False
        try:
            self.bootstrap(anchor, n_windows=n_windows)
        except BaseException:
            self.needs_rebuild = True
            raise
        return True

    # ------------------------------------------------------------------
    # quantile sketch channel (percentile views)
    # ------------------------------------------------------------------

    def enable_sketch(self) -> None:
        """Turn the sketch channel on for an already-live partial (a
        percentile view attached to a ring that predates it); the next
        rebuild seeds it."""
        with self.lock:
            if not self.want_sketch:
                self.want_sketch = True
                self.needs_rebuild = True

    def _sketch_params(self) -> tuple[float, int]:
        cfg = self.tsdb.config
        return (cfg.get_float("tsd.sketch.alpha", 0.01),
                cfg.get_int("tsd.sketch.max_buckets", 4096))

    def _merge_sketch_cell(self, slot: int, col: int, sk) -> None:
        from opentsdb_tpu.sketch.ddsketch import SketchError
        cur = self._sketch.get((slot, col))
        if cur is None:
            self._sketch[(slot, col)] = sk
        else:
            try:
                cur.merge(sk)
            except SketchError:
                self._sketch[(slot, col)] = sk  # alpha changed: newest wins

    def _fold_sketch_points(self, slots: np.ndarray, ts: np.ndarray,
                            vals: np.ndarray) -> None:
        """Vectorized sketch fold of one chunk (caller holds ``lock``
        and has already masked non-members/NaN/late points)."""
        from opentsdb_tpu.ops import sketch_fold
        iv, w = self.interval_ms, self.n_windows
        alpha, maxb = self._sketch_params()
        bucket = ts - ts % iv
        folded = sketch_fold.fold_series_cells(slots, bucket, vals, 1,
                                               alpha, maxb)
        for (slot, b), sk in folded.items():
            c = int((int(b) // iv) % w)
            if self.win_ts[c] != b:
                continue
            self._merge_sketch_cell(int(slot), c, sk)

    def _seed_sketch_locked(self, start_edge: int,
                            span_end: int) -> None:
        """Seed the sketch channel over the horizon: demoted/cold
        history through the three-zone sketch read (exact when the
        sketch tier's cell interval nests in the base interval), the
        raw tail through the vectorized fold. When demoted history
        cannot seed exactly, ``sketch_from_ms`` records the demote
        boundary so pre-boundary percentile serves shed to the batch
        engine instead of answering from missing data."""
        from opentsdb_tpu.lifecycle.stitch import sketch_zone_read
        t = self.tsdb
        iv, w = self.interval_ms, self.n_windows
        self._sketch = {}
        self.sketch_from_ms = int(start_edge)
        items, raw_rng, cold_ok = sketch_zone_read(
            t, self.metric, self.metric_id, int(start_edge),
            int(span_end))
        lc = getattr(t, "lifecycle", None)
        demote_b = lc.demote_boundary(self.metric_id) \
            if lc is not None else 0
        sketches = getattr(lc, "sketches", None) \
            if lc is not None else None
        cell_ms = sketches.cell_ms(self.metric) \
            if sketches is not None else 0
        nests = bool(cell_ms) and iv % cell_ms == 0
        if demote_b > start_edge and not (nests and cold_ok):
            self.sketch_from_ms = int(demote_b)
            items = []
        if items:
            uids = t.uids
            pos: dict[tuple, int] = {}
            for slot, pairs in enumerate(self._tag_pairs):
                try:
                    pos[tuple(sorted(
                        (uids.tag_names.get_name(k),
                         uids.tag_values.get_name(v))
                        for k, v in pairs))] = slot
                except LookupError:
                    continue
            for names, cts, sk in items:
                slot = pos.get(tuple(names))
                if slot is None or cts < self.sketch_from_ms:
                    continue
                b = cts - cts % iv
                c = int((b // iv) % w)
                if self.win_ts[c] != b:
                    continue
                self._merge_sketch_cell(slot, c, sk.copy())
        if raw_rng is not None:
            lo = max(int(raw_rng[0]), self.sketch_from_ms)
            hi = min(int(raw_rng[1]), int(span_end))
            if lo <= hi and len(self._sids):
                sid_arr = np.asarray(self._sids, dtype=np.int64)
                batch = t.store.materialize(sid_arr, lo, hi)
                if batch.num_points:
                    self._fold_sketch_points(
                        np.asarray(batch.series_idx, dtype=np.int64),
                        np.asarray(batch.ts_ms, dtype=np.int64),
                        np.asarray(batch.values, dtype=np.float64))

    def sketch_items_for(self, start_ms: int, end_ms: int):
        """Live ``(slot, bucket_ts, sketch)`` triples whose base
        bucket falls inside [start, end], or None when the range
        reaches behind the channel's exact coverage. Caller holds
        ``lock``; the returned sketches are the ring's own — callers
        must copy before merging."""
        if not self.want_sketch:
            return None
        lo = max(int(start_ms), self.sketch_from_ms,
                 self.covered_from_ms)
        if int(start_ms) < lo:
            return None
        out = []
        for (slot, c), sk in self._sketch.items():
            b = int(self.win_ts[c])
            if b < 0 or b < start_ms or b > end_ms:
                continue
            out.append((slot, b, sk))
        return out

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------

    def _grow_to(self, rows: int) -> None:
        cap = self._sum.shape[0]
        if rows <= cap:
            return
        new_cap = cap
        while new_cap < rows:
            new_cap *= 2
        w = self.n_windows

        def grow(arr, fill):
            out = np.full((new_cap, w), fill, dtype=arr.dtype)
            out[:cap] = arr
            return out

        self._sum = grow(self._sum, 0.0)
        self._cnt = grow(self._cnt, 0.0)
        self._min = grow(self._min, np.inf)
        self._max = grow(self._max, -np.inf)

    def _admit_locked(self, sid: int, check_filters: bool = True) -> int:
        """Slot for ``sid``, admitting it when it matches the plan's
        filters (a series first seen by a WRITE is brand new — its
        points arrive through the very fold that admits it, so no
        backfill is needed). Returns -1 for non-members."""
        slot = self._slots.get(sid)
        if slot is not None:
            return slot
        rec = self.tsdb.store.series(sid)
        if self.metric_id is None:
            # the metric materialized after registration: latch its id
            try:
                self.metric_id = self.tsdb.uids.metrics.get_id(
                    self.metric)
            except LookupError:
                return -1
        if rec.metric_id != self.metric_id:
            self._slots[sid] = -1
            return -1
        if check_filters and self.filters:
            triples = (np.asarray(
                [(sid, k, v) for k, v in rec.tags],
                dtype=np.int64).reshape(-1, 3)
                if rec.tags else np.empty((0, 3), dtype=np.int64))
            mask = self._filter_eval.apply(
                self.filters, np.asarray([sid], dtype=np.int64),
                triples)
            if not bool(mask[0]):
                self._slots[sid] = -1
                return -1
        slot = len(self._sids)
        self._grow_to(slot + 1)
        self._slots[sid] = slot
        self._sids.append(sid)
        self._tag_pairs.append(tuple(rec.tags))
        self.member_seq += 1
        return slot

    # ------------------------------------------------------------------
    # ingest tap: O(1) columnar enqueue
    # ------------------------------------------------------------------

    def offer(self, sids: np.ndarray, ts_ms: np.ndarray,
              values: np.ndarray) -> int:
        """Buffer a chunk from the ingest tap (O(1) append under the
        small pending lock — never the fold lock); returns the
        pending-point total so the registry can decide to hand the
        partial to a worker or degrade it."""
        with self._pending_lock:
            self._pending.append((sids, ts_ms, values))
            self.pending_points += len(ts_ms)
            return self.pending_points

    def offer_one(self, sid: int, ts_ms: int, value: float) -> int:
        """Scalar tap: one point, no numpy on the write path (a
        tuple append under the pending lock — ``take_pending``
        columnarizes the accumulated scalars in one conversion)."""
        with self._pending_lock:
            self._pending_scalars.append((sid, ts_ms, value))
            self.pending_points += 1
            return self.pending_points

    def take_pending(self) -> list[tuple]:
        with self._pending_lock:
            out, self._pending = self._pending, []
            sc, self._pending_scalars = self._pending_scalars, []
            self.pending_points = 0
        if sc:
            # float64 carries sid and ts_ms exactly (< 2**53)
            cols = np.asarray(sc, dtype=np.float64)
            out.append((cols[:, 0].astype(np.int64),
                        cols[:, 1].astype(np.int64), cols[:, 2]))
        return out

    def drop_pending(self) -> int:
        """Backpressure degrade: throw the backlog away (the partial
        is marked for rebuild-on-serve by the registry) and return
        the dropped point count. Never blocks the write path."""
        with self._pending_lock:
            dropped = self.pending_points
            self._pending = []
            self._pending_scalars = []
            self.pending_points = 0
        self.backpressure_dropped += dropped
        return dropped

    # ------------------------------------------------------------------
    # folds (run by workers / serve-path drains, never the tap)
    # ------------------------------------------------------------------

    def fold(self, sids: np.ndarray, ts_ms: np.ndarray,
             values: np.ndarray) -> None:
        """Fold one chunk of points into the window partials — ONE
        scatter per stat channel serving every attached view."""
        with self.lock:
            iv, w = self.interval_ms, self.n_windows
            sids = np.asarray(sids, dtype=np.int64).reshape(-1)
            ts_ms = np.asarray(ts_ms, dtype=np.int64).reshape(-1)
            values = np.asarray(values, dtype=np.float64).reshape(-1)
            slots = np.empty(len(sids), dtype=np.int64)
            slot_map = self._slots
            for i, sid in enumerate(sids.tolist()):
                s = slot_map.get(sid)
                if s is None:
                    s = self._admit_locked(sid)
                slots[i] = s
            keep = (slots >= 0) & ~np.isnan(values)
            if not keep.any():
                self.folds += 1
                return
            slots = slots[keep]
            ts = ts_ms[keep]
            vals = values[keep]
            bucket = ts - ts % iv
            if self.tier_seeded and self.seed_boundary_ms:
                # pre-boundary backfills are invisible to stitched
                # batch reads (documented divergence); folding them
                # additively would double-serve once — drop + count
                pre = bucket < self.seed_boundary_ms
                if pre.any():
                    self.preboundary_dropped += int(pre.sum())
                    live0 = ~pre
                    slots, ts = slots[live0], ts[live0]
                    vals, bucket = vals[live0], bucket[live0]
                    if not len(bucket):
                        self.folds += 1
                        return
            if self.lateness_ms > 0:
                # event-time watermark as it stood BEFORE this drain
                # pass: a watermark is only emitted after the events
                # that advanced it, so a batch's own points are never
                # late relative to its own max (a bulk in-order
                # backfill — or the same batch chunked per series —
                # must not mass-drop its older half). Buckets the
                # standing watermark has passed are FINAL — late
                # points into them drop and count instead of silently
                # mutating a window already surfaced as complete.
                wm = self.wm_event_ms - self.lateness_ms
                final = (bucket + iv) <= wm
                if final.any():
                    self.late_dropped += int(final.sum())
                    keep2 = ~final
                    slots, ts = slots[keep2], ts[keep2]
                    vals, bucket = vals[keep2], bucket[keep2]
                    if not len(bucket):
                        self.max_ts_ms = max(self.max_ts_ms,
                                             int(ts_ms[keep].max()))
                        self.folds += 1
                        return
            col = ((bucket // iv) % w).astype(np.int64)
            # tumble columns whose newest incoming bucket is newer
            for c in np.unique(col).tolist():
                nb = int(bucket[col == c].max())
                if nb > self.win_ts[c]:
                    self._sum[:, c] = 0.0
                    self._cnt[:, c] = 0.0
                    self._min[:, c] = np.inf
                    self._max[:, c] = -np.inf
                    if self._sketch:
                        for key in [k for k in self._sketch
                                    if k[1] == c]:
                            del self._sketch[key]
                    self.win_ts[c] = nb
                    self.covered_from_ms = max(
                        self.covered_from_ms, nb - (w - 1) * iv)
            live = bucket == self.win_ts[col]
            self.late_dropped += int((~live).sum())
            # live points landing BEHIND the ring's newest bucket are
            # allowed-lateness refolds into already-published windows
            # (counted so completeness markers can surface them)
            high = int(self.win_ts.max())
            self.late_refolded += int((live & (bucket < high)).sum())
            if live.any():
                slots, col = slots[live], col[live]
                vals, bucket = vals[live], bucket[live]
                stream_fold.scatter_fold(self._sum, self._cnt,
                                         self._min, self._max,
                                         slots, col, vals)
                if self.want_sketch:
                    self._fold_sketch_points(slots, bucket, vals)
                changed = [int(b) for b in np.unique(bucket).tolist()]
                for view in self.views:
                    view.note_changed(changed, self.covered_from_ms)
                self.points_folded += len(vals)
                self.max_ts_ms = max(self.max_ts_ms, int(ts.max()))
                self._wm_staged_ms = max(self._wm_staged_ms,
                                         int(ts.max()))
                self.fold_seq += 1
            self.folds += 1

    # ------------------------------------------------------------------
    # read side: derive per-view channel grids from the shared ring
    # ------------------------------------------------------------------

    def channels_for(self, start_ms: int, end_ms: int,
                     view_interval_ms: int):
        """(sums, cnts, mins, maxs, view_edges) over the requested
        range at the VIEW's bucket granularity (stride combine over
        the base ring), or None when the range is outside the
        maintained horizon. Caller holds ``lock``."""
        base_iv, w = self.interval_ms, self.n_windows
        stride = view_interval_ms // base_iv
        edges = ds_mod.fixed_bucket_edges(start_ms, end_ms,
                                          view_interval_ms)
        if len(edges) == 0:
            return None
        base = (edges[:, None]
                + np.arange(stride, dtype=np.int64)
                * base_iv).reshape(-1)
        if len(base) > w or int(base[0]) < self.covered_from_ms:
            return None
        cols = ((base // base_iv) % w).astype(np.int64)
        live = self.win_ts[cols] == base
        s = len(self._sids)
        sums = np.where(live[None, :], self._sum[:s][:, cols], 0.0)
        cnts = np.where(live[None, :], self._cnt[:s][:, cols], 0.0)
        mins = np.where(live[None, :], self._min[:s][:, cols], np.inf)
        maxs = np.where(live[None, :], self._max[:s][:, cols], -np.inf)
        sums, cnts, mins, maxs = stream_fold.combine_stride(
            sums, cnts, mins, maxs, stride)
        return sums, cnts, mins, maxs, edges

    # ------------------------------------------------------------------
    # event-time observability (streaming/eventtime)
    # ------------------------------------------------------------------

    def commit_watermark(self) -> None:
        """Publish the event times this drain pass folded into the
        watermark basis (see ``wm_event_ms`` in ``__init__``). Called
        by the registry's drain loop AFTER all of a pass's chunks
        folded, under ``_drain_lock``."""
        with self.lock:
            if self._wm_staged_ms > self.wm_event_ms:
                self.wm_event_ms = self._wm_staged_ms

    def watermark_ms(self) -> int:
        """Event-time watermark: the newest live-folded event time
        minus the allowed lateness (without a policy the watermark
        rides the newest point — nothing is ever final)."""
        return max(0, self.wm_event_ms - self.lateness_ms)

    def ring_bytes(self) -> int:
        """Actual resident bytes of the ring channels (the fold-
        memory number the control plane's miner and the QoS tenant
        fold budget account against — capacity, not membership
        estimate)."""
        n = self._sum.nbytes + self._cnt.nbytes + self._min.nbytes \
            + self._max.nbytes + self.win_ts.nbytes
        if self._sketch:
            # dominated by bucket maps; ~16B/bucket is the DDSketch
            # store's observed footprint
            n += sum(16 * len(getattr(sk, "buckets", ()))
                     for sk in self._sketch.values())
        return n

    def session_stats(self, gap_ms: int,
                      watermark_ms: int) -> tuple[int, int]:
        """(open, closed) session counts for a session view at
        ``gap_ms``: a row's session is CLOSED once the watermark has
        passed its last active bucket's end by more than the gap —
        no in-lateness point can extend it. One vectorized pass over
        the ring (caller holds ``lock``)."""
        s = len(self._sids)
        if not s:
            return 0, 0
        live = self.win_ts >= 0
        if not live.any():
            return 0, 0
        present = self._cnt[:s][:, live] > 0
        edges = self.win_ts[live]
        has_any = present.any(axis=1)
        # newest active edge per row: argmax over edge-ranked columns
        rank = np.where(present, edges[None, :], -1)
        last_edge = rank.max(axis=1)
        closed = has_any & (last_edge + self.interval_ms + gap_ms
                            <= watermark_ms)
        return int((has_any & ~closed).sum()), int(closed.sum())

    def info(self) -> dict[str, Any]:
        with self.lock:
            return {
                "metric": self.metric,
                "intervalMs": self.interval_ms,
                "windows": self.n_windows,
                "series": len(self._sids),
                "views": len(self.views),
                "coveredFromMs": self.covered_from_ms,
                "pointsFolded": self.points_folded,
                "folds": self.folds,
                "pendingPoints": self.pending_points,
                "lateDropped": self.late_dropped,
                "lateRefolded": self.late_refolded,
                "latenessMs": self.lateness_ms,
                "watermarkMs": self.watermark_ms(),
                "ringBytes": self.ring_bytes(),
                "preboundaryDropped": self.preboundary_dropped,
                "backpressureDropped": self.backpressure_dropped,
                "bootstrapPoints": self.bootstrap_points,
                "tierSeeded": self.tier_seeded,
                "seedBoundaryMs": self.seed_boundary_ms,
                "needsRebuild": self.needs_rebuild,
                "sketchChannel": self.want_sketch,
                "sketchFromMs": self.sketch_from_ms,
            }


class PlanView:
    """One registered sub-query's view over a :class:`SharedPartial`:
    stride-derived grid + window combine + the pipeline tail. All
    fold/coverage state lives on the shared partial; the view owns
    only its caches, its window spec and its dirty-bucket set."""

    def __init__(self, shared: SharedPartial, sub: TSSubQuery,
                 n_windows: int, window: WindowSpec | None = None):
        self.shared = shared
        self.sub = sub
        self.window = window or WindowSpec()
        self.interval_ms = int(sub.ds_spec.interval_ms)
        self.n_windows = int(n_windows)
        # buckets touched since the last SSE publish (base-interval
        # edges; mutated under shared.lock by folds, drained by
        # take_changed)
        self.changed_ts: set[int] = set()
        self._tail_cache: tuple | None = None
        self._groups_cache: tuple | None = None

    # -- properties delegated to the shared partial (registry + test
    # surface compatibility: ``cq.plans[0].covered_from_ms`` etc.) ----

    @property
    def metric(self) -> str:
        return self.shared.metric

    @property
    def metric_id(self) -> int | None:
        return self.shared.metric_id

    @property
    def covered_from_ms(self) -> int:
        return self.shared.covered_from_ms

    @property
    def max_ts_ms(self) -> int:
        return self.shared.max_ts_ms

    @property
    def late_dropped(self) -> int:
        return self.shared.late_dropped

    @property
    def late_refolded(self) -> int:
        return self.shared.late_refolded

    @property
    def pending_points(self) -> int:
        return self.shared.pending_points

    @property
    def needs_rebuild(self) -> bool:
        return self.shared.needs_rebuild

    @property
    def _sids(self) -> list[int]:
        return self.shared._sids

    @property
    def stride(self) -> int:
        return self.interval_ms // self.shared.interval_ms

    # ------------------------------------------------------------------

    def invalidate_caches(self) -> None:
        self._tail_cache = None
        self._groups_cache = None

    def note_changed(self, buckets: list[int],
                     covered_from_ms: int) -> None:
        """Record fold-dirty base buckets (called under
        ``shared.lock`` by the fold)."""
        self.changed_ts.update(buckets)
        self._tail_cache = None
        if len(self.changed_ts) > 4 * max(
                self.n_windows * self.stride, 1):
            # nobody is draining the changed-set (no subscriber):
            # keep it bounded by the horizon
            self.changed_ts = {c for c in self.changed_ts
                               if c >= covered_from_ms}

    def take_changed(self) -> list[int]:
        with self.shared.lock:
            out = sorted(self.changed_ts)
            self.changed_ts = set()
            return out

    def publish_buckets(self, changed: set[int]) -> set[int] | None:
        """Map fold-dirty BASE buckets to the output buckets an SSE
        delta frame must re-emit: the enclosing view bucket for
        tumbling, the trailing-window fan-out for sliding (hopping
        keeps only the slide-aligned edges of that fan-out), None
        (whole frame) for session windows — a fold anywhere can move
        a session's start bucket."""
        if self.window.kind == "session":
            return None
        iv = self.interval_ms
        out = {c - c % iv for c in changed}
        if self.window.kind == "sliding":
            k = self.window.size_ms // iv
            out = {c + i * iv for c in out for i in range(k)}
        elif self.window.kind == "hopping":
            k = self.window.size_ms // iv
            slide = self.window.slide_ms
            out = {e for c in out
                   for e in range(c - c % slide,
                                  c + (k - 1) * iv + 1, slide)
                   if e >= c}
        return out

    # ------------------------------------------------------------------
    # serve: grid derivation + window combine + pipeline tail
    # ------------------------------------------------------------------

    def _windowed_channels(self, start_ms: int, end_ms: int):
        """Channels over [start, end] at view granularity with the
        window combine applied. Sliding windows extend the derivation
        ``k-1`` buckets into trailing history when the ring covers it
        (leading outputs otherwise aggregate their clipped window).
        Caller holds ``shared.lock``."""
        iv = self.interval_ms
        ch = None
        lead = 0
        if self.window.kind in ("sliding", "hopping"):
            k = self.window.size_ms // iv
            ext = start_ms - (k - 1) * iv
            if ext > 0:
                ch = self.shared.channels_for(ext, end_ms, iv)
                if ch is not None:
                    lead = k - 1
        if ch is None:
            ch = self.shared.channels_for(start_ms, end_ms, iv)
            if ch is None:
                return None
        sums, cnts, mins, maxs, edges = ch
        # the REAL point count, before any window combine: a sliding
        # combine sums the count channel across k overlapping
        # windows, which would k-fold overcount against query limits
        num_points = int(cnts.sum())
        if self.window.kind == "sliding":
            k = self.window.size_ms // iv
            sums, cnts, mins, maxs = stream_fold.combine_sliding(
                sums, cnts, mins, maxs, k)
            if lead:
                sums, cnts = sums[:, lead:], cnts[:, lead:]
                mins, maxs = mins[:, lead:], maxs[:, lead:]
                edges = edges[lead:]
        elif self.window.kind == "hopping":
            k = self.window.size_ms // iv
            body = edges[lead:] if lead else edges
            sel = np.nonzero(body % self.window.slide_ms == 0)[0] \
                + lead
            sums, cnts, mins, maxs = stream_fold.combine_hopping(
                sums, cnts, mins, maxs, k, sel)
            edges = edges[sel]
            if not len(edges):
                # no slide-aligned edge falls in the range: the view
                # has nothing to emit (callers see a 0-bucket frame)
                num_points = 0
        elif self.window.kind == "session":
            sums, cnts, mins, maxs = stream_fold.session_grid(
                sums, cnts, mins, maxs, edges, self.window.gap_ms)
        return sums, cnts, mins, maxs, edges, num_points

    def grid_for(self, start_ms: int, end_ms: int):
        """[S, B] downsampled+windowed grid over the requested range,
        or None when outside the horizon. Caller holds
        ``shared.lock``."""
        ch = self._windowed_channels(start_ms, end_ms)
        if ch is None:
            return None
        sums, cnts, mins, maxs, edges, num_points = ch
        present = cnts > 0
        fn = self.sub.ds_spec.function
        if fn in ("sum", "zimsum", "pfsum"):
            grid = np.where(present, sums, np.nan)
        elif fn == "count":
            grid = np.where(present, cnts, np.nan)
        elif fn == "avg":
            grid = np.where(present, sums / np.maximum(cnts, 1.0),
                            np.nan)
        elif fn in ("min", "mimmin"):
            grid = np.where(present, mins, np.nan)
        else:  # max, mimmax
            grid = np.where(present, maxs, np.nan)
        return grid, present, edges, num_points

    def _groups_locked(self):
        """(tag_mat, group_ids, num_groups, gb_kids) over the current
        members, rebuilt only when membership changed. None when a
        group-by key has no UID yet (batch returns [] there too)."""
        cached = self._groups_cache
        if cached is not None and cached[0] == self.shared.member_seq:
            return cached[1]
        from opentsdb_tpu.query.engine import QueryEngine, TagMatrix
        uids = self.shared.tsdb.uids
        tag_mat = TagMatrix.from_pairs(self.shared._tag_pairs)
        gb_tagks = sorted({f.tagk for f in self.sub.filters
                           if f.group_by})
        gb_kids = []
        for k in gb_tagks:
            try:
                gb_kids.append(uids.tag_names.get_id(k))
            except LookupError:
                self._groups_cache = (self.shared.member_seq, None)
                return None
        group_ids, num_groups = QueryEngine._group_ids(tag_mat, gb_kids)
        out = (tag_mat, group_ids, num_groups, gb_kids)
        self._groups_cache = (self.shared.member_seq, out)
        return out

    def serve(self, tsq, sub: TSSubQuery, engine) -> list | None:
        """Answer one request from the maintained windows: drain is
        the caller's job (registry), here the grid derives from the
        shared partials and ONLY the pipeline tail runs (host CPU —
        dashboard-sized, and consistent with the degraded-fallback
        placement idiom). Returns result groups, [] for
        genuinely-empty, or None when this view cannot serve the
        window."""
        if self.sub.percentiles:
            return self._serve_percentiles(tsq, sub)
        shared = self.shared
        with shared.lock:
            g = self.grid_for(tsq.start_ms, tsq.end_ms)
            if g is None:
                return None
            grid, present, edges, num_points = g
            shared.tsdb.query_limits.check(shared.metric, num_points)
            if num_points == 0 or not len(shared._sids):
                return []
            groups = self._groups_locked()
            if groups is None:
                return []
            tag_mat, group_ids, num_groups, gb_kids = groups
            emit_raw = self.sub.agg.is_none
            if emit_raw:
                group_ids = np.arange(len(shared._sids),
                                      dtype=np.int32)
                num_groups = len(shared._sids)
            result, emit = self._tail_locked(edges, grid, present,
                                             group_ids, num_groups,
                                             emit_raw)
            sid_arr = np.asarray(shared._sids, dtype=np.int64)
            return engine._build_results(
                tsq, sub, shared.metric, sid_arr, tag_mat, group_ids,
                num_groups, gb_kids, edges, result, emit)

    def _serve_percentiles(self, tsq, sub) -> list | None:
        """Answer a percentile pull from the shared sketch channel:
        stride-merge the base buckets of each view bucket per group
        (sketch merges are exact), extract quantiles once through the
        batch sketch path's emitter — so a CQ pull and a batch
        ``/api/query`` over the same aligned window extract from
        identically-folded state."""
        shared = self.shared
        if self.window.kind != "tumbling":
            return None
        from opentsdb_tpu.sketch.ddsketch import SketchError
        from opentsdb_tpu.sketch.query import _emit
        iv = self.interval_ms
        with shared.lock:
            items = shared.sketch_items_for(tsq.start_ms, tsq.end_ms)
            if items is None:
                return None
            groups = self._groups_locked()
            if groups is None:
                return []
            tag_mat, group_ids, num_groups, gb_kids = groups
            gvec = np.asarray(group_ids, dtype=np.int64)
            acc: dict[tuple[int, int], Any] = {}
            num_points = 0
            first_edge = tsq.start_ms - tsq.start_ms % iv
            for slot, b, sk in items:
                out_b = b - b % iv
                if out_b < first_edge or out_b > tsq.end_ms:
                    continue
                num_points += sk.count
                key = (int(gvec[slot]), int(out_b))
                cur = acc.get(key)
                if cur is None:
                    acc[key] = sk.copy()  # never mutate ring state
                else:
                    try:
                        cur.merge(sk)
                    except SketchError:
                        acc[key] = sk.copy()  # alpha skew: newest wins
            shared.tsdb.query_limits.check(shared.metric, num_points)
            if not acc:
                return []
            return _emit(shared.tsdb, tsq, sub, tag_mat, group_ids,
                         num_groups, acc, False, True)

    def _tail_locked(self, edges, grid, present, group_ids,
                     num_groups: int, emit_raw: bool):
        """fill/rate/interpolate/aggregate over the derived grid — the
        exact kernel chain of the batch engine's grid path, pinned to
        the host CPU backend. Cached per (fold, membership, window)."""
        shared = self.shared
        key = (shared.fold_seq, shared.member_seq, int(edges[0]),
               len(edges))
        cached = self._tail_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        from opentsdb_tpu.ops.pipeline import PipelineSpec, execute_grid
        sub = self.sub
        spec = PipelineSpec(
            num_series=grid.shape[0], num_buckets=len(edges),
            num_groups=num_groups,
            # normalized like the engine's grid tail: downsampling
            # already happened (partials), the tail never reads it
            ds_function="avg", agg_name=sub.agg.name,
            fill_policy=sub.ds_spec.fill_policy,
            fill_value=sub.ds_spec.fill_value, rate=sub.rate,
            rate_counter=sub.rate_options.counter,
            rate_drop_resets=sub.rate_options.drop_resets,
            emit_raw=emit_raw, host=True)
        import jax
        cpu = jax.devices("cpu")[0]
        result, emit = execute_grid(grid, present, edges, group_ids,
                                    spec, sub.rate_options, device=cpu)
        out = (np.asarray(result), np.asarray(emit, dtype=bool))
        self._tail_cache = (key, out)
        return out

    # ------------------------------------------------------------------

    def info(self) -> dict[str, Any]:
        out = self.shared.info()
        out.update({
            "viewIntervalMs": self.interval_ms,
            "viewWindows": self.n_windows,
            "window": self.window.to_json(),
            "stride": self.stride,
        })
        return out
