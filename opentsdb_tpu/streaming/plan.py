"""Incremental window state for one continuous sub-query.

A standing sub-query compiles into tumbling windows aligned to its
downsample interval. Each window keeps per-series PARTIAL aggregates —
sum/count/min/max, with ``avg`` derived as sum/count at read time —
the same decomposition the rollup tiers use (``rollup/job.py``,
ref: RollupConfig sum+count qualifiers). Ingest folds new points into
the partials with vectorized scatters, so maintaining the query costs
O(new points); a refresh then derives the [S, B] downsampled grid from
the partials and runs ONLY the existing fill/rate/interpolate/
aggregate tail (:func:`opentsdb_tpu.ops.pipeline.execute_grid`) — the
store is never re-scanned. Because the tail is the same compiled
kernel chain the batch engine's grid path runs, maintained results are
value-identical to a cold ``/api/query`` over the same bucket-aligned
range (asserted by the streaming oracle battery).

Windows live in a ring of ``n_windows`` columns keyed by
``(bucket_ts // interval) % n_windows``; a point landing in a newer
bucket than a column holds tumbles that column (reset + re-key), and
points older than the ring's horizon are dropped and counted (they can
no longer affect any servable window).
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from opentsdb_tpu.ops import downsample as ds_mod
from opentsdb_tpu.query import filters as filters_mod
from opentsdb_tpu.query.model import TSSubQuery

# downsample functions whose bucket statistic decomposes into the
# sum/count/min/max partials this plan maintains (avg = sum / count) —
# mirrors the rollup tier decomposition AND the engine's _GRID_FNS, so
# every continuous query is also batch-grid-eligible
DECOMPOSABLE_DS = frozenset(("sum", "zimsum", "pfsum", "count", "min",
                             "mimmin", "max", "mimmax", "avg"))

_GROW = 64  # initial / doubling row capacity for the partial arrays


class IncrementalSubPlan:
    """Partial-aggregate window ring for one sub-query (see module
    docstring). Thread-safe: every mutation happens under ``lock``."""

    def __init__(self, tsdb, sub: TSSubQuery, n_windows: int):
        self.tsdb = tsdb
        self.sub = sub
        self.metric: str = sub.metric
        self.metric_id: int | None = None
        self.interval_ms = int(sub.ds_spec.interval_ms)
        self.n_windows = int(n_windows)
        self.lock = threading.RLock()
        self._filter_eval = filters_mod.FilterEvaluator(tsdb.uids)
        # membership: sid -> row slot (-1 = evaluated, not a member)
        self._slots: dict[int, int] = {}
        self._sids: list[int] = []
        self._tag_pairs: list[tuple] = []  # row -> ((kid, vid), ...)
        w = self.n_windows
        cap = _GROW
        self._sum = np.zeros((cap, w))
        self._cnt = np.zeros((cap, w))
        self._min = np.full((cap, w), np.inf)
        self._max = np.full((cap, w), -np.inf)
        self.win_ts = np.full(w, -1, dtype=np.int64)
        # the oldest bucket edge every ring column still covers; a
        # request starting before it cannot be served incrementally
        self.covered_from_ms = 0
        # newest folded timestamp: absolute-range serves past it are
        # exact (nothing newer exists to diverge on)
        self.max_ts_ms = 0
        # versions: folds invalidate the tail cache, membership
        # changes invalidate the group structures
        self.fold_seq = 0
        self.member_seq = 0
        # counters (read by the registry's stats/health export)
        self.points_folded = 0
        self.folds = 0
        self.late_dropped = 0
        self.bootstrap_points = 0
        # buckets touched since the last SSE publish
        self.changed_ts: set[int] = set()
        # pending (sids, ts_ms, values) chunks offered by the ingest
        # tap; folded in batches so the hot write path stays O(1)
        self._pending: list[tuple] = []
        self.pending_points = 0
        self.needs_rebuild = False
        self._tail_cache: tuple | None = None
        self._groups_cache: tuple | None = None
        # the raw store's mutation epoch at bootstrap: deletes/repairs
        # bump it, and partials cannot "unfold" removed points — the
        # registry forces a rebuild on mismatch before serving.
        # Known limitation (documented): DUPLICATE writes (same
        # series+timestamp rewritten) fold additively while the store
        # dedupes last-write-wins; they do not bump the epoch, so the
        # divergence persists until a tumble or rebuild. The reference
        # treats duplicate writes as an error condition
        # (tsd.storage.fix_duplicates), so this trades exactness on an
        # abnormal workload for an O(1) write path.
        self.store_epoch = -1

    # ------------------------------------------------------------------
    # bootstrap: one batch scan seeds the partials, then folds keep up
    # ------------------------------------------------------------------

    def bootstrap(self, now_ms: int) -> None:
        """Seed the window ring from the store: one fused
        ``bucket_reduce`` pass over the horizon produces exactly the
        sum/count/min/max partials the folds maintain afterwards."""
        with self.lock:
            iv, w = self.interval_ms, self.n_windows
            last_edge = now_ms - now_ms % iv
            start_edge = last_edge - (w - 1) * iv
            edges = start_edge + np.arange(w, dtype=np.int64) * iv
            cols = ((edges // iv) % w).astype(np.int64)
            self.win_ts = np.full(w, -1, dtype=np.int64)
            self.win_ts[cols] = edges
            self._slots.clear()
            self._sids = []
            self._tag_pairs = []
            self._sum[:] = 0.0
            self._cnt[:] = 0.0
            self._min[:] = np.inf
            self._max[:] = -np.inf
            self._pending = []
            self.pending_points = 0
            self._tail_cache = None
            self._groups_cache = None
            self.covered_from_ms = int(start_edge)
            self.max_ts_ms = int(now_ms)
            self.store_epoch = getattr(self.tsdb.store,
                                       "mutation_epoch", 0)
            uids = self.tsdb.uids
            try:
                self.metric_id = uids.metrics.get_id(self.metric)
            except LookupError:
                self.metric_id = None  # metric not written yet
                self.member_seq += 1
                self.fold_seq += 1
                return
            store = self.tsdb.store
            sids = store.series_ids_for_metric(self.metric_id)
            if len(sids) and self.sub.filters:
                idx = store.metric_index(self.metric_id)
                _, triples = idx.arrays()
                mask = self._filter_eval.apply(self.sub.filters, sids,
                                               triples)
                sids = sids[mask]
            for sid in np.asarray(sids).tolist():
                self._admit_locked(int(sid), check_filters=False)
            if len(self._sids):
                sid_arr = np.asarray(self._sids, dtype=np.int64)
                sums, cnts, mins, maxs = store.bucket_reduce(
                    sid_arr, int(start_edge), int(start_edge + w * iv - 1),
                    int(start_edge), iv, w, want_minmax=True)
                s = len(sid_arr)
                self._grow_to(s)
                self._sum[:s, cols] = sums
                self._cnt[:s, cols] = cnts
                present = cnts > 0
                self._min[:s, cols] = np.where(present, mins, np.inf)
                self._max[:s, cols] = np.where(present, maxs, -np.inf)
                self.bootstrap_points += int(cnts.sum())
            self.member_seq += 1
            self.fold_seq += 1

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------

    def _grow_to(self, rows: int) -> None:
        cap = self._sum.shape[0]
        if rows <= cap:
            return
        new_cap = cap
        while new_cap < rows:
            new_cap *= 2
        w = self.n_windows

        def grow(arr, fill):
            out = np.full((new_cap, w), fill, dtype=arr.dtype)
            out[:cap] = arr
            return out

        self._sum = grow(self._sum, 0.0)
        self._cnt = grow(self._cnt, 0.0)
        self._min = grow(self._min, np.inf)
        self._max = grow(self._max, -np.inf)

    def _admit_locked(self, sid: int, check_filters: bool = True) -> int:
        """Slot for ``sid``, admitting it when it matches the plan's
        filters (a series first seen by a WRITE is brand new — its
        points arrive through the very fold that admits it, so no
        backfill is needed). Returns -1 for non-members."""
        slot = self._slots.get(sid)
        if slot is not None:
            return slot
        rec = self.tsdb.store.series(sid)
        if self.metric_id is None:
            # the metric materialized after registration: latch its id
            try:
                self.metric_id = self.tsdb.uids.metrics.get_id(
                    self.metric)
            except LookupError:
                return -1
        if rec.metric_id != self.metric_id:
            self._slots[sid] = -1
            return -1
        if check_filters and self.sub.filters:
            triples = (np.asarray(
                [(sid, k, v) for k, v in rec.tags],
                dtype=np.int64).reshape(-1, 3)
                if rec.tags else np.empty((0, 3), dtype=np.int64))
            mask = self._filter_eval.apply(
                self.sub.filters, np.asarray([sid], dtype=np.int64),
                triples)
            if not bool(mask[0]):
                self._slots[sid] = -1
                return -1
        slot = len(self._sids)
        self._grow_to(slot + 1)
        self._slots[sid] = slot
        self._sids.append(sid)
        self._tag_pairs.append(tuple(rec.tags))
        self.member_seq += 1
        return slot

    # ------------------------------------------------------------------
    # ingest folds
    # ------------------------------------------------------------------

    def offer(self, sids: np.ndarray, ts_ms: np.ndarray,
              values: np.ndarray) -> int:
        """Buffer a chunk from the ingest tap (O(1) append); returns
        the pending-point total so the registry can decide to drain."""
        with self.lock:
            self._pending.append((sids, ts_ms, values))
            self.pending_points += len(ts_ms)
            return self.pending_points

    def take_pending(self) -> list[tuple]:
        with self.lock:
            out, self._pending = self._pending, []
            self.pending_points = 0
            return out

    def fold(self, sids: np.ndarray, ts_ms: np.ndarray,
             values: np.ndarray) -> None:
        """Fold one chunk of points into the window partials."""
        with self.lock:
            iv, w = self.interval_ms, self.n_windows
            sids = np.asarray(sids, dtype=np.int64).reshape(-1)
            ts_ms = np.asarray(ts_ms, dtype=np.int64).reshape(-1)
            values = np.asarray(values, dtype=np.float64).reshape(-1)
            slots = np.empty(len(sids), dtype=np.int64)
            slot_map = self._slots
            for i, sid in enumerate(sids.tolist()):
                s = slot_map.get(sid)
                if s is None:
                    s = self._admit_locked(sid)
                slots[i] = s
            keep = (slots >= 0) & ~np.isnan(values)
            if not keep.any():
                self.folds += 1
                return
            slots = slots[keep]
            ts = ts_ms[keep]
            vals = values[keep]
            bucket = ts - ts % iv
            col = ((bucket // iv) % w).astype(np.int64)
            # tumble columns whose newest incoming bucket is newer
            for c in np.unique(col).tolist():
                nb = int(bucket[col == c].max())
                if nb > self.win_ts[c]:
                    self._sum[:, c] = 0.0
                    self._cnt[:, c] = 0.0
                    self._min[:, c] = np.inf
                    self._max[:, c] = -np.inf
                    self.win_ts[c] = nb
                    self.covered_from_ms = max(
                        self.covered_from_ms, nb - (w - 1) * iv)
            live = bucket == self.win_ts[col]
            self.late_dropped += int((~live).sum())
            if live.any():
                slots, col = slots[live], col[live]
                vals, bucket = vals[live], bucket[live]
                np.add.at(self._sum, (slots, col), vals)
                np.add.at(self._cnt, (slots, col), 1.0)
                np.minimum.at(self._min, (slots, col), vals)
                np.maximum.at(self._max, (slots, col), vals)
                self.changed_ts.update(
                    int(b) for b in np.unique(bucket).tolist())
                if len(self.changed_ts) > 4 * w:
                    # nobody is draining the changed-set (no
                    # subscriber): keep it bounded by the horizon
                    cutoff = self.covered_from_ms
                    self.changed_ts = {c for c in self.changed_ts
                                       if c >= cutoff}
                self.points_folded += len(vals)
                self.max_ts_ms = max(self.max_ts_ms, int(ts.max()))
                self.fold_seq += 1
                self._tail_cache = None
            self.folds += 1

    # ------------------------------------------------------------------
    # read side: derive the downsampled grid + run the pipeline tail
    # ------------------------------------------------------------------

    def grid_for(self, start_ms: int, end_ms: int):
        """[S, B] downsampled grid over the requested range derived
        from the partials, or None when the range is outside the
        maintained horizon. Caller holds ``lock``."""
        iv, w = self.interval_ms, self.n_windows
        edges = ds_mod.fixed_bucket_edges(start_ms, end_ms, iv)
        if len(edges) == 0 or len(edges) > w:
            return None
        if int(edges[0]) < self.covered_from_ms:
            return None
        cols = ((edges // iv) % w).astype(np.int64)
        live = self.win_ts[cols] == edges
        s = len(self._sids)
        sums = np.where(live[None, :], self._sum[:s][:, cols], 0.0)
        cnts = np.where(live[None, :], self._cnt[:s][:, cols], 0.0)
        present = cnts > 0
        fn = self.sub.ds_spec.function
        if fn in ("sum", "zimsum", "pfsum"):
            grid = np.where(present, sums, np.nan)
        elif fn == "count":
            grid = np.where(present, cnts, np.nan)
        elif fn == "avg":
            grid = np.where(present, sums / np.maximum(cnts, 1.0),
                            np.nan)
        elif fn in ("min", "mimmin"):
            mins = np.where(live[None, :], self._min[:s][:, cols],
                            np.inf)
            grid = np.where(present, mins, np.nan)
        else:  # max, mimmax
            maxs = np.where(live[None, :], self._max[:s][:, cols],
                            -np.inf)
            grid = np.where(present, maxs, np.nan)
        return grid, present, edges, int(cnts.sum())

    def _groups_locked(self):
        """(tag_mat, group_ids, num_groups, gb_kids) over the current
        members, rebuilt only when membership changed. None when a
        group-by key has no UID yet (batch returns [] there too)."""
        cached = self._groups_cache
        if cached is not None and cached[0] == self.member_seq:
            return cached[1]
        from opentsdb_tpu.query.engine import QueryEngine, TagMatrix
        uids = self.tsdb.uids
        tag_mat = TagMatrix.from_pairs(self._tag_pairs)
        gb_tagks = sorted({f.tagk for f in self.sub.filters
                           if f.group_by})
        gb_kids = []
        for k in gb_tagks:
            try:
                gb_kids.append(uids.tag_names.get_id(k))
            except LookupError:
                self._groups_cache = (self.member_seq, None)
                return None
        group_ids, num_groups = QueryEngine._group_ids(tag_mat, gb_kids)
        out = (tag_mat, group_ids, num_groups, gb_kids)
        self._groups_cache = (self.member_seq, out)
        return out

    def serve(self, tsq, sub: TSSubQuery, engine) -> list | None:
        """Answer one request from the maintained windows: drain is the
        caller's job (registry), here the grid derives from partials
        and ONLY the pipeline tail runs (host CPU — dashboard-sized,
        and consistent with the degraded-fallback placement idiom).
        Returns result groups, [] for genuinely-empty, or None when
        this plan cannot serve the window."""
        with self.lock:
            g = self.grid_for(tsq.start_ms, tsq.end_ms)
            if g is None:
                return None
            grid, present, edges, num_points = g
            self.tsdb.query_limits.check(self.metric, num_points)
            if num_points == 0 or not len(self._sids):
                return []
            groups = self._groups_locked()
            if groups is None:
                return []
            tag_mat, group_ids, num_groups, gb_kids = groups
            emit_raw = self.sub.agg.is_none
            if emit_raw:
                group_ids = np.arange(len(self._sids), dtype=np.int32)
                num_groups = len(self._sids)
            result, emit = self._tail_locked(edges, grid, present,
                                             group_ids, num_groups,
                                             emit_raw)
            sid_arr = np.asarray(self._sids, dtype=np.int64)
            return engine._build_results(
                tsq, sub, self.metric, sid_arr, tag_mat, group_ids,
                num_groups, gb_kids, edges, result, emit)

    def _tail_locked(self, edges, grid, present, group_ids,
                     num_groups: int, emit_raw: bool):
        """fill/rate/interpolate/aggregate over the derived grid — the
        exact kernel chain of the batch engine's grid path, pinned to
        the host CPU backend. Cached per (fold, membership, window)."""
        key = (self.fold_seq, self.member_seq, int(edges[0]),
               len(edges))
        cached = self._tail_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        from opentsdb_tpu.ops.pipeline import PipelineSpec, execute_grid
        sub = self.sub
        spec = PipelineSpec(
            num_series=grid.shape[0], num_buckets=len(edges),
            num_groups=num_groups,
            # normalized like the engine's grid tail: downsampling
            # already happened (partials), the tail never reads it
            ds_function="avg", agg_name=sub.agg.name,
            fill_policy=sub.ds_spec.fill_policy,
            fill_value=sub.ds_spec.fill_value, rate=sub.rate,
            rate_counter=sub.rate_options.counter,
            rate_drop_resets=sub.rate_options.drop_resets,
            emit_raw=emit_raw, host=True)
        import jax
        cpu = jax.devices("cpu")[0]
        result, emit = execute_grid(grid, present, edges, group_ids,
                                    spec, sub.rate_options, device=cpu)
        out = (np.asarray(result), np.asarray(emit, dtype=bool))
        self._tail_cache = (key, out)
        return out

    # ------------------------------------------------------------------

    def take_changed(self) -> list[int]:
        with self.lock:
            out = sorted(self.changed_ts)
            self.changed_ts = set()
            return out

    def info(self) -> dict[str, Any]:
        with self.lock:
            return {
                "metric": self.metric,
                "intervalMs": self.interval_ms,
                "windows": self.n_windows,
                "series": len(self._sids),
                "coveredFromMs": self.covered_from_ms,
                "pointsFolded": self.points_folded,
                "folds": self.folds,
                "pendingPoints": self.pending_points,
                "lateDropped": self.late_dropped,
                "bootstrapPoints": self.bootstrap_points,
                "needsRebuild": self.needs_rebuild,
            }
