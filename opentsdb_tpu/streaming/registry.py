"""Continuous-query registry v2: standing TSQueries maintained by
shared off-path fold workers and served three ways.

Clients register a standing TSQuery (``POST /api/query/continuous``,
optionally with a ``window`` object — tumbling by default, sliding or
session-gap). Each sub-query compiles into a
:class:`~opentsdb_tpu.streaming.plan.PlanView` attached to a
:class:`~opentsdb_tpu.streaming.plan.SharedPartial` keyed by the
canonical sub-plan identity ``(metric, membership filters, base
downsample interval)`` — N continuous queries over the same
sub-expression share ONE partial array and one fold
(multi-query plan sharing; a divisible coarser interval derives by
stride combine).

The ingest tap (``TSDB.add_point`` / ``add_points`` /
``import_buffer`` through :meth:`offer`) is an O(1) columnar append
per partial — folds NEVER run on the write path. When a partial's
backlog crosses ``tsd.streaming.buffer_points`` it is handed to the
shared fold-worker pool (:mod:`opentsdb_tpu.streaming.workers`); a
backlog past ``tsd.streaming.workers.max_pending_points`` degrades
the lagging partial to rebuild-on-serve (backlog dropped, counted)
instead of blocking or failing the acknowledged write.

Results serve three ways:

- **pull** — the query engine consults :meth:`try_serve` before the
  result cache: a live-window request matching a registered tumbling
  query is answered from the maintained partials (synchronous drain +
  pipeline tail, never a store scan — and never stale: the serve path
  drains pending folds itself, whatever the workers' lag).
- **push** — Server-Sent Events (``GET /api/query/continuous/<id>/
  stream``) emitting incremental window updates, with bounded
  per-subscription queues and slow-consumer shedding
  (:mod:`opentsdb_tpu.streaming.sse`).
- **fetch** — ``GET /api/query/continuous/<id>/result`` returns the
  current windowed results (the only pull surface for sliding /
  session windows, which no plain TSQuery can express).

Bootstrap seeds partials from the raw store — and, when the window
reaches behind the metric's demotion boundary, from the rollup/cold
tiers through the stitched per-stat views, so pre-boundary windows
serve incrementally instead of shedding to the batch engine.

Degradation follows the PR-1 idiom: serve-path folds/rebuilds run
under the ``stream.fold`` fault site, worker drains additionally
under ``stream.worker``, both behind one :class:`CircuitBreaker`; a
failed fold marks the partial for rebuild (one batch re-scan), a
tripped breaker routes pulls back to the batch engine (shed to the
always-correct path, never a 500) until the reset-window probe heals
it. Counters export through /api/stats and /api/health.

Knobs (``tsd.streaming.*``): ``enable``, ``serve``, ``max_queries``,
``max_windows``, ``buffer_points``, ``queue_events``,
``heartbeat_s``, ``publish_min_interval_ms``, ``resume_events``,
``workers.count``, ``workers.max_pending_points``,
``breaker.failure_threshold``, ``breaker.reset_timeout_ms``.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any

import numpy as np

from opentsdb_tpu.query.model import BadRequestError, TSQuery
from opentsdb_tpu.query.result_cache import _is_relative
from opentsdb_tpu.streaming.eventtime import (SessionPartial,
                                              WatermarkPolicy,
                                              completeness_marker)
from opentsdb_tpu.streaming.plan import (DECOMPOSABLE_DS, PlanView,
                                         SharedPartial, WindowSpec,
                                         filter_identity)
from opentsdb_tpu.streaming.workers import FoldWorkerPool
from opentsdb_tpu.utils.faults import CircuitBreaker, DegradedError

LOG = logging.getLogger("streaming.registry")


class ContinuousQuery:
    """One registered standing query: the validated TSQuery plus one
    plan view per sub-query and the SSE subscriber set."""

    def __init__(self, cid: str, raw: dict, tsq: TSQuery,
                 plans: list[PlanView],
                 policy: WatermarkPolicy | None = None):
        self.id = cid
        self.raw = raw          # original JSON body (re-resolved per emit)
        self.tsq = tsq
        self.plans = plans
        # event-time watermark/lateness policy (None = legacy
        # processing-time contract, no completeness markers)
        self.policy = policy
        self.created = time.time()
        self.lock = threading.Lock()
        self.subscribers: list = []
        self.emit_seq = 0
        self.last_publish = 0.0
        self.closed = False
        # bounded replay history for SSE resume (Last-Event-ID): the
        # last N published `windows` frames, each tagged with its emit
        # seq. evicted_seq = the newest frame pushed out — a reconnect
        # older than it has missed un-replayable events and falls back
        # to a snapshot.
        self.history: list[tuple[int, bytes]] = []
        self.evicted_seq = 0

    def fold_bytes(self) -> int:
        """Resident ring bytes this query's views hold (distinct
        shared partials counted once) — the per-CQ attribution the
        tenant fold budget sums."""
        seen: set[int] = set()
        total = 0
        for p in self.plans:
            g = p.shared
            if id(g) in seen:
                continue
            seen.add(id(g))
            total += g.ring_bytes()
        return total

    def describe(self, verbose: bool = False) -> dict[str, Any]:
        out: dict[str, Any] = {
            "id": self.id,
            "query": self.tsq.to_json(),
            "intervalMs": [p.interval_ms for p in self.plans],
            "windows": [p.n_windows for p in self.plans],
            "series": sum(len(p._sids) for p in self.plans),
            "subscribers": len(self.subscribers),
            "emitSeq": self.emit_seq,
            "foldBytes": self.fold_bytes(),
        }
        if self.plans:
            out["windowSpec"] = self.plans[0].window.to_json()
            out["sharedPlan"] = [len(p.shared.views) > 1
                                 for p in self.plans]
        if self.policy is not None:
            out["watermark"] = self.policy.to_json()
        if verbose:
            out["plans"] = [p.info() for p in self.plans]
        return out


class ContinuousQueryRegistry:
    """(see module docstring)"""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        cfg = tsdb.config
        self._lock = threading.Lock()
        # registrations serialize here (control-plane; the ingest tap
        # and publish paths never take it) so two concurrent registers
        # cannot mint duplicate shared partials for one identity
        self._register_lock = threading.Lock()
        self._ids = itertools.count(1)
        self._queries: dict[str, ContinuousQuery] = {}
        # every live shared partial (fold state the tap feeds)
        self._partials: list[SharedPartial] = []
        # metric_id -> partials watching it (the tap's fast path);
        # partials whose metric has no UID yet park in _unresolved
        # until a write materializes the metric
        self._by_mid: dict[int, list[SharedPartial]] = {}
        self._unresolved: list[SharedPartial] = []
        # (metric, sub identity) -> tumbling view for the pull path
        # (sliding/session views are push/fetch-only: a plain TSQuery
        # cannot express their combine)
        self._by_identity: dict[tuple, PlanView] = {}
        self.max_queries = cfg.get_int("tsd.streaming.max_queries", 64)
        self.max_windows = cfg.get_int("tsd.streaming.max_windows",
                                       2880)
        self.buffer_points = cfg.get_int("tsd.streaming.buffer_points",
                                         4096)
        self.max_pending_points = cfg.get_int(
            "tsd.streaming.workers.max_pending_points", 262144)
        self.queue_events = cfg.get_int("tsd.streaming.queue_events",
                                        256)
        self.heartbeat_s = cfg.get_float("tsd.streaming.heartbeat_s",
                                         5.0)
        self.publish_min_interval_ms = cfg.get_float(
            "tsd.streaming.publish_min_interval_ms", 200.0)
        # SSE resume replay depth (0 disables Last-Event-ID resume)
        self.resume_events = cfg.get_int(
            "tsd.streaming.resume_events", 64)
        threshold = cfg.get_int(
            "tsd.streaming.breaker.failure_threshold", 3)
        self.breaker = CircuitBreaker(
            "stream.fold", failure_threshold=threshold,
            reset_timeout_ms=cfg.get_float(
                "tsd.streaming.breaker.reset_timeout_ms", 30000.0)) \
            if threshold > 0 else None
        if self.breaker is not None:
            tsdb.stats.register(self.breaker)
        self.workers = FoldWorkerPool(
            self, cfg.get_int("tsd.streaming.workers.count", 2))
        # live SSE subscriber count, maintained so the ingest tap's
        # publish check is one integer read (never a registry walk)
        self._active_subs = 0
        # counters
        self.serve_hits = 0
        self.serve_fallbacks = 0
        self.fold_errors = 0
        self.rebuilds = 0
        self.backpressure_drops = 0
        self.backpressure_events = 0
        self.tier_seeded_bootstraps = 0
        self.sse_shed = 0
        self.sse_events = 0
        self.sse_resumes = 0
        self.sse_resume_snapshots = 0
        self.sse_events_delivered = 0  # frames on CLOSED streams
        self.publishes = 0

    # ------------------------------------------------------------------
    # registration surface
    # ------------------------------------------------------------------

    def register(self, obj: dict, now_ms: int | None = None
                 ) -> ContinuousQuery:
        """Validate + compile one standing TSQuery; raises
        :class:`BadRequestError` on anything the incremental engine
        cannot maintain (the client should run it as a plain query)."""
        if not isinstance(obj, dict):
            raise BadRequestError("continuous query must be an object")
        cid = obj.get("id")
        window_obj = obj.get("window")
        policy = WatermarkPolicy.from_json(obj.get("watermark"))
        body = {k: v for k, v in obj.items() if k != "id"}
        tsq = TSQuery.from_json(body).validate(now_ms)
        if tsq.delete:
            raise BadRequestError(
                "delete=true cannot be a continuous query")
        if tsq.timezone or tsq.use_calendar:
            raise BadRequestError(
                "continuous queries do not support timezone/calendar "
                "downsampling")
        specs: list[tuple] = []
        for sub in tsq.queries:
            if sub.tsuids or not sub.metric:
                raise BadRequestError(
                    "continuous queries require a metric (tsuids are "
                    "not supported)")
            if sub.explicit_tags:
                raise BadRequestError(
                    "continuous queries do not support explicitTags")
            spec = sub.ds_spec
            if spec is None or spec.run_all or spec.use_calendar \
                    or spec.unit in ("n", "y") or spec.interval_ms <= 0:
                raise BadRequestError(
                    "continuous queries require a fixed-interval "
                    "downsample (e.g. 1m-avg)")
            if spec.function not in DECOMPOSABLE_DS:
                raise BadRequestError(
                    f"downsample function {spec.function!r} is not "
                    f"decomposable into streaming partials "
                    f"(supported: {', '.join(sorted(DECOMPOSABLE_DS))})")
            window = WindowSpec.from_json(window_obj, spec.interval_ms)
            if window.by_tag:
                # per-tag session rows ARE the tag's values: grouping
                # by any other key has no per-row answer, and the
                # sketch channel is per-series — both refuse loudly
                # instead of answering wrong
                bad_gb = sorted({f.tagk for f in sub.filters
                                 if f.group_by} - {window.by_tag})
                if bad_gb:
                    raise BadRequestError(
                        f"session window by={window.by_tag!r} cannot "
                        f"group by other tags ({', '.join(bad_gb)})")
                if sub.percentiles:
                    raise BadRequestError(
                        "per-tag session windows do not support "
                        "percentiles (the sketch channel is "
                        "per-series)")
            if sub.percentiles:
                # percentile CQs serve from the shared ring's sketch
                # channel; only tumbling windows extract exactly
                # (sliding/session would need per-window sketch
                # re-merges the channel does not maintain)
                if not self.tsdb.config.get_bool(
                        "tsd.sketch.enable", True):
                    raise BadRequestError(
                        "continuous percentile queries need the "
                        "sketch subsystem (tsd.sketch.enable)")
                if window.kind != "tumbling":
                    raise BadRequestError(
                        "continuous percentile queries support "
                        "tumbling windows only")
            lat_b = policy.lateness_buckets(spec.interval_ms) \
                if policy is not None else 0
            windows = int((tsq.end_ms - tsq.start_ms)
                          // spec.interval_ms) + 2 \
                + window.lead_for(spec.interval_ms) + lat_b
            if windows > self.max_windows:
                raise BadRequestError(
                    f"window range needs {windows} tumbling windows; "
                    f"tsd.streaming.max_windows={self.max_windows}")
            specs.append((sub, window, windows))
        # the horizon anchors at the query's RESOLVED end: now for the
        # live-dashboard shape (end=now), the window's own end for an
        # absolute registration — either way the ring covers exactly
        # the window the standing query answers, and tumbles forward
        # with ingest from there
        anchor_ms = tsq.end_ms
        with self._register_lock:
            with self._lock:
                if len(self._queries) >= self.max_queries:
                    raise BadRequestError(
                        f"too many continuous queries (tsd.streaming."
                        f"max_queries={self.max_queries})")
                if cid is None:
                    cid = f"cq{next(self._ids)}"
                cid = str(cid)
                if cid in self._queries:
                    raise BadRequestError(
                        f"continuous query {cid!r} already exists")
                # reserve the id; the bootstrap scans below run
                # OUTSIDE the registry lock (the ingest tap takes it —
                # a wide bootstrap must not stall every write)
                self._queries[cid] = cq = ContinuousQuery(
                    cid, body, tsq, [], policy=policy)
            new_groups: list[SharedPartial] = []
            views: list[PlanView] = []
            try:
                for sub, window, need_w in specs:
                    fid = filter_identity(sub)
                    # a lateness policy (strict drops) or per-tag
                    # session keying (rows are tag values) changes
                    # fold SEMANTICS, not just the view combine —
                    # such partials only share with identical twins
                    if policy is not None:
                        fid = fid + (
                            f"lateness={policy.lateness_ms}",)
                    if window.by_tag:
                        fid = fid + (f"session_by={window.by_tag}",)
                    view_iv = int(sub.ds_spec.interval_ms)
                    with self._lock:
                        group = self._find_group_locked(
                            sub.metric, fid, view_iv)
                    if group is not None:
                        # the shared ring must cover BOTH its current
                        # span and this view's (lead-extended) range
                        # from the joint anchor; if stretching over
                        # both would exceed max_windows (e.g. a live
                        # dashboard attaching to a partial anchored
                        # on an old absolute range), the view gets
                        # its own partial instead of silently never
                        # being covered
                        base_iv = group.interval_ms
                        with group.lock:
                            newest = int(group.win_ts.max())
                            covered = group.covered_from_ms
                        anchor = max(anchor_ms,
                                     newest if newest > 0 else 0)
                        anchor_edge = anchor - anchor % base_iv
                        lat_v = policy.lateness_buckets(view_iv) \
                            if policy is not None else 0
                        start_edge = (
                            tsq.start_ms - tsq.start_ms % view_iv
                            - (window.lead_for(view_iv) + lat_v)
                            * view_iv)
                        floor = min(start_edge, covered) \
                            if covered else start_edge
                        needed = int(
                            (anchor_edge - floor) // base_iv) + 2
                        if needed > self.max_windows:
                            group = None
                        else:
                            if sub.percentiles:
                                # a ring that predates its first
                                # percentile view seeds the sketch
                                # channel on the rebuild below (or
                                # lazily at first serve)
                                group.enable_sketch()
                            if group.ensure_horizon(needed, anchor_ms):
                                if group.tier_seeded:
                                    self.tier_seeded_bootstraps += 1
                    if group is None:
                        if window.by_tag:
                            group = SessionPartial(
                                self.tsdb, sub.metric, sub.filters,
                                view_iv, need_w, window.by_tag)
                        else:
                            group = SharedPartial(
                                self.tsdb, sub.metric, sub.filters,
                                view_iv, need_w)
                        group.filter_key = fid
                        if policy is not None:
                            group.lateness_ms = policy.lateness_ms
                        if sub.percentiles:
                            group.want_sketch = True
                        group.bootstrap(anchor_ms)
                        if group.tier_seeded:
                            self.tier_seeded_bootstraps += 1
                        new_groups.append(group)
                    view = PlanView(group, sub, need_w, window)
                    views.append(view)
                for view in views:
                    view.shared.attach(view)
                cq.plans = views
                with self._lock:
                    for group in new_groups:
                        self._partials.append(group)
                        self._index_group_locked(group)
                    for view in views:
                        # policy views drop late points the raw store
                        # accepted, so they can no longer answer
                        # /api/query value-identically — pull through
                        # .../result, where the marker says what you
                        # got
                        if view.window.kind == "tumbling" \
                                and policy is None:
                            key = (view.metric,
                                   view.sub.identity_key())
                            self._by_identity.setdefault(key, view)
            except BaseException:
                for view in views:
                    view.shared.detach(view)
                with self._lock:
                    self._queries.pop(cid, None)
                raise
        LOG.info("registered continuous query %s (%d sub-plans, "
                 "%d new shared partials)", cid, len(views),
                 len(new_groups))
        return cq

    def _find_group_locked(self, metric: str, fid: tuple,
                           interval_ms: int) -> SharedPartial | None:
        """The best existing shared partial this sub-expression can
        attach to: same metric, same membership filters, base
        interval dividing the sub's interval (coarsest such base
        wins — least stride work per serve)."""
        best = None
        for g in self._partials:
            if g.metric == metric \
                    and getattr(g, "filter_key", None) == fid \
                    and interval_ms % g.interval_ms == 0:
                if best is None or g.interval_ms > best.interval_ms:
                    best = g
        return best

    def _index_group_locked(self, group: SharedPartial) -> None:
        if group.metric_id is not None:
            self._by_mid.setdefault(group.metric_id, []).append(group)
        else:
            self._unresolved.append(group)

    def _drop_group_locked(self, group: SharedPartial) -> None:
        if group in self._partials:
            self._partials.remove(group)
        if group.metric_id is not None:
            lst = self._by_mid.get(group.metric_id, [])
            if group in lst:
                lst.remove(group)
            if not lst:
                self._by_mid.pop(group.metric_id, None)
        if group in self._unresolved:
            self._unresolved.remove(group)

    def delete(self, cid: str) -> bool:
        with self._lock:
            cq = self._queries.pop(cid, None)
            if cq is None:
                return False
            cq.closed = True
            for view in cq.plans:
                if view.shared.detach(view):
                    self._drop_group_locked(view.shared)
                if view.window.kind != "tumbling":
                    continue
                key = (view.metric, view.sub.identity_key())
                if self._by_identity.get(key) is view:
                    del self._by_identity[key]
                    # a surviving query with the same identity takes
                    # over the pull path instead of silently falling
                    # back to batch scans (policy queries stay out of
                    # it: strict lateness breaks batch exactness)
                    for other in self._queries.values():
                        if other.policy is not None:
                            continue
                        for p in other.plans:
                            if p.window.kind == "tumbling" and \
                                    (p.metric,
                                     p.sub.identity_key()) == key:
                                self._by_identity[key] = p
                                break
                        if key in self._by_identity:
                            break
            subs = list(cq.subscribers)
        from opentsdb_tpu.streaming import sse
        for sub in subs:
            sse.offer_frame(sub, sse.frame(
                "deleted", {"id": cid}))
        return True

    def get(self, cid: str) -> ContinuousQuery | None:
        with self._lock:
            return self._queries.get(cid)

    def list(self) -> list[ContinuousQuery]:
        with self._lock:
            return [self._queries[k] for k in sorted(self._queries)]

    def invalidate(self) -> None:
        """Mark every partial for rebuild (the ``/api/dropcaches``
        escape hatch: the next serve/pump re-seeds from the store)."""
        with self._lock:
            groups = list(self._partials)
        for group in groups:
            group.needs_rebuild = True

    def shutdown(self) -> None:
        for cq in self.list():
            self.delete(cq.id)
        self.workers.stop()

    # ------------------------------------------------------------------
    # ingest tap (called from TSDB under the write-hook guard):
    # O(1) columnar enqueue per shared partial — never a fold
    # ------------------------------------------------------------------

    def _groups_for(self, metric_id: int
                    ) -> list[SharedPartial] | None:
        groups = self._by_mid.get(metric_id)
        if groups is not None or not self._unresolved:
            return groups
        # a parked partial's metric may have just been minted by this
        # very write: resolve by name once, then the fast path hits
        with self._lock:
            if not self._unresolved:
                return self._by_mid.get(metric_id)
            try:
                name = self.tsdb.uids.metrics.get_name(metric_id)
            except LookupError:
                return None
            for group in list(self._unresolved):
                if group.metric == name:
                    group.metric_id = metric_id
                    self._unresolved.remove(group)
                    self._by_mid.setdefault(metric_id,
                                            []).append(group)
            return self._by_mid.get(metric_id)

    def offer(self, metric_id: int, sid: int, ts_ms: int,
              value: float) -> None:
        groups = self._groups_for(metric_id)
        if not groups:
            return
        for group in groups:
            self._post_offer(group,
                             group.offer_one(sid, ts_ms, value))
        self._notify_publish()

    def offer_many(self, metric_id: int, sid: int, ts_ms: np.ndarray,
                   values: np.ndarray) -> None:
        groups = self._groups_for(metric_id)
        if not groups:
            return
        n = len(ts_ms)
        sid_a = np.full(n, sid, dtype=np.int64)
        for group in groups:
            self._post_offer(group,
                             group.offer(sid_a, ts_ms, values))
        self._notify_publish()

    def _post_offer(self, group: SharedPartial, pending: int) -> None:
        """Post-enqueue policy, still on the write path so it must be
        O(1): hand a full buffer to the workers; DEGRADE a partial
        whose backlog says the workers cannot keep up — drop the
        backlog, rebuild on the next serve, never block the write."""
        if pending > self.max_pending_points:
            dropped = group.drop_pending()
            group.needs_rebuild = True
            self.backpressure_drops += dropped
            self.backpressure_events += 1
            LOG.warning(
                "streaming partial for %s lagging (%d pending "
                "points > tsd.streaming.workers.max_pending_points);"
                " degraded to rebuild-on-serve", group.metric,
                dropped)
        elif pending >= self.buffer_points:
            if self.workers.enabled:
                self.workers.submit(group)
            else:
                # workers disabled (tsd.streaming.workers.count=0):
                # the v1 inline drain is the explicit opt-back-in
                self._drain_group(group)

    def _notify_publish(self) -> None:
        if self._active_subs <= 0:
            return
        if self.workers.enabled:
            self.workers.notify_publish()
        else:
            self._maybe_publish()

    # ------------------------------------------------------------------
    # folds: off-path (workers) or serve-path (synchronous freshness)
    # ------------------------------------------------------------------

    def _drain_group(self, group: SharedPartial) -> None:
        """Fold a partial's pending chunks under the ``stream.fold``
        fault site + breaker. Drains serialize per partial
        (``_drain_lock``) so worker and serve-path drains fold chunks
        in arrival order. A failed fold loses the chunks, so the
        partial is marked for rebuild (one batch re-scan) —
        correctness is restored by the rebuild, availability by the
        batch-engine fallback in the meantime."""
        with group._drain_lock:
            pending = group.take_pending()
            if not pending:
                return
            br = self.breaker
            if br is not None and br.blocking():
                # folds while open would be wasted against a failing
                # dependency; the rebuild after reset covers the gap
                group.needs_rebuild = True
                return
            try:
                faults = getattr(self.tsdb, "faults", None)
                if faults is not None:
                    faults.check("stream.fold")
                if len(pending) > 1:
                    # per-point ingest taps one 1-point chunk each —
                    # folding those one at a time pays the full
                    # lock/admit/scatter overhead per POINT. fold()
                    # resolves sids per element, so a pass's chunks
                    # concatenate (arrival order preserved) into one
                    # columnar scatter; the per-pass watermark commit
                    # already treats the pass as one batch
                    group.fold(
                        np.concatenate([p[0] for p in pending]),
                        np.concatenate([p[1] for p in pending]),
                        np.concatenate([p[2] for p in pending]))
                else:
                    group.fold(*pending[0])
            except Exception as exc:  # noqa: BLE001 - degrade
                self.fold_errors += 1
                group.needs_rebuild = True
                if br is not None:
                    br.record_failure()
                LOG.warning("stream.fold failed for %s (%s: %s); "
                            "partial will rebuild", group.metric,
                            type(exc).__name__, exc)
            else:
                if br is not None and br.state != br.CLOSED:
                    br.record_success()
            finally:
                # event-time watermark advances once per PASS, not
                # per chunk: a batch the tap chunked per series must
                # fold wholly against the pre-batch watermark
                group.commit_watermark()

    def worker_drain(self, group: SharedPartial) -> None:
        """One worker-pool drain: the ``stream.worker`` fault site
        wraps the hand-off so worker faults degrade exactly like fold
        faults (rebuild-on-serve, breaker, counters) without ever
        touching the write path or a serve."""
        try:
            faults = getattr(self.tsdb, "faults", None)
            if faults is not None:
                faults.check("stream.worker")
        except Exception as exc:  # noqa: BLE001 - degrade
            self.fold_errors += 1
            group.needs_rebuild = True
            if self.breaker is not None:
                self.breaker.record_failure()
            LOG.warning("stream.worker failed for %s (%s: %s); "
                        "partial will rebuild", group.metric,
                        type(exc).__name__, exc)
            return
        self._drain_group(group)

    def _rebuild_group(self, group: SharedPartial,
                       now_ms: int) -> bool:
        """Re-seed a failed partial from the store, gated by the
        breaker (a rebuild IS the half-open probe when the breaker
        is open)."""
        br = self.breaker
        if br is not None and not br.allow():
            return False
        try:
            faults = getattr(self.tsdb, "faults", None)
            if faults is not None:
                faults.check("stream.fold")
            group.bootstrap(now_ms)
        except Exception as exc:  # noqa: BLE001
            if br is not None:
                br.record_failure()
            LOG.warning("stream rebuild failed for %s (%s: %s)",
                        group.metric, type(exc).__name__, exc)
            return False
        group.needs_rebuild = False
        self.rebuilds += 1
        if group.tier_seeded:
            self.tier_seeded_bootstraps += 1
        if br is not None:
            br.record_success()
        return True

    # ------------------------------------------------------------------
    # pull path: serve /api/query from the maintained windows
    # ------------------------------------------------------------------

    def try_serve(self, tsq: TSQuery, sub, engine) -> list | None:
        """Results for one sub-query when a registered tumbling view
        covers the requested window, else None (caller falls through
        to the result cache / batch engine).

        Exactness contract: bucket-aligned absolute windows (and any
        window whose end is past the newest folded point) are
        value-identical to the batch engine; relative dashboard
        windows (``1h-ago`` .. now) share the result cache's
        GraphHandler staleness rule — the first bucket may cover up to
        one extra downsample interval."""
        if not self.tsdb.config.get_bool("tsd.streaming.serve", True):
            return None
        if tsq.delete or tsq.timezone or tsq.use_calendar:
            return None
        view = self._by_identity.get((sub.metric, sub.identity_key()))
        if view is None:
            return None
        group = view.shared
        iv = view.interval_ms
        relative = _is_relative(tsq.start) or _is_relative(tsq.end)
        if not relative and tsq.start_ms % iv:
            return None
        # lifecycle demotion: a partial seeded from the stitched
        # rollup/cold tiers covers pre-boundary history exactly;
        # one that is NOT tier-seeded (no nesting tier configured)
        # never saw it — shed those windows to the batch engine,
        # whose stitched store serves them
        lc = getattr(self.tsdb, "lifecycle", None)
        if lc is not None and not sub.percentiles \
                and not group.tier_seeded and \
                tsq.start_ms < lc.demote_boundary_for(sub.metric):
            # (percentile views carry their own coverage boundary —
            # sketch_from_ms — checked inside the serve)
            self.serve_fallbacks += 1
            return None
        # deletes/repairs/sweeps bump the read-set's mutation epochs;
        # partials cannot unfold removed points, so a mismatch forces
        # a rebuild before anything is served (this also covers
        # delete=true queries and fsck repairs the registry never
        # sees directly)
        if group.epoch_changed():
            group.needs_rebuild = True
        if group.needs_rebuild and not self._rebuild_group(
                group, tsq.end_ms):
            self.serve_fallbacks += 1
            return None
        # synchronous drain: freshness never depends on worker lag
        self._drain_group(group)
        if group.needs_rebuild:  # the drain itself just failed
            self.serve_fallbacks += 1
            return None
        if not relative and (tsq.end_ms + 1) % iv \
                and tsq.end_ms < group.max_ts_ms:
            # checked AFTER the drain: points past the unaligned end
            # may have just folded into the final bucket — the batch
            # engine would exclude them, so exactness is gone
            self.serve_fallbacks += 1
            return None
        out = view.serve(tsq, sub, engine)
        if out is None:
            self.serve_fallbacks += 1
            return None
        self.serve_hits += 1
        return out

    # ------------------------------------------------------------------
    # push path: SSE publication
    # ------------------------------------------------------------------

    def subscribe(self, cq: ContinuousQuery,
                  last_event_id: int | None = None):
        from opentsdb_tpu.streaming.sse import Subscription
        sub = Subscription(self.queue_events)
        # resume (Last-Event-ID): replay only the `windows` frames
        # published since the client's last seen event instead of the
        # full snapshot; an id that aged out of the bounded history
        # (or is unknown) falls back to the snapshot. Registration +
        # replay happen in ONE cq.lock section so a concurrent
        # publish (which snapshots targets and appends history under
        # the same lock) can neither interleave a newer frame ahead
        # of the replay nor slip a frame past both paths.
        resumed = False
        with cq.lock:
            cq.subscribers.append(sub)
            self._active_subs += 1
            if last_event_id is not None:
                resumed = self._resume_locked(cq, sub,
                                              int(last_event_id))
        if resumed:
            self.sse_resumes += 1
            return sub
        # initial snapshot so a dashboard renders before the first
        # incremental update arrives
        try:
            self._publish(cq, snapshot=True, only=[sub])
        except Exception:  # noqa: BLE001 - snapshot is best-effort
            LOG.exception("initial snapshot failed for %s", cq.id)
        return sub

    def _resume_locked(self, cq: ContinuousQuery, sub,
                       last_id: int) -> bool:
        """Replay the frames the reconnecting client missed (caller
        holds ``cq.lock``); False when only a snapshot can catch it
        up."""
        from opentsdb_tpu.streaming import sse
        if self.resume_events <= 0:
            return False
        if last_id > cq.emit_seq or last_id < cq.evicted_seq:
            # future/bogus id, or a `windows` frame newer than the
            # client's position was already evicted: the gap is not
            # replayable
            self.sse_resume_snapshots += 1
            return False
        for seq, fr in cq.history:
            if seq > last_id and not sse.offer_frame(sub, fr):
                return False  # overflowed mid-replay: sub is shed
        return True

    def unsubscribe(self, cq: ContinuousQuery, sub) -> None:
        with cq.lock:
            if sub in cq.subscribers:
                cq.subscribers.remove(sub)
                self._active_subs -= 1
                # fold the stream's delivered-frame count into the
                # registry total (per-sub counts die with the sub)
                self.sse_events_delivered += sub.events

    def _maybe_publish(self) -> None:
        """Rate-limited push pass: at most one publish per
        ``tsd.streaming.publish_min_interval_ms`` per query, and only
        when someone is listening. v1 ran this on the write path; v2
        runs it on the worker pool (the tap just sets a flag)."""
        if self._active_subs <= 0:
            return
        now = time.monotonic()
        for cq in self.list():
            if not cq.subscribers or cq.closed:
                continue
            if (now - cq.last_publish) * 1000.0 \
                    < self.publish_min_interval_ms:
                continue
            if any(p.changed_ts or p.shared.pending_points
                   for p in cq.plans):
                self.pump(cq)

    def _pump_groups(self, cq: ContinuousQuery) -> bool:
        """Rebuild-if-needed + drain every distinct partial under one
        query (shared partials drain once however many views ride
        them). Returns False when any partial is STILL marked for
        rebuild afterwards — its state is known-stale (breaker open,
        rebuild/drain failure) and exactness-requiring callers must
        not serve from it."""
        anchor = None
        seen: set[int] = set()
        clean = True
        for view in cq.plans:
            group = view.shared
            if id(group) in seen:
                continue
            seen.add(id(group))
            if group.epoch_changed():
                # a delete/repair/sweep happened: partials cannot
                # unfold removed points — re-seed before publishing
                group.needs_rebuild = True
            if group.needs_rebuild:
                if anchor is None:
                    try:
                        anchor = self._emit_tsq(
                            cq, int(time.time() * 1000)).end_ms
                    except BadRequestError:
                        anchor = int(time.time() * 1000)
                self._rebuild_group(group, anchor)
            self._drain_group(group)
            clean &= not group.needs_rebuild
        return clean

    def pump(self, cq: ContinuousQuery, force: bool = False) -> bool:
        """Drain + publish one query's incremental updates to every
        subscriber. Returns True when an event was published. Called
        from the SSE generator's heartbeat loop and from the worker
        pool's publish pass (rate-limited)."""
        self._pump_groups(cq)
        if not force and not any(p.changed_ts for p in cq.plans):
            return False
        return self._publish(cq, snapshot=False)

    def flush(self) -> None:
        """Drain + publish everything now (tests, benchmarks, and the
        admin surface)."""
        for cq in self.list():
            self.pump(cq, force=True)

    def _emit_tsq(self, cq: ContinuousQuery, now_ms: int) -> TSQuery:
        """The registration query re-resolved against *now* so emitted
        windows track the live horizon."""
        tsq = TSQuery.from_json(cq.raw)
        return tsq.validate(now_ms)

    def current_results(self, cq: ContinuousQuery,
                        now_ms: int | None = None) -> list[dict]:
        """The query's CURRENT windowed results as row dicts (the
        ``GET .../result`` fetch surface — the only pull path for
        sliding/session windows). Drains pending folds first, so the
        answer reflects every acknowledged write — and REFUSES with a
        structured 503 (DegradedError) when a partial is known-stale
        (rebuild failed / breaker open): unlike /api/query there is
        no batch engine to shed a windowed result to, and serving
        stale data silently would break the freshness contract."""
        from opentsdb_tpu.query.engine import QueryEngine
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        tsq = self._emit_tsq(cq, now_ms)
        if not self._pump_groups(cq):
            raise DegradedError(
                f"continuous query {cq.id!r}: partials are "
                f"rebuilding (fold failure or open stream.fold "
                f"breaker); retry shortly")
        engine = QueryEngine(self.tsdb)
        rows: list[dict] = []
        for view, sub in zip(cq.plans, tsq.queries):
            results = view.serve(tsq, sub, engine) or []
            for r in results:
                rows.append({
                    "metric": r.metric, "tags": r.tags,
                    "aggregateTags": r.aggregated_tags,
                    "index": r.sub_query_index,
                    "dps": {str(ts): (None if v != v else v)
                            for ts, v in r.dps}})
        if cq.policy is not None:
            # trailing completeness marker (the shardsDegraded idiom:
            # the row array keeps its shape for result consumers, the
            # marker rides at the end). A failed marker build — e.g.
            # an armed stream.watermark fault — degrades the WHOLE
            # pull: results without their completeness contract must
            # not pass as complete.
            try:
                marker = completeness_marker(self, cq, tsq.end_ms)
            except Exception as exc:  # noqa: BLE001 - degrade to 503
                raise DegradedError(
                    f"continuous query {cq.id!r}: completeness "
                    f"marker unavailable ({type(exc).__name__}); "
                    f"retry shortly") from exc
            rows.append({"completeness": marker})
        return rows

    def _collect_updates(self, cq: ContinuousQuery, tsq: TSQuery,
                         engine, snapshot: bool) -> list[dict]:
        """The incremental update rows for one publish/delta pass:
        per view, CONSUME the fold-dirty buckets, map them through
        the window's publish fan-out, and serve only the dps that
        changed (snapshot=True serves everything). Shared by the SSE
        publish path and the router's delta-drain pull
        (:meth:`delta_updates`) so federated frames carry exactly
        what a local subscriber would have seen."""
        from opentsdb_tpu.query.model import effective_pixels
        updates: list[dict] = []
        for view, sub in zip(cq.plans, tsq.queries):
            changed = None if snapshot else set(view.take_changed())
            if changed is not None and not changed:
                continue
            if changed is not None and effective_pixels(tsq, sub)[0]:
                # pixel-budgeted standing query: the M4/LTTB selection
                # can move with every fold (a new point displaces a
                # pixel's min/max), so dirty-window deltas cannot
                # describe the reduced series — publish the WHOLE
                # reduced frame instead. It is <= ~4 points/pixel by
                # construction, i.e. already smaller than one dirty
                # window of a dense full-resolution plan.
                changed = None
            if changed is not None:
                # map fold-dirty base buckets to the output buckets
                # this view's window re-emits (sliding fans each fold
                # into its k trailing outputs; session publishes the
                # whole frame — a fold can move a session's start)
                changed = view.publish_buckets(changed)
            if changed is not None:
                # result timestamps are second-rounded unless
                # ms_resolution; changed buckets are ms edges
                changed |= {c // 1000 * 1000 for c in changed}
            results = view.serve(tsq, sub, engine)
            if not results:
                continue
            for r in results:
                dps = {str(ts): (None if v != v else v)
                       for ts, v in r.dps
                       if changed is None or ts in changed}
                if not dps:
                    continue
                updates.append({
                    "metric": r.metric, "tags": r.tags,
                    "aggregateTags": r.aggregated_tags,
                    "index": r.sub_query_index, "dps": dps})
        return updates

    def delta_updates(self, cq: ContinuousQuery,
                      now_ms: int | None = None) -> dict:
        """Drain + return one incremental update batch WITHOUT an SSE
        subscriber — the router's federated pump pulls this from each
        shard (``GET .../<id>/deltas``, HTTP or wire) and merges the
        per-shard rows into one cross-shard frame. Consuming the
        dirty sets here competes with nothing: a router-registered CQ
        has no local subscribers, so the shard-local publish pass
        never touches it."""
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        from opentsdb_tpu.query.engine import QueryEngine
        tsq = self._emit_tsq(cq, now_ms)
        clean = self._pump_groups(cq)
        engine = QueryEngine(self.tsdb)
        updates = self._collect_updates(cq, tsq, engine,
                                        snapshot=False)
        with cq.lock:
            cq.emit_seq += 1
            seq = cq.emit_seq
        out = {"id": cq.id, "seq": seq, "ts": now_ms,
               "updates": updates, "clean": clean}
        if cq.policy is not None:
            try:
                out["completeness"] = completeness_marker(
                    self, cq, tsq.end_ms)
            except Exception:  # noqa: BLE001 - flag, never fail the drain
                out["completeness"] = {"degraded": True}
        cq.last_publish = time.monotonic()
        return out

    def _publish(self, cq: ContinuousQuery, snapshot: bool,
                 only: list | None = None) -> bool:
        from opentsdb_tpu.streaming import sse
        from opentsdb_tpu.query.engine import QueryEngine
        now_ms = int(time.time() * 1000)
        try:
            tsq = self._emit_tsq(cq, now_ms)
        except BadRequestError:
            return False
        engine = QueryEngine(self.tsdb)
        updates = self._collect_updates(cq, tsq, engine, snapshot)
        # ONE critical section for seq + target snapshot + history
        # append: a subscriber resuming concurrently either appears in
        # `targets` (gets the frame live) or subscribes after — and
        # then its replay reads a history that already holds this
        # frame. Split sections would let a frame slip between its
        # target snapshot and its history append, lost to both paths.
        completeness = None
        if cq.policy is not None:
            try:
                completeness = completeness_marker(self, cq,
                                                   tsq.end_ms)
            except Exception:  # noqa: BLE001 - push degrades, never dies
                # the frame still ships (subscribers keep their data
                # feed) but is FLAGGED: no silent "complete" claim
                completeness = {"degraded": True}
        with cq.lock:
            cq.emit_seq += 1
            seq = cq.emit_seq
            targets = list(only if only is not None
                           else cq.subscribers)
            if not updates and not snapshot:
                return False
            payload = {"id": cq.id, "seq": seq, "ts": now_ms,
                       "updates": updates}
            if completeness is not None:
                payload["completeness"] = completeness
            fr = sse.frame("snapshot" if snapshot else "windows",
                           payload, event_id=seq)
            if not snapshot and self.resume_events > 0:
                cq.history.append((seq, fr))
                while len(cq.history) > self.resume_events:
                    cq.evicted_seq = cq.history.pop(0)[0]
        shed = 0
        for s in targets:
            if not sse.offer_frame(s, fr):
                shed += 1
                with cq.lock:
                    if s in cq.subscribers:
                        cq.subscribers.remove(s)
                        self._active_subs -= 1
                        # shed bypasses unsubscribe: fold the
                        # stream's delivered-frame count here too
                        self.sse_events_delivered += s.events
        self.sse_shed += shed
        self.sse_events += len(targets) - shed
        self.publishes += 1
        cq.last_publish = time.monotonic()
        return True

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def _totals(self) -> dict[str, int]:
        t = {"points_folded": 0, "folds": 0, "late_dropped": 0,
             "late_refolded": 0, "preboundary_dropped": 0,
             "pending_points": 0, "series": 0, "plans": 0,
             "groups": 0, "ring_bytes": 0}
        with self._lock:
            groups = list(self._partials)
            t["plans"] = sum(len(cq.plans)
                             for cq in self._queries.values())
        for g in groups:
            t["points_folded"] += g.points_folded
            t["folds"] += g.folds
            t["late_dropped"] += g.late_dropped
            t["late_refolded"] += g.late_refolded
            t["preboundary_dropped"] += g.preboundary_dropped
            t["pending_points"] += g.pending_points
            t["series"] += len(g._sids)
            t["groups"] += 1
            t["ring_bytes"] += g.ring_bytes()
        return t

    def fold_bytes(self) -> int:
        """Actual resident fold memory across every shared partial —
        the number the control-plane miner and the per-tenant QoS
        fold budget account against."""
        with self._lock:
            groups = list(self._partials)
        return sum(g.ring_bytes() for g in groups)

    def tenant_fold_bytes(self, tenant: str) -> int:
        """Actual resident fold memory attributed to one tenant's
        registrations (a shared partial counts once per CQ riding
        it — deliberately conservative for a budget)."""
        return sum(cq.fold_bytes() for cq in self.list()
                   if getattr(cq, "tenant", None) == tenant)

    def projected_fold_bytes(self, obj: dict) -> int:
        """Projected resident ring bytes registering ``obj`` would
        ADD: per sub-query, the window count registration would size
        (range + pipeline lead + lateness columns) times a per-window
        row estimate — live partials on the same metric give the row
        count (their membership is ground truth), a cold metric
        projects one row. Feeds the QoS fold-budget gate and the
        control-plane miner's memory penalty; returns 0 for shapes
        that cannot register anyway (they fail their own 400 path)."""
        from opentsdb_tpu.streaming.eventtime import WatermarkPolicy
        from opentsdb_tpu.streaming.plan import WindowSpec
        try:
            tsq = TSQuery.from_json(
                {k: v for k, v in obj.items()
                 if k not in ("id", "window", "watermark")})
            tsq.validate()
            policy = WatermarkPolicy.from_json(obj.get("watermark"))
        except Exception:  # noqa: BLE001 - unregisterable shape
            return 0
        total = 0
        for sub in tsq.queries:
            spec = sub.ds_spec
            if spec is None or spec.interval_ms <= 0:
                continue
            try:
                window = WindowSpec.from_json(obj.get("window"),
                                              spec.interval_ms)
            except BadRequestError:
                return 0
            lat_b = policy.lateness_buckets(spec.interval_ms) \
                if policy is not None else 0
            windows = int((tsq.end_ms - tsq.start_ms)
                          // spec.interval_ms) + 2 \
                + window.lead_for(spec.interval_ms) + lat_b
            rows = 1
            with self._lock:
                for g in self._partials:
                    if g.metric == sub.metric:
                        rows = max(rows, len(g._sids))
            # 4 f8 channels + the shared win_ts row
            total += windows * (rows * 32 + 8)
        return total

    def collect_stats(self, collector) -> None:
        t = self._totals()
        with self._lock:
            n = len(self._queries)
            subs = sum(len(cq.subscribers)
                       for cq in self._queries.values())
        collector.record("streaming.queries", n)
        collector.record("streaming.plans", t["plans"])
        # shared partials actually folding: plans/groups is the plan-
        # sharing ratio (N dashboards per fold)
        collector.record("streaming.groups", t["groups"])
        collector.record("streaming.series", t["series"])
        collector.record("streaming.points.folded", t["points_folded"])
        collector.record("streaming.folds", t["folds"])
        collector.record("streaming.points.pending",
                         t["pending_points"])
        collector.record("streaming.points.late_dropped",
                         t["late_dropped"])
        collector.record("streaming.points.late_refolded",
                         t["late_refolded"])
        collector.record("streaming.points.preboundary_dropped",
                         t["preboundary_dropped"])
        collector.record("streaming.fold.bytes", t["ring_bytes"])
        collector.record("streaming.serve.hits", self.serve_hits)
        collector.record("streaming.serve.fallbacks",
                         self.serve_fallbacks)
        collector.record("streaming.fold.errors", self.fold_errors)
        collector.record("streaming.rebuilds", self.rebuilds)
        collector.record("streaming.rebuilds.tier_seeded",
                         self.tier_seeded_bootstraps)
        collector.record("streaming.backpressure.dropped_points",
                         self.backpressure_drops)
        collector.record("streaming.backpressure.events",
                         self.backpressure_events)
        collector.record("streaming.worker.drains",
                         self.workers.drains)
        collector.record("streaming.worker.errors",
                         self.workers.errors)
        collector.record("streaming.worker.publish_runs",
                         self.workers.publish_runs)
        collector.record("streaming.sse.subscribers", subs)
        collector.record("streaming.sse.events", self.sse_events)
        # delivery-side twin of sse.events: frames that actually
        # landed in subscriber queues (resume replays + snapshots
        # included, queue-full sheds excluded); live streams' counts
        # fold in when they unsubscribe
        collector.record("streaming.sse.events_delivered",
                         self.sse_events_delivered)
        collector.record("streaming.sse.shed", self.sse_shed)
        collector.record("streaming.sse.resumes", self.sse_resumes)
        collector.record("streaming.sse.resume_snapshots",
                         self.sse_resume_snapshots)
        collector.record("streaming.publishes", self.publishes)

    def health_info(self) -> dict[str, Any]:
        t = self._totals()
        with self._lock:
            n = len(self._queries)
            subs = sum(len(cq.subscribers)
                       for cq in self._queries.values())
        out = {
            "enabled": True,
            "queries": n,
            "plans": t["plans"],
            "groups": t["groups"],
            "series": t["series"],
            "points_folded": t["points_folded"],
            "pending_points": t["pending_points"],
            "late_dropped": t["late_dropped"],
            "late_refolded": t["late_refolded"],
            "preboundary_dropped": t["preboundary_dropped"],
            "fold_bytes": t["ring_bytes"],
            "serve_hits": self.serve_hits,
            "serve_fallbacks": self.serve_fallbacks,
            "fold_errors": self.fold_errors,
            "rebuilds": self.rebuilds,
            "tier_seeded_bootstraps": self.tier_seeded_bootstraps,
            "backpressure_dropped_points": self.backpressure_drops,
            "backpressure_events": self.backpressure_events,
            "workers": self.workers.health_info(),
            "subscribers": subs,
            "sse_events": self.sse_events,
            "sse_events_delivered": self.sse_events_delivered,
            "sse_shed": self.sse_shed,
            "sse_resumes": self.sse_resumes,
            "sse_resume_snapshots": self.sse_resume_snapshots,
        }
        if self.breaker is not None:
            out["breaker"] = self.breaker.health_info()
        return out
