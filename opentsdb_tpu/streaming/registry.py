"""Continuous-query registry: standing TSQueries maintained under
ingest and served two ways.

Clients register a standing TSQuery (``POST /api/query/continuous``);
the registry compiles each sub-query into an
:class:`~opentsdb_tpu.streaming.plan.IncrementalSubPlan` (tumbling
windows of per-series partial aggregates) and taps
``TSDB.add_point`` / ``add_points`` / ``import_buffer`` through
:meth:`offer` — a buffered O(1) append on the hot write path, folded
in batches.

Results serve two ways:

- **pull** — the query engine consults :meth:`try_serve` before the
  result cache: a live-window request matching a registered query is
  answered from the maintained partials (fold pending + pipeline
  tail, never a store scan). This is the non-invalidating feeder that
  closes the result cache's live-query gap: ingest to the read store
  no longer evicts the dashboard's answer, it *updates* it.
- **push** — Server-Sent Events (``GET /api/query/continuous/<id>/
  stream``) emitting incremental window updates, with bounded
  per-subscription queues and slow-consumer shedding
  (:mod:`opentsdb_tpu.streaming.sse`).

Degradation follows the PR-1 idiom: the ``stream.fold`` fault site
runs every fold and rebuild under a :class:`CircuitBreaker`; a failed
fold marks the plan for rebuild (one batch re-scan), a tripped breaker
routes pulls back to the batch engine (shed to the always-correct
path, never a 500) until the reset-window probe heals it. Counters
export through /api/stats and /api/health.

Knobs (``tsd.streaming.*``): ``enable``, ``serve``, ``max_queries``,
``max_windows``, ``buffer_points``, ``queue_events``, ``heartbeat_s``,
``publish_min_interval_ms``, ``breaker.failure_threshold``,
``breaker.reset_timeout_ms``.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any

import numpy as np

from opentsdb_tpu.query.model import BadRequestError, TSQuery
from opentsdb_tpu.query.result_cache import _is_relative
from opentsdb_tpu.streaming.plan import (DECOMPOSABLE_DS,
                                         IncrementalSubPlan)
from opentsdb_tpu.utils.faults import CircuitBreaker

LOG = logging.getLogger("streaming.registry")


class ContinuousQuery:
    """One registered standing query: the validated TSQuery plus one
    incremental plan per sub-query and the SSE subscriber set."""

    def __init__(self, cid: str, raw: dict, tsq: TSQuery,
                 plans: list[IncrementalSubPlan]):
        self.id = cid
        self.raw = raw          # original JSON body (re-resolved per emit)
        self.tsq = tsq
        self.plans = plans
        self.created = time.time()
        self.lock = threading.Lock()
        self.subscribers: list = []
        self.emit_seq = 0
        self.last_publish = 0.0
        self.closed = False
        # bounded replay history for SSE resume (Last-Event-ID): the
        # last N published `windows` frames, each tagged with its emit
        # seq. evicted_seq = the newest frame pushed out — a reconnect
        # older than it has missed un-replayable events and falls back
        # to a snapshot.
        self.history: list[tuple[int, bytes]] = []
        self.evicted_seq = 0

    def describe(self, verbose: bool = False) -> dict[str, Any]:
        out: dict[str, Any] = {
            "id": self.id,
            "query": self.tsq.to_json(),
            "intervalMs": [p.interval_ms for p in self.plans],
            "windows": [p.n_windows for p in self.plans],
            "series": sum(len(p._sids) for p in self.plans),
            "subscribers": len(self.subscribers),
            "emitSeq": self.emit_seq,
        }
        if verbose:
            out["plans"] = [p.info() for p in self.plans]
        return out


class ContinuousQueryRegistry:
    """(see module docstring)"""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        cfg = tsdb.config
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._queries: dict[str, ContinuousQuery] = {}
        # metric_id -> plans watching it (the tap's fast path); plans
        # whose metric has no UID yet park in _unresolved until a
        # write materializes the metric
        self._by_mid: dict[int, list[IncrementalSubPlan]] = {}
        self._unresolved: list[IncrementalSubPlan] = []
        # (metric, sub identity) -> plan for the pull path
        self._by_identity: dict[tuple, IncrementalSubPlan] = {}
        self.max_queries = cfg.get_int("tsd.streaming.max_queries", 64)
        self.max_windows = cfg.get_int("tsd.streaming.max_windows",
                                       2880)
        self.buffer_points = cfg.get_int("tsd.streaming.buffer_points",
                                         4096)
        self.queue_events = cfg.get_int("tsd.streaming.queue_events",
                                        256)
        self.heartbeat_s = cfg.get_float("tsd.streaming.heartbeat_s",
                                         5.0)
        self.publish_min_interval_ms = cfg.get_float(
            "tsd.streaming.publish_min_interval_ms", 200.0)
        # SSE resume replay depth (0 disables Last-Event-ID resume)
        self.resume_events = cfg.get_int(
            "tsd.streaming.resume_events", 64)
        threshold = cfg.get_int(
            "tsd.streaming.breaker.failure_threshold", 3)
        self.breaker = CircuitBreaker(
            "stream.fold", failure_threshold=threshold,
            reset_timeout_ms=cfg.get_float(
                "tsd.streaming.breaker.reset_timeout_ms", 30000.0)) \
            if threshold > 0 else None
        if self.breaker is not None:
            tsdb.stats.register(self.breaker)
        # live SSE subscriber count, maintained so the ingest tap's
        # publish check is one integer read (never a registry walk)
        self._active_subs = 0
        # counters
        self.serve_hits = 0
        self.serve_fallbacks = 0
        self.fold_errors = 0
        self.rebuilds = 0
        self.sse_shed = 0
        self.sse_events = 0
        self.sse_resumes = 0
        self.sse_resume_snapshots = 0
        self.sse_events_delivered = 0  # frames on CLOSED streams
        self.publishes = 0

    # ------------------------------------------------------------------
    # registration surface
    # ------------------------------------------------------------------

    def register(self, obj: dict, now_ms: int | None = None
                 ) -> ContinuousQuery:
        """Validate + compile one standing TSQuery; raises
        :class:`BadRequestError` on anything the incremental engine
        cannot maintain (the client should run it as a plain query)."""
        if not isinstance(obj, dict):
            raise BadRequestError("continuous query must be an object")
        cid = obj.get("id")
        body = {k: v for k, v in obj.items() if k != "id"}
        tsq = TSQuery.from_json(body).validate(now_ms)
        if tsq.delete:
            raise BadRequestError(
                "delete=true cannot be a continuous query")
        if tsq.timezone or tsq.use_calendar:
            raise BadRequestError(
                "continuous queries do not support timezone/calendar "
                "downsampling")
        plans = []
        for sub in tsq.queries:
            if sub.percentiles:
                raise BadRequestError(
                    "continuous queries do not support percentiles")
            if sub.tsuids or not sub.metric:
                raise BadRequestError(
                    "continuous queries require a metric (tsuids are "
                    "not supported)")
            if sub.explicit_tags:
                raise BadRequestError(
                    "continuous queries do not support explicitTags")
            spec = sub.ds_spec
            if spec is None or spec.run_all or spec.use_calendar \
                    or spec.unit in ("n", "y") or spec.interval_ms <= 0:
                raise BadRequestError(
                    "continuous queries require a fixed-interval "
                    "downsample (e.g. 1m-avg)")
            if spec.function not in DECOMPOSABLE_DS:
                raise BadRequestError(
                    f"downsample function {spec.function!r} is not "
                    f"decomposable into streaming partials "
                    f"(supported: {', '.join(sorted(DECOMPOSABLE_DS))})")
            windows = int((tsq.end_ms - tsq.start_ms)
                          // spec.interval_ms) + 2
            if windows > self.max_windows:
                raise BadRequestError(
                    f"window range needs {windows} tumbling windows; "
                    f"tsd.streaming.max_windows={self.max_windows}")
            plans.append(IncrementalSubPlan(self.tsdb, sub, windows))
        # the horizon anchors at the query's RESOLVED end: now for the
        # live-dashboard shape (end=now), the window's own end for an
        # absolute registration — either way the ring covers exactly
        # the window the standing query answers, and tumbles forward
        # with ingest from there
        anchor_ms = tsq.end_ms
        with self._lock:
            if len(self._queries) >= self.max_queries:
                raise BadRequestError(
                    f"too many continuous queries (tsd.streaming."
                    f"max_queries={self.max_queries})")
            if cid is None:
                cid = f"cq{next(self._ids)}"
            cid = str(cid)
            if cid in self._queries:
                raise BadRequestError(
                    f"continuous query {cid!r} already exists")
            # reserve the id so a concurrent same-id register fails
            # fast; the bootstrap scan runs OUTSIDE the registry lock
            # (the ingest tap and _maybe_publish take it — a wide
            # bootstrap must not stall every write for seconds)
            self._queries[cid] = cq = ContinuousQuery(
                cid, body, tsq, plans)
        try:
            for plan in plans:
                plan.bootstrap(anchor_ms)
        except BaseException:
            with self._lock:
                self._queries.pop(cid, None)
            raise
        with self._lock:
            for plan in plans:
                self._index_plan_locked(plan)
                key = (plan.metric, plan.sub.identity_key())
                self._by_identity.setdefault(key, plan)
        LOG.info("registered continuous query %s (%d sub-plans)",
                 cid, len(plans))
        return cq

    def _index_plan_locked(self, plan: IncrementalSubPlan) -> None:
        if plan.metric_id is not None:
            self._by_mid.setdefault(plan.metric_id, []).append(plan)
        else:
            self._unresolved.append(plan)

    def delete(self, cid: str) -> bool:
        with self._lock:
            cq = self._queries.pop(cid, None)
            if cq is None:
                return False
            cq.closed = True
            for plan in cq.plans:
                if plan.metric_id is not None:
                    lst = self._by_mid.get(plan.metric_id, [])
                    if plan in lst:
                        lst.remove(plan)
                    if not lst:
                        self._by_mid.pop(plan.metric_id, None)
                if plan in self._unresolved:
                    self._unresolved.remove(plan)
                key = (plan.metric, plan.sub.identity_key())
                if self._by_identity.get(key) is plan:
                    del self._by_identity[key]
                    # a surviving query with the same identity takes
                    # over the pull path instead of silently falling
                    # back to batch scans
                    for other in self._queries.values():
                        for p in other.plans:
                            if (p.metric,
                                    p.sub.identity_key()) == key:
                                self._by_identity[key] = p
                                break
                        if key in self._by_identity:
                            break
            subs = list(cq.subscribers)
        from opentsdb_tpu.streaming import sse
        for sub in subs:
            sse.offer_frame(sub, sse.frame(
                "deleted", {"id": cid}))
        return True

    def get(self, cid: str) -> ContinuousQuery | None:
        with self._lock:
            return self._queries.get(cid)

    def list(self) -> list[ContinuousQuery]:
        with self._lock:
            return [self._queries[k] for k in sorted(self._queries)]

    def invalidate(self) -> None:
        """Mark every plan for rebuild (the ``/api/dropcaches``
        escape hatch: the next serve/pump re-seeds from the store)."""
        for cq in self.list():
            for plan in cq.plans:
                plan.needs_rebuild = True

    def shutdown(self) -> None:
        for cq in self.list():
            self.delete(cq.id)

    # ------------------------------------------------------------------
    # ingest tap (called from TSDB under the write-hook guard)
    # ------------------------------------------------------------------

    def _plans_for(self, metric_id: int
                   ) -> list[IncrementalSubPlan] | None:
        plans = self._by_mid.get(metric_id)
        if plans is not None or not self._unresolved:
            return plans
        # a parked plan's metric may have just been minted by this
        # very write: resolve by name once, then the fast path hits
        with self._lock:
            if not self._unresolved:
                return self._by_mid.get(metric_id)
            try:
                name = self.tsdb.uids.metrics.get_name(metric_id)
            except LookupError:
                return None
            for plan in list(self._unresolved):
                if plan.metric == name:
                    plan.metric_id = metric_id
                    self._unresolved.remove(plan)
                    self._by_mid.setdefault(metric_id, []).append(plan)
            return self._by_mid.get(metric_id)

    def offer(self, metric_id: int, sid: int, ts_ms: int,
              value: float) -> None:
        plans = self._plans_for(metric_id)
        if not plans:
            return
        sid_a = np.asarray([sid], dtype=np.int64)
        ts_a = np.asarray([ts_ms], dtype=np.int64)
        val_a = np.asarray([value], dtype=np.float64)
        for plan in plans:
            if plan.offer(sid_a, ts_a, val_a) >= self.buffer_points:
                self._drain_plan(plan)
        self._maybe_publish()

    def offer_many(self, metric_id: int, sid: int, ts_ms: np.ndarray,
                   values: np.ndarray) -> None:
        plans = self._plans_for(metric_id)
        if not plans:
            return
        n = len(ts_ms)
        sid_a = np.full(n, sid, dtype=np.int64)
        for plan in plans:
            if plan.offer(sid_a, ts_ms, values) >= self.buffer_points:
                self._drain_plan(plan)
        self._maybe_publish()

    def _drain_plan(self, plan: IncrementalSubPlan) -> None:
        """Fold a plan's pending chunks under the ``stream.fold``
        fault site + breaker. A failed fold loses the chunks, so the
        plan is marked for rebuild (one batch re-scan) — correctness
        is restored by the rebuild, availability by the batch-engine
        fallback in the meantime."""
        pending = plan.take_pending()
        if not pending:
            return
        br = self.breaker
        if br is not None and br.blocking():
            # folds while open would be wasted against a failing
            # dependency; the rebuild after reset covers the gap
            plan.needs_rebuild = True
            return
        try:
            faults = getattr(self.tsdb, "faults", None)
            if faults is not None:
                faults.check("stream.fold")
            for sids, ts, vals in pending:
                plan.fold(sids, ts, vals)
        except Exception as exc:  # noqa: BLE001 - degrade, never fail
            self.fold_errors += 1
            plan.needs_rebuild = True
            if br is not None:
                br.record_failure()
            LOG.warning("stream.fold failed for %s (%s: %s); plan "
                        "will rebuild", plan.metric,
                        type(exc).__name__, exc)
        else:
            if br is not None and br.state != br.CLOSED:
                br.record_success()

    def _rebuild_plan(self, plan: IncrementalSubPlan,
                      now_ms: int) -> bool:
        """Re-seed a failed plan from the store, gated by the breaker
        (a rebuild IS the half-open probe when the breaker is open)."""
        br = self.breaker
        if br is not None and not br.allow():
            return False
        try:
            faults = getattr(self.tsdb, "faults", None)
            if faults is not None:
                faults.check("stream.fold")
            plan.bootstrap(now_ms)
        except Exception as exc:  # noqa: BLE001
            if br is not None:
                br.record_failure()
            LOG.warning("stream rebuild failed for %s (%s: %s)",
                        plan.metric, type(exc).__name__, exc)
            return False
        plan.needs_rebuild = False
        self.rebuilds += 1
        if br is not None:
            br.record_success()
        return True

    # ------------------------------------------------------------------
    # pull path: serve /api/query from the maintained windows
    # ------------------------------------------------------------------

    def try_serve(self, tsq: TSQuery, sub, engine) -> list | None:
        """Results for one sub-query when a registered plan covers the
        requested window, else None (caller falls through to the
        result cache / batch engine).

        Exactness contract: bucket-aligned absolute windows (and any
        window whose end is past the newest folded point) are
        value-identical to the batch engine; relative dashboard
        windows (``1h-ago`` .. now) share the result cache's
        GraphHandler staleness rule — the first bucket may cover up to
        one extra downsample interval."""
        if not self.tsdb.config.get_bool("tsd.streaming.serve", True):
            return None
        if tsq.delete or sub.percentiles or tsq.timezone \
                or tsq.use_calendar:
            return None
        plan = self._by_identity.get((sub.metric, sub.identity_key()))
        if plan is None:
            return None
        iv = plan.interval_ms
        relative = _is_relative(tsq.start) or _is_relative(tsq.end)
        if not relative and tsq.start_ms % iv:
            return None
        # lifecycle demotion: windows that reach behind the metric's
        # demotion boundary need tier history the partials never saw
        # (plans fold raw writes only; a rebuild scans raw only) — shed
        # those to the batch engine, whose stitched store serves them
        lc = getattr(self.tsdb, "lifecycle", None)
        if lc is not None and \
                tsq.start_ms < lc.demote_boundary_for(sub.metric):
            self.serve_fallbacks += 1
            return None
        # deletes/repairs bump the store's mutation epoch; partials
        # cannot unfold removed points, so a mismatch forces a rebuild
        # before anything is served (this also covers delete=true
        # queries and fsck repairs the registry never sees directly)
        if plan.store_epoch != getattr(self.tsdb.store,
                                       "mutation_epoch", 0):
            plan.needs_rebuild = True
        if plan.needs_rebuild and not self._rebuild_plan(
                plan, tsq.end_ms):
            self.serve_fallbacks += 1
            return None
        self._drain_plan(plan)
        if plan.needs_rebuild:  # the drain itself just failed
            self.serve_fallbacks += 1
            return None
        if not relative and (tsq.end_ms + 1) % iv \
                and tsq.end_ms < plan.max_ts_ms:
            # checked AFTER the drain: points past the unaligned end
            # may have just folded into the final bucket — the batch
            # engine would exclude them, so exactness is gone
            self.serve_fallbacks += 1
            return None
        out = plan.serve(tsq, sub, engine)
        if out is None:
            self.serve_fallbacks += 1
            return None
        self.serve_hits += 1
        return out

    # ------------------------------------------------------------------
    # push path: SSE publication
    # ------------------------------------------------------------------

    def subscribe(self, cq: ContinuousQuery,
                  last_event_id: int | None = None):
        from opentsdb_tpu.streaming.sse import Subscription
        sub = Subscription(self.queue_events)
        # resume (Last-Event-ID): replay only the `windows` frames
        # published since the client's last seen event instead of the
        # full snapshot; an id that aged out of the bounded history
        # (or is unknown) falls back to the snapshot. Registration +
        # replay happen in ONE cq.lock section so a concurrent
        # publish (which snapshots targets and appends history under
        # the same lock) can neither interleave a newer frame ahead
        # of the replay nor slip a frame past both paths.
        resumed = False
        with cq.lock:
            cq.subscribers.append(sub)
            self._active_subs += 1
            if last_event_id is not None:
                resumed = self._resume_locked(cq, sub,
                                              int(last_event_id))
        if resumed:
            self.sse_resumes += 1
            return sub
        # initial snapshot so a dashboard renders before the first
        # incremental update arrives
        try:
            self._publish(cq, snapshot=True, only=[sub])
        except Exception:  # noqa: BLE001 - snapshot is best-effort
            LOG.exception("initial snapshot failed for %s", cq.id)
        return sub

    def _resume_locked(self, cq: ContinuousQuery, sub,
                       last_id: int) -> bool:
        """Replay the frames the reconnecting client missed (caller
        holds ``cq.lock``); False when only a snapshot can catch it
        up."""
        from opentsdb_tpu.streaming import sse
        if self.resume_events <= 0:
            return False
        if last_id > cq.emit_seq or last_id < cq.evicted_seq:
            # future/bogus id, or a `windows` frame newer than the
            # client's position was already evicted: the gap is not
            # replayable
            self.sse_resume_snapshots += 1
            return False
        for seq, fr in cq.history:
            if seq > last_id and not sse.offer_frame(sub, fr):
                return False  # overflowed mid-replay: sub is shed
        return True

    def unsubscribe(self, cq: ContinuousQuery, sub) -> None:
        with cq.lock:
            if sub in cq.subscribers:
                cq.subscribers.remove(sub)
                self._active_subs -= 1
                # fold the stream's delivered-frame count into the
                # registry total (per-sub counts die with the sub)
                self.sse_events_delivered += sub.events

    def _maybe_publish(self) -> None:
        """Rate-limited push after ingest drains: at most one publish
        per ``tsd.streaming.publish_min_interval_ms`` per query, and
        only when someone is listening (one integer read on the hot
        write path when nobody is)."""
        if self._active_subs <= 0:
            return
        now = time.monotonic()
        for cq in self.list():
            if not cq.subscribers or cq.closed:
                continue
            if (now - cq.last_publish) * 1000.0 \
                    < self.publish_min_interval_ms:
                continue
            if any(p.changed_ts or p.pending_points
                   for p in cq.plans):
                self.pump(cq)

    def pump(self, cq: ContinuousQuery, force: bool = False) -> bool:
        """Drain + publish one query's incremental updates to every
        subscriber. Returns True when an event was published. Called
        from the SSE generator's heartbeat loop and from the ingest
        drain path (rate-limited)."""
        anchor = None
        epoch = getattr(self.tsdb.store, "mutation_epoch", 0)
        for plan in cq.plans:
            if plan.store_epoch != epoch:
                # a delete/repair happened: partials cannot unfold
                # removed points — re-seed before publishing
                plan.needs_rebuild = True
            if plan.needs_rebuild:
                if anchor is None:
                    try:
                        anchor = self._emit_tsq(
                            cq, int(time.time() * 1000)).end_ms
                    except BadRequestError:
                        anchor = int(time.time() * 1000)
                self._rebuild_plan(plan, anchor)
            self._drain_plan(plan)
        if not force and not any(p.changed_ts for p in cq.plans):
            return False
        return self._publish(cq, snapshot=False)

    def flush(self) -> None:
        """Drain + publish everything now (tests, benchmarks, and the
        admin surface)."""
        for cq in self.list():
            self.pump(cq, force=True)

    def _emit_tsq(self, cq: ContinuousQuery, now_ms: int) -> TSQuery:
        """The registration query re-resolved against *now* so emitted
        windows track the live horizon."""
        tsq = TSQuery.from_json(cq.raw)
        return tsq.validate(now_ms)

    def _publish(self, cq: ContinuousQuery, snapshot: bool,
                 only: list | None = None) -> bool:
        from opentsdb_tpu.streaming import sse
        from opentsdb_tpu.query.engine import QueryEngine
        now_ms = int(time.time() * 1000)
        try:
            tsq = self._emit_tsq(cq, now_ms)
        except BadRequestError:
            return False
        engine = QueryEngine(self.tsdb)
        from opentsdb_tpu.query.model import effective_pixels
        updates = []
        for plan, sub in zip(cq.plans, tsq.queries):
            changed = None if snapshot else set(plan.take_changed())
            if changed is not None and not changed:
                continue
            if changed is not None and effective_pixels(tsq, sub)[0]:
                # pixel-budgeted standing query: the M4/LTTB selection
                # can move with every fold (a new point displaces a
                # pixel's min/max), so dirty-window deltas cannot
                # describe the reduced series — publish the WHOLE
                # reduced frame instead. It is <= ~4 points/pixel by
                # construction, i.e. already smaller than one dirty
                # window of a dense full-resolution plan.
                changed = None
            if changed is not None:
                # result timestamps are second-rounded unless
                # ms_resolution; changed buckets are ms edges
                changed |= {c // 1000 * 1000 for c in changed}
            results = plan.serve(tsq, sub, engine)
            if not results:
                continue
            for r in results:
                dps = {str(ts): (None if v != v else v)
                       for ts, v in r.dps
                       if changed is None or ts in changed}
                if not dps:
                    continue
                updates.append({
                    "metric": r.metric, "tags": r.tags,
                    "aggregateTags": r.aggregated_tags,
                    "index": r.sub_query_index, "dps": dps})
        # ONE critical section for seq + target snapshot + history
        # append: a subscriber resuming concurrently either appears in
        # `targets` (gets the frame live) or subscribes after — and
        # then its replay reads a history that already holds this
        # frame. Split sections would let a frame slip between its
        # target snapshot and its history append, lost to both paths.
        with cq.lock:
            cq.emit_seq += 1
            seq = cq.emit_seq
            targets = list(only if only is not None
                           else cq.subscribers)
            if not updates and not snapshot:
                return False
            payload = {"id": cq.id, "seq": seq, "ts": now_ms,
                       "updates": updates}
            fr = sse.frame("snapshot" if snapshot else "windows",
                           payload, event_id=seq)
            if not snapshot and self.resume_events > 0:
                cq.history.append((seq, fr))
                while len(cq.history) > self.resume_events:
                    cq.evicted_seq = cq.history.pop(0)[0]
        shed = 0
        for s in targets:
            if not sse.offer_frame(s, fr):
                shed += 1
                with cq.lock:
                    if s in cq.subscribers:
                        cq.subscribers.remove(s)
                        self._active_subs -= 1
                        # shed bypasses unsubscribe: fold the
                        # stream's delivered-frame count here too
                        self.sse_events_delivered += s.events
        self.sse_shed += shed
        self.sse_events += len(targets) - shed
        self.publishes += 1
        cq.last_publish = time.monotonic()
        return True

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def _totals(self) -> dict[str, int]:
        t = {"points_folded": 0, "folds": 0, "late_dropped": 0,
             "pending_points": 0, "series": 0, "plans": 0}
        for cq in self.list():
            for p in cq.plans:
                t["points_folded"] += p.points_folded
                t["folds"] += p.folds
                t["late_dropped"] += p.late_dropped
                t["pending_points"] += p.pending_points
                t["series"] += len(p._sids)
                t["plans"] += 1
        return t

    def collect_stats(self, collector) -> None:
        t = self._totals()
        with self._lock:
            n = len(self._queries)
            subs = sum(len(cq.subscribers)
                       for cq in self._queries.values())
        collector.record("streaming.queries", n)
        collector.record("streaming.plans", t["plans"])
        collector.record("streaming.series", t["series"])
        collector.record("streaming.points.folded", t["points_folded"])
        collector.record("streaming.folds", t["folds"])
        collector.record("streaming.points.pending",
                         t["pending_points"])
        collector.record("streaming.points.late_dropped",
                         t["late_dropped"])
        collector.record("streaming.serve.hits", self.serve_hits)
        collector.record("streaming.serve.fallbacks",
                         self.serve_fallbacks)
        collector.record("streaming.fold.errors", self.fold_errors)
        collector.record("streaming.rebuilds", self.rebuilds)
        collector.record("streaming.sse.subscribers", subs)
        collector.record("streaming.sse.events", self.sse_events)
        # delivery-side twin of sse.events: frames that actually
        # landed in subscriber queues (resume replays + snapshots
        # included, queue-full sheds excluded); live streams' counts
        # fold in when they unsubscribe
        collector.record("streaming.sse.events_delivered",
                         self.sse_events_delivered)
        collector.record("streaming.sse.shed", self.sse_shed)
        collector.record("streaming.sse.resumes", self.sse_resumes)
        collector.record("streaming.sse.resume_snapshots",
                         self.sse_resume_snapshots)
        collector.record("streaming.publishes", self.publishes)

    def health_info(self) -> dict[str, Any]:
        t = self._totals()
        with self._lock:
            n = len(self._queries)
            subs = sum(len(cq.subscribers)
                       for cq in self._queries.values())
        out = {
            "enabled": True,
            "queries": n,
            "plans": t["plans"],
            "series": t["series"],
            "points_folded": t["points_folded"],
            "pending_points": t["pending_points"],
            "late_dropped": t["late_dropped"],
            "serve_hits": self.serve_hits,
            "serve_fallbacks": self.serve_fallbacks,
            "fold_errors": self.fold_errors,
            "rebuilds": self.rebuilds,
            "subscribers": subs,
            "sse_events": self.sse_events,
            "sse_events_delivered": self.sse_events_delivered,
            "sse_shed": self.sse_shed,
            "sse_resumes": self.sse_resumes,
            "sse_resume_snapshots": self.sse_resume_snapshots,
        }
        if self.breaker is not None:
            out["breaker"] = self.breaker.health_info()
        return out
