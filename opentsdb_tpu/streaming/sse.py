"""Server-Sent Events transport for continuous-query push results.

One subscription = one bounded :class:`queue.Queue` of pre-formatted
SSE frames. The registry publishes each update frame once and offers
it to every subscriber with ``put_nowait`` — a consumer that cannot
keep up (queue full) is SHED: marked dropped, removed from the
subscriber set, and its stream ends with a terminal ``shed`` event.
Backpressure therefore never propagates into the ingest path and a
stalled dashboard can never make the registry buffer unboundedly (the
PR-1 shed-don't-wedge idiom, transplanted to the push surface).

The generator produced by :func:`sse_stream` is consumed by the HTTP
server's chunked-streaming writer; between events it wakes every
``tsd.streaming.heartbeat_s`` to pump pending folds (so a quiet
subscriber still sees updates without a dedicated publisher thread)
and emits comment keepalives.
"""

from __future__ import annotations

import json
import queue
import time


class Subscription:
    """One SSE consumer: a bounded frame queue + shed flag."""

    __slots__ = ("queue", "dropped", "created", "events")

    def __init__(self, maxsize: int):
        self.queue: queue.Queue = queue.Queue(maxsize=max(maxsize, 1))
        self.dropped = False
        self.created = time.time()
        self.events = 0


def frame(event: str, payload: dict,
          event_id: int | None = None) -> bytes:
    """One SSE frame: optional ``id:`` (the per-query emit sequence —
    browsers echo the last one back as ``Last-Event-ID`` on
    reconnect), ``event: <type>`` + one JSON ``data:`` line."""
    body = json.dumps(payload, allow_nan=False, separators=(",", ":"))
    head = f"id: {event_id}\n" if event_id is not None else ""
    return (f"{head}event: {event}\ndata: {body}\n\n").encode()


def offer_frame(sub: Subscription, fr: bytes) -> bool:
    """Non-blocking delivery; a full queue sheds the subscriber."""
    if sub.dropped:
        return False
    try:
        sub.queue.put_nowait(fr)
    except queue.Full:
        sub.dropped = True
        return False
    sub.events += 1
    return True


def sse_stream(registry, cq, max_lifetime_s: float = 0.0,
               last_event_id: int | None = None):
    """Generator of SSE byte chunks for one subscriber (consumed by
    the server's chunked writer on a worker thread).

    ``last_event_id`` (the browser's ``Last-Event-ID`` reconnect
    header) resumes the stream: the registry replays only the
    ``windows`` events published since that id instead of the full
    snapshot, falling back to a snapshot when the id has aged out of
    the bounded replay history."""
    sub = registry.subscribe(cq, last_event_id=last_event_id)
    heartbeat = max(registry.heartbeat_s, 0.05)
    started = time.monotonic()
    try:
        yield b"retry: 5000\n\n"
        while True:
            if cq.closed:
                yield frame("end", {"id": cq.id, "reason": "deleted"})
                return
            if sub.dropped:
                # shed: the queue overflowed while we slept — tell the
                # client it missed updates and end the stream cleanly
                yield frame("shed", {
                    "id": cq.id,
                    "reason": "slow consumer: event queue overflow"})
                return
            if max_lifetime_s and \
                    time.monotonic() - started > max_lifetime_s:
                yield frame("end", {"id": cq.id, "reason": "lifetime"})
                return
            try:
                yield sub.queue.get(timeout=heartbeat)
                continue
            except queue.Empty:
                pass
            # quiet period: fold pending ingest and publish if dirty,
            # else keep the connection alive with a comment
            try:
                registry.pump(cq)
            except Exception:  # noqa: BLE001 - never kill the stream
                # tsdlint: allow[swallow] a pump hiccup must not kill
                # a long-lived dashboard stream; fold failures are
                # counted by the registry (fold_errors) and the next
                # pump retries
                pass
            try:
                yield sub.queue.get_nowait()
            except queue.Empty:
                yield b": keepalive\n\n"
    finally:
        registry.unsubscribe(cq, sub)
