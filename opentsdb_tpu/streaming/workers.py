"""Shared fold-worker pool: off-path execution for continuous-query
folds and push publication.

The ingest tap (``TSDB.add_point*`` -> ``ContinuousQueryRegistry``)
is an O(1) columnar enqueue into each shared partial's pending
buffer. When a partial's backlog crosses the drain threshold
(``tsd.streaming.buffer_points``), the tap hands the partial to this
pool instead of folding inline — the write path never executes a
fold, so high-cardinality standing queries cost ingest a buffer
append, nothing more. The pool also runs the rate-limited SSE
publish walk after drains when subscribers exist (v1 ran it on the
write path).

Degradation (the PR-1 idiom, under the ``stream.worker`` fault
site + the existing streaming breaker): a worker failure marks the
partial for rebuild-on-serve and is counted — it can NEVER fail or
block an acknowledged write, and the serve path drains/rebuilds
synchronously before answering so a lagging worker can never cause
a stale serve. When a partial's backlog exceeds
``tsd.streaming.workers.max_pending_points`` the registry degrades
it instead of buffering unboundedly: the backlog is dropped and the
partial rebuilds from the store on its next serve.

``tsd.streaming.workers.count = 0`` disables the pool; the tap then
folds inline at the drain threshold (the v1 behavior) — the escape
hatch for single-threaded embedders.

Threads start lazily on the first hand-off and stop with the
registry (``TSDB.shutdown`` -> ``ContinuousQueryRegistry.shutdown``).
"""

from __future__ import annotations

import collections
import logging
import threading

LOG = logging.getLogger("streaming.workers")

# idle wake interval: a worker with an empty queue re-checks the
# publish flag this often so a subscriber behind a rate-limited
# publish window is never stranded until the next ingest tick
_IDLE_WAKE_S = 0.25


class FoldWorkerPool:
    """(see module docstring)"""

    def __init__(self, registry, count: int):
        self.registry = registry
        self.count = max(int(count), 0)
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._stop = threading.Event()
        # dirty partials, FIFO with membership dedupe: a partial
        # already queued is not queued twice however many writes land
        self._dirty: collections.deque = collections.deque()
        self._queued: set = set()
        self._publish_pending = False
        self._threads: list[threading.Thread] = []
        self._started = False
        # counters (exported via the registry's stats/health surface)
        self.drains = 0
        self.errors = 0
        self.publish_runs = 0

    @property
    def enabled(self) -> bool:
        return self.count > 0

    @property
    def started(self) -> bool:
        return self._started

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        """Spawn the worker threads (idempotent, lazy — the first
        hand-off calls this; TSDServer also warms it at startup so a
        server's first ingest burst never pays thread creation)."""
        if not self.enabled or self._started:
            return
        with self._lock:
            if self._started:
                return
            self._stop.clear()
            for i in range(self.count):
                t = threading.Thread(target=self._loop,
                                     name=f"tsd-stream-fold-{i}",
                                     daemon=True)
                self._threads.append(t)
                t.start()
            self._started = True
        LOG.info("streaming fold-worker pool running (%d workers)",
                 self.count)

    def stop(self) -> None:
        self._stop.set()
        self._event.set()
        threads, self._threads = self._threads, []
        for t in threads:
            if t.is_alive():
                t.join(timeout=5)
        self._started = False

    # ------------------------------------------------------------------
    # hand-off surface (called from the ingest tap)
    # ------------------------------------------------------------------

    def submit(self, partial) -> None:
        """Queue one shared partial for an off-path drain (O(1):
        set-membership check + deque append + event set)."""
        self.start()
        with self._lock:
            if partial not in self._queued:
                self._queued.add(partial)
                self._dirty.append(partial)
        self._event.set()

    def notify_publish(self) -> None:
        """Ask a worker to run the rate-limited publish walk (there
        are live SSE subscribers and fresh folds)."""
        self.start()
        self._publish_pending = True
        self._event.set()

    def _take(self):
        with self._lock:
            if not self._dirty:
                return None
            partial = self._dirty.popleft()
            self._queued.discard(partial)
            return partial

    # ------------------------------------------------------------------

    def _loop(self) -> None:
        registry = self.registry
        from opentsdb_tpu.obs import trace as trace_mod
        tracer = getattr(registry.tsdb, "tracer", None)
        while not self._stop.is_set():
            self._event.wait(timeout=_IDLE_WAKE_S)
            self._event.clear()
            while not self._stop.is_set():
                partial = self._take()
                if partial is None:
                    break
                # each off-path drain is a (sampled) background trace
                # root, so fold-worker time shows up in /api/trace
                # and the streaming.drain latency histogram
                tctx = tracer.start_background(
                    "streaming.drain", sample=True) \
                    if tracer is not None and tracer.enabled else None
                try:
                    with trace_mod.use(tctx):
                        registry.worker_drain(partial)
                    self.drains += 1
                except Exception as exc:  # noqa: BLE001 - never die
                    # tsdlint: allow[swallow] a worker must outlive any
                    # fold failure; the drain already counted the
                    # error and marked the partial for rebuild
                    self.errors += 1
                    if tctx is not None:
                        tctx.set_error(exc)
                    LOG.exception("fold worker drain failed; partial "
                                  "will rebuild on serve")
                finally:
                    if tracer is not None and tctx is not None:
                        tracer.finish(tctx)
            if self._publish_pending and not self._stop.is_set():
                self._publish_pending = False
                try:
                    registry._maybe_publish()
                    self.publish_runs += 1
                except Exception:  # noqa: BLE001 - degrade, never die
                    # tsdlint: allow[swallow] publish hiccups are
                    # retried by the next ingest tick / SSE heartbeat
                    self.errors += 1
                    LOG.exception("worker publish walk failed")

    # ------------------------------------------------------------------

    def health_info(self) -> dict:
        with self._lock:
            backlog = len(self._dirty)
        return {
            "workers": self.count,
            "started": self._started,
            "backlog_partials": backlog,
            "drains": self.drains,
            "errors": self.errors,
            "publish_runs": self.publish_runs,
        }
