"""``tsdb check`` — Nagios-compatible threshold alerting over a live TSD
(ref: ``tools/check_tsd``: queries ``/q?...&ascii`` and compares the
returned datapoints against warning/critical thresholds).

Same flag surface and exit-code contract as the reference script
(0 = OK, 1 = WARNING, 2 = CRITICAL), reimplemented with
argparse + urllib over the same ``/q`` ascii endpoint.
"""

from __future__ import annotations

import argparse
import operator
import time
import urllib.error
import urllib.request

COMPARATORS = {"gt": operator.gt, "ge": operator.ge, "lt": operator.lt,
               "le": operator.le, "eq": operator.eq, "ne": operator.ne}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tsdb check",
        description="Simple TSDB data extractor for Nagios.")
    p.add_argument("-H", "--host", default="localhost")
    p.add_argument("-p", "--port", type=int, default=4242)
    p.add_argument("-m", "--metric", required=True)
    p.add_argument("-t", "--tag", action="append", default=[])
    p.add_argument("-d", "--duration", type=int, default=600,
                   help="How far back to look for data (seconds).")
    p.add_argument("-D", "--downsample", default="none")
    p.add_argument("-W", "--downsample-window", type=int, default=60)
    p.add_argument("-F", "--downsample-fill-policy", default="none",
                   choices=("none", "nan", "null", "zero"))
    p.add_argument("-a", "--aggregator", default="sum")
    p.add_argument("-x", "--method", dest="comparator", default="gt",
                   choices=sorted(COMPARATORS))
    p.add_argument("-r", "--rate", action="store_true")
    p.add_argument("-w", "--warning", type=float)
    p.add_argument("-c", "--critical", type=float)
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-T", "--timeout", type=int, default=10)
    p.add_argument("-E", "--no-result-ok", action="store_true")
    p.add_argument("-I", "--ignore-recent", type=int, default=0)
    p.add_argument("-P", "--percent-over", type=float, default=0.0)
    p.add_argument("-N", "--now", type=int, default=None,
                   help='Unix timestamp for "now" (testing).')
    p.add_argument("-S", "--ssl", action="store_true")
    return p


def build_url(o) -> str:
    tags = ",".join(o.tag)
    tags = "{" + tags + "}" if tags else ""
    ds = ("" if o.downsample == "none" else
          f"{o.downsample_window}s-{o.downsample}-"
          f"{o.downsample_fill_policy}:")
    rate = "rate:" if o.rate else ""
    start = (f"{o.now - o.duration}" if o.now
             else f"{o.duration}s-ago")
    scheme = "https" if o.ssl else "http"
    return (f"{scheme}://{o.host}:{o.port}/q?start={start}"
            f"&m={o.aggregator}:{ds}{rate}{o.metric}{tags}&ascii&nagios")


def main(argv: list[str]) -> int:
    parser = build_parser()
    o = parser.parse_args(argv)
    if o.duration <= 0:
        parser.error("Duration must be strictly positive.")
    if o.downsample_window <= 0:
        parser.error("Downsample window must be strictly positive.")
    if o.critical is None and o.warning is None:
        parser.error("You must specify at least a warning threshold "
                     "(-w) or a critical threshold (-c).")
    if o.ignore_recent < 0:
        parser.error("--ignore-recent must be positive.")
    if not 0 <= o.percent_over <= 100:
        parser.error("--percent-over must be in the range 0..100.")
    percent_over = o.percent_over / 100.0
    if o.critical is None:
        o.critical = o.warning
    elif o.warning is None:
        o.warning = o.critical

    url = build_url(o)
    if o.verbose:
        print(f"GET {url}")
    try:
        with urllib.request.urlopen(url, timeout=o.timeout) as resp:
            status = resp.status
            body = resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as e:
        print(f"CRITICAL: status = {e.code} when talking to "
              f"{o.host}:{o.port}")
        if o.verbose:
            print("TSD said:")
            print(e.read().decode("utf-8", "replace"))
        return 2
    except OSError as e:
        print(f"ERROR: couldn't GET {url}: {e}")
        return 2
    if status not in (200, 202):
        print(f"CRITICAL: status = {status} when talking to "
              f"{o.host}:{o.port}")
        return 2

    def no_data_point() -> int:
        if o.no_result_ok:
            print("OK: query did not return any data point "
                  "(--no-result-ok)")
            return 0
        print("CRITICAL: query did not return any data point")
        return 2

    lines = [ln for ln in body.splitlines() if ln.strip()]
    if not lines:
        return no_data_point()

    cmp_fn = COMPARATORS[o.comparator]
    now = o.now or int(time.time())
    npoints = nwarn = ncrit = 0
    badval = badts = None
    for line in lines:
        fields = line.split()
        ts = int(fields[1])
        delta = now - ts
        if delta > o.duration or delta <= o.ignore_recent:
            if delta < 0:
                break
            continue
        raw = fields[2]
        try:
            val = float(raw)
        except ValueError:
            continue  # unparseable cell
        if val != val:  # NaN fill (-F nan) — no data, not a violation
            continue
        npoints += 1
        bad = False
        if cmp_fn(val, o.critical):
            bad = True
            ncrit += 1
            nwarn += 1
        elif cmp_fn(val, o.warning):
            bad = True
            nwarn += 1
        if bad and (badval is None or cmp_fn(val, badval)):
            badval, badts = val, ts
    if not npoints:
        return no_data_point()
    if ncrit > 0 and ncrit / npoints > percent_over:
        rv, nbad, thresh = 2, ncrit, o.critical
    elif nwarn > 0 and nwarn / npoints > percent_over:
        rv, nbad, thresh = 1, nwarn, o.warning
    else:
        rv, nbad, thresh = 0, 0, None
    state = {0: "OK", 1: "WARNING", 2: "CRITICAL"}[rv]
    if rv:
        when = time.asctime(time.localtime(badts))
        print(f"{state}: {nbad}/{npoints} points {o.comparator} "
              f"{thresh} for {o.metric} (worst: {badval} @ {when})")
    else:
        print(f"{state}: {npoints} points within thresholds for "
              f"{o.metric}")
    return rv
