"""The ``tsdb`` command-line dispatcher (ref: ``tsdb.in:65-117``,
``src/tools/``).

Subcommands mirror the reference shell wrapper:

- ``tsd``      start the daemon (TSDMain.java:48)
- ``query``    ad-hoc queries, CliQuery output format (CliQuery.java:34)
- ``import``   bulk load text files (TextImporter.java:40)
- ``scan``     dump series, optionally in import format (DumpSeries.java:42)
- ``mkmetric`` assign metric UIDs (shortcut for ``uid assign metrics``)
- ``uid``      grep/assign/rename/delete/fsck the UID tables
  (UidManager.java:50)
- ``fsck``     storage integrity check/repair (Fsck.java:83)
- ``search``   time-series lookup (Search.java)
- ``treesync`` batch-rebuild trees (TreeSync.java)
- ``rollup``   run the in-framework rollup job (no reference
  equivalent: the reference relies on external jobs, SURVEY.md §2.3)
- ``version``

Config handling mirrors CliOptions/ConfigArgP: ``--config=PATH`` loads
a properties file; any ``--tsd.key=value`` flag overrides a config key.
"""

from __future__ import annotations

import gzip
import os
import sys
import time

from opentsdb_tpu.utils.config import Config
from opentsdb_tpu.utils import datetime_util

USAGE = """usage: tsdb <command> [args]
Valid commands: fsck, import, mkmetric, query, tsd, scan, search,
                treesync, rollup, uid, version, drain, check,
                cleancache
"""


def parse_common_args(argv: list[str]) -> tuple[Config, list[str]]:
    """(ref: CliOptions.parse + ConfigArgP overrides)"""
    config_file = None
    overrides: dict[str, str] = {}
    rest: list[str] = []
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg.startswith("--config"):
            config_file = (arg.split("=", 1)[1] if "=" in arg
                           else argv[(i := i + 1)])
        elif arg.startswith("--tsd."):
            if "=" in arg:
                key, val = arg[2:].split("=", 1)
            else:
                key, val = arg[2:], argv[(i := i + 1)]
            overrides[key] = val
        elif arg == "--auto-metric":
            overrides["tsd.core.auto_create_metrics"] = "true"
        elif arg.startswith("--datadir"):
            overrides["tsd.storage.data_dir"] = (
                arg.split("=", 1)[1] if "=" in arg else argv[(i := i + 1)])
        elif arg.startswith("--port"):
            overrides["tsd.network.port"] = (
                arg.split("=", 1)[1] if "=" in arg else argv[(i := i + 1)])
        else:
            rest.append(arg)
        i += 1
    config = Config(config_file=config_file, auto_load=config_file is None)
    for k, v in overrides.items():
        config.override_config(k, v)
    return config, rest


def make_tsdb(config: Config):
    from opentsdb_tpu.core.tsdb import TSDB
    return TSDB(config)


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------

def cmd_tsd(config: Config, args: list[str]) -> int:
    """(ref: TSDMain.java:71)"""
    import asyncio
    import signal

    from opentsdb_tpu.tsd.server import TSDServer
    from opentsdb_tpu.utils.plugin import load_plugin_instances

    # StartupPlugin.initialize runs before the TSDB exists
    # (ref: TSDMain.java:251)
    startup = load_plugin_instances(config, "tsd.startup", single=True)
    tsdb = make_tsdb(config)
    tsdb.initialize_plugins()
    server = TSDServer(tsdb)
    # protocol plugins sharing the process (ref: RpcPlugin.java:36,
    # RpcManager tsd.rpc.plugins)
    rpc_plugins = load_plugin_instances(config, "tsd.rpc",
                                        init_arg=tsdb) or []

    async def main():
        loop = asyncio.get_event_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, server.request_shutdown)
            except NotImplementedError:
                pass
        await server.start()
        if startup is not None:
            # server socket is bound (ref: StartupPlugin.setReady)
            startup.set_ready(tsdb)
        await server.serve_forever()

    asyncio.run(main())
    for plugin in rpc_plugins:
        plugin.shutdown()
    if startup is not None:
        startup.shutdown()
    return 0


def cmd_query(config: Config, args: list[str]) -> int:
    """``tsdb query [--graph PATH] START [END]
    <aggregator:[ds:][rate:]metric tagk=v...>`` (ref: CliQuery.java:34,
    incl. its --graph basepath chart output — matplotlib PNG here
    instead of gnuplot files). Output: ``metric timestamp value tags``.
    """
    from opentsdb_tpu.query.model import TSQuery, parse_uri_subquery
    graph_path = None
    if "--graph" in args:
        i = args.index("--graph")
        if i + 1 >= len(args):
            print("--graph needs a PATH", file=sys.stderr)
            return 2
        graph_path = args[i + 1]
        del args[i:i + 2]
    if len(args) < 2:
        print("usage: tsdb query [--graph PATH] START-DATE [END-DATE] "
              "[queries...]", file=sys.stderr)
        return 2
    start = args[0]
    pos = 1
    end = None
    # END is optional: detect by absence of ':' (queries contain agg:)
    if pos < len(args) and ":" not in args[pos]:
        end = args[pos]
        pos += 1
    subs = []
    while pos < len(args):
        spec = args[pos]
        pos += 1
        tag_parts = []
        while pos < len(args) and "=" in args[pos] \
                and ":" not in args[pos]:
            tag_parts.append(args[pos])
            pos += 1
        if tag_parts:
            spec += "{" + ",".join(tag_parts) + "}"
        subs.append(parse_uri_subquery(spec, len(subs)))
    tsq = TSQuery(start=start, end=end, queries=subs)
    tsq.validate()
    if graph_path:
        # fail fast BEFORE running the query: scanning a large range
        # only to discard the results on a missing optional dep is
        # wasted work
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            print("--graph requires matplotlib", file=sys.stderr)
            return 2
    tsdb = make_tsdb(config)
    results = tsdb.new_query().run(tsq)
    if graph_path:
        from opentsdb_tpu.tsd.graph import plot_results_basic
        fig, ax = plt.subplots(figsize=(10, 6), dpi=100)
        plot_results_basic(ax, results)
        if results:
            ax.legend(fontsize=8)
        fig.autofmt_xdate()
        fig.savefig(graph_path)
        plt.close(fig)
        print(f"wrote {graph_path}")
        return 0
    for r in results:
        tag_str = " ".join(f"{k}={v}" for k, v in sorted(r.tags.items()))
        for ts, v in r.dps:
            val = int(v) if float(v).is_integer() else v
            print(f"{r.metric} {ts // 1000} {val} {tag_str}".rstrip())
    return 0


def cmd_import(config: Config, args: list[str]) -> int:
    """(ref: TextImporter.java:40) Lines: ``metric ts value tagk=tagv...``
    Gzip files auto-detected by extension.

    Files stream through the columnar import
    (``TSDB.import_buffer``): one pass parses each chunk (native C++
    when the toolchain exists, the strict pure-Python twin otherwise),
    UID resolution runs once per distinct series, points land via bulk
    appends, and each chunk commits as one WAL write + one fsync.

    ``--no-wal`` skips write-ahead logging for the bulk load (parity
    with the reference batch import's ``setDurable(false)``,
    IncomingDataPoints.java:355-360) — run ``flush``/let the daemon
    snapshot afterwards."""
    durable = "--no-wal" not in args
    args = [a for a in args if a != "--no-wal"]
    if not args:
        print("usage: tsdb import [--no-wal] path [more paths]",
              file=sys.stderr)
        return 2
    tsdb = make_tsdb(config)
    total = 0
    errors = 0
    start = time.monotonic()
    CHUNK_BYTES = 64 << 20

    class _TooManyErrors(Exception):
        pass

    # the columnar import path no longer needs the native library —
    # parse_import_buffer carries a strict pure-Python twin, so every
    # host gets the one-pass decode + batched WAL commit per chunk
    for path in args:
        opener = gzip.open if path.endswith(".gz") else open
        base_line = 0

        def on_error(i: int, e: Exception) -> None:
            # stop printing (and abort) promptly at the cap — a
            # binary/wrong-format chunk can hold millions of bad
            # lines
            nonlocal errors
            errors += 1
            if errors <= 100:
                print(f"error: {path}:{base_line + i}: {e}",
                      file=sys.stderr)
            else:
                raise _TooManyErrors

        with opener(path, "rb") as fh:
            tail = b""
            while True:
                block = fh.read(CHUNK_BYTES)
                if not block:
                    buf, tail = tail, b""
                    if not buf:
                        break
                else:
                    block = tail + block
                    cut = block.rfind(b"\n")
                    if cut < 0:
                        tail = block
                        continue
                    buf, tail = block[:cut + 1], block[cut + 1:]
                try:
                    written, _ = tsdb.import_buffer(
                        buf, on_error=on_error, durable=durable)
                except _TooManyErrors:
                    print("too many errors, aborting",
                          file=sys.stderr)
                    return 1
                total += written
                base_line += buf.count(b"\n")
                if not block:
                    break
    tsdb.flush()
    dt = time.monotonic() - start
    rate = total / dt if dt > 0 else 0
    print(f"Total: imported {total} data points in {dt:.3f}s "
          f"({rate:,.1f} points/s)")
    return 0 if errors == 0 else 1


def cmd_scan(config: Config, args: list[str]) -> int:
    """(ref: DumpSeries.java:42) ``tsdb scan [--import] START [END]
    query...``"""
    import_format = False
    if args and args[0] == "--import":
        import_format = True
        args = args[1:]
    rc_config = config
    code = _scan_impl(rc_config, args, import_format)
    return code


def _scan_impl(config: Config, args: list[str],
               import_format: bool) -> int:
    from opentsdb_tpu.query.model import TSQuery, parse_uri_subquery
    if len(args) < 2:
        print("usage: tsdb scan [--import] START [END] queries...",
              file=sys.stderr)
        return 2
    start = args[0]
    pos = 1
    end = None
    if pos < len(args) and ":" not in args[pos]:
        end = args[pos]
        pos += 1
    subs = []
    while pos < len(args):
        spec = args[pos]
        pos += 1
        tag_parts = []
        while pos < len(args) and "=" in args[pos] \
                and ":" not in args[pos]:
            tag_parts.append(args[pos])
            pos += 1
        if tag_parts:
            spec += "{" + ",".join(tag_parts) + "}"
        if ":" not in spec:
            spec = "none:" + spec
        subs.append(parse_uri_subquery(spec, len(subs)))
    for sub in subs:
        if sub.aggregator != "none":
            sub.aggregator = "none"
    tsq = TSQuery(start=start, end=end, queries=subs)
    tsq.validate()
    tsdb = make_tsdb(config)
    results = tsdb.new_query().run(tsq)
    for r in results:
        tag_str = " ".join(f"{k}={v}" for k, v in sorted(r.tags.items()))
        for ts, v in r.dps:
            val = int(v) if float(v).is_integer() else v
            if import_format:
                print(f"{r.metric} {ts // 1000} {val} {tag_str}".rstrip())
            else:
                print(f"{r.metric} {ts} {val} {{{tag_str}}}")
    return 0


def cmd_mkmetric(config: Config, args: list[str]) -> int:
    """(ref: tsdb.in mkmetric = uid assign metrics)"""
    return cmd_uid(config, ["assign", "metrics"] + args)


def cmd_uid(config: Config, args: list[str]) -> int:
    """(ref: UidManager.java:50)"""
    if not args:
        print("usage: tsdb uid <subcommand> args\n"
              "  grep [kind] <RE>\n"
              "  assign <kind> <name>...\n"
              "  rename <kind> <name> <newname>\n"
              "  delete <kind> <name>\n"
              "  fsck\n  metasync\n  metapurge", file=sys.stderr)
        return 2
    tsdb = make_tsdb(config)
    sub = args[0]
    kinds = ("metrics", "tagk", "tagv")
    if sub == "assign":
        if len(args) < 3:
            print("usage: tsdb uid assign <kind> <name>...",
                  file=sys.stderr)
            return 2
        registry = tsdb.uids.by_kind(args[1])
        for name in args[2:]:
            try:
                uid = tsdb.assign_uid(
                    args[1].rstrip("s") if args[1] == "metrics"
                    else args[1], name)
                print(f"{name} {args[1]}: "
                      f"[{', '.join(str(b) for b in registry.int_to_uid(uid))}]")
            except Exception as e:  # noqa: BLE001
                print(f"{name} {args[1]}: {e}", file=sys.stderr)
        tsdb.flush()
        return 0
    if sub == "grep":
        kind_filter = None
        pattern_args = args[1:]
        if pattern_args and pattern_args[0] in kinds:
            kind_filter = pattern_args[0]
            pattern_args = pattern_args[1:]
        if not pattern_args:
            print("usage: tsdb uid grep [kind] <RE>", file=sys.stderr)
            return 2
        pattern = pattern_args[0]
        for kind in (kind_filter,) if kind_filter else kinds:
            registry = tsdb.uids.by_kind(kind)
            for name in registry.grep(pattern):
                uid = registry.int_to_uid(registry.get_id(name))
                print(f"{kind} {name}: {uid.hex()}")
        return 0
    if sub == "rename":
        if len(args) != 4:
            print("usage: tsdb uid rename <kind> <name> <newname>",
                  file=sys.stderr)
            return 2
        tsdb.uids.by_kind(args[1]).rename(args[2], args[3])
        tsdb.flush()
        return 0
    if sub == "delete":
        if len(args) != 3:
            print("usage: tsdb uid delete <kind> <name>", file=sys.stderr)
            return 2
        tsdb.uids.by_kind(args[1]).delete(args[2])
        tsdb.flush()
        return 0
    if sub == "fsck":
        errors = _uid_fsck(tsdb)
        print(f"{errors} errors found")
        return 0 if errors == 0 else 1
    if sub == "metasync":
        count = 0
        for mid in tsdb.store.metric_ids():
            for sid in tsdb.store.series_ids_for_metric(mid):
                rec = tsdb.store.series(int(sid))
                tsdb.meta.on_datapoint(rec.metric_id, rec.tags,
                                       rec.series_id)
                count += 1
        print(f"synced meta for {count} timeseries")
        tsdb.flush()
        return 0
    if sub == "metapurge":
        # (ref: UidManager.java:208 -> MetaPurge threads)
        n_ts, n_uid = tsdb.meta.purge()
        print(f"purged {n_ts} TSMeta and {n_uid} UIDMeta entries")
        tsdb.flush()
        return 0
    print(f"unknown uid subcommand: {sub}", file=sys.stderr)
    return 2


def _uid_fsck(tsdb) -> int:
    """(ref: UidManager fsck — forward/reverse map consistency)"""
    errors = 0
    for kind in ("metric", "tagk", "tagv"):
        registry = tsdb.uids.by_kind(kind)
        with registry._lock:
            fwd = dict(registry._name_to_id)
            rev = dict(registry._id_to_name)
        for name, uid in fwd.items():
            if rev.get(uid) != name:
                print(f"ERROR: {kind} forward map {name}->{uid} has no "
                      f"matching reverse entry")
                errors += 1
        for uid, name in rev.items():
            if fwd.get(name) != uid:
                print(f"ERROR: {kind} reverse map {uid}->{name} has no "
                      f"matching forward entry")
                errors += 1
    return errors


def cmd_fsck(config: Config, args: list[str]) -> int:
    from opentsdb_tpu.tools.fsck import run_fsck
    fix = "--fix" in args or "--fix-all" in args
    tsdb = make_tsdb(config)
    report = run_fsck(tsdb, fix=fix)
    for line in report.lines:
        print(line)
    print(f"Total errors: {report.errors}  "
          f"(fixed: {report.fixed})" if fix
          else f"Total errors: {report.errors}")
    if fix and report.fixed:
        tsdb.flush()
    return 0 if report.errors == report.fixed else 1


def cmd_search(config: Config, args: list[str]) -> int:
    """(ref: Search.java) ``tsdb search lookup [--use_meta] metric
    tagk=tagv...``"""
    if not args or args[0] != "lookup":
        print("usage: tsdb search lookup [--use_meta] <query>",
              file=sys.stderr)
        return 2
    args = args[1:]
    use_meta = False
    if args and args[0] == "--use_meta":
        use_meta = True
        args = args[1:]
    metric = args[0] if args and "=" not in args[0] else "*"
    tag_args = [a for a in args if "=" in a]
    tags = [tuple(a.split("=", 1)) for a in tag_args]
    tsdb = make_tsdb(config)
    from opentsdb_tpu.search.lookup import time_series_lookup
    out = time_series_lookup(tsdb, metric, tags, limit=2**31,
                             use_meta=use_meta)
    for r in out["results"]:
        tag_str = " ".join(f"{k}={v}" for k, v in sorted(r["tags"].items()))
        print(f"{r['metric']} {tag_str}  tsuid={r['tsuid']}")
    print(f"{out['totalResults']} results")
    return 0


def cmd_treesync(config: Config, args: list[str]) -> int:
    """(ref: TreeSync.java)"""
    tsdb = make_tsdb(config)
    from opentsdb_tpu.tree.tree import tree_manager
    count = tree_manager(tsdb).sync_all()
    print(f"Processed {count} timeseries through trees")
    return 0


def cmd_rollup(config: Config, args: list[str]) -> int:
    """Run the batch rollup job over a time range."""
    from opentsdb_tpu.rollup.job import run_rollup_job
    if len(args) < 2:
        print("usage: tsdb rollup START END [interval...]",
              file=sys.stderr)
        return 2
    config.override_config("tsd.rollups.enable", "true")
    tsdb = make_tsdb(config)
    start_ms = datetime_util.parse_datetime_ms(args[0])
    end_ms = datetime_util.parse_datetime_ms(args[1])
    intervals = args[2:] or None
    written = run_rollup_job(tsdb, start_ms, end_ms, intervals)
    for interval, count in written.items():
        print(f"{interval}: {count} rollup points written")
    tsdb.flush()
    return 0


def cmd_version(config: Config, args: list[str]) -> int:
    from opentsdb_tpu.tsd.http_api import version_info
    info = version_info()
    print(f"opentsdb_tpu version [{info['version']}] "
          f"built from revision {info['short_revision']}")
    return 0


def cmd_drain(config: Config, args: list[str]) -> int:
    """(ref: tools/tsddrain.py — outage spooler)"""
    from opentsdb_tpu.tools.drain import main as drain_main
    return drain_main(args)


def cmd_check(config: Config, args: list[str]) -> int:
    """(ref: tools/check_tsd — Nagios threshold check)"""
    from opentsdb_tpu.tools.check_tsd import main as check_main
    return check_main(args)


def cmd_cleancache(config: Config, args: list[str]) -> int:
    """Purge the /q graph cache (ref: tools/clean_cache.sh)."""
    import shutil
    cache_dir = config.get_string("tsd.http.cachedir",
                                  "/tmp/opentsdb_tpu")
    if os.path.isdir(cache_dir):
        n = len(os.listdir(cache_dir))
        shutil.rmtree(cache_dir, ignore_errors=True)
        print(f"removed {n} cached entries from {cache_dir}")
    else:
        print(f"no cache at {cache_dir}")
    return 0


COMMANDS = {
    "tsd": cmd_tsd,
    "query": cmd_query,
    "import": cmd_import,
    "scan": cmd_scan,
    "mkmetric": cmd_mkmetric,
    "uid": cmd_uid,
    "fsck": cmd_fsck,
    "search": cmd_search,
    "treesync": cmd_treesync,
    "rollup": cmd_rollup,
    "version": cmd_version,
    "drain": cmd_drain,
    "check": cmd_check,
    "cleancache": cmd_cleancache,
}


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        print(USAGE, file=sys.stderr)
        return 2
    command = argv[0]
    handler = COMMANDS.get(command)
    if handler is None:
        print(f"unknown command: {command}\n{USAGE}", file=sys.stderr)
        return 2
    config, rest = parse_common_args(argv[1:])
    return handler(config, rest)


if __name__ == "__main__":
    sys.exit(main())
