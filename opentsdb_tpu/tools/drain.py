"""``tsdb drain`` — absorb telnet ``put`` traffic during maintenance
(ref: ``tools/tsddrain.py``: a low-end TCP server that accepts
collector traffic and dumps the datapoints to one file per client IP,
for batch import once storage is back).

Differences from the reference script, kept deliberately small:
- asyncio instead of a thread-per-connection SocketServer;
- the leading ``put `` verb is stripped so the spool files are directly
  consumable by ``tsdb import`` (TextImporter line format).

Usage: ``tsdb drain --port 4242 --dir /var/spool/tsd``.
"""

from __future__ import annotations

import asyncio
import os


class DrainServer:
    def __init__(self, drain_dir: str, host: str = "0.0.0.0",
                 port: int = 4242):
        self.drain_dir = drain_dir
        self.host = host
        self.port = port
        self.lines_received = 0
        self._server: asyncio.AbstractServer | None = None
        os.makedirs(drain_dir, exist_ok=True)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, reuse_address=True)

    @property
    def bound_port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        client = peer[0] if peer else "unknown"
        path = os.path.join(self.drain_dir, client)
        try:
            with open(path, "a", encoding="utf-8") as out:
                while True:
                    line = await reader.readline()
                    if not line:
                        break
                    text = line.decode("utf-8", "replace").strip()
                    if not text:
                        continue
                    if text in ("exit", "quit", "diediedie"):
                        break
                    if text == "version":
                        # keep collectors that probe the TSD happy
                        writer.write(b"opentsdb_tpu drain\n")
                        await writer.drain()
                        continue
                    if text.startswith("put "):
                        text = text[4:]
                    out.write(text + "\n")
                    # flush per line: concurrent connections from one
                    # client IP share the spool file, and buffered
                    # flushes at arbitrary boundaries would tear lines
                    out.flush()
                    self.lines_received += 1
        finally:
            writer.close()


def main(argv: list[str]) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="tsdb drain",
        description="Spool telnet put traffic to files during outages")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=4242)
    parser.add_argument("--dir", default="./tsd-drain",
                        help="spool directory (one file per client IP)")
    args = parser.parse_args(argv)
    server = DrainServer(args.dir, args.host, args.port)

    async def run():
        await server.start()
        print(f"draining port {args.port} -> {args.dir}", flush=True)
        await server.serve_forever()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    # the operator's import checklist: how much landed in the spool
    print(f"drained {server.lines_received} line(s) into {args.dir}",
          flush=True)
    return 0
