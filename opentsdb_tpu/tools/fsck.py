"""Storage integrity check/repair (ref: ``src/tools/Fsck.java:83``).

The reference fsck walks HBase rows per salt bucket detecting bad row
keys, duplicate timestamps, orphaned/unknown cells, bad value
encodings, and bad compacted columns (Fsck.java:99-119). The columnar
store can't express most byte-level corruptions, so the checks map to
the store's own invariants:

- **unresolvable UIDs** — a series referencing metric/tagk/tagv ids
  missing from the UID tables (ref: "orphaned rows")
- **duplicate timestamps** — pending last-write-wins resolution
  (``--fix`` forces the resolve, ref: fix_duplicates)
- **unsorted buffers** — pending sort (fixed the same way)
- **non-finite values** — NaN/Inf datapoints (ref: bad VLE/float
  encodings; these poison aggregations)
- **out-of-range timestamps** — non-positive or beyond the 4-byte
  second range used by the row-key format
- **value-length integrity** — buffer length bookkeeping

When the data-lifecycle subsystem is enabled
(:mod:`opentsdb_tpu.lifecycle`), fsck additionally reports
**expired-but-present points** (raw points older than their metric's
retention TTL — a sweep should have purged them) and **ghost series**
(UID assigned, zero live points); ``--fix`` purges both through the
lifecycle sweep so mutation epochs, the snapshot and the WAL stay
consistent (an out-of-band delete would leave caches/replay able to
resurrect them).

When the cold tier is active (:mod:`opentsdb_tpu.coldstore`), fsck
verifies every manifest segment: header and data checksums, range
consistency against the metric's spill boundary, spill-vs-demotion
boundary ordering (a spill boundary past the demotion boundary would
double-serve the range between them), and orphan segment files left
by an interrupted spill. ``--fix`` quarantines corrupt segments
(renamed aside, dropped from the manifest) so queries degrade to
tier/raw serving instead of the TSD failing cold reads forever, and
clamps inconsistent boundaries.

The checker fans out per shard like the reference's per-salt-bucket
FsckWorker threads (Fsck.java:257), via a thread pool.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from opentsdb_tpu.core import const


@dataclass
class FsckReport:
    errors: int = 0
    fixed: int = 0
    series_checked: int = 0
    points_checked: int = 0
    lines: list[str] = field(default_factory=list)

    def error(self, msg: str, fixed: bool = False) -> None:
        self.errors += 1
        if fixed:
            self.fixed += 1
        self.lines.append(("FIXED: " if fixed else "ERROR: ") + msg)

    def merge(self, other: "FsckReport") -> None:
        self.errors += other.errors
        self.fixed += other.fixed
        self.series_checked += other.series_checked
        self.points_checked += other.points_checked
        self.lines.extend(other.lines)


MAX_VALID_MS = (const.MAX_SECOND_TIMESTAMP + const.MAX_TIMESPAN) * 1000


def run_fsck(tsdb, fix: bool = False, workers: int = 8) -> FsckReport:
    store = tsdb.store
    shards: dict[int, list[int]] = {}
    for sid in range(store.num_series()):
        shards.setdefault(store.series(sid).shard, []).append(sid)
    report = FsckReport()
    with ThreadPoolExecutor(max_workers=workers) as pool:
        futures = [pool.submit(_fsck_shard, tsdb, sids, fix)
                   for sids in shards.values()]
        for fut in futures:
            report.merge(fut.result())
    _fsck_lifecycle(tsdb, fix, report)
    _fsck_coldstore(tsdb, fix, report)
    if fix and report.fixed and getattr(tsdb, "data_dir", ""):
        # make repairs durable (ref: Fsck writes repairs back to
        # HBase): snapshot the repaired store and truncate the WAL so
        # replay-on-restart cannot resurrect the dropped points
        tsdb.flush()
    return report


def _fsck_lifecycle(tsdb, fix: bool, report: FsckReport) -> None:
    """Lifecycle-policy checks: expired-but-present points and ghost
    series. Active only when the subsystem is enabled — repairs go
    through the lifecycle purge path (manager.sweep), never an
    out-of-band delete, so epochs/snapshot/WAL stay consistent."""
    lc = getattr(tsdb, "lifecycle", None)
    if lc is None:
        return
    store = tsdb.store
    expired = lc.scan_expired()
    for metric in sorted(expired):
        report.error(
            f"metric {metric}: {expired[metric]} expired-but-present "
            f"point(s) past the retention TTL", fixed=fix)
    # ghost = zero live points but still-allocated columns: the sweep
    # releases those buffers, so --fix converges (a re-run is clean).
    # Fully-released ghosts are the designed end state — the sid/UID
    # survives by construction (numbering is positional; reclamation
    # is a ROADMAP item) and is NOT re-reported as an error forever.
    ghosts = [sid for sid in range(store.num_series())
              if len(store.series(sid).buffer) == 0
              and getattr(store.series(sid).buffer, "resident_bytes",
                          0) > 0]
    if ghosts:
        report.error(
            f"{len(ghosts)} ghost series (UID assigned, zero live "
            f"points, buffers not released): "
            f"{ghosts[:16]}{'...' if len(ghosts) > 16 else ''}",
            fixed=fix)
    if fix and (expired or ghosts):
        lc.sweep()
        if ghosts and hasattr(store, "compact_series"):
            # the sweep compacts policied metrics only; release the
            # remaining ghosts' columns directly (no data changes —
            # the buffers are empty — so no epoch/WAL work needed)
            store.compact_series(ghosts, pack_ts=False)


def _fsck_coldstore(tsdb, fix: bool, report: FsckReport) -> None:
    """Cold-tier segment integrity (see module docstring). Repairs go
    through the ColdStore's own quarantine/clamp paths so the manifest
    stays atomic and the cold mutation epoch bumps — queries fall back
    to tier/raw serving, the TSD never crashes on a bad segment."""
    lc = getattr(tsdb, "lifecycle", None)
    cold = getattr(lc, "coldstore", None) if lc is not None else None
    if cold is None:
        return
    boundaries: dict[str, int] = {}
    with lc._lock:
        mids = dict(lc._boundaries)
    for mid, b in mids.items():
        try:
            boundaries[tsdb.uids.metrics.get_name(mid)] = b
        except LookupError:
            continue
    for finding in cold.fsck_scan(boundaries):
        what = finding["file"] or "manifest"
        msg = f"cold segment {what}: {finding['problem']}"
        if not fix or finding["fix"] == "report":
            # "report" findings have no safe automated repair (e.g. a
            # lost lifecycle.json — quarantining healthy segments
            # would destroy servable history)
            report.error(msg)
            continue
        if finding["fix"] == "quarantine":
            fixed = cold.quarantine(finding["metric"], finding["file"])
        elif finding["fix"] == "clamp":
            fixed = cold.clamp_boundary(finding["metric"],
                                        finding["boundary"])
        else:  # orphan file from an interrupted spill
            cold.remove_orphan(finding["file"])
            fixed = True
        report.error(msg, fixed=fixed)


def _fsck_shard(tsdb, sids: list[int], fix: bool) -> FsckReport:
    """(ref: FsckWorker per-salt-bucket scan, Fsck.java:257)"""
    report = FsckReport()
    uids = tsdb.uids
    for sid in sids:
        rec = tsdb.store.series(sid)
        report.series_checked += 1
        name = f"series {sid}"
        # UID resolution (ref: unknown/orphaned cells)
        try:
            metric = uids.metrics.get_name(rec.metric_id)
            name = f"series {sid} ({metric})"
        except LookupError:
            report.error(f"{name}: unresolvable metric UID "
                         f"{rec.metric_id}")
        for kid, vid in rec.tags:
            try:
                uids.tag_names.get_name(kid)
            except LookupError:
                report.error(f"{name}: unresolvable tagk UID {kid}")
            try:
                uids.tag_values.get_name(vid)
            except LookupError:
                report.error(f"{name}: unresolvable tagv UID {vid}")

        buf = rec.buffer
        native = not hasattr(buf, "lock")
        if native:
            # native buffers sort/dedupe internally; inspect the
            # canonical view (order/dupe violations are unobservable)
            raw_ts, raw_vals, _ = buf.view_full()
            n = len(raw_ts)
            was_sorted = True
        else:
            with buf.lock:
                n = buf.n
                raw_ts = buf._ts64_locked().copy()
                raw_vals = buf.vals[:n].copy()
                was_sorted = buf._sorted
        report.points_checked += n
        if n == 0:
            continue
        # duplicate timestamps / unsorted buffer
        if not was_sorted:
            uniq = len(np.unique(raw_ts))
            dupes = n - uniq
            if dupes > 0:
                report.error(
                    f"{name}: {dupes} duplicate timestamp(s), "
                    "last write wins", fixed=fix)
            else:
                report.error(f"{name}: buffer out of order", fixed=fix)
            if fix:
                buf.view()  # forces sort + dedupe
        else:
            dupes = 0
        # non-finite values (ref: bad float encodings)
        bad_vals = int(np.sum(~np.isfinite(raw_vals)))
        if bad_vals:
            report.error(f"{name}: {bad_vals} non-finite value(s)",
                         fixed=fix)
        # timestamp range (ref: bad row keys / timestamps)
        bad_ts = int(np.sum((raw_ts <= 0) | (raw_ts > MAX_VALID_MS)))
        if bad_ts:
            report.error(f"{name}: {bad_ts} timestamp(s) out of range",
                         fixed=fix)
        if fix and (bad_vals or bad_ts):
            # unified in-place repair on either backend (native:
            # tss_repair_series; ref: Fsck.java:99-119)
            tsdb.store.repair_series(sid, 1, MAX_VALID_MS,
                                     drop_nonfinite=True)
    return report
